"""Soft-DTW timing + correctness harness.

TPU-native port of the reference's only self-verification tool
(`/root/reference/soft_dtw_cuda.py:389-463` — ``timed_run``/``profile``):
times forward+backward of the Pallas kernel against the ``lax.scan``
golden implementation and asserts they agree, across shape sweeps.

Run standalone on any backend (Pallas runs compiled on TPU, interpret
elsewhere):

    python -m milnce_tpu.ops.softdtw_profile            # default sweep
    python -m milnce_tpu.ops.softdtw_profile 32 256 256 512

Unlike the reference, the profile is also exercised in the test suite
(tests/test_softdtw_pallas.py) — the reference had no tests at all
(SURVEY.md §4).
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np


def timed_run(fn, D, n_iters: int = 256):
    """Mirror of soft_dtw_cuda.py:389-413: one verification pass with
    gradients + a timed fwd / fwd+bwd measurement.  Returns
    (fwd_s, bwd_s, value, grad).

    Timing protocol: ``milnce_tpu.utils.timing.chained_seconds`` (chained
    scan with a CSE-defeating carry perturbation, differenced between two
    chain lengths, host-materialized — the axon tunnel's
    ``block_until_ready`` resolves early and each dispatch costs ~70 ms
    of latency, so naive per-dispatch timing reports latency, not kernel
    time)."""
    from milnce_tpu.utils.timing import chained_seconds

    value_and_grad = jax.jit(jax.value_and_grad(lambda d: jnp.sum(fn(d))))

    # verification pass (also compiles the single-shot forms)
    value, grad = value_and_grad(D)
    jax.block_until_ready((value, grad))

    t_fwd = chained_seconds(lambda d: jnp.sum(fn(d)), D, n_iters)
    # grad() re-runs the forward, so each iteration is one fwd+bwd pass
    t_bwd = chained_seconds(lambda d: jnp.sum(jax.grad(
        lambda x: jnp.sum(fn(x)))(d)), D, n_iters)

    return t_fwd, t_bwd, np.asarray(value), np.asarray(grad)


def profile(batch_size: int, seq_len_a: int, seq_len_b: int, dims: int,
            gamma: float = 1.0, n_iters: int = 256, tol: float = 1e-3):
    """Cross-check scan vs Pallas fwd+bwd and report timings
    (soft_dtw_cuda.py:416-452).  Returns the result record."""
    from milnce_tpu.ops.softdtw import softdtw_scan
    from milnce_tpu.ops.softdtw_pallas import softdtw_pallas

    rng = np.random.RandomState(0)
    x = rng.randn(batch_size, seq_len_a, dims).astype(np.float32)
    y = rng.randn(batch_size, seq_len_b, dims).astype(np.float32)
    # Mean (not summed) squared-euclidean cost keeps the harness focused
    # on the DP kernel itself at a realistic O(1) cost scale (training
    # costs are cosine/dot on normalized embeddings).  Unnormalized d=512
    # costs push R to ~1e5+, where f32 rounding of R enters the
    # E-recurrence's exp((r1 - r - d)/gamma) as multiplicative weight
    # error and the hand-rolled backward (the reference's own algorithm,
    # soft_dtw_cuda.py:106-109) visibly drifts from autodiff — a drift the
    # reference harness can't see because it compares the E-recurrence
    # against itself (soft_dtw_cuda.py:439-440).
    D = jnp.asarray(((x[:, :, None, :] - y[:, None, :, :]) ** 2).mean(-1))

    t_fwd_s, t_bwd_s, v_s, g_s = timed_run(
        lambda d: softdtw_scan(d, gamma), D, n_iters)
    t_fwd_p, t_bwd_p, v_p, g_p = timed_run(
        lambda d: softdtw_pallas(d, gamma), D, n_iters)

    # the allclose half of the reference harness (soft_dtw_cuda.py:439-440)
    assert np.allclose(v_s, v_p, atol=tol, rtol=tol), (
        f"forward mismatch: max|dv|={np.abs(v_s - v_p).max()}")
    assert np.allclose(g_s, g_p, atol=tol, rtol=tol), (
        f"backward mismatch: max|dg|={np.abs(g_s - g_p).max()}")

    backend = jax.default_backend()
    rec = {
        "backend": backend,
        "pallas_compiled": backend == "tpu",
        "shape": [batch_size, seq_len_a, seq_len_b, dims],
        "scan_fwd_ms": round(t_fwd_s * 1e3, 3),
        "scan_fwd_bwd_ms": round(t_bwd_s * 1e3, 3),
        "pallas_fwd_ms": round(t_fwd_p * 1e3, 3),
        "pallas_fwd_bwd_ms": round(t_bwd_p * 1e3, 3),
        "speedup_fwd": round(t_fwd_s / t_fwd_p, 2) if t_fwd_p else None,
        "speedup_fwd_bwd": round(t_bwd_s / t_bwd_p, 2) if t_bwd_p else None,
        "allclose": True,
    }
    print(json.dumps(rec))
    return rec


if __name__ == "__main__":
    import os

    if os.environ.get("MILNCE_PROFILE_CPU") == "1":
        # escape hatch for hosts whose accelerator tunnel is down
        jax.config.update("jax_platforms", "cpu")
    if len(sys.argv) == 5:
        shapes = [tuple(int(a) for a in sys.argv[1:])]
    else:
        # reference presets (soft_dtw_cuda.py:460-463) + the MIL-NCE
        # training regime (SDTW_3 scores B^2 short pairs, loss.py:103-106)
        shapes = [(128, 17, 15, 2), (512, 64, 64, 2), (32, 256, 256, 512),
                  (1024, 32, 32, 64)]
    for shape in shapes:
        profile(*shape)
