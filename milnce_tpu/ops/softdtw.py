"""Soft-DTW: anti-diagonal wavefront DP as a jit-compiled `lax.scan`.

This is the *golden* implementation (and the long-sequence fallback): the
same recurrence the reference runs as a numba-CUDA wavefront kernel
(soft_dtw_cuda.py:34-76) and a numba-CPU triple loop (:185-207), expressed
TPU-natively:

- the cost matrix is pre-skewed into diagonal-major layout, so the scan
  body is pure vector ops over one anti-diagonal (VPU-friendly, no
  gather/scatter inside the loop);
- borders use a large-finite sentinel instead of +inf so reverse-mode AD
  through the softmin is NaN-free; JAX AD then yields exactly the
  Cuturi-Blondel E-matrix gradient that the reference hand-codes
  (soft_dtw_cuda.py:79-112, 211-240);
- no 1024-length cap (the reference falls back to CPU beyond 1024,
  soft_dtw_cuda.py:318-320).

The Pallas TPU kernel (`milnce_tpu.ops.softdtw_pallas`) is checked against
this implementation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# Finite stand-in for +inf: keeps softmin AD NaN-free.  Must dominate any
# real path cost — exp(euclidean) costs on raw d=512 gaussian features
# reach ~1e13 per cell (~1e16 per path), which overran the previous 1e10
# sentinel and corrupted the backward's r >= BIG/2 invalid-cell test
# (caught by the TPU profile harness at the reference's 32x256x256x512
# preset).  1e30 leaves 13 orders of magnitude of headroom and is exactly
# representable in both f32 and bf16 exponent range.
BIG = 1e30


def skew_cost(D: jax.Array, n_diags: int | None = None,
              row_offset=0) -> jax.Array:
    """(B, N, M) cost -> diagonal-major (B, n_diags, N) with
    ``out[:, p, i] = D[:, i, p - (row_offset + i)]`` (0 where out of
    range).  The defaults give the classic full-matrix skew; a nonzero
    ``row_offset`` (may be traced) skews a row-shard of a larger matrix
    against GLOBAL diagonal indices — used by the sequence-parallel
    wavefront (ops/softdtw_sp.py)."""
    _, n, m = D.shape
    if n_diags is None:
        n_diags = n + m - 1
    p_idx = jnp.arange(n_diags)[:, None]
    i_idx = jnp.arange(n)[None, :]
    j_idx = p_idx - (row_offset + i_idx)
    valid = (j_idx >= 0) & (j_idx < m)
    gathered = D[:, i_idx, jnp.clip(j_idx, 0, m - 1)]
    return jnp.where(valid[None], gathered, 0.0)


def softmin3(a, b, c, gamma):
    """-gamma * log(exp(-a/g) + exp(-b/g) + exp(-c/g)), stable."""
    stack = jnp.stack([-a, -b, -c], axis=0) / gamma
    return -gamma * jax.nn.logsumexp(stack, axis=0)


def check_bandwidth(n: int, m: int, bandwidth: int) -> None:
    """A Sakoe-Chiba band narrower than |N - M| prunes the terminal DP
    cell: every value degenerates to the finite BIG sentinel and training
    silently flatlines (no NaN for the divergence guard to catch).
    Shapes are static under jit, so this check costs nothing."""
    if 0 < bandwidth < abs(n - m):
        raise ValueError(
            f"sdtw bandwidth {bandwidth} cannot cover the |N-M| = "
            f"{abs(n - m)} length difference of a {n}x{m} alignment — the "
            "terminal cell is outside the band and every soft-DTW value "
            "degenerates to the BIG sentinel")


@partial(jax.jit, static_argnames=("bandwidth",))
def softdtw_scan(D: jax.Array, gamma: float, bandwidth: int = 0) -> jax.Array:
    """Soft-DTW values for a batch of cost matrices.

    Args:
      D: (B, N, M) pairwise cost.
      gamma: smoothing (>0).
      bandwidth: Sakoe-Chiba band; 0 disables pruning.

    Returns: (B,) soft-DTW alignment costs R[N, M].
    """
    bsz, n, m = D.shape
    check_bandwidth(n, m, bandwidth)
    d_skew = skew_cost(D)                       # (B, N+M-1, N)
    gamma = jnp.asarray(gamma, D.dtype)

    # R buffers are one anti-diagonal of the padded (N+1)x(M+1) DP table,
    # indexed by padded row i in [0, N].
    init_mm = jnp.full((bsz, n + 1), BIG, D.dtype).at[:, 0].set(0.0)  # diag 0
    init_m = jnp.full((bsz, n + 1), BIG, D.dtype)                     # diag 1
    i_buf = jnp.arange(n + 1)

    def step(carry, inputs):
        r_mm, r_m = carry
        cost_row, p = inputs                    # p = padded diagonal index
        prev_diag = r_mm[:, :-1]                # R[i-1, j-1]
        prev_up = r_m[:, :-1]                   # R[i-1, j]
        prev_left = r_m[:, 1:]                  # R[i, j-1]
        interior = cost_row + softmin3(prev_diag, prev_up, prev_left, gamma)
        r_new = jnp.concatenate(
            [jnp.full((bsz, 1), BIG, D.dtype), interior], axis=1)
        j_buf = p - i_buf
        valid = (i_buf >= 1) & (j_buf >= 1) & (i_buf <= n) & (j_buf <= m)
        if bandwidth > 0:                       # soft_dtw_cuda.py:66
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        r_new = jnp.where(valid[None, :], r_new, BIG)
        return (r_m, r_new), None

    diag_ids = jnp.arange(2, n + m + 1)
    (_, r_last), _ = lax.scan(step, (init_mm, init_m),
                              (d_skew.transpose(1, 0, 2), diag_ids))
    return r_last[:, n]


def euclidean_cost(x: jax.Array, y: jax.Array) -> jax.Array:
    """exp(L2 distance) per timestep pair (soft_dtw_cuda.py:325-335).

    (The reference really exponentiates the distance — parity kept.)
    Matmul formulation keeps the FLOPs on the MXU.
    """
    sq = (jnp.sum(x * x, -1)[:, :, None] + jnp.sum(y * y, -1)[:, None, :]
          - 2.0 * jnp.einsum("bnd,bmd->bnm", x, y))
    # Grad-safe sqrt: d/ds sqrt(s) -> inf at s=0 (hit deterministically by
    # the xx/yy legs of normalize=True); pick subgradient 0 there without
    # changing the forward value.
    nonzero = sq > 0.0
    safe = jnp.sqrt(jnp.where(nonzero, sq, 1.0))
    return jnp.exp(jnp.where(nonzero, safe, 0.0))


def cosine_cost(x: jax.Array, y: jax.Array, eps: float = 1e-8) -> jax.Array:
    """exp(1 - cosine_similarity) (soft_dtw_cuda.py:337-348)."""
    return jnp.exp(1.0 - _cosine_sim(x, y, eps))


def negative_cosine_cost(x: jax.Array, y: jax.Array, eps: float = 1e-8) -> jax.Array:
    """-cosine_similarity.  (The reference *names* this option at
    soft_dtw_cuda.py:299-300 but never defines the function — selecting it
    would AttributeError; we implement the evident intent.)"""
    return -_cosine_sim(x, y, eps)


def negative_dot_cost(x: jax.Array, y: jax.Array) -> jax.Array:
    """-<x, y> per timestep pair (soft_dtw_cuda.py:350-363)."""
    return -jnp.einsum("bnd,bmd->bnm", x, y)


def _cosine_sim(x, y, eps):
    # torch.cosine_similarity semantics: x.y / max(|x||y|, eps)
    num = jnp.einsum("bnd,bmd->bnm", x, y)
    nx = jnp.linalg.norm(x, axis=-1)[:, :, None]
    ny = jnp.linalg.norm(y, axis=-1)[:, None, :]
    return num / jnp.maximum(nx * ny, eps)


DIST_FUNCS = {
    "euclidean": euclidean_cost,
    "cosine": cosine_cost,
    "negative_cosine": negative_cosine_cost,
    "negative_dot": negative_dot_cost,
}


class SoftDTW:
    """Front-end mirroring the reference module (soft_dtw_cuda.py:274-386):
    distance function + optional normalization + batched soft-DTW.

    ``backend='scan'`` uses this module's lax.scan DP; ``backend='pallas'``
    uses the TPU wavefront kernel (same math, kernel-resident diagonals);
    ``backend='auto'`` picks per cost-matrix shape (v5e measurements,
    BENCH_SOFTDTW.md): the kernel wherever the batch-on-lanes layout
    applies (3.5-26x over the scan at large-batch/short-pair shapes) or
    the whole padded batch fits one sublane-batch VMEM block (~3x); the
    scan otherwise, where re-running the diagonal loop per batch tile
    makes the kernel lose to one scan over the full batch."""

    def __init__(self, gamma: float = 1.0, normalize: bool = False,
                 bandwidth: int | None = None, dist_func: str = "euclidean",
                 backend: str = "scan"):
        self.gamma = float(gamma)
        self.normalize = normalize
        self.bandwidth = 0 if bandwidth is None else int(bandwidth)
        if dist_func not in DIST_FUNCS:
            raise ValueError(
                f"unknown soft-DTW dist_func {dist_func!r} (the "
                f"--loss.sdtw_dist knob); expected one of "
                f"{sorted(DIST_FUNCS)}")
        self.dist_func = DIST_FUNCS[dist_func]
        if backend not in ("scan", "pallas", "auto"):
            raise ValueError(f"unknown soft-DTW backend {backend!r}")
        self.backend = backend

    def _dp(self, D: jax.Array) -> jax.Array:
        backend = self.backend
        if backend == "auto":
            from milnce_tpu.ops.softdtw_pallas import prefers_pallas

            backend = "pallas" if prefers_pallas(*D.shape) else "scan"
        if backend == "pallas":
            from milnce_tpu.ops.softdtw_pallas import softdtw_pallas

            return softdtw_pallas(D, self.gamma, self.bandwidth)
        return softdtw_scan(D, self.gamma, self.bandwidth)

    def __call__(self, x: jax.Array, y: jax.Array) -> jax.Array:
        """x: (B, N, D), y: (B, M, D) -> (B,) alignment costs."""
        if self.normalize:                      # soft_dtw_cuda.py:376-383
            if x.shape[1] == y.shape[1]:
                # one batched DP over [xy, xx, yy] (the reference's trick)
                xx = jnp.concatenate([x, x, y], axis=0)
                yy = jnp.concatenate([y, x, y], axis=0)
                out = self._dp(self.dist_func(xx, yy))
                out_xy, out_xx, out_yy = jnp.split(out, 3)
            else:
                # unequal lengths can't share one cost-matrix shape (the
                # reference's torch.cat would raise here); three DP calls
                out_xy = self._dp(self.dist_func(x, y))
                out_xx = self._dp(self.dist_func(x, x))
                out_yy = self._dp(self.dist_func(y, y))
            return out_xy - 0.5 * (out_xx + out_yy)
        return self._dp(self.dist_func(x, y))
