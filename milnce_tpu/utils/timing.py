"""On-device timing that survives a lying remote backend.

Remote tunnels (the axon TPU relay) add seconds of per-dispatch latency
and their ``block_until_ready`` can resolve before device work is
observable — naive per-dispatch timing reports latency, not kernel time
(observed: the same kernel "measured" 11.5 ms singly and 5 us chained).
The protocol here, shared by ``bench.py``-adjacent harnesses
(``milnce_tpu/ops/softdtw_profile.py``, ``scripts/stage_probe.py``):

1. run ``k`` executions inside ONE XLA program (a ``lax.scan`` whose
   carry perturbs the input by ±1e-30, defeating CSE; the perturbation
   is cast to the input dtype so bf16 workloads aren't silently promoted
   to f32);
2. materialize the scalar result ON HOST (a device->host transfer of the
   computed value cannot resolve early);
3. report the difference ``(T(k1+n) - T(k1)) / n``, which cancels the
   fixed dispatch cost.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def chained_seconds(step: Callable, x, n_iters: int, k1: int = 16,
                    reps: int = 2) -> float:
    """Seconds per execution of ``step(x) -> scalar`` under the protocol
    above.  ``step`` must be a pure jittable function of one array."""

    def chain(k):
        def run(d):
            def body(acc, _):
                bump = (acc * 1e-30).astype(d.dtype)
                return acc + jnp.asarray(step(d + bump),
                                         jnp.float32), None

            return lax.scan(body, jnp.float32(0.0), None, length=k)[0]

        return jax.jit(run)

    f1, f2 = chain(k1), chain(k1 + n_iters)
    float(f1(x)), float(f2(x))                  # compile + warm
    t1 = min(_wall(f1, x) for _ in range(reps))
    t2 = min(_wall(f2, x) for _ in range(reps))
    return max(t2 - t1, 0.0) / n_iters


def _wall(f, x) -> float:
    # graftlint: disable=GL005(the float() host materialization below IS the sync — step 2 of the differenced protocol; block_until_ready is exactly what remote tunnels resolve early)
    t0 = time.perf_counter()
    float(f(x))                                 # host materialization
    return time.perf_counter() - t0
