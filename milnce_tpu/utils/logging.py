"""Run logger: stdout + append-only file under log_root
(reference: main_distributed.py:304-306, rank-0 gated at call sites).

Both file handles are opened ONCE in ``__init__``, line-buffered, and
flushed per line — the original open-per-``log()`` cost a full
open/write/close syscall round-trip on every display line (and on every
decode-failure message arriving from reader threads), and the later
lazy open of the JSONL twin happened *inside* the lock: file I/O while
every logging thread waits, plus a lock-free ``_closed`` double-check
racing ``close()`` (graftlint GL012/GL010, ISSUE 7).  ``log_event``
appends structured JSONL alongside the text log (``<run>.jsonl``) for
machine consumers; the richer span/event stream lives in obs/spans.py
(RUN_EVENTS.jsonl).

Thread model: ``log``/``log_event`` arrive from reader threads and the
train loop; ``close`` is terminal (handles are nulled under the lock,
late calls are no-ops, never a resurrected handle).
"""

from __future__ import annotations

import json
import os
import time

from milnce_tpu.analysis.lockrt import make_lock


class RunLogger:
    def __init__(self, log_root: str, run_name: str = "", enabled: bool = True):
        self.enabled = enabled
        self.path = None
        self.events_path = None
        self._fh = None
        self._events_fh = None
        self._lock = make_lock("utils.runlogger")
        if enabled and log_root:
            os.makedirs(log_root, exist_ok=True)
            base = os.path.join(log_root, run_name or "run")
            self.path = base + ".log"
            self.events_path = base + ".jsonl"
            self._fh = open(self.path, "a", buffering=1)
            self._events_fh = open(self.events_path, "a", buffering=1)

    def log(self, message: str) -> None:
        if not self.enabled:
            return
        line = f"[{time.strftime('%H:%M:%S')}] {message}"
        print(line, flush=True)
        with self._lock:            # reader threads log decode failures;
            if self._fh is not None:  # handle check INSIDE the lock — a
                # racing close() between check and write would otherwise
                # deref None / a closed file
                self._fh.write(line + "\n")

    def log_event(self, event: dict) -> None:
        """Append one structured record to the JSONL twin of the text
        log.  A no-op after ``close()``, like ``log``: close is
        terminal, not a flush (the nulled handle IS the closed flag —
        one guarded field instead of a racy double-checked pair)."""
        if not self.enabled:
            return
        with self._lock:
            if self._events_fh is not None:
                self._events_fh.write(json.dumps(event) + "\n")

    def close(self) -> None:
        with self._lock:
            for fh in (self._fh, self._events_fh):
                if fh is not None:
                    fh.close()
            self._fh = None
            self._events_fh = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: disable=GL007(interpreter-teardown finalizer: close is best-effort, raising only makes unraisable-exception noise)
            pass
