"""Run logger: stdout + append-only file under log_root
(reference: main_distributed.py:304-306, rank-0 gated at call sites)."""

from __future__ import annotations

import os
import time


class RunLogger:
    def __init__(self, log_root: str, run_name: str = "", enabled: bool = True):
        self.enabled = enabled
        self.path = None
        if enabled and log_root:
            os.makedirs(log_root, exist_ok=True)
            self.path = os.path.join(log_root, (run_name or "run") + ".log")

    def log(self, message: str) -> None:
        if not self.enabled:
            return
        line = f"[{time.strftime('%H:%M:%S')}] {message}"
        print(line, flush=True)
        if self.path:
            with open(self.path, "a") as f:
                f.write(line + "\n")
