"""Run logger: stdout + append-only file under log_root
(reference: main_distributed.py:304-306, rank-0 gated at call sites).

The file handle is opened ONCE, line-buffered, and flushed per line —
the original open-per-``log()`` cost a full open/write/close syscall
round-trip on every display line (and on every decode-failure message
arriving from reader threads).  ``log_event`` appends structured JSONL
alongside the text log (``<run>.jsonl``) for machine consumers; the
richer span/event stream lives in obs/spans.py (RUN_EVENTS.jsonl).
"""

from __future__ import annotations

import json
import os
import threading
import time


class RunLogger:
    def __init__(self, log_root: str, run_name: str = "", enabled: bool = True):
        self.enabled = enabled
        self.path = None
        self.events_path = None
        self._fh = None
        self._events_fh = None
        self._closed = False
        self._lock = threading.Lock()
        if enabled and log_root:
            os.makedirs(log_root, exist_ok=True)
            base = os.path.join(log_root, run_name or "run")
            self.path = base + ".log"
            self.events_path = base + ".jsonl"
            self._fh = open(self.path, "a", buffering=1)

    def log(self, message: str) -> None:
        if not self.enabled:
            return
        line = f"[{time.strftime('%H:%M:%S')}] {message}"
        print(line, flush=True)
        with self._lock:            # reader threads log decode failures;
            if self._fh is not None:  # handle check INSIDE the lock — a
                # racing close() between check and write would otherwise
                # deref None / a closed file
                self._fh.write(line + "\n")

    def log_event(self, event: dict) -> None:
        """Append one structured record to the JSONL twin of the text
        log (opened lazily — most runs never call this).  A no-op after
        ``close()``, like ``log``: close is terminal, not a flush."""
        if not self.enabled or not self.events_path or self._closed:
            return
        with self._lock:
            if self._closed:
                return
            if self._events_fh is None:
                self._events_fh = open(self.events_path, "a", buffering=1)
            self._events_fh.write(json.dumps(event) + "\n")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for fh in (self._fh, self._events_fh):
                if fh is not None:
                    fh.close()
            self._fh = None
            self._events_fh = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: disable=GL007(interpreter-teardown finalizer: close is best-effort, raising only makes unraisable-exception noise)
            pass
