"""Analytic FLOPs / bytes / arithmetic-intensity model of S3D-G.

Two consumers:

- ``bench.py``: an independent per-step FLOPs source for the MFU
  diagnostic when XLA cost analysis is unavailable (the axon tunnel's
  lowered cost_analysis returns None, and the compiled fallback costs a
  full-model compile over a slow relay).
- ``python -m milnce_tpu.utils.roofline``: per-stage roofline table —
  which stages are MXU-bound vs HBM-bound on a given chip — the
  quantitative form of BENCH_NOTES.md's "headroom" reading.

The stage list mirrors ``models/s3dg.py`` (reference s3dg.py:207-328)
structurally: conv1 -> conv_2b -> conv_2c -> 9 Inception blocks with the
reference channel plan, TF-SAME pools between.  Accuracy contract:
convolution/dense FLOPs are exact (2 * out_elems * fan_in); elementwise
work (BN, ReLU, gating mults, pools, softmax) is counted as bytes but
NOT flops, so totals land a few percent under XLA's count, which also
folds in those vector ops.  tests/test_roofline.py pins the analytic
total against XLA's compiled cost analysis.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Tuple

# Peak dense matmul FLOP/s per chip (bf16), by device_kind substring.
# Public figures; the MFU diagnostic's denominator — THE table, shared
# by bench.py and the train loop's live ``milnce_train_mfu`` gauge so
# the two can never disagree on what "peak" means.
PEAK_FLOPS_BY_KIND = {
    "v6": 918e12,       # Trillium / v6e
    "v5p": 459e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device_kind: str = "") -> Optional[float]:
    """Peak FLOP/s per chip for a jax ``device_kind`` string, or None
    when unknown (CPU hosts).  ``MILNCE_PEAK_FLOPS`` overrides — how
    hermetic CPU tests (and odd fleets) get a deterministic MFU
    denominator."""
    env = os.environ.get("MILNCE_PEAK_FLOPS", "")
    if env:
        return float(env)
    kind = device_kind.lower()
    for key, val in PEAK_FLOPS_BY_KIND.items():
        if key in kind:
            return val
    return None


def mfu(flops_per_step: float, steps_per_sec: float,
        peak_per_chip: float, n_chips: int) -> float:
    """Model FLOPs utilization: achieved FLOP/s over the fleet's peak.
    ``flops_per_step`` counts the WHOLE sharded step (the convention of
    every FLOPs source in this module), so the denominator scales by
    chip count.  One definition, two consumers — bench.py's offline
    diagnostic and train/loop.py's live display-cadence gauge — pinned
    within 2% of each other by tests/test_goodput.py (they agree
    exactly given the same measured throughput)."""
    return flops_per_step * steps_per_sec / (peak_per_chip * n_chips)

# (out0a, out1a, out1b, out2a, out2b, out3b) per block — s3dg.py:223-233
INCEPTION_PLAN = [
    ("mixed_3b", (64, 96, 128, 16, 32, 32)),
    ("mixed_3c", (128, 128, 192, 32, 96, 64)),
    ("mixed_4b", (192, 96, 208, 16, 48, 64)),
    ("mixed_4c", (160, 112, 224, 24, 64, 64)),
    ("mixed_4d", (128, 128, 256, 24, 64, 64)),
    ("mixed_4e", (112, 144, 288, 32, 64, 64)),
    ("mixed_4f", (256, 160, 320, 32, 128, 128)),
    ("mixed_5b", (256, 160, 320, 32, 128, 128)),
    ("mixed_5c", (384, 192, 384, 48, 128, 128)),
]
# TF-SAME pools before these block indices: window/stride (s3dg.py ordering)
POOLS_BEFORE = {2: ((3, 3, 3), (2, 2, 2)), 7: ((2, 2, 2), (2, 2, 2))}


@dataclasses.dataclass
class Stage:
    name: str
    out_shape: Tuple[int, ...]          # (B, T, H, W, C)
    flops: float                        # fwd multiply-adds * 2 (conv/dense)
    bytes: float                        # in + out + weights, at `dtype_bytes`

    @property
    def intensity(self) -> float:
        return self.flops / self.bytes if self.bytes else 0.0


def _valid_taps(size: int, k: int, s: int, pad: int) -> Tuple[int, int]:
    """(output size, total VALID kernel taps over all outputs) for one
    spatial dim with symmetric padding.  Multiplications against the
    zero-padding are not real work — XLA's cost analysis agrees — and at
    small dims (4 frames, 3-tap temporal convs) the difference is ~17%,
    so the naive out*k count would overstate FLOPs."""
    out = (size + 2 * pad - k) // s + 1
    taps = 0
    for o in range(out):
        start = o * s - pad
        taps += min(start + k, size) - max(start, 0)
    return out, taps


def _conv_stage(name, in_shape, out_c, kernel, stride, dtype_bytes) -> Stage:
    b, t, h, w, c = in_shape
    # torch-style symmetric padding keeping ceil(dim/stride), as every
    # conv in this trunk uses (s3dg.py paddings)
    dims = [_valid_taps(size, k, s, k // 2)
            for size, k, s in zip((t, h, w), kernel, stride)]
    (ot, vt), (oh, vh), (ow, vw) = dims
    out_elems = b * ot * oh * ow * out_c
    # valid-tap sums factorize across dims: total MACs = B*Cin*Cout*∏Σv
    flops = 2.0 * b * c * out_c * vt * vh * vw
    weights = kernel[0] * kernel[1] * kernel[2] * c * out_c
    return Stage(name, (b, ot, oh, ow, out_c), flops,
                 dtype_bytes * (b * t * h * w * c + out_elems + weights))


def _sep_conv(name, in_shape, out_c, k, stride, dtype_bytes) -> List[Stage]:
    """Separable (t,k,k) = spatial (1,k,k) + temporal (t,1,1), each its
    own conv+BN+ReLU (s3dg.py:74-99)."""
    spatial = _conv_stage(f"{name}.spatial", in_shape, out_c, (1, k, k),
                          (1, stride[1], stride[2]), dtype_bytes)
    temporal = _conv_stage(f"{name}.temporal", spatial.out_shape, out_c,
                           (k, 1, 1), (stride[0], 1, 1), dtype_bytes)
    return [spatial, temporal]


def _pool_shape(shape, window, stride):
    b, t, h, w, c = shape
    return (b, -(-t // stride[0]), -(-h // stride[1]), -(-w // stride[2]), c)


def _inception(name, in_shape, plan, dtype_bytes) -> List[Stage]:
    c0, c1a, c1b, c2a, c2b, c3b = plan
    stages = [_conv_stage(f"{name}.b0", in_shape, c0, (1, 1, 1), (1, 1, 1),
                          dtype_bytes),
              _conv_stage(f"{name}.b1a", in_shape, c1a, (1, 1, 1), (1, 1, 1),
                          dtype_bytes)]
    stages += _sep_conv(f"{name}.b1b", stages[-1].out_shape, c1b, 3,
                        (1, 1, 1), dtype_bytes)
    stages.append(_conv_stage(f"{name}.b2a", in_shape, c2a, (1, 1, 1),
                              (1, 1, 1), dtype_bytes))
    stages += _sep_conv(f"{name}.b2b", stages[-1].out_shape, c2b, 3,
                        (1, 1, 1), dtype_bytes)
    stages.append(_conv_stage(f"{name}.b3b", in_shape, c3b, (1, 1, 1),
                              (1, 1, 1), dtype_bytes))
    out_c = c0 + c1b + c2b + c3b
    b, t, h, w, _ = in_shape
    # self-gating: 4 tiny dense (C->C) — flops negligible, bytes counted
    stages.append(Stage(f"{name}.concat+gate", (b, t, h, w, out_c),
                        2.0 * b * out_c * out_c * 4,
                        dtype_bytes * 2 * b * t * h * w * out_c))
    return stages


def s3d_video_stages(batch: int, frames: int, size: int,
                     space_to_depth: bool = False,
                     inception_blocks: int = 9,
                     dtype_bytes: int = 2) -> List[Stage]:
    """Forward conv trunk as a stage list (conv1 .. mixed_5c)."""
    stages: List[Stage] = []
    if space_to_depth:
        shape = (batch, frames // 2, size // 2, size // 2, 24)
        conv1 = _conv_stage("conv1(s2d)", shape, 64, (2, 4, 4),
                            (1, 1, 1), dtype_bytes)
        # the model crops the even-kernel conv's +1 overhang (s3dg.py
        # forward: net[:, 1:, 1:, 1:]) — downstream stages see size//2
        b, ot, oh, ow, c = conv1.out_shape
        conv1.out_shape = (b, ot - 1, oh - 1, ow - 1, c)
        stages.append(conv1)
    else:
        shape = (batch, frames, size, size, 3)
        stages.append(_conv_stage("conv1", shape, 64, (3, 7, 7), (2, 2, 2),
                                  dtype_bytes))
    shape = _pool_shape(stages[-1].out_shape, (1, 3, 3), (1, 2, 2))
    stages.append(_conv_stage("conv_2b", shape, 64, (1, 1, 1), (1, 1, 1),
                              dtype_bytes))
    stages += _sep_conv("conv_2c", stages[-1].out_shape, 192, 3, (1, 1, 1),
                        dtype_bytes)
    shape = _pool_shape(stages[-1].out_shape, (1, 3, 3), (1, 2, 2))
    for idx, (name, plan) in enumerate(INCEPTION_PLAN[:inception_blocks]):
        if idx in POOLS_BEFORE:
            shape = _pool_shape(shape, *POOLS_BEFORE[idx])
        block = _inception(name, shape, plan, dtype_bytes)
        stages += block
        shape = block[-1].out_shape
    return stages


def video_fwd_flops(batch: int, frames: int, size: int,
                    space_to_depth: bool = False,
                    inception_blocks: int = 9,
                    embedding_dim: int = 512) -> float:
    stages = s3d_video_stages(batch, frames, size, space_to_depth,
                              inception_blocks)
    trunk_c = stages[-1].out_shape[-1]
    return (sum(s.flops for s in stages)
            + 2.0 * batch * trunk_c * embedding_dim)          # final fc


def text_fwd_flops(rows: int, words: int, word_dim: int = 300,
                   hidden: int = 2048, embedding_dim: int = 512) -> float:
    """Frozen embed lookup (0 flops) -> dense(word_dim->hidden) per word
    -> word-max -> dense(hidden->embd) (s3dg.py:196-204)."""
    return (2.0 * rows * words * word_dim * hidden
            + 2.0 * rows * hidden * embedding_dim)


def milnce_logits_flops(batch: int, k_candidates: int,
                        embedding_dim: int = 512) -> float:
    """fwd+bwd FLOPs of the MIL-NCE logits matmul — the one QUADRATIC-in-
    batch term of the step (loss.py:11-17); callers rescaling a measured
    step count across batch sizes must scale this term separately."""
    return 3.0 * 2.0 * batch * batch * k_candidates * embedding_dim


def train_step_flops(batch: int, frames: int, size: int, k_candidates: int,
                     words: int, space_to_depth: bool = False,
                     inception_blocks: int = 9,
                     embedding_dim: int = 512,
                     word_dim: int = 300, hidden: int = 2048) -> float:
    """Full fwd+bwd step estimate: backward of a conv stack costs ~2x the
    forward (grad-wrt-input + grad-wrt-weights matmuls), so fwd+bwd = 3x
    fwd model flops; the MIL-NCE logits matmul (B*Bg*K*D, both directions
    counted once — loss.py:11-17) rides on top.  Optimizer/BN/pool vector
    work is excluded (sub-1%)."""
    model = (video_fwd_flops(batch, frames, size, space_to_depth,
                             inception_blocks, embedding_dim)
             + text_fwd_flops(batch * k_candidates, words, word_dim, hidden,
                              embedding_dim))
    return 3.0 * model + milnce_logits_flops(batch, k_candidates,
                                             embedding_dim)


def roofline_table(batch: int, frames: int, size: int,
                   space_to_depth: bool = False, peak_flops: float = 197e12,
                   hbm_bw: float = 820e9, dtype_bytes: int = 2) -> str:
    """Markdown per-stage table: FLOPs, bytes, intensity, bound, and the
    roofline-attained fraction of peak for each stage (v5e defaults)."""
    ridge = peak_flops / hbm_bw
    stages = s3d_video_stages(batch, frames, size, space_to_depth,
                              dtype_bytes=dtype_bytes)
    lines = [f"| stage | out shape | GFLOP | MB | AI (F/B) | bound | "
             f"roofline max MFU |",
             "|---|---|---|---|---|---|---|"]
    for s in stages:
        bound = "MXU" if s.intensity >= ridge else "HBM"
        attained = min(1.0, s.intensity / ridge)
        lines.append(
            f"| {s.name} | {'x'.join(map(str, s.out_shape))} | "
            f"{s.flops / 1e9:.2f} | {s.bytes / 1e6:.1f} | "
            f"{s.intensity:.0f} | {bound} | {attained:.0%} |")
    total_f = sum(s.flops for s in stages)
    total_b = sum(s.bytes for s in stages)
    # weighted attainable MFU: each stage runs at min(peak, AI*bw)
    time = sum(max(s.flops / peak_flops, s.bytes / hbm_bw) for s in stages)
    lines.append(f"| **total fwd trunk** | | {total_f / 1e9:.1f} | "
                 f"{total_b / 1e6:.1f} | {total_f / total_b:.0f} | | "
                 f"{total_f / time / peak_flops:.0%} |")
    return "\n".join(lines)


if __name__ == "__main__":
    import sys

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    for s2d in (False, True):
        print(f"\n## 16f@224, batch {batch}, bf16, "
              f"{'s2d stem' if s2d else 'plain stem'} (v5e roofline)\n")
        print(roofline_table(batch, 16, 224, space_to_depth=s2d))
