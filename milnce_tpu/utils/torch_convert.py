"""Convert reference PyTorch S3D checkpoints -> Flax variables.

Handles both checkpoint flavors the reference eval scripts accept
(eval_msrvtt.py:21-32):

- this-repo DDP format: ``{'state_dict': {'module.<name>': tensor}}``
- upstream flat S3D_HowTo100M format: ``{'<name>': tensor}`` (used with
  ``space_to_depth=True``).

Torch is NOT imported here; callers pass a ``Mapping[str, np.ndarray]``
(e.g. ``{k: v.numpy() for k, v in torch.load(p).items()}``), keeping the
library torch-free.

Layout rules:
- Conv3d  ``(O, I, t, h, w)`` -> flax Conv ``(t, h, w, I, O)``
- Linear  ``(O, I)``          -> flax Dense ``(I, O)``
- Embedding row-major         -> unchanged
- BatchNorm weight/bias -> params scale/bias; running_mean/var -> batch_stats.
"""

from __future__ import annotations

from typing import Mapping, MutableMapping

import numpy as np


def _set(tree: MutableMapping, path: list[str], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def strip_ddp_prefix(state_dict: Mapping[str, np.ndarray]) -> dict[str, np.ndarray]:
    return {k.removeprefix("module."): v for k, v in state_dict.items()}


def torch_state_dict_to_flax(state_dict: Mapping[str, np.ndarray]) -> dict:
    """Return ``{'params': ..., 'batch_stats': ...}`` nested dicts matching
    ``milnce_tpu.models.S3D``."""
    sd = strip_ddp_prefix(state_dict)
    params: dict = {}
    stats: dict = {}
    for key, raw in sd.items():
        if key.endswith("num_batches_tracked"):
            continue
        val = np.asarray(raw)
        parts = key.split(".")
        leaf = parts[-1]
        mods = parts[:-1]
        # Rename the STConv3D internals: conv1/bn1 (+conv2/bn2 when separable)
        # -> conv/bn or conv_spatial/bn_spatial + conv_temporal/bn_temporal.
        renamed: list[str] = []
        for i, m in enumerate(mods):
            if m in ("conv1", "bn1", "conv2", "bn2") and i == len(mods) - 1:
                prefix = ".".join(mods[:i])
                separable = f"{prefix}.conv2.weight" in sd
                if separable:
                    m = {"conv1": "conv_spatial", "bn1": "bn_spatial",
                         "conv2": "conv_temporal", "bn2": "bn_temporal"}[m]
                else:
                    m = {"conv1": "conv", "bn1": "bn"}[m]
            renamed.append(m)
        mods = renamed

        is_bn = mods and mods[-1].startswith("bn")
        if is_bn and leaf in ("running_mean", "running_var"):
            _set(stats, mods + [{"running_mean": "mean", "running_var": "var"}[leaf]], val)
        elif is_bn:
            _set(params, mods + [{"weight": "scale", "bias": "bias"}[leaf]], val)
        elif leaf == "weight":
            if val.ndim == 5:        # Conv3d
                _set(params, mods + ["kernel"], val.transpose(2, 3, 4, 1, 0))
            elif val.ndim == 2:
                if mods and mods[-1] == "word_embd":   # Embedding
                    _set(params, mods + ["embedding"], val)
                else:                # Linear
                    _set(params, mods + ["kernel"], val.transpose(1, 0))
            else:
                raise ValueError(f"unexpected weight rank for {key}: {val.shape}")
        elif leaf == "bias":
            _set(params, mods + ["bias"], val)
        else:
            raise ValueError(f"unrecognized checkpoint entry: {key}")
    return {"params": params, "batch_stats": stats}


def flax_to_torch_state_dict(variables: Mapping) -> dict[str, np.ndarray]:
    """Inverse of ``torch_state_dict_to_flax``: Flax S3D variables ->
    a flat torch-style state dict the reference's scripts can load
    (eval_msrvtt.py:21-32 flat flavor; wrap under ``{'state_dict':
    {'module.'+k: v}}`` for the DDP flavor).

    Completes the interop loop: train here, evaluate there.  Inversion
    is pinned by a roundtrip test (tests/test_reference_parity.py)."""
    out: dict[str, np.ndarray] = {}

    def walk(node, path, in_stats):
        if not isinstance(node, Mapping):
            _emit_leaf(out, path, np.asarray(node), in_stats)
            return
        for k, v in node.items():
            walk(v, path + [k], in_stats)

    walk(variables.get("params", {}), [], False)
    walk(variables.get("batch_stats", {}), [], True)
    # torch BN modules track an update count; emit one per running_mean so
    # a strict load_state_dict finds every expected key
    for key in [k for k in out if k.endswith("running_mean")]:
        out[key.removesuffix("running_mean") + "num_batches_tracked"] = (
            np.asarray(0, np.int64))
    return out


_INV_CONV = {"conv_spatial": "conv1", "bn_spatial": "bn1",
             "conv_temporal": "conv2", "bn_temporal": "bn2",
             "conv": "conv1", "bn": "bn1"}


def _emit_leaf(out: dict, path: list[str], val: np.ndarray,
               in_stats: bool) -> None:
    mods = [_INV_CONV.get(m, m) for m in path[:-1]]
    leaf = path[-1]
    prefix = ".".join(mods)
    if in_stats:
        out[f"{prefix}.{ {'mean': 'running_mean', 'var': 'running_var'}[leaf] }"] = val
    elif leaf == "scale":
        out[f"{prefix}.weight"] = val
    elif leaf == "bias":
        out[f"{prefix}.bias"] = val
    elif leaf == "embedding":
        out[f"{prefix}.weight"] = val
    elif leaf == "kernel":
        if val.ndim == 5:            # flax (t,h,w,I,O) -> torch (O,I,t,h,w)
            out[f"{prefix}.weight"] = val.transpose(4, 3, 0, 1, 2)
        elif val.ndim == 2:          # flax (I,O) -> torch (O,I)
            out[f"{prefix}.weight"] = val.transpose(1, 0)
        else:
            raise ValueError(f"unexpected kernel rank at {prefix}: {val.shape}")
    else:
        raise ValueError(f"unrecognized flax leaf: {'.'.join(path)}")


def load_torch_checkpoint_as_flax(path: str) -> dict:
    """torch.load a reference checkpoint file — either flavor
    (eval_msrvtt.py:21-32): the DDP ``{'state_dict': ...}`` wrapper or the
    upstream flat table — and convert to Flax variables.  The one place
    the library imports torch (train resume, eval CLI and the assets
    converter all route through here)."""
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=False)
    sd = raw.get("state_dict", raw) if isinstance(raw, dict) else raw
    sd = {k: v.numpy() for k, v in sd.items() if hasattr(v, "numpy")}
    return torch_state_dict_to_flax(sd)
