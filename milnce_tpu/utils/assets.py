"""Asset conversion CLI: the reference's binary blobs -> framework-native
files.

The reference distributes three external assets (README.md:31-43):
``word2vec.pth`` (torch-saved (V, 300) embedding table, s3dg.py:159),
``dict.npy`` (token vocabulary, s3dg.py:152) and S3D checkpoints.
The library itself never imports torch (models/build.py loads .npy/.npz);
this CLI does the one-off conversions so a deployment can drop torch
entirely:

    python -m milnce_tpu.utils.assets word2vec word2vec.pth word2vec.npy
    python -m milnce_tpu.utils.assets ckpt epoch0012.pth.tar run_dir/
    python -m milnce_tpu.utils.assets inspect some_checkpoint.pth.tar
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def convert_word2vec(src: str, dst: str) -> tuple[int, int]:
    """torch-saved embedding table -> .npy; returns (vocab, dim)."""
    import torch

    table = torch.load(src, map_location="cpu", weights_only=False)
    if hasattr(table, "weight"):             # nn.Embedding module
        table = table.weight.detach()
    arr = np.asarray(table.numpy() if hasattr(table, "numpy") else table,
                     np.float32)
    assert arr.ndim == 2, f"expected (V, D) table, got {arr.shape}"
    np.save(dst, arr)
    return arr.shape


def convert_checkpoint(src: str, dst: str) -> int:
    """Reference torch checkpoint (either flavor, eval_msrvtt.py:21-32)
    -> an Orbax RUN directory in exactly the layout train ``--resume``
    and the eval CLI restore (CheckpointManager step dirs holding a full
    TrainState — optimizer state freshly initialized, matching the
    template both consumers build).  Returns the saved epoch label."""
    import torch

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import cosine_with_warmup
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.utils.torch_convert import load_torch_checkpoint_as_flax

    raw = torch.load(src, map_location="cpu", weights_only=False)
    epoch = int(raw.get("epoch", 0)) if isinstance(raw, dict) else 0
    variables = load_torch_checkpoint_as_flax(src)
    optimizer = build_optimizer(OptimConfig(), cosine_with_warmup(1e-3, 1, 2))
    state = create_train_state(variables, optimizer)
    mgr = CheckpointManager(dst)
    mgr.save(epoch, state)
    mgr.wait()
    return epoch


def export_checkpoint(src: str, dst: str) -> int:
    """Orbax RUN directory -> a torch .pth the REFERENCE's eval scripts
    load (the DDP ``{'epoch', 'state_dict': {'module.<k>': tensor}}``
    flavor their format sniff expects, eval_msrvtt.py:21-26).  The
    reverse of ``convert_checkpoint``: train here, evaluate there."""
    import jax
    import torch

    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.utils.torch_convert import flax_to_torch_state_dict

    epoch, tree = CheckpointManager(src, create=False).restore_raw(
        subtrees={"params", "batch_stats"})
    if not isinstance(tree, dict):      # a TrainState restored as object
        tree = {"params": tree.params, "batch_stats": tree.batch_stats}
    sd = flax_to_torch_state_dict(
        {"params": jax.device_get(tree["params"]),
         "batch_stats": jax.device_get(tree["batch_stats"])})
    torch.save({"epoch": epoch,
                "state_dict": {f"module.{k}": torch.from_numpy(
                    np.array(v)) for k, v in sd.items()}}, dst)
    return epoch


def inspect(src: str) -> None:
    import torch

    raw = torch.load(src, map_location="cpu", weights_only=False)
    sd = raw.get("state_dict", raw) if isinstance(raw, dict) else raw
    if isinstance(sd, dict):
        print(f"{len(sd)} entries"
              + (f" (epoch {raw['epoch']})" if isinstance(raw, dict)
                 and "epoch" in raw else ""))
        for k, v in list(sd.items())[:40]:
            shape = tuple(v.shape) if hasattr(v, "shape") else type(v).__name__
            print(f"  {k}: {shape}")
        if len(sd) > 40:
            print(f"  ... {len(sd) - 40} more")
    else:
        print(type(sd), getattr(sd, "shape", ""))


def main(argv=None):
    p = argparse.ArgumentParser(description="milnce-tpu asset converter")
    sub = p.add_subparsers(dest="cmd", required=True)
    w = sub.add_parser("word2vec", help="torch .pth table -> .npy")
    w.add_argument("src")
    w.add_argument("dst")
    c = sub.add_parser("ckpt", help="torch checkpoint -> Orbax dir")
    c.add_argument("src")
    c.add_argument("dst")
    e = sub.add_parser("export", help="Orbax run dir -> torch .pth "
                                      "(reference eval scripts load it)")
    e.add_argument("src")
    e.add_argument("dst")
    i = sub.add_parser("inspect", help="list a torch checkpoint's tensors")
    i.add_argument("src")
    for sp in (w, c, e, i):
        sp.add_argument("--platform", default="",
                        help="force a jax backend (e.g. 'cpu' — conversion "
                             "needs no accelerator; same pin as the other CLIs)")
    args = p.parse_args(argv)
    if getattr(args, "platform", ""):
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.cmd == "word2vec":
        v, d = convert_word2vec(args.src, args.dst)
        print(f"wrote {args.dst}: ({v}, {d})")
    elif args.cmd == "ckpt":
        epoch = convert_checkpoint(args.src, args.dst)
        print(f"wrote {args.dst}: run dir at epoch {epoch}")
    elif args.cmd == "export":
        epoch = export_checkpoint(args.src, args.dst)
        print(f"wrote {args.dst}: torch checkpoint at epoch {epoch}")
    else:
        inspect(args.src)


if __name__ == "__main__":
    sys.exit(main())
