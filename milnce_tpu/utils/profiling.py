"""Profiling / step-timing utilities.

The reference's only instrumentation is ad-hoc ``time.time()`` deltas
(main_distributed.py:204-224, with ``d_step`` computed then unused);
here: a windowed step timer (steps/sec, clips/sec) and an optional
``jax.profiler`` trace context for real TPU traces (SURVEY.md §5
tracing note).
"""

from __future__ import annotations

import contextlib
import time


class StepTimer:
    """Windowed throughput meter."""

    def __init__(self, clips_per_step: int):
        self.clips_per_step = clips_per_step
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._steps = 0

    def tick(self) -> None:
        self._steps += 1

    @property
    def steps(self) -> int:
        return self._steps

    @property
    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    @property
    def steps_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._steps / dt if dt > 0 else 0.0

    @property
    def clips_per_sec(self) -> float:
        return self.steps_per_sec * self.clips_per_step


@contextlib.contextmanager
def maybe_trace(log_dir: str | None):
    """``with maybe_trace('/tmp/trace'):`` wraps the block in a
    ``jax.profiler`` trace when a directory is given; no-op otherwise."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
