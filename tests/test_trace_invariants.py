"""graftlint Pass 2 gates: jaxpr-level invariants over the hot-path entry
points, on the hermetic 8-virtual-device CPU mesh (tier-1 by design —
see ISSUE/ANALYSIS.md; the marker audit in test_suite_hygiene.py pins
these as NOT slow).

The positive test runs the full registered suite (train-step variants,
soft-DTW, retrieval embedders, conv-impl treedefs, double-call recompile
checks).  The negative tests plant each failure class and assert the
detector actually fires — an invariant checker that can't fail is
decoration.
"""

import jax
import jax.numpy as jnp
import numpy as np

from milnce_tpu.analysis.trace_invariants import (CheckResult,
                                                  collective_counts,
                                                  f64_sites,
                                                  run_trace_invariants,
                                                  _recompile_check)


def test_all_registered_entry_invariants_hold():
    results = run_trace_invariants()
    bad = [r.format() for r in results if not r.ok]
    assert not bad, "trace invariants violated:\n" + "\n".join(bad)
    # required coverage: train step, softdtw, retrieval (the ISSUE floor)
    # + the serving entries (ISSUE 4: bucket-ladder recompile gate and
    # pinned index collectives)
    entries = {r.entry for r in results}
    assert {"train_step_milnce", "train_step_milnce_guarded",
            "train_step_milnce_instrumented", "train_step_sdtw3",
            "grad_cache_step_milnce", "video_embed", "text_embed",
            "softdtw_scan_grad", "param_treedef",
            # ISSUE 12: chunked streaming MIL-NCE — dense-identical
            # collective pins, collective-free chunk scans, and the
            # backend-dispatch no-recompile gate
            "train_step_milnce_chunked", "train_step_milnce_chunked_2d",
            "milnce_chunked_dispatch",
            "serve_embed_ladder", "serve_text_embed", "serve_video_embed",
            "serve_index_topk",
            # ISSUE 10: pooled serving — per-replica ladder recompile pin
            # + collective-free replica embed programs
            "serve_pool_embed", "serve_pool_text_embed",
            "serve_pool_video_embed",
            # ISSUE 14: generation-swapped live index — same pinned
            # program + zero query-path recompiles across swaps
            "serve_live_index"} <= entries
    # the double-call recompile detector ran on every executable entry
    recompiled = {r.entry for r in results if r.check == "recompile"}
    assert {"train_step_milnce", "train_step_milnce_guarded",
            "train_step_milnce_instrumented",
            "video_embed", "text_embed",
            "softdtw_scan_grad", "serve_embed_ladder",
            "serve_index_topk"} <= recompiled
    # ISSUE 5 acceptance: the instrumented step executed under the
    # steady-state transfer guard and its pins match the plain step's
    checks = {(r.entry, r.check) for r in results}
    assert ("train_step_milnce_instrumented", "transfer-guard") in checks
    assert ("train_step_milnce_instrumented",
            "identical-to-uninstrumented") in checks
    # ISSUE 14 tentpole pin: swaps never compile on the query path
    assert ("serve_live_index", "recompile-across-swaps") in checks


def test_f64_detector_catches_planted_upcast():
    from jax.experimental import enable_x64

    def f(x):
        return x.astype("float64") + 1.0

    with enable_x64():
        jaxpr = jax.make_jaxpr(f)(np.ones((3,), np.float32)).jaxpr
    assert f64_sites(jaxpr), "planted f64 upcast not detected"


def test_f64_detector_clean_on_f32():
    jaxpr = jax.make_jaxpr(lambda x: x * 2.0)(
        np.ones((3,), np.float32)).jaxpr
    assert f64_sites(jaxpr) == []


def test_collective_counter_sees_through_nested_jaxprs():
    from jax.sharding import PartitionSpec as P

    from milnce_tpu.parallel.compat import shard_map
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.config import ParallelConfig

    mesh = build_mesh(ParallelConfig())

    @jax.jit
    def summed(x):
        return shard_map(lambda xs: jax.lax.psum(xs.sum(), "data"),
                         mesh=mesh, in_specs=P("data"), out_specs=P())(x)

    jaxpr = jax.make_jaxpr(summed)(np.ones((8,), np.float32)).jaxpr
    assert collective_counts(jaxpr) == {"psum": 1}


def test_recompile_detector_catches_dtype_drift():
    """Same shape, drifting dtype across calls — the classic silent
    retrace (e.g. an np.zeros fallback built without dtype= on one call
    path): the detector must flag the second cache entry."""
    f = jax.jit(lambda x: x + 1)

    def make_args(seed):
        return (np.ones((4,), np.float32 if seed == 0 else np.int32),)

    r = _recompile_check("planted", f, make_args)
    assert isinstance(r, CheckResult)
    if "skipped" in r.detail:       # jax without _cache_size introspection
        return
    assert not r.ok and "cache entries" in r.detail


def test_recompile_detector_passes_stable_fn():
    f = jax.jit(lambda x: x * 2)

    def make_args(seed):
        return (np.full((4,), seed, np.float32),)

    assert _recompile_check("stable", f, make_args).ok


def test_treedef_mismatch_would_be_reported():
    """The treedef check compares structure AND leaf shapes/dtypes; spot
    check the comparison logic on a synthetic divergence."""
    a = {"w": jax.ShapeDtypeStruct((2, 3), jnp.float32)}
    b = {"w": jax.ShapeDtypeStruct((3, 2), jnp.float32)}
    ta, tb = (jax.tree_util.tree_structure(x) for x in (a, b))
    la, lb = (jax.tree_util.tree_leaves(x) for x in (a, b))
    same = ta == tb and all(
        x.shape == y.shape and x.dtype == y.dtype for x, y in zip(la, lb))
    assert not same
