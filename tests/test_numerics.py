"""graftlint Pass 5 gates: the precision-flow audit (analysis/numerics.py).

Four layers, the same discipline as the Pass 4 suite:

- **unit**: dtype-flow corner cases — the census counts bytes by dtype,
  GL016 prices reduction extents, the cast inventory names boundaries.
- **parity**: the audit's tolerance claim checked against reality — the
  f32 and bf16 milnce losses agree within the bound derived from the
  audited reduction extent (eps(bf16) x extent), so the what-if table's
  "bf16 costs you this much accuracy" framing is calibrated, not vibes.
- **planted failures**: each of GL016/GL017/GL018 fires exactly once on
  a planted regression — a detector that can't fail is decoration.
- **the gate**: every registered entry audits green against the pins
  (census + cast inventory + f32 residency), with the pin-table and
  entry coverage floors asserted — the tier-1 check the tentpole
  exists for.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from milnce_tpu.analysis import numerics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- unit: the dtype-flow walk -------------------------------------------

def test_census_counts_bytes_by_dtype():
    def mixed(x, idx):
        return x.sum() + idx.astype(jnp.float32).sum()

    audit = numerics.audit_fn(
        mixed, (jax.ShapeDtypeStruct((1024,), jnp.float32),
                jax.ShapeDtypeStruct((256,), jnp.int32)),
        argnames=("x", "idx"))
    # args alone: 4 KB f32 + 1 KB i32; outputs/temps add f32 bytes only
    assert audit.census["f32"] >= 1024 * 4
    assert audit.census["i32"] >= 256 * 4
    assert "i32->f32 @ idx" in audit.casts, audit.casts


def test_census_hash_moves_with_precision_not_with_values():
    """The bench-record identity: same program -> same hash; the SAME
    program at bf16 -> a different hash (cross-precision compares must
    be flaggable from the record alone)."""
    def dot(a, b):
        return a @ b

    def args(dt):
        return (jax.ShapeDtypeStruct((8, 128), dt),
                jax.ShapeDtypeStruct((128, 8), dt))

    h32a = numerics.audit_fn(dot, args(jnp.float32)).census_hash()
    h32b = numerics.audit_fn(dot, args(jnp.float32)).census_hash()
    h16 = numerics.audit_fn(dot, args(jnp.bfloat16)).census_hash()
    assert h32a == h32b
    assert h32a != h16


# ---- parity: the bf16 tolerance claim vs reality -------------------------

def parity_tolerance(dtype, extent: int, safety: float = 4.0) -> float:
    """The audit-derived agreement bound: one rounding step per element
    of the largest low-precision reduction, rms-accumulated
    (eps x sqrt(extent)), with a safety factor for the exp/log
    nonlinearity around the reduction."""
    eps = float(jnp.finfo(dtype).eps)
    return eps * float(np.sqrt(extent)) * safety


def test_f32_vs_bf16_milnce_loss_within_audited_tolerance():
    """The what-if table says bf16 demotes the logsumexp reductions; the
    parity bound derived from that audited extent must hold on real
    values — and a bound 100x tighter must NOT (the tolerance is a
    measurement, not slack)."""
    from milnce_tpu.losses.milnce import milnce_loss

    b, k, d = 8, 4, 16
    rng = np.random.default_rng(0)
    video = rng.standard_normal((b, d)).astype(np.float32)
    text = rng.standard_normal((b * k, d)).astype(np.float32)

    loss32 = float(milnce_loss(jnp.asarray(video), jnp.asarray(text)))
    loss16 = float(milnce_loss(jnp.asarray(video, jnp.bfloat16),
                               jnp.asarray(text, jnp.bfloat16)))
    # the denominator lse concatenates row + column cubes: 2*B*K terms,
    # on top of a D-deep bf16 dot contraction
    extent = 2 * b * k * d
    tol = parity_tolerance(jnp.bfloat16, extent)
    assert abs(loss32 - loss16) <= tol * max(1.0, abs(loss32)), (
        f"f32 {loss32} vs bf16 {loss16} outside audited tolerance {tol}")
    # f32-vs-f32 determinism sanity: the bound is about precision, not
    # run-to-run noise
    again = float(milnce_loss(jnp.asarray(video), jnp.asarray(text)))
    assert loss32 == again


# ---- planted failures: each rule fires exactly once ----------------------

def test_gl016_fires_once_on_planted_bf16_accumulation():
    def dot(a, b):
        return a @ b

    args16 = (jax.ShapeDtypeStruct((8, 128), jnp.bfloat16),
              jax.ShapeDtypeStruct((128, 8), jnp.bfloat16))
    audit = numerics.audit_fn(dot, args16)
    assert len(audit.gl016_sites) == 1, audit.gl016_sites
    assert "contraction 128" in audit.gl016_sites[0]
    # and the check turns the site into exactly one failing result
    bad = [r for r in (numerics._check_gl016("planted", audit),)
           if not r.ok]
    assert len(bad) == 1 and "EXPECTED_GL016" in bad[0].detail

    # the f32 twin is silent
    args32 = (jax.ShapeDtypeStruct((8, 128), jnp.float32),
              jax.ShapeDtypeStruct((128, 8), jnp.float32))
    assert numerics.audit_fn(dot, args32).gl016_sites == ()

    # below the extent threshold: a tiny bf16 dot is noise, not a finding
    small = (jax.ShapeDtypeStruct((8, 16), jnp.bfloat16),
             jax.ShapeDtypeStruct((16, 8), jnp.bfloat16))
    assert numerics.audit_fn(dot, small).gl016_sites == ()


def test_gl017_fires_once_on_planted_fixture():
    """The AST half, on the fixture under tests/fixtures/losses/ (the
    path gate is part of the contract: GL017 is scoped to loss
    modules): exactly ONE finding — the bare exp — while the guarded
    softmax/lse/eps-floor idioms beside it stay silent."""
    from milnce_tpu.analysis.astlint import lint_paths

    fixture = os.path.join(_REPO, "tests", "fixtures", "losses",
                           "gl017_fixture.py")
    findings = [f for f in lint_paths([fixture]) if not f.suppressed]
    gl017 = [f for f in findings if f.rule.id == "GL017"]
    assert len(gl017) == 1, [f.format() for f in findings]
    assert gl017[0].line == 16  # the bare jnp.exp(scores)
    assert [f for f in findings if f.rule.id != "GL017"] == []


def test_gl017_jaxpr_half_counts_unguarded_exp():
    arg = (jax.ShapeDtypeStruct((64,), jnp.float32),)

    def guarded(x):
        return jnp.exp(x - x.max()).sum()

    assert numerics.audit_fn(guarded, arg).exp_sites == ()

    # exp directly of an ENTRY ARG reads guarded by the boundary rule
    # (the guard may live a level up), so the planted site routes
    # through an unbounded producer: exp(x + x) -> exactly one site
    def raw(x):
        return jnp.exp(x + x).sum()

    audit_raw = numerics.audit_fn(raw, arg)
    assert len(audit_raw.exp_sites) == 1, audit_raw.exp_sites
    bad = numerics._check_gl017("planted", audit_raw)
    assert not bad.ok and "EXPECTED_UNGUARDED_EXP" in bad.detail


def test_gl018_census_fires_once_on_planted_drift(monkeypatch):
    audits = numerics.audit_all(["milnce_loss_dense"])
    real = dict(audits["milnce_loss_dense"].census)
    real["f32"] = real.get("f32", 0) + 12345
    monkeypatch.setitem(numerics.EXPECTED_DTYPE_CENSUS,
                        "milnce_loss_dense", real)
    results = numerics.run_numerics_checks(["milnce_loss_dense"],
                                           audits=audits)
    bad = [r for r in results if not r.ok]
    assert [r.check for r in bad] == ["GL018-dtype-census"], (
        [r.format() for r in results])
    assert "re-pin" in bad[0].detail


def test_gl018_cast_inventory_fires_once_on_planted_boundary(monkeypatch):
    audits = numerics.audit_all(["milnce_loss_dense"])
    planted = dict(audits["milnce_loss_dense"].casts)
    planted["f32->bf16 @ phantom_boundary"] = 1
    monkeypatch.setitem(numerics.EXPECTED_CASTS, "milnce_loss_dense",
                        planted)
    results = numerics.run_numerics_checks(["milnce_loss_dense"],
                                           audits=audits)
    bad = [r for r in results if not r.ok]
    assert [r.check for r in bad] == ["GL018-cast-inventory"], (
        [r.format() for r in results])
    assert "phantom_boundary" in bad[0].detail


def test_entry_name_filter_rejects_typos():
    with pytest.raises(ValueError, match="unknown numerics entries"):
        numerics.audit_all(["train_step_milcne"])
    with pytest.raises(ValueError, match="unknown numerics entries"):
        numerics.run_numerics_checks(["no_such_entry"])


# ---- the what-if axis ----------------------------------------------------

def test_bf16_what_if_names_the_demotions():
    """The static half of the mixed-precision decision at the tiny
    preset: flipping the model dtype must surface low-precision
    accumulations AND log-domain residency violations, while the f32
    twin stays clean — the NUMERICS.md what-if table's content."""
    a32 = numerics.what_if_audit(batch=16, frames=4, size=32, words=6,
                                 k=2, dtype="float32", preset="tiny")
    a16 = numerics.what_if_audit(batch=16, frames=4, size=32, words=6,
                                 k=2, dtype="bfloat16", preset="tiny")
    assert a32.gl016_sites == ()
    assert a32.residency_violations == ()
    assert len(a16.gl016_sites) > 0
    assert any("bf16" in s or "bfloat16" in s for s in a16.gl016_sites)
    assert a16.census.get("bf16", 0) > 0
    assert a32.census.get("bf16", 0) == 0


# ---- the gate ------------------------------------------------------------

def test_all_registered_entries_audit_green():
    """The Pass 5 merge gate: GL016 + GL017 + GL018 + f32-residency hold
    for every registered entry, with the coverage floor asserted."""
    results = numerics.run_numerics_checks()
    bad = [r.format() for r in results if not r.ok]
    assert not bad, "numerics invariants violated:\n" + "\n".join(bad)
    entries = {r.entry for r in results}
    assert {"train_step_milnce", "train_step_milnce_guarded",
            "train_step_sdtw3", "grad_cache_step_milnce",
            "train_step_milnce_chunked", "milnce_loss_dense",
            "milnce_loss_chunked", "train_step_milnce_2d",
            "grad_cache_2d", "serve_text_embed@b0", "serve_video_embed@b1",
            "serve_index_topk", "serve_index_topk@gen",
            "train_step_curriculum@s1"} <= entries
    # every entry carries all five checks
    for entry in entries:
        checks = {r.check for r in results if r.entry == entry}
        assert {"GL016-low-precision-accum", "GL017-exp-domain",
                "GL018-dtype-census", "GL018-cast-inventory",
                "f32-residency"} <= checks, (entry, checks)
    # train entries actually audit a nonempty residency set (BN stats +
    # optimizer moments) — an empty set would make the rule vacuous
    audit = numerics.audit_entry("train_step_milnce")
    assert len(audit.f32_residency) > 0


def test_pin_tables_cover_every_registered_entry():
    """Unpinned entries fail the gate as 'entry unpinned', so the pin
    tables and the registry must move together — this is the coverage
    floor that keeps a new entry from shipping censusless."""
    names = set(numerics.entry_names())
    assert set(numerics.EXPECTED_DTYPE_CENSUS) == names, (
        names ^ set(numerics.EXPECTED_DTYPE_CENSUS))
    assert set(numerics.EXPECTED_CASTS) == names, (
        names ^ set(numerics.EXPECTED_CASTS))
