"""2-D ``(data, model)`` FSDP training gates (ISSUE 6).

Loss parity pins the tentpole's semantics: the 4x2 FSDP grid, the 1-D
8-way data mesh, and a single device must train IDENTICALLY to float
tolerance for both loss families — the sharding map changes where bytes
live and which collectives move them, never the math.  The grad-cache
path gets the same pin (4x2 M=2 == 8-way M=2: a microbatch is a virtual
shard, so the virtual-shard census must match, not the mesh shape).

The acceptance gates are here too: a 2-step ``run_training`` on the 4x2
grid completes under the loop's own ``transfer_guard("disallow")`` with
large params VERIFIABLY sharded (per-shard byte accounting on the live
TrainState, not just specs), the direct 2-D step runs twice on one
jit-cache entry under an explicit guard, and a 1-D checkpoint resumes
onto the 2-D mesh and back (MIGRATING.md "Checkpoint resharding").

Pinned tier-1 (never @slow) by tests/test_suite_hygiene.py: these are
the regression fence for the pod-scale layout.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from milnce_tpu.config import LossConfig, OptimConfig, ParallelConfig, tiny_preset
from milnce_tpu.models import S3D
from milnce_tpu.parallel.mesh import build_mesh, batch_sharding, replicate_to_mesh
from milnce_tpu.parallel.sharding_map import (place_tree, sharded_count,
                                              sharded_dim, spec_leaves,
                                              state_partition_specs)
from milnce_tpu.train.schedule import build_schedule
from milnce_tpu.train.state import (build_optimizer, create_train_state,
                                    per_device_state_bytes)
from milnce_tpu.train.step import make_grad_cache_step, make_train_step

# Tiny-entry geometry (mirrors analysis/trace_invariants.py _setup): 16
# clips = 2 per shard on every 8-shard layout below; threshold 256 so
# several kernels actually shard on the 2-wide model axis.
_B, _FRAMES, _SIZE, _WORDS, _VOCAB = 16, 4, 32, 5, 32
_MIN_SIZE = 256


def _model(bn_axes):
    # sync BN over the mesh's batch axes: makes normalization a function
    # of the GLOBAL batch, so the single-device run (whole batch, no
    # axis) is comparable with every sharded layout
    return S3D(num_classes=16, vocab_size=_VOCAB, word_embedding_dim=8,
               text_hidden_dim=16, inception_blocks=1, bn_axis_name=bn_axes)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    video = rng.integers(0, 255, (_B, _FRAMES, _SIZE, _SIZE, 3),
                         dtype=np.uint8)
    text = rng.integers(0, _VOCAB, (_B, _WORDS)).astype(np.int32)
    start = np.zeros((_B,), np.float32)
    return video, text, start


def _mesh(kind):
    if kind == "single":
        return build_mesh(ParallelConfig(), devices=jax.devices()[:1])
    if kind == "1d":
        return build_mesh(ParallelConfig())
    return build_mesh(ParallelConfig(model_axis="model",
                                     model_parallel_size=2))


def _train(kind, loss_cfg=None, n_steps=2, grad_accum=1):
    """Fresh init (same PRNG key on every layout) -> n_steps of the real
    step program on the ``kind`` mesh; returns per-step losses and the
    final state."""
    mesh = _mesh(kind)
    fsdp = kind == "2d"
    bn_axes = (("data", "model") if fsdp else "data")
    model = _model(bn_axes)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, _FRAMES, _SIZE, _SIZE, 3), jnp.float32),
        jnp.zeros((2, _WORDS), jnp.int32))
    opt = build_optimizer(OptimConfig(warmup_steps=2),
                          build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, opt)
    if fsdp:
        specs = state_partition_specs(state, mesh, "model",
                                      min_size=_MIN_SIZE)
        assert sharded_count(specs.params, "model") > 0
        state = place_tree(state, specs, mesh)
    else:
        specs = None
        state = replicate_to_mesh(state, mesh)
    kw = dict(donate=False, loss_cfg=loss_cfg, state_specs=specs,
              model_axis="model" if fsdp else None)
    if grad_accum > 1:
        step = make_grad_cache_step(model, opt, mesh, grad_accum, **kw)
    else:
        step = make_train_step(model, opt, mesh, **kw)
    losses = []
    for i in range(n_steps):
        state, loss = step(state, *_batch(i))
        losses.append(float(loss))
    return losses, state


# --------------------------------------------------------------------------
# loss parity: 2-D == 1-D == single device, both loss families
# --------------------------------------------------------------------------

@pytest.mark.parametrize("loss_cfg", [
    None,                                             # milnce
    LossConfig(name="sdtw_3", sdtw_backend="scan"),   # DTW family
], ids=["milnce", "sdtw_3"])
def test_mesh_layout_parity(loss_cfg):
    """Two full optimizer steps agree across layouts: step-2 loss is a
    function of step-1's update, so agreement transitively pins grads,
    the FSDP gather/reduce-scatter pair, and the optimizer running on
    local shards — not just the forward."""
    ref, _ = _train("single", loss_cfg)
    one_d, _ = _train("1d", loss_cfg)
    two_d, _ = _train("2d", loss_cfg)
    np.testing.assert_allclose(one_d, ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(two_d, ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(two_d, one_d, rtol=2e-4, atol=2e-5)


def test_grad_cache_parity_2d_vs_1d():
    """The once-per-step-reduction grad-cache program is mesh-layout
    invariant: 4x2 M=2 == 8-way M=2, microbatch census identical (BN
    sees the same virtual shards), losses equal to float tolerance.
    Final params agree leaf-for-leaf — the 2-D run's optimizer only
    ever saw LOCAL shards of grads and moments, so equality here is
    the end-to-end FSDP correctness pin."""
    one_d, st1 = _train("1d", grad_accum=2)
    two_d, st2 = _train("2d", grad_accum=2)
    np.testing.assert_allclose(two_d, one_d, rtol=2e-4, atol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(st1.params),
                    jax.tree_util.tree_leaves(st2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


# --------------------------------------------------------------------------
# acceptance: transfer guard + zero recompiles + real byte accounting
# --------------------------------------------------------------------------

def _assert_state_bytes_match_specs(state, specs, mesh):
    """Per-shard byte accounting asserted on COMMITTED arrays: every
    device holds exactly (replicated bytes + sharded bytes / axis size)
    — specs claiming FSDP while bytes stay replicated would fail here."""
    axis_size = mesh.shape["model"]
    expect = 0
    for leaf, sp in zip(jax.tree_util.tree_leaves(state),
                        spec_leaves(specs)):
        n = leaf.nbytes if hasattr(leaf, "nbytes") else np.asarray(leaf).nbytes
        expect += n // axis_size if sharded_dim(sp, "model") is not None else n
    per_dev = per_device_state_bytes(state)
    assert len(per_dev) == 8
    for dev, got in per_dev.items():
        assert got == expect, (dev, got, expect)
    replicated = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(state))
    assert expect < replicated   # the map sharded something real


def test_2d_step_zero_recompiles_under_transfer_guard():
    """Direct twin of the acceptance criterion: two 2-D steps with
    fresh batches run under ``transfer_guard("disallow")`` (all inputs
    explicitly placed) on ONE jit-cache entry."""
    mesh = _mesh("2d")
    model = _model(("data", "model"))
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, _FRAMES, _SIZE, _SIZE, 3), jnp.float32),
        jnp.zeros((2, _WORDS), jnp.int32))
    opt = build_optimizer(OptimConfig(warmup_steps=2),
                          build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, opt)
    specs = state_partition_specs(state, mesh, "model", min_size=_MIN_SIZE)
    state = place_tree(state, specs, mesh)
    _assert_state_bytes_match_specs(state, specs, mesh)
    step = make_train_step(model, opt, mesh, donate=False,
                           state_specs=specs, model_axis="model")
    data_sh = batch_sharding(mesh, ("data", "model"))

    def place(seed):
        video, text, start = _batch(seed)
        return (jax.device_put(video, data_sh), jax.device_put(text, data_sh),
                jax.device_put(start, data_sh))

    args = [place(0), place(1)]
    with jax.transfer_guard("disallow"):
        for a in args:
            state, loss = step(state, *a)
    assert np.isfinite(jax.device_get(loss))
    # the updated state is STILL sharded: the step's out_specs keep the
    # FSDP layout, no silent re-replication after one update
    _assert_state_bytes_match_specs(state, specs, mesh)
    if hasattr(step, "_cache_size"):
        assert step._cache_size() == 1, step._cache_size()


def _run_cfg(tmp_path, name, two_d):
    cfg = tiny_preset()
    cfg.model.inception_blocks = 1
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = 32
    cfg.data.num_reader_threads = 2
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")   # shared: resume
    cfg.train.log_root = str(tmp_path / f"log_{name}")
    if two_d:
        cfg.parallel.model_axis = "model"
        cfg.parallel.model_parallel_size = 2
        cfg.parallel.fsdp_min_size = _MIN_SIZE
    return cfg


def test_model_axis_without_size_refuses_loudly(tmp_path):
    """--parallel.model_axis set but model_parallel_size left at 1 must
    be an error, not a silent 1-D run the config claims is FSDP (the
    same refuse-loudly rule as GL009 / bench's shards-NOTHING)."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "phantom", two_d=True)
    cfg.parallel.model_parallel_size = 1
    with pytest.raises(ValueError, match="model_parallel_size"):
        run_training(cfg, max_steps=1)


def test_run_training_2d_two_steps_sharded(tmp_path):
    """The loop-level acceptance run: 2 steps on the 4x2 grid through
    ``run_training`` (its own steady-state transfer guard armed), the
    returned live state carrying real model-axis shards."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "accept", two_d=True)
    res = run_training(cfg, max_steps=2)
    assert res.steps == 2
    assert np.isfinite(res.last_loss)
    mesh = _mesh("2d")
    specs = state_partition_specs(res.state, mesh, "model",
                                  min_size=_MIN_SIZE)
    _assert_state_bytes_match_specs(res.state, specs, mesh)


# --------------------------------------------------------------------------
# checkpoint resharding round trip: 1-D -> 2-D -> 1-D
# --------------------------------------------------------------------------

def test_resume_1d_checkpoint_onto_2d_mesh_and_back(tmp_path):
    """A checkpoint carries global arrays, never a mesh layout
    (MIGRATING.md): a 1-D run's checkpoint resumes onto the 4x2 FSDP
    grid (state resharded through the loop's single placement path,
    step counter carried, update applied on local shards) and THAT
    run's checkpoint opens back on the 1-D mesh."""
    from milnce_tpu.train.loop import run_training

    r1 = run_training(_run_cfg(tmp_path, "seed1d", two_d=False),
                      max_steps=2)

    cfg2 = _run_cfg(tmp_path, "to2d", two_d=True)
    cfg2.train.resume = True
    cfg2.optim.epochs = 2
    r2 = run_training(cfg2, max_steps=1)
    assert int(r2.state.step) == int(r1.state.step) + 1
    assert np.isfinite(r2.last_loss)
    mesh = _mesh("2d")
    specs = state_partition_specs(r2.state, mesh, "model",
                                  min_size=_MIN_SIZE)
    _assert_state_bytes_match_specs(r2.state, specs, mesh)

    cfg3 = _run_cfg(tmp_path, "back1d", two_d=False)
    cfg3.train.resume = True
    cfg3.optim.epochs = 3
    r3 = run_training(cfg3, max_steps=1)
    assert int(r3.state.step) == int(r2.state.step) + 1
    assert np.isfinite(r3.last_loss)
    # back on the data mesh every leaf is fully replicated again
    per_dev = per_device_state_bytes(r3.state)
    replicated = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(r3.state))
    assert set(per_dev.values()) == {replicated}
