"""S3D-G model shape/behavior tests (hermetic, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.models import S3D
from milnce_tpu.parallel.compat import set_mesh, shard_map
from milnce_tpu.models.s3dg import space_to_depth, _tf_same_max_pool


def tiny_model(**kw):
    defaults = dict(num_classes=32, vocab_size=64, word_embedding_dim=8,
                    text_hidden_dim=16)
    defaults.update(kw)
    return S3D(**defaults)


@pytest.fixture(scope="module")
def model_and_vars():
    model = tiny_model()
    video = jnp.zeros((2, 4, 32, 32, 3), jnp.float32)
    text = jnp.zeros((2, 6), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), video, text)
    return model, variables


def test_forward_all_shapes(model_and_vars):
    model, variables = model_and_vars
    video = jnp.ones((2, 4, 32, 32, 3), jnp.float32) * 0.5
    text = jnp.ones((4, 6), jnp.int32)  # B*K flattened rows, K=2
    v, t = model.apply(variables, video, text)
    assert v.shape == (2, 32)
    assert t.shape == (4, 32)


def test_mixed5c_features_are_1024d(model_and_vars):
    model, variables = model_and_vars
    video = jnp.ones((1, 4, 32, 32, 3), jnp.float32)
    feats = model.apply(variables, video, None, mode="video", mixed5c=True)
    assert feats.shape == (1, 1024)  # mixed_5c output dim (s3dg.py:233)


def test_text_only_mode(model_and_vars):
    model, variables = model_and_vars
    out = model.apply(variables, None, jnp.zeros((3, 6), jnp.int32), mode="text")
    assert out.shape == (3, 32)


@pytest.mark.slow
def test_train_mode_updates_batch_stats(model_and_vars):
    model, variables = model_and_vars
    video = jnp.ones((2, 4, 32, 32, 3), jnp.float32)
    text = jnp.zeros((2, 6), jnp.int32)
    _, mutated = model.apply(variables, video, text, train=True,
                             mutable=["batch_stats"])
    old = variables["batch_stats"]["conv1"]["bn"]["mean"]
    new = mutated["batch_stats"]["conv1"]["bn"]["mean"]
    assert not np.allclose(np.asarray(old), np.asarray(new))


def test_gating_flag_actually_disables_gating():
    """The reference cannot disable gating (s3dg.py:212/220 overwrite bug,
    SURVEY.md §2.4); ours must."""
    m = tiny_model(gating=False)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 32, 32, 3)),
               jnp.zeros((1, 6), jnp.int32))
    flat = jax.tree_util.tree_leaves_with_path(v["params"])
    names = ["/".join(str(k.key) for k in path) for path, _ in flat]
    assert not any("gating" in n for n in names)


@pytest.mark.slow
def test_text_embedding_gradient_is_zero(model_and_vars):
    """word2vec table is frozen via stop_gradient (s3dg.py:199-200)."""
    model, variables = model_and_vars

    def loss_fn(params):
        out = model.apply({**variables, "params": params},
                          None, jnp.ones((2, 6), jnp.int32), mode="text")
        return jnp.sum(out ** 2)

    grads = jax.grad(loss_fn)(variables["params"])
    emb_grad = grads["text_module"]["word_embd"]["embedding"]
    assert np.allclose(np.asarray(emb_grad), 0.0)
    fc1_grad = grads["text_module"]["fc1"]["kernel"]
    assert not np.allclose(np.asarray(fc1_grad), 0.0)


def test_space_to_depth_layout():
    x = jnp.arange(2 * 4 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 4, 3)
    y = space_to_depth(x)
    assert y.shape == (2, 2, 2, 2, 24)
    # channel order is (t2, h2, w2, c): channel 0 at output (t,h,w) must be
    # input (2t, 2h, 2w, 0)
    np.testing.assert_allclose(y[0, 1, 1, 1, 0], x[0, 2, 2, 2, 0])
    # last channel = (t2=1, h2=1, w2=1, c=2) -> input (2t+1, 2h+1, 2w+1, 2)
    np.testing.assert_allclose(y[0, 0, 0, 0, 23], x[0, 1, 1, 1, 2])


@pytest.mark.slow
def test_space_to_depth_model_shapes():
    m = tiny_model(use_space_to_depth=True)
    video = jnp.zeros((1, 8, 64, 64, 3), jnp.float32)
    text = jnp.zeros((1, 6), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), video, text)
    v, t = m.apply(variables, video, text)
    assert v.shape == (1, 32)


def _naive_ref_maxpool_1d(row, k, s):
    """Reference MaxPool3dTFPadding semantics (s3dg.py:114-146): pad
    max(k-s,0) low-first, then ceil-mode pooling (zero pad; inputs >=0)."""
    pad_along = max(k - s, 0)
    lo, hi = pad_along // 2, pad_along - pad_along // 2
    padded = np.concatenate([np.zeros(lo), row, np.zeros(hi)])
    out_len = -(-(len(padded) - k) // s) + 1
    return np.array([padded[i * s: i * s + k].max() for i in range(out_len)])


@pytest.mark.parametrize("length", [5, 6, 7, 8])
def test_tf_same_maxpool_matches_reference_semantics(length):
    rng = np.random.RandomState(0)
    # odd lengths are where XLA 'SAME' and the reference's padding differ
    x = rng.rand(1, 1, 1, length, 1).astype(np.float32)
    out = _tf_same_max_pool(jnp.asarray(x), (1, 1, 3), (1, 1, 2))
    expected = _naive_ref_maxpool_1d(x[0, 0, 0, :, 0], 3, 2)
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0, :, 0], expected)


def test_sync_batchnorm_merges_stats_across_shards():
    """bn_axis_name='data' (model.sync_batchnorm — the original TPU run's
    cross-replica BN, README.md:13 flips the trade-off on TPU): batch
    stats computed under shard_map over sharded data must equal the
    stats of the FULL batch, unlike local BN which sees only its shard."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from milnce_tpu.models.s3dg import STConv3D

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    b, t, hw, cin = 16, 2, 4, 3
    rng = np.random.RandomState(0)
    # per-shard means differ: scale each sample by its index
    x = (rng.rand(b, t, hw, hw, cin) * np.arange(1, b + 1)[:, None, None,
                                                          None, None]
         ).astype(np.float32)

    sync = STConv3D(features=4, kernel_size=(1, 1, 1), bn_axis_name="data")
    variables = sync.init(jax.random.PRNGKey(0), jnp.zeros((2, t, hw, hw, cin)))

    @jax.jit
    def sharded_stats(x):
        def local(xs):
            _, mut = sync.apply(variables, xs, train=True,
                                mutable=["batch_stats"])
            return mut["batch_stats"]

        return shard_map(local, mesh=mesh, in_specs=P("data"),
                             out_specs=P(), check_vma=False)(x)

    with set_mesh(mesh):
        stats_sharded = sharded_stats(
            jax.device_put(x, NamedSharding(mesh, P("data"))))

    # reference: local BN over the WHOLE batch in one program
    local_mod = STConv3D(features=4, kernel_size=(1, 1, 1))
    _, mut_full = local_mod.apply(variables, jnp.asarray(x), train=True,
                                  mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(stats_sharded["bn"]["mean"]),
        np.asarray(mut_full["batch_stats"]["bn"]["mean"]), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats_sharded["bn"]["var"]),
        np.asarray(mut_full["batch_stats"]["bn"]["var"]), rtol=1e-4)


class TestConv3DFold2D:
    """fold2d lowers every trunk conv shape as 2D convolutions with an
    IDENTICAL parameter layout (models/conv3d.py) — outputs must match
    the native 3D lowering to numerical noise."""

    # (kernel, strides, padding) — every distinct conv shape in the trunk
    SHAPES = [
        ((1, 1, 1), (1, 1, 1), (0, 0, 0)),       # pointwise branches
        ((1, 3, 3), (1, 1, 1), (0, 1, 1)),       # separable spatial
        ((3, 1, 1), (1, 1, 1), (1, 0, 0)),       # separable temporal
        ((1, 7, 7), (1, 2, 2), (0, 3, 3)),       # strided spatial
        ((3, 7, 7), (2, 2, 2), (1, 3, 3)),       # conv1 stem (full 3D)
        ((2, 4, 4), (1, 1, 1), (1, 2, 2)),       # s2d stem (even kernel)
    ]

    @pytest.mark.parametrize("kernel,strides,padding", SHAPES)
    def test_matches_native(self, kernel, strides, padding):
        from milnce_tpu.models.conv3d import Conv3D

        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 5, 12, 12, 6).astype(np.float32))
        kw = dict(features=8, kernel_size=kernel, strides=strides,
                  padding=padding)
        native = Conv3D(impl="native", **kw)
        params = native.init(jax.random.PRNGKey(1), x)
        ref = native.apply(params, x)
        out = Conv3D(impl="fold2d", **kw).apply(params, x)
        assert out.shape == ref.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_full_model_parity(self):
        """Whole S3D-G forward agrees across conv impls on the same
        variables (the param trees are layout-identical by design)."""
        video = jnp.asarray(np.random.RandomState(0)
                            .rand(2, 4, 32, 32, 3).astype(np.float32))
        text = jnp.zeros((2, 6), jnp.int32)
        native = tiny_model()
        variables = native.init(jax.random.PRNGKey(0), video, text)
        v_ref, t_ref = native.apply(variables, video, text)
        v_out, t_out = tiny_model(conv_impl="fold2d").apply(
            variables, video, text)
        np.testing.assert_allclose(np.asarray(v_out), np.asarray(v_ref),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(t_out), np.asarray(t_ref),
                                   rtol=1e-4, atol=1e-4)


class TestConv3DIm2col:
    """im2col lowers every trunk conv shape as patch extraction + one
    dot_general with an IDENTICAL parameter layout (models/conv3d.py);
    its custom VJP keeps dW and dX in matmul form — so BOTH the forward
    and the gradients must match the native 3D lowering."""

    # the two stem shapes the impl was built for, plus every other
    # distinct trunk conv shape
    STEM_SHAPES = [
        ((3, 7, 7), (2, 2, 2), (1, 3, 3)),       # conv1 stem (full 3D)
        ((2, 4, 4), (1, 1, 1), (1, 2, 2)),       # s2d stem (even kernel)
    ]
    SHAPES = STEM_SHAPES + [
        ((1, 1, 1), (1, 1, 1), (0, 0, 0)),       # pointwise branches
        ((1, 3, 3), (1, 1, 1), (0, 1, 1)),       # separable spatial
        ((3, 1, 1), (1, 1, 1), (1, 0, 0)),       # separable temporal
        ((1, 7, 7), (1, 2, 2), (0, 3, 3)),       # strided spatial
    ]

    @pytest.mark.parametrize("kernel,strides,padding", SHAPES)
    def test_forward_matches_native(self, kernel, strides, padding):
        from milnce_tpu.models.conv3d import Conv3D

        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 5, 12, 12, 6).astype(np.float32))
        kw = dict(features=8, kernel_size=kernel, strides=strides,
                  padding=padding)
        native = Conv3D(impl="native", **kw)
        params = native.init(jax.random.PRNGKey(1), x)
        ref = native.apply(params, x)
        out = Conv3D(impl="im2col", **kw).apply(params, x)
        assert out.shape == ref.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("kernel,strides,padding", SHAPES)
    def test_gradients_match_native(self, kernel, strides, padding):
        """Parameter AND input gradients of the custom VJP vs native
        autodiff at EVERY trunk conv shape — the backward is where the
        measured MFU sink lives (PERF.md), and the autotuner may pick
        im2col for any stage, so no shape's VJP goes unguarded."""
        from milnce_tpu.models.conv3d import Conv3D

        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(2, 5, 12, 12, 6).astype(np.float32))
        kw = dict(features=8, kernel_size=kernel, strides=strides,
                  padding=padding)
        params = Conv3D(impl="native", **kw).init(jax.random.PRNGKey(1), x)
        cot = jnp.asarray(rng.randn(
            *Conv3D(impl="native", **kw).apply(params, x).shape)
            .astype(np.float32))

        def loss(p, xx, impl):
            # a random cotangent (via the elementwise product) exercises
            # every output position's contribution to both grads
            return jnp.sum(Conv3D(impl=impl, **kw).apply(p, xx) * cot)

        gp_ref, gx_ref = jax.grad(loss, argnums=(0, 1))(params, x, "native")
        gp, gx = jax.grad(loss, argnums=(0, 1))(params, x, "im2col")
        np.testing.assert_allclose(
            np.asarray(gp["params"]["kernel"]),
            np.asarray(gp_ref["params"]["kernel"]), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_unknown_impl_raises(self):
        from milnce_tpu.models.conv3d import Conv3D

        x = jnp.zeros((1, 3, 8, 8, 2), jnp.float32)
        conv = Conv3D(features=4, kernel_size=(1, 1, 1), impl="wat")
        with pytest.raises(ValueError, match="unknown conv impl"):
            conv.init(jax.random.PRNGKey(0), x)


class TestConvImplMap:
    """Per-stage impl map threading: S3D resolves (stage, impl) pairs at
    probe granularity, param trees stay identical, unnamed stages fall
    back to the uniform conv_impl."""

    def test_map_overrides_resolve_per_stage(self):
        m = tiny_model(conv_impl="fold2d",
                       conv_impl_map=(("conv1", "im2col"),
                                      ("mixed_4d", "native")))
        video = jnp.zeros((1, 4, 32, 32, 3), jnp.float32)
        text = jnp.zeros((1, 6), jnp.int32)
        variables = m.init(jax.random.PRNGKey(0), video, text)
        bound = m.bind(variables)
        assert bound.conv1.conv_impl == "im2col"
        assert bound.mixed_4d.conv_impl == "native"
        # unnamed stages keep the uniform default
        assert bound.conv_2c.conv_impl == "fold2d"
        assert bound.mixed_3b.conv_impl == "fold2d"

    def test_mapped_model_matches_native_forward(self):
        video = jnp.asarray(np.random.RandomState(0)
                            .rand(1, 4, 32, 32, 3).astype(np.float32))
        text = jnp.zeros((1, 6), jnp.int32)
        native = tiny_model()
        variables = native.init(jax.random.PRNGKey(0), video, text)
        v_ref, _ = native.apply(variables, video, text)
        mapped = tiny_model(conv_impl_map=(("conv1", "im2col"),
                                           ("mixed_3b", "fold2d")))
        v_out, _ = mapped.apply(variables, video, text)
        np.testing.assert_allclose(np.asarray(v_out), np.asarray(v_ref),
                                   rtol=1e-4, atol=1e-4)
