"""Planted GL017 fixture (tests/test_numerics.py).

Lives under a ``losses/`` path segment on purpose: the AST half of
GL017 (analysis/astlint.py check_exp_stability) is scoped to loss
modules.  Exactly ONE finding must fire here — the bare ``exp`` in
``unguarded_softmax`` — and every guarded idiom below must stay silent,
so the test pins both the hit and the non-hits.
"""

import jax.numpy as jnp


def unguarded_softmax(scores):
    # the planted GL017: exp over raw scores, no max-subtraction —
    # overflows f32 as soon as a dot product exceeds ~88
    weights = jnp.exp(scores)
    return weights / (weights.sum(axis=-1, keepdims=True) + 1e-6)


def guarded_softmax(scores):
    # silent: the house idiom — subtract the row max before exp
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    weights = jnp.exp(scores - row_max)
    return weights / (weights.sum(axis=-1, keepdims=True) + 1e-6)


def guarded_via_lse(scores, row_lse):
    # silent: subtracting a logsumexp-derived name is a guard reference
    return jnp.exp(scores - row_lse)


def masked_mean(values, mask):
    # silent: the denominator has a maximum floor, not a bare sum
    return (values * mask).sum() / jnp.maximum(mask.sum(), 1.0)
