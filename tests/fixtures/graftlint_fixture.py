"""graftlint test fixture — LINTED AS SOURCE, NEVER IMPORTED.

Every rule is violated exactly once unsuppressed, and once more under an
inline suppression, so tests/test_graftlint.py can pin EXACT per-rule
finding counts (a lint whose counts drift is a lint nobody trusts).
Four GL000 cases at the bottom pin the meta-rule: a reasonless
suppression, an unknown rule, a STALE suppression (well-formed but its
rule no longer fires on that line), and a suppression of an entry-level
planner rule (GL013-GL015 attach to registered trace entries, never to
source lines — the sanctioned route is re-pinning analysis/memplan.py).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


# ---- GL002 traced-python-flow ------------------------------------------

@jax.jit
def traced_flow(x):
    if x > 0:                       # GL002: Python `if` on a tracer
        y = x
    else:
        y = -x
    for _ in x:  # graftlint: disable=GL002(fixture: the audited suppressed occurrence)
        y = y + 1
    return y


# ---- GL006 print-under-trace -------------------------------------------

@jax.jit
def traced_print(x):
    print("tracing", x)             # GL006: fires once, shows tracers
    return jnp.sum(x)


@jax.jit
def traced_print_suppressed(x):
    print("known")  # graftlint: disable=GL006(fixture: the audited suppressed occurrence)
    return x


# ---- GL001 host-sync-hot-loop ------------------------------------------

def hot_loop(loader, mesh, step_fn, state):
    from milnce_tpu.data.pipeline import device_prefetch

    for batch in device_prefetch(loader, mesh, "data"):
        state, loss = step_fn(state, batch)
        lv = float(loss)            # GL001: per-step host sync
        _ = jax.device_get(loss)  # graftlint: disable=GL001(fixture: the audited suppressed occurrence)
        del lv
    return state


# ---- GL003 jit-missing-donate ------------------------------------------

def train_step(state, batch):
    return state


jitted_bad = jax.jit(train_step)    # GL003: no donate_argnums
jitted_ok = jax.jit(train_step, donate_argnums=(0,))
jitted_sup = jax.jit(train_step)  # graftlint: disable=GL003(fixture: the audited suppressed occurrence)


# ---- GL004 f64-literal-drift -------------------------------------------

bad_pad = jnp.asarray(0.5)          # GL004: f64 under x64
ok_pad = jnp.asarray(0.5, jnp.float32)
sup_pad = np.zeros((4,))  # graftlint: disable=GL004(fixture: the audited suppressed occurrence)


# ---- GL005 unsynced-walltime -------------------------------------------

def naive_timing(f, x):
    t0 = time.time()                # GL005: measures enqueue, not work
    f(x)
    return time.time() - t0


def audited_timing(f, x):
    # graftlint: disable=GL005(fixture: the audited suppressed occurrence)
    t0 = time.perf_counter()
    f(x)
    return time.perf_counter() - t0


# ---- GL007 swallowed-broad-except --------------------------------------

def swallow(f):
    try:
        return f()
    except Exception:               # GL007: error dropped on the floor
        return None


def swallow_suppressed(f):
    try:
        return f()
    except Exception:  # graftlint: disable=GL007(fixture: the audited suppressed occurrence)
        return None


def broad_but_recorded(f, log):
    try:
        return f()
    except Exception as exc:        # ok: the bound exception is recorded
        log(exc)
        return None


# ---- GL008 obs-under-trace ---------------------------------------------

class _Meter:                           # registry-metric stand-in
    def inc(self):
        pass


METER = _Meter()


@jax.jit
def traced_obs(x):
    METER.inc()                     # GL008: host telemetry under trace
    return x


@jax.jit
def traced_obs_suppressed(x):
    METER.inc()  # graftlint: disable=GL008(fixture: the audited suppressed occurrence)
    return x


# ---- GL009 phantom-mesh-axis -------------------------------------------

from jax.sharding import PartitionSpec as P  # noqa: E402


def constrain_typo(x):
    # GL009: 'modle' is declared by no mesh — GSPMD silently replicates
    return jax.lax.with_sharding_constraint(x, P("modle", None))


def constrain_foreign(x):
    return jax.lax.with_sharding_constraint(x, P("expert"))  # graftlint: disable=GL009(fixture: the audited suppressed occurrence)


def constrain_ok(x):
    return jax.lax.with_sharding_constraint(x, P("data", "model"))


# ---- GL010 unguarded-shared-state --------------------------------------

import threading  # noqa: E402


class UnguardedStats:
    """Thread-shared (owns + acquires a lock): one unguarded shared
    write, one suppressed guarded-write violation, plus the write-once
    and annotated exemptions the rule documents."""

    def __init__(self):
        self._lock = threading.Lock()
        self.calls = 0
        self.errors = 0
        self.mode = "ladder"            # write-once: lock-free reads ok
        self.depth = 2  # guarded-by: _lock

    def record(self):
        self.calls += 1                 # GL010: unguarded shared write
        with self._lock:
            self.errors += 1            # infers the guard: errors -> _lock

    def snapshot(self):
        with self._lock:
            errs = self.errors          # ok: read under the guard
        return {"mode": self.mode,      # ok: write-once lock-free read
                "depth": self.depth,    # ok: annotated write-once read
                "errors": errs, "calls": self.calls}

    def reset(self):
        self.errors = 0  # graftlint: disable=GL010(fixture: the audited suppressed occurrence)


# ---- GL011 lock-order-cycle --------------------------------------------

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()
_LOCK_C = threading.Lock()
_LOCK_D = threading.Lock()


def ordered_ab():
    with _LOCK_A:
        with _LOCK_B:                   # establishes A -> B
            pass


def ordered_ba():
    with _LOCK_B:
        with _LOCK_A:                   # GL011: closes the A/B cycle
            pass


def ordered_cd():
    with _LOCK_C:
        with _LOCK_D:                   # establishes C -> D
            pass


def ordered_dc():
    with _LOCK_D:
        # graftlint: disable=GL011(fixture: the audited suppressed occurrence)
        with _LOCK_C:
            pass


# ---- GL012 blocking-under-lock -----------------------------------------

def wait_under_lock(fut):
    with _LOCK_A:
        return fut.result()             # GL012: every A contender stalls


def read_under_lock(path):
    with _LOCK_B:
        with open(path) as fh:  # graftlint: disable=GL012(fixture: the audited suppressed occurrence)
            return fh.read()


def wait_outside_lock(fut):
    with _LOCK_A:
        state = dict(ready=True)        # ok: copy state under the lock,
    del state                           # block after release
    return fut.result()


# ---- GL000 bad-suppression ---------------------------------------------

x_no_reason = 1  # graftlint: disable=GL001
x_unknown_rule = 2  # graftlint: disable=GL999(no such rule)
x_stale = 3  # graftlint: disable=GL001(fixture: stale — GL001 does not fire here)
x_entry_level = 4  # graftlint: disable=GL013(planner rules pin entries, not source lines)
x_entry_level_numerics = 5  # graftlint: disable=GL018(numerics rules pin entries, not source lines)
