"""Sequence-parallel soft-DTW (ops/softdtw_sp.py) vs the scan golden on
the virtual 8-device mesh: values, gradients, rectangular shapes,
bandwidth, and row counts that don't divide the device count."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from milnce_tpu.ops.softdtw import softdtw_scan
from milnce_tpu.ops.softdtw_sp import softdtw_seq_parallel


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def _cost(b, n, m, seed=0):
    return jnp.asarray(np.random.RandomState(seed).rand(b, n, m)
                       .astype(np.float32))


@pytest.mark.parametrize("b,n,m", [(3, 16, 16), (2, 24, 10), (2, 9, 17)])
def test_matches_scan_golden(b, n, m):
    D = _cost(b, n, m, seed=n + m)
    want = np.asarray(softdtw_scan(D, 0.5))
    got = np.asarray(softdtw_seq_parallel(D, 0.5, _mesh()))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rows_not_divisible_by_devices():
    # N=13 over 8 devices: padded rows must stay masked out
    D = _cost(2, 13, 11, seed=3)
    want = np.asarray(softdtw_scan(D, 0.3))
    got = np.asarray(softdtw_seq_parallel(D, 0.3, _mesh()))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fewer_rows_than_devices():
    # N=5 over 8 devices: some shards own only padded rows
    D = _cost(2, 5, 7, seed=4)
    want = np.asarray(softdtw_scan(D, 0.5))
    got = np.asarray(softdtw_seq_parallel(D, 0.5, _mesh()))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_bandwidth_matches_scan():
    D = _cost(2, 16, 16, seed=5)
    want = np.asarray(softdtw_scan(D, 0.5, bandwidth=3))
    got = np.asarray(softdtw_seq_parallel(D, 0.5, _mesh(), bandwidth=3))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_gradient_matches_scan_autodiff():
    """JAX AD through the shard_map program (ppermute transpose) must give
    the same E-matrix gradient as AD through the scan golden."""
    D = _cost(2, 16, 12, seed=6)
    mesh = _mesh()
    want = np.asarray(jax.grad(lambda d: softdtw_scan(d, 0.7).sum())(D))
    got = np.asarray(jax.grad(
        lambda d: softdtw_seq_parallel(d, 0.7, mesh).sum())(D))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_long_sequence_beyond_reference_cap():
    """Lengths past the reference's 1024-thread CUDA cap are the point:
    a 512x512 alignment (table would be ~1M cells/pair) runs row-sharded
    with each device holding 1/8 of every diagonal."""
    D = _cost(1, 512, 512, seed=7)
    want = np.asarray(softdtw_scan(D, 0.5))
    got = np.asarray(softdtw_seq_parallel(D, 0.5, _mesh()))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sp_bandwidth_narrower_than_length_gap_rejected():
    import pytest

    from milnce_tpu.ops.softdtw_sp import softdtw_seq_parallel

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    D = jnp.ones((2, 10, 4), jnp.float32)
    with pytest.raises(ValueError, match="bandwidth"):
        softdtw_seq_parallel(D, 1.0, mesh, bandwidth=3)
