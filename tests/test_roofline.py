"""Analytic FLOPs model vs XLA's own cost analysis.

The roofline model (utils/roofline.py) feeds bench.py's MFU diagnostic
when XLA cost analysis is unavailable, so its totals must track what XLA
counts: convolution math dominates, elementwise work is excluded, so the
analytic number is expected a little UNDER XLA's — pinned to a band.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.utils.roofline import (roofline_table, s3d_video_stages,
                                       text_fwd_flops, train_step_flops,
                                       video_fwd_flops)


def _xla_flops(fn, *args):
    cost = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["flops"])


@pytest.mark.slow
def test_video_fwd_tracks_xla():
    from milnce_tpu.models import S3D

    batch, frames, size = 2, 4, 64
    model = S3D(num_classes=64, vocab_size=128, word_embedding_dim=32,
                text_hidden_dim=64, inception_blocks=9)
    video = jnp.zeros((batch, frames, size, size, 3), jnp.float32)
    text = jnp.zeros((2, 6), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), video, text)

    got = _xla_flops(
        lambda v: model.apply(variables, v, None, mode="video"), video)
    want = video_fwd_flops(batch, frames, size, embedding_dim=64)
    # analytic excludes BN/ReLU/pool/gating-mult vector flops -> under,
    # but conv math must dominate
    assert 0.75 * got <= want <= 1.05 * got, (want, got)


@pytest.mark.slow
def test_video_fwd_tracks_xla_s2d():
    from milnce_tpu.models import S3D

    batch, frames, size = 2, 4, 64
    model = S3D(num_classes=64, vocab_size=128, word_embedding_dim=32,
                text_hidden_dim=64, inception_blocks=9,
                use_space_to_depth=True)
    video = jnp.zeros((batch, frames, size, size, 3), jnp.float32)
    text = jnp.zeros((2, 6), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), video, text)
    got = _xla_flops(
        lambda v: model.apply(variables, v, None, mode="video"), video)
    want = video_fwd_flops(batch, frames, size, space_to_depth=True,
                           embedding_dim=64)
    assert 0.75 * got <= want <= 1.05 * got, (want, got)


def test_text_fwd_tracks_xla():
    from milnce_tpu.models import S3D

    model = S3D(num_classes=64, vocab_size=128, word_embedding_dim=32,
                text_hidden_dim=64, inception_blocks=1)
    text = jnp.zeros((6, 5), jnp.int32)
    video = jnp.zeros((2, 4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), video,
                           jnp.zeros((2, 5), jnp.int32))
    got = _xla_flops(
        lambda t: model.apply(variables, None, t, mode="text"), text)
    want = text_fwd_flops(6, 5, word_dim=32, hidden=64, embedding_dim=64)
    assert 0.7 * got <= want <= 1.1 * got, (want, got)


@pytest.mark.slow
def test_train_step_tracks_xla():
    """The bench fallback path: full train-step estimate (3x fwd + logits)
    vs XLA's count of the real sharded step program."""
    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    batch, frames, size, k, words = 8, 4, 32, 3, 6
    model = S3D(num_classes=64, vocab_size=128, word_embedding_dim=32,
                text_hidden_dim=64, inception_blocks=9)
    video = np.zeros((batch, frames, size, size, 3), np.uint8)
    text = np.zeros((batch * k, words), np.int32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3), jnp.float32),
                           jnp.zeros((2 * k, words), jnp.int32))
    optimizer = build_optimizer(OptimConfig(warmup_steps=2),
                                build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, optimizer)
    mesh = build_mesh(ParallelConfig())
    step = make_train_step(model, optimizer, mesh, donate=False)

    cost = step.lower(state, video, text,
                      np.zeros((batch,), np.float32)).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    # XLA reports the PER-SHARD program of a shard_map'ed step; the
    # analytic estimate is global — scale by the mesh size
    got = float(cost["flops"]) * len(jax.devices())
    want = train_step_flops(batch, frames, size, k, words, embedding_dim=64,
                            word_dim=32, hidden=64)
    # XLA's backward bookkeeping and the excluded vector work widen the
    # band vs the forward-only tests; the estimate must still land in the
    # same ballpark for MFU to be meaningful
    assert 0.6 * got <= want <= 1.4 * got, (want, got)


def test_roofline_table_renders():
    table = roofline_table(256, 16, 224)
    assert "conv1" in table and "mixed_5c" in table and "total fwd trunk" in table
    # the HBM-bound stages on v5e are the 1x1 convs (tiny fan-in over big
    # activations), not conv1 (441-tap fan-in -> AI ~300, MXU-bound)
    conv1_row = next(l for l in table.splitlines() if "| conv1 |" in l)
    assert "MXU" in conv1_row
    c2b_row = next(l for l in table.splitlines() if "| conv_2b |" in l)
    assert "HBM" in c2b_row


@pytest.mark.slow
def test_stage_shapes_match_model():
    """The stage list's final shape must equal the real trunk output."""
    from milnce_tpu.models import S3D

    model = S3D(num_classes=64, vocab_size=128, word_embedding_dim=32,
                text_hidden_dim=64, inception_blocks=9)
    video = jnp.zeros((2, 4, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), video,
                           jnp.zeros((2, 6), jnp.int32))
    feats = model.apply(variables, video, None, mode="video", mixed5c=True)
    stages = s3d_video_stages(2, 4, 64)
    assert stages[-1].out_shape[-1] == feats.shape[-1] == 1024
