"""Data layer: caption/candidate sampling, decode helpers, dataset sources
(hermetic via FakeDecoder; behavior spec: reference video_loader.py and
the three eval loaders)."""

import json

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset
from milnce_tpu.data.captions import (CaptionTrack, nearest_candidate_window,
                                      sample_caption, widen_to_min_time)
from milnce_tpu.data.tokenizer import Tokenizer
from milnce_tpu.data.video import FakeDecoder, eval_windows, pad_or_trim


def track(starts, ends, texts=None):
    return CaptionTrack(np.asarray(starts, float), np.asarray(ends, float),
                        texts or [f"t{i}" for i in range(len(starts))])


class TestCandidateWindow:
    def test_middle_grows_to_nearest(self):
        # captions at [0,10],[10,12],[12,14],[14,16],[30,40]; ind=2, K=3:
        # growing left (12-10=2 wider span) vs right (16-12) chooses left
        t = track([0, 10, 12, 14, 30], [10, 12, 14, 16, 40])
        start = nearest_candidate_window(t, 2, 3)
        assert start == 1  # window {1,2,3}: tight middle captions

    def test_left_edge_clamps_to_zero(self):
        t = track([0, 5, 10], [5, 10, 15])
        assert nearest_candidate_window(t, 0, 3) == 0

    def test_right_edge_backfills(self):
        t = track([0, 5, 10, 15], [5, 10, 15, 20])
        # ind at last caption: window backfills from the left
        assert nearest_candidate_window(t, 3, 3) == 1

    def test_k1_is_identity(self):
        t = track([0, 5], [5, 10])
        assert nearest_candidate_window(t, 1, 1) == 1


class TestWidenMinTime:
    def test_short_clip_widened_centered(self):
        s, e = widen_to_min_time(10.0, 11.0, 5.0)
        assert (s, e) == (8, 13)

    def test_clamped_at_zero(self):
        s, e = widen_to_min_time(0.5, 1.0, 5.0)
        assert s == 0 and e == 5

    def test_long_clip_untouched(self):
        assert widen_to_min_time(3.0, 20.0, 5.0) == (3, 20)


def test_sample_caption_shapes_and_determinism():
    t = track([0, 5, 10, 15], [5, 10, 15, 20],
              ["word1 word2", "word3", "word1", "word2 word3"])
    tok = Tokenizer([f"word{i}" for i in range(1, 6)], max_words=4)
    tokens, start, end = sample_caption(t, np.random.RandomState(0), tok,
                                        num_candidates=3, max_words=4,
                                        min_time=5.0)
    assert tokens.shape == (3, 4) and tokens.dtype == np.int32
    assert end - start >= 5
    tokens2, *_ = sample_caption(t, np.random.RandomState(0), tok, 3, 4, 5.0)
    np.testing.assert_array_equal(tokens, tokens2)


def test_pad_or_trim():
    x = np.ones((5, 4, 4, 3), np.uint8)
    assert pad_or_trim(x, 8).shape == (8, 4, 4, 3)
    assert pad_or_trim(x, 8)[5:].sum() == 0  # zero tail
    assert pad_or_trim(x, 3).shape == (3, 4, 4, 3)


def test_eval_windows_deterministic_and_shaped():
    dec = FakeDecoder()
    w1 = eval_windows(dec, "vid.mp4", 0.0, 30.0, num_clip=4, num_frames=4,
                      fps=2, size=8)
    w2 = eval_windows(dec, "vid.mp4", 0.0, 30.0, num_clip=4, num_frames=4,
                      fps=2, size=8)
    assert w1.shape == (4, 4, 8, 8, 3) and w1.dtype == np.uint8
    np.testing.assert_array_equal(w1, w2)


@pytest.fixture
def howto_dir(tmp_path):
    """Tiny on-disk HowTo100M layout: manifest csv + caption JSONs."""
    (tmp_path / "videos").mkdir()
    (tmp_path / "captions").mkdir()
    rows = ["video_path"]
    for i in range(4):
        vid = f"vid{i}"
        rows.append(f"{vid}.mp4")
        caps = {"start": [0, 6, 12], "end": [6, 12, 18],
                "text": [f"word{i} word2", "word3 word4", "word5"]}
        (tmp_path / "captions" / f"{vid}.json").write_text(json.dumps(caps))
    (tmp_path / "train.csv").write_text("\n".join(rows))
    return tmp_path


def test_howto100m_source(howto_dir):
    from milnce_tpu.data.datasets import HowTo100MSource

    cfg = tiny_preset()
    cfg.data.train_csv = str(howto_dir / "train.csv")
    cfg.data.video_root = str(howto_dir / "videos")
    cfg.data.caption_root = str(howto_dir / "captions")
    cfg.data.num_candidates = 3
    tok = Tokenizer([f"word{i}" for i in range(1, 8)], cfg.data.max_words)
    src = HowTo100MSource(cfg.data, cfg.model, decoder=FakeDecoder(),
                          tokenizer=tok)
    assert len(src) == 4
    s = src.sample(1, np.random.RandomState(0))
    c = cfg.data
    assert s["video"].shape == (c.num_frames, c.video_size, c.video_size, 3)
    assert s["video"].dtype == np.uint8
    assert s["text"].shape == (3, c.max_words)


class FlakyDecoder(FakeDecoder):
    """Raises on a deterministic subset of paths (simulated corrupt files —
    HowTo100M at scale has thousands; VERDICT r1 #5 / SURVEY §7 hard 2).
    Selection is by the digits in the filename (vid0, vid1, ...), never by
    hash() — which is per-process-randomized and would make tests flaky."""

    def __init__(self, bad_every: int = 0, **kw):
        super().__init__(**kw)
        self.bad_every = bad_every
        self.failures = 0

    def decode(self, path, *a, **kw):
        num = int("".join(c for c in path if c.isdigit()) or 0)
        if self.bad_every and num % self.bad_every == 0:
            self.failures += 1
            raise RuntimeError(f"simulated corrupt video: {path}")
        return super().decode(path, *a, **kw)


def _howto_source(howto_dir, decoder, n_candidates=3):
    from milnce_tpu.data.datasets import HowTo100MSource

    cfg = tiny_preset()
    cfg.data.train_csv = str(howto_dir / "train.csv")
    cfg.data.video_root = str(howto_dir / "videos")
    cfg.data.caption_root = str(howto_dir / "captions")
    cfg.data.num_candidates = n_candidates
    tok = Tokenizer([f"word{i}" for i in range(1, 8)], cfg.data.max_words)
    return cfg, HowTo100MSource(cfg.data, cfg.model, decoder=decoder,
                                tokenizer=tok)


def test_bad_videos_resampled_full_batches(howto_dir):
    """A decoder failing on a subset of paths must not kill the epoch:
    the source resamples and every batch comes out full."""
    from milnce_tpu.data.pipeline import ShardedLoader

    dec = FlakyDecoder(bad_every=3)  # ~1/3 of paths raise
    cfg, src = _howto_source(howto_dir, dec)
    loader = ShardedLoader(src, global_batch_size=2, seed=0, num_threads=2,
                           process_index=0, process_count=1)
    batches = list(loader.epoch(0))
    assert len(batches) == 2
    for b in batches:
        assert b["video"].shape[0] == 2
        assert b["text"].shape == (2, 3, cfg.data.max_words)
    assert dec.failures > 0              # the flaky paths were actually hit
    assert src.decode_failures == dec.failures


def test_all_bad_videos_black_frame_fallback(howto_dir):
    """Every path failing: bounded retries, then a black-frame sample
    (never an exception, never a stalled step)."""

    class AlwaysBad(FakeDecoder):
        def decode(self, *a, **kw):
            raise RuntimeError("all videos corrupt")

    cfg, src = _howto_source(howto_dir, AlwaysBad())
    s = src.sample(0, np.random.RandomState(0))
    c = cfg.data
    assert s["video"].shape == (c.num_frames, c.video_size, c.video_size, 3)
    assert s["video"].sum() == 0
    assert s["text"].shape == (3, c.max_words) and s["text"].dtype == np.int32
    assert src.decode_failures == src.MAX_RETRIES + 1


def test_hmdb_label_stripping():
    from milnce_tpu.data.datasets import HMDBSource

    assert HMDBSource.label_of("brush_hair_test") == "brush_hair"
    assert HMDBSource.label_of("wave") == "wave"


class TestManifestTool:
    @pytest.fixture
    def tree(self, tmp_path):
        (tmp_path / "videos" / "a").mkdir(parents=True)
        (tmp_path / "captions").mkdir()
        for i in range(3):
            (tmp_path / "videos" / "a" / f"vid{i}.mp4").write_bytes(b"x")
        (tmp_path / "videos" / "notes.txt").write_text("not a video")
        for i in range(2):   # captions only for vid0/vid1
            (tmp_path / "captions" / f"vid{i}.json").write_text(
                json.dumps({"start": [0], "end": [5], "text": ["hi"]}))
        return tmp_path

    def test_build_and_validate_roundtrip(self, tree):
        from milnce_tpu.data.manifest import build, validate

        out = tree / "train.csv"
        n, skipped = build(str(tree / "videos"), str(out),
                           caption_root=str(tree / "captions"))
        assert (n, skipped) == (2, 1)        # vid2 has no captions
        rep = validate(str(out), video_root=str(tree / "videos"),
                       caption_root=str(tree / "captions"))
        assert rep == {"rows": 2, "missing_video": 0,
                       "missing_captions": 0, "bad_captions": 0}

    def test_built_manifest_feeds_the_source(self, tree):
        from milnce_tpu.data.datasets import HowTo100MSource
        from milnce_tpu.data.manifest import build

        out = tree / "train.csv"
        build(str(tree / "videos"), str(out),
              caption_root=str(tree / "captions"))
        cfg = tiny_preset()
        cfg.data.train_csv = str(out)
        cfg.data.video_root = str(tree / "videos")
        cfg.data.caption_root = str(tree / "captions")
        tok = Tokenizer(["hi"], cfg.data.max_words)
        src = HowTo100MSource(cfg.data, cfg.model, decoder=FakeDecoder(),
                              tokenizer=tok)
        s = src.sample(0, np.random.RandomState(0))
        assert s["video"].shape[0] == cfg.data.num_frames

    def test_validate_flags_problems(self, tree):
        from milnce_tpu.data.manifest import build, validate

        out = tree / "all.csv"
        build(str(tree / "videos"), str(out))    # includes caption-less vid2
        (tree / "captions" / "vid1.json").write_text("{not json")
        rep = validate(str(out), caption_root=str(tree / "captions"))
        assert rep["rows"] == 3
        assert rep["missing_captions"] == 1      # vid2
        assert rep["bad_captions"] == 1          # vid1

    def test_cli(self, tree, capsys):
        from milnce_tpu.data.manifest import main

        rc = main(["build", str(tree / "videos"), "--out",
                   str(tree / "m.csv")])
        assert rc == 0
        assert "3 videos" in capsys.readouterr().out


def test_ffmpeg_decoder_gated_without_binary(monkeypatch):
    from milnce_tpu.data.video import FFmpegDecoder

    dec = FFmpegDecoder(binary="definitely-not-a-binary-xyz")
    with pytest.raises(RuntimeError, match="synthetic"):
        dec.decode("x.mp4", 0, 1.0, 2, 8)


def test_loader_skip_batches_resumes_exact_order():
    """epoch(skip_batches=k) must yield exactly the batches epoch() yields
    after the first k — the mid-epoch resume contract (sample content is a
    pure function of (seed, epoch, index), so nothing is decoded twice)."""
    from milnce_tpu.config import DataConfig
    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource

    cfg = DataConfig(synthetic=True, synthetic_num_samples=24, num_frames=2,
                     video_size=8, max_words=4, num_candidates=2)
    src = SyntheticVideoTextSource(cfg, vocab_size=16)
    loader = ShardedLoader(src, global_batch_size=4, seed=3, num_threads=2,
                           process_index=0, process_count=1)
    full = list(loader.epoch(epoch=1))
    skipped = list(loader.epoch(epoch=1, skip_batches=2))
    assert len(skipped) == len(full) - 2
    for a, b in zip(full[2:], skipped):
        for key in a:
            np.testing.assert_array_equal(a[key], b[key])
