"""DTW-loss training path: sequence-mode model + loss dispatch + sharded
step (the fork's temporal-alignment training, made runnable — its
committed trainers are import-broken, SURVEY.md §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.config import LossConfig


def _tiny_model():
    from milnce_tpu.models import S3D

    return S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
               text_hidden_dim=16)


@pytest.mark.slow
def test_sequence_mode_shapes():
    model = _tiny_model()
    video = jnp.zeros((2, 8, 32, 32, 3), jnp.float32)
    text = jnp.zeros((6, 5), jnp.int32)          # B*K rows, K=3
    variables = model.init(jax.random.PRNGKey(0), video, text)
    v_seq, t_emb = model.apply(variables, video, text, mode="sequence")
    # T=8 -> conv1 stride 2 -> 4 -> maxpool_4a -> 2 -> maxpool_5a -> 1
    assert v_seq.shape == (2, 1, 16)
    assert t_emb.shape == (6, 16)


@pytest.mark.parametrize("loss_name", ["cdtw", "sdtw_cidm", "sdtw_negative",
                                       "sdtw_3"])
@pytest.mark.slow
def test_dtw_loss_train_step(loss_name):
    from milnce_tpu.config import OptimConfig
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    model = _tiny_model()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    b, k, frames, size, words = 8, 2, 8, 32, 5
    rng = np.random.RandomState(0)
    video = rng.randint(0, 255, (b, frames, size, size, 3), np.uint8)
    text = rng.randint(0, 64, (b * k, words)).astype(np.int32)
    start = (np.arange(b) * 7.0).astype(np.float32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3)),
                           jnp.zeros((2 * k, words), jnp.int32))
    optim_cfg = OptimConfig(warmup_steps=2)
    optimizer = build_optimizer(optim_cfg, build_schedule(optim_cfg, 10))
    state = create_train_state(variables, optimizer)
    step_fn = make_train_step(model, optimizer, mesh,
                              loss_cfg=LossConfig(name=loss_name))

    sh = NamedSharding(mesh, P("data"))
    state, loss = step_fn(state,
                          jax.device_put(video, sh),
                          jax.device_put(text, sh),
                          jax.device_put(start, sh))
    assert np.isfinite(float(loss)), (loss_name, float(loss))
    assert int(state.step) == 1


def test_unknown_loss_rejected():
    from milnce_tpu.config import OptimConfig
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer
    from milnce_tpu.train.step import make_grad_cache_step, make_train_step

    mesh = Mesh(np.array(jax.devices()), ("data",))
    optim_cfg = OptimConfig(warmup_steps=2)
    optimizer = build_optimizer(optim_cfg, build_schedule(optim_cfg, 10))
    # rejected at BUILD time — a bad name must not cost params or a
    # trace/compile (on a pod, a typo'd flag would otherwise only
    # surface after minutes of XLA compile)
    with pytest.raises(ValueError, match="bogus"):
        make_train_step(_tiny_model(), optimizer, mesh,
                        loss_cfg=LossConfig(name="bogus"))
    with pytest.raises(ValueError, match="bogus"):
        make_grad_cache_step(_tiny_model(), optimizer, mesh, 2,
                             loss_cfg=LossConfig(name="bogus"))


@pytest.mark.slow
def test_pallas_backend_selected_from_config_matches_scan():
    """--loss.sdtw_backend pallas trains on the TPU kernel (VERDICT r1
    missing #4): the sharded step must produce the same loss as the scan
    backend (interpret mode on CPU; the identical code path compiles on
    TPU)."""
    from milnce_tpu.config import OptimConfig
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    model = _tiny_model()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    b, k, frames, size, words = 8, 2, 8, 32, 5
    rng = np.random.RandomState(1)
    video = rng.randint(0, 255, (b, frames, size, size, 3), np.uint8)
    text = rng.randint(0, 64, (b * k, words)).astype(np.int32)
    start = np.zeros((b,), np.float32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3)),
                           jnp.zeros((2 * k, words), jnp.int32))
    optim_cfg = OptimConfig(warmup_steps=2)
    optimizer = build_optimizer(optim_cfg, build_schedule(optim_cfg, 10))
    state = create_train_state(variables, optimizer)
    sh = NamedSharding(mesh, P("data"))
    args = (jax.device_put(video, sh), jax.device_put(text, sh),
            jax.device_put(start, sh))

    losses = {}
    for backend in ("scan", "pallas"):
        step_fn = make_train_step(
            model, optimizer, mesh, donate=False,
            loss_cfg=LossConfig(name="sdtw_3", sdtw_backend=backend))
        _, loss = step_fn(state, *args)
        losses[backend] = float(loss)
        assert np.isfinite(losses[backend]), (backend, losses[backend])
    np.testing.assert_allclose(losses["pallas"], losses["scan"], rtol=1e-4)
