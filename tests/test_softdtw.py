"""Soft-DTW: the lax.scan DP vs an independent numpy triple-loop golden,
gradients vs the analytic E-matrix recursion, distance-function goldens.

(This replicates — hermetically — the reference's only correctness check,
the CPU<->GPU allclose cross-check at soft_dtw_cuda.py:439-440.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.ops.softdtw import (SoftDTW, cosine_cost, euclidean_cost,
                                    negative_dot_cost, skew_cost, softdtw_scan)


def numpy_softdtw(D, gamma, bandwidth=0):
    """Triple-loop DP golden (independent transcription of the Cuturi-
    Blondel recurrence, cf. soft_dtw_cuda.py:185-207)."""
    B, N, M = D.shape
    R = np.full((B, N + 2, M + 2), np.inf)
    R[:, 0, 0] = 0.0
    for b in range(B):
        for j in range(1, M + 1):
            for i in range(1, N + 1):
                if 0 < bandwidth < abs(i - j):
                    continue
                r = np.array([-R[b, i - 1, j - 1], -R[b, i - 1, j],
                              -R[b, i, j - 1]]) / gamma
                rmax = r.max()
                softmin = -gamma * (np.log(np.exp(r - rmax).sum()) + rmax)
                R[b, i, j] = D[b, i - 1, j - 1] + softmin
    return R[:, N, M], R


def numpy_softdtw_grad(D, R, gamma):
    """Analytic backward (E-matrix recursion, cf. soft_dtw_cuda.py:211-240)."""
    B, N, M = D.shape
    D_ = np.zeros((B, N + 2, M + 2))
    E = np.zeros((B, N + 2, M + 2))
    D_[:, 1:N + 1, 1:M + 1] = D
    E[:, -1, -1] = 1.0
    R = R.copy()
    R[:, :, -1] = -np.inf
    R[:, -1, :] = -np.inf
    R[:, -1, -1] = R[:, -2, -2]
    for b in range(B):
        for j in range(M, 0, -1):
            for i in range(N, 0, -1):
                if np.isinf(R[b, i, j]):
                    R[b, i, j] = -np.inf
                a = np.exp((R[b, i + 1, j] - R[b, i, j] - D_[b, i + 1, j]) / gamma)
                bb = np.exp((R[b, i, j + 1] - R[b, i, j] - D_[b, i, j + 1]) / gamma)
                c = np.exp((R[b, i + 1, j + 1] - R[b, i, j] - D_[b, i + 1, j + 1]) / gamma)
                E[b, i, j] = E[b, i + 1, j] * a + E[b, i, j + 1] * bb + E[b, i + 1, j + 1] * c
    return E[:, 1:N + 1, 1:M + 1]


def test_skew_cost_layout():
    D = jnp.arange(6, dtype=jnp.float32).reshape(1, 2, 3)
    s = np.asarray(skew_cost(D))
    # out[p, i] = D[i, p - i]
    assert s.shape == (1, 4, 2)
    np.testing.assert_allclose(s[0, 0], [0, 0])        # D[0,0], pad
    np.testing.assert_allclose(s[0, 1], [1, 3])        # D[0,1], D[1,0]
    np.testing.assert_allclose(s[0, 2], [2, 4])
    np.testing.assert_allclose(s[0, 3], [0, 5])


@pytest.mark.parametrize("n,m,gamma", [(5, 5, 1.0), (7, 4, 0.1), (3, 9, 0.5)])
def test_forward_matches_numpy(n, m, gamma):
    rng = np.random.RandomState(0)
    D = rng.rand(3, n, m).astype(np.float32)
    expected, _ = numpy_softdtw(D.astype(np.float64), gamma)
    got = np.asarray(softdtw_scan(jnp.asarray(D), gamma))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_forward_with_bandwidth():
    rng = np.random.RandomState(1)
    D = rng.rand(2, 8, 8).astype(np.float32)
    expected, _ = numpy_softdtw(D.astype(np.float64), 0.5, bandwidth=2)
    got = np.asarray(softdtw_scan(jnp.asarray(D), 0.5, bandwidth=2))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_gradient_matches_analytic_e_matrix():
    rng = np.random.RandomState(2)
    gamma = 0.8
    D = rng.rand(2, 6, 5).astype(np.float32)
    _, R = numpy_softdtw(D.astype(np.float64), gamma)
    expected = numpy_softdtw_grad(D.astype(np.float64), R, gamma)
    grad = jax.grad(lambda d: softdtw_scan(d, gamma).sum())(jnp.asarray(D))
    np.testing.assert_allclose(np.asarray(grad), expected, rtol=1e-3, atol=1e-4)


def test_gradient_is_nan_free_for_long_sequences():
    rng = np.random.RandomState(3)
    D = rng.rand(1, 64, 64).astype(np.float32)
    grad = jax.grad(lambda d: softdtw_scan(d, 0.1).sum())(jnp.asarray(D))
    assert np.isfinite(np.asarray(grad)).all()


def test_distance_functions_match_naive():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(2, 5, 4).astype(np.float32)
    # naive loops
    def naive(fn):
        out = np.zeros((2, 3, 5), np.float32)
        for b in range(2):
            for i in range(3):
                for j in range(5):
                    out[b, i, j] = fn(x[b, i], y[b, j])
        return out

    np.testing.assert_allclose(
        np.asarray(euclidean_cost(jnp.asarray(x), jnp.asarray(y))),
        naive(lambda a, b: np.exp(np.linalg.norm(a - b))), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(cosine_cost(jnp.asarray(x), jnp.asarray(y))),
        naive(lambda a, b: np.exp(1 - a @ b / max(np.linalg.norm(a) * np.linalg.norm(b), 1e-8))),
        rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(negative_dot_cost(jnp.asarray(x), jnp.asarray(y))),
        naive(lambda a, b: -(a @ b)), rtol=1e-4, atol=1e-5)


def test_softdtw_module_normalize_self_is_zero():
    """normalize=True: sdtw(x, x) must be ~0 (soft_dtw_cuda.py:376-383)."""
    rng = np.random.RandomState(5)
    x = rng.randn(2, 6, 8).astype(np.float32)
    sdtw = SoftDTW(gamma=1.0, normalize=True, dist_func="euclidean")
    out = np.asarray(sdtw(jnp.asarray(x), jnp.asarray(x)))
    np.testing.assert_allclose(out, 0.0, atol=1e-3)


def test_no_length_cap():
    """Sequences beyond the reference's 1024 CUDA cap still run."""
    D = jnp.ones((1, 1100, 8), jnp.float32)
    out = softdtw_scan(D, 1.0)
    assert np.isfinite(float(out[0]))


@pytest.mark.slow
def test_auto_backend_dispatch(monkeypatch):
    """backend='auto' picks the kernel wherever a measured-winning layout
    applies (one-block sublane-batch, or batch-on-lanes at any batch) and
    the scan elsewhere; both arms must agree with the scan."""
    monkeypatch.delenv("MILNCE_SDTW_LANES", raising=False)
    from milnce_tpu.ops.softdtw import SoftDTW

    from milnce_tpu.ops.softdtw_pallas import (_batch_tile, fits_one_block,
                                               prefers_pallas)

    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(4, 10, 6).astype(np.float32))
    y = jnp.asarray(rng.randn(4, 8, 6).astype(np.float32))
    assert fits_one_block(4, 10, 8)            # -> pallas arm
    want = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine")(x, y))
    got = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine",
                             backend="auto")(x, y))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # pallas arm via lanes: batch beyond one sublane tile still routes to
    # the kernel (batch-on-lanes layout) and must agree
    big = _batch_tile(10, 8) + 8
    xb = jnp.asarray(rng.randn(big, 10, 6).astype(np.float32))
    yb = jnp.asarray(rng.randn(big, 8, 6).astype(np.float32))
    assert not fits_one_block(big, 10, 8) and prefers_pallas(big, 10, 8)
    want_b = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine")(xb, yb))
    got_b = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine",
                               backend="auto")(xb, yb))
    np.testing.assert_allclose(got_b, want_b, rtol=1e-5, atol=1e-6)

    # scan arm: tables past the Mosaic area cap (long pairs, multi-block
    # batch) dispatch to the scan and agree
    assert not prefers_pallas(40, 70, 70)
    xl = jnp.asarray(rng.randn(40, 70, 6).astype(np.float32))
    yl = jnp.asarray(rng.randn(40, 70, 6).astype(np.float32))
    want_l = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine")(xl, yl))
    got_l = np.asarray(SoftDTW(gamma=0.5, dist_func="cosine",
                               backend="auto")(xl, yl))
    np.testing.assert_allclose(got_l, want_l, rtol=1e-5, atol=1e-6)

    with np.testing.assert_raises(Exception):
        SoftDTW(backend="cuda")  # the reference's backend name is invalid


def test_bandwidth_narrower_than_length_gap_rejected():
    """A band that cannot cover |N-M| silently degenerates every value to
    the BIG sentinel (finite -> invisible to the NaN guard); it must be a
    loud static error on both backends."""
    import pytest

    from milnce_tpu.ops.softdtw_pallas import softdtw_pallas

    D = jnp.ones((2, 10, 4), jnp.float32)
    with pytest.raises(ValueError, match="bandwidth"):
        softdtw_scan(D, 1.0, bandwidth=3)
    with pytest.raises(ValueError, match="bandwidth"):
        softdtw_pallas(D, 1.0, 3)
    # a covering band is fine
    assert np.isfinite(float(softdtw_scan(D, 1.0, bandwidth=6)[0]))


def test_unknown_dist_func_named_error():
    import pytest

    from milnce_tpu.ops.softdtw import SoftDTW

    with pytest.raises(ValueError, match="sdtw_dist"):
        SoftDTW(dist_func="negative-dot")
