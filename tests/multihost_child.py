"""Child process + shared fixtures for test_multihost.py.

As __main__: join a 2-process jax.distributed cluster over loopback
(Gloo CPU collectives), run ONE sharded train step on a global mesh
spanning both processes, print the loss as JSON.  This is the real
multi-host path (parallel/mesh.py initialize_distributed with an
explicit coordinator — the replacement for the reference's hardcoded-IP
rendezvous, train.py:48-56), not the single-host no-op.

As a module: exposes the EXACT shapes/model/data used by the child so
the parent test's in-process cross-check consumes one definition
(import is side-effect-free; jax.config mutations happen only in
main()).
"""

import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

B_LOCAL, NPROCS, K, FRAMES, SIZE, WORDS = 2, 2, 2, 4, 32, 5
B_GLOBAL = B_LOCAL * NPROCS


def global_batch():
    """Identical deterministic global batch on every process; each holds
    its own slice (exactly the per-host loader contract)."""
    rng = np.random.RandomState(0)
    video = rng.randint(0, 255, (B_GLOBAL, FRAMES, SIZE, SIZE, 3), np.uint8)
    text = rng.randint(0, 32, (B_GLOBAL * K, WORDS)).astype(np.int32)
    start = np.zeros((B_GLOBAL,), np.float32)
    return video, text, start


def build_model_and_state():
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = jax.jit(lambda key: model.init(
        key, jnp.zeros((2, FRAMES, SIZE, SIZE, 3), jnp.float32),
        jnp.zeros((2 * K, WORDS), jnp.int32)))(jax.random.PRNGKey(0))
    ocfg = OptimConfig(warmup_steps=2)
    optimizer = build_optimizer(ocfg, build_schedule(ocfg, 10))
    return model, optimizer, create_train_state(variables, optimizer)


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jax: default implementation

    from jax.sharding import NamedSharding, PartitionSpec as P

    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.parallel.mesh import build_mesh, initialize_distributed
    from milnce_tpu.train.step import make_train_step

    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert nprocs == NPROCS, (nprocs, NPROCS)
    pcfg = ParallelConfig(coordinator_address=f"127.0.0.1:{port}",
                          num_processes=nprocs, process_id=pid)
    initialize_distributed(pcfg)
    assert jax.process_count() == nprocs, jax.process_count()

    video, text, start = global_batch()
    model, optimizer, state = build_model_and_state()

    mesh = build_mesh(pcfg)             # spans BOTH processes' devices
    sharding = NamedSharding(mesh, P("data"))
    lo, hi = pid * B_LOCAL, (pid + 1) * B_LOCAL
    video_g = jax.make_array_from_process_local_data(sharding, video[lo:hi])
    text_g = jax.make_array_from_process_local_data(
        sharding, text[lo * K:hi * K])
    start_g = jax.make_array_from_process_local_data(sharding, start[lo:hi])

    step = make_train_step(model, optimizer, mesh, donate=False)
    _, loss = step(state, video_g, text_g, start_g)
    print(json.dumps({"process": pid, "loss": float(loss)}), flush=True)


if __name__ == "__main__":
    main()
