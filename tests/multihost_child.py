"""Child process + shared fixtures for test_multihost.py.

As __main__: join an N-process jax.distributed cluster over loopback
(Gloo CPU collectives) and run one of four modes on a global mesh
spanning every process.  This is the real multi-host path
(parallel/mesh.py initialize_distributed with an explicit coordinator —
the replacement for the reference's hardcoded-IP rendezvous,
train.py:48-56), not the single-host no-op.

    python multihost_child.py <pid> <nprocs> <port> [mode] [workdir]

modes:
- ``step`` (default): ONE sharded train step, print the loss as JSON.
- ``trainA``: multi-step loop with a cooperative-preemption protocol:
  process 0 receives a REAL mid-run SIGTERM (delivered to itself after
  step 2 — deterministic, same signal path as a TPU-VM maintenance
  event); the handler only sets a flag, and between steps every process
  all-reduces the flag over the mesh so the whole cluster agrees to
  checkpoint together at the same step boundary (one worker exiting
  unilaterally would wedge the others inside the next collective).
  Saves via CheckpointManager (every process calls save; Orbax
  coordinates the primary-host write), prints a record, exits 0.
- ``trainB``: resume — restore_latest on EVERY process + the
  ``device_put(state, NamedSharding(mesh, P()))`` re-replication that
  train/loop.py's resume path uses (the multihost claim flagged by
  ADVICE r3), then run to MAX_STEPS and print the final record.
- ``fallback``: resume with an EVOLVED optimizer tree (chain-wrapped):
  full restore fails structurally on every process, the per-path
  fingerprint mismatches, and the weights-only fallback (restore_raw on
  every process) must rescue the run cluster-wide.

As a module: exposes the EXACT shapes/model/data used by the child so
the parent test's in-process cross-check consumes one definition
(import is side-effect-free; jax.config mutations happen only in
main()).
"""

import json
import os
import signal
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

B_LOCAL, NPROCS, K, FRAMES, SIZE, WORDS = 2, 2, 2, 4, 32, 5
B_GLOBAL = B_LOCAL * NPROCS
MAX_STEPS = 6           # trainA preempts at 3; trainB finishes the rest


def subprocess_env() -> dict:
    """Environment for spawning a single-device-per-process child: the
    parent pytest process forces 8 virtual CPU devices (conftest.py);
    children must not inherit that flag."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    return env


def global_batch(nprocs: int = NPROCS):
    """Identical deterministic global batch on every process; each holds
    its own slice (exactly the per-host loader contract)."""
    rng = np.random.RandomState(0)
    b = B_LOCAL * nprocs
    video = rng.randint(0, 255, (b, FRAMES, SIZE, SIZE, 3), np.uint8)
    text = rng.randint(0, 32, (b * K, WORDS)).astype(np.int32)
    start = np.zeros((b,), np.float32)
    return video, text, start


def _optim_cfg():
    from milnce_tpu.config import OptimConfig

    return OptimConfig(warmup_steps=2)


def build_model_and_state():
    import jax
    import jax.numpy as jnp

    from milnce_tpu.models import S3D
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = jax.jit(lambda key: model.init(
        key, jnp.zeros((2, FRAMES, SIZE, SIZE, 3), jnp.float32),
        jnp.zeros((2 * K, WORDS), jnp.int32)))(jax.random.PRNGKey(0))
    ocfg = _optim_cfg()
    optimizer = build_optimizer(ocfg, build_schedule(ocfg, 10))
    return model, optimizer, create_train_state(variables, optimizer)


def _shard_batch(mesh, nprocs: int, pid: int):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    video, text, start = global_batch(nprocs)
    sharding = NamedSharding(mesh, P("data"))
    lo, hi = pid * B_LOCAL, (pid + 1) * B_LOCAL
    return (jax.make_array_from_process_local_data(sharding, video[lo:hi]),
            jax.make_array_from_process_local_data(sharding,
                                                   text[lo * K:hi * K]),
            jax.make_array_from_process_local_data(sharding, start[lo:hi]))


def _coord_barrier(name: str, timeout_ms: int = 600_000) -> None:
    """Rendezvous on the COORDINATION SERVICE (gRPC), not on a device
    collective: Gloo's key-value exchange has a hard 30 s timeout baked
    into XLA, which N children skewed by concurrent backend-init/compile
    on a saturated host routinely blow.  This barrier has a caller-chosen
    timeout, so processes align here first and then hit the Gloo exchange
    within milliseconds of each other."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is not None:
        client.wait_at_barrier(name, timeout_in_ms=timeout_ms)


def _flag_reducer(mesh):
    """The production cooperative-preemption primitive
    (parallel.mesh.make_flag_reducer): AOT-compiled, so the barrier in
    main() can align processes before its first (Gloo-initializing)
    execution."""
    from milnce_tpu.parallel.mesh import make_flag_reducer

    return make_flag_reducer(mesh)


def _run_training_modes(pid: int, mode: str, workdir: str) -> None:
    """Drive the PRODUCTION `run_training` loop across the cluster.

    ``preempt_loop``: process 0 receives a real SIGTERM mid-run (a timer
    thread — whenever it lands, the coordinated protocol converges); the
    loop's cluster-wide flag all-reduce must make EVERY process
    checkpoint at the same step and exit cleanly.
    ``preempt_resume``: `--resume`-style restart of the same run dir on
    every process (restore_latest + replicate_to_mesh inside
    run_training), bounded by max_steps.
    """
    import threading

    import jax

    from milnce_tpu.config import tiny_preset
    from milnce_tpu.train.loop import run_training

    assert workdir, "preempt modes need a workdir argv"

    # pre-establish the Gloo communicator for this device clique (same
    # barrier-then-trivial-collective recipe as the other modes: the S3D
    # compile skew would otherwise trip Gloo's 30 s setup timeouts at
    # the first train step); run_training's own mesh over the same
    # devices reuses the cached communicator
    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.parallel.mesh import build_mesh

    warm = _flag_reducer(build_mesh(ParallelConfig()))
    _coord_barrier("milnce_gloo_warmup")
    warm(False)

    cfg = tiny_preset()
    # initialize_distributed already ran with the explicit coordinator;
    # run_training must take the single-host no-op path, not re-init
    cfg.parallel.coordinator_address = None
    cfg.train.batch_size = 4            # 2 per process on a 2-proc cluster
    cfg.data.synthetic_num_samples = 32
    cfg.data.num_reader_threads = 2
    cfg.train.n_display = 8
    cfg.train.preempt_sync_steps = 4
    cfg.train.checkpoint_root = workdir
    cfg.train.log_root = ""
    cfg.train.verbose = False
    cfg.optim.epochs = 400              # far beyond the SIGTERM horizon

    if mode == "preempt_loop":
        if pid == 0:
            # A real maintenance event would deliver SIGTERM once at an
            # arbitrary time; before run_training installs its handler
            # the default action would kill the process outright, so
            # install a placeholder now and RE-send every 10 s until the
            # production handler (installed mid-run) catches one — the
            # coordinated protocol must converge whenever that happens.
            signal.signal(signal.SIGTERM, lambda *_: None)

            def fire():
                os.kill(os.getpid(), signal.SIGTERM)
                t = threading.Timer(10.0, fire)
                t.daemon = True
                t.start()

            t0 = threading.Timer(15.0, fire)
            t0.daemon = True
            t0.start()
        result = run_training(cfg)
    else:
        cfg.train.resume = True
        result = run_training(cfg, max_steps=3)
    print(json.dumps({"process": pid, "steps": result.steps,
                      "step_counter": int(result.state.step),
                      "loss": float(result.last_loss)}), flush=True)
    _coord_barrier("milnce_exit")


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jax: default implementation

    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.parallel.mesh import build_mesh, initialize_distributed
    from milnce_tpu.train.step import make_train_step

    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "step"
    workdir = sys.argv[5] if len(sys.argv) > 5 else ""
    pcfg = ParallelConfig(coordinator_address=f"127.0.0.1:{port}",
                          num_processes=nprocs, process_id=pid)
    initialize_distributed(pcfg)
    assert jax.process_count() == nprocs, jax.process_count()

    if mode in ("preempt_loop", "preempt_resume"):
        _run_training_modes(pid, mode, workdir)
        return

    model, optimizer, state = build_model_and_state()
    mesh = build_mesh(pcfg)             # spans every process's devices
    any_flagged = _flag_reducer(mesh)   # AOT-compiled, no Gloo yet
    # Establish the Gloo communicator NOW, with every process aligned by
    # a coordination-service barrier first: the KV exchange + TCP pair
    # connect then happen within ms of each other.  Without this, the
    # first collective fires inside the S3D step's first execution, and
    # with N children cold-compiling concurrently on a saturated host
    # the 30 s Gloo timeouts trip before the slowest catches up.
    _coord_barrier("milnce_gloo_warmup")
    any_flagged(False)
    video_g, text_g, start_g = _shard_batch(mesh, nprocs, pid)
    step = make_train_step(model, optimizer, mesh, donate=False)

    if mode == "step":
        assert nprocs == NPROCS, (nprocs, NPROCS)
        _, loss = step(state, video_g, text_g, start_g)
        print(json.dumps({"process": pid, "loss": float(loss)}), flush=True)
        # align exits: a worker held up in teardown (async Orbax, log
        # flush) must not trip jax's fixed-timeout shutdown barrier for
        # the whole cluster on a saturated host
        _coord_barrier("milnce_exit")
        return

    if mode == "cdtw_step":
        # the DTW-family collective pattern is DIFFERENT from MIL-NCE:
        # all_gather of sequence embeddings + replicated loss + pmean of
        # grads (vs psum of partial sums) — virtual meshes proved the
        # math, this proves it across a real process boundary
        # (VERDICT r4 #5; reference counterpart: the NCCL gather at
        # train.py:217-219)
        from milnce_tpu.config import LossConfig

        step = make_train_step(model, optimizer, mesh, donate=False,
                               loss_cfg=LossConfig(name="cdtw"))
        _, loss = step(state, video_g, text_g, start_g)
        print(json.dumps({"process": pid, "loss": float(loss)}), flush=True)
        _coord_barrier("milnce_exit")
        return

    if mode == "gradcache_step":
        # two-pass embedding-cache step (scan embed -> global loss ->
        # VJP re-forward) with its own collective placement; the r4
        # restore bug showed exactly this class of program needs a real
        # process boundary to be trusted (VERDICT r4 #5)
        from milnce_tpu.train.step import make_grad_cache_step

        step = make_grad_cache_step(model, optimizer, mesh,
                                    micro_batches=2, donate=False)
        _, loss = step(state, video_g, text_g, start_g)
        print(json.dumps({"process": pid, "loss": float(loss)}), flush=True)
        _coord_barrier("milnce_exit")
        return

    from milnce_tpu.train.checkpoint import CheckpointManager

    assert workdir, "trainA/trainB/fallback modes need a workdir argv"

    if mode == "trainA":
        preempted = {"flag": False}
        signal.signal(signal.SIGTERM,
                      lambda *_: preempted.update(flag=True))
        mgr = CheckpointManager(workdir, keep=2)
        s = 0
        loss = None
        while s < MAX_STEPS:
            state, loss = step(state, video_g, text_g, start_g)
            s += 1
            flagged = any_flagged(preempted["flag"])
            if pid == 0 and s == 2:
                # the mid-run preemption under test: a real signal
                # through the real handler, to ONE process only.  Sent
                # AFTER this boundary's flag exchange (the handler runs
                # synchronously on os.kill), so the cluster detects it
                # at the step-3 boundary, mid-step like a real
                # maintenance event.
                os.kill(os.getpid(), signal.SIGTERM)
            if flagged:
                mgr.save(s, state)
                mgr.wait()
                break
        print(json.dumps({"process": pid, "loss": float(loss),
                          "steps_done": s,
                          "preempted": bool(s < MAX_STEPS)}), flush=True)
        _coord_barrier("milnce_exit")
        return

    if mode in ("trainB", "fallback"):
        if mode == "fallback":
            # the run was upgraded across an optimizer-tree change while
            # preempted: full restore fails, weights-only fallback rescues
            import optax

            from milnce_tpu.train.schedule import build_schedule
            from milnce_tpu.train.state import (build_optimizer,
                                                create_train_state)

            ocfg = _optim_cfg()
            optimizer = optax.chain(
                optax.clip_by_global_norm(1.0),
                build_optimizer(ocfg, build_schedule(ocfg, 10)))
            state = create_train_state(
                {"params": state.params, "batch_stats": state.batch_stats},
                optimizer)
            step = make_train_step(model, optimizer, mesh, donate=False)
        mgr = CheckpointManager(workdir, keep=2, create=False)
        restored_step, state = mgr.restore_latest(state)
        # the train/loop.py resume path's re-replication over the mesh
        # (replicate_to_mesh: a plain device_put to a replicated spec
        # raises 'does not support cross-host device transfers' here —
        # the bug this phase exists to catch)
        from milnce_tpu.parallel.mesh import replicate_to_mesh

        state = replicate_to_mesh(state, mesh)
        s = int(state.step)
        loss = None
        while s < MAX_STEPS:
            state, loss = step(state, video_g, text_g, start_g)
            s += 1
        print(json.dumps({"process": pid, "loss": float(loss),
                          "restored_step": restored_step,
                          "final_step": int(state.step)}), flush=True)
        _coord_barrier("milnce_exit")
        return

    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
