"""Measurement-script machinery: the xla_flag_probe launcher/grid, the
stage_probe autotuner, and bench's impl-map/cliff plumbing.

The round-5 flag probe shipped a table where every non-baseline row
died ``rc=1, no record`` (XLA_FLAGS_PROBE.md) — an instrument that
errors on every interesting row and ships anyway settles nothing, so
its pure logic is pinned here and the CPU child is exercised as a real
subprocess (slow tier).
"""

import json
import os
import subprocess
import sys

import jax
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402

sys.path.insert(0, os.path.join(_REPO, "scripts"))
import xla_flag_probe  # noqa: E402


class TestSplitFlags:
    """--xla_tpu_* knobs are libtpu flags; the CLIENT's XLA_FLAGS parser
    hard-aborts on them (observed: rc=-6 'Unknown flags in XLA_FLAGS'
    — the round-5 row killer), so the router must keep the two apart."""

    def test_tpu_flags_routed_to_libtpu(self):
        xla, libtpu = xla_flag_probe.split_flags(
            "--xla_tpu_scoped_vmem_limit_kib=65536")
        assert xla == ""
        assert libtpu == "--xla_tpu_scoped_vmem_limit_kib=65536"

    def test_generic_flags_stay_in_xla_flags(self):
        xla, libtpu = xla_flag_probe.split_flags(
            "--xla_force_host_platform_device_count=2")
        assert xla == "--xla_force_host_platform_device_count=2"
        assert libtpu == ""

    def test_mixed_set_splits(self):
        xla, libtpu = xla_flag_probe.split_flags(
            "--xla_tpu_enable_latency_hiding_scheduler=true "
            "--xla_dump_to=/tmp/d")
        assert xla == "--xla_dump_to=/tmp/d"
        assert libtpu == "--xla_tpu_enable_latency_hiding_scheduler=true"

    def test_every_tpu_candidate_routes_clear_of_xla_flags(self):
        for _, flags in xla_flag_probe.CANDIDATES:
            xla, _ = xla_flag_probe.split_flags(flags)
            assert "--xla_tpu_" not in xla, (
                f"candidate {flags!r} would abort the client flag parser")


class TestBuildGrid:
    def test_cpu_grid_has_no_tpu_flags(self):
        # the CPU client would abort on any --xla_tpu_* candidate
        for name, flags, _ in xla_flag_probe.build_grid(True, ""):
            assert "--xla_tpu_" not in flags, name

    def test_cpu_grid_has_a_non_baseline_row(self):
        grid = xla_flag_probe.build_grid(True, "")
        assert any(flags for _, flags, _ in grid)

    def test_stem_map_is_crossed_with_flags_on_tpu(self):
        grid = xla_flag_probe.build_grid(False, "conv1=im2col")
        tuned = [(name, flags, kw) for name, flags, kw in grid
                 if kw.get("conv_impl_map")]
        assert len(tuned) >= 3           # bare + vmem + lhs crossings
        assert any(flags for _, flags, _ in tuned)
        assert all(kw["conv_impl_map"] == "conv1=im2col"
                   for _, _, kw in tuned)

    def test_no_map_no_tuned_rows(self):
        grid = xla_flag_probe.build_grid(False, "")
        assert all(not kw for _, _, kw in grid)


class TestResolveImplMap:
    @staticmethod
    def _write_artifact(tmp_path, **kw):
        art = tmp_path / "build" / "impl_map.json"
        art.parent.mkdir(exist_ok=True)
        payload = {"impl_map": {"conv1": "im2col"}}
        payload.update(kw)
        art.write_text(json.dumps(payload))
        return art

    def test_inline_spec_passes_through(self):
        assert xla_flag_probe.resolve_impl_map("conv1=im2col") == "conv1=im2col"

    def test_missing_default_artifact_means_no_map(self, monkeypatch, tmp_path):
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        assert xla_flag_probe.resolve_impl_map("") == ""

    def test_default_artifact_picked_up_when_trustworthy(self, monkeypatch,
                                                         tmp_path):
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        art = self._write_artifact(tmp_path, complete=True,
                                   device="TPU v5 lite")
        assert xla_flag_probe.resolve_impl_map("") == str(art)

    def test_incomplete_default_artifact_rejected(self, monkeypatch,
                                                  tmp_path):
        # a mid-wedge partial autotune must not silently steer the grid
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        self._write_artifact(tmp_path, complete=False, device="TPU v5 lite")
        assert xla_flag_probe.resolve_impl_map("") == ""

    def test_cpu_tuned_default_rejected_for_tpu_run(self, monkeypatch,
                                                    tmp_path):
        # the documented CPU smoke writes the same default path; a TPU
        # probe crossing its grid with CPU-chosen winners would publish
        # wrong rows
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        self._write_artifact(tmp_path, complete=True, device="cpu")
        assert xla_flag_probe.resolve_impl_map("", cpu=False) == ""

    def test_cpu_tuned_default_accepted_for_cpu_smoke(self, monkeypatch,
                                                      tmp_path):
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        art = self._write_artifact(tmp_path, complete=True, device="cpu")
        assert xla_flag_probe.resolve_impl_map("", cpu=True) == str(art)

    def test_explicit_path_obeyed_as_given(self, monkeypatch, tmp_path):
        monkeypatch.setattr(xla_flag_probe, "_REPO", str(tmp_path))
        got = xla_flag_probe.resolve_impl_map("build/other.json")
        assert got == str(tmp_path / "build" / "other.json")


def test_autotune_stage_filter_typo_fails_fast():
    """--stages conv_1 (typo) must raise before any backend work, not
    autotune zero stages and ship an empty map marked complete."""
    import stage_probe

    with pytest.raises(ValueError, match="unknown conv stage"):
        stage_probe._validate_stage_filter("conv_1")
    assert stage_probe._validate_stage_filter("conv1,mixed_3b") == {
        "conv1", "mixed_3b"}
    assert stage_probe._validate_stage_filter("") == set()


def test_run_config_no_record_carries_stderr(monkeypatch):
    """A config child that dies before emitting its record must raise
    with the child's stderr tail — not the bare 'no record' the round-5
    probe table was full of."""

    class FakeProc:
        returncode = -6

        def communicate(self, timeout=None):
            return b"", b"F0803 xla: Unknown flags in XLA_FLAGS: --boom\n"

    monkeypatch.setattr(bench.subprocess, "Popen",
                        lambda *a, **kw: FakeProc())
    with pytest.raises(RuntimeError) as exc_info:
        bench._run_config(timeout_s=5, platform_pin="cpu", dtype="float32",
                          batch=1, frames=2, size=8, words=4, k=2,
                          remat=False, inner=1, s2d=False,
                          conv_impl="native", peak=None, flops_hint=None)
    msg = str(exc_info.value)
    assert "rc=-6" in msg
    assert "Unknown flags in XLA_FLAGS" in msg


def test_bench_flags_batch_cliff(monkeypatch):
    """A row regressing >10% clips/s vs a SMALLER batch (the observed
    281-vs-393 drop at batch 192) must be flagged as a cliff on the
    result row, not silently averaged into the table."""
    base = {"dtype": "bfloat16", "remat": False, "s2d": False,
            "conv_impl": "native", "impl_map": "", "loss": "milnce",
            "grad_accum": 1, "inner": 4, "flops_per_step": None,
            "flops_source": None, "flops_per_sec": None}
    ladder = {64: 330.0, 128: 393.0, 192: 281.0}   # BENCH_NOTES r5 shape

    def fake_run_config(timeout_s=None, **kw):
        b = kw["batch"]
        if b not in ladder:
            raise RuntimeError(f"config timeout>{timeout_s}s: {kw}")
        return dict(base, batch=b, step_ms=1.0,
                    clips_per_sec_per_chip=ladder[b])

    notes = {}
    monkeypatch.setattr(bench, "_run_config", fake_run_config)
    monkeypatch.setattr(bench, "_probe_backend", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_emit", lambda rec: None)
    monkeypatch.setattr(bench, "_write_notes",
                        lambda results, *a, **k: notes.setdefault(
                            "results", list(results)))

    bench.run_bench(True, {"platform": "tpu", "kind": "TPU v5 lite", "n": 1})
    by_batch = {r["batch"]: r for r in notes["results"]}
    assert "cliff_vs_smaller_batch" not in by_batch[128]
    assert by_batch[192]["cliff_vs_smaller_batch"] == pytest.approx(
        1 - 281.0 / 393.0, abs=1e-3)


def test_write_notes_marks_cliff_and_preserves_hand_notes(tmp_path,
                                                          monkeypatch):
    """BENCH_NOTES.md must carry the cliff marker on flagged rows and
    keep the '## Hand notes' section across auto-rewrites (the r5
    rewrite silently dropped the hand-written methodology caveats)."""
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    notes = tmp_path / "BENCH_NOTES.md"
    notes.write_text("# BENCH notes (auto-written by bench.py)\n\n"
                     "- device: TPU v5 lite x1 (on_tpu=True)\n\n"
                     "## Hand notes\n\nanchor predates differenced timing.\n")
    rows = [{"dtype": "bfloat16", "batch": 128, "remat": False,
             "step_ms": 325.0, "clips_per_sec_per_chip": 393.0},
            {"dtype": "bfloat16", "batch": 192, "remat": False,
             "step_ms": 682.0, "clips_per_sec_per_chip": 281.0,
             "cliff_vs_smaller_batch": 0.285, "impl_map": "conv1=im2col"}]
    bench._write_notes(rows, rows[0], "TPU v5 lite", True, 1)
    text = notes.read_text()
    assert "cliff: -28% vs smaller batch" in text
    assert "## Hand notes" in text
    assert "anchor predates differenced timing." in text
    assert "conv1=im2col" in text


@pytest.mark.slow
def test_flag_probe_cpu_smoke():
    """The whole probe as a real subprocess in CPU mode: every grid row
    must complete — a measured row, or an error row carrying a captured
    diagnosis.  The bare 'no record' failure mode (round 5: rc=1 on
    every non-baseline row) must be gone."""
    env = dict(os.environ)
    env["MILNCE_FLAGPROBE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "xla_flag_probe.py"),
         "--timeout", "420"],
        env=env, cwd=_REPO, capture_output=True, timeout=1500)
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    rows = [json.loads(line) for line in proc.stdout.decode().splitlines()
            if line.strip().startswith("{")]
    named = [r for r in rows if "name" in r]
    grid = xla_flag_probe.build_grid(
        True, xla_flag_probe.resolve_impl_map("", cpu=True))
    assert len(named) == len(grid), named
    for r in named:
        if "error" in r:
            # a captured diagnosis, never the bare no-record marker
            assert not r["error"].rstrip().endswith("no record"), r
        else:
            assert r["step_ms"] > 0
    if hasattr(jax, "shard_map"):
        # environments with a full jax (the TPU rig, modern CPU CI) must
        # actually MEASURE a non-baseline row, not just diagnose it
        non_baseline = [r for r in named
                        if r["name"] != "baseline" and "error" not in r]
        assert non_baseline, named


@pytest.mark.slow
def test_stage_probe_autotune_cpu_smoke(tmp_path):
    """--autotune end-to-end on CPU: emits the per-stage impl-map
    artifact, and the artifact round-trips into build_model (the exact
    path bench.py / train cli consume)."""
    out = tmp_path / "impl_map.json"
    env = dict(os.environ)
    env["MILNCE_PROFILE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "stage_probe.py"),
         "--autotune", "--batch", "2", "--frames", "4", "--size", "32",
         "--stages", "conv1", "--iters", "2",
         "--impls", "native,im2col", "--out", str(out)],
        env=env, cwd=_REPO, capture_output=True, timeout=1200)
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    art = json.loads(out.read_text())
    assert art["generator"].startswith("scripts/stage_probe.py")
    assert art["complete"] is True
    assert set(art["impl_map"]) <= {"conv1"}
    timings = art["stage_ms"]["conv1"]
    assert set(timings) == {"native", "im2col"}
    for impl in timings:
        assert timings[impl]["fwd"] > 0 and timings[impl]["fwdbwd"] > 0

    from milnce_tpu.config import small_preset
    from milnce_tpu.models.build import build_model

    cfg = small_preset().model
    cfg.conv_impl_map = str(out)
    model = build_model(cfg)             # consumes without error
    assert dict(model.conv_impl_map or ()) == art["impl_map"]
