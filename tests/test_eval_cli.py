"""Eval CLI smoke: all three tasks run end-to-end from a clean checkout —
vendored manifests + FakeDecoder + a round-tripped Orbax checkpoint
(VERDICT r1 missing #3 / next #9; reference: eval_youcook.py,
eval_msrvtt.py, eval_hmdb.py)."""

import csv as csv_mod

import numpy as np
import pytest

TINY = dict(embedding_dim=16, inception_blocks=2, word_embedding_dim=8,
            text_hidden_dim=16, vocab_size=64)
SHAPE = dict(num_frames=4, video_size=32, max_words=6)


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    """Orbax checkpoint for the tiny model the CLI will rebuild."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import ModelConfig, OptimConfig
    from milnce_tpu.models.build import build_model
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import cosine_with_warmup
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model_cfg = ModelConfig(**TINY)
    model = build_model(model_cfg)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, SHAPE["num_frames"], SHAPE["video_size"],
                   SHAPE["video_size"], 3), jnp.float32),
        jnp.zeros((1, SHAPE["max_words"]), jnp.int32))
    optimizer = build_optimizer(OptimConfig(), cosine_with_warmup(1e-3, 1, 2))
    state = create_train_state(variables, optimizer)
    path = tmp_path_factory.mktemp("eval_ckpt")
    mgr = CheckpointManager(str(path))
    mgr.save(1, state)
    mgr.wait()
    return str(path)


def _write_csv(path, header, rows):
    with open(path, "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(header)
        w.writerows(rows)
    return str(path)


def _cli_args(task, csv_path, ckpt):
    args = [task, "--ckpt", ckpt, "--csv", csv_path, "--video_root", "/none",
            "--fake_decoder", "--num_windows", "2", "--batch_size", "4",
            "--num_frames", str(SHAPE["num_frames"]),
            "--video_size", str(SHAPE["video_size"]),
            "--max_words", str(SHAPE["max_words"])]
    for k, v in TINY.items():
        args += [f"--{k}", str(v)]
    return args


@pytest.mark.slow
def test_youcook_cli_smoke(ckpt_dir, tmp_path):
    from milnce_tpu.eval.cli import main

    rows = [[47 + i, 40 + i, "226", f"step {i} of the recipe", f"vid{i}"]
            for i in range(6)]
    path = _write_csv(tmp_path / "yc.csv",
                      ["end", "start", "task", "text", "video_id"], rows)
    metrics = main(_cli_args("youcook", path, ckpt_dir))
    assert set(metrics) == {"R1", "R5", "R10", "MR"}


def test_msrvtt_cli_smoke(ckpt_dir, tmp_path):
    from milnce_tpu.eval.cli import main

    rows = [[f"ret{i}", f"msr{i}", f"video{i}", f"somebody does thing {i}"]
            for i in range(6)]
    path = _write_csv(tmp_path / "mv.csv",
                      ["key", "vid_key", "video_id", "sentence"], rows)
    metrics = main(_cli_args("msrvtt", path, ckpt_dir))
    assert set(metrics) == {"R1", "R5", "R10", "MR"}


@pytest.mark.slow
def test_hmdb_cli_smoke(ckpt_dir, tmp_path):
    from milnce_tpu.eval.cli import main

    rows = []
    for i in range(8):
        label = "brush_hair_test" if i % 2 == 0 else "wave_test"
        split = 1 if i < 6 else 2
        rows.append([f"v{i}.avi", label, split, split, split])
    path = _write_csv(tmp_path / "hm.csv",
                      ["video_id", "label", "split1", "split2", "split3"],
                      rows)
    accs = main(_cli_args("hmdb", path, ckpt_dir))
    assert set(accs) == {"split1", "split2", "split3", "mean"}


REPO = __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__)))


def test_vendored_manifests_match_reference_schemas():
    """The csv/ tables ship with the repo (the reference's csv/ dir) and
    parse with the documented schemas and row counts."""
    import os

    from milnce_tpu.data.datasets import read_csv

    hmdb = read_csv(os.path.join(REPO, "csv/hmdb51.csv"))
    assert len(hmdb) == 6766
    assert set(hmdb[0]) == {"video_id", "label", "split1", "split2", "split3"}
    msrvtt = read_csv(os.path.join(REPO, "csv/msrvtt_test.csv"))
    assert len(msrvtt) == 1000
    assert set(msrvtt[0]) == {"key", "vid_key", "video_id", "sentence"}
    yc = read_csv(os.path.join(REPO, "csv/validation_youcook.csv"))
    assert len(yc) == 3350
    assert set(yc[0]) == {"end", "start", "task", "text", "video_id"}


def test_default_eval_csv_exists():
    """DataConfig.eval_csv must not dangle (VERDICT r1 component #40)."""
    import os

    from milnce_tpu.config import DataConfig

    assert os.path.exists(os.path.join(REPO, DataConfig().eval_csv))

@pytest.mark.slow
def test_youcook_cli_on_real_videos(ckpt_dir, tmp_path):
    """First fully-real eval drive: actual encoded mp4s decoded by the
    production backend (auto -> cv2 on this binary-less host), through
    the youcook directory layout, window ensembling, and retrieval
    metrics — no FakeDecoder anywhere."""
    cv2 = pytest.importorskip("cv2")
    from milnce_tpu.eval.cli import main

    vid_root = tmp_path / "videos"
    rows = []
    for i in range(4):
        d = vid_root / "validation" / "226"
        d.mkdir(parents=True, exist_ok=True)
        vw = cv2.VideoWriter(str(d / f"vid{i}.mp4"),
                             cv2.VideoWriter_fourcc(*"mp4v"), 10.0, (48, 48))
        for k in range(120):
            vw.write(np.full((48, 48, 3), (i * 60 + k) % 255, np.uint8))
        vw.release()
        rows.append([9 + i, 2 + i, "226", f"step {i} of the recipe",
                     f"vid{i}"])
    path = _write_csv(tmp_path / "yc_real.csv",
                      ["end", "start", "task", "text", "video_id"], rows)
    args = [a for a in _cli_args("youcook", path, ckpt_dir)
            if a != "--fake_decoder"]
    args[args.index("/none")] = str(vid_root)
    metrics = main(args)
    assert set(metrics) == {"R1", "R5", "R10", "MR"}
    assert 0.0 <= metrics["R1"] <= 1.0 and metrics["MR"] >= 1
