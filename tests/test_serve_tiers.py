"""Per-tenant SLO classes (ISSUE 14): tier spec parsing, the
starvation-protection cap on the admission controller, tier threading
through the service + HTTP front, the serve_bench knee finder, and the
two-tier chaos bench acceptance (interactive + batch backfill under
``index.swap_raise@%3``, gated via ``obs_report --check``).

The unit layers are jax-free (an engine-shaped fake); the chaos bench
is a subprocess because the acceptance pin IS the real script end to
end (fast-child exemption in test_suite_hygiene.py)."""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.serving.service import (AdmissionController,
                                        RetrievalService, ShedError,
                                        parse_tier_spec, serve_http)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_serve_bench():
    spec = importlib.util.spec_from_file_location(
        "serve_bench_under_test",
        os.path.join(_REPO, "scripts", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeEngine:
    """Engine-shaped stand-in (mirrors test_serve_chaos's): embed is a
    pure function of the rows, with injectable delay."""

    buckets = (4, 8)
    max_batch = 8
    text_words = 4
    embed_dim = 8

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def embed_text(self, rows):
        if self.delay_s:
            time.sleep(self.delay_s)
        rows = np.asarray(rows)
        return np.tile(rows[:, :1].astype(np.float32),
                       (1, self.embed_dim))

    embed_video = embed_text

    def recompiles(self):
        return 0

    def stats(self):
        return {"buckets": list(self.buckets), "max_batch": self.max_batch,
                "recompiles": 0, "dead": False, "calls": {}}


def _rows(n=1, fill=3):
    return np.full((n, 4), fill, np.int32)


class TestTierSpec:
    def test_parse_ordered_shares(self):
        spec = parse_tier_spec("interactive:1.0,batch:0.5")
        assert list(spec) == ["interactive", "batch"]  # priority order
        assert spec == {"interactive": 1.0, "batch": 0.5}

    @pytest.mark.parametrize("bad", [
        "interactive", "a:0", "a:1.5", "a:1.0,a:0.5", ":0.5"])
    def test_malformed_specs_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_tier_spec(bad)

    def test_empty_spec_is_untiered(self):
        assert parse_tier_spec("") == {}


class TestTierAdmission:
    def _ac(self, max_inflight=4, tiers="interactive:1.0,batch:0.25"):
        return AdmissionController(
            max_inflight, max_batch=4, tiers=tiers,
            registry=obs_metrics.MetricsRegistry())

    def test_batch_backfill_cannot_starve_interactive(self):
        """THE SLO-class property: with batch capped at share 0.25 of
        max_inflight=4 (cap 1), a saturating batch tenant sheds on its
        OWN cap while interactive still admits up to the global bound."""
        ac = self._ac()
        with ac.admit(1, None, "batch"):
            with pytest.raises(ShedError) as exc_info:
                with ac.admit(1, None, "batch"):
                    pass
            assert exc_info.value.reason == "tier_overload"
            assert exc_info.value.retry_after_ms > 0
            with ac.admit(3, None, "interactive"):   # up to the global 4
                pass
        st = ac.stats()
        assert st["tiers"]["batch"]["cap"] == 1
        assert st["tiers"]["batch"]["shed"] == {"tier_overload": 1}
        assert st["tiers"]["interactive"]["shed"] == {}

    def test_default_tier_is_the_highest_priority_one(self):
        ac = self._ac()
        with ac.admit(1, None, None):
            assert ac.tier_inflight("interactive") == 1
            assert ac.tier_inflight("batch") == 0

    def test_unknown_tier_is_a_loud_error(self):
        ac = self._ac()
        with pytest.raises(ValueError, match="unknown SLO tier"):
            with ac.admit(1, None, "nope"):
                pass

    def test_unarmed_controller_never_tier_sheds(self):
        ac = self._ac(max_inflight=0)
        with ac.admit(100, None, "batch"):           # unbounded
            with ac.admit(100, None, "batch"):
                pass

    def test_slots_release_per_tier_on_exit(self):
        ac = self._ac()
        with ac.admit(1, None, "batch"):
            pass
        with ac.admit(1, None, "batch"):             # admissible again
            pass
        assert ac.tier_inflight("batch") == 0

    def test_untiered_controller_ignores_tier_names(self):
        ac = AdmissionController(4, max_batch=4,
                                 registry=obs_metrics.MetricsRegistry())
        with ac.admit(1, None, "anything"):          # no tiers: pass-through
            pass
        assert "tiers" not in ac.stats()


class TestTierService:
    def test_tier_threads_through_service_and_http_with_429_and_400(self):
        slow = FakeEngine(delay_s=0.6)
        service = RetrievalService(
            slow, None, max_delay_ms=1.0,
            registry=obs_metrics.MetricsRegistry(),
            max_inflight=4, tiers="interactive:1.0,batch:0.25")
        server = serve_http(service, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"

        def post(route, payload):
            req = urllib.request.Request(
                base + route, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=30)

        try:
            started = threading.Event()

            def occupy():                      # batch's 1 slot, slowly
                started.set()
                try:
                    post("/v1/embed_text", {"token_ids": [[1, 1, 1, 1]],
                                            "tier": "batch"})
                except Exception:
                    pass

            t = threading.Thread(target=occupy, daemon=True)
            t.start()
            started.wait()
            deadline = time.monotonic() + 5.0
            while (time.monotonic() < deadline
                   and service._admission.tier_inflight("batch") < 1):
                time.sleep(0.01)
            assert service._admission.tier_inflight("batch") == 1
            # a second batch request: 429 with the tier_overload reason
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                post("/v1/embed_text", {"token_ids": [[2, 2, 2, 2]],
                                        "tier": "batch"})
            err = exc_info.value
            assert err.code == 429
            body = json.loads(err.read())
            assert body["kind"] == "shed"
            assert body["reason"] == "tier_overload"
            assert int(err.headers["Retry-After"]) >= 1
            # interactive still served while batch is capped out
            with post("/v1/embed_text", {"token_ids": [[3, 3, 3, 3]],
                                         "tier": "interactive"}) as r:
                assert r.status == 200
            # unknown tier: 400, never a silent default
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                post("/v1/embed_text", {"token_ids": [[4, 4, 4, 4]],
                                        "tier": "platinum"})
            assert exc_info.value.code == 400
            # /healthz surfaces the per-tier admission block
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=30) as r:
                h = json.loads(r.read())
            tiers = h["admission"]["tiers"]
            assert tiers["batch"]["shed"].get("tier_overload", 0) >= 1
            t.join(timeout=10)
        finally:
            server.shutdown()
            server.server_close()
            service.close()


class TestKneeFinder:
    def test_knee_is_the_highest_load_inside_slo_and_served_frac(self):
        sb = _load_serve_bench()
        rounds = [
            {"qps_offered": 50, "p99_ms": 40.0, "served_frac": 1.0},
            {"qps_offered": 100, "p99_ms": 80.0, "served_frac": 0.98},
            {"qps_offered": 200, "p99_ms": 900.0, "served_frac": 0.6},
        ]
        assert sb.knee_from_rounds(rounds, slo_ms=100.0) == 100
        assert sb.knee_from_rounds(rounds, slo_ms=50.0) == 50
        assert sb.knee_from_rounds(rounds, slo_ms=10.0) is None

    def test_served_frac_gate_counts_refusals_against_the_knee(self):
        sb = _load_serve_bench()
        rounds = [{"qps_offered": 50, "p99_ms": 5.0, "served_frac": 0.5}]
        assert sb.knee_from_rounds(rounds, slo_ms=100.0) is None

    def test_tier_qps_spec_parses(self):
        sb = _load_serve_bench()
        assert sb.parse_tier_qps("interactive:80,batch:200") == {
            "interactive": 80.0, "batch": 200.0}
        with pytest.raises(ValueError):
            sb.parse_tier_qps("interactive")
        with pytest.raises(ValueError, match="UNIQUE"):
            sb.parse_tier_qps("interactive:80,interactive:200")


# ---------------------------------------------------------------------------
# ISSUE acceptance: the two-tier chaos bench — interactive + batch
# backfill, live-index ingest under index.swap_raise@%3, continuous
# batching on — gated against the committed baseline via obs_report
# --check (fast-child exemption in test_suite_hygiene.py)
# ---------------------------------------------------------------------------

TIER_BENCH_ARGS = [
    "--backend", "cpu", "--preset", "tiny", "--duration", "2",
    "--corpus", "12", "--distinct", "0",
    "--max_batch", "8", "--min_bucket", "8", "--cache_capacity", "0",
    "--timeout_ms", "250", "--continuous", "--live_index",
    "--ingest_rows", "4", "--ingest_interval_s", "0.3",
    "--max_inflight", "8",
    "--tiers", "interactive:25,batch:120",
    "--tier_shares", "interactive:1.0,batch:0.5",
    "--faults", "index.swap_raise@%3",
]


def test_two_tier_chaos_bench_acceptance(tmp_path):
    """Interactive + batch backfill under swap chaos: the bench
    completes with zero unstructured errors, the batch tier absorbs the
    shedding (its cap, not interactive's traffic, is the limiter),
    ingest keeps landing generations THROUGH injected swap failures,
    recompiles stay 0 — and the per-tier gate metrics clear
    ``obs_report --check`` against the committed baseline."""
    out = tmp_path / "SB_TIERS.json"
    env = dict(os.environ)
    env.pop("MILNCE_FAULTS", None)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "serve_bench.py")]
        + TIER_BENCH_ARGS + ["--out", str(out)],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, (
        f"tier chaos bench failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    report = json.loads(out.read_text())
    tiers = report["tiers"]
    assert set(tiers) == {"interactive", "batch"}
    # zero unstructured failures anywhere; refusals are structured sheds
    assert report["errors"] == 0
    for name, td in tiers.items():
        assert td["error_rate"] == 0.0, (name, td)
    # the batch tier absorbs the shedding: its share cap binds first
    assert tiers["batch"]["shed"] >= tiers["interactive"]["shed"]
    assert tiers["batch"]["shed"] >= 1, "backfill never hit its cap"
    # interactive kept being served through the chaos window
    assert tiers["interactive"]["requests"] >= 10
    # ingest survived the injected swap failures: generations advanced
    # AND failures actually fired
    ing = report["ingest"]
    assert ing["swap_failures"] >= 1, "index.swap_raise@%3 never fired"
    assert ing["generation"] >= 1 and ing["swaps"] >= 1
    assert ing["corpus_size"] > 12
    # steady state stayed pre-traced through ingest + swaps + chaos
    assert report["engine"]["recompiles"] in (0, -1)
    assert report["index"]["recompiles"] == 0

    # the obs_report gate: per-tier p99 + error_rate + qps against the
    # committed baseline.  Tolerance is deliberately wide (5x band):
    # the thread-per-arrival open-loop driver's latencies swing several-
    # fold run to run on a loaded CI box, so this gate is the
    # catastrophic-regression fence (a wedged batcher or a quarantine
    # storm blows through 5x instantly) while the structural pins above
    # are the tight ones
    baseline = os.path.join(_REPO, "SERVE_BENCH_tiny_tiers.json")
    assert os.path.exists(baseline), "committed tier baseline missing"
    gate = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "obs_report.py"),
         str(out), "--check", "--baseline", baseline,
         "--tolerance", "4.0"],
        capture_output=True, text=True, timeout=120)
    assert gate.returncode == 0, (
        f"obs_report gate failed:\n{gate.stdout}\n{gate.stderr}")
    assert "latency_ms_p99@interactive" in gate.stdout
    assert "error_rate@batch" in gate.stdout
