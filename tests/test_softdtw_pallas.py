"""Pallas soft-DTW kernel vs the lax.scan golden: forward values and
custom-VJP gradients (the hermetic port of the reference's CPU<->GPU
cross-check, soft_dtw_cuda.py:439-440).  Runs in interpret mode on CPU,
compiled on TPU — same code path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.ops.softdtw import SoftDTW, softdtw_scan
from milnce_tpu.ops.softdtw_pallas import softdtw_pallas


@pytest.mark.parametrize("n,m", [
    (4, 4),
    pytest.param(7, 5, marks=pytest.mark.slow),
    pytest.param(3, 9, marks=pytest.mark.slow),
    (16, 16),
])
def test_forward_matches_scan(n, m):
    rng = np.random.RandomState(0)
    D = jnp.asarray(rng.rand(3, n, m).astype(np.float32))
    expected = np.asarray(softdtw_scan(D, 0.5))
    got = np.asarray(softdtw_pallas(D, 0.5))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("gamma", [1.0, 0.1])
def test_gradient_matches_scan_autodiff(gamma):
    rng = np.random.RandomState(1)
    D = jnp.asarray(rng.rand(2, 6, 5).astype(np.float32))
    expected = jax.grad(lambda d: softdtw_scan(d, gamma).sum())(D)
    got = jax.grad(lambda d: softdtw_pallas(d, gamma).sum())(D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-3, atol=1e-5)


def test_bandwidth_matches_scan():
    rng = np.random.RandomState(2)
    D = jnp.asarray(rng.rand(2, 8, 8).astype(np.float32))
    expected = np.asarray(softdtw_scan(D, 0.5, bandwidth=2))
    got = np.asarray(softdtw_pallas(D, 0.5, 2))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_gradient_with_upstream_cotangent():
    rng = np.random.RandomState(3)
    D = jnp.asarray(rng.rand(3, 5, 5).astype(np.float32))
    w = jnp.asarray([0.5, -1.0, 2.0])
    expected = jax.grad(lambda d: (w * softdtw_scan(d, 0.7)).sum())(D)
    got = jax.grad(lambda d: (w * softdtw_pallas(d, 0.7)).sum())(D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-3, atol=1e-5)


def test_softdtw_module_pallas_backend():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 6, 8).astype(np.float32))
    y = jnp.asarray(rng.randn(2, 5, 8).astype(np.float32))
    ref = SoftDTW(gamma=0.1, dist_func="cosine", backend="scan")(x, y)
    got = SoftDTW(gamma=0.1, dist_func="cosine", backend="pallas")(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4)


def test_rectangular_extreme():
    rng = np.random.RandomState(5)
    D = jnp.asarray(rng.rand(1, 2, 12).astype(np.float32))
    np.testing.assert_allclose(np.asarray(softdtw_pallas(D, 1.0)),
                               np.asarray(softdtw_scan(D, 1.0)), rtol=1e-5)


@pytest.mark.slow
def test_batch_tiling_pads_and_slices():
    """Batches above the 128-element tile cap split into multiple padded
    blocks (fwd AND bwd); values/grads must match the scan exactly."""
    rng = np.random.RandomState(6)
    D = jnp.asarray(rng.rand(130, 4, 4).astype(np.float32))
    np.testing.assert_allclose(np.asarray(softdtw_pallas(D, 0.5)),
                               np.asarray(softdtw_scan(D, 0.5)),
                               rtol=1e-5, atol=1e-5)
    got = jax.grad(lambda d: softdtw_pallas(d, 0.5).sum())(D)
    want = jax.grad(lambda d: softdtw_scan(d, 0.5).sum())(D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-5)


@pytest.mark.slow
def test_profile_harness_smoke():
    """The timing+allclose harness (the reference's only self-check,
    soft_dtw_cuda.py:389-463) runs end-to-end and reports agreement."""
    from milnce_tpu.ops.softdtw_profile import profile

    rec = profile(4, 5, 6, 3, n_iters=4)
    assert rec["allclose"] is True
    assert rec["shape"] == [4, 5, 6, 3]
    assert rec["scan_fwd_ms"] >= 0.0 and rec["pallas_fwd_ms"] >= 0.0


@pytest.mark.slow
def test_mil_regime_batch_squared_pairs():
    """The SDTW_3 training regime: B^2 short pairs (32x32 alignment, the
    shape that crashed Mosaic's vector lowering before the batch-tile
    cap; see _batch_tile)."""
    rng = np.random.RandomState(7)
    D = jnp.asarray(rng.rand(64, 32, 32).astype(np.float32))
    np.testing.assert_allclose(np.asarray(softdtw_pallas(D, 1.0)),
                               np.asarray(softdtw_scan(D, 1.0)),
                               rtol=1e-4, atol=1e-4)
    got = jax.grad(lambda d: softdtw_pallas(d, 1.0).sum())(D)
    want = jax.grad(lambda d: softdtw_scan(d, 1.0).sum())(D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_lanes_layout_matches_scan(monkeypatch):
    """Large-batch short-pair shapes route through the batch-on-lanes
    kernels by default (measured 3.5-26x on v5e, BENCH_SOFTDTW.md);
    values and grads must match the scan (multi-block at B=300,
    rectangular, and the 32x32 MIL shape)."""
    monkeypatch.delenv("MILNCE_SDTW_LANES", raising=False)
    from milnce_tpu.ops import softdtw_pallas as sp

    rng = np.random.RandomState(13)
    for (b, n, m) in [(64, 32, 32), (300, 10, 8), (40, 16, 24)]:
        assert sp._use_lanes(b, n, m)
        D = jnp.asarray(rng.rand(b, n, m).astype(np.float32))
        np.testing.assert_allclose(np.asarray(softdtw_pallas(D, 0.7)),
                                   np.asarray(softdtw_scan(D, 0.7)),
                                   rtol=1e-4, atol=1e-4)
        got = jax.grad(lambda d: softdtw_pallas(d, 0.7).sum())(D)
        want = jax.grad(lambda d: softdtw_scan(d, 0.7).sum())(D)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-3)
    # small batches stay on the sublane-batch layout
    assert not sp._use_lanes(4, 10, 8)
    # MILNCE_SDTW_LANES=0 is the escape hatch back to sublane-batch
    monkeypatch.setenv("MILNCE_SDTW_LANES", "0")
    assert not sp._use_lanes(64, 32, 32)
