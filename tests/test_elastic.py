"""Elastic pod training gates (ISSUE 20).

The acceptance chain that lives here: a tiny-CPU run receives
``host.preempt`` mid-run, drains with a clean forced checkpoint +
``ELASTIC_STAMP.json``, resumes onto a DIFFERENT mesh shape — 8-way ->
4x2 AND 4x2 -> 4-way, both covered by one three-leg chain — and
finishes the plan with loss-trajectory continuity pinned against an
uninterrupted same-seed run: zero skipped/duplicated batches (the
per-step losses would diverge on the first one), resharded state
leaf-for-leaf equal after restore, and 0 recompiles within each
topology segment.

Around the chain: the stamp refusals (mesh-indivisible batch, stale
sidecar pair, schedule-removed), the drained-save atomicity regression
(``ckpt.save_ioerror`` inside the drain's forced save), the distinct
drained CLI exit status, the ``host.slow`` fault site, and the
straggler policy's flag -> demote -> resize-recommendation ladder.

Pinned tier-1 (never @slow) by tests/test_suite_hygiene.py
``_ELASTIC_GATES``.
"""

import json
import os

import numpy as np
import pytest

import jax

from milnce_tpu import elastic
from milnce_tpu.config import tiny_preset
from milnce_tpu.elastic.drain import DRAINED_EXIT_CODE, DrainController
from milnce_tpu.elastic.stamp import (ELASTIC_STAMP_NAME,
                                      check_topology_resume,
                                      read_elastic_stamp,
                                      write_elastic_stamp)
from milnce_tpu.elastic.straggler import StragglerPolicy
from milnce_tpu.resilience import faults
from milnce_tpu.train import curriculum
from milnce_tpu.train import loop as loop_mod
from milnce_tpu.train.checkpoint import CheckpointManager


def _cfg(tmp_path, name, samples=32, epochs=2):
    cfg = tiny_preset()
    cfg.model.inception_blocks = 1
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = samples
    cfg.data.num_reader_threads = 2
    cfg.optim.epochs = epochs
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")   # shared: resume
    cfg.train.log_root = str(tmp_path / f"log_{name}")
    cfg.train.n_display = 1         # per-step display events: the loss
    #                                 trajectory the continuity pin reads
    cfg.train.run_id = name
    return cfg


def _display_losses(cfg):
    path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")
    records = [json.loads(line) for line in open(path)]
    return {r["step"]: r["loss"] for r in records
            if r.get("name") == "display"}


def _goodput(cfg):
    return json.load(
        open(os.path.join(cfg.train.log_root, "GOODPUT.json")))


# ---------------------------------------------------------------------------
# the chaos acceptance chain: 8-way -> 4x2 -> 4-way
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chain(tmp_path_factory):
    """ONE drained/resumed chain + its uninterrupted same-seed twin,
    shared by the acceptance pins below (each training leg pays model
    init + compile; the artifacts are read-only afterwards).

    Plan: 32 samples / batch 8 / 2 epochs = 8 global steps.  Baseline
    runs all 8 on the 8-way mesh.  The chain: leg1 drains at step 2
    (mid-epoch, 8-way), leg2 resumes on the 4x2 FSDP grid and drains at
    global step 5 (mid-epoch 1), leg3 resumes on the 4-way data mesh
    (parallel.num_devices=4) and finishes the plan."""
    from milnce_tpu.train.loop import run_training

    tmp = tmp_path_factory.mktemp("elastic_chain")
    base_tmp = tmp_path_factory.mktemp("elastic_base")

    captured = []                   # one jitted step per leg (recompile pin)
    orig = loop_mod.make_train_step

    def capturing(*args, **kwargs):
        fn = orig(*args, **kwargs)
        captured.append(fn)
        return fn

    loop_mod.make_train_step = capturing
    try:
        cfg_b = _cfg(base_tmp, "baseline")
        res_b = run_training(cfg_b)

        cfg1 = _cfg(tmp, "leg1")
        cfg1.train.faults = "host.preempt@2"
        res1 = run_training(cfg1)

        cfg2 = _cfg(tmp, "leg2")
        cfg2.train.resume = True
        cfg2.train.faults = "host.preempt@3"
        cfg2.parallel.model_axis = "model"
        cfg2.parallel.model_parallel_size = 2
        cfg2.parallel.fsdp_min_size = 256   # tiny model: actually shard
        res2 = run_training(cfg2)

        cfg3 = _cfg(tmp, "leg3")
        cfg3.train.resume = True
        cfg3.parallel.num_devices = 4
        res3 = run_training(cfg3)
    finally:
        loop_mod.make_train_step = orig
    return {"cfgs": (cfg_b, cfg1, cfg2, cfg3),
            "results": (res_b, res1, res2, res3),
            "steps": captured,
            "ckpt_dir": os.path.join(cfg1.train.checkpoint_root, "run")}


def test_chain_drains_and_finishes_the_plan(chain):
    res_b, res1, res2, res3 = chain["results"]
    assert res_b.steps == 8 and not res_b.drained
    assert res1.drained and res1.steps == 2
    assert res2.drained and res2.steps == 3      # global 3..5
    assert not res3.drained and res3.steps == 3  # global 6..8
    # zero skipped / duplicated batches: the three legs' step counts
    # partition the plan exactly, and the device counters agree
    assert res1.steps + res2.steps + res3.steps == res_b.steps
    assert int(res3.state.step) == int(res_b.state.step) == 8
    assert np.isfinite(res3.last_loss)


def test_chain_loss_trajectory_matches_uninterrupted_run(chain):
    """The continuity pin: same seed, same per-step losses across the
    drain/resume/topology changes — any skipped or repeated batch would
    diverge the trajectory ~30% at the first occurrence (neighboring
    batches' losses differ that much on this run).

    Tolerance is layout-honest.  The step program computes BN batch
    statistics per data shard (local BN — step.py), so the 8-way and
    4x2 legs both normalize over 1-clip shards and match the baseline
    to reduction-order noise (rtol 2e-4), while the 4-way leg's 2-clip
    shards legitimately shift the BN math ~1% — its pin is rtol 5e-2:
    loose enough for the semantics change, still ~20x tighter than a
    data misalignment.  Verified empirically: the SAME checkpoint
    resumed 8-way reproduces the baseline exactly; resumed 4-way it
    lands within 1.4% — the drift is BN shard size, not the resume."""
    cfg_b, cfg1, cfg2, cfg3 = chain["cfgs"]
    base = _display_losses(cfg_b)
    assert sorted(base) == list(range(1, 9))
    chained = {}
    shard_clips = {}                # global step -> clips per data shard
    for cfg, n_shards in ((cfg1, 8), (cfg2, 8), (cfg3, 4)):
        leg = _display_losses(cfg)
        chained.update(leg)
        for s in leg:
            shard_clips[s] = cfg.train.batch_size // n_shards
    assert sorted(chained) == sorted(base)
    for step in sorted(base):
        rtol = 2e-4 if shard_clips[step] == 1 else 5e-2
        np.testing.assert_allclose(
            chained[step], base[step], rtol=rtol, atol=2e-5,
            err_msg=f"loss diverged at global step {step}")


def test_chain_zero_recompiles_per_topology_segment(chain):
    """0 recompiles WITHIN each topology segment: every leg's jitted
    step holds exactly one cache entry at exit — the resharded resume
    compiles once for its layout and never retraces."""
    steps = chain["steps"]
    assert len(steps) == 4          # baseline + three legs
    for i, fn in enumerate(steps):
        if not hasattr(fn, "_cache_size"):
            pytest.skip("no _cache_size on this jax")
        assert fn._cache_size() == 1, f"leg {i} retraced"


def test_chain_stamps_and_ledger_categories(chain):
    cfg_b, cfg1, cfg2, cfg3 = chain["cfgs"]
    stamp = read_elastic_stamp(chain["ckpt_dir"])
    assert stamp["schema"] == "milnce.elastic/v1"
    assert stamp["mesh"] == {"data": 4} and stamp["n_devices"] == 4
    assert stamp["step"] == 8 and not stamp["drained"]
    cstamp = curriculum.read_stage_stamp(chain["ckpt_dir"])
    assert cstamp["step"] == stamp["step"]      # the sidecar pair agrees
    # drained legs attribute the forced save to drain; resumed legs
    # attribute the (resharding) restore to reshard — and the partition
    # property survives both (sum == wall is pinned externally by
    # tests/test_goodput.py; here the categories must exist and be fed)
    for cfg, drained, resumed in ((cfg1, True, False), (cfg2, True, True),
                                  (cfg3, False, True)):
        cats = _goodput(cfg)["categories_s"]
        assert (cats["drain"] > 0) == drained, (cfg.train.run_id, cats)
        assert (cats["reshard"] > 0) == resumed, (cfg.train.run_id, cats)
    base_cats = _goodput(cfg_b)["categories_s"]
    assert base_cats["drain"] == 0 and base_cats["reshard"] == 0


def test_chain_resharded_restore_leaf_for_leaf(chain):
    """A checkpoint written by the 8-way leg restores INTO the 4x2
    FSDP sharding (the live leg2 state as restore template — the loop's
    restore-template path) with every leaf bit-equal to the drained
    live state: resharding moves bytes, never changes them."""
    res_b, res1, res2, res3 = chain["results"]
    mgr = CheckpointManager(chain["ckpt_dir"], create=False)
    try:
        # label 0: leg1's mid-epoch forced save (8-way writer)
        restored = mgr.restore(0, res2.state)   # 4x2-sharded template
    finally:
        mgr.close()
    want = jax.tree_util.tree_leaves(jax.device_get(res1.state))
    got = jax.tree_util.tree_leaves(jax.device_get(restored))
    assert len(want) == len(got)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


# ---------------------------------------------------------------------------
# drained-save atomicity + host.slow (one run covers both)
# ---------------------------------------------------------------------------

def test_drain_save_survives_transient_ioerror_and_host_slow(tmp_path):
    """The fix satellite: the drain path routes through the atomic
    tmp+rename checkpoint discipline WITH the transient-I/O retry — an
    injected OSError inside the drained forced save must not leave a
    partial rotation (the next open restores cleanly).  The same run
    arms ``host.slow`` and pins that the injected inflation shows in
    the recorded step spans (the skew the straggler policy feeds on)."""
    from milnce_tpu.train.loop import run_training

    slow_s = 0.02
    cfg = _cfg(tmp_path, "atomic", samples=32, epochs=2)
    cfg.train.faults = (f"host.preempt@2;ckpt.save_ioerror@1;"
                        f"host.slow@*:x={slow_s}")
    res = run_training(cfg)
    assert res.drained and res.steps == 2
    # the rotation is clean: a fresh manager opens it and restores
    ckpt_dir = os.path.join(cfg.train.checkpoint_root, "run")
    mgr = CheckpointManager(ckpt_dir, create=False)
    try:
        latest = mgr.latest_epoch()
        assert latest is not None
        restored = mgr.restore(latest, res.state)
    finally:
        mgr.close()
    for w, g in zip(jax.tree_util.tree_leaves(jax.device_get(res.state)),
                    jax.tree_util.tree_leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
    assert read_elastic_stamp(ckpt_dir)["drained"]
    # no partial-rotation debris (the stale-epoch backup is removed
    # after commit; a .tmp dir would be an uncommitted Orbax write)
    debris = [n for n in os.listdir(ckpt_dir)
              if n.startswith("stale-epoch-") or n.endswith(".tmp")]
    assert not debris, debris
    # host.slow inflated every recorded step span by >= x
    path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")
    step_spans = [r for r in map(json.loads, open(path))
                  if r.get("name") == "step"]
    assert step_spans
    assert all(s["dur_ms"] >= slow_s * 1e3 for s in step_spans)
    # the drain announced its source as the fault site
    events = [r for r in map(json.loads, open(path))
              if r.get("name") == "preempt.signal"]
    assert [e["source"] for e in events] == ["host.preempt"]


def test_drained_cli_exit_status(monkeypatch, capsys):
    """The distinct drained status: cli.main exits DRAINED_EXIT_CODE
    (75, EX_TEMPFAIL) when the loop reports a drain, 0 otherwise."""
    from milnce_tpu.train import cli

    def fake_run(cfg):
        return loop_mod.TrainResult(state=None, steps=3, last_loss=1.25,
                                    drained=True)

    monkeypatch.setattr(cli, "run_training", fake_run)
    with pytest.raises(SystemExit) as exc:
        cli.main(["--preset", "tiny"])
    assert exc.value.code == DRAINED_EXIT_CODE
    assert "resume" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# curriculum interop: drain mid-stage, resume on a smaller mesh
# ---------------------------------------------------------------------------

_TWO_STAGE = ("num_frames=4,resolution=32,until_step=3;"
              "num_frames=8,resolution=32")


def test_curriculum_drain_resume_smaller_mesh_stamps_agree(tmp_path):
    """Drain mid-stage on the 8-way mesh, resume on the 4-way mesh:
    CURRICULUM_STAMP.json and ELASTIC_STAMP.json agree on the plan
    cursor at every save, and the resumed run crosses the stage
    boundary exactly where the plan says (no skipped/repeated step)."""
    from milnce_tpu.train.loop import run_training

    cfg1 = _cfg(tmp_path, "cur1", samples=48, epochs=1)   # 6 plan steps
    cfg1.train.curriculum = _TWO_STAGE
    cfg1.train.faults = "host.preempt@2"
    res1 = run_training(cfg1)
    assert res1.drained and res1.steps == 2 and res1.stage == 0
    ckpt_dir = os.path.join(cfg1.train.checkpoint_root, "run")
    estamp = read_elastic_stamp(ckpt_dir)
    cstamp = curriculum.read_stage_stamp(ckpt_dir)
    assert estamp["step"] == cstamp["step"] == 2
    assert estamp["drained"] and estamp["stage"] == 0
    assert estamp["mesh"] == {"data": 8}

    cfg2 = _cfg(tmp_path, "cur2", samples=48, epochs=1)
    cfg2.train.curriculum = _TWO_STAGE
    cfg2.train.resume = True
    cfg2.parallel.num_devices = 4
    res2 = run_training(cfg2, max_steps=2)      # global steps 3, 4
    assert not res2.drained and res2.steps == 2
    assert res2.stage == 1          # until_step=3 boundary crossed
    estamp2 = read_elastic_stamp(ckpt_dir)
    cstamp2 = curriculum.read_stage_stamp(ckpt_dir)
    assert estamp2["step"] == cstamp2["step"] == 4
    assert estamp2["mesh"] == {"data": 4}
    assert estamp2["stage"] == cstamp2["stage"] == 1


def test_schedule_removed_resume_refuses_loudly(tmp_path):
    from milnce_tpu.train.loop import run_training

    cfg1 = _cfg(tmp_path, "sched1", samples=48, epochs=1)
    cfg1.train.curriculum = _TWO_STAGE
    cfg1.train.faults = "host.preempt@2"
    run_training(cfg1)

    cfg2 = _cfg(tmp_path, "sched2", samples=48, epochs=1)
    cfg2.train.resume = True        # curriculum spec REMOVED
    with pytest.raises(ValueError, match="curriculum"):
        run_training(cfg2, max_steps=1)


def test_mesh_indivisible_batch_resume_refuses_loudly(tmp_path):
    from milnce_tpu.train.loop import run_training

    cfg1 = _cfg(tmp_path, "indiv1", samples=16, epochs=2)
    cfg1.train.faults = "host.preempt@2"
    run_training(cfg1)

    cfg2 = _cfg(tmp_path, "indiv2", samples=16, epochs=2)
    cfg2.train.resume = True
    cfg2.parallel.num_devices = 3   # batch 8 % 3 != 0
    with pytest.raises(ValueError, match="does not divide"):
        run_training(cfg2, max_steps=1)


# ---------------------------------------------------------------------------
# stamp unit behavior
# ---------------------------------------------------------------------------

class TestStamp:
    def test_write_read_round_trip_is_atomic(self, tmp_path):
        d = str(tmp_path)
        write_elastic_stamp(d, mesh_shape={"data": 4, "model": 2},
                            sharding_hash="abc", step=7, stage_index=1,
                            batch_offset=3, drained=True)
        s = read_elastic_stamp(d)
        assert s["mesh"] == {"data": 4, "model": 2}
        assert s["n_devices"] == 8 and s["step"] == 7
        assert s["stage"] == 1 and s["batch_offset"] == 3 and s["drained"]
        assert not os.path.exists(
            os.path.join(d, ELASTIC_STAMP_NAME + ".tmp"))

    def test_missing_stamp_is_none_and_passes(self, tmp_path):
        assert read_elastic_stamp(str(tmp_path)) is None
        assert check_topology_resume(
            None, mesh_shape={"data": 8}, batch_sizes=[8],
            curriculum_stamp=None) is None

    def test_unchanged_topology_is_silent(self):
        stamp = {"mesh": {"data": 8}, "step": 4, "sharding_hash": ""}
        assert check_topology_resume(
            stamp, mesh_shape={"data": 8}, batch_sizes=[8],
            curriculum_stamp={"step": 4}) is None

    def test_topology_change_is_logged(self):
        stamp = {"mesh": {"data": 8}, "step": 4, "sharding_hash": "h"}
        note = check_topology_resume(
            stamp, mesh_shape={"data": 4, "model": 2}, batch_sizes=[8],
            curriculum_stamp=None)
        assert "topology change" in note and "'data': 4" in note

    def test_indivisible_batch_refused_before_io(self):
        with pytest.raises(ValueError, match="does not divide"):
            check_topology_resume(
                None, mesh_shape={"data": 3}, batch_sizes=[8, 6],
                curriculum_stamp=None)

    def test_stale_sidecar_pair_refused(self):
        stamp = {"mesh": {"data": 8}, "step": 4}
        with pytest.raises(ValueError, match="sidecar pair is stale"):
            check_topology_resume(
                stamp, mesh_shape={"data": 8}, batch_sizes=[8],
                curriculum_stamp={"step": 6})


# ---------------------------------------------------------------------------
# drain controller + fault sites
# ---------------------------------------------------------------------------

class _Rec:
    def __init__(self):
        self.events = []

    def event(self, name, **attrs):
        self.events.append((name, attrs))


class TestDrainController:
    def test_host_preempt_fires_at_scheduled_step(self):
        rec = _Rec()
        d = DrainController(recorder=rec)
        with faults.armed("host.preempt@3"):
            assert not d.poll(1) and not d.poll(2)
            assert d.poll(3)
            assert d.poll(4)        # latched
        assert d.source == "host.preempt"
        # announced exactly once, on the poll thread
        assert [e for e in rec.events if e[0] == "preempt.signal"] == [
            ("preempt.signal", {"source": "host.preempt", "step": 3})]

    def test_signal_file_trips_and_latches(self, tmp_path):
        flag = str(tmp_path / "drain.now")
        d = DrainController(signal_file=flag)
        assert not d.poll(1)
        open(flag, "w").close()
        assert d.poll(2) and d.source == "signal_file"
        os.remove(flag)
        assert d.poll(3)            # latched: removal doesn't untrip

    def test_sigterm_install_uninstall_round_trip(self):
        import signal as _signal

        prev = _signal.getsignal(_signal.SIGTERM)
        d = DrainController()
        d.install()
        try:
            _signal.raise_signal(_signal.SIGTERM)
            assert d.poll(5) and d.source == "sigterm"
        finally:
            d.uninstall()
        assert _signal.getsignal(_signal.SIGTERM) is prev

    def test_known_sites_include_elastic_pair(self):
        assert "host.preempt" in faults.KNOWN_SITES
        assert "host.slow" in faults.KNOWN_SITES
        spec = faults.parse_spec("host.slow@%2:x=0.5")
        assert spec["host.slow"].x == 0.5


# ---------------------------------------------------------------------------
# straggler policy
# ---------------------------------------------------------------------------

class TestStragglerPolicy:
    def test_single_host_never_flags(self):
        p = StragglerPolicy(window=1)
        for _ in range(5):
            p.observe(0, 100.0)
        assert p.demoted == [] and p.last_skew == 1.0

    def test_flag_streak_demotes_once(self):
        rec = _Rec()
        p = StragglerPolicy(ratio=1.25, window=3, recorder=rec)
        for _ in range(4):
            p.observe(0, 10.0)
            p.observe(1, 20.0)      # 2x the fastest: flagged each round
        assert p.demoted == [1]
        names = [n for n, _ in rec.events]
        assert names.count("straggler.demote") == 1
        assert names.count("straggler.resize_recommended") == 0
        straggler_events = [a for n, a in rec.events if n == "straggler"]
        assert all(a["process"] == 1 for a in straggler_events)
        assert p.ledger_extra()["demoted_hosts"] == [1]
        assert p.ledger_extra()["straggler_skew"] == pytest.approx(2.0)

    def test_streak_resets_on_recovery(self):
        p = StragglerPolicy(ratio=1.25, window=3)
        p.observe(0, 10.0)          # single host: nothing to compare
        p.observe(1, 20.0)          # flagged, streak 1
        p.observe(1, 10.0)          # p50 over [20,10] = 15 — streak 2
        p.observe(1, 10.0)          # p50 over [20,10,10] = 10 — reset
        for _ in range(5):
            p.observe(0, 10.0)
            p.observe(1, 10.0)
        assert p.demoted == []      # streak never reached the window

    def test_resize_recommendation_behind_knob(self):
        rec = _Rec()
        p = StragglerPolicy(ratio=1.25, window=2, recommend_resize=True,
                            recorder=rec)
        for _ in range(3):
            p.observe(0, 10.0)
            p.observe(1, 30.0)
        names = [n for n, _ in rec.events]
        assert names.count("straggler.resize_recommended") == 1
        rec_attrs = [a for n, a in rec.events
                     if n == "straggler.resize_recommended"][0]
        assert "drain" in rec_attrs["reason"]

    def test_feed_merged_pod_view(self):
        """The post-hoc twin: an obs_report --merge pod view feeds every
        host's p50 in one call, same rule as the live path."""
        p = StragglerPolicy(ratio=1.25, window=1)
        merged = {"per_process": {0: {"steps": 4, "step_ms_p50": 10.0},
                                  1: {"steps": 4, "step_ms_p50": 40.0},
                                  2: {"steps": 0, "step_ms_p50": 0.0}}}
        p.feed_merged(merged)
        assert p.demoted == [1]
        assert p.last_skew == pytest.approx(4.0)

    def test_bad_knobs_refused(self):
        with pytest.raises(ValueError, match="ratio"):
            StragglerPolicy(ratio=1.0)
        with pytest.raises(ValueError, match="window"):
            StragglerPolicy(window=0)
