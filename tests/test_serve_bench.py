"""serve_bench CPU smoke (ISSUE 4 acceptance): the load generator must
complete on CPU with the tiny preset and emit a valid SERVE_BENCH_*.json
— latency percentiles, QPS, batch-occupancy histogram, cache hit rate —
with zero steady-state recompiles.

This intentionally runs the real script as a child process (the report
format IS the contract), but at a seconds-scale tiny configuration —
it is tier-1 by design (suite-hygiene exemption documents this)."""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# path kept in a module constant: the hygiene marker-audit scans test
# function BODIES for measurement-stack fragments; the explicit
# exemption in test_suite_hygiene.py is the authoritative carve-out
_SERVE_BENCH = os.path.join(_REPO, "scripts", "serve_bench.py")


def test_cpu_smoke_emits_valid_report(tmp_path):
    out = tmp_path / "SERVE_BENCH_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # share the suite's persistent compile cache with the child (the
    # script itself doesn't configure one — production benches must
    # measure real compiles): the tiny-preset warmup becomes disk hits,
    # holding this child inside the tier-1 budget
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    proc = subprocess.run(
        [sys.executable, _SERVE_BENCH, "--backend", "cpu",
         "--preset", "tiny", "--duration", "1.0", "--concurrency", "2",
         "--corpus", "12", "--distinct", "6", "--max_batch", "8",
         "--out", str(out)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out.read_text())

    assert report["generator"] == "scripts/serve_bench.py"
    # ISSUE 5: the report is a versioned obs snapshot — one schema for
    # serve benches, train benches, and registry dumps, so
    # scripts/obs_report.py can summarize and gate any of them
    assert report["schema"] == "milnce.obs/v1"
    assert report["kind"] == "serve_bench"
    for family in ("milnce_serve_requests_total",
                   "milnce_serve_batch_occupancy",
                   "milnce_serve_cache_hit_rate",
                   "milnce_serve_engine_recompiles"):
        assert family in report["metrics"], f"{family} missing"
    assert report["requests"] > 0 and report["qps"] > 0
    assert report["errors"] == 0 and report["deadline_expired"] == 0
    # latency percentiles present, ordered, finite
    lat = report["latency_ms"]
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    assert all(v > 0 for v in (lat["p50"], lat["p95"], lat["p99"]))
    # batch-occupancy histogram: per-bucket flush counts + fill
    assert report["batch_occupancy"], "no occupancy recorded"
    for bucket, ent in report["batch_occupancy"].items():
        assert int(bucket) >= 1
        assert ent["flushes"] >= 1 and 0.0 < ent["mean_fill"] <= 1.0
    # cache saw repeats (distinct pool << requests)
    assert 0.0 <= report["cache"]["hit_rate"] <= 1.0
    assert report["cache"]["hits"] + report["cache"]["misses"] > 0
    # steady state stayed pre-traced
    assert report["engine"]["recompiles"] in (0, -1)
    assert report["index"]["size"] == 12