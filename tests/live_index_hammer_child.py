"""Child process of the live-index ingest-while-query hammer
(tests/test_live_index.py).

16 threads — 12 issuing retrieval queries, 4 ingesting embedding rows —
against one :class:`~milnce_tpu.serving.live_index.LiveRetrievalIndex`
under ``MILNCE_LOCK_SANITIZE=1`` (exported by the parent BEFORE import,
so the state lock, dispatch lock, and every obs lock is an
order-checking SanitizedLock).  The pins (ISSUE 14 satellite):

- **exact-count accounting**: the final corpus size equals boot +
  every row every ingest thread added — no lost or double-counted rows
  under contention;
- **no torn generations**: every query result must equal the exact
  ``np.argsort`` ranking over SOME published corpus prefix (the ingest
  threads serialize their ``add`` calls through one lock while
  recording order, so the corpus at any generation is a known prefix);
  a result mixing two generations matches NO prefix and fails loudly.
  The generation→prefix association must also be consistent: one
  generation never answers with two different corpus sizes;
- **recompiles=0 across >= 3 swaps** on the query path;
- the sanitizer actually engaged (observed lock edges), and the builder
  thread survived the whole run.
"""

import os
import sys
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Same hermetic platform the test suite uses; must precede jax import.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from milnce_tpu.analysis import lockrt  # noqa: E402

assert lockrt.sanitizing_enabled(), \
    "parent must export MILNCE_LOCK_SANITIZE=1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from milnce_tpu.serving.live_index import LiveRetrievalIndex  # noqa: E402

DIM, BOOT, K = 16, 12, 5
N_QUERY_THREADS, N_INGEST_THREADS = 12, 4
QUERIES_PER_THREAD, ADDS_PER_THREAD, ROWS_PER_ADD = 8, 3, 4
MIN_SWAPS = 3


def main() -> int:
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.default_rng(0)
    boot = rng.standard_normal((BOOT, DIM)).astype(np.float32)
    index = LiveRetrievalIndex(mesh, boot, k=K, query_buckets=(8,))
    assert isinstance(index._state_lock, lockrt.SanitizedLock), \
        "live-index state lock must be sanitized"

    # ingest rows pre-generated; the add lock serializes the calls AND
    # records acceptance order, so the corpus at any instant is a known
    # prefix of `appended` — the torn-generation check's ground truth
    total_adds = N_INGEST_THREADS * ADDS_PER_THREAD
    pool = rng.standard_normal(
        (total_adds * ROWS_PER_ADD, DIM)).astype(np.float32)
    add_lock = threading.Lock()
    appended: list[np.ndarray] = []
    errors: list[str] = []
    observed: list[tuple] = []          # (gen, q_seed, idx_rows)
    obs_lock = threading.Lock()

    def ingester(tid: int) -> None:
        try:
            for j in range(ADDS_PER_THREAD):
                base = (tid * ADDS_PER_THREAD + j) * ROWS_PER_ADD
                rows = pool[base:base + ROWS_PER_ADD]
                with add_lock:          # serialize add + order record
                    index.add(rows)
                    appended.append(rows)
                # wait for THIS add to publish before the next one: a
                # thread's sequential adds then land in distinct swaps,
                # guaranteeing >= ADDS_PER_THREAD swaps however hard
                # the builder coalesces concurrent ingests
                assert index.flush(60.0), "mid-hammer flush timed out"
        except Exception as exc:  # noqa: BLE001 - child reports
            errors.append(f"ingest {tid}: {type(exc).__name__}: {exc}")

    def querier(tid: int) -> None:
        try:
            qrng = np.random.default_rng(1000 + tid)
            for _ in range(QUERIES_PER_THREAD):
                q = qrng.standard_normal((2, DIM)).astype(np.float32)
                scores, idx, gen = index.topk_with_gen(q)
                assert scores.shape == (2, K) and idx.shape == (2, K)
                with obs_lock:
                    observed.append((gen, q, idx.copy()))
        except Exception as exc:  # noqa: BLE001 - child reports
            errors.append(f"query {tid}: {type(exc).__name__}: {exc}")

    threads = [threading.Thread(target=ingester, args=(t,))
               for t in range(N_INGEST_THREADS)]
    threads += [threading.Thread(target=querier, args=(t,))
                for t in range(N_QUERY_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    if errors:
        print("\n".join(errors), file=sys.stderr)
        return 1
    if not index.flush(30.0):
        print("final flush timed out — pending rows never landed",
              file=sys.stderr)
        return 1

    st = index.stats()
    expect = BOOT + total_adds * ROWS_PER_ADD
    if st["size"] != expect or st["ingested_rows"] != expect - BOOT:
        print(f"count accounting broken: {st} != size {expect}",
              file=sys.stderr)
        return 1
    if st["swaps"] < MIN_SWAPS:
        print(f"only {st['swaps']} swaps < {MIN_SWAPS} — the hammer "
              "never exercised concurrent swapping", file=sys.stderr)
        return 1
    if index.recompiles() != 0:
        print(f"query-path recompiles={index.recompiles()} != 0 across "
              f"{st['swaps']} swaps", file=sys.stderr)
        return 1
    if not st["builder_alive"]:
        print("builder thread died during the hammer", file=sys.stderr)
        return 1

    # torn-generation audit: every observed ranking must equal the
    # argsort over a corpus PREFIX (the only corpora ever published —
    # a result mixing two generations matches none), and per generation
    # there must exist ONE corpus size consistent with every result it
    # answered (a ranking can legitimately match several prefixes when
    # the newer rows don't crack its top-k, so the pin is set
    # intersection, not first-match equality)
    full = np.concatenate([boot] + appended)
    sizes = [BOOT + sum(a.shape[0] for a in appended[:m])
             for m in range(len(appended) + 1)]
    gen_sets: dict[int, set] = {}
    for gen, q, idx in observed:
        matches = set()
        for size in sizes:
            if size < K:
                continue
            ref = np.argsort(-(q @ full[:size].T), axis=1)[:, :K]
            if np.array_equal(idx, ref):
                matches.add(size)
        if not matches:
            print(f"TORN GENERATION: a gen-{gen} result matches no "
                  "published corpus prefix", file=sys.stderr)
            return 1
        gen_sets[gen] = (gen_sets[gen] & matches
                         if gen in gen_sets else matches)
        if not gen_sets[gen]:
            print(f"generation {gen}: no single corpus size is "
                  "consistent with every result it answered",
                  file=sys.stderr)
            return 1
    edges = lockrt.GLOBAL_GRAPH.snapshot()["edges"]
    if not edges:
        print("sanitizer saw no lock edges — not actually engaged?",
              file=sys.stderr)
        return 1
    print(f"HAMMER_OK threads={len(threads)} queries={len(observed)} "
          f"swaps={st['swaps']} size={st['size']} edges={len(edges)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
