"""Child process of the lockrt serving hammer (tests/test_lockrt.py).

Runs the FULL serving stack — a 2-replica engine POOL (per-replica
dispatch locks, pool state lock, probe thread — ISSUE 10), dynamic
batcher in pipelined mode, embedding cache, device-resident index
(still behind the module-level DEVICE_DISPATCH_LOCK), HTTP front,
Prometheus scrape — with ``MILNCE_LOCK_SANITIZE=1`` exported by the
parent BEFORE import, so every lock in the mesh is an order-checking
SanitizedLock.  16 threads mix query / embed / healthz / metrics /
events traffic; any lock-order cycle, self-deadlock or sanitizer
failure raises and fails the child.

Model/engine dimensions deliberately match tests/test_serving.py's
module stack so the persistent jax compilation cache (conftest wiring,
replicated below) turns the warmup sweep into disk hits.
"""

import json
import os
import sys
import threading
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Same hermetic platform the test suite uses; must precede jax import.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from milnce_tpu.analysis import lockrt  # noqa: E402

assert lockrt.sanitizing_enabled(), \
    "parent must export MILNCE_LOCK_SANITIZE=1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from milnce_tpu.models import S3D  # noqa: E402
from milnce_tpu.obs import metrics as obs_metrics  # noqa: E402
from milnce_tpu.serving import engine as engine_mod  # noqa: E402
from milnce_tpu.serving.cache import EmbeddingLRUCache  # noqa: E402
from milnce_tpu.serving.index import DeviceRetrievalIndex  # noqa: E402
from milnce_tpu.serving.pool import ReplicaPool  # noqa: E402
from milnce_tpu.serving.service import (RetrievalService,  # noqa: E402
                                        serve_http)

_FRAMES, _SIZE, _WORDS, _CORPUS = 4, 32, 6, 21
N_THREADS, OPS_PER_THREAD = 16, 6


def main() -> int:
    assert isinstance(engine_mod.DEVICE_DISPATCH_LOCK,
                      lockrt.SanitizedLock), (
        "DEVICE_DISPATCH_LOCK must be sanitized — env not seen at import?")

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, _FRAMES, _SIZE, _SIZE, 3)),
                           jnp.zeros((1, _WORDS), jnp.int32))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    # ISSUE 10: the hammer drives the POOL — 16 request threads against
    # 2 single-device replicas (own dispatch locks + workers + probe
    # thread) while the index still serializes on the process-wide
    # DEVICE_DISPATCH_LOCK; the whole lock mesh is sanitized
    pool = ReplicaPool.build(model, dict(variables), 2,
                             text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=16, min_bucket=8,
                             probe_interval_s=0.5,
                             registry=obs_metrics.registry())
    assert isinstance(pool._state_lock, lockrt.SanitizedLock)
    for r in pool.replicas:
        assert isinstance(r.engine._dispatch_lock, lockrt.SanitizedLock)
        assert isinstance(r.engine._stats_lock, lockrt.SanitizedLock)
    rng = np.random.default_rng(0)
    clips = rng.integers(0, 255, (_CORPUS, _FRAMES, _SIZE, _SIZE, 3),
                         dtype=np.uint8)
    corpus = np.concatenate(
        [pool.embed_video(clips[:16]), pool.embed_video(clips[16:])])
    index = DeviceRetrievalIndex(mesh, corpus, k=5,
                                 query_buckets=pool.buckets)
    service = RetrievalService(pool, index,
                               cache=EmbeddingLRUCache(128),
                               max_delay_ms=2.0,
                               registry=obs_metrics.registry())
    server = serve_http(service, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    errors: list = []

    def post(route, payload):
        req = urllib.request.Request(
            base + route, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200, (route, r.status)
            return json.loads(r.read())

    def get(route):
        with urllib.request.urlopen(base + route, timeout=60) as r:
            assert r.status == 200, (route, r.status)
            return r.read()

    def hammer(tid):
        try:
            for i in range(OPS_PER_THREAD):
                ids = [[1 + (tid + i + j) % 60 for j in range(_WORDS)]]
                body = post("/v1/query", {"token_ids": ids, "k": 3})
                assert len(body["results"][0]["indices"]) == 3
                post("/v1/embed_text", {"token_ids": ids})
                health = json.loads(get("/healthz"))
                assert health["status"] == "ok"
                assert health["engine"]["recompiles"] == 0
                get("/metrics")
                get("/obs/events?n=20")
        except Exception as exc:  # noqa: BLE001 - child reports, parent asserts
            errors.append(f"thread {tid}: {type(exc).__name__}: {exc}")

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    server.shutdown()
    server.server_close()
    service.close()
    pool.close()

    if errors:
        print("\n".join(errors), file=sys.stderr)
        return 1
    if pool.recompiles() != 0:
        print(f"pool recompiles={pool.recompiles()} != 0", file=sys.stderr)
        return 1
    edges = lockrt.GLOBAL_GRAPH.snapshot()["edges"]
    if not edges:
        print("sanitizer saw no lock edges — not actually engaged?",
              file=sys.stderr)
        return 1
    print(f"HAMMER_OK threads={N_THREADS} ops={OPS_PER_THREAD} "
          f"edges={len(edges)} replicas={len(pool.replicas)}")
    print(json.dumps({"edges": edges}, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
