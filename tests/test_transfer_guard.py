"""Steady-state transfer-guard gates (ISSUE 2 satellite).

``run_training`` wraps its hot loop in ``jax.transfer_guard("disallow")``
with audited escape hatches at display/preemption/checkpoint cadence.
The negative test smuggles an implicit host sync into the loop body and
asserts the guard turns it into an immediate error (instead of a silent
per-step pipeline stall — the failure mode PR 1's throughput work can't
survive).  The positive test proves the legitimate path still trains:
every remaining transfer in the steady state is explicit or cadenced.
"""

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset


def _tiny_cfg(tmp_path):
    cfg = tiny_preset()
    cfg.model.inception_blocks = 1       # 1-block S3D: tier-1 compile time
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = 16
    cfg.data.num_reader_threads = 2
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")
    cfg.train.log_root = str(tmp_path / "log")
    return cfg


def test_smuggled_host_sync_raises(tmp_path, monkeypatch):
    """Re-introduce the pre-fix pothole: a HOST numpy array built per
    step and fed to the jitted step forces an implicit H2D transfer
    every iteration (this is literally what the un-hoisted np.zeros
    ``start`` fallback used to do).  The steady-state guard must turn
    it into an immediate error.  (On the CPU test backend implicit D2H
    is zero-copy and unguardable; implicit H2D into the committed,
    mesh-sharded step inputs is the guarded class on every backend.)"""
    import milnce_tpu.train.loop as loop_mod

    real_flatten = loop_mod.flatten_text

    def smuggled(batch):
        video, text = real_flatten(batch)
        return video, np.asarray(text)     # host copy -> implicit H2D

    monkeypatch.setattr(loop_mod, "flatten_text", smuggled)
    with pytest.raises(Exception, match="[Dd]isallowed"):
        loop_mod.run_training(_tiny_cfg(tmp_path), max_steps=1)


def test_clean_run_trains_under_guard(tmp_path):
    from milnce_tpu.train.loop import run_training

    res = run_training(_tiny_cfg(tmp_path), max_steps=2)
    assert res.steps == 2
    assert np.isfinite(res.last_loss)
