"""Numerical parity vs the reference PyTorch model (the only trustworthy
full-model oracle — SURVEY.md §7 hard part 3).

Builds the reference torch S3D with random weights, converts its state_dict
through `torch_state_dict_to_flax`, and checks our Flax forward matches in
eval mode.  Skipped when /root/reference or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

REFERENCE = "/root/reference"

torch = pytest.importorskip("torch")
pytestmark = [
    pytest.mark.slow,           # builds the real torch S3D (~1 min)
    pytest.mark.skipif(not os.path.isdir(REFERENCE),
                       reason="reference checkout not available"),
]


def _ref_model(tmp, seed: int, **s3d_kwargs):
    """Reference torch S3D with random weights + on-disk vocab/word2vec
    assets (the constructor loads both from paths)."""
    vocab = np.array([f"word{i}" for i in range(50)])
    np.save(tmp / "dict.npy", vocab)
    torch.manual_seed(seed)
    torch.save(torch.randn(51, 300), tmp / "word2vec.pth")
    sys.path.insert(0, REFERENCE)
    try:
        import s3dg as ref_s3dg  # noqa
    finally:
        sys.path.remove(REFERENCE)
    model = ref_s3dg.S3D(word2vec_path=str(tmp / "word2vec.pth"),
                         token_to_word_path=str(tmp / "dict.npy"),
                         **s3d_kwargs)
    model.eval()
    return model


@pytest.fixture(scope="module")
def torch_model(tmp_path_factory):
    return _ref_model(tmp_path_factory.mktemp("ref_assets"), seed=0,
                      num_classes=64)


def _flax_model():
    from milnce_tpu.models import S3D

    return S3D(num_classes=64, vocab_size=51, word_embedding_dim=300,
               text_hidden_dim=2048)


def test_full_forward_parity(torch_model):
    import jax.numpy as jnp

    from milnce_tpu.utils.torch_convert import torch_state_dict_to_flax

    sd = {k: v.detach().numpy() for k, v in torch_model.state_dict().items()}
    variables = torch_state_dict_to_flax(sd)

    rng = np.random.RandomState(1)
    # odd post-conv1 spatial size (30 -> 15) exercises asymmetric TF-SAME pads
    video = rng.rand(2, 3, 6, 30, 30).astype(np.float32)
    text = rng.randint(0, 51, size=(2, 7)).astype(np.int64)

    with torch.no_grad():
        tv, tt = torch_model(torch.from_numpy(video), torch.from_numpy(text))

    model = _flax_model()
    jv, jt = model.apply(variables, jnp.asarray(video.transpose(0, 2, 3, 4, 1)),
                         jnp.asarray(text.astype(np.int32)))

    np.testing.assert_allclose(np.asarray(jt), tt.numpy(), atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jv), tv.numpy(), atol=2e-4, rtol=1e-3)


def test_ckpt_converter_roundtrips_through_eval_loader(torch_model, tmp_path):
    """assets.py ckpt conversion must produce a run dir the eval CLI's
    Orbax path actually restores — same weights as direct conversion,
    not a silent fresh-init fallback."""
    import jax.numpy as jnp

    from milnce_tpu.config import ModelConfig
    from milnce_tpu.eval.cli import load_variables
    from milnce_tpu.models import S3D
    from milnce_tpu.utils.assets import convert_checkpoint
    from milnce_tpu.utils.torch_convert import torch_state_dict_to_flax

    src = tmp_path / "epoch0007.pth.tar"
    torch.save({"epoch": 7, "state_dict": torch_model.state_dict()}, src)
    run_dir = tmp_path / "run"
    assert convert_checkpoint(str(src), str(run_dir)) == 7

    model = S3D(num_classes=64, vocab_size=51, word_embedding_dim=300,
                text_hidden_dim=2048)
    sample = (jnp.zeros((1, 4, 32, 32, 3), jnp.float32),
              jnp.zeros((1, 6), jnp.int32))
    restored = load_variables(str(run_dir), model, ModelConfig(), sample)

    direct = torch_state_dict_to_flax(
        {k: v.detach().numpy() for k, v in torch_model.state_dict().items()})
    leaf = restored["params"]["fc"]["kernel"]
    np.testing.assert_allclose(np.asarray(leaf),
                               direct["params"]["fc"]["kernel"], rtol=1e-6)
    stats = restored["batch_stats"]["conv1"]["bn"]["mean"]
    np.testing.assert_allclose(np.asarray(stats),
                               direct["batch_stats"]["conv1"]["bn"]["mean"],
                               rtol=1e-6)


def test_space_to_depth_forward_parity(tmp_path):
    """space_to_depth=True is the stem the PUBLISHED upstream checkpoint
    uses (eval_msrvtt.py:27-32) — the eval-parity path must match torch
    exactly too (reference s3dg.py:248-253, 267-271)."""
    import jax.numpy as jnp

    from milnce_tpu.models import S3D
    from milnce_tpu.utils.torch_convert import torch_state_dict_to_flax

    tmodel = _ref_model(tmp_path, seed=3, num_classes=64,
                        space_to_depth=True)

    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}
    variables = torch_state_dict_to_flax(sd)
    rng = np.random.RandomState(4)
    video = rng.rand(1, 3, 8, 32, 32).astype(np.float32)
    with torch.no_grad():
        tfeat = tmodel(torch.from_numpy(video), None, mode="video")

    jmodel = S3D(num_classes=64, vocab_size=51, word_embedding_dim=300,
                 text_hidden_dim=2048, use_space_to_depth=True)
    jfeat = jmodel.apply(variables,
                         jnp.asarray(video.transpose(0, 2, 3, 4, 1)),
                         None, mode="video")
    np.testing.assert_allclose(np.asarray(jfeat), tfeat.numpy(), atol=2e-4,
                               rtol=1e-3)


def test_mixed5c_parity(torch_model):
    import jax.numpy as jnp

    from milnce_tpu.utils.torch_convert import torch_state_dict_to_flax

    sd = {k: v.detach().numpy() for k, v in torch_model.state_dict().items()}
    variables = torch_state_dict_to_flax(sd)
    rng = np.random.RandomState(2)
    video = rng.rand(1, 3, 4, 32, 32).astype(np.float32)
    with torch.no_grad():
        tfeat = torch_model(torch.from_numpy(video), None, mode="video",
                            mixed5c=True)
    model = _flax_model()
    jfeat = model.apply(variables, jnp.asarray(video.transpose(0, 2, 3, 4, 1)),
                        None, mode="video", mixed5c=True)
    np.testing.assert_allclose(np.asarray(jfeat), tfeat.numpy(), atol=2e-4,
                               rtol=1e-3)


def test_flax_to_torch_roundtrip():
    """flax -> torch state dict -> flax must be the identity (the export
    path the reference's eval scripts consume, utils/torch_convert.py
    flax_to_torch_state_dict)."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.models import S3D
    from milnce_tpu.utils.torch_convert import (flax_to_torch_state_dict,
                                                torch_state_dict_to_flax)

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=2)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4, 32, 32, 3), jnp.float32),
                           jnp.zeros((1, 5), jnp.int32))
    variables = jax.device_get(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]})

    sd = flax_to_torch_state_dict(variables)
    # every tensor is torch-layout: conv weights (O,I,t,h,w)
    assert any(k.endswith("num_batches_tracked") for k in sd)
    back = torch_state_dict_to_flax(sd)

    flat_a = jax.tree_util.tree_flatten_with_path(variables)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(back)[0]
    assert len(flat_a) == len(flat_b)
    keys_a = {jax.tree_util.keystr(p) for p, _ in flat_a}
    keys_b = {jax.tree_util.keystr(p) for p, _ in flat_b}
    assert keys_a == keys_b, keys_a ^ keys_b
    by_key = {jax.tree_util.keystr(p): v for p, v in flat_b}
    for path, val in flat_a:
        np.testing.assert_array_equal(
            val, by_key[jax.tree_util.keystr(path)], err_msg=str(path))


def test_export_checkpoint_cli(tmp_path):
    """Orbax run dir -> torch .pth via the assets CLI export path: the
    file must be the DDP flavor the reference's eval format sniff
    expects ('state_dict' + 'module.' prefixes, eval_msrvtt.py:21-26)."""
    import jax
    import jax.numpy as jnp
    import torch

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.utils.assets import export_checkpoint

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4, 32, 32, 3), jnp.float32),
                           jnp.zeros((1, 5), jnp.int32))
    cfg = OptimConfig(warmup_steps=1)
    optimizer = build_optimizer(cfg, build_schedule(cfg, 4))
    state = create_train_state(variables, optimizer)
    run_dir = str(tmp_path / "run")
    mgr = CheckpointManager(run_dir)
    mgr.save(3, state)
    mgr.wait()

    dst = str(tmp_path / "export.pth")
    assert export_checkpoint(run_dir, dst) == 3
    raw = torch.load(dst, map_location="cpu", weights_only=False)
    assert raw["epoch"] == 3
    keys = list(raw["state_dict"])
    assert keys and all(k.startswith("module.") for k in keys)
    w = raw["state_dict"]["module.conv1.conv1.weight"]
    assert tuple(w.shape) == (64, 3, 3, 7, 7)       # torch (O,I,t,h,w)


def test_published_eval_shape_parity(tmp_path):
    """Eval-mode parity at the PUBLISHED checkpoint's exact operating
    point: 32 frames @ 224^2, space_to_depth stem, 512-d embeddings
    (eval_msrvtt.py:21-32 / eval_youcook.py).  The actual published
    S3D_HowTo100M weights are unreachable in this zero-egress
    environment (PUBLISHED_CKPT.md documents the blocker), so this pins
    the next-best oracle: the reference torch model under the published
    CONFIG at the published INPUT SHAPE, random weights, converted
    through the same path the real checkpoint would take."""
    import jax.numpy as jnp

    from milnce_tpu.models import S3D
    from milnce_tpu.utils.torch_convert import torch_state_dict_to_flax

    tmodel = _ref_model(tmp_path, seed=7, num_classes=512,
                        space_to_depth=True)

    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}
    variables = torch_state_dict_to_flax(sd)
    rng = np.random.RandomState(11)
    video = rng.rand(1, 3, 32, 224, 224).astype(np.float32)
    text_ids = rng.randint(0, 51, size=(1, 20)).astype(np.int64)
    with torch.no_grad():
        tfeat = tmodel(torch.from_numpy(video), None, mode="video")
        ttext = tmodel(None, torch.from_numpy(text_ids), mode="text")

    jmodel = S3D(num_classes=512, vocab_size=51, word_embedding_dim=300,
                 text_hidden_dim=2048, use_space_to_depth=True)
    jfeat = jmodel.apply(variables,
                         jnp.asarray(video.transpose(0, 2, 3, 4, 1)),
                         None, mode="video")
    assert jfeat.shape == (1, 512)
    np.testing.assert_allclose(np.asarray(jfeat), tfeat.numpy(), atol=5e-4,
                               rtol=1e-3)
    # text tower at the published width (20-word eval captions)
    jtext = jmodel.apply(variables, None,
                         jnp.asarray(text_ids.astype(np.int32)), mode="text")
    np.testing.assert_allclose(np.asarray(jtext), ttext.numpy(), atol=5e-4,
                               rtol=1e-3)
