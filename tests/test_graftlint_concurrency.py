"""graftlint Pass 3a gates (ISSUE 7): lock-discipline lint unit cases.

The fixture test (test_graftlint.py) pins exact per-rule counts on the
shared fixture; this file pins the rule SEMANTICS — scope heuristics,
the write-once exemption, guard-map inference and annotation, cross-
module cycle unification, the dispatch-lock exemption, and stale-
suppression detection — each on a minimal snippet, so a behavior drift
names the exact heuristic that moved.
"""

import os
import subprocess
import sys

from milnce_tpu.analysis.astlint import lint_paths, lint_source

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ids(src, **kw):
    return [f.rule.id for f in lint_source(src, **kw) if not f.suppressed]


# ---------------------------------------------------------------------------
# GL010 unguarded-shared-state
# ---------------------------------------------------------------------------

_SHARED_WRITE = """
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.calls = 0

    def record(self):
        with self._lock:
            pass
        self.calls += 1

    def snapshot(self):
        return self.calls
"""


def test_unguarded_shared_write_flagged():
    assert _ids(_SHARED_WRITE) == ["GL010"]


def test_single_root_attr_is_not_shared():
    """An attribute reachable from ONE thread root only (the
    ShardedLoader.decode_timeouts pattern: consumer-thread-private
    bookkeeping) is not shared state — no finding."""
    src = _SHARED_WRITE.replace("    def snapshot(self):\n"
                                "        return self.calls\n", "")
    assert _ids(src) == []


def test_write_once_read_exempt_and_guarded_read_flagged():
    src = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.mode = "ladder"
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def peek(self):
        return (self.mode, self.count)
"""
    findings = [f for f in lint_source(src) if not f.suppressed]
    # exactly one: the lock-free read of guarded `count`; the write-once
    # `mode` read is exempt
    assert [f.rule.id for f in findings] == ["GL010"]
    assert "count" in findings[0].message
    assert "mode" not in findings[0].message


def test_guarded_by_annotation_audits_lock_free_reads():
    """An annotated write-once attribute reads lock-free without a
    finding; the same annotation on a mutated attribute still flags
    unguarded writes."""
    ok = """
import threading

class Cfg:
    def __init__(self):
        self._lock = threading.Lock()
        self.depth = 2  # guarded-by: _lock

    def use(self):
        with self._lock:
            pass
        return self.depth
"""
    assert _ids(ok) == []
    # once mutated it is no longer write-once: the unguarded write AND
    # the now-racy lock-free read both fire
    bad = ok.replace("        return self.depth",
                     "        self.depth = 3\n        return self.depth")
    assert _ids(bad) == ["GL010", "GL010"]


def test_unknown_guarded_by_lock_is_gl000():
    src = """
import threading

class Cfg:
    def __init__(self):
        self._lock = threading.Lock()
        self.depth = 2  # guarded-by: _lok

    def use(self):
        with self._lock:
            pass
"""
    findings = lint_source(src)
    assert [f.rule.id for f in findings] == ["GL000"]
    assert "_lok" in findings[0].message


def test_method_level_guarded_by_means_caller_holds_the_lock():
    """A private helper annotated `# guarded-by:` on its def line is
    analyzed as if the lock were held throughout (the helper-relies-on-
    caller pattern)."""
    src = """
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.calls = 0

    def record(self):
        with self._lock:
            self._bump()

    def _bump(self):  # guarded-by: _lock
        self.calls += 1

    def snapshot(self):
        with self._lock:
            return self.calls
"""
    assert _ids(src) == []


def test_lockless_single_threaded_class_out_of_scope():
    """A class with no locks, no threads, no HTTP handlers mutates its
    attributes freely — Pass 3 must not police ordinary objects."""
    src = """
class Accum:
    def __init__(self):
        self.total = 0

    def add(self, x):
        self.total += x

    def value(self):
        return self.total
"""
    assert _ids(src) == []


def test_thread_target_private_method_is_a_root():
    """Thread(target=self._run) makes the private worker a thread root:
    state it shares with a public method needs a guard."""
    src = """
import threading

class Worker:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._run)

    def _run(self):
        self.count += 1

    def stats(self):
        return self.count
"""
    assert _ids(src) == ["GL010"]


# ---------------------------------------------------------------------------
# GL011 lock-order-cycle
# ---------------------------------------------------------------------------

_CYCLE = """
import threading

A = threading.Lock()
B = threading.Lock()

def one():
    with A:
        with B:
            pass

def two():
    with B:
        with A:
            pass
"""


def test_two_lock_cycle_detected():
    assert _ids(_CYCLE) == ["GL011"]


def test_consistent_order_is_clean():
    consistent = _CYCLE.replace("    with B:\n        with A:",
                                "    with A:\n        with B:")
    assert _ids(consistent) == []


def test_cycle_through_same_module_call_detected():
    """with A: helper() where helper takes B, plus the inverse order
    elsewhere — the interprocedural edge closes the cycle."""
    src = """
import threading

A = threading.Lock()
B = threading.Lock()

def helper():
    with B:
        pass

def one():
    with A:
        helper()

def two():
    with B:
        with A:
            pass
"""
    assert _ids(src) == ["GL011"]


def test_cross_module_cycle_via_imported_lock(tmp_path):
    """AB in one module, BA in another, joined by an imported
    module-level lock (the DEVICE_DISPATCH_LOCK shape) — only the
    merged graph contains the cycle."""
    a = tmp_path / "mod_a.py"
    b = tmp_path / "mod_b.py"
    a.write_text(
        "import threading\n"
        "ALPHA_LOCK = threading.Lock()\n"
        "BETA_LOCK = threading.Lock()\n"
        "def one():\n"
        "    with ALPHA_LOCK:\n"
        "        with BETA_LOCK:\n"
        "            pass\n")
    b.write_text(
        "from mod_a import ALPHA_LOCK, BETA_LOCK\n"
        "def two():\n"
        "    with BETA_LOCK:\n"
        "        with ALPHA_LOCK:\n"
        "            pass\n")
    # each module alone is clean...
    assert [f.rule.id for f in lint_paths([str(a)])] == []
    # ...the union has the cycle
    ids = [f.rule.id for f in lint_paths([str(a), str(b)])]
    assert ids == ["GL011"], ids


# ---------------------------------------------------------------------------
# GL012 blocking-under-lock
# ---------------------------------------------------------------------------

def test_future_result_under_lock_flagged():
    src = """
import threading

L = threading.Lock()

def wait(fut):
    with L:
        return fut.result()
"""
    assert _ids(src) == ["GL012"]
    # ...and the same call outside the critical section is fine
    clean = src.replace("    with L:\n        return fut.result()",
                        "    with L:\n        pass\n    return fut.result()")
    assert _ids(clean) == []


def test_str_join_under_lock_not_confused_with_thread_join():
    src = """
import threading

L = threading.Lock()

def fmt(parts, worker):
    with L:
        label = ",".join(parts)
        worker.join()
    return label
"""
    findings = [f for f in lint_source(src) if not f.suppressed]
    assert [f.rule.id for f in findings] == ["GL012"]
    assert findings[0].message.startswith(".join()")


def test_device_dispatch_exempt_only_under_dispatch_named_lock():
    dispatch = """
import threading
import jax

DEVICE_DISPATCH_LOCK = threading.Lock()

def run(fn, x, sh):
    with DEVICE_DISPATCH_LOCK:
        return jax.device_get(fn(jax.device_put(x, sh)))
"""
    assert _ids(dispatch) == []
    other = dispatch.replace("DEVICE_DISPATCH_LOCK", "STATS_LOCK")
    assert _ids(other) == ["GL012", "GL012"]  # device_put + device_get


# ---------------------------------------------------------------------------
# GL000 stale suppressions + the --no-concurrency contract
# ---------------------------------------------------------------------------

def test_stale_suppression_is_gl000():
    findings = lint_source("y = 1  # graftlint: disable=GL004(was real once)\n")
    assert [f.rule.id for f in findings] == ["GL000"]
    assert "stale" in findings[0].message


def test_matching_suppression_is_not_stale():
    src = ("import jax.numpy as jnp\n"
           "pad = jnp.asarray(0.5)  # graftlint: disable=GL004(audited)\n")
    findings = lint_source(src)
    assert [f.rule.id for f in findings] == ["GL004"]
    assert findings[0].suppressed


def test_pass3_suppressions_not_stale_under_no_concurrency():
    """With the concurrency pass off, a GL010 suppression is
    unevaluated, not stale — staleness only judges rules that ran."""
    src = _SHARED_WRITE.replace(
        "        self.calls += 1",
        "        self.calls += 1  # graftlint: disable=GL010(audited)")
    with_pass = lint_source(src)
    assert [f.rule.id for f in with_pass] == ["GL010"]
    assert with_pass[0].suppressed
    without = lint_source(src, concurrency=False)
    assert without == []


def test_gl011_suppression_never_judged_stale_under_narrowed_scope():
    """A cross-module cycle's audited GL011 suppression must survive a
    narrowed-scope lint (the partner module's edge isn't in scope, so
    absence-of-cycle is not evidence of staleness)."""
    src = """
import threading

A = threading.Lock()

def one():
    # graftlint: disable=GL011(cycle partner lives in another module)
    with A:
        pass
"""
    assert [f.rule.id for f in lint_source(src)] == []


def test_cli_no_concurrency_skips_gl010(tmp_path):
    bad = tmp_path / "racy.py"
    bad.write_text(_SHARED_WRITE)
    cli = [sys.executable, os.path.join(_REPO, "scripts", "graft_lint.py"),
           "--check", "--no-trace", "--report", "", str(bad)]
    proc = subprocess.run(cli, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1 and "GL010" in proc.stdout, proc.stdout
    proc = subprocess.run(cli + ["--no-concurrency"], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# guard-map CLI (the SERVING.md "Threading model" source)
# ---------------------------------------------------------------------------

def test_guard_map_markdown_covers_the_serving_mesh():
    from milnce_tpu.analysis.concurrency import guard_map_markdown

    table = guard_map_markdown([os.path.join(_REPO, "milnce_tpu",
                                             "serving"),
                                os.path.join(_REPO, "milnce_tpu", "obs")])
    # the inferred guard map names the classes and disciplines the
    # threading-model doc is generated from
    assert "`engine.InferenceEngine`" in table
    assert "`batcher.DynamicBatcher`" in table
    assert "`_calls`" in table
    assert "guarded by `InferenceEngine._stats_lock`" in table
    assert "write-once in `__init__`" in table
