"""Dynamic micro-batcher edge semantics (ISSUE 4 satellite): flush on
size and on delay, pad/unpad identity, bucket selection at boundaries,
deadline-expired -> error (never a silent drop), batch-failure
propagation.  jax-free by construction — the batcher is numpy-only and
these tests pin that boundary too (a fake run_batch stands in for the
engine)."""

import threading
import time

import numpy as np
import pytest

from milnce_tpu.serving.batcher import DeadlineExpired, DynamicBatcher

_BUCKETS = (4, 8)


def _bucket_for(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    raise ValueError(n)


class _FakeEngine:
    """Records every padded batch; result row = payload * 2 (so per-row
    identity is checkable through pad/unpad)."""

    def __init__(self, fail=False, delay_s=0.0):
        self.batches: list[np.ndarray] = []
        self.fail = fail
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def __call__(self, rows: np.ndarray) -> np.ndarray:
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise ValueError("injected batch failure")
        with self._lock:
            self.batches.append(np.array(rows, copy=True))
        return rows * 2.0


def _mk(engine, **kw):
    kw.setdefault("max_batch", _BUCKETS[-1])
    return DynamicBatcher(engine, _bucket_for, **kw)


def _rows(n, w=3):
    return [np.full((w,), float(i), np.float32) for i in range(n)]


def test_flush_on_max_batch_does_not_wait_for_delay():
    eng = _FakeEngine()
    b = _mk(eng, max_batch=4, max_delay_ms=10_000)   # delay flush never fires
    t0 = time.monotonic()
    futs = [b.submit(r) for r in _rows(4)]
    out = [f.result(timeout=5) for f in futs]
    assert time.monotonic() - t0 < 5.0               # well under the 10s delay
    assert len(eng.batches) == 1 and eng.batches[0].shape == (4, 3)
    for i, row in enumerate(out):
        assert np.array_equal(row, np.full((3,), 2.0 * i))
    occ = b.stats()["occupancy"]["4"]
    assert occ == {"flushes": 1, "rows": 4, "mean_fill": 1.0}
    b.close()


def test_flush_on_delay_serves_a_lone_request():
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=40)
    t0 = time.monotonic()
    row = b.submit(np.ones((3,), np.float32)).result(timeout=5)
    waited = time.monotonic() - t0
    assert np.array_equal(row, np.full((3,), 2.0))
    assert waited >= 0.03                 # did wait for company...
    assert eng.batches[0].shape == (4, 3)  # ...then padded to the floor bucket
    b.close()


def test_pad_unpad_identity_matches_per_sample_results():
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=30)
    futs = [b.submit(r) for r in _rows(3)]
    batched = np.stack([f.result(timeout=5) for f in futs])
    assert np.array_equal(batched, np.stack(_rows(3)) * 2.0)
    # the engine really saw ONE padded bucket, zeros in the pad slots
    (batch,) = eng.batches
    assert batch.shape == (4, 3)
    assert np.array_equal(batch[3], np.zeros((3,)))
    b.close()


@pytest.mark.parametrize("n,bucket", [(1, 4), (4, 4), (5, 8), (8, 8)])
def test_bucket_selection_at_boundaries(n, bucket):
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=150)        # plenty to collect all n submits
    futs = [b.submit(r) for r in _rows(n)]
    for f in futs:
        f.result(timeout=5)
    assert len(eng.batches) == 1, "expected one flush for the burst"
    assert eng.batches[0].shape == (bucket, 3)
    b.close()


def test_expired_deadline_is_an_error_not_a_silent_drop():
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=10_000)     # only the deadline can end the wait
    fut = b.submit(np.ones((3,), np.float32), timeout_ms=40)
    with pytest.raises(DeadlineExpired):
        fut.result(timeout=5)             # resolves promptly, NOT after 10s
    assert b.stats()["deadline_expired"] == 1
    assert eng.batches == []              # never reached the engine
    b.close()


def test_live_requests_survive_a_neighbors_expiry():
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=10_000)
    doomed = b.submit(np.zeros((3,), np.float32), timeout_ms=40)
    alive = b.submit(np.ones((3,), np.float32))     # no deadline
    with pytest.raises(DeadlineExpired):
        doomed.result(timeout=5)
    assert np.array_equal(alive.result(timeout=5), np.full((3,), 2.0))
    b.close()


def test_mixed_shape_batch_fails_the_batch_not_the_worker():
    """A malformed payload mix (np.stack of unequal row shapes raises
    BEFORE run_batch) must fail that batch's futures and leave the
    worker alive — a dead worker would strand every later request."""
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=60)
    f1 = b.submit(np.ones((3,), np.float32))
    f2 = b.submit(np.ones((4,), np.float32))      # width mismatch
    for f in (f1, f2):
        with pytest.raises(ValueError):
            f.result(timeout=5)
    assert b.stats()["batch_errors"] == 1
    # the worker survived: a well-formed request still gets served
    ok = b.submit(np.ones((3,), np.float32)).result(timeout=5)
    assert np.array_equal(ok, np.full((3,), 2.0))
    b.close()


def test_batch_failure_propagates_to_every_caller():
    b = _mk(_FakeEngine(fail=True), max_delay_ms=20)
    futs = [b.submit(r) for r in _rows(2)]
    for f in futs:
        with pytest.raises(ValueError, match="injected batch failure"):
            f.result(timeout=5)
    assert b.stats()["batch_errors"] == 1
    b.close()


def test_default_timeout_applies_when_submit_passes_none():
    b = _mk(_FakeEngine(), max_delay_ms=10_000, default_timeout_ms=40)
    with pytest.raises(DeadlineExpired):
        b.submit(np.ones((3,), np.float32)).result(timeout=5)
    b.close()


def test_explicit_zero_timeout_disables_the_default_deadline():
    # default deadline (20ms) < delay flush (60ms): a request that kept
    # the default would expire; timeout_ms=0 opts out and gets served
    b = _mk(_FakeEngine(), max_delay_ms=60, default_timeout_ms=20)
    fut = b.submit(np.ones((3,), np.float32), timeout_ms=0)
    assert np.array_equal(fut.result(timeout=5), np.full((3,), 2.0))
    b.close()


def test_submit_after_close_raises():
    b = _mk(_FakeEngine())
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(np.ones((3,), np.float32))


def test_stats_shape():
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=20)
    b.submit(np.ones((3,), np.float32)).result(timeout=5)
    s = b.stats()
    assert s["requests"] == 1 and s["flushes"] == 1
    assert s["deadline_expired"] == 0 and s["batch_errors"] == 0
    assert s["occupancy"]["4"]["mean_fill"] == pytest.approx(0.25)
    b.close()

def test_stats_readers_race_flushes_with_exact_final_occupancy():
    """ISSUE 7 regression: the worker's per-bucket children lookup ran
    OUTSIDE the children lock while stats() iterated under it
    (graftlint GL010) — hammer stats() from readers during a stream of
    flushes; final occupancy totals must be exact."""
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=1)
    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                s = b.stats()
                # mid-race sanity only: counters are monotonic and the
                # occupancy dict never tears (exactness is pinned on
                # the quiesced state below; the flushes/rows PAIR is
                # deliberately not atomic across two counters)
                assert s["requests"] >= s["flushes"] >= 0
                for occ in s["occupancy"].values():
                    assert occ["rows"] >= 0 and occ["flushes"] >= 0
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in readers:
        t.start()
    n = 40
    futs = [b.submit(np.full((3,), float(i), np.float32)) for i in range(n)]
    for f in futs:
        f.result(timeout=10)
    stop.set()
    for t in readers:
        t.join(timeout=10)
    b.close()
    assert not errors, errors
    s = b.stats()
    assert s["requests"] == n
    assert sum(occ["rows"] for occ in s["occupancy"].values()) == n
    assert sum(occ["flushes"]
               for occ in s["occupancy"].values()) == s["flushes"]


def test_continuous_lone_request_skips_the_delay_wait():
    """Continuous batching (ISSUE 14): a lone request flushes the
    moment the lane is free — it never pays max_delay_ms waiting for
    company that isn't coming (the flush-and-wait path's cost)."""
    eng = _FakeEngine()
    b = _mk(eng, max_delay_ms=10_000, continuous=True)
    t0 = time.monotonic()
    row = b.submit(np.ones((3,), np.float32)).result(timeout=5)
    waited = time.monotonic() - t0
    assert np.array_equal(row, np.full((3,), 2.0))
    assert waited < 2.0, f"continuous mode waited {waited:.3f}s"
    b.close()


def test_continuous_accumulates_into_bucket_slots_while_lane_busy():
    """While the single lane executes, arrivals accumulate into the
    forming batch — occupancy rises exactly when the device is the
    bottleneck (the slot-reuse win over flush-and-wait)."""
    eng = _FakeEngine(delay_s=0.15)
    b = _mk(eng, continuous=True)                    # max_batch 8
    futs = [b.submit(np.full((3,), 0.0, np.float32))]
    time.sleep(0.03)                 # first flush (1 row) is in flight
    futs += [b.submit(np.full((3,), float(i), np.float32))
             for i in range(1, 7)]
    for f in futs:
        f.result(timeout=5)
    b.close()
    sizes = [batch.shape[0] for batch in eng.batches]
    assert sizes == [4, 8], (
        f"expected the 6 lane-busy arrivals to coalesce: {sizes}")


def test_continuous_deadline_expires_promptly_while_lane_busy():
    """Pipelined continuous mode: a request aging out while the worker
    is PARKED on a busy lane fails with DeadlineExpired at the
    lane-wait tick — it never waits for the lane to free first."""
    from concurrent.futures import Future

    slow: list[Future] = []

    def run_async(rows):
        fut: Future = Future()
        slow.append(fut)
        return fut                        # resolved manually, late

    b = _mk(_FakeEngine(), continuous=True, lanes=1,
            run_batch_async=run_async)
    blocker = b.submit(np.ones((3,), np.float32))     # occupies the lane
    deadline = time.monotonic() + 5.0
    while not slow and time.monotonic() < deadline:
        time.sleep(0.005)
    assert slow, "the blocker batch never dispatched"
    doomed = b.submit(np.zeros((3,), np.float32), timeout_ms=60)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExpired):
        doomed.result(timeout=5)
    waited = time.monotonic() - t0
    assert waited < 0.4, (f"expiry took {waited:.3f}s — waited for the "
                          "lane instead of the deadline")
    slow[0].set_result(np.ones((4, 3), np.float32) * 2.0)
    assert np.array_equal(blocker.result(timeout=5), np.full((3,), 2.0))
    assert b.stats()["deadline_expired"] == 1
    b.close()


def test_continuous_async_lanes_bound_inflight_batches():
    """Pipelined continuous mode: at most ``lanes`` batches are ever in
    flight at once (the semaphore), and every batch still resolves."""
    from concurrent.futures import Future

    inflight = {"now": 0, "max": 0}
    lock = threading.Lock()
    pending: list[tuple] = []

    def run_async(rows):
        fut: Future = Future()
        with lock:
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
            pending.append((fut, np.array(rows, copy=True)))
        return fut

    def resolver():
        while not stop.is_set():
            with lock:
                item = pending.pop(0) if pending else None
            if item is None:
                time.sleep(0.005)
                continue
            time.sleep(0.05)                  # the "dispatch"
            fut, rows = item
            with lock:
                inflight["now"] -= 1
            fut.set_result(rows * 2.0)

    stop = threading.Event()
    t = threading.Thread(target=resolver, daemon=True)
    t.start()
    b = _mk(_FakeEngine(), continuous=True, lanes=2,
            run_batch_async=run_async)
    try:
        futs = []
        for burst in range(6):                # 6 bursts of 2 rows
            futs += [b.submit(np.full((3,), float(burst), np.float32))
                     for _ in range(2)]
            time.sleep(0.02)
        out = [f.result(timeout=10) for f in futs]
        assert all(o.shape == (3,) for o in out)
        assert inflight["max"] <= 2, (
            f"{inflight['max']} batches in flight > 2 lanes")
        assert inflight["max"] >= 2, "lanes never actually pipelined"
    finally:
        stop.set()
        b.close()
        t.join(timeout=5)


def test_injected_recorder_receives_flush_spans():
    # an owner that isolates its span stream (recorder=...) must get the
    # flush spans there — not on the process-default recorder, which a
    # co-resident train run can swap out via spans.install()
    from milnce_tpu.obs.spans import SpanRecorder

    rec = SpanRecorder()
    b = _mk(_FakeEngine(), max_delay_ms=20, recorder=rec)
    b.submit(np.ones((3,), np.float32)).result(timeout=5)
    b.close()
    spans = [r for r in rec.tail() if r.get("name") == "batcher.flush"]
    assert len(spans) == 1 and spans[0]["rows"] == 1


def test_on_flush_observer_sees_duration_and_rows():
    """ISSUE 9: the flush-latency observer (the service's EWMA spike
    detector feed) fires once per successful flush with (dur_ms, rows)
    — and never for a failed batch."""
    seen = []
    eng = _FakeEngine(delay_s=0.02)
    b = _mk(eng, max_delay_ms=10,
            on_flush=lambda dur_ms, rows: seen.append((dur_ms, rows)))
    futs = [b.submit(r) for r in _rows(3)]
    for f in futs:
        f.result(timeout=5)
    b.close()
    assert len(seen) == 1
    dur_ms, rows = seen[0]
    assert rows == 3 and dur_ms >= 20.0 - 1.0   # the engine's delay

    seen.clear()
    bad = _mk(_FakeEngine(fail=True), max_delay_ms=10,
              on_flush=lambda dur_ms, rows: seen.append((dur_ms, rows)))
    fut = bad.submit(np.ones((3,), np.float32))
    with pytest.raises(ValueError, match="injected"):
        fut.result(timeout=5)
    bad.close()
    assert seen == []
