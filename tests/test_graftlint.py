"""graftlint Pass 1 gates: exact fixture counts, suppression syntax, and
the repo-wide clean bill.

The repo-clean test is the actual CI gate the tentpole exists for: a new
hot-path pothole (host sync in the loop, f64 drift, undonated train-step
jit, ...) lands as a FAILING tier-1 test, not as a TPU-session surprise
weeks later.  The fixture tests pin the linter itself — rules that
silently stop firing are worse than no rules.
"""

import os
import subprocess
import sys
from collections import Counter

import pytest

from milnce_tpu.analysis.astlint import lint_paths, lint_source
from milnce_tpu.analysis.rules import RULES, RULES_BY_NAME

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "graftlint_fixture.py")


def _fixture_findings():
    with open(_FIXTURE) as fh:
        return lint_source(fh.read(), _FIXTURE)


def test_fixture_violates_every_rule_exactly_once():
    active = Counter(f.rule.id for f in _fixture_findings()
                     if not f.suppressed)
    assert active == {
        # missing reason + unknown rule + stale + entry-level (GL013)
        # + entry-level numerics (GL018)
        "GL000": 5,
        "GL001": 1, "GL002": 1, "GL003": 1,
        "GL004": 1, "GL005": 1, "GL006": 1, "GL007": 1, "GL008": 1,
        "GL009": 1, "GL010": 1, "GL011": 1, "GL012": 1,
    }, f"per-rule finding counts drifted: {dict(active)}"


def test_fixture_suppresses_every_rule_exactly_once():
    suppressed = [f for f in _fixture_findings() if f.suppressed]
    counts = Counter(f.rule.id for f in suppressed)
    assert counts == {"GL001": 1, "GL002": 1, "GL003": 1,
                      "GL004": 1, "GL005": 1, "GL006": 1, "GL007": 1,
                      "GL008": 1, "GL009": 1, "GL010": 1, "GL011": 1,
                      "GL012": 1}, (
        f"suppressed counts drifted: {dict(counts)}")
    assert all(f.suppress_reason for f in suppressed), (
        "suppressed findings must carry their audit reason")


def test_suppression_without_reason_is_gl000():
    findings = lint_source("y = 1  # graftlint: disable=GL004\n")
    assert [f.rule.id for f in findings] == ["GL000"]
    assert "no reason" in findings[0].message


def test_unknown_rule_in_suppression_is_gl000():
    findings = lint_source("y = 1  # graftlint: disable=GL123(whatever)\n")
    assert [f.rule.id for f in findings] == ["GL000"]


def test_suppression_accepts_rule_names():
    src = ("import jax.numpy as jnp\n"
           "pad = jnp.asarray(0.5)  "
           "# graftlint: disable=f64-literal-drift(name-addressed)\n")
    (finding,) = lint_source(src)
    assert finding.rule.id == "GL004" and finding.suppressed
    assert finding.suppress_reason == "name-addressed"


def test_standalone_suppression_covers_next_line():
    src = ("import jax.numpy as jnp\n"
           "# graftlint: disable=GL004(own-line comment form)\n"
           "pad = jnp.asarray(0.5)\n")
    (finding,) = lint_source(src)
    assert finding.suppressed


def test_docstrings_mentioning_the_syntax_do_not_parse_as_suppressions():
    src = '"""Docs: write # graftlint: disable=GL001(reason) inline."""\n'
    assert lint_source(src) == []


def test_rule_registry_is_consistent():
    assert set(RULES) == {"GL000", "GL001", "GL002", "GL003", "GL004",
                          "GL005", "GL006", "GL007", "GL008", "GL009",
                          "GL010", "GL011", "GL012", "GL013", "GL014",
                          "GL015", "GL016", "GL017", "GL018"}
    assert len(RULES_BY_NAME) == len(RULES), "duplicate rule names"
    for rule in RULES.values():
        assert rule.summary and rule.rationale and rule.fix


def test_entry_level_rule_suppression_is_gl000():
    """GL013-GL015 (Pass 4) and GL016/GL018 (Pass 5) attach to
    registered trace entries, never source lines — an inline
    suppression can't match anything, so writing one is itself a GL000
    with the re-pin route named (the stale-suppression audit extended
    to the rules that cannot fire here).  GL017 is the exception: its
    AST half fires on source lines in losses/, so it stays inline-
    suppressible."""
    for rule_id in ("GL013", "GL014", "GL015", "GL016", "GL018"):
        findings = lint_source(
            f"y = 1  # graftlint: disable={rule_id}(some reason)\n")
        assert [f.rule.id for f in findings] == ["GL000"], rule_id
        assert "memplan" in findings[0].message
    # by name too
    (f,) = lint_source("y = 1  # graftlint: disable="
                       "peak-budget-regression(reason)\n")
    assert f.rule.id == "GL000" and "memplan" in f.message
    # GL017 IS inline-suppressible where it fires (a losses/ module)
    src = ("import jax.numpy as jnp\n"
           "def f(s):\n"
           "    return jnp.exp(s)  "
           "# graftlint: disable=GL017(domain bounded by construction)\n")
    (f,) = lint_source(src, "milnce_tpu/losses/fake.py")
    assert f.rule.id == "GL017" and f.suppressed


def test_duplicate_nested_names_are_all_linted():
    """Two factories each defining `def local(x)` (the train/step.py
    pattern): EVERY same-named def must be linted, not just the first
    (code-review r7 finding — the second body shipped unchecked)."""
    src = (
        "import jax\n"
        "def make_a():\n"
        "    def local(x):\n"
        "        return x\n"
        "    return jax.jit(local)\n"
        "def make_b():\n"
        "    def local(x):\n"
        "        if x > 0:\n"
        "            print('hot', x)\n"
        "        return x\n"
        "    return jax.jit(local)\n")
    ids = [f.rule.id for f in lint_source(src)]
    assert "GL002" in ids and "GL006" in ids, ids


def test_method_form_block_until_ready_flagged_in_hot_loop():
    """x.block_until_ready() per step is the same stall as the function
    form and must not slip past GL001 (code-review r7 finding)."""
    src = (
        "import jax\n"
        "def run(loader, mesh, step_fn, state):\n"
        "    from milnce_tpu.data.pipeline import device_prefetch\n"
        "    for batch in device_prefetch(loader, mesh, 'data'):\n"
        "        state, loss = step_fn(state, batch)\n"
        "        loss.block_until_ready()\n"
        "    return state\n")
    assert any(f.rule.id == "GL001" and "block_until_ready" in f.message
               for f in lint_source(src))


def test_phantom_mesh_axis_detector():
    """GL009 (ISSUE 6): a typo'd PartitionSpec axis inside
    with_sharding_constraint traces fine and silently replicates — the
    lint must flag axes no mesh declares, accept the canonical
    data/model axes, and accept axes a Mesh() in the same module
    declares."""
    bad = ("import jax\nfrom jax.sharding import PartitionSpec as P\n"
           "def f(x):\n"
           "    return jax.lax.with_sharding_constraint(x, P('modle'))\n")
    assert [f.rule.id for f in lint_source(bad)] == ["GL009"]
    ok = ("import jax\nfrom jax.sharding import PartitionSpec as P\n"
          "def f(x):\n"
          "    return jax.lax.with_sharding_constraint(x, "
          "P('data', 'model'))\n")
    assert lint_source(ok) == []
    # an exotic axis is fine once a Mesh in the module declares it
    exotic = ("import jax\n"
              "from jax.sharding import Mesh, PartitionSpec as P\n"
              "mesh = Mesh(devs, ('expert', 'data'))\n"
              "def f(x):\n"
              "    return jax.lax.with_sharding_constraint(x, P('expert'))\n")
    assert lint_source(exotic) == []


def test_repo_hot_path_lints_clean():
    """The merge gate: every finding in the package is either fixed or
    carries a reasoned inline suppression (the audited exceptions)."""
    findings = lint_paths([os.path.join(_REPO, "milnce_tpu")])
    active = [f.format() for f in findings if not f.suppressed]
    assert not active, (
        "new graftlint findings — fix them or add a reasoned "
        "# graftlint: disable=RULE(reason):\n" + "\n".join(active))
    # the audited exceptions exist and all carry reasons
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "expected the documented audited exceptions"
    assert all(f.suppress_reason for f in suppressed)


def test_lint_paths_rejects_scope_matching_no_files(tmp_path):
    """A typo'd scope must fail loudly, not pass the gate vacuously."""
    with pytest.raises(FileNotFoundError, match="matches no Python"):
        lint_paths([str(tmp_path / "no_such_dir")])


def test_cli_check_exits_zero_on_clean_repo():
    """`scripts/graft_lint.py --check` is the CI/tooling entry (AST pass;
    the trace pass is gated in-process by test_trace_invariants.py)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "graft_lint.py"),
         "--check", "--no-trace", "--report", ""],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_check_exits_nonzero_on_findings(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text("import jax.numpy as jnp\npad = jnp.asarray(0.5)\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "graft_lint.py"),
         "--check", "--no-trace", "--report", "", str(bad)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "GL004" in proc.stdout
