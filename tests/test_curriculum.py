"""Curriculum training (ISSUE 16): staged (frames, resolution, batch)
schedule with pre-flighted re-trace and checkpoint-compatible
transitions.

Covers the four layers the tentpole touches:

- the ``train.curriculum`` grammar and the step-level plan simulator
  (train/curriculum.py), including the pinned equivalence of the flat
  plan to the historical ``resume_batch_offset`` / ``stop_save_label``
  modulo helpers and the satellite-4 schedule-total audit;
- the goodput ledger's ``stage_switch`` attribution (obs/goodput.py);
- the mem_plan pre-flight refusing an over-budget stage BEFORE any
  stage traces;
- the two-stage tiny-CPU acceptance run: loss-trajectory continuity,
  ledger summing to measured wall within 5% with a nonzero
  ``stage_switch`` bucket, the stage stamp, and the three
  checkpoint/resume scenarios (mid-stage, boundary, schedule removed).

Pinned tier-1 (never @slow) by tests/test_suite_hygiene.py.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset
from milnce_tpu.train import curriculum as curr
from milnce_tpu.train.curriculum import CurriculumStage

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the acceptance schedule: 4f until global step 3, then 8f to the end.
# Shapes deliberately reuse the rig's cached 4f@32 batch-8 program and
# add exactly ONE new shape (8f@32) — tier-1 compile budget.
TWO_STAGE = ("num_frames=4,resolution=32,until_step=3;"
             "num_frames=8,resolution=32")


def _tiny_cfg(tmp_path, samples=48, epochs=1):
    cfg = tiny_preset()
    cfg.model.inception_blocks = 1      # 1-block S3D: tier-1 compile time
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = samples
    cfg.data.num_reader_threads = 2
    cfg.optim.epochs = epochs
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")
    cfg.train.log_root = str(tmp_path / "log")
    return cfg


def _read_events(cfg):
    path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")
    assert os.path.exists(path)
    return [json.loads(line) for line in open(path)]


# --------------------------------------------------------------------------
# grammar
# --------------------------------------------------------------------------

class TestParseCurriculum:
    def test_empty_spec_is_flat(self):
        assert curr.parse_curriculum("") == []

    def test_inline_grammar_with_inherited_batch(self):
        stages = curr.parse_curriculum(TWO_STAGE, default_batch_size=8)
        assert [s.num_frames for s in stages] == [4, 8]
        assert [s.resolution for s in stages] == [32, 32]
        assert [s.batch_size for s in stages] == [8, 8]
        assert stages[0].until_step == 3 and stages[1].until_step is None
        assert stages[0].label() == "4f@32 batch 8"

    def test_json_artifact_path(self, tmp_path):
        art = tmp_path / "sched.json"
        art.write_text(json.dumps({"curriculum": [
            {"num_frames": 4, "resolution": 64, "batch_size": 16,
             "until_epoch": 1},
            {"num_frames": 8, "resolution": 112, "batch_size": 8},
        ]}))
        stages = curr.parse_curriculum(str(art))
        assert stages[0].until_epoch == 1
        assert stages[1].batch_size == 8

    @pytest.mark.parametrize("bad,match", [
        ("num_frames=4,fps=2;num_frames=8,resolution=32",
         "unknown key"),                          # unknown key
        ("num_frames=x,resolution=32", "not an integer"),
        ("num_frames=0,resolution=32", "must be > 0"),
        ("num_frames=4,resolution=32,until_step=2,until_epoch=1;"
         "num_frames=8,resolution=32", "BOTH"),   # both bounds
        ("num_frames=4,resolution=32,until_step=2", "open-ended"),
        ("num_frames=4,resolution=32;num_frames=8,resolution=32",
         "needs until_step or until_epoch"),      # unbounded middle
        ("num_frames=4", "resolution"),           # missing required
        ("/no/such/artifact.json", "no such file"),
    ])
    def test_malformed_specs_fail_loudly(self, bad, match):
        with pytest.raises(ValueError, match=match):
            curr.parse_curriculum(bad, default_batch_size=8)

    def test_missing_batch_without_default_fails(self):
        with pytest.raises(ValueError, match="no batch_size"):
            curr.parse_curriculum("num_frames=4,resolution=32")


# --------------------------------------------------------------------------
# plan simulator
# --------------------------------------------------------------------------

class TestPlanCurriculum:
    def test_flat_plan_matches_modulo_helpers(self):
        """The flat run is a single-stage plan through the SAME
        machinery; its locate()/epoch math must equal the historical
        resume_batch_offset / stop_save_label helpers exactly."""
        from milnce_tpu.train.loop import (resume_batch_offset,
                                           stop_save_label,
                                           stop_save_label_planned)

        plan = curr.plan_curriculum(
            [CurriculumStage(num_frames=4, resolution=32, batch_size=8)],
            num_samples=48, epochs=2)       # spe 6, total 12
        assert plan.total_steps == 12
        for step in range(12):
            seg, off = plan.locate(step)
            assert seg.skip_batches + off == resume_batch_offset(step, 6)
        for epoch, opt_step in [(0, 2), (0, 6), (1, 8), (1, 12)]:
            assert (stop_save_label_planned(epoch, opt_step, plan)
                    == stop_save_label(epoch, opt_step, 6))

    def test_mid_epoch_switch_segments(self):
        stages = curr.parse_curriculum(TWO_STAGE, default_batch_size=8)
        plan = curr.plan_curriculum(stages, num_samples=48, epochs=1)
        assert plan.total_steps == 6
        segs = plan.segments
        assert [(s.stage, s.epoch, s.skip_batches, s.start_step, s.n_steps)
                for s in segs] == [(0, 0, 0, 0, 3), (1, 0, 3, 3, 3)]
        assert plan.stage_at(2) == 0 and plan.stage_at(3) == 1
        seg, off = plan.locate(4)
        assert seg.stage == 1 and off == 1
        # a finished run resumes to a no-op at the end of the last seg
        seg, off = plan.locate(plan.total_steps)
        assert seg is segs[-1] and off == seg.n_steps

    def test_batch_change_reskips_consumed_samples(self):
        # stage 0 consumes 3*4=12 samples; stage 1 at batch 8 must skip
        # ceil(12/8)=2 batches so no sample trains twice in the epoch
        stages = [
            CurriculumStage(num_frames=4, resolution=32, batch_size=4,
                            until_step=3),
            CurriculumStage(num_frames=4, resolution=32, batch_size=8)]
        plan = curr.plan_curriculum(stages, num_samples=48, epochs=1)
        seg1 = plan.segments[1]
        assert seg1.skip_batches == 2
        assert seg1.n_steps == 48 // 8 - 2
        assert plan.total_steps == 3 + 4

    def test_until_epoch_switches_at_epoch_entry(self):
        stages = [
            CurriculumStage(num_frames=4, resolution=32, batch_size=8,
                            until_epoch=1),
            CurriculumStage(num_frames=8, resolution=32, batch_size=8)]
        plan = curr.plan_curriculum(stages, num_samples=48, epochs=2)
        assert [(s.stage, s.epoch) for s in plan.segments] == [(0, 0),
                                                               (1, 1)]
        assert plan.epoch_start_step(1) == 6 and plan.total_steps == 12

    def test_unreachable_stage_refused(self):
        stages = [
            CurriculumStage(num_frames=4, resolution=32, batch_size=8,
                            until_step=100),
            CurriculumStage(num_frames=8, resolution=32, batch_size=8)]
        with pytest.raises(ValueError, match="unreachable"):
            curr.plan_curriculum(stages, num_samples=48, epochs=1)

    def test_oversized_stage_batch_refused(self):
        with pytest.raises(ValueError, match="exceeds the dataset"):
            curr.plan_curriculum(
                [CurriculumStage(num_frames=4, resolution=32,
                                 batch_size=64)],
                num_samples=48, epochs=1)

    def test_schedule_totals_follow_the_plan_not_flat_spe(self):
        """Satellite 4: warmup/cosine totals must come from the plan's
        simulated step count.  With per-stage batch sizes the naive
        ``steps_per_epoch(flat) * epochs`` is simply wrong — pin both
        the divergence and the flat-case equivalence."""
        from milnce_tpu.config import OptimConfig
        from milnce_tpu.train.schedule import (build_host_schedule,
                                               build_host_schedule_total)

        mixed = curr.plan_curriculum(
            [CurriculumStage(num_frames=4, resolution=32, batch_size=4,
                             until_step=3),
             CurriculumStage(num_frames=4, resolution=32, batch_size=8)],
            num_samples=48, epochs=1)
        assert mixed.total_steps == 7       # != 48//8 and != 48//4
        assert mixed.total_steps != 48 // 8 * 1

        ocfg = OptimConfig()
        ocfg.epochs = 2
        flat = curr.plan_curriculum(
            [CurriculumStage(num_frames=4, resolution=32, batch_size=8)],
            num_samples=48, epochs=2)
        by_total = build_host_schedule_total(ocfg, flat.total_steps)
        by_spe = build_host_schedule(ocfg, 6)
        for step in range(flat.total_steps + 1):
            assert by_total(step) == pytest.approx(by_spe(step), rel=1e-12)


# --------------------------------------------------------------------------
# goodput: stage_switch attribution (pure ledger unit)
# --------------------------------------------------------------------------

def test_ledger_attributes_stage_switch_and_retrace():
    """The stage.switch span AND the first step dispatched after it (the
    new stage's trace+compile) land in ``stage_switch``, excluded from
    the compute pool — curriculum overhead is measured, not guessed."""
    from milnce_tpu.obs.goodput import compute_ledger

    recs = [
        {"kind": "event", "name": "run.start", "ts": 0.0},
        {"kind": "span", "name": "step", "ts": 1.0, "dur_ms": 2000.0},
        {"kind": "span", "name": "step", "ts": 3.0, "dur_ms": 500.0},
        {"kind": "span", "name": "stage.switch", "ts": 3.6,
         "dur_ms": 400.0},
        {"kind": "span", "name": "step", "ts": 4.0, "dur_ms": 1500.0},
        {"kind": "span", "name": "step", "ts": 5.5, "dur_ms": 500.0},
        {"kind": "event", "name": "run.end", "ts": 7.0},
    ]
    led = compute_ledger(recs)
    assert led.stage_switches == 1
    assert led.categories["compile"] == pytest.approx(2.0)
    assert led.categories["stage_switch"] == pytest.approx(0.4 + 1.5)
    assert led.categories["compute"] == pytest.approx(1.0)
    assert led.to_extra()["stage_switches"] == 1
    assert sum(led.categories.values()) == pytest.approx(led.wall_s)


# --------------------------------------------------------------------------
# pre-flight
# --------------------------------------------------------------------------

def test_hbm_budget_env_wins(monkeypatch):
    monkeypatch.setenv("MILNCE_HBM_GIB", "2.0")
    assert curr.hbm_budget_bytes() == 2 * 2 ** 30


def test_preflight_refuses_over_budget_stage_before_trace(tmp_path,
                                                          monkeypatch):
    """An impossible per-chip budget must refuse the run AT STARTUP with
    the stage named — before any stage compiles (the refusal arrives in
    well under a compile's time because the plan traces abstractly)."""
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path)
    cfg.train.curriculum = TWO_STAGE
    monkeypatch.setenv("MILNCE_HBM_GIB", "0.0001")
    with pytest.raises(ValueError) as exc_info:
        run_training(cfg, max_steps=6)
    msg = str(exc_info.value)
    assert "curriculum pre-flight refused" in msg
    assert "curriculum stage 0 (4f@32 batch 8)" in msg
    assert "EXCEEDS" in msg
    # top contributors are named so the refusal is actionable
    assert "top contributors" in msg


# --------------------------------------------------------------------------
# acceptance: the two-stage tiny-CPU run (ISSUE 16 acceptance criteria)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def curriculum_run(tmp_path_factory):
    """ONE two-stage run (4f@32 -> 8f@32, switch at step 3) shared by
    the acceptance pins below — training runs are the expensive part of
    this file, so the ledger/stamp/events assertions share it."""
    from milnce_tpu.train.loop import run_training

    tmp = tmp_path_factory.mktemp("curr_accept")
    cfg = _tiny_cfg(tmp)
    cfg.train.curriculum = TWO_STAGE
    cfg.train.run_id = "curr-accept"
    t0 = time.monotonic()
    res = run_training(cfg, max_steps=6)
    return {"cfg": cfg, "res": res, "wall": time.monotonic() - t0}


def test_two_stage_run_finishes_in_final_stage(curriculum_run):
    res = curriculum_run["res"]
    assert res.steps == 6
    assert res.stage == 1
    assert np.isfinite(res.last_loss)


def test_stage_switch_events_and_plan_event(curriculum_run):
    events = _read_events(curriculum_run["cfg"])
    plans = [e for e in events if e.get("name") == "curriculum.plan"]
    assert len(plans) == 1
    assert plans[0]["total_steps"] == 6 and len(plans[0]["stages"]) == 2
    switches = [e for e in events if e.get("name") == "stage.switch"]
    assert len(switches) == 1
    sw = switches[0]
    assert sw["stage"] == 1 and sw["prev_stage"] == 0 and sw["step"] == 3
    assert sw["num_frames"] == 8 and sw["resolution"] == 32
    # the display line tracks the live stage (n_display=1: every step)
    displays = [e for e in events if e.get("name") == "display"]
    assert displays and displays[0]["stage"] == 0
    assert displays[-1]["stage"] == 1
    # checkpoint spans carry the stage they saved under
    saves = [e for e in events if e.get("name") == "ckpt.save"]
    assert saves and saves[-1]["stage"] == 1


def test_ledger_sums_to_wall_with_nonzero_stage_switch(curriculum_run):
    cfg, wall = curriculum_run["cfg"], curriculum_run["wall"]
    doc = json.load(open(os.path.join(cfg.train.log_root, "GOODPUT.json")))
    assert doc["stage_switches"] == 1
    assert doc["categories_s"]["stage_switch"] > 0.0
    total = sum(doc["categories_s"].values())
    assert total == pytest.approx(wall, rel=0.05), (
        f"ledger sum {total:.3f}s vs measured {wall:.3f}s "
        f"(categories {doc['categories_s']})")


def test_stage_stamp_written_next_to_rotation(curriculum_run):
    cfg = curriculum_run["cfg"]
    stamp = curr.read_stage_stamp(
        os.path.join(cfg.train.checkpoint_root, "run"))
    assert stamp is not None
    assert stamp["schema"] == "milnce.curriculum/v1"
    assert stamp["curriculum"] == TWO_STAGE
    assert stamp["stage"] == 1
    assert stamp["num_frames"] == 8 and stamp["resolution"] == 32
    assert stamp["step"] == 6


def test_loss_continuity_vs_flat_run_at_final_shape(curriculum_run,
                                                    tmp_path):
    """Post-switch, the curriculum run trains at the flat 8f config's
    shape from a 3-step head start; its post-switch window mean must sit
    in the same regime as a flat 8f run of the same seed/data (synthetic
    losses are volatile step to step, so the band is generous — the
    failure mode this guards is a divergence/garbage state after the
    transition, which lands orders of magnitude away)."""
    from milnce_tpu.train.loop import run_training

    flat_cfg = _tiny_cfg(tmp_path)
    flat_cfg.data.num_frames = 8
    flat_cfg.data.video_size = 32
    flat_res = run_training(flat_cfg, max_steps=6)
    assert np.isfinite(flat_res.last_loss)

    disp_c = [e for e in _read_events(curriculum_run["cfg"])
              if e.get("name") == "display"]
    disp_f = [e for e in _read_events(flat_cfg)
              if e.get("name") == "display"]
    post = [e["loss"] for e in disp_c if e["stage"] == 1]
    ref = [e["loss"] for e in disp_f][-len(post):]
    assert post and all(np.isfinite(v) for v in post)
    ratio = np.mean(post) / np.mean(ref)
    assert 0.25 <= ratio <= 4.0, (
        f"post-switch window mean {np.mean(post):.3f} vs flat "
        f"{np.mean(ref):.3f} (ratio {ratio:.2f})")


# --------------------------------------------------------------------------
# checkpoint-compatible transitions (satellite 3)
# --------------------------------------------------------------------------

def test_resume_mid_stage_lands_at_right_offset(tmp_path, capsys):
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path)
    cfg.train.curriculum = TWO_STAGE
    r1 = run_training(cfg, max_steps=5)     # stops mid-stage-1 at step 5
    assert r1.stage == 1

    cfg.train.resume = True
    r2 = run_training(cfg, max_steps=1)
    out = capsys.readouterr().out
    assert r2.steps == 1 and r2.stage == 1
    assert int(r2.state.step) == 6          # optimizer counter carried
    # the resume log pins the batch offset (stage-1 skip 3 + 2 done) and
    # the stage the plan located
    assert "at batch 5" in out, out
    assert "curriculum stage 1" in out, out


def test_resume_at_boundary_enters_next_stage(tmp_path):
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path)
    cfg.train.curriculum = TWO_STAGE
    r1 = run_training(cfg, max_steps=3)     # stops ON the stage boundary
    assert r1.stage == 0                    # saved while still in stage 0
    stamp = curr.read_stage_stamp(
        os.path.join(cfg.train.checkpoint_root, "run"))
    assert stamp["stage"] == 0 and stamp["step"] == 3

    cfg.train.resume = True
    r2 = run_training(cfg, max_steps=1)     # plan.locate(3) -> stage 1
    assert r2.stage == 1
    assert int(r2.state.step) == 4


def test_resume_with_curriculum_removed_fails_loudly(tmp_path):
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path)
    cfg.train.curriculum = TWO_STAGE
    run_training(cfg, max_steps=3)

    cfg.train.curriculum = ""
    cfg.train.resume = True
    with pytest.raises(ValueError) as exc_info:
        run_training(cfg, max_steps=1)
    msg = str(exc_info.value)
    assert "train.curriculum is unset" in msg
    assert "4f@32" in msg                   # the saved stage's shape named


# --------------------------------------------------------------------------
# bench curriculum axis (satellite 1) — sweep logic with a fake child
# --------------------------------------------------------------------------

def _fake_bench_row(timeout_s=None, **kw):
    f = kw["frames"]
    return {"dtype": kw["dtype"], "batch": kw["batch"],
            "remat": kw["remat"], "s2d": kw["s2d"],
            "conv_impl": kw["conv_impl"], "loss": kw.get("loss", "milnce"),
            "loss_impl": None, "grad_accum": kw.get("grad_accum", 1),
            "inner": kw["inner"], "step_ms": 100.0 * f,
            "clips_per_sec_per_chip": 240.0 / f,
            "flops_per_step": None, "flops_source": None,
            "flops_per_sec": None}


def test_bench_curriculum_axis_composes_schedule_rate(monkeypatch):
    """MILNCE_BENCH_CURRICULUM measures each stage at its own shape and
    reports the whole-schedule rate vs a flat full-res run of the same
    total clip count; stage rows never displace the headline."""
    import bench

    recs, notes = [], {}
    monkeypatch.setattr(bench, "_run_config", _fake_bench_row)
    monkeypatch.setattr(bench, "_emit", recs.append)
    monkeypatch.setattr(bench, "_write_notes",
                        lambda *a, **k: notes.update(k))
    monkeypatch.setenv(
        "MILNCE_BENCH_CURRICULUM",
        "num_frames=2,resolution=32,batch_size=8,until_step=100;"
        "num_frames=4,resolution=64,batch_size=8")
    rec = bench.run_bench(False, {"platform": "cpu", "kind": "cpu", "n": 1})

    # headline = the sweep's 4f row (240/4), untouched by stage rows
    assert rec["value"] == pytest.approx(60.0)
    cur = rec["curriculum"]
    assert [s["label"] for s in cur["stages"]] == ["2f@32 batch 8",
                                                   "4f@64 batch 8"]
    # final stage defaults to the bounded stages' total steps
    assert [s["steps"] for s in cur["stages"]] == [100, 100]
    assert cur["total_clips"] == 1600
    # 800 clips @120 + 800 @60 -> 20s vs flat 1600 @60 -> 26.67s
    assert cur["schedule_clips_per_sec_per_chip"] == pytest.approx(80.0)
    assert cur["flat_clips_per_sec_per_chip"] == pytest.approx(60.0)
    assert cur["speedup_vs_flat"] == pytest.approx(4.0 / 3.0, abs=1e-3)
    # BENCH_NOTES gets the same summary (the stage column's source)
    assert notes["curriculum"]["speedup_vs_flat"] == cur["speedup_vs_flat"]


def test_bench_curriculum_axis_requires_step_bounds(monkeypatch):
    """Epoch-bounded stages need a dataset size a synthetic bench does
    not have — the axis fails softly (sweep results kept, no curriculum
    key) rather than fabricating a schedule rate."""
    import bench

    monkeypatch.setattr(bench, "_run_config", _fake_bench_row)
    monkeypatch.setattr(bench, "_emit", lambda r: None)
    monkeypatch.setattr(bench, "_write_notes", lambda *a, **k: None)
    monkeypatch.setenv(
        "MILNCE_BENCH_CURRICULUM",
        "num_frames=2,resolution=32,batch_size=8,until_epoch=1;"
        "num_frames=4,resolution=64,batch_size=8")
    rec = bench.run_bench(False, {"platform": "cpu", "kind": "cpu", "n": 1})
    assert "curriculum" not in rec
    assert rec["value"] == pytest.approx(60.0)
