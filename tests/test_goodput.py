"""Attribution-tier gates (ISSUE 9): goodput ledger, live MFU, anomaly
-> bounded profiler capture, run-identity tagging, pod aggregation.

The acceptance pins that live here:

- the goodput ledger on a 2-step instrumented CPU run AND on a chaos
  run (injected decode-timeout + nonfinite-grad faults) sums to the
  externally measured wall time within 5%, attributing nonzero badput
  to the injected sites;
- the live ``milnce_train_mfu`` gauge agrees with bench.py's
  roofline-derived MFU within 2% on the same steps (shared
  ``utils/roofline.py`` formula + table);
- a planted step-time spike fires the anomaly event and EXACTLY ONE
  profiler capture; a clean run captures zero times;
- ``obs_report --merge`` over >= 2 process-local snapshots produces a
  pod view ``--check`` can gate; mixed-run streams error loudly.

All tier-1 (suite-hygiene obs gate); the training runs share the
1-block tiny S3D jit cache with tests/test_obs.py.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from milnce_tpu.obs import aggregate
from milnce_tpu.obs import runctx
from milnce_tpu.obs.anomaly import EwmaSpikeDetector
from milnce_tpu.obs.capture import ProfilerCapture
from milnce_tpu.obs.export import SNAPSHOT_SCHEMA, snapshot
from milnce_tpu.obs.goodput import (CATEGORIES, compute_ledger,
                                    ledger_to_registry, select_run,
                                    split_runs)
from milnce_tpu.obs.metrics import MetricsRegistry
from milnce_tpu.obs.spans import SpanRecorder

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS_REPORT = os.path.join(_REPO, "scripts", "obs_report.py")


def _span(name, ts, dur_s, **attrs):
    return {"kind": "span", "name": name, "ts": ts,
            "dur_ms": dur_s * 1e3, **attrs}


def _event(name, ts, **attrs):
    return {"kind": "event", "name": name, "ts": ts, **attrs}


# ---------------------------------------------------------------------------
# ledger unit behavior
# ---------------------------------------------------------------------------

class TestLedger:
    def _stream(self):
        recs = [_event("run.start", 0.0)]
        recs.append(_span("step", 1.0, 5.0, step=1))       # compile
        for i in range(4):                                  # 4 x 1s steps
            recs.append(_span("step", 6.0 + i, 1.0, step=i + 2))
        recs.append(_span("data.wait", 10.0, 0.5))
        recs.append(_span("data.wait", 10.5, 0.5))
        recs.append(_span("ckpt.save", 11.0, 1.0))
        recs.append(_span("sync", 12.0, 0.5, cause="display"))
        recs.append(_event("run.end", 20.0))
        return recs

    def test_categories_partition_and_sum_to_wall(self):
        led = compute_ledger(self._stream())
        assert led.wall_s == 20.0
        cats = led.categories
        assert cats["compile"] == 5.0
        assert cats["compute"] == pytest.approx(4.5)    # 4 steps + sync
        assert cats["data_wait"] == pytest.approx(1.0)
        assert cats["checkpoint"] == pytest.approx(1.0)
        assert cats["skipped"] == 0.0
        assert sum(cats.values()) == pytest.approx(led.wall_s)
        assert set(cats) == set(CATEGORIES)
        assert led.steps == 5
        assert 0 < led.goodput_fraction < 1

    def test_elastic_drain_and_reshard_categories(self):
        """ISSUE 20 satellite: elastic.drain / elastic.resume spans land
        in their own drain / reshard buckets (used INSTEAD of ckpt.save /
        ckpt.restore on the drain path — never alongside, which would
        double-count), and the partition still sums to wall."""
        recs = [_event("run.start", 0.0),
                _span("elastic.resume", 0.5, 1.5, label="latest",
                      from_mesh="{'data': 8}", to_mesh="{'data': 4}"),
                _span("step", 2.0, 5.0, step=1),            # compile
                _span("step", 7.0, 1.0, step=2),
                _span("step", 8.0, 1.0, step=3),
                _span("elastic.drain", 9.0, 2.0, label=1, forced=True,
                      source="host.preempt"),
                _event("run.end", 12.0)]
        led = compute_ledger(recs)
        cats = led.categories
        assert cats["reshard"] == pytest.approx(1.5)
        assert cats["drain"] == pytest.approx(2.0)
        assert cats["checkpoint"] == 0.0
        assert cats["compute"] == pytest.approx(2.0)
        assert sum(cats.values()) == pytest.approx(led.wall_s)
        assert set(cats) == set(CATEGORIES)

    def test_skipped_steps_reattributed_out_of_compute(self):
        recs = self._stream()
        recs.insert(-1, _event("display", 12.5, skipped_total=2))
        led = compute_ledger(recs)
        # 2 of 4 post-compile steps skipped -> half the compute moved
        assert led.skipped_steps == 2
        assert led.categories["skipped"] == pytest.approx(4.5 / 2)
        assert led.categories["compute"] == pytest.approx(4.5 / 2)
        assert sum(led.categories.values()) == pytest.approx(led.wall_s)

    def test_rollback_lost_uses_mean_step_time(self):
        recs = self._stream()
        recs.insert(-1, _event("rollback", 13.0, lost_updates=2,
                               consecutive_skips=1))
        led = compute_ledger(recs)
        assert led.rollbacks == 1 and led.lost_updates == 2
        # mean post-compile step = 1s -> 2s moved out of compute
        assert led.categories["rollback_lost"] == pytest.approx(2.0)
        assert led.categories["compute"] == pytest.approx(2.5)
        assert sum(led.categories.values()) == pytest.approx(led.wall_s)

    def test_overlapping_spans_exceed_wall_not_hidden(self):
        # double-counted attribution must SHOW (sum > wall), never be
        # silently clamped — the 5% acceptance pin relies on this
        recs = [_event("run.start", 0.0),
                _span("step", 0.0, 8.0, step=1),
                _span("step", 0.0, 8.0, step=2),
                _event("run.end", 10.0)]
        led = compute_ledger(recs)
        assert sum(led.categories.values()) > led.wall_s

    def test_resumed_run_same_id_window_covers_both_sessions(self):
        # review fix: a crashed run re-launched under the same explicit
        # run_id appends a second marker pair into the same stream; the
        # window must span FIRST start -> LAST end or the categories
        # (summed over both sessions) exceed wall and the gated
        # goodput_fraction inflates past 1.0
        recs = [_event("run.start", 0.0),
                _span("step", 1.0, 5.0, step=1),
                _span("step", 6.0, 5.0, step=2)]     # crash: no run.end
        recs += [_event("run.start", 100.0),
                 _span("step", 101.0, 5.0, step=1),
                 _span("step", 106.0, 5.0, step=2),
                 _event("run.end", 112.0)]
        led = compute_ledger(recs)
        assert led.wall_s == 112.0
        assert sum(led.categories.values()) == pytest.approx(112.0)
        assert led.goodput_fraction <= 1.0

    def test_mixed_run_stream_is_loud(self):
        recs = [dict(r, run_id="a") for r in self._stream()]
        recs += [dict(r, run_id="b") for r in self._stream()]
        with pytest.raises(ValueError, match="mixed-run"):
            compute_ledger(recs)
        led = compute_ledger(recs, run_id="a")
        assert led.run_id == "a" and led.wall_s == 20.0
        assert sorted(split_runs(recs)) == ["a", "b"]
        with pytest.raises(ValueError, match="not in stream"):
            select_run(recs, "c")

    def test_ledger_exports_gauges(self):
        reg = MetricsRegistry()
        ledger_to_registry(compute_ledger(self._stream()), reg)
        fam = reg.gauge("milnce_goodput_seconds", labels=("category",))
        vals = {k[0]: ch.value for k, ch in fam.items()}
        assert vals["compile"] == 5.0
        assert reg.gauge("milnce_goodput_wall_seconds").value == 20.0
        assert 0 < reg.gauge("milnce_goodput_fraction").value < 1


# ---------------------------------------------------------------------------
# EWMA spike detector
# ---------------------------------------------------------------------------

class TestDetector:
    def test_spike_fires_once_then_cooldown(self):
        clock = {"t": 0.0}
        rec = SpanRecorder()
        fired = []
        det = EwmaSpikeDetector("t.ms", ratio=2.0, warmup=3,
                                cooldown_s=100.0, recorder=rec,
                                on_anomaly=lambda v, e: fired.append(v),
                                time_fn=lambda: clock["t"])
        for _ in range(5):
            assert not det.observe(10.0)
        assert det.observe(50.0)                 # the spike
        assert not det.observe(50.0)             # cooldown suppresses
        clock["t"] = 200.0
        assert det.observe(50.0)                 # cooldown elapsed
        assert fired == [50.0, 50.0]
        events = [r for r in rec.tail() if r["name"] == "anomaly"]
        assert len(events) == 2
        assert events[0]["detector"] == "t.ms"
        assert events[0]["value"] == 50.0

    def test_warmup_suppresses_and_baseline_not_poisoned(self):
        det = EwmaSpikeDetector("t.ms", ratio=2.0, warmup=2,
                                cooldown_s=0.0, recorder=SpanRecorder())
        assert not det.observe(100.0)            # warmup: huge first value
        assert not det.observe(10.0)
        # anomalous samples must not be folded into the EWMA
        ewma_before = det.stats()["ewma"]
        det.observe(1000.0)
        assert det.stats()["ewma"] == ewma_before

    def test_bad_ratio_rejected(self):
        with pytest.raises(ValueError, match="ratio"):
            EwmaSpikeDetector("t", ratio=1.0)


# ---------------------------------------------------------------------------
# bounded one-shot capture
# ---------------------------------------------------------------------------

class _FakeProfiler:
    def __init__(self):
        self.starts, self.stops = [], []

    def start(self, d):
        self.starts.append(d)

    def stop(self):
        self.stops.append(True)


class TestCapture:
    def test_one_shot_budget_and_cooldown(self, tmp_path):
        clock = {"t": 0.0}
        prof = _FakeProfiler()
        rec = SpanRecorder()
        cap = ProfilerCapture(str(tmp_path), duration_s=1000.0,
                              cooldown_s=50.0, max_captures=2,
                              recorder=rec, start_fn=prof.start,
                              stop_fn=prof.stop,
                              time_fn=lambda: clock["t"])
        v = cap.arm(reason="spike")
        assert v["armed"] and "capture_001-spike" in v["trace_dir"]
        assert os.path.isdir(v["trace_dir"])
        # active: a second arm is refused, not double-started
        assert not cap.arm(reason="again")["armed"]
        assert cap.stop()
        assert not cap.stop()                    # idempotent
        # cooldown refuses, then a later arm succeeds
        assert "cooldown" in cap.arm()["reason"]
        clock["t"] = 60.0
        assert cap.arm(reason="second")["armed"]
        cap.stop()
        # budget exhausted at max_captures
        clock["t"] = 200.0
        assert "exhausted" in cap.arm()["reason"]
        assert prof.starts and len(prof.starts) == 2 == len(prof.stops)
        names = [r["name"] for r in rec.tail()]
        assert names.count("capture.start") == 2
        assert names.count("capture.stop") == 2

    def test_timer_auto_stops(self, tmp_path):
        prof = _FakeProfiler()
        rec = SpanRecorder()
        cap = ProfilerCapture(str(tmp_path), duration_s=0.05,
                              max_captures=1, recorder=rec,
                              start_fn=prof.start, stop_fn=prof.stop)
        assert cap.arm()["armed"]
        deadline = time.time() + 5.0
        while not prof.stops and time.time() < deadline:
            time.sleep(0.01)
        assert prof.stops, "duration timer never stopped the capture"
        stop_ev = [r for r in rec.tail() if r["name"] == "capture.stop"]
        assert stop_ev and stop_ev[0]["cause"] == "duration"
        assert cap.stats()["state"] == "idle"

    def test_http_reason_cannot_escape_out_dir(self, tmp_path):
        # review fix: the reason string arrives from the NETWORK (POST
        # /obs/capture) — path separators/.. must not steer the trace
        # write outside the capture root
        root = tmp_path / "caps"
        cap = ProfilerCapture(str(root), start_fn=lambda d: None,
                              stop_fn=lambda: None,
                              recorder=SpanRecorder())
        v = cap.arm(reason="../../../tmp/evil")
        assert v["armed"]
        inside = os.path.realpath(v["trace_dir"])
        assert inside.startswith(os.path.realpath(str(root)) + os.sep)
        assert ".." not in os.path.relpath(inside, str(root))

    def test_stop_during_starting_still_flushes(self, tmp_path):
        # review fix: close() landing while arm() is inside start_fn on
        # another thread must still stop the trace (a daemon timer dies
        # with the process and the capture would be lost)
        started = threading.Event()
        release = threading.Event()
        calls = {"stop": 0}

        def slow_start(d):
            started.set()
            assert release.wait(10)

        rec = SpanRecorder()
        cap = ProfilerCapture(str(tmp_path), duration_s=1000.0,
                              start_fn=slow_start,
                              stop_fn=lambda: calls.__setitem__(
                                  "stop", calls["stop"] + 1),
                              recorder=rec)
        result = {}
        t = threading.Thread(target=lambda: result.update(cap.arm()))
        t.start()
        assert started.wait(10)
        assert not cap.stop()           # lands in 'starting': flagged
        release.set()
        t.join(timeout=10)
        assert not result["armed"]
        assert "stop requested" in result["reason"]
        assert calls["stop"] == 1
        assert cap.stats()["state"] == "idle"
        stops = [r for r in rec.tail() if r["name"] == "capture.stop"]
        assert stops and stops[0]["cause"] == "stopped-during-start"

    def test_start_failure_returns_to_idle(self, tmp_path):
        def boom(d):
            raise RuntimeError("no profiler here")

        rec = SpanRecorder()
        cap = ProfilerCapture(str(tmp_path), start_fn=boom,
                              stop_fn=lambda: None, recorder=rec)
        v = cap.arm()
        assert not v["armed"] and "no profiler here" in v["reason"]
        assert cap.stats()["state"] == "idle"
        assert [r for r in rec.tail() if r["name"] == "capture.error"]


# ---------------------------------------------------------------------------
# run identity tagging
# ---------------------------------------------------------------------------

class TestRunIdentity:
    def test_records_and_snapshots_stamped(self):
        prev = runctx.set_run_context("runX", 3)
        try:
            rec = SpanRecorder()
            rec.event("e")
            with rec.span("s"):
                pass
            for r in rec.tail():
                assert r["run_id"] == "runX"
                assert r["process_index"] == 3
                assert "mono" in r
            doc = snapshot(MetricsRegistry())
            assert doc["run_id"] == "runX" and doc["process_index"] == 3
            # explicit args override the context
            doc2 = snapshot(MetricsRegistry(), run_id="other",
                            process_index=7)
            assert doc2["run_id"] == "other" and doc2["process_index"] == 7
        finally:
            runctx.set_run_context(*prev)

    def test_mono_is_append_ordered(self):
        rec = SpanRecorder()
        for i in range(5):
            rec.event("e", i=i)
        monos = [r["mono"] for r in rec.tail()]
        assert monos == sorted(monos)
        # since= filter returns only newer records
        newer = rec.tail(since=monos[2])
        assert [r["i"] for r in newer] == [3, 4]

    def test_mono_strictly_increasing_under_bursts(self):
        # review fix: back-to-back records rounding to the same
        # microsecond would let a poller whose cursor lands between
        # them miss the second forever (tail's filter is a strict '>')
        rec = SpanRecorder()
        for i in range(500):
            rec.event("burst", i=i)
        monos = [r["mono"] for r in rec.tail()]
        assert all(b > a for a, b in zip(monos, monos[1:]))
        # every cursor position yields exactly the records after it
        assert len(rec.tail(since=monos[249])) == 250


# ---------------------------------------------------------------------------
# pod aggregation
# ---------------------------------------------------------------------------

def _proc_snapshot(pi, qps, run_id="podrun"):
    reg = MetricsRegistry()
    reg.counter("req_total", "h").inc(10 * (pi + 1))
    reg.gauge("load", "h").set(float(pi))
    h = reg.histogram("lat", "h", buckets=(1.0, 2.0))
    h.observe(0.5)
    return snapshot(reg, kind="serve_bench", extra={"qps": qps},
                    run_id=run_id, process_index=pi)


class TestAggregate:
    def test_merge_snapshots_sum_and_spread(self):
        docs = [_proc_snapshot(0, 100.0), _proc_snapshot(1, 200.0),
                _proc_snapshot(2, 400.0)]
        pod = aggregate.merge_snapshots(docs)
        assert pod["kind"] == "pod_serve_bench"
        assert pod["processes"] == 3 and pod["run_id"] == "podrun"
        m = pod["metrics"]
        assert m["req_total"]["values"][0]["value"] == 60     # summed
        g = m["load"]["values"][0]
        assert (g["min"], g["value"], g["max"]) == (0.0, 1.0, 2.0)
        assert m["lat"]["values"][0]["count"] == 3            # summed
        assert pod["qps"] == 200.0                            # median
        assert pod["spread"]["qps"]["max"] == 400.0

    def test_merge_refuses_mixed_runs_and_dup_processes(self):
        with pytest.raises(ValueError, match="mixed-run"):
            aggregate.merge_snapshots(
                [_proc_snapshot(0, 1.0, "a"), _proc_snapshot(1, 1.0, "b")])
        with pytest.raises(ValueError, match="duplicate process_index"):
            aggregate.merge_snapshots(
                [_proc_snapshot(0, 1.0), _proc_snapshot(0, 2.0)])
        with pytest.raises(ValueError, match=">= 2"):
            aggregate.merge_snapshots([_proc_snapshot(0, 1.0)])
        with pytest.raises(ValueError, match="run_id"):
            aggregate.merge_snapshots([
                {"schema": SNAPSHOT_SCHEMA, "kind": "metrics",
                 "metrics": {}, "process_index": i} for i in range(2)])

    def test_event_stream_merge_flags_straggler(self):
        def stream(pi, step_ms):
            return [dict(_span("step", float(i), step_ms / 1e3, step=i),
                         run_id="podrun", process_index=pi)
                    for i in range(10)]

        view = aggregate.merge_event_streams(
            [stream(0, 10.0), stream(1, 10.5), stream(2, 20.0)])
        assert view["step_p50_skew"] == pytest.approx(2.0)
        assert view["stragglers"] == [2]
        assert view["per_process"][0]["step_ms_p50"] == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# obs_report CLI: merge / latest-baseline / run-id split
# ---------------------------------------------------------------------------

def _run_report(*args):
    proc = subprocess.run([sys.executable, _OBS_REPORT, *args],
                          capture_output=True, text=True, timeout=120)
    return proc.returncode, proc.stdout + proc.stderr


def _goodput_doc(pi, frac, run_id="podrun"):
    return {"schema": SNAPSHOT_SCHEMA, "kind": "goodput",
            "run_id": run_id, "process_index": pi, "metrics": {},
            "goodput_fraction": frac, "mfu": 0.3,
            "wall_s": 100.0, "categories_s": {"compute": frac * 100.0}}


class TestObsReportCli:
    def test_mixed_run_stream_errors_and_run_id_selects(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as fh:
            for rid in ("a", "b"):
                for i in range(3):
                    fh.write(json.dumps(dict(
                        _span("step", float(i), 0.01, step=i),
                        run_id=rid)) + "\n")
        code, out = _run_report(str(path))
        assert code == 2 and "mixed-run stream" in out
        code, out = _run_report(str(path), "--run-id", "a")
        assert code == 0 and "step" in out

    def test_merge_produces_gateable_pod_view(self, tmp_path):
        for pi, frac in enumerate((0.5, 0.6)):
            (tmp_path / f"g{pi}.json").write_text(
                json.dumps(_goodput_doc(pi, frac)))
        pod = tmp_path / "POD.json"
        code, out = _run_report("--merge", str(tmp_path / "g0.json"),
                                str(tmp_path / "g1.json"),
                                "--out", str(pod))
        assert code == 0, out
        assert "pod_goodput" in out and "spread" in out.lower()
        doc = json.load(open(pod))
        assert doc["kind"] == "pod_goodput"
        assert doc["goodput_fraction"] == pytest.approx(0.55)
        # the merged view gates like any artifact: a baseline pod with
        # better goodput fails the check, a worse one passes
        better = tmp_path / "base.json"
        better.write_text(json.dumps(dict(doc, goodput_fraction=0.9)))
        code, out = _run_report("--check", str(pod),
                                "--baseline", str(better))
        assert code == 1 and "[FAIL] goodput_fraction" in out
        worse = tmp_path / "worse.json"
        worse.write_text(json.dumps(dict(doc, goodput_fraction=0.4)))
        code, out = _run_report("--check", str(pod),
                                "--baseline", str(worse))
        assert code == 0, out

    def test_merge_event_streams_reports_straggler(self, tmp_path):
        for pi, ms in ((0, 10.0), (1, 25.0)):
            with open(tmp_path / f"ev{pi}.jsonl", "w") as fh:
                for i in range(8):
                    fh.write(json.dumps(dict(
                        _span("step", float(i), ms / 1e3, step=i),
                        run_id="podrun", process_index=pi)) + "\n")
        code, out = _run_report("--merge", str(tmp_path / "ev0.jsonl"),
                                str(tmp_path / "ev1.jsonl"))
        assert code == 0, out
        assert "STRAGGLER" in out and "skew" in out

    def test_baseline_latest_picks_newest_same_kind(self, tmp_path):
        old = tmp_path / "old.json"
        old.write_text(json.dumps(_goodput_doc(0, 0.9, "r-old")))
        new = tmp_path / "new.json"
        new.write_text(json.dumps(_goodput_doc(0, 0.5, "r-new")))
        os.utime(old, (time.time() - 1000, time.time() - 1000))
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_goodput_doc(0, 0.52, "r-cur")))
        # newest same-kind is new.json (0.5): 0.52 vs 0.5 passes; had it
        # picked old.json (0.9) this would FAIL — the pass proves the pick
        code, out = _run_report("--check", str(cur), "--baseline",
                                "latest")
        assert code == 0, out
        assert "new.json" in out

    def test_merge_check_latest_resolves_in_inputs_dir(self, tmp_path):
        # review fix: --merge has a placeholder path ("<merged:N>") —
        # --baseline latest must scan the INPUT artifacts' directory,
        # not the cwd, even without --out
        for pi, frac in enumerate((0.5, 0.6)):
            (tmp_path / f"g{pi}.json").write_text(
                json.dumps(_goodput_doc(pi, frac)))
        pod_base = tmp_path / "POD_baseline.json"
        base = aggregate.merge_snapshots(
            [_goodput_doc(0, 0.5, "old"), _goodput_doc(1, 0.6, "old")])
        pod_base.write_text(json.dumps(base))
        code, out = _run_report("--merge", str(tmp_path / "g0.json"),
                                str(tmp_path / "g1.json"),
                                "--check", "--baseline", "latest",
                                "--tolerance", "0.5")
        assert code == 0, out
        assert "POD_baseline.json" in out

    def test_baseline_latest_refuses_kind_mismatch(self, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_goodput_doc(0, 0.5)))
        other = tmp_path / "serve.json"
        other.write_text(json.dumps(
            {"schema": SNAPSHOT_SCHEMA, "kind": "serve_bench",
             "metrics": {}, "qps": 1.0}))
        code, out = _run_report("--check", str(cur), "--baseline",
                                "latest")
        assert code == 2
        assert "no other goodput artifact" in out
        assert "serve_bench" in out


# ---------------------------------------------------------------------------
# MFU: one formula, two consumers
# ---------------------------------------------------------------------------

def test_mfu_helper_matches_bench_formula():
    """bench.py computes flops_per_sec / (peak * n_chips); the loop's
    live gauge calls roofline.mfu — given the same measured throughput
    they must agree exactly (well inside the 2% acceptance bound)."""
    from milnce_tpu.utils.roofline import mfu

    flops, dt, inner, peak, chips = 3.2e9, 0.25, 4, 1.0e12, 8
    bench_style = (flops * inner / dt) / (peak * chips)
    assert mfu(flops, inner / dt, peak, chips) == pytest.approx(
        bench_style, rel=1e-12)


def test_peak_flops_env_override(monkeypatch):
    from milnce_tpu.utils.roofline import device_peak_flops

    assert device_peak_flops("cpu") is None
    assert device_peak_flops("TPU v5e") == 197e12
    monkeypatch.setenv("MILNCE_PEAK_FLOPS", "2.5e12")
    assert device_peak_flops("cpu") == 2.5e12


# ---------------------------------------------------------------------------
# end to end: instrumented CPU runs (the ISSUE 9 acceptance)
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path, samples=16, epochs=1):
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    cfg.model.inception_blocks = 1      # 1-block S3D: tier-1 compile time
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = samples
    cfg.data.num_reader_threads = 2
    cfg.optim.epochs = epochs
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")
    cfg.train.log_root = str(tmp_path / "log")
    return cfg


def _read_events(cfg):
    path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")
    assert os.path.exists(path)
    return path, [json.loads(l) for l in open(path)]


@pytest.fixture(scope="module")
def two_step_run(tmp_path_factory):
    """ONE instrumented 2-step CPU run shared by the ledger-sum and
    pod-merge tests (each training run pays model init + a stop-save;
    the artifacts are read-only afterwards)."""
    from milnce_tpu.train.loop import run_training

    tmp = tmp_path_factory.mktemp("goodput_two_step")
    cfg = _tiny_cfg(tmp)
    cfg.train.run_id = "goodput-2step"
    t0 = time.monotonic()
    res = run_training(cfg, max_steps=2)
    return {"cfg": cfg, "res": res, "wall": time.monotonic() - t0}


def test_two_step_run_ledger_sums_to_measured_wall(two_step_run):
    """ISSUE 9 acceptance: ledger categories on the 2-step instrumented
    run sum to the externally measured wall time within 5%; every event
    line and the GOODPUT snapshot carry run_id + process_index."""
    cfg, res = two_step_run["cfg"], two_step_run["res"]
    measured_wall = two_step_run["wall"]
    assert res.steps == 2 and np.isfinite(res.last_loss)

    path, records = _read_events(cfg)
    for r in records:
        assert r["run_id"] == "goodput-2step", r
        assert r["process_index"] == 0
        assert "mono" in r
    assert [r["name"] for r in records].count("data.wait") >= 2

    gp_path = os.path.join(cfg.train.log_root, "GOODPUT.json")
    assert os.path.exists(gp_path), "run wrote no goodput ledger"
    doc = json.load(open(gp_path))
    assert doc["schema"] == SNAPSHOT_SCHEMA and doc["kind"] == "goodput"
    assert doc["run_id"] == "goodput-2step"
    assert doc["process_index"] == 0
    total = sum(doc["categories_s"].values())
    assert total == pytest.approx(measured_wall, rel=0.05), (
        f"ledger sum {total:.3f}s vs measured {measured_wall:.3f}s "
        f"(categories {doc['categories_s']})")
    assert doc["steps"] == 2
    assert 0.0 <= doc["goodput_fraction"] <= 1.0
    # obs_report summarizes + gates the artifact end to end
    code, out = _run_report(gp_path)
    assert code == 0 and "wall-time attribution" in out


def test_chaos_run_ledger_attributes_injected_badput(tmp_path):
    """ISSUE 9 acceptance: injected decode-timeout + nonfinite-grad
    faults produce a ledger that (a) sums to measured wall within 5%
    and (b) shows nonzero badput at BOTH injected sites.  The same run
    also pins the SIGUSR1 manual-capture path (detector disabled so the
    one capture is attributable to the signal alone) — training runs
    are the expensive part of this file, so acceptance pins share them."""
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path, samples=64, epochs=2)
    cfg.train.run_id = "goodput-chaos"
    cfg.train.capture_dir = str(tmp_path / "captures")
    cfg.train.capture_ms = 100.0
    cfg.train.anomaly_detect = False    # isolate the signal path
    # sample 20 hangs 1.5s -> watchdog timeout at 0.3s -> retry decodes
    # clean; optimizer step 3's gradients are poisoned -> finite guard
    # skips the update.  Lookahead/prefetch pinned to 0 so the hang
    # sits on the consumer's critical path deterministically — with
    # decode-ahead, a slow (loaded) run finishes the hung decode before
    # the consumer awaits it and the timeout never fires (flake).
    cfg.train.faults = "decode.hang@20:x=1.5;grad.nonfinite@3"
    cfg.data.sample_timeout = 0.3
    cfg.data.sample_timeout_retries = 1
    cfg.data.decode_lookahead = 0
    cfg.data.prefetch_depth = 0
    events_path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")

    def send_after_first_display():
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if os.path.exists(events_path):
                with open(events_path) as fh:
                    if any('"display"' in line for line in fh):
                        os.kill(os.getpid(), signal.SIGUSR1)
                        return
            time.sleep(0.01)

    t = threading.Thread(target=send_after_first_display, daemon=True)
    t.start()
    t0 = time.monotonic()
    res = run_training(cfg, max_steps=6)
    measured_wall = time.monotonic() - t0
    t.join(timeout=5)
    assert res.steps == 6
    assert res.skipped_steps == 1

    doc = json.load(open(os.path.join(cfg.train.log_root,
                                      "GOODPUT.json")))
    cats = doc["categories_s"]
    total = sum(cats.values())
    assert total == pytest.approx(measured_wall, rel=0.05), (
        f"ledger sum {total:.3f}s vs measured {measured_wall:.3f}s "
        f"({cats})")
    # the injected sites show up as attributed badput
    assert doc["skipped_steps"] == 1
    assert cats["skipped"] > 0.0, cats
    assert doc["decode_timeouts"] >= 1
    assert cats["data_wait"] > 0.0, cats
    # SIGUSR1 armed exactly one manual capture (detector was off)
    _, records = _read_events(cfg)
    starts = [r for r in records if r["name"] == "capture.start"]
    assert len(starts) == 1 and starts[0]["reason"] == "sigusr1"
    assert doc["captures"] == 1 and doc["anomalies"] == 0


def test_live_mfu_gauge_agrees_with_bench_formula(tmp_path, monkeypatch):
    """ISSUE 9 acceptance: the live gauge and bench.py's roofline MFU
    agree within 2% on the same steps — same FLOPs model, same peak
    table, same formula, same displayed throughput."""
    from milnce_tpu.obs import metrics as obs_metrics
    from milnce_tpu.train.loop import run_training
    from milnce_tpu.utils.roofline import (device_peak_flops, mfu,
                                           train_step_flops)

    monkeypatch.setenv("MILNCE_PEAK_FLOPS", "1e12")
    cfg = _tiny_cfg(tmp_path, samples=32)
    cfg.train.run_id = "goodput-mfu"
    # capture configured but the run is clean: doubles as the
    # zero-captures half of the anomaly acceptance (below)
    cfg.train.capture_dir = str(tmp_path / "captures")
    res = run_training(cfg, max_steps=3)
    assert res.steps == 3

    reg = obs_metrics.registry()
    live_mfu = reg.gauge("milnce_train_mfu").value
    clips_per_sec = reg.gauge("milnce_train_clips_per_sec").value
    assert live_mfu > 0 and clips_per_sec > 0
    flops = train_step_flops(
        cfg.train.batch_size, cfg.data.num_frames, cfg.data.video_size,
        cfg.data.num_candidates, cfg.data.max_words,
        inception_blocks=cfg.model.inception_blocks)
    import jax

    expected = mfu(flops, clips_per_sec / cfg.train.batch_size,
                   device_peak_flops("cpu"), len(jax.devices()))
    assert live_mfu == pytest.approx(expected, rel=0.02), (
        f"live {live_mfu} vs bench-formula {expected}")
    # the display events carry mfu, and the ledger snapshot exposes it
    # at top level for the obs_report gate
    _, records = _read_events(cfg)
    displays = [r for r in records if r["name"] == "display"]
    assert displays and all("mfu" in r for r in displays)
    doc = json.load(open(os.path.join(cfg.train.log_root,
                                      "GOODPUT.json")))
    assert doc["mfu"] > 0
    # clean run: zero anomalies, zero captures (ISSUE 9 acceptance —
    # the detector's warmup + ratio gates stay quiet on a healthy run)
    names = [r["name"] for r in records]
    assert names.count("anomaly") == 0
    assert names.count("capture.start") == 0
    assert doc["captures"] == 0


def test_planted_spike_fires_one_anomaly_and_one_capture(tmp_path):
    """ISSUE 9 acceptance: a planted step-time spike (a 2s decode hang
    surfacing as data wait in one display window) fires the anomaly
    event and EXACTLY ONE bounded profiler capture."""
    from milnce_tpu.train.loop import run_training

    cfg = _tiny_cfg(tmp_path, samples=64, epochs=1)
    cfg.train.run_id = "goodput-spike"
    cfg.train.capture_dir = str(tmp_path / "captures")
    cfg.train.capture_ms = 100.0
    cfg.train.anomaly_warmup = 3
    cfg.train.anomaly_ratio = 2.0
    # sample 60 (in step 8's batch) hangs 2s with the watchdog off: the
    # consumer waits the full hang -> one window spikes far past 2x
    # EWMA.  Lookahead/prefetch 0 keep the hang on the consumer's
    # critical path (decode-ahead on a slow machine would absorb it
    # before the await and the spike would vanish — observed flake).
    cfg.train.faults = "decode.hang@60:x=2.0"
    cfg.data.sample_timeout = 0.0
    cfg.data.decode_lookahead = 0
    cfg.data.prefetch_depth = 0
    res = run_training(cfg, max_steps=8)
    assert res.steps == 8

    _, records = _read_events(cfg)
    names = [r["name"] for r in records]
    anomalies = [r for r in records if r["name"] == "anomaly"]
    assert len(anomalies) == 1, (
        f"expected exactly 1 anomaly, got {len(anomalies)}: {anomalies}")
    assert anomalies[0]["detector"] == "train.step_ms"
    assert names.count("capture.start") == 1
    assert names.count("capture.stop") == 1
    start = [r for r in records if r["name"] == "capture.start"][0]
    assert start["reason"] == "step_time_spike"
    assert os.path.isdir(start["trace_dir"])
    # the real jax.profiler wrote an actual trace
    trace_files = [f for root, _, fs in os.walk(start["trace_dir"])
                   for f in fs]
    assert trace_files, "capture directory holds no trace"
    doc = json.load(open(os.path.join(cfg.train.log_root,
                                      "GOODPUT.json")))
    assert doc["anomalies"] == 1 and doc["captures"] == 1


def test_pod_merge_of_real_goodput_snapshots(two_step_run, tmp_path):
    """ISSUE 9 acceptance: obs_report --merge over two process-local
    snapshots of one run -> a pod view --check gates.  The second
    process view is synthesized from the real one (one CPU process
    can't host two jax process indices), exercising the REAL merge path
    over a REAL artifact."""
    cfg = two_step_run["cfg"]
    p0 = os.path.join(cfg.train.log_root, "GOODPUT.json")
    doc = json.load(open(p0))
    doc1 = dict(doc, process_index=1,
                goodput_fraction=doc["goodput_fraction"] * 0.8)
    p1 = os.path.join(cfg.train.log_root, "GOODPUT.p1.json")
    json.dump(doc1, open(p1, "w"))
    pod = os.path.join(str(tmp_path), "POD.json")
    code, out = _run_report("--merge", p0, p1, "--out", pod)
    assert code == 0, out
    merged = json.load(open(pod))
    assert merged["kind"] == "pod_goodput"
    assert merged["processes"] == 2
    assert merged["run_id"] == "goodput-2step"
    # gates like any single-process artifact
    code, out = _run_report("--check", pod, "--baseline", p0,
                            "--tolerance", "0.5")
    assert code == 0, out
