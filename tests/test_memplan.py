"""graftlint Pass 4 gates: the static HBM planner (analysis/memplan.py).

Four layers, mirroring how the other passes are pinned:

- **unit**: live-range corner cases the model must get right — scan
  bodies reuse their per-iteration buffers (peak is body-peak plus the
  stacked IO, never iterations x temp), donated args free at last use,
  sharded leaves divide by the mesh-axis extent, and trailing-None
  normalized specs land on the same divisor as their un-normalized
  twins.
- **calibration**: planner-vs-reality on the CPU backend — the per-chip
  resident bytes the planner claims for an entry's arguments must match
  the per-shard byte accounting of the ACTUAL committed arrays
  (train/state.per_device_state_bytes, the PR 6 helpers) within ±10%,
  for the 1-D milnce step AND the 4x2 2-D FSDP step.
- **planted failures**: each of GL013/GL014/GL015 must fire exactly
  once on a planted regression — a detector that can't fail is
  decoration (same discipline as the graftlint fixture's exact
  per-rule counts).
- **the gate**: every registered entry plans green against the pins,
  with the coverage floor asserted — this is the tier-1 check the
  tentpole exists for.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from milnce_tpu.analysis import memplan
from milnce_tpu.parallel.compat import shard_map


def _mesh1d():
    return Mesh(np.array(jax.devices()).reshape(-1), ("data",))


# ---- unit: live-range corner cases ---------------------------------------

def test_scan_body_buffers_are_reused_across_iterations():
    """16 iterations whose body holds a 1 MB temp must plan ~1 temp +
    the stacked IO — a planner that charges temp x iterations would
    refuse every microbatched config that actually fits."""
    n, width = 16, 65536            # 16 x 256 KB slices

    def scanned(xs):
        def body(carry, x):
            big = jnp.outer(x, jnp.ones((4,), jnp.float32))  # 4x the slice
            return carry + big.sum(), x * 2.0

        return jax.lax.scan(body, jnp.float32(0.0), xs)

    xs = jax.ShapeDtypeStruct((n, width), jnp.float32)
    plan = memplan.analyze_jaxpr(jax.make_jaxpr(scanned)(xs))
    stacked = n * width * 4                       # xs, and ys same size
    body_temp = width * 4 * 4                     # the outer-product temp
    assert plan.peak_bytes < 2 * stacked + 4 * body_temp, (
        f"scan peak {plan.peak_bytes} charges per-iteration temps "
        f"cumulatively (stacked IO {stacked}, body temp {body_temp})")
    assert plan.peak_bytes >= 2 * stacked, "stacked xs+ys must be counted"


def test_donated_arg_frees_at_last_use():
    """A consumed-and-returned buffer donated vs pinned: donation must
    lower the planned peak by about one copy."""
    def update(state, grad):
        return state + grad * 0.1, (grad ** 2).sum()

    args = (jax.ShapeDtypeStruct((1 << 20,), jnp.float32),
            jax.ShapeDtypeStruct((1 << 20,), jnp.float32))
    closed = jax.make_jaxpr(update)(*args)
    pinned = memplan.analyze_jaxpr(closed, donated=[False, False],
                                   labels=["state", "grad"])
    donated = memplan.analyze_jaxpr(closed, donated=[True, False],
                                    labels=["state", "grad"])
    one_copy = (1 << 20) * 4
    assert pinned.peak_bytes - donated.peak_bytes >= one_copy // 2, (
        f"donation saved only {pinned.peak_bytes - donated.peak_bytes} B "
        f"of a {one_copy} B reusable state")


def test_sharded_leaf_divides_by_axis_extent():
    """P('data') over the 8-way mesh: the entry arg contributes 1/8 of
    its global bytes per chip; a replicated arg contributes all of it."""
    mesh = _mesh1d()
    ndev = len(jax.devices())

    def f(w, x):
        return shard_map(lambda wv, xv: (xv * 2.0 + wv.sum()),
                         mesh=mesh, in_specs=(P(), P("data")),
                         out_specs=P("data"), check_vma=False)(w, x)

    w = jax.ShapeDtypeStruct((1024,), jnp.float32)      # replicated
    x = jax.ShapeDtypeStruct((8 * 1024,), jnp.float32)  # sharded
    plan = memplan.analyze_jaxpr(jax.make_jaxpr(f)(w, x),
                                 labels=["w", "x"])
    want = 1024 * 4 + (8 * 1024 * 4) // ndev
    assert plan.arg_bytes == want, (plan.arg_bytes, want)


def test_trailing_none_normalized_specs_same_divisor():
    """P('data') and P('data', None) (the sharding_map._dim_spec
    normalization concern) must produce identical per-chip plans — the
    divisor reads sharded dims only, never the spec's rank padding."""
    mesh = _mesh1d()

    def build(spec):
        def f(x):
            return shard_map(lambda xv: xv * 2.0, mesh=mesh,
                             in_specs=(spec,), out_specs=spec,
                             check_vma=False)(x)
        return jax.make_jaxpr(f)(
            jax.ShapeDtypeStruct((8, 64), jnp.float32))

    a = memplan.analyze_jaxpr(build(P("data")), labels=["x"])
    b = memplan.analyze_jaxpr(build(P("data", None)), labels=["x"])
    assert a.arg_bytes == b.arg_bytes
    assert a.peak_bytes == b.peak_bytes


def test_contributor_labels_name_args_by_tree_path():
    args = ({"params": {"w": jnp.zeros((4,), jnp.float32)}},
            jnp.zeros((2,), jnp.float32))
    labels = memplan.arg_leaf_labels(args, ("state", "x"))
    assert labels == ["state/params/w", "x"]
    assert memplan.donated_leaf_flags(args, (0,)) == [True, False]


# ---- calibration: planner vs committed arrays ----------------------------

def _measured_per_chip_bytes(trees) -> float:
    """Max per-device committed bytes across placed pytrees — the PR 6
    per-shard accounting (train/state.per_device_state_bytes reasoning)
    applied to everything the entry holds resident."""
    per_dev: dict = {}
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            for sh in getattr(leaf, "addressable_shards", ()):
                per_dev[sh.device] = per_dev.get(sh.device, 0) + sh.data.nbytes
    return max(per_dev.values())


def test_calibration_1d_milnce_step_args_within_10pct():
    from milnce_tpu.analysis.trace_invariants import _setup
    from milnce_tpu.data.pipeline import shard_placer
    from milnce_tpu.parallel.mesh import replicate_to_mesh

    model, _opt, mesh, state, batch = _setup()
    plans = memplan.plan_all(["train_step_milnce"])
    plan = plans["train_step_milnce"]
    place = shard_placer(mesh)
    placed_state = replicate_to_mesh(state, mesh)
    placed_batch = [place(b) for b in batch()]
    measured = _measured_per_chip_bytes([placed_state] + placed_batch)
    ratio = plan.arg_bytes / measured
    assert 0.9 <= ratio <= 1.1, (
        f"planner args/chip {plan.arg_bytes} vs measured committed "
        f"{measured} ({ratio:.3f}x) — the sharding-aware byte model "
        "drifted from reality")


def test_calibration_2d_fsdp_step_args_within_10pct():
    """The 4x2 (data, model) twin: sharded state leaves count 1/2 per
    chip, the batch 1/8 — planner and committed arrays must agree."""
    from milnce_tpu.analysis.trace_invariants import _setup_2d
    from milnce_tpu.parallel.mesh import batch_sharding

    _model, _opt, mesh, _specs, state, batch = _setup_2d()
    plans = memplan.plan_all(["train_step_milnce_2d"])
    plan = plans["train_step_milnce_2d"]
    sh = batch_sharding(mesh, ("data", "model"))
    placed_batch = [jax.device_put(b, sh) for b in batch()]
    measured = _measured_per_chip_bytes([state] + placed_batch)
    ratio = plan.arg_bytes / measured
    assert 0.9 <= ratio <= 1.1, (
        f"planner args/chip {plan.arg_bytes} vs measured committed "
        f"{measured} ({ratio:.3f}x) on the 4x2 FSDP mesh")
    # and the FSDP layout must actually be cheaper than replication
    full = sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(state))
    assert plan.arg_bytes < full, "2-D plan shows no sharding saving"


# ---- planted failures: each rule fires exactly once ----------------------

def test_gl013_fires_once_on_planted_peak_drift(monkeypatch):
    plans = memplan.plan_all(["train_step_milnce"])
    real = plans["train_step_milnce"].peak_bytes
    monkeypatch.setitem(memplan.EXPECTED_PEAK_BYTES, "train_step_milnce",
                        int(real * 2))
    results = memplan.run_memplan_checks(["train_step_milnce"],
                                         plans=plans)
    bad = [r for r in results if not r.ok]
    assert [r.check for r in bad] == ["GL013-peak-budget"], (
        [r.format() for r in results])
    assert "re-pin" in bad[0].detail


def test_gl015_fires_once_on_planted_contributor_drift(monkeypatch):
    plans = memplan.plan_all(["train_step_milnce"])
    monkeypatch.setitem(memplan.EXPECTED_TOP_CONTRIBUTORS,
                        "train_step_milnce",
                        ("phantom_buffer_a", "phantom_buffer_b",
                         "phantom_buffer_c"))
    results = memplan.run_memplan_checks(["train_step_milnce"],
                                         plans=plans)
    bad = [r for r in results if not r.ok]
    assert [r.check for r in bad] == ["GL015-top-contributors"], (
        [r.format() for r in results])
    assert "phantom_buffer_a" in bad[0].detail


def test_gl014_fires_once_per_planted_donation_bug():
    # (a) donated buffer that matches no output — dead-weight donation
    def no_alias(state, x):
        return (x * 2.0).sum()

    args = (jnp.zeros((1 << 16,), jnp.float32),
            jnp.zeros((8,), jnp.float32))
    found = memplan.donation_findings(
        no_alias, args, argnames=("state", "x"), donate_argnums=(0,),
        grad_bearing=True)
    assert len(found) == 1 and "matches no program output" in found[0]
    assert "state" in found[0]

    # (b) large aliasable arg NOT donated on a grad-bearing entry
    def aliasable(state, x):
        return state + 1.0, (x * 2.0).sum()

    found = memplan.donation_findings(
        aliasable, args, argnames=("state", "x"), donate_argnums=(),
        grad_bearing=True)
    assert len(found) == 1 and "not donated" in found[0]

    # (c) donated passthrough — buffer live to the end
    def passthrough(state, x):
        return state, (x + state.sum()).sum()

    found = memplan.donation_findings(
        passthrough, args, argnames=("state", "x"), donate_argnums=(0,),
        grad_bearing=True)
    assert len(found) == 1 and "returned unchanged" in found[0]

    # and the clean shape: consumed + same-shape output + donated
    def clean(state, x):
        return state + 1.0, (x * 2.0).sum()

    assert memplan.donation_findings(
        clean, args, argnames=("state", "x"), donate_argnums=(0,),
        grad_bearing=True) == []

    # an UNDONATED passthrough must stay silent on BOTH branches:
    # donating it could never take effect, so "donate it" would
    # oscillate with the passthrough finding above (review r13)
    def undonated_passthrough(state, x):
        return state, (x + state.sum()).sum()

    assert memplan.donation_findings(
        undonated_passthrough, args, argnames=("state", "x"),
        donate_argnums=(), grad_bearing=True) == []


def test_gl014_tpu_gate_verified_through_cpu_donation_gate():
    """The audit must honor the CPU gate (donation legitimately dropped
    here) while proving the TPU path still requests it — the pure
    backend-keyed half of parallel/compat.donation_argnums."""
    from milnce_tpu.parallel.compat import (donation_argnums,
                                            donation_argnums_for_backend)

    assert donation_argnums_for_backend("tpu", 0) == (0,)
    assert donation_argnums_for_backend("gpu", 0) == (0,)
    assert donation_argnums_for_backend("cpu", 0) == ()
    # this suite runs on CPU: the live gate and the pure function agree
    assert donation_argnums(0) == donation_argnums_for_backend(
        jax.default_backend(), 0)


def test_gl014_tpu_wiring_read_off_the_traced_program():
    """The TPU half of GL014 must interrogate what the factory REALLY
    passes to jax.jit, not round-trip a registry constant (review r13:
    a factory that drops its donate_argnums= plumbing must fail).  The
    donated production build traces one donated invar per state leaf;
    the donate=False build — exactly what a plumbing-less factory would
    produce — traces zero."""
    traced, expected = memplan._tpu_donation_wired("train_step_milnce")
    assert expected > 0 and traced == expected, (traced, expected)
    # the regression shape: no donate wiring -> zero donated invars
    spec = memplan._entries()["train_step_milnce"]
    fn, args = spec.build(donate=False)
    assert memplan.traced_donated_invar_count(fn, args) == 0


def test_entry_name_filter_rejects_typos():
    """A typo'd --entries filter must fail loudly, never plan zero
    entries and pass the gate vacuously (review r13)."""
    with pytest.raises(ValueError, match="unknown memplan entries"):
        memplan.plan_all(["train_step_milcne"])
    with pytest.raises(ValueError, match="unknown memplan entries"):
        memplan.run_memplan_checks(["no_such_entry"])


# ---- the gate ------------------------------------------------------------

def test_all_registered_entries_plan_green():
    """The Pass 4 merge gate: GL013 + GL014 + GL015 hold for every
    registered entry on both hermetic meshes, with the grad-bearing
    coverage floor asserted (the ISSUE 8 acceptance)."""
    results = memplan.run_memplan_checks()
    bad = [r.format() for r in results if not r.ok]
    assert not bad, "memplan invariants violated:\n" + "\n".join(bad)
    entries = {r.entry for r in results}
    assert {"train_step_milnce", "train_step_milnce_guarded",
            "train_step_sdtw3", "grad_cache_step_milnce",
            "train_step_milnce_2d", "grad_cache_2d",
            # ISSUE 12: the chunked step + the loss-only pair isolating
            # the O(B_local*Bg*K) -> O(B_local*chunk) claim
            "train_step_milnce_chunked", "milnce_loss_dense",
            "milnce_loss_chunked",
            "serve_text_embed@b0", "serve_text_embed@b1",
            "serve_video_embed@b0", "serve_video_embed@b1",
            "serve_index_topk",
            # ISSUE 14: the live index's generation program at its
            # capacity rung
            "serve_index_topk@gen",
            "train_step_milnce_instrumented"} <= entries
    # every grad-bearing entry carries all three rule checks + TPU gate
    checks = {(r.entry, r.check) for r in results}
    for entry in ("train_step_milnce", "train_step_milnce_2d",
                  "grad_cache_2d"):
        assert (entry, "GL013-peak-budget") in checks
        assert (entry, "GL015-top-contributors") in checks
        assert (entry, "GL014-donation") in checks
        assert (entry, "GL014-tpu-donation-requested") in checks


def test_guarded_step_peak_exceeds_plain_by_one_state_copy():
    """A real property the planner surfaced: the finite guard's
    skip-select keeps the OLD params/opt_state live until the end of
    the step, so donation cannot retire them — its pinned peak sits one
    state copy above the plain step's.  If these ever converge, the
    guard semantics (or the planner's donation model) changed."""
    plain = memplan.EXPECTED_PEAK_BYTES["train_step_milnce"]
    guarded = memplan.EXPECTED_PEAK_BYTES["train_step_milnce_guarded"]
    assert guarded > plain * 1.2


def test_milnce_chunked_loss_peak_strictly_below_dense():
    """The ISSUE 12 acceptance pin, stated on the pins themselves: at
    the loss-only entry shape (B_local=64, Bg=512, K=5) the chunked
    stream's per-chip peak is strictly — and substantially — below the
    dense cube's, and the chunked step never exceeds the dense step.
    The GL015 names behind the numbers: dense peaks at the
    (B_local, Bg*K) cube intermediates, chunked at one
    (B_local, chunk*K) streamed block (analysis/memplan.py)."""
    e = memplan.EXPECTED_PEAK_BYTES
    assert e["milnce_loss_chunked"] < e["milnce_loss_dense"]
    # the gap is structural (Bg/chunk = 8 at this shape), not noise
    assert e["milnce_loss_chunked"] < 0.5 * e["milnce_loss_dense"]
    assert e["train_step_milnce_chunked"] <= e["train_step_milnce"]
    # and the planned (not just pinned) values agree with the claim
    plans = memplan.plan_all(["milnce_loss_dense", "milnce_loss_chunked"])
    assert (plans["milnce_loss_chunked"].peak_bytes
            < plans["milnce_loss_dense"].peak_bytes)


def test_what_if_loss_impl_axis_reaches_the_traced_program(monkeypatch):
    """--loss-impl / --milnce-chunk must reach the step FACTORY (a
    config-only dead knob here would quietly un-gate the 8192 crossover
    table in BENCH_MILNCE_LOSS.md): a spy on make_train_step captures
    the loss_cfg the what-if actually builds with — one trace instead
    of a dense/chunked plan pair (the strictly-below direction is
    already pinned by the milnce_loss_* entries; the Bg=8192 pair lives
    in the committed table).  A --milnce-chunk without a chunked impl
    is refused outright."""
    import milnce_tpu.train.step as step_mod

    with pytest.raises(ValueError, match="milnce-chunk"):
        memplan.what_if_step(batch=16, frames=4, size=32, words=6, k=3,
                             preset="tiny", milnce_chunk=8)
    seen = {}
    real = step_mod.make_train_step

    def spy(*args, **kwargs):
        seen["loss_cfg"] = kwargs.get("loss_cfg")
        return real(*args, **kwargs)

    monkeypatch.setattr(step_mod, "make_train_step", spy)
    plan = memplan.what_if_step(batch=64, frames=4, size=32, words=6,
                                k=3, dtype="float32", preset="tiny",
                                loss_impl="chunked", milnce_chunk=8)
    assert "loss=chunked" in plan.entry
    assert seen["loss_cfg"] is not None
    assert seen["loss_cfg"].milnce_impl == "chunked"
    assert seen["loss_cfg"].milnce_chunk == 8


def test_2d_entries_plan_below_their_1d_twins():
    """FSDP must show up in the plan: the 4x2 sharded step's peak is
    strictly below the 8-way replicated step's (the PR 6 storage win,
    now claimed statically rather than only by live-byte counting)."""
    e = memplan.EXPECTED_PEAK_BYTES
    assert e["train_step_milnce_2d"] < e["train_step_milnce"]
    assert e["grad_cache_2d"] < e["grad_cache_step_milnce"]


# ---- what-if -------------------------------------------------------------

def test_what_if_refuses_oversized_config():
    """Library-level refusal on the tiny preset (the CLI twin is the
    subprocess test below): predicted peak over budget -> fits=False
    with the top-3 contributors named in the message."""
    plan = memplan.what_if_step(batch=16, frames=4, size=32, words=6,
                                k=3, dtype="float32", preset="tiny")
    fits, msg = memplan.budget_verdict(plan, hbm_gib=1e-4)
    assert not fits and "EXCEEDS" in msg
    assert msg.count("MiB") >= 3, f"top-3 contributors not named: {msg}"
    ok, msg2 = memplan.budget_verdict(plan, hbm_gib=1024.0)
    assert ok and "fits" in msg2


def test_mem_plan_cli_what_if_refuses_with_nonzero_exit():
    """The ISSUE 8 acceptance, end to end through the real CLI: an
    oversized config exits 1 with the top-3 contributors named; the
    same config under a generous budget exits 0.  Tiny preset keeps the
    child seconds-scale (the batch-256 full-preset refusal is the same
    code path — budget_verdict — pinned above at library level)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cli = [sys.executable, os.path.join(repo, "scripts", "mem_plan.py"),
           "--what-if", "--preset", "tiny", "--batch", "16",
           "--frames", "4", "--size", "32", "--words", "6", "--k", "3",
           "--dtype", "float32"]
    proc = subprocess.run(cli + ["--hbm-gib", "0.0001"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "EXCEEDS" in proc.stdout and proc.stdout.count("MiB") >= 3
    proc = subprocess.run(cli + ["--hbm-gib", "1024"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fits" in proc.stdout


def test_what_if_rejects_mesh_larger_than_devices():
    with pytest.raises(ValueError, match="devices"):
        memplan.what_if_step(batch=8, frames=4, size=32, words=6, k=3,
                             preset="tiny",
                             mesh_axes={"data": 64, "model": 4})


def test_what_if_grad_accum_plans_below_single_pass():
    """The grad-cache two-pass step exists to cut activation memory;
    the planner must agree at a shape where activations dominate
    (16f@112: ~1.1 GiB single-pass vs ~0.46 GiB at M=4 when this pin
    was taken — at activation-light shapes the cached embeddings +
    grad-carry overhead genuinely flips the ordering, which is exactly
    the crossover the what-if mode exists to predict)."""
    single = memplan.what_if_step(batch=64, frames=16, size=112, words=6,
                                  k=3, dtype="float32", preset="tiny")
    cached = memplan.what_if_step(batch=64, frames=16, size=112, words=6,
                                  k=3, dtype="float32", preset="tiny",
                                  grad_accum=4)
    assert cached.peak_bytes < 0.7 * single.peak_bytes, (
        f"grad-cache plan {cached.peak_bytes} not meaningfully below "
        f"single-pass {single.peak_bytes} at an activation-dominated "
        "shape")


# ---- stage_probe pre-flight ----------------------------------------------

def test_stage_probe_preflight_budget_env(monkeypatch):
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import stage_probe

    monkeypatch.setenv("MILNCE_HBM_GIB", "2")
    assert stage_probe._hbm_budget_bytes() == 2 * 2 ** 30
    monkeypatch.delenv("MILNCE_HBM_GIB")
    # CPU backend exposes no bytes_limit -> pre-flight off
    assert stage_probe._hbm_budget_bytes() in (None,) or isinstance(
        stage_probe._hbm_budget_bytes(), float)


def test_preflight_fn_peak_scales_with_shape():
    def probe(x):
        return (x.astype(jnp.float32) * 2.0).sum()

    small = memplan.preflight_fn_peak(
        probe, jax.ShapeDtypeStruct((1024,), jnp.float32))
    big = memplan.preflight_fn_peak(
        probe, jax.ShapeDtypeStruct((1024 * 64,), jnp.float32))
    assert big > small * 16
