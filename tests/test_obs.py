"""Unified observability gates (ISSUE 5): metrics registry, span
recorder, Prometheus/JSONL exposition, and the obs_report regression
gate.

The load-bearing invariants pinned here:

- **thread safety with exact counts**: the registry exists to replace
  the unsynchronized ``/healthz`` dict race — N threads hammering one
  counter/histogram must land EXACTLY N*K increments, not "about";
- **host-side only**: recording anything that quacks like a device
  array is a ``TypeError``, never a silent ``float()`` device sync;
- **format stability**: the Prometheus text exposition and the
  ``milnce.obs/v1`` snapshot schema are contracts for scrapers and for
  ``scripts/obs_report.py`` — the goldens pin them byte-for-byte;
- **end to end**: a real 2-step instrumented CPU train run writes
  ``RUN_EVENTS.jsonl`` with step + checkpoint spans (ISSUE 5
  acceptance), and obs_report can summarize and gate it.

All tier-1 (the suite-hygiene obs gate pins this file never-slow);
the train-run test shares the S3D compile cache with
test_transfer_guard.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from milnce_tpu.obs.export import (PROMETHEUS_CONTENT_TYPE, SNAPSHOT_SCHEMA,
                                   snapshot, to_prometheus, write_snapshot)
from milnce_tpu.obs.metrics import MetricsRegistry
from milnce_tpu.obs.spans import SpanRecorder, get_recorder, install

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS_REPORT = os.path.join(_REPO, "scripts", "obs_report.py")
_BASELINE = os.path.join(_REPO, "tests", "fixtures",
                         "obs_baseline_serve.json")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_thread_hammer_exact_final_counts(self):
        """8 threads x 2000 mixed recordings; every count must be exact
        — this is the /healthz race, fixed."""
        reg = MetricsRegistry()
        c = reg.counter("hammer_total", "t")
        g = reg.gauge("hammer_gauge", "t")
        fam = reg.counter("hammer_labeled_total", "t", ("site",))
        h = reg.histogram("hammer_hist", "t", buckets=(2.0, 5.0))
        n_threads, k = 8, 2000

        def worker(tid):
            child = fam.labels(site=f"s{tid % 2}")
            for i in range(k):
                c.inc()
                g.inc()
                child.inc()
                h.observe(float(i % 10))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = n_threads * k
        assert c.value == total
        assert g.value == total
        assert sum(ch.value for _, ch in fam.items()) == total
        snap = h.snapshot()
        assert snap["count"] == total
        assert sum(snap["counts"]) == total
        # per-thread values 0..9 uniformly: 0,1,2 <= 2.0; 3,4,5 <= 5.0
        assert snap["counts"] == [total * 3 // 10, total * 3 // 10,
                                  total * 4 // 10]

    def test_create_or_get_is_idempotent(self):
        reg = MetricsRegistry()
        a = reg.counter("dup_total", "x")
        b = reg.counter("dup_total", "x")
        assert a is b
        a.inc()
        assert b.value == 1

    def test_conflicting_reregistration_raises(self):
        reg = MetricsRegistry()
        reg.counter("one_name", "x")
        with pytest.raises(ValueError, match="conflicting"):
            reg.gauge("one_name", "x")
        with pytest.raises(ValueError, match="conflicting"):
            reg.counter("one_name", "x", labels=("site",))

    def test_counter_rejects_negative(self):
        with pytest.raises(ValueError):
            MetricsRegistry().counter("neg_total", "x").inc(-1)

    def test_label_names_must_match_declaration(self):
        fam = MetricsRegistry().counter("lbl_total", "x", ("site",))
        with pytest.raises(ValueError):
            fam.labels(zone="a")

    def test_callback_gauge_reads_live_and_rejects_set(self):
        reg = MetricsRegistry()
        box = {"v": 1.0}
        g = reg.gauge("cb_gauge", "x", fn=lambda: box["v"])
        assert g.value == 1.0
        box["v"] = 7.5
        assert g.value == 7.5
        with pytest.raises(ValueError):
            g.set(3.0)

    def test_gauge_bind_races_value_reads_without_tearing(self):
        """ISSUE 7 regression: bind() swapped the callback with no lock
        while scrape threads read (graftlint GL010) — rebinding under
        concurrent reads must never raise and every read resolves to
        SOME bound callback's value."""
        g = MetricsRegistry().gauge("rebind_gauge", "x", fn=lambda: 1.0)
        errors = []
        stop = threading.Event()

        def reader():
            try:
                while not stop.is_set():
                    assert g.value in (1.0, 2.0)
            except Exception as exc:  # pragma: no cover - asserted below
                errors.append(exc)

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for _ in range(500):
            g.bind(lambda: 2.0)
            g.bind(lambda: 1.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors

    def test_callback_gauge_callback_runs_outside_the_gauge_lock(self):
        """The callback is invoked AFTER the gauge lock is released:
        callbacks read other components' stats (the serving pattern:
        recompile gauge -> engine stats lock), and calling through
        while holding this gauge's lock would stack it above every
        callee lock in the order graph — lock-order hygiene (GL011/
        GL012 discipline, ISSUE 7)."""
        reg = MetricsRegistry()
        g = reg.gauge("hygiene_gauge", "x")
        held_during_callback = []
        g.bind(lambda: held_during_callback.append(g._lock.locked()) or 5.0)
        assert g.value == 5.0
        assert held_during_callback == [False]

    def test_device_array_recording_raises(self):
        """The tentpole invariant: float() of a device array is a
        blocking sync — the registry refuses it at the boundary."""
        import jax.numpy as jnp

        reg = MetricsRegistry()
        dev = jnp.ones(())
        with pytest.raises(TypeError, match="host-side only"):
            reg.counter("dev_total", "x").inc(dev)
        with pytest.raises(TypeError, match="host-side only"):
            reg.gauge("dev_gauge", "x").set(dev)
        with pytest.raises(TypeError, match="host-side only"):
            reg.histogram("dev_hist", "x", buckets=(1.0,)).observe(dev)


class TestHistogram:
    def test_bucket_edges_le_convention(self):
        """A value equal to an edge lands in THAT bucket (Prometheus
        cumulative-le semantics)."""
        h = MetricsRegistry().histogram("edges_hist", "x",
                                        buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.0, 1.5, 2.0, 4.0, 4.5):
            h.observe(v)
        snap = h.snapshot()
        assert snap["edges"] == [1.0, 2.0, 4.0]
        assert snap["counts"] == [2, 2, 1, 1]   # le1, le2, le4, +Inf
        assert snap["count"] == 6 and snap["sum"] == 13.5

    def test_bad_edges_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("no_edges", "x", buckets=())
        with pytest.raises(ValueError):
            reg.histogram("unsorted", "x", buckets=(2.0, 1.0))


# ---------------------------------------------------------------------------
# exposition: Prometheus text + JSON snapshot
# ---------------------------------------------------------------------------

def _golden_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("requests_total", "requests handled").inc(3)
    reg.gauge("temperature", "current temp").set(1.5)
    reg.counter("by_site_total", "per-site requests",
                ("site",)).labels(site='a"b\\c').inc(2)
    h = reg.histogram("latency_ms", "request latency", buckets=(1.0, 2.0))
    for v in (0.5, 1.0, 5.0):
        h.observe(v)
    return reg


# the byte-exact exposition contract (format 0.0.4): integral values
# print without a decimal point, histogram buckets are cumulative with
# +Inf and _sum/_count, label values escaped per the spec
_GOLDEN_TEXT = """\
# HELP requests_total requests handled
# TYPE requests_total counter
requests_total 3
# HELP temperature current temp
# TYPE temperature gauge
temperature 1.5
# HELP by_site_total per-site requests
# TYPE by_site_total counter
by_site_total{site="a\\"b\\\\c"} 2
# HELP latency_ms request latency
# TYPE latency_ms histogram
latency_ms_bucket{le="1"} 2
latency_ms_bucket{le="2"} 2
latency_ms_bucket{le="+Inf"} 3
latency_ms_sum 6.5
latency_ms_count 3
"""


class TestExposition:
    def test_prometheus_golden(self):
        assert to_prometheus(_golden_registry()) == _GOLDEN_TEXT

    def test_content_type_pinned(self):
        assert PROMETHEUS_CONTENT_TYPE == (
            "text/plain; version=0.0.4; charset=utf-8")

    def test_snapshot_roundtrip(self, tmp_path):
        path = str(tmp_path / "snap.json")
        doc = write_snapshot(path, _golden_registry(), kind="metrics",
                             extra={"run": "r1"})
        back = json.load(open(path))
        assert back == doc
        assert back["schema"] == SNAPSHOT_SCHEMA == "milnce.obs/v1"
        assert back["kind"] == "metrics" and back["run"] == "r1"
        assert back["metrics"]["requests_total"]["values"][0]["value"] == 3
        hist = back["metrics"]["latency_ms"]["values"][0]
        assert hist["counts"] == [2, 0, 1] and hist["sum"] == 6.5

    def test_snapshot_reserved_extra_key_raises(self):
        with pytest.raises(ValueError, match="reserved"):
            snapshot(MetricsRegistry(), extra={"metrics": {}})

    def test_nonfinite_samples_render_not_crash(self):
        # a guarded train window with zero applied updates sets the loss
        # gauge to nan by construction — one non-finite sample must
        # never 500 the whole scrape (NaN/+Inf are legal sample values)
        reg = MetricsRegistry()
        reg.gauge("g_nan").set(float("nan"))
        reg.gauge("g_inf").set(float("inf"))
        reg.gauge("g_ninf").set(float("-inf"))
        text = to_prometheus(reg)
        assert "g_nan NaN" in text
        assert "g_inf +Inf" in text
        assert "g_ninf -Inf" in text


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_jsonl_roundtrip(self, tmp_path):
        path = str(tmp_path / "RUN_EVENTS.jsonl")
        rec = SpanRecorder(path=path)
        with rec.span("step", step=1):
            pass
        rec.event("rollback", step=1, restored_epoch=3)
        with pytest.raises(RuntimeError, match="boom"):
            with rec.span("ckpt.save", label=2):
                raise RuntimeError("boom")
        rec.close()
        lines = [json.loads(l) for l in open(path)]
        assert [r["name"] for r in lines] == ["step", "rollback",
                                              "ckpt.save"]
        assert lines[0]["kind"] == "span" and lines[0]["dur_ms"] >= 0
        assert lines[0]["step"] == 1 and "ts" in lines[0]
        assert lines[1]["kind"] == "event"
        assert lines[1]["restored_epoch"] == 3
        # the failing span still recorded, carrying the exception type
        assert lines[2]["error"] == "RuntimeError"
        # the in-memory ring saw the same records
        assert rec.tail() == lines

    def test_ring_is_bounded_most_recent(self):
        rec = SpanRecorder(ring=4)
        for i in range(10):
            rec.event("e", i=i)
        tail = rec.tail()
        assert [r["i"] for r in tail] == [6, 7, 8, 9]
        assert [r["i"] for r in rec.tail(2)] == [8, 9]

    def test_install_swaps_and_restores(self):
        mine = SpanRecorder()
        prev = install(mine)
        try:
            assert get_recorder() is mine
        finally:
            assert install(prev) is mine
        assert get_recorder() is prev

    def test_profiler_bridge_spans_still_record(self):
        """opt-in TraceAnnotation bridge: spans must record normally
        (and not crash) when wrapped in the jax profiler annotation."""
        rec = SpanRecorder(profiler_bridge=True)
        with rec.span("step", step=1):
            pass
        last = rec.tail()[-1]
        assert last["name"] == "step" and last["dur_ms"] >= 0

    def test_close_is_idempotent(self, tmp_path):
        rec = SpanRecorder(path=str(tmp_path / "x.jsonl"))
        rec.event("e")
        rec.close()
        rec.close()
        rec.event("ring_only_after_close")    # must not raise
        assert rec.tail()[-1]["name"] == "ring_only_after_close"


# ---------------------------------------------------------------------------
# obs_report: summaries + the CI regression gate
# ---------------------------------------------------------------------------

def _run_report(*args):
    proc = subprocess.run([sys.executable, _OBS_REPORT, *args],
                          capture_output=True, text=True, timeout=120)
    return proc.returncode, proc.stdout + proc.stderr


def _serve_doc(p99=10.0, p50=4.0, qps=800.0):
    doc = json.load(open(_BASELINE))
    doc["latency_ms"]["p99"] = p99
    doc["latency_ms"]["p50"] = p50
    doc["qps"] = qps
    return doc


def _events_file(tmp_path, name, step_ms):
    path = tmp_path / name
    with open(path, "w") as fh:
        for i, ms in enumerate(step_ms):
            fh.write(json.dumps({"kind": "span", "name": "step",
                                 "ts": 0.0, "step": i,
                                 "dur_ms": ms}) + "\n")
        fh.write(json.dumps({"kind": "event", "name": "display",
                             "ts": 0.0}) + "\n")
    return str(path)


class TestObsReport:
    def test_summarize_snapshot(self):
        code, out = _run_report(_BASELINE)
        assert code == 0
        assert "kind: serve_bench" in out and "latency_ms_p99: 10" in out

    def test_summarize_events(self, tmp_path):
        path = _events_file(tmp_path, "ev.jsonl", [5.0, 6.0, 7.0])
        code, out = _run_report(path)
        assert code == 0
        assert "step" in out and "display=1" in out

    def test_gate_passes_within_tolerance(self, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_serve_doc(p99=10.5, qps=790.0)))
        code, out = _run_report("--check", str(cur),
                                "--baseline", _BASELINE)
        assert code == 0, out
        assert "FAIL" not in out

    def test_gate_fails_on_15pct_p99_drift(self, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_serve_doc(p99=11.5)))
        code, out = _run_report("--check", str(cur),
                                "--baseline", _BASELINE)
        assert code == 1
        assert "[FAIL] latency_ms_p99" in out

    def test_gate_fails_on_qps_collapse(self, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_serve_doc(qps=600.0)))
        code, out = _run_report("--check", str(cur),
                                "--baseline", _BASELINE)
        assert code == 1
        assert "[FAIL] qps" in out

    def test_gate_fails_on_memory_footprint_inflation(self, tmp_path):
        """ISSUE 8: predicted_peak_bytes_per_chip (the static HBM plan
        bench stamps into each record) gates lower-is-better — a row
        that got faster by inflating its footprint is a regression; a
        shrinking footprint never fails (good direction)."""
        base = tmp_path / "base.json"
        base.write_text(json.dumps(
            {**_serve_doc(), "predicted_peak_bytes_per_chip": 10_000_000}))
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(
            {**_serve_doc(), "predicted_peak_bytes_per_chip": 12_000_000}))
        code, out = _run_report("--check", str(cur),
                                "--baseline", str(base))
        assert code == 1
        assert "[FAIL] predicted_peak_bytes_per_chip" in out
        slim = tmp_path / "slim.json"
        slim.write_text(json.dumps(
            {**_serve_doc(), "predicted_peak_bytes_per_chip": 8_000_000}))
        code, out = _run_report("--check", str(slim),
                                "--baseline", str(base))
        assert code == 0, out

    def test_gate_all_zero_baseline_never_passes_vacuously(self, tmp_path):
        # an all-zero baseline (e.g. a bench error-path record committed
        # by mistake) skips every shared metric — a gate that compared
        # NOTHING must fail loudly, not exit 0
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_serve_doc(p99=0.0, p50=0.0, qps=0.0)))
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_serve_doc(p99=999.0, qps=1.0)))
        code, out = _run_report("--check", str(cur),
                                "--baseline", str(base))
        assert code == 1
        assert "nothing was compared" in out

    def test_gate_good_direction_drift_never_fails(self, tmp_path):
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps(_serve_doc(p99=2.0, p50=1.0,
                                             qps=2000.0)))
        code, out = _run_report("--check", str(cur),
                                "--baseline", _BASELINE)
        assert code == 0, out

    def test_gate_step_time_drift_on_event_streams(self, tmp_path):
        base = _events_file(tmp_path, "base.jsonl", [10.0] * 20)
        ok = _events_file(tmp_path, "ok.jsonl", [10.5] * 20)
        bad = _events_file(tmp_path, "bad.jsonl", [11.5] * 20)
        code, out = _run_report("--check", ok, "--baseline", base)
        assert code == 0, out
        code, out = _run_report("--check", bad, "--baseline", base)
        assert code == 1
        assert "[FAIL] step_ms_p50" in out

    def test_gate_notes_cross_layout_compare(self, tmp_path):
        # ISSUE 6: 1-D vs 2-D runs ARE comparable (that IS the point of
        # the mesh/map-hash fields), but the report must attribute the
        # layout difference instead of reading it as a plain regression
        base = tmp_path / "base.json"
        base.write_text(json.dumps({**_serve_doc(), "mesh": "8 (data)"}))
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps({**_serve_doc(),
                                   "mesh": "4x2 (data,model)",
                                   "sharding_map_hash": "abc123def456"}))
        code, out = _run_report("--check", str(cur),
                                "--baseline", str(base))
        assert code == 0, out
        assert "[note] mesh differs: baseline 8 (data) -> current " \
               "4x2 (data,model)" in out
        assert "[note] sharding_map_hash differs" in out
        # identical layouts stay note-free
        code, out = _run_report("--check", str(base),
                                "--baseline", str(base))
        assert code == 0 and "[note]" not in out

    def test_gate_notes_cross_precision_compare(self, tmp_path):
        # Pass 5: a differing dtype_census_hash means the rows ran
        # different-precision programs — attributable, not a regression
        base = tmp_path / "base.json"
        base.write_text(json.dumps({**_serve_doc(),
                                    "dtype_census_hash": "f33cda64207f"}))
        cur = tmp_path / "cur.json"
        cur.write_text(json.dumps({**_serve_doc(),
                                   "dtype_census_hash": "0123abcd4567"}))
        code, out = _run_report("--check", str(cur),
                                "--baseline", str(base))
        assert code == 0, out
        assert "[note] dtype_census_hash differs" in out

    def test_incomparable_artifacts_fail_loudly(self, tmp_path):
        empty = tmp_path / "empty.jsonl"
        empty.write_text(json.dumps({"kind": "event", "name": "e",
                                     "ts": 0.0}) + "\n")
        code, out = _run_report("--check", str(empty),
                                "--baseline", _BASELINE)
        assert code != 0
        assert "no shared gate metrics" in out

    def test_unversioned_snapshot_rejected(self, tmp_path):
        legacy = tmp_path / "legacy.json"
        legacy.write_text(json.dumps({"qps": 1.0}))
        code, out = _run_report(str(legacy))
        assert code == 2
        assert "schema" in out


# ---------------------------------------------------------------------------
# end to end: the instrumented train loop (ISSUE 5 acceptance)
# ---------------------------------------------------------------------------

def test_two_step_train_run_writes_run_events(tmp_path):
    """A 2-step instrumented CPU train run must write RUN_EVENTS.jsonl
    with step + checkpoint spans — and the whole run already executes
    under the steady-state transfer guard, so this doubles as proof the
    recorder adds no host sync to the hot loop."""
    from milnce_tpu.config import tiny_preset
    from milnce_tpu.train.loop import run_training

    cfg = tiny_preset()
    cfg.model.inception_blocks = 1       # 1-block S3D: tier-1 compile time
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = 16
    cfg.data.num_reader_threads = 2
    cfg.train.checkpoint_root = str(tmp_path / "ckpt")
    cfg.train.log_root = str(tmp_path / "log")
    res = run_training(cfg, max_steps=2)
    assert res.steps == 2 and np.isfinite(res.last_loss)

    path = os.path.join(cfg.train.log_root, "RUN_EVENTS.jsonl")
    assert os.path.exists(path), "instrumented run wrote no event stream"
    records = [json.loads(l) for l in open(path)]
    steps = [r for r in records
             if r["kind"] == "span" and r["name"] == "step"]
    saves = [r for r in records
             if r["kind"] == "span" and r["name"] == "ckpt.save"]
    assert len(steps) == 2, f"expected 2 step spans, got {len(steps)}"
    assert [r["step"] for r in steps] == [1, 2]
    assert all(r["dur_ms"] >= 0 for r in steps)
    assert saves, "stop-save produced no ckpt.save span"
    # the run's stream detached: later library events go to the previous
    # process-default recorder, not the closed file
    assert get_recorder().path != path

    # obs_report summarizes the real artifact end to end
    code, out = _run_report(path)
    assert code == 0 and "ckpt.save" in out
