"""Real video decode via the in-process cv2 backend.

These tests exercise the PRODUCTION decode path on actual encoded mp4
bytes — the first in the suite to do so (the ffmpeg-binary path stays
argv-parity-tested only, no binary in this environment; cv2 links the
same libav* libraries directly).  Videos are written with
cv2.VideoWriter (mpeg4): each frame is a constant uint8 value equal to
4x its index, so frame *identity* survives lossy encode within a small
tolerance and seek/fps-resample selection is checkable frame by frame.
"""

import json

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset
from milnce_tpu.data.tokenizer import Tokenizer
from milnce_tpu.data.video import Cv2Decoder, build_decoder

cv2 = pytest.importorskip("cv2")

SRC_FPS = 20
W, H = 96, 64
N_FRAMES = 120                      # 6 s at 20 fps


def _frame_value(i: int) -> int:
    return (i * 4) % 250


def _write_video(path, w=W, h=H, n=N_FRAMES, fps=SRC_FPS):
    vw = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"),
                         float(fps), (w, h))
    assert vw.isOpened()
    for i in range(n):
        vw.write(np.full((h, w, 3), _frame_value(i), np.uint8))
    vw.release()


@pytest.fixture(scope="module")
def video_path(tmp_path_factory):
    p = tmp_path_factory.mktemp("vids") / "clip.mp4"
    _write_video(p)
    return str(p)


def _values(frames):
    """Median pixel value per frame — robust to mpeg4 ringing."""
    return np.median(frames.reshape(frames.shape[0], -1), axis=1)


class TestCv2Decoder:
    def test_duration(self, video_path):
        dec = Cv2Decoder()
        assert dec.duration(video_path) == pytest.approx(
            N_FRAMES / SRC_FPS, rel=0.02)

    def test_fps_downsample_selects_expected_frames(self, video_path):
        """Target 5 fps over a 20 fps source: output k maps to source
        frame 4k (the last source frame with pts <= k/5)."""
        dec = Cv2Decoder()
        out = dec.decode(video_path, 0.0, 2.0, fps=5, size=48)
        assert out.shape[1:] == (48, 48, 3) and out.dtype == np.uint8
        vals = _values(out)
        expect = [_frame_value(4 * k) for k in range(len(vals))]
        np.testing.assert_allclose(vals, expect, atol=12)

    def test_fps_upsample_duplicates(self, video_path):
        """Target 40 fps over a 20 fps source: each source frame appears
        twice."""
        dec = Cv2Decoder()
        out = dec.decode(video_path, 0.0, 0.5, fps=40, size=32)
        vals = _values(out)
        expect = [_frame_value(k // 2) for k in range(len(vals))]
        np.testing.assert_allclose(vals, expect, atol=12)

    def test_seek_starts_at_requested_second(self, video_path):
        dec = Cv2Decoder()
        out = dec.decode(video_path, 3.0, 1.0, fps=SRC_FPS, size=32)
        vals = _values(out)
        # first output frame = source frame at 3.0 s = index 60
        assert abs(vals[0] - _frame_value(60)) <= 12

    def test_eof_stops_instead_of_duplicating(self, video_path):
        """Request far past the end: output stops at the last source
        frame's span (ffmpeg -t semantics); the caller pads."""
        dec = Cv2Decoder()
        out = dec.decode(video_path, 5.0, 10.0, fps=10, size=32)
        assert out.shape[0] <= 12       # ~1 s of source remains

    def test_crop_only_offsets(self, tmp_path):
        """Spatial gradient source: fractional offsets select the
        expected window (ffmpeg crop=(iw-size)*aw parity)."""
        p = tmp_path / "grad.mp4"
        vw = cv2.VideoWriter(str(p), cv2.VideoWriter_fourcc(*"mp4v"),
                             10.0, (96, 64))
        col = np.linspace(0, 240, 96, dtype=np.uint8)
        frame = np.repeat(col[None, :, None], 64, axis=0)
        frame = np.repeat(frame, 3, axis=2)
        for _ in range(20):
            vw.write(frame)
        vw.release()
        dec = Cv2Decoder()
        left = dec.decode(str(p), 0.0, 0.5, fps=10, size=32, aw=0.0, ah=0.5,
                          crop_only=True)
        right = dec.decode(str(p), 0.0, 0.5, fps=10, size=32, aw=1.0, ah=0.5,
                           crop_only=True)
        # gradient increases left->right: the aw=1 crop is brighter
        assert right.mean() > left.mean() + 50

    def test_square_crop_and_scale(self, video_path):
        dec = Cv2Decoder()
        out = dec.decode(video_path, 0.0, 0.5, fps=10, size=40,
                         crop_only=False)
        assert out.shape[1:] == (40, 40, 3)

    def test_hflip(self, tmp_path):
        p = tmp_path / "flip.mp4"
        vw = cv2.VideoWriter(str(p), cv2.VideoWriter_fourcc(*"mp4v"),
                             10.0, (64, 64))
        frame = np.zeros((64, 64, 3), np.uint8)
        frame[:, :32] = 200             # bright LEFT half
        for _ in range(10):
            vw.write(frame)
        vw.release()
        dec = Cv2Decoder()
        plain = dec.decode(str(p), 0.0, 0.3, fps=10, size=64, aw=0.5,
                           ah=0.5, crop_only=True, hflip=False)
        flip = dec.decode(str(p), 0.0, 0.3, fps=10, size=64, aw=0.5,
                          ah=0.5, crop_only=True, hflip=True)
        assert plain[0, :, :32].mean() > plain[0, :, 32:].mean() + 100
        assert flip[0, :, 32:].mean() > flip[0, :, :32].mean() + 100

    def test_missing_file_raises(self):
        with pytest.raises(RuntimeError):
            Cv2Decoder().decode("/nonexistent/x.mp4", 0.0, 1.0, 10, 32)

    def test_crop_only_rejects_small_frames(self, video_path):
        """ffmpeg's crop filter fails frames smaller than the crop; the
        cv2 backend must too (same decode-failure resampling on both)."""
        with pytest.raises(RuntimeError, match="smaller than crop"):
            Cv2Decoder().decode(video_path, 0.0, 0.5, fps=10, size=128,
                                crop_only=True)


def test_build_decoder_auto_falls_back_to_cv2(monkeypatch):
    """No ffmpeg binary on this host -> auto resolves to cv2."""
    import milnce_tpu.data.video as video_mod

    monkeypatch.setattr(video_mod.shutil, "which", lambda _: None)
    assert isinstance(build_decoder("auto"), Cv2Decoder)


def test_build_decoder_rejects_unknown():
    with pytest.raises(ValueError):
        build_decoder("quicktime")


def test_build_decoder_cv2_warns_on_native_reader():
    with pytest.warns(UserWarning, match="native"):
        dec = build_decoder("cv2", use_native_reader=True)
    assert isinstance(dec, Cv2Decoder)


def test_howto_source_end_to_end_on_real_videos(tmp_path):
    """The full production train path on actual encoded bytes: manifest
    csv -> caption sampling -> cv2 decode -> (T, H, W, 3) uint8 clips,
    through HowTo100MSource with NO fake decoder."""
    from milnce_tpu.data.datasets import HowTo100MSource

    (tmp_path / "videos").mkdir()
    (tmp_path / "captions").mkdir()
    rows = ["video_path"]
    for i in range(2):
        _write_video(tmp_path / "videos" / f"vid{i}.mp4")
        rows.append(f"vid{i}.mp4")
        caps = {"start": [0.0, 2.0], "end": [2.0, 4.0],
                "text": ["word1 word2", "word3 word4"]}
        (tmp_path / "captions" / f"vid{i}.json").write_text(json.dumps(caps))
    (tmp_path / "train.csv").write_text("\n".join(rows))

    cfg = tiny_preset()
    cfg.data.train_csv = str(tmp_path / "train.csv")
    cfg.data.video_root = str(tmp_path / "videos")
    cfg.data.caption_root = str(tmp_path / "captions")
    cfg.data.decoder_backend = "cv2"
    cfg.data.num_candidates = 2
    cfg.data.num_frames = 8
    cfg.data.fps = 5
    cfg.data.video_size = 32
    cfg.data.crop_only = False          # sources are 96x64 < 224
    tok = Tokenizer([f"word{i}" for i in range(1, 5)], cfg.data.max_words)
    src = HowTo100MSource(cfg.data, cfg.model, tokenizer=tok)
    assert isinstance(src.decoder, Cv2Decoder)
    rng = np.random.RandomState(0)
    for idx in range(2):
        s = src.sample(idx, rng)
        assert s["video"].shape == (8, 32, 32, 3)
        assert s["video"].dtype == np.uint8
        assert s["video"].max() > 0     # real decoded content, not padding
        assert s["text"].shape == (2, cfg.data.max_words)
    assert src.decode_failures == 0


def test_build_decoder_native_requires_binary(monkeypatch):
    """auto + use_native_reader with no ffmpeg binary must fail at BUILD
    time: a decoder whose every decode raises would be swallowed by the
    source's black-frame resampling and the run would train on garbage."""
    import milnce_tpu.data.video as video_mod

    monkeypatch.setattr(video_mod.shutil, "which", lambda _: None)
    with pytest.raises(RuntimeError, match="ReaderPool"):
        build_decoder("auto", use_native_reader=True)
