"""REAL multi-host bootstrap: two OS processes join one jax.distributed
cluster over loopback and run a single SPMD train step on a mesh that
spans both — the gather/psum collectives actually cross a process
boundary (Gloo CPU transport standing in for ICI/DCN).

This exercises the path the reference implements with a hardcoded 10-IP
list + TCP store rendezvous (train.py:48-56, main_distributed.py:70-75)
and that the in-process 8-virtual-device tests cannot: real
`jax.distributed.initialize`, `jax.make_array_from_process_local_data`
per-host sharding, cross-process collectives.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import multihost_child as mh

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Error texts a saturated CI host produces for STARTUP/transport races
# (never for an assertion or divergence inside the step itself).
_RETRYABLE_MARKERS = ("TIMEOUT: rendezvous", "Connect timeout",
                      "Gloo context initialization failed")
# The specific exit-time coordination message; a bare 'shutdown'
# substring would also match real teardown-path regressions.
_SHUTDOWN_BARRIER_MARKER = "Shutdown barrier has failed"


def _run_cluster_once(nprocs: int = mh.NPROCS, mode: str = "step",
                      workdir: str = ""):
    """One N-process cluster attempt.

    Returns ``(ok, outs, per_child_errors)`` where ``per_child_errors``
    lists ONE entry per failed child (crash stderr tail, or the TIMEOUT
    marker for a child that never finished) — the caller decides
    retryability per child, so one child's transport error can never
    launder a sibling's genuine crash."""
    port = _free_port()
    # each process contributes exactly one CPU device to the cluster
    env = mh.subprocess_env()
    child = os.path.join(_REPO, "tests", "multihost_child.py")
    extra = [mode, workdir] if workdir else ([mode] if mode != "step" else [])
    procs = [subprocess.Popen(
        [sys.executable, child, str(pid), str(nprocs), str(port)] + extra,
        env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for pid in range(nprocs)]
    results = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                # keep collecting the siblings' outcomes: an earlier
                # child's real crash text must not be discarded just
                # because this one hung (the finally block reaps it)
                results.append((None, b"", "TIMEOUT: rendezvous/step >600s"))
                continue
            results.append((p.returncode, out,
                            err.decode(errors="replace")))
    finally:
        # one child dying (port race, coordinator failure) must not leave
        # the other blocked forever at the rendezvous barrier as an orphan
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    outs = [out for rc, out, _ in results if rc == 0]
    failures = [err[-800:] if rc is not None else err
                for rc, _, err in results if rc != 0]
    if failures:
        # The EXIT-time coordination barrier can time out on a saturated
        # single-core host even though the distributed work — rendezvous,
        # cross-process collectives, the loss record — fully completed
        # (the child prints its JSON before shutdown).  That is an
        # environmental teardown race, not the behavior under test; it
        # only passes when every child produced its record AND every
        # failure is that specific barrier timeout.
        work_done = (len(results) == nprocs
                     and all(b'"loss"' in out for _, out, _ in results))
        only_shutdown = all(_SHUTDOWN_BARRIER_MARKER in err
                            for err in failures)
        if work_done and only_shutdown:
            import warnings

            warnings.warn("multihost children completed the step but "
                          "tripped the exit-time shutdown barrier "
                          "(saturated host); results validated anyway")
            return True, [out for _, out, _ in results], []
        return False, outs, failures
    return True, outs, []


def _all_retryable(errs) -> bool:
    # EVERY failed child must look like a startup/transport race —
    # a sibling's Gloo timeout can't launder one child's real crash
    return errs and all(
        any(m in e for m in _RETRYABLE_MARKERS) for e in errs)


def _run_cluster(nprocs: int = mh.NPROCS, mode: str = "step",
                 workdir: str = ""):
    """Cluster attempt with ONE bounded retry for startup/transport races
    only (saturated-host rendezvous is load, not a product bug); a child
    that CRASHES is never retried.  Returns the parsed per-process JSON
    records keyed by process id; asserts every process reported."""
    import warnings

    ok, outs, errs = _run_cluster_once(nprocs, mode, workdir)
    if not ok and _all_retryable(errs):
        first_errs = errs
        ok, outs, errs = _run_cluster_once(nprocs, mode, workdir)
        if ok:
            warnings.warn("multihost cluster needed a retry "
                          f"(attempt 1: {'; '.join(first_errs)[:300]})")
        else:
            errs = [f"attempt1: {e}" for e in first_errs] + [
                f"attempt2: {e}" for e in errs]
    assert ok, " | ".join(errs)
    records = {}
    for out in outs:
        for line in out.decode().splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                records[rec["process"]] = rec
    assert set(records) == set(range(nprocs)), sorted(records)
    return records


@pytest.mark.slow
def test_two_process_cluster_matches_single_process():
    from milnce_tpu.train.step import make_train_step

    records = _run_cluster()
    _cross_check_mode(records, lambda m, o, mesh: make_train_step(
        m, o, mesh, donate=False))


def _cross_check_mode(records, build_step):
    """Shared body for the per-step-program cluster tests: both
    processes computed the same mesh-global loss, and it matches the
    identical program run in-process on a 2-shard virtual mesh (local
    BatchNorm makes shard count part of the semantics, as the grad-cache
    microbatch==virtual-shard tests pin)."""
    losses = {p: r["loss"] for p, r in records.items()}
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    assert np.isfinite(losses[0])

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    video, text, start = mh.global_batch()
    model, optimizer, state = mh.build_model_and_state()
    mesh = Mesh(np.asarray(jax.devices()[:mh.NPROCS]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    step = build_step(model, optimizer, mesh)
    _, loss = step(state, jax.device_put(video, sh),
                   jax.device_put(text, sh), jax.device_put(start, sh))
    assert losses[0] == pytest.approx(float(loss), rel=2e-5)


@pytest.mark.slow
def test_two_process_cdtw_step_matches_single_process():
    """The DTW-family step's collective pattern (all_gather sequence
    embeddings -> replicated loss -> pmean grads) across a REAL process
    boundary — the virtual-mesh tests can't catch transport-layer bugs
    (VERDICT r4 #5)."""
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import make_train_step

    records = _run_cluster(mode="cdtw_step")
    _cross_check_mode(records, lambda m, o, mesh: make_train_step(
        m, o, mesh, donate=False, loss_cfg=LossConfig(name="cdtw")))


@pytest.mark.slow
def test_two_process_gradcache_step_matches_single_process():
    """The two-pass embedding-cache step (grad_accum=2) across a REAL
    process boundary: scan-embed, mesh-global loss on cached embeddings,
    VJP re-forward, psum — each collective crossing Gloo (VERDICT r4 #5)."""
    from milnce_tpu.train.step import make_grad_cache_step

    records = _run_cluster(mode="gradcache_step")
    _cross_check_mode(records, lambda m, o, mesh: make_grad_cache_step(
        m, o, mesh, micro_batches=2, donate=False))


@pytest.mark.slow
def test_four_process_sigterm_checkpoint_resume(tmp_path):
    """The pod-scale failure story end to end, at 4 processes (VERDICT r3
    #7): mid-run SIGTERM to ONE worker -> cluster-wide cooperative
    checkpoint (the preempt flag is all-reduced over the mesh, so no
    worker exits unilaterally inside a collective) -> full restart ->
    restore_latest + mesh re-replication on EVERY process -> run to
    completion -> identical mesh-global losses.  A third phase resumes
    the same checkpoint under an EVOLVED optimizer tree, exercising the
    weights-only fallback (restore_raw) on every process — the multihost
    path ADVICE r3 flagged as untested.  Reference equivalent: the
    10-node launcher + manual epoch-file restarts (train.py:37-66)."""
    workdir = str(tmp_path / "mh_ckpt")

    # phase A: 4-way collectives; process 0 is SIGTERM'd after step 2,
    # everyone checkpoints together at the step-3 boundary
    rec_a = _run_cluster(nprocs=4, mode="trainA", workdir=workdir)
    for p in range(4):
        assert rec_a[p]["preempted"], rec_a[p]
        assert rec_a[p]["steps_done"] == 3, rec_a[p]
        assert rec_a[p]["loss"] == pytest.approx(rec_a[0]["loss"], rel=1e-6)
    assert np.isfinite(rec_a[0]["loss"])

    # phase B: fresh cluster resumes from the cooperative checkpoint
    rec_b = _run_cluster(nprocs=4, mode="trainB", workdir=workdir)
    for p in range(4):
        assert rec_b[p]["restored_step"] == 3, rec_b[p]
        assert rec_b[p]["final_step"] == mh.MAX_STEPS, rec_b[p]
        assert rec_b[p]["loss"] == pytest.approx(rec_b[0]["loss"], rel=1e-6)
    assert np.isfinite(rec_b[0]["loss"])
    # training continued: the post-resume loss differs from the
    # pre-preemption loss (parameters moved)
    assert rec_b[0]["loss"] != pytest.approx(rec_a[0]["loss"], rel=1e-6)

    # phase C: resume the SAME checkpoint under a chain-wrapped optimizer
    # -> structural restore failure -> weights-only fallback, cluster-wide
    rec_c = _run_cluster(nprocs=4, mode="fallback", workdir=workdir)
    for p in range(4):
        assert rec_c[p]["restored_step"] == 3, rec_c[p]
        assert rec_c[p]["final_step"] == mh.MAX_STEPS, rec_c[p]
        assert rec_c[p]["loss"] == pytest.approx(rec_c[0]["loss"], rel=1e-6)
    assert np.isfinite(rec_c[0]["loss"])


@pytest.mark.slow
def test_production_loop_coordinated_preemption(tmp_path):
    """run_training itself (not a hand-rolled loop) across a 2-process
    cluster: ONE worker receives a real SIGTERM at an arbitrary time;
    the loop's preempt_sync_steps flag all-reduce must stop EVERY
    process at the same step with a cooperative checkpoint, and a
    `--resume`-style restart (restore_latest + replicate_to_mesh inside
    the production loop) must continue on every process."""
    workdir = str(tmp_path / "preempt_run")

    rec = _run_cluster(nprocs=2, mode="preempt_loop", workdir=workdir)
    # the run was cut short, at the SAME step on every process
    assert rec[0]["steps"] == rec[1]["steps"], rec
    assert 0 < rec[0]["steps"] < 3200, rec
    assert rec[0]["step_counter"] == rec[1]["step_counter"], rec
    assert rec[0]["loss"] == pytest.approx(rec[1]["loss"], rel=1e-6)
    assert np.isfinite(rec[0]["loss"])

    rec2 = _run_cluster(nprocs=2, mode="preempt_resume", workdir=workdir)
    for p in range(2):
        assert rec2[p]["steps"] == 3, rec2[p]
        assert (rec2[p]["step_counter"]
                == rec[0]["step_counter"] + 3), (rec, rec2)
    assert rec2[0]["loss"] == pytest.approx(rec2[1]["loss"], rel=1e-6)
    assert np.isfinite(rec2[0]["loss"])
