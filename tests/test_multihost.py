"""REAL multi-host bootstrap: two OS processes join one jax.distributed
cluster over loopback and run a single SPMD train step on a mesh that
spans both — the gather/psum collectives actually cross a process
boundary (Gloo CPU transport standing in for ICI/DCN).

This exercises the path the reference implements with a hardcoded 10-IP
list + TCP store rendezvous (train.py:48-56, main_distributed.py:70-75)
and that the in-process 8-virtual-device tests cannot: real
`jax.distributed.initialize`, `jax.make_array_from_process_local_data`
per-host sharding, cross-process collectives.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import multihost_child as mh

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Error texts a saturated CI host produces for STARTUP/transport races
# (never for an assertion or divergence inside the step itself).
_RETRYABLE_MARKERS = ("TIMEOUT: rendezvous", "Connect timeout",
                      "Gloo context initialization failed")
# The specific exit-time coordination message; a bare 'shutdown'
# substring would also match real teardown-path regressions.
_SHUTDOWN_BARRIER_MARKER = "Shutdown barrier has failed"


def _run_cluster_once():
    """One two-process cluster attempt.

    Returns ``(ok, outs, per_child_errors)`` where ``per_child_errors``
    lists ONE entry per failed child (crash stderr tail, or the TIMEOUT
    marker for a child that never finished) — the caller decides
    retryability per child, so one child's transport error can never
    launder a sibling's genuine crash."""
    port = _free_port()
    env = dict(os.environ)
    # the children must NOT inherit the parent's forced 8-device flag:
    # each process contributes exactly one CPU device to the cluster
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    child = os.path.join(_REPO, "tests", "multihost_child.py")
    procs = [subprocess.Popen(
        [sys.executable, child, str(pid), str(mh.NPROCS), str(port)],
        env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for pid in range(mh.NPROCS)]
    results = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                # keep collecting the siblings' outcomes: an earlier
                # child's real crash text must not be discarded just
                # because this one hung (the finally block reaps it)
                results.append((None, b"", "TIMEOUT: rendezvous/step >600s"))
                continue
            results.append((p.returncode, out,
                            err.decode(errors="replace")))
    finally:
        # one child dying (port race, coordinator failure) must not leave
        # the other blocked forever at the rendezvous barrier as an orphan
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    outs = [out for rc, out, _ in results if rc == 0]
    failures = [err[-800:] if rc is not None else err
                for rc, _, err in results if rc != 0]
    if failures:
        # The EXIT-time coordination barrier can time out on a saturated
        # single-core host even though the distributed work — rendezvous,
        # cross-process collectives, the loss record — fully completed
        # (the child prints its JSON before shutdown).  That is an
        # environmental teardown race, not the behavior under test; it
        # only passes when every child produced its record AND every
        # failure is that specific barrier timeout.
        work_done = (len(results) == mh.NPROCS
                     and all(b'"loss"' in out for _, out, _ in results))
        only_shutdown = all(_SHUTDOWN_BARRIER_MARKER in err
                            for err in failures)
        if work_done and only_shutdown:
            import warnings

            warnings.warn("multihost children completed the step but "
                          "tripped the exit-time shutdown barrier "
                          "(saturated host); results validated anyway")
            return True, [out for _, out, _ in results], []
        return False, outs, failures
    return True, outs, []


@pytest.mark.slow
def test_two_process_cluster_matches_single_process():
    # One bounded retry, for the TIMEOUT case only: the rendezvous of
    # two fresh processes on a saturated single-core CI host is
    # inherently racy, and a timeout there is load, not a product bug.
    # A child that CRASHES is never retried — a nondeterministic product
    # failure must stay red.  A retried-then-green run still warns so a
    # rising flake rate is visible before it becomes two-in-a-row.
    import warnings

    def _all_retryable(errs) -> bool:
        # EVERY failed child must look like a startup/transport race —
        # a sibling's Gloo timeout can't launder one child's real crash
        return errs and all(
            any(m in e for m in _RETRYABLE_MARKERS) for e in errs)

    ok, outs, errs = _run_cluster_once()
    if not ok and _all_retryable(errs):
        first_errs = errs
        ok, outs, errs = _run_cluster_once()
        if ok:
            warnings.warn("multihost cluster needed a retry "
                          f"(attempt 1: {'; '.join(first_errs)[:300]})")
        else:
            errs = [f"attempt1: {e}" for e in first_errs] + [
                f"attempt2: {e}" for e in errs]
    assert ok, " | ".join(errs)

    losses = {}
    for out in outs:
        for line in out.decode().splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                losses[rec["process"]] = rec["loss"]
    assert set(losses) == set(range(mh.NPROCS)), losses
    # the loss is mesh-global: both processes must compute the same value
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    assert np.isfinite(losses[0])

    # cross-check the SAME global batch in-process, on the SAME shard
    # layout (2 shards): local BatchNorm computes per-shard statistics,
    # so shard count is part of the semantics (as the grad-cache
    # microbatch==virtual-shard tests pin)
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from milnce_tpu.train.step import make_train_step

    video, text, start = mh.global_batch()
    model, optimizer, state = mh.build_model_and_state()

    mesh = Mesh(np.asarray(jax.devices()[:mh.NPROCS]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    step = make_train_step(model, optimizer, mesh, donate=False)
    _, loss = step(state, jax.device_put(video, sh),
                   jax.device_put(text, sh), jax.device_put(start, sh))
    assert losses[0] == pytest.approx(float(loss), rel=2e-5)
