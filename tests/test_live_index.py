"""Live retrieval index gates (ISSUE 14): generation-swapped corpus
shards, online ingest through the serve path, swap chaos, snapshot
round trip, and the ingest-while-query hammer.

The freshness parity pin is the tentpole acceptance: after
``POST /v1/index/add`` + swap, a served query ranks the GROWN corpus
exactly like the offline ``eval/retrieval.py`` argsort, queries answer
from exactly one generation, and the query path never recompiles across
swaps.  Model/engine dimensions match tests/test_serving.py's stack so
the persistent compile cache keeps this module seconds-scale.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from milnce_tpu.resilience import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FRAMES, _SIZE, _WORDS = 4, 32, 6
_BOOT, _GROW = 12, 9            # corpus: 12 at boot, 9 ingested -> 21


@pytest.fixture(scope="module")
def stack():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from milnce_tpu.models import S3D
    from milnce_tpu.serving.cache import EmbeddingLRUCache
    from milnce_tpu.serving.engine import InferenceEngine
    from milnce_tpu.serving.live_index import LiveRetrievalIndex
    from milnce_tpu.serving.service import RetrievalService

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, _FRAMES, _SIZE, _SIZE, 3)),
                           jnp.zeros((1, _WORDS), jnp.int32))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    engine = InferenceEngine(model, dict(variables), mesh,
                             text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=16)
    rng = np.random.default_rng(0)
    clips = rng.integers(0, 255, (_BOOT + _GROW, _FRAMES, _SIZE, _SIZE, 3),
                         dtype=np.uint8)
    boot_emb = engine.embed_video(clips[:_BOOT])
    index = LiveRetrievalIndex(mesh, boot_emb, k=5,
                               query_buckets=engine.buckets)
    # cache off: ingest changes the right answer, a stale hit would
    # hide exactly the freshness this module pins
    service = RetrievalService(engine, index,
                               cache=EmbeddingLRUCache(0),
                               max_delay_ms=2.0)
    yield dict(model=model, variables=variables, mesh=mesh, engine=engine,
               clips=clips, index=index, service=service)
    service.close()
    index.close()


def _mini_index(mesh, corpus, **kw):
    from milnce_tpu.serving.live_index import LiveRetrievalIndex

    kw.setdefault("k", 5)
    kw.setdefault("query_buckets", (8,))
    return LiveRetrievalIndex(mesh, corpus, **kw)


class TestFreshnessParity:
    def test_ingested_clips_rank_exactly_like_offline_eval(self, stack):
        """THE acceptance pin: raw clips through /v1/index/add's embed
        path + one generation swap, then every served query ranks the
        GROWN corpus exactly like the offline eval/retrieval.py
        extraction + argsort — freshly ingested rows are first-class
        corpus citizens, and the swap cost zero query-path recompiles."""
        from milnce_tpu.eval.retrieval import extract_retrieval_embeddings

        service, index, clips = stack["service"], stack["index"], \
            stack["clips"]
        rng = np.random.default_rng(5)
        texts = rng.integers(1, 64, (_BOOT + _GROW, _WORDS)).astype(np.int32)

        out = service.index_add(clips=clips[_BOOT:], wait=True)
        assert out["live"] and out["rows"] == _GROW
        assert out["generation"] >= 1
        assert index.size == _BOOT + _GROW

        class _Source:
            def __len__(self):
                return _BOOT + _GROW

            def sample(self, i, rng=None):
                return {"video": clips[i:i + 1], "text": texts[i:i + 1]}

        t_emb, v_emb = extract_retrieval_embeddings(
            stack["model"], dict(stack["variables"]), _Source(),
            stack["mesh"], batch_size=8)
        offline = np.argsort(-(t_emb @ v_emb.T), axis=1)[:, :5]

        gens = set()
        served = []
        for i in range(_BOOT + _GROW):
            scores, idx, gen = service.query_ids_with_gen(texts[i:i + 1])
            served.append(idx[0])
            gens.add(gen)
        assert np.array_equal(np.stack(served), offline), (
            "served top-k over the grown corpus diverged from the "
            "offline eval ranking")
        # every answer came from ONE generation (nothing ingested
        # mid-loop), and the swap never recompiled the query path
        assert len(gens) == 1 and gens.pop() == out["generation"]
        assert index.recompiles() == 0
        assert stack["engine"].recompiles() == 0

    def test_healthz_index_section_and_generation_stamp_over_http(
            self, stack):
        """Satellite: /healthz gains the additive index keys and
        /v1/query stamps index_generation so clients detect freshness."""
        from milnce_tpu.serving.service import serve_http

        service = stack["service"]
        server = serve_http(service, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
                h = json.loads(r.read())
            idx = h["index"]
            # byte-compatible frozen keys...
            for key in ("size", "dim", "k", "query_buckets", "calls",
                        "recompiles"):
                assert key in idx, f"frozen index key {key} missing"
            # ...plus the additive live keys
            for key in ("generation", "pending_rows", "last_swap_age_s",
                        "swaps", "swap_failures", "ingested_rows",
                        "builder_alive"):
                assert key in idx, f"live index key {key} missing"
            assert idx["builder_alive"] and idx["pending_rows"] == 0

            req = urllib.request.Request(
                base + "/v1/query",
                data=json.dumps({"token_ids": [[1, 2, 3, 0, 0, 0]],
                                 "k": 3}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            assert body["index_generation"] == idx["generation"]

            # the HTTP write path: precomputed embeddings, wait for swap
            rows = np.random.default_rng(8).standard_normal(
                (2, service.engine.embed_dim)).astype(np.float32)
            req = urllib.request.Request(
                base + "/v1/index/add",
                data=json.dumps({"embeddings": rows.tolist(),
                                 "wait": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())
            assert out["live"] and out["rows"] == 2
            assert out["generation"] > idx["generation"]
        finally:
            server.shutdown()
            server.server_close()

    def test_index_add_on_frozen_index_is_a_400_class_error(self, stack):
        from milnce_tpu.serving.index import DeviceRetrievalIndex
        from milnce_tpu.serving.service import RetrievalService

        frozen = DeviceRetrievalIndex(
            stack["mesh"],
            np.ones((8, stack["engine"].embed_dim), np.float32),
            k=3, query_buckets=stack["engine"].buckets, precompile=False)
        service = RetrievalService(stack["engine"], frozen)
        try:
            with pytest.raises(ValueError, match="not a live index"):
                service.index_add(embeddings=np.zeros(
                    (1, stack["engine"].embed_dim), np.float32))
        finally:
            service.close()


class TestSwapChaos:
    def test_failed_swap_keeps_old_generation_and_builder_retries(
            self, stack):
        """Satellite: under ``index.swap_raise@*`` every build fails —
        the old generation keeps serving bit-identically, rows are
        never lost, the builder thread never wedges; disarmed, the
        retry lands the rows."""
        mesh = stack["mesh"]
        rng = np.random.default_rng(11)
        corpus = rng.standard_normal((12, 16)).astype(np.float32)
        li = _mini_index(mesh, corpus)
        try:
            q = rng.standard_normal((3, 16)).astype(np.float32)
            s0, i0, g0 = li.topk_with_gen(q)
            with faults.armed("index.swap_raise@*"):
                li.add(rng.standard_normal((3, 16)).astype(np.float32))
                assert not li.flush(0.8), "swap 'succeeded' under @*"
                st = li.stats()
                assert st["swap_failures"] >= 1
                assert st["pending_rows"] == 3, "failed swap lost rows"
                assert st["builder_alive"], "builder thread wedged"
                s1, i1, g1 = li.topk_with_gen(q)
                assert g1 == g0 and np.array_equal(i1, i0) \
                    and np.array_equal(s1, s0), "old generation torn"
            # disarmed: the builder's retry publishes the held rows
            assert li.flush(10.0), li.stats()
            st = li.stats()
            assert st["generation"] == g0 + 1 and st["size"] == 15
            assert st["pending_rows"] == 0 and st["builder_alive"]
            assert li.recompiles() == 0
        finally:
            li.close()

    def test_transient_swap_failure_self_heals_without_flush(self, stack):
        """One scheduled failure (@1): the builder's own idle-backoff
        retry publishes the rows with no explicit flush() nudge."""
        rng = np.random.default_rng(12)
        li = _mini_index(stack["mesh"],
                         rng.standard_normal((12, 16)).astype(np.float32))
        try:
            with faults.armed("index.swap_raise@1"):
                li.add(rng.standard_normal((2, 16)).astype(np.float32))
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if li.stats()["generation"] == 1:
                        break
                    time.sleep(0.02)
            st = li.stats()
            assert st["generation"] == 1 and st["size"] == 14, st
            assert st["swap_failures"] == 1
        finally:
            li.close()

    def test_ingest_hang_does_not_block_queries(self, stack):
        rng = np.random.default_rng(13)
        li = _mini_index(stack["mesh"],
                         rng.standard_normal((12, 16)).astype(np.float32))
        try:
            q = rng.standard_normal((2, 16)).astype(np.float32)
            li.topk_with_gen(q)                      # warm the path
            done = threading.Event()

            def slow_add():
                li.add(rng.standard_normal((2, 16)).astype(np.float32))
                done.set()

            faults.arm("index.ingest_hang@1:x=0.8")
            try:
                t = threading.Thread(target=slow_add, daemon=True)
                t.start()
                time.sleep(0.05)                     # add is hanging now
                t0 = time.monotonic()
                li.topk_with_gen(q)
                dt = time.monotonic() - t0
                t.join(timeout=10)
            finally:
                faults.disarm()
            assert done.is_set()
            assert dt < 0.5, (f"query took {dt:.3f}s while an ingest "
                              "hung — the hang leaked into the query path")
        finally:
            li.close()

    def test_new_fault_sites_parse_and_unknown_rejected(self):
        spec = faults.parse_spec(
            "index.swap_raise@%3;index.ingest_hang@1:x=0.5")
        assert set(spec) == {"index.swap_raise", "index.ingest_hang"}
        assert spec["index.ingest_hang"].x == 0.5
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.parse_spec("index.typo@*")


class TestSnapshotRestore:
    def test_snapshot_restore_query_bit_exact_round_trip(self, stack,
                                                         tmp_path):
        rng = np.random.default_rng(21)
        li = _mini_index(stack["mesh"],
                         rng.standard_normal((12, 16)).astype(np.float32))
        try:
            li.add(rng.standard_normal((5, 16)).astype(np.float32))
            assert li.flush(10.0)
            q = rng.standard_normal((4, 16)).astype(np.float32)
            s0, i0, g0 = li.topk_with_gen(q)
            li.snapshot(str(tmp_path / "snap"))
            from milnce_tpu.serving.live_index import LiveRetrievalIndex

            li2 = LiveRetrievalIndex.restore(str(tmp_path / "snap"),
                                             stack["mesh"],
                                             query_buckets=(8,))
            try:
                s1, i1, g1 = li2.topk_with_gen(q)
                assert np.array_equal(s0, s1), "scores not bit-exact"
                assert np.array_equal(i0, i1), "indices not bit-exact"
                assert g1 == g0, "generation counter lost in the round trip"
                assert li2.size == 17 and li2.k == 5
            finally:
                li2.close()
        finally:
            li.close()

    def test_snapshot_format_is_corpus_npz_compatible(self, stack,
                                                      tmp_path):
        """The snapshot's corpus.npz is the exact --serve.corpus_npz
        contract ('emb' key) — a cold DeviceRetrievalIndex boot off it
        serves the same corpus."""
        from milnce_tpu.serving.export import (INDEX_ARRAYS_FILE,
                                               INDEX_METADATA_FILE)
        from milnce_tpu.serving.index import DeviceRetrievalIndex

        rng = np.random.default_rng(22)
        corpus = rng.standard_normal((10, 16)).astype(np.float32)
        li = _mini_index(stack["mesh"], corpus)
        try:
            li.snapshot(str(tmp_path / "snap2"))
        finally:
            li.close()
        with np.load(str(tmp_path / "snap2" / INDEX_ARRAYS_FILE)) as z:
            np.testing.assert_array_equal(z["emb"], corpus)
        meta = json.loads(
            (tmp_path / "snap2" / INDEX_METADATA_FILE).read_text())
        assert meta["format_version"] == 1 and meta["size"] == 10
        frozen = DeviceRetrievalIndex(stack["mesh"], corpus, k=5,
                                      query_buckets=(8,))
        q = rng.standard_normal((2, 16)).astype(np.float32)
        _, idx = frozen.topk(q)
        ref = np.argsort(-(q @ corpus.T), axis=1)[:, :5]
        assert np.array_equal(idx, ref)


class TestRungRule:
    def test_growth_within_a_rung_reuses_shapes_across_rungs_rebaselines(
            self, stack):
        """The zero-recompile story end to end: swaps inside a rung are
        shape-identical (no compile at all); crossing a rung compiles
        ON THE BUILDER (counted as builder work) and the query path
        still reports 0."""
        rng = np.random.default_rng(31)
        li = _mini_index(stack["mesh"],
                         rng.standard_normal((12, 16)).astype(np.float32))
        try:
            q = rng.standard_normal((2, 16)).astype(np.float32)
            assert li.stats()["shard_rows"] == 8      # capacity 64
            full = li.stats()["size"]
            for n in (9, 10, 20):                     # stays under 64
                li.add(rng.standard_normal((n, 16)).astype(np.float32))
                assert li.flush(10.0)
                full += n
                li.topk_with_gen(q)
            st = li.stats()
            assert st["swaps"] == 3 and st["shard_rows"] == 8
            assert li.recompiles() == 0
            # cross the rung: capacity doubles, query path stays clean
            li.add(rng.standard_normal((40, 16)).astype(np.float32))
            assert li.flush(30.0)
            li.topk_with_gen(q)
            st = li.stats()
            assert st["shard_rows"] == 16 and st["size"] == full + 40
            assert li.recompiles() == 0, (
                "rung crossing leaked a compile into the query path")
        finally:
            li.close()

    def test_empty_boot_ingest_then_query(self, stack):
        rng = np.random.default_rng(32)
        li = _mini_index(stack["mesh"], None, dim=16)
        try:
            with pytest.raises(ValueError, match="ingest more"):
                li.topk(np.zeros((1, 16), np.float32))
            li.add(rng.standard_normal((8, 16)).astype(np.float32))
            assert li.flush(10.0)
            q = rng.standard_normal((2, 16)).astype(np.float32)
            _, idx, gen = li.topk_with_gen(q)
            assert gen == 1 and idx.max() < 8
        finally:
            li.close()

    def test_shard_rung_rule(self):
        from milnce_tpu.serving.live_index import shard_rung

        assert shard_rung(0, 8, 5) == 8        # k floor, then pow2
        assert shard_rung(12, 8, 5) == 8       # ceil(12/8)=2 < k=5 -> 8
        assert shard_rung(65, 8, 5) == 16      # 9 rows/shard -> rung 16
        assert shard_rung(12, 8, 5, floor=32) == 32

    def test_recommended_min_shard_rows_sizing_rule(self):
        """The ``--serve.index_min_shard_rows`` sizing helper (ISSUE 19
        small fix): plan the rung for the corpus's end-of-life size so
        growth to ``headroom`` x never re-traces the query program."""
        from milnce_tpu.serving.live_index import (
            recommended_min_shard_rows, shard_rung)

        # HowTo100M scale: ~1.2M videos, 8-way data axis, 2x headroom
        # -> 2**19 rows/shard (the documented config.py default)
        assert recommended_min_shard_rows(1_200_000, 8) == 524_288
        # and the rung is exactly what the ladder would pick at the
        # doubled corpus size, so the first swap lands in-rung
        assert shard_rung(2_400_000, 8, 1,
                          floor=recommended_min_shard_rows(
                              1_200_000, 8)) == 524_288
        assert recommended_min_shard_rows(100, 8, headroom=1) == 16
        for bad in ((0, 8, 2), (100, 0, 2), (100, 8, 0)):
            with pytest.raises(ValueError):
                recommended_min_shard_rows(*bad)


# ---------------------------------------------------------------------------
# ISSUE 14 satellite: the 16-thread ingest-while-query hammer under the
# runtime lock sanitizer (subprocess: MILNCE_LOCK_SANITIZE must be armed
# before the serving modules import — fast-child exemption in
# test_suite_hygiene.py; tiny dims + shared compile cache keep it
# seconds-scale)
# ---------------------------------------------------------------------------

def test_live_index_hammer_subprocess_under_sanitizer():
    env = dict(os.environ, MILNCE_LOCK_SANITIZE="1")
    env.pop("MILNCE_FAULTS", None)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "tests", "live_index_hammer_child.py")],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, (
        f"live-index hammer failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    assert "HAMMER_OK" in proc.stdout
