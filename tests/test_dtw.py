"""Hard DTW: DP table vs numpy golden, path backtracking, loss semantics
(behavior spec: reference dtw.py:5-75)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.ops.dtw import dtw_loss, dtw_path, dtw_table


def numpy_dtw_table(cost):
    B, N, M = cost.shape
    tc = np.full((B, N, M), np.inf)
    tc[:, 0, 0] = cost[:, 0, 0]
    for i in range(1, N):
        tc[:, i, 0] = tc[:, i - 1, 0] + cost[:, i, 0]
    for j in range(1, M):
        tc[:, 0, j] = tc[:, 0, j - 1] + cost[:, 0, j]
    for i in range(1, N):
        for j in range(1, M):
            tc[:, i, j] = cost[:, i, j] + np.minimum(
                np.minimum(tc[:, i - 1, j - 1], tc[:, i - 1, j]), tc[:, i, j - 1])
    return tc


def test_table_matches_numpy():
    rng = np.random.RandomState(0)
    cost = rng.rand(3, 6, 5).astype(np.float32)
    got = np.asarray(dtw_table(jnp.asarray(cost)))
    np.testing.assert_allclose(got, numpy_dtw_table(cost), rtol=1e-5)


def test_path_on_identity_cost():
    """Zero cost on the diagonal forces the diagonal path."""
    n = 5
    cost = np.ones((1, n, n), np.float32)
    cost[0, np.arange(n), np.arange(n)] = 0.0
    path = np.asarray(dtw_path(jnp.asarray(cost)))[0]
    np.testing.assert_allclose(path, np.eye(n))


def test_path_always_marks_corners():
    rng = np.random.RandomState(1)
    cost = rng.rand(2, 7, 4).astype(np.float32)
    path = np.asarray(dtw_path(jnp.asarray(cost)))
    assert (path[:, 0, 0] == 1).all()
    assert (path[:, -1, -1] == 1).all()


@pytest.mark.slow
def test_loss_runs_and_differentiates():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 6, 8).astype(np.float32))
    y = jnp.asarray(rng.randn(2, 5, 8).astype(np.float32))
    loss = dtw_loss(x, y)
    assert loss.shape == (2,)
    grad = jax.grad(lambda a: dtw_loss(a, y).sum())(x)
    assert np.isfinite(np.asarray(grad)).all()


def test_identical_sequences_give_most_negative_loss():
    """pos - neg is minimized (most negative) when the path collects
    near-zero cost, i.e. x == y."""
    rng = np.random.RandomState(3)
    x = rng.randn(1, 6, 8).astype(np.float32)
    same = float(dtw_loss(jnp.asarray(x), jnp.asarray(x))[0])
    other = float(dtw_loss(jnp.asarray(x),
                           jnp.asarray(rng.randn(1, 6, 8).astype(np.float32)))[0])
    assert same < other
