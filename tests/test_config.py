"""Config presets + CLI overrides (replaces args.py/args_small.py)."""

import json

import pytest

from milnce_tpu.config import (CONV_STAGES, parse_cli, parse_conv_impl_map,
                               small_preset, tiny_preset)


def test_full_defaults_match_reference_args():
    """Every behavioral default of /root/reference/args.py:3-52, pinned
    (path-like defaults excluded — environment leaks, SURVEY §2.4)."""
    cfg = parse_cli([])
    expected = {
        "optim.name": "adam",               # args.py:12
        "model.weight_init": "uniform",     # args.py:13
        "data.num_reader_threads": 20,      # args.py:14
        "model.embedding_dim": 512,         # args.py:15 --num_class
        "data.num_candidates": 5,           # args.py:16
        "train.batch_size": 128,            # args.py:17
        "train.num_windows_test": 4,        # args.py:18
        "train.batch_size_val": 32,         # args.py:19
        "optim.momentum": 0.9,              # args.py:20 (the typo'd --momemtum)
        "train.n_display": 400,             # args.py:21
        "data.num_frames": 32,              # args.py:22
        "data.video_size": 224,             # args.py:23
        "data.crop_only": True,             # args.py:24
        "data.center_crop": False,          # args.py:25
        "data.random_flip": True,           # args.py:26
        "train.verbose": True,              # args.py:27
        "optim.warmup_steps": 50_000,       # args.py:28
        "data.min_time": 5.0,               # args.py:29
        "data.fps": 10,                     # args.py:32
        "optim.epochs": 300,                # args.py:34
        "optim.lr": 1e-3,                   # args.py:36
        "train.resume": False,              # args.py:38
        "train.evaluate": False,            # args.py:39
        "train.seed": 1,                    # args.py:47
    }
    for key, want in expected.items():
        section, field = key.split(".")
        got = getattr(getattr(cfg, section), field)
        assert got == want, f"{key}: {got!r} != reference default {want!r}"


def test_small_preset_deltas():
    """Exactly the args_small.py deltas (diff vs args.py); everything
    else — input shapes included — stays at the full-run defaults."""
    cfg = small_preset()
    assert cfg.train.batch_size == 12          # args_small.py:17
    assert cfg.train.n_display == 100          # args_small.py:21
    assert cfg.optim.warmup_steps == 1000      # args_small.py:28
    assert cfg.optim.epochs == 100             # args_small.py:34
    assert cfg.data.num_frames == 32           # unchanged by args_small
    assert cfg.data.num_candidates == 5        # unchanged by args_small


def test_cli_overrides():
    cfg = parse_cli(["--preset", "small", "--optim.lr", "0.01",
                     "--train.batch_size", "64", "--data.random_flip", "false"])
    assert cfg.optim.lr == 0.01
    assert cfg.train.batch_size == 64
    assert cfg.data.random_flip is False
    assert cfg.optim.warmup_steps == 1000  # preserved from preset


def test_optional_int_fields_parse_as_int():
    cfg = parse_cli(["--parallel.num_processes", "4",
                     "--parallel.process_id", "0",
                     "--parallel.coordinator_address", "10.0.0.1:8476"])
    assert cfg.parallel.num_processes == 4 and isinstance(cfg.parallel.num_processes, int)
    assert cfg.parallel.process_id == 0 and isinstance(cfg.parallel.process_id, int)
    assert cfg.parallel.coordinator_address == "10.0.0.1:8476"


def test_tiny_preset_is_hermetic():
    cfg = tiny_preset()
    assert cfg.data.synthetic
    assert cfg.train.batch_size <= 8


class TestConvImplMap:
    """ModelConfig.conv_impl_map parsing: inline specs, autotune
    artifacts, and the typo-fails-at-config-time contract."""

    def test_empty_spec_is_empty_map(self):
        assert parse_conv_impl_map("") == {}

    def test_inline_spec(self):
        got = parse_conv_impl_map("conv1=im2col,mixed_3b=fold2d")
        assert got == {"conv1": "im2col", "mixed_3b": "fold2d"}

    def test_artifact_path(self, tmp_path):
        # the shape scripts/stage_probe.py --autotune writes
        art = {"generator": "scripts/stage_probe.py --autotune",
               "device": "TPU v5 lite",
               "impl_map": {"conv1": "im2col"},
               "stage_ms": {"conv1": {"native": {"fwdbwd": 266.0},
                                      "im2col": {"fwdbwd": 9.0}}}}
        path = tmp_path / "impl_map.json"
        path.write_text(json.dumps(art))
        assert parse_conv_impl_map(str(path)) == {"conv1": "im2col"}

    def test_raw_json_map_also_accepted(self, tmp_path):
        path = tmp_path / "map.json"
        path.write_text(json.dumps({"conv_2c": "fold2d"}))
        assert parse_conv_impl_map(str(path)) == {"conv_2c": "fold2d"}

    def test_unknown_stage_rejected(self):
        with pytest.raises(ValueError, match="unknown stage"):
            parse_conv_impl_map("conv9000=native")

    def test_unknown_impl_rejected(self):
        with pytest.raises(ValueError, match="unknown impl"):
            parse_conv_impl_map("conv1=winograd")

    def test_cli_override_reaches_model_config(self):
        cfg = parse_cli(["--model.conv_impl_map", "conv1=im2col"])
        assert cfg.model.conv_impl_map == "conv1=im2col"

    def test_stage_names_cover_the_probe_walk(self):
        # the map grain must match what scripts/stage_probe.py measures
        assert CONV_STAGES[:3] == ("conv1", "conv_2b", "conv_2c")
        assert len([s for s in CONV_STAGES if s.startswith("mixed_")]) == 9

    def test_artifact_round_trip_through_build_model(self, tmp_path):
        """config -> model -> autotune artifact -> reload: the emitted
        artifact drives build_model and the per-stage resolution."""
        from milnce_tpu.models.build import build_model

        art = {"generator": "scripts/stage_probe.py --autotune",
               "impl_map": {"conv1": "im2col", "mixed_5c": "fold2d"}}
        path = tmp_path / "impl_map.json"
        path.write_text(json.dumps(art))
        cfg = small_preset().model
        cfg.conv_impl_map = str(path)
        model = build_model(cfg)
        assert model.conv_impl_map == (("conv1", "im2col"),
                                       ("mixed_5c", "fold2d"))
