"""Config presets + CLI overrides (replaces args.py/args_small.py)."""

from milnce_tpu.config import parse_cli, small_preset, tiny_preset


def test_full_defaults_match_reference_args():
    cfg = parse_cli([])
    # args.py defaults
    assert cfg.train.batch_size == 128
    assert cfg.optim.lr == 1e-3
    assert cfg.optim.warmup_steps == 50_000
    assert cfg.data.fps == 10
    assert cfg.data.num_frames == 32
    assert cfg.data.video_size == 224
    assert cfg.data.num_candidates == 5
    assert cfg.model.embedding_dim == 512


def test_small_preset_deltas():
    cfg = small_preset()
    assert cfg.train.batch_size == 12
    assert cfg.optim.warmup_steps == 1000
    assert cfg.optim.epochs == 100
    assert cfg.data.num_frames == 16


def test_cli_overrides():
    cfg = parse_cli(["--preset", "small", "--optim.lr", "0.01",
                     "--train.batch_size", "64", "--data.random_flip", "false"])
    assert cfg.optim.lr == 0.01
    assert cfg.train.batch_size == 64
    assert cfg.data.random_flip is False
    assert cfg.optim.warmup_steps == 1000  # preserved from preset


def test_optional_int_fields_parse_as_int():
    cfg = parse_cli(["--parallel.num_processes", "4",
                     "--parallel.process_id", "0",
                     "--parallel.coordinator_address", "10.0.0.1:8476"])
    assert cfg.parallel.num_processes == 4 and isinstance(cfg.parallel.num_processes, int)
    assert cfg.parallel.process_id == 0 and isinstance(cfg.parallel.process_id, int)
    assert cfg.parallel.coordinator_address == "10.0.0.1:8476"


def test_tiny_preset_is_hermetic():
    cfg = tiny_preset()
    assert cfg.data.synthetic
    assert cfg.train.batch_size <= 8
