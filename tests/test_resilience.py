"""Chaos tests: every recovery path driven under an injected fault
(ISSUE 3).  Each fault site in resilience/faults.py has a tier-1 test
proving the run SURVIVES, the response matches the ROBUSTNESS.md matrix,
and training reaches max_steps with the fault armed — plus unit coverage
of the registry, the watchdog, the finite guard, the circuit breaker,
the checkpoint retry, the failure-rate abort, and the orphan reaper.

Pinned tier-1 (never @slow) by tests/test_suite_hygiene.py: these ARE
the permanent regression harness for the failure paths, including PRs
1-2's hot-path guarantees holding *under* faults (run_training's
transfer guard stays armed throughout; the guarded step's collective
counts are pinned with injection enabled)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset
from milnce_tpu.resilience import faults
from milnce_tpu.resilience.faults import FaultRegistry, InjectedFault


# --------------------------------------------------------------------------
# fault registry
# --------------------------------------------------------------------------

class TestFaultRegistry:
    def test_spec_grammar(self):
        reg = FaultRegistry("decode.raise@1,3;decode.hang@%2:x=0.5;"
                            "grad.nonfinite@*")
        assert reg.sites["decode.raise"].at == (1, 3)
        assert reg.sites["decode.hang"].every == 2
        assert reg.sites["decode.hang"].x == 0.5
        assert reg.sites["grad.nonfinite"].mode == "all"

    @pytest.mark.parametrize("bad", [
        "decode.raise",                  # missing @sched
        "no.such.site@1",                # unknown site
        "decode.raise@0",                # 0-based index
        "decode.raise@%0",               # every-0
        "decode.hang@1:y=3",             # unknown parameter
    ])
    def test_bad_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            FaultRegistry(bad)

    def test_hit_scheduling_is_deterministic(self):
        reg = FaultRegistry("decode.raise@2,4")
        fired = [reg.fire("decode.raise") is not None for _ in range(5)]
        assert fired == [False, True, False, True, False]
        # unarmed site never fires and costs nothing
        assert reg.fire("ckpt.save_ioerror") is None

    def test_disarmed_sites_are_noops(self):
        faults.disarm()
        faults.maybe_raise("decode.raise")      # must not raise
        faults.maybe_hang("decode.hang")        # must not sleep
        assert faults.device_schedule("grad.nonfinite") is None

    def test_armed_context_raises_and_disarms(self):
        with faults.armed("decode.raise@1"):
            with pytest.raises(InjectedFault):
                faults.maybe_raise("decode.raise")
            faults.maybe_raise("decode.raise")  # occurrence 2: clean
        faults.maybe_raise("decode.raise")      # disarmed again

    def test_env_arming(self, monkeypatch):
        monkeypatch.setattr(faults, "_registry", None)
        monkeypatch.setattr(faults, "_env_checked", False)
        monkeypatch.setenv(faults.ENV_VAR, "decode.raise@1")
        with pytest.raises(InjectedFault):
            faults.maybe_raise("decode.raise")
        faults.disarm()

    def test_exception_class_is_callers_choice(self):
        with faults.armed("ckpt.save_ioerror@1"):
            with pytest.raises(OSError):
                faults.maybe_raise("ckpt.save_ioerror", OSError)


# --------------------------------------------------------------------------
# decode watchdog (loader level)
# --------------------------------------------------------------------------

class _HangingSource:
    """Synthetic-shaped source whose chosen draws sleep: a stand-in for a
    wedged decode pipe, below the fault-site layer so the watchdog can be
    unit-tested without a manifest."""

    def __init__(self, cfg, hang_first_n=0, hang_idx=None, sleep=2.0):
        from milnce_tpu.data.synthetic import SyntheticVideoTextSource

        self.inner = SyntheticVideoTextSource(cfg, num_samples=32)
        self.hang_first_n = hang_first_n
        self.hang_idx = hang_idx
        self.sleep = sleep
        self._lock = threading.Lock()
        self._calls = 0

    def __len__(self):
        return len(self.inner)

    def fallback_sample(self):
        return self.inner.fallback_sample()

    def sample(self, idx, rng):
        with self._lock:
            self._calls += 1
            n = self._calls
        if n <= self.hang_first_n or (self.hang_idx is not None
                                      and idx == self.hang_idx):
            time.sleep(self.sleep)
        return self.inner.sample(idx, rng)


def test_watchdog_retry_recovers_from_one_hang():
    from milnce_tpu.data.pipeline import ShardedLoader

    cfg = tiny_preset()
    src = _HangingSource(cfg.data, hang_first_n=1, sleep=2.0)
    loader = ShardedLoader(src, 4, seed=0, num_threads=2, process_index=0,
                           process_count=1, sample_timeout=0.2,
                           timeout_retries=2)
    batch = next(iter(loader.epoch(0)))
    assert batch["video"].shape[0] == 4
    assert loader.decode_timeouts >= 1
    # the retried decode succeeded: no black-frame fallback needed
    assert all(batch["video"][i].sum() > 0 for i in range(4))


def test_watchdog_escalates_to_black_frame_fallback():
    """An index whose EVERY decode attempt hangs is unrecoverable: after
    the retries, the watchdog escalates to the source's black-frame
    fallback and the batch still comes out full."""
    from milnce_tpu.data.pipeline import ShardedLoader

    class AlwaysHangOnOne(_HangingSource):
        def sample(self, idx, rng):
            if idx == self.hang_idx:
                time.sleep(self.sleep)
            return self.inner.sample(idx, rng)

    cfg = tiny_preset()
    order = np.arange(32)
    np.random.RandomState(0 + 0).shuffle(order)      # seed + epoch
    src = AlwaysHangOnOne(cfg.data, hang_idx=int(order[1]), sleep=4.0)
    loader = ShardedLoader(src, 4, seed=0, num_threads=2, process_index=0,
                           process_count=1, sample_timeout=0.1,
                           timeout_retries=1)
    gen = loader.epoch(0)
    batch = next(gen)
    gen.close()
    assert batch["video"].shape[0] == 4
    assert loader.decode_timeouts >= 2  # initial + retry both timed out
    # exactly the wedged row fell back to black frames
    assert any(batch["video"][i].sum() == 0 for i in range(4))
    assert sum(batch["video"][i].sum() > 0 for i in range(4)) == 3


def test_watchdog_off_by_default_in_direct_loader_use():
    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource

    cfg = tiny_preset()
    loader = ShardedLoader(SyntheticVideoTextSource(cfg.data), 4)
    assert loader.sample_timeout == 0.0


# --------------------------------------------------------------------------
# orphaned decoder subprocesses
# --------------------------------------------------------------------------

def test_kill_inflight_decoders_reaps_registered_children():
    import subprocess

    from milnce_tpu.data import video as video_mod

    proc = subprocess.Popen(["sleep", "30"])
    video_mod._register_inflight(proc)
    try:
        assert video_mod.kill_inflight_decoders() >= 1
        assert proc.wait(timeout=5) != 0    # terminated, not completed
    finally:
        video_mod._unregister_inflight(proc)


def test_ffmpeg_decode_child_registered_while_pumping(tmp_path):
    """A decode() in flight must be reapable: its child is in the
    registry for the duration of the pipe read, so a mid-epoch generator
    close kills it instead of orphaning a full decode."""
    from milnce_tpu.data import video as video_mod

    stub = tmp_path / "ffmpeg"
    # exec: the Popen child IS the sleeping process (like real ffmpeg),
    # not an sh wrapper whose orphan would keep the stdout pipe open
    stub.write_text("#!/bin/sh\nexec sleep 30\n")
    stub.chmod(0o755)
    dec = video_mod.FFmpegDecoder(binary=str(stub))
    result = {}

    def run():
        try:
            dec.decode("x.mp4", 0.0, 1.0, 10, 8)
        except Exception as exc:
            result["exc"] = exc

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        with video_mod._INFLIGHT_LOCK:
            if video_mod._INFLIGHT:
                break
        time.sleep(0.02)
    assert video_mod.kill_inflight_decoders() >= 1
    t.join(timeout=5)
    assert not t.is_alive(), "decode survived the reaper"
    assert isinstance(result.get("exc"), Exception)


def test_loader_close_reaps_inflight_children(monkeypatch):
    """The generator's finally must call the reaper (the satellite fix:
    cancel_futures drops queued work but not already-spawned children)."""
    from milnce_tpu.data import pipeline as pipeline_mod
    from milnce_tpu.data import video as video_mod
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource

    calls = {"n": 0}
    real = video_mod.kill_inflight_decoders
    monkeypatch.setattr(video_mod, "kill_inflight_decoders",
                        lambda *a, **k: (calls.__setitem__("n", calls["n"] + 1)
                                         or real(*a, **k)))
    cfg = tiny_preset()
    loader = pipeline_mod.ShardedLoader(
        SyntheticVideoTextSource(cfg.data, num_samples=16), 4, num_threads=2)
    gen = loader.epoch(0)
    next(gen)
    gen.close()
    assert calls["n"] == 1


# --------------------------------------------------------------------------
# dataset health: max_failure_rate + failure logging
# --------------------------------------------------------------------------

def _howto_fixture(tmp_path, n_rows=16):
    rows = ["video_path"] + [f"vid{i}.mp4" for i in range(n_rows)]
    (tmp_path / "train.csv").write_text("\n".join(rows) + "\n")
    (tmp_path / "captions").mkdir(exist_ok=True)
    for i in range(n_rows):
        (tmp_path / "captions" / f"vid{i}.json").write_text(json.dumps(
            {"start": [0.0, 6.0], "end": [5.0, 12.0],
             "text": ["pour the batter", "flip the pancake"]}))
    cfg = tiny_preset()
    cfg.data.train_csv = str(tmp_path / "train.csv")
    cfg.data.video_root = str(tmp_path)
    cfg.data.caption_root = str(tmp_path / "captions")
    cfg.data.synthetic = False
    cfg.data.decoder_backend = "fake"
    return cfg


def test_max_failure_rate_aborts_broken_dataset(tmp_path):
    from milnce_tpu.data.datasets import DataHealthError, HowTo100MSource
    from milnce_tpu.data.video import FakeDecoder

    class AlwaysBad(FakeDecoder):
        def decode(self, *a, **kw):
            raise RuntimeError("corrupt")

    cfg = _howto_fixture(tmp_path)
    cfg.data.max_failure_rate = 0.5
    src = HowTo100MSource(cfg.data, cfg.model, decoder=AlwaysBad())
    rng = np.random.RandomState(0)
    with pytest.raises(DataHealthError, match="max_failure_rate"):
        for i in range(16):
            src.sample(i % len(src), rng)
    # and the default black-frame behavior survives when DISABLED
    cfg.data.max_failure_rate = 1.0
    src2 = HowTo100MSource(cfg.data, cfg.model, decoder=AlwaysBad())
    for i in range(8):
        s = src2.sample(i, rng)
    assert s["video"].sum() == 0


def test_failure_details_route_through_log_fn(tmp_path):
    from milnce_tpu.data.datasets import HowTo100MSource
    from milnce_tpu.data.video import FakeDecoder

    class BadOnce(FakeDecoder):
        def __init__(self):
            super().__init__()
            self.raised = False

        def decode(self, *a, **kw):
            if not self.raised:
                self.raised = True
                raise RuntimeError("corrupt")
            return super().decode(*a, **kw)

    cfg = _howto_fixture(tmp_path)
    lines = []
    src = HowTo100MSource(cfg.data, cfg.model, decoder=BadOnce(),
                          log_fn=lines.append)
    src.sample(0, np.random.RandomState(0))
    assert src.decode_failures == 1
    assert any("resampling" in ln for ln in lines), lines


# --------------------------------------------------------------------------
# chaos: the four fault sites through run_training (the acceptance gate)
# --------------------------------------------------------------------------

def _run_cfg(tmp_path, name):
    cfg = tiny_preset()
    cfg.model.inception_blocks = 1
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = 32
    cfg.data.num_reader_threads = 2
    cfg.train.checkpoint_root = str(tmp_path / f"ckpt_{name}")
    cfg.train.log_root = str(tmp_path / f"log_{name}")
    return cfg


def test_chaos_host_sites_combined_run_survives(tmp_path, capsys):
    """decode.raise + decode.hang + ckpt.save_ioerror armed TOGETHER in
    one production run over the real HowTo100M source stack (fake
    decoder backend): the source resamples the corrupt decodes (counted,
    surfaced in the display line — satellite), the watchdog times the
    wedged decode out and retries, the exit checkpoint save survives its
    first-attempt IOError via retry, and training reaches max_steps.
    One run, three fault sites — each with its own evidence."""
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "hostsites")
    hcfg = _howto_fixture(tmp_path)
    cfg.data = hcfg.data
    cfg.data.num_reader_threads = 2
    cfg.data.sample_timeout = 0.3
    cfg.data.sample_timeout_retries = 2
    cfg.train.faults = ("decode.raise@1,2;decode.hang@3:x=3.0;"
                        "ckpt.save_ioerror@1")
    res = run_training(cfg, max_steps=2)
    assert res.steps == 2 and np.isfinite(res.last_loss)
    out = capsys.readouterr().out
    assert "Decode failures: 2" in out, out       # decode.raise resampled
    assert "Decode timeouts:" in out, out         # decode.hang watchdogged
    assert faults._active() is None               # config arming disarmed
    mgr = CheckpointManager(str(tmp_path / "ckpt_hostsites" / "run"),
                            create=False)
    assert mgr.latest_epoch() is not None         # retried save committed
    mgr.close()


def test_chaos_grad_nonfinite_guard_skips_and_run_survives(tmp_path, capsys):
    """grad.nonfinite armed at step 2: the finite guard skips exactly
    that update (device-side, under the steady-state transfer guard —
    a smuggled host sync would raise) and training reaches max_steps."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "gnan")
    cfg.train.faults = "grad.nonfinite@2"
    res = run_training(cfg, max_steps=3)
    assert res.steps == 3 and np.isfinite(res.last_loss)
    assert res.skipped_steps == 1
    assert res.rollbacks == 0
    assert "Skipped steps: 1" in capsys.readouterr().out


def test_ckpt_save_retry_exhaustion_reraises(tmp_path):
    import jax.numpy as jnp
    import optax

    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.state import create_train_state

    variables = {"params": {"w": np.ones((4,), np.float32)}}
    state = create_train_state(variables, optax.sgd(1e-2))
    mgr = CheckpointManager(str(tmp_path / "run"), keep=2,
                            save_retries=1, retry_backoff=0.01)
    with faults.armed("ckpt.save_ioerror@*"):
        with pytest.raises(OSError):
            mgr.save(1, state)
    # transient single failure: retried and committed
    with faults.armed("ckpt.save_ioerror@1"):
        mgr.save(1, state)
    mgr.wait()
    assert mgr.latest_epoch() == 1
    mgr.close()


def test_chaos_circuit_breaker_rolls_back_and_resumes(tmp_path, capsys):
    """Every step non-finite: after K consecutive skips the breaker
    restores the rotation checkpoint and resumes PAST the poisoned
    window (instead of halting); the run still reaches max_steps."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "breaker")
    cfg.optim.epochs = 2
    first = run_training(cfg, max_steps=2)          # clean run: rotation ckpt
    assert first.steps == 2 and first.rollbacks == 0

    cfg.train.resume = True
    cfg.train.faults = "grad.nonfinite@*"
    cfg.train.skip_rollback_after = 2
    cfg.train.n_display = 2
    res = run_training(cfg, max_steps=3)
    assert res.steps == 3
    assert res.skipped_steps == 3                   # every update skipped
    assert res.rollbacks >= 1
    assert "circuit breaker" in capsys.readouterr().out


def test_breaker_halts_after_fruitless_rollback(tmp_path):
    """Persistent non-finite gradients (every step, forever) must
    TERMINATE: a second breaker trip with zero applied updates since the
    previous rollback proves the failure isn't a data window — halt
    instead of looping rollback-skip-rollback for the rest of the pod
    budget."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "fruitless")
    cfg.optim.epochs = 4
    first = run_training(cfg, max_steps=2)          # rotation checkpoint
    assert first.rollbacks == 0
    cfg.train.resume = True
    cfg.train.faults = "grad.nonfinite@*"
    cfg.train.skip_rollback_after = 2
    cfg.train.n_display = 2
    with pytest.raises(FloatingPointError, match="persistent"):
        run_training(cfg, max_steps=50)


def test_breaker_without_checkpoint_halts(tmp_path):
    """Poisoned from step 1 with nothing to roll back to: the breaker
    must halt loudly, not spin forever."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "nockpt")
    cfg.train.faults = "grad.nonfinite@*"
    cfg.train.skip_rollback_after = 2
    cfg.train.n_display = 2
    with pytest.raises(FloatingPointError, match="no rotation checkpoint"):
        run_training(cfg, max_steps=8)


# --------------------------------------------------------------------------
# finite guard: step-level semantics + trace invariants under injection
# --------------------------------------------------------------------------

def _tiny_step_setup():
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import OptimConfig, ParallelConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    video = np.random.default_rng(0).integers(
        0, 255, (8, 4, 32, 32, 3), dtype=np.uint8)
    text = np.zeros((8, 5), np.int32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2,) + video.shape[1:], jnp.float32),
                           text[:2])
    ocfg = OptimConfig(name="adam", warmup_steps=1)
    opt = build_optimizer(ocfg, build_schedule(ocfg, 10))
    state = create_train_state(variables, opt)
    mesh = build_mesh(ParallelConfig())
    return model, opt, mesh, state, video, text


def test_finite_guard_skips_poisoned_update_keeps_clean_ones():
    import jax

    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, video, text = _tiny_step_setup()
    zeros = np.zeros((8,), np.float32)
    with faults.armed("grad.nonfinite@2"):
        step = make_train_step(model, opt, mesh, donate=False,
                               finite_guard=True)
        s1, loss1, sk1 = step(state, video, text, zeros)    # occurrence 1
        s2, loss2, sk2 = step(s1, video, text, zeros)       # occurrence 2: hit
        s3, loss3, sk3 = step(s2, video, text, zeros)       # occurrence 3
    assert (int(sk1), int(sk2), int(sk3)) == (0, 1, 0)
    # the poisoned step kept params bit-identical and still advanced step
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (int(s1.step), int(s2.step), int(s3.step)) == (1, 2, 3)
    # the clean step after the skip really updated
    changed = [not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(jax.tree_util.tree_leaves(s2.params),
                               jax.tree_util.tree_leaves(s3.params))]
    assert any(changed)
    assert all(np.isfinite(float(l)) for l in (loss1, loss2, loss3))


def test_guarded_step_collectives_unchanged_under_injection():
    """The acceptance pin: arming grad.nonfinite must not change the
    step's communication structure (no new collectives, hence no new
    sync points) — the injection is pure jnp on state.step."""
    import jax

    from milnce_tpu.analysis.trace_invariants import (EXPECTED_COLLECTIVES,
                                                      collective_counts,
                                                      f64_sites, _setup)
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    with faults.armed("grad.nonfinite@*"):
        step = make_train_step(model, opt, mesh, donate=False,
                               finite_guard=True)
        jaxpr = jax.make_jaxpr(step)(state, *batch()).jaxpr
    assert (collective_counts(jaxpr)
            == EXPECTED_COLLECTIVES["train_step_milnce_guarded"])
    assert f64_sites(jaxpr) == []


# --------------------------------------------------------------------------
# checkpoint fallback branches + nan_postmortem isolation (satellite)
# --------------------------------------------------------------------------

def test_restore_fallback_reinit_vs_reraise_fast(tmp_path):
    """Tier-1 (model-free) pin of restore_latest's discrimination: an
    optimizer-structure evolution falls back to weights-only restore; a
    params mismatch re-raises (the slow tier covers the full-model
    variants in test_train.py)."""
    import jax
    import jax.numpy as jnp
    import optax

    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.state import create_train_state

    variables = {"params": {"w": np.ones((4,), np.float32),
                            "b": np.zeros((2,), np.float32)}}
    old_state = create_train_state(variables, optax.adam(1e-3)).replace(
        step=jnp.asarray(5, jnp.int32))
    mgr = CheckpointManager(str(tmp_path / "run"), keep=2)
    mgr.save(2, old_state)
    mgr.close()

    # optimizer tree evolved (chain wrapper): weights-only fallback
    new_opt = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3))
    template = create_train_state(variables, new_opt)
    mgr2 = CheckpointManager(str(tmp_path / "run"), keep=2, create=False)
    epoch, restored = mgr2.restore_latest(template)
    assert epoch == 2 and int(restored.step) == 5
    assert (jax.tree_util.tree_structure(restored.opt_state)
            == jax.tree_util.tree_structure(template.opt_state))

    # params tree changed (model evolved): NOT rescuable — re-raise
    bad_vars = {"params": {"w": np.ones((4,), np.float32)}}
    bad_template = create_train_state(bad_vars, new_opt)
    mgr3 = CheckpointManager(str(tmp_path / "run"), keep=2, create=False)
    with pytest.raises((ValueError, KeyError, TypeError)):
        mgr3.restore_latest(bad_template)


def test_nan_postmortem_isolated_from_rotation(tmp_path):
    """finite_guard OFF + halt_on_nan: the legacy divergence guard still
    halts, snapshotting into nan_postmortem/ WITHOUT touching the
    rotation directory — a later --resume must not restore NaN params."""
    from milnce_tpu.train.loop import run_training

    cfg = _run_cfg(tmp_path, "postmortem")
    cfg.train.finite_guard = False
    cfg.train.faults = "grad.nonfinite@1"
    with pytest.raises(FloatingPointError, match="non-finite"):
        run_training(cfg, max_steps=4)
    run_dir = tmp_path / "ckpt_postmortem" / "run"
    pm = run_dir / "nan_postmortem"
    assert pm.is_dir() and any(p.name.isdigit() for p in pm.iterdir())
    rotation = [p for p in run_dir.iterdir() if p.name.isdigit()]
    assert not rotation, f"NaN state leaked into the rotation: {rotation}"


def test_resume_and_stop_label_math():
    """The epoch-boundary edge cases of the mid-epoch resume math
    (satellite): offsets and checkpoint labels, as pure functions."""
    from milnce_tpu.train.loop import resume_batch_offset, stop_save_label

    assert resume_batch_offset(0, 4) == 0
    assert resume_batch_offset(3, 4) == 3
    assert resume_batch_offset(4, 4) == 0        # boundary: nothing to skip
    assert resume_batch_offset(9, 4) == 1
    # mid-epoch stop: current epoch, forced (label collides with the
    # previous boundary save)
    assert stop_save_label(0, 2, 4) == (0, True)
    assert stop_save_label(1, 6, 4) == (1, True)
    # stop ON the boundary: epoch+1, ordinary save
    assert stop_save_label(0, 4, 4) == (1, False)
    assert stop_save_label(1, 8, 4) == (2, False)
