"""Real-video train->eval loop (VERDICT r3 #5): actual encoded mp4
bytes through the production pipeline — Cv2Decoder container decode,
HowTo100M-style caption JSON -> MIL candidate windows, sharded MIL-NCE
train step, Orbax checkpoint, and the real youcook eval CLI on held-out
videos.  No FakeDecoder and no synthetic in-memory source anywhere.

The committed 300-step run (REAL_TRAIN.md, scripts/real_train_eval.py)
is the full-size record: loss 3.38 -> 1.62, held-out R@1 0.062 (chance)
-> 0.562, MR 8.5 -> 1.0.  This test runs the same script scaled down
(4 classes x 6 videos, 80 steps) in a subprocess WITHOUT the conftest's
8-virtual-device flag: the committed run trains one data shard, and
batch 8 split over 8 shards would give per-shard BatchNorm a single
sample — a different (and much noisier) training regime than the one
the thresholds were calibrated on (R@1 0.625, MR 1.0, loss -1.16).

Reference equivalent: train.py:70-225 on real HowTo100M -> the
README.md:114-129 table.
"""

import json
import os
import subprocess
import sys

import pytest

from multihost_child import subprocess_env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_real_video_corpus_training_learns_retrieval(tmp_path):
    pytest.importorskip("cv2")
    env = subprocess_env()
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "real_train_eval.py"),
         "--root", str(tmp_path / "corpus"), "--steps", "80",
         "--classes", "4", "--train_per_class", "6", "--eval_per_class", "2",
         "--batch", "8", "--json_out", str(report)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=1500)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rep = json.loads(report.read_text())

    # the loss moved substantially on real decoded content
    assert rep["final_loss"] < rep["first_loss"] - 0.5, rep
    # held-out retrieval through the eval CLI beats chance by >= 3x
    # (calibrated point: R@1 0.625 vs chance 0.125)
    assert rep["after"]["R1"] >= 3 * rep["chance_r1"], rep
    assert rep["after"]["MR"] <= 2.0, rep
    # and improved over the init checkpoint's ranking
    assert rep["after"]["MR"] < rep["before"]["MR"], rep


@pytest.mark.slow
def test_real_video_training_bf16_with_linear_probe(tmp_path):
    """The bench operating point's numerics actually train (VERDICT r4
    #3): the same real-mp4 loop with model.dtype=bfloat16 must show the
    same qualitative behavior as the calibrated f32 run — loss drop,
    held-out retrieval above chance — and the HMDB-style linear probe
    (VERDICT r4 #4: mixed_5c -> LinearSVC per split -> window-summed
    top-1, real decoded bytes end to end) must beat chance after
    training."""
    pytest.importorskip("cv2")
    pytest.importorskip("sklearn")
    env = subprocess_env()
    report = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "real_train_eval.py"),
         "--root", str(tmp_path / "corpus"), "--steps", "80",
         "--classes", "4", "--train_per_class", "6", "--eval_per_class", "2",
         "--batch", "8", "--dtype", "bfloat16", "--probe",
         "--json_out", str(report)],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rep = json.loads(report.read_text())

    # bf16 numerics track the f32 regime: substantial loss drop, no NaNs
    assert rep["final_loss"] < rep["first_loss"] - 0.5, rep
    # held-out retrieval through the eval CLI beats chance
    assert rep["after"]["R1"] >= 3 * rep["chance_r1"], rep
    assert rep["after"]["MR"] < rep["before"]["MR"], rep
    # the linear probe on real bytes separates the classes well above
    # chance (0.25 at 4 classes) once the trunk is trained
    assert rep["probe_after"]["mean"] >= 2 * rep["probe_chance"], rep
