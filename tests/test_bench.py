"""bench.py gate machinery: record forwarding, schema, and the
end-to-end CPU measurement child.

The bench is a driver gate — its one-JSON-line contract failing is
round-1's top verdict item — so its pure logic is unit-tested here and
the CPU child is exercised as a real subprocess.
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402


class TestLastJson:
    def test_picks_last_record(self):
        raw = (b'{"metric": "m", "value": 1.0, "unit": "u"}\n'
               b'{"metric": "m", "value": 2.0, "unit": "u"}\n')
        assert bench._last_json(raw)["value"] == 2.0

    def test_skips_non_record_json(self):
        # stray JSON-shaped log lines after the record must not win
        raw = (b'{"metric": "m", "value": 3.0}\n'
               b'{"event": "shutdown"}\n'
               b'not json at all\n')
        assert bench._last_json(raw)["value"] == 3.0

    def test_unparsable_tail_then_record(self):
        raw = b'garbage\n{"metric": "m", "value": 4.0}\n{"broken\n'
        assert bench._last_json(raw)["value"] == 4.0

    def test_no_record(self):
        assert bench._last_json(b"") is None
        assert bench._last_json(b"warning: something\n") is None


class TestMakeRecord:
    BEST = {"dtype": "bfloat16", "batch": 256, "remat": False, "s2d": False,
            "clips_per_sec_per_chip": 100.0, "mfu": 0.05}

    def test_schema_and_anchor(self):
        rec = bench._make_record(self.BEST, 16, 224, True, "TPU v5 lite")
        # ISSUE 5: the record is a milnce.obs/v1 document (diffable by
        # scripts/obs_report.py alongside serve benches)
        from milnce_tpu.obs.export import SNAPSHOT_SCHEMA
        assert rec["schema"] == SNAPSHOT_SCHEMA
        assert rec["kind"] == "train_bench"
        assert rec["unit"] == "clips/sec/chip"
        assert rec["value"] == 100.0
        assert rec["on_tpu"] is True
        assert rec["mfu"] == 0.05
        assert rec["vs_baseline"] == round(100.0 / bench.BASELINE_THROUGHPUT, 3)
        assert "16f@224" in rec["metric"] and "bfloat16" in rec["metric"]

    def test_cpu_fallback_vs_baseline_is_neutral(self):
        # a CPU number against a TPU anchor would be noise; pinned to 1.0
        rec = bench._make_record(self.BEST, 4, 64, False, "cpu")
        assert rec["vs_baseline"] == 1.0 and rec["on_tpu"] is False

    def test_s2d_flagged_in_metric(self):
        best = dict(self.BEST, s2d=True)
        rec = bench._make_record(best, 16, 224, True, "TPU v5 lite")
        assert "s2d stem" in rec["metric"]

    def test_predicted_peak_rides_into_the_obs_record(self):
        # ISSUE 8: the static HBM plan is a gate metric — obs_report
        # flags memory drift only if the record carries it (and a row
        # whose planner errored ships WITHOUT the field, never with 0)
        best = dict(self.BEST, predicted_peak_bytes_per_chip=123456789)
        rec = bench._make_record(best, 16, 224, True, "TPU v5 lite")
        assert rec["predicted_peak_bytes_per_chip"] == 123456789
        rec = bench._make_record(self.BEST, 16, 224, True, "TPU v5 lite")
        assert "predicted_peak_bytes_per_chip" not in rec

    def test_dtype_census_hash_rides_into_the_obs_record(self):
        # Pass 5: the precision fingerprint is how obs_report tells a
        # dtype change from a speedup — best-effort, so an errored
        # audit ships without the field, never with a fake hash
        best = dict(self.BEST, dtype_census_hash="abc123def456")
        rec = bench._make_record(best, 16, 224, True, "TPU v5 lite")
        assert rec["dtype_census_hash"] == "abc123def456"
        rec = bench._make_record(self.BEST, 16, 224, True, "TPU v5 lite")
        assert "dtype_census_hash" not in rec


def test_wedge_truncation_marks_partial(monkeypatch):
    """A config timeout followed by a dead re-probe must stop the sweep
    immediately, keep the measured rows, and stamp the final record with
    the partial marker (the orchestrator exits 0, so the parent's
    timeout marker never fires for this case)."""
    row = {"dtype": "bfloat16", "batch": 64, "remat": False, "s2d": False,
           "conv_impl": "native", "loss": "milnce", "inner": 4,
           "step_ms": 100.0, "clips_per_sec_per_chip": 50.0,
           "flops_per_step": None, "flops_source": None,
           "flops_per_sec": None}
    calls = {"n": 0}

    def fake_run_config(timeout_s=None, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            return dict(row, batch=kw["batch"])
        raise RuntimeError(f"config timeout>{timeout_s}s: {kw}")

    recs = []
    monkeypatch.setattr(bench, "_run_config", fake_run_config)
    monkeypatch.setattr(bench, "_probe_backend", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_emit", recs.append)
    monkeypatch.setattr(bench, "_write_notes",
                        lambda *a, **k: None)   # don't clobber the artifact

    rec = bench.run_bench(True, {"platform": "tpu", "kind": "TPU v5 lite",
                                 "n": 1})
    assert rec["partial"] == "tunnel wedged mid-sweep"
    assert rec["value"] == 50.0
    assert rec["on_tpu"] is True
    # wedge detected on call 2: no remat retry, no f32 plan, no extra rows
    assert calls["n"] == 2
    assert recs, "interim record for the measured row was never streamed"


def test_main_waits_for_tunnel_heal(monkeypatch):
    """A failed initial probe must not immediately mean CPU fallback:
    main re-probes within the MILNCE_BENCH_WAIT_HEAL budget and runs the
    TPU child once the tunnel answers (VERDICT r2: BENCH_r02.json was a
    CPU fallback recorded during a heal-able wedge)."""
    probes = {"n": 0}

    def flaky_probe(*a, **k):
        probes["n"] += 1
        if probes["n"] < 3:
            return None                  # wedged...
        return {"platform": "tpu", "kind": "TPU v5 lite", "n": 1}

    sleeps = []
    monkeypatch.setenv("MILNCE_BENCH_WAIT_HEAL", "700")
    monkeypatch.setattr(bench, "_probe_backend", flaky_probe)
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)

    # intercept the child launch: record which platform main chose
    import subprocess as sp
    launched = {}

    class FakeProc:
        returncode = 0
        stdout = None

        def wait(self, timeout=None):
            return 0

    def fake_popen(cmd, **kw):
        launched["env_child"] = kw.get("env", {}).get("MILNCE_BENCH_CHILD_MODE")
        p = FakeProc()
        import io
        p.stdout = io.BytesIO(
            b'{"metric": "train_step clips/sec/chip", "value": 1.0, '
            b'"unit": "clips/sec/chip", "vs_baseline": 0.01, '
            b'"_bench_record": true}\n')
        return p

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
    recs = []
    monkeypatch.setattr(bench, "_emit", recs.append)
    bench.main()
    assert probes["n"] == 3              # healed on the third probe
    assert len(sleeps) == 2              # slept between failed probes
    assert launched["env_child"] == "tpu"
    assert recs and recs[-1]["value"] == 1.0


def test_peak_flops_lookup():
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v4") == 275e12
    assert bench._peak_flops("cpu") is None


def test_parse_mesh_spec_grammar():
    # '' = 1-D data mesh; 'data,model[=N]' = 2-D grid (default width 2);
    # anything else fails at parse time like the config grammars
    assert bench._parse_mesh_spec("") == (None, 1)
    assert bench._parse_mesh_spec("data,model") == ("model", 2)
    assert bench._parse_mesh_spec("data,model=4") == ("model", 4)
    for bad in ("model,data", "data", "data,model,extra"):
        with pytest.raises(ValueError, match="mesh spec"):
            bench._parse_mesh_spec(bad)


@pytest.mark.slow
class TestConfigChild:
    """The per-config measurement grand-child protocol: one tagged JSON
    line per run, errors carried as data (the orchestrator's OOM /
    timeout handling matches on the text).  Each test spawns a fresh
    python that imports jax — slow-marked like the end-to-end child."""

    def test_device_info_cpu(self):
        info = bench._device_info(force_cpu=True)
        assert info["platform"] == "cpu" and info["n"] >= 1

    def test_run_config_error_text_propagates(self):
        # an impossible config must raise with the child's error text,
        # not hang or return a record
        with pytest.raises(RuntimeError) as exc_info:
            bench._run_config(timeout_s=300, platform_pin="cpu",
                              dtype="no_such_dtype", batch=1, frames=2,
                              size=8, words=4, k=2, remat=False, inner=1,
                              s2d=False, conv_impl="native", peak=None,
                              flops_hint=1.0)
        assert "no_such_dtype" in str(exc_info.value) or "TypeError" in str(
            exc_info.value) or "dtype" in str(exc_info.value)

    def test_dtw_row_serializes_with_loss_tag(self):
        # the sdtw_3 comparison row: result must round-trip through the
        # tagged-JSON protocol (regression: the warmup loss scalar once
        # shadowed the loss-name arg -> ArrayImpl in the record) and
        # carry no MFU/FLOPs (the analytic model doesn't count the DP)
        # batch must divide the forced 8-device CPU mesh the child sees
        r = bench._run_config(timeout_s=600, platform_pin="cpu",
                              dtype="float32", batch=16, frames=4, size=32,
                              words=4, k=2, remat=False, inner=1, s2d=False,
                              conv_impl="native", loss="sdtw_3", peak=None,
                              flops_hint=None)
        assert r["loss"] == "sdtw_3"
        assert r["flops_per_step"] is None and "mfu" not in r
        assert r["clips_per_sec_per_chip"] > 0
        json.dumps(r)

    def test_grad_accum_row_measures_embedding_cache_step(self):
        # the north-star recipe row: grad_accum>1 routes the measurement
        # through make_grad_cache_step; FLOPs/MFU are suppressed (the
        # plain-step model doesn't describe the two-pass program) and the
        # record carries the grad_accum tag for BENCH_NOTES
        r = bench._run_config(timeout_s=600, platform_pin="cpu",
                              dtype="float32", batch=16, frames=4, size=32,
                              words=4, k=2, remat=False, inner=1, s2d=False,
                              conv_impl="native", grad_accum=2, peak=None,
                              flops_hint=None)
        assert r["grad_accum"] == 2
        assert r["flops_per_step"] is None and "mfu" not in r
        assert r["clips_per_sec_per_chip"] > 0
        json.dumps(r)

    def test_mesh_2d_row_carries_layout_identity(self, monkeypatch):
        # the ISSUE 6 sweep axis: a 2-D row must record which layout and
        # which sharding map produced the number (mesh shape + map hash),
        # so obs_report compares like with like
        monkeypatch.setenv("MILNCE_BENCH_FSDP_MIN", "256")
        r = bench._run_config(timeout_s=600, platform_pin="cpu",
                              dtype="float32", batch=16, frames=4, size=32,
                              words=4, k=2, remat=False, inner=1, s2d=False,
                              conv_impl="native", mesh_spec="data,model",
                              peak=None, flops_hint=None)
        assert r["mesh"] == "4x2 (data,model)"
        assert r["params_sharded"] > 0
        assert len(r["sharding_map_hash"]) == 12
        assert r["clips_per_sec_per_chip"] > 0
        # ISSUE 8: every measured row carries its static HBM plan, and
        # the 2-D row's per-chip prediction reflects the FSDP sharding
        assert r["predicted_peak_bytes_per_chip"] > 0
        # Pass 5: and its precision fingerprint, so obs_report can flag
        # cross-precision compares
        assert len(r["dtype_census_hash"]) == 12
        json.dumps(r)

    def test_mesh_2d_row_refuses_pure_replication(self, monkeypatch):
        # a map that shards nothing must be REFUSED, not measured: paying
        # model-axis collectives for replication is not an FSDP data point
        monkeypatch.setenv("MILNCE_BENCH_FSDP_MIN", str(10 ** 9))
        with pytest.raises(RuntimeError, match="shards NOTHING"):
            bench._run_config(timeout_s=600, platform_pin="cpu",
                              dtype="float32", batch=16, frames=4, size=32,
                              words=4, k=2, remat=False, inner=1, s2d=False,
                              conv_impl="native", mesh_spec="data,model",
                              peak=None, flops_hint=None)

    def test_run_config_timeout_is_tagged(self):
        # a child that cannot finish inside the watchdog raises the
        # 'config timeout' marker the sweep's wedge detection keys on
        with pytest.raises(RuntimeError, match="config timeout"):
            bench._run_config(timeout_s=0.5, platform_pin="cpu",
                              dtype="float32", batch=1, frames=2, size=8,
                              words=4, k=2, remat=False, inner=1, s2d=False,
                              conv_impl="native", peak=None, flops_hint=1.0)


@pytest.mark.slow
def test_cpu_child_end_to_end():
    """The CPU measurement child — the gate's last line of defense before
    the error record — must emit at least one parsable record with a
    positive value (interim + final; the parent forwards the last)."""
    env = dict(os.environ)
    env["MILNCE_BENCH_CHILD_MODE"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, os.path.join(_REPO, "bench.py")],
                          env=env, cwd=_REPO, capture_output=True,
                          timeout=900)
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    rec = bench._last_json(proc.stdout)
    assert rec is not None, proc.stdout
    assert rec["value"] > 0 and rec["on_tpu"] is False
    assert rec["unit"] == "clips/sec/chip"
    # schema fields the driver relies on
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
