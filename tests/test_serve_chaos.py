"""Serving-path chaos suite (ISSUE 10): replica-pool failure isolation.

Three layers, mirroring how the pool is built:

- **jax-free unit chaos** over engine-shaped fakes: hedge
  first-result-wins determinism, loser-slot reclaim, requeue masking a
  flaky replica, the consecutive-error quarantine + probe recovery
  round trip, saturation, and the admission controller's shed rules —
  the state machine logic, fast and deterministic;
- **real-engine chaos** on 2 single-device replicas: every new fault
  site (``serve.dispatch_raise`` / ``serve.dispatch_hang`` /
  ``serve.replica_dead``) threaded through ``InferenceEngine._run``,
  surviving exactly as ROBUSTNESS.md's failure matrix promises, with
  recompiles pinned 0 on every surviving replica;
- **closed-loop chaos bench** (subprocess): the ISSUE acceptance pin —
  ``serve.dispatch_raise@%5`` armed and one replica force-killed
  mid-run, zero hung requests, dead replica quarantined and rerouted,
  errors bounded and structured, recompiles=0 on survivors.

All tier-1 (pinned never-slow by the suite_hygiene serving-chaos gate).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.resilience import faults
from milnce_tpu.serving.engine import ReplicaDead
from milnce_tpu.serving.pool import (DEGRADED, QUARANTINED, SERVING,
                                     PoolSaturated, PoolUnavailable,
                                     ReplicaPool)
from milnce_tpu.serving.service import (AdmissionController, DegradedError,
                                        RetrievalService, ShedError,
                                        serve_http)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FRAMES, _SIZE, _WORDS = 4, 32, 6


# ---------------------------------------------------------------------------
# engine-shaped fakes (jax-free: the pool only needs the embed surface)
# ---------------------------------------------------------------------------

class FakeEngine:
    """Deterministic engine stand-in: ``embed_*`` is a pure function of
    the rows (so first-result-wins hedging is CHECKABLE for value
    determinism), with injectable delay / scripted failures / death."""

    buckets = (4, 8)
    max_batch = 8
    text_words = 4
    embed_dim = 8

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.calls = 0
        self.fail_next = 0           # raise on the next N calls
        self._dead = False
        self._lock = threading.Lock()

    @property
    def dead(self) -> bool:
        return self._dead

    def kill(self) -> None:
        self._dead = True

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def embed_text(self, rows):
        if self._dead:
            raise ReplicaDead("fake replica is dead")
        with self._lock:
            self.calls += 1
            if self.fail_next > 0:
                self.fail_next -= 1
                raise RuntimeError("scripted dispatch failure")
            delay = self.delay_s
        if delay:
            time.sleep(delay)
        rows = np.asarray(rows)
        return np.tile(rows[:, :1].astype(np.float32), (1, self.embed_dim))

    embed_video = embed_text

    def recompiles(self):
        return 0

    def stats(self):
        return {"buckets": list(self.buckets), "max_batch": self.max_batch,
                "recompiles": 0, "dead": self._dead, "calls": {}}


def _fake_pool(n=2, **kwargs):
    engines = [FakeEngine() for _ in range(n)]
    kwargs.setdefault("probe_interval_s", 0.05)
    kwargs.setdefault("registry", obs_metrics.MetricsRegistry())
    return engines, ReplicaPool(engines, **kwargs)


def _rows(n=2, fill=3):
    return np.full((n, 4), fill, np.int32)


def _expected(rows, dim=8):
    return np.tile(np.asarray(rows)[:, :1].astype(np.float32), (1, dim))


# ---------------------------------------------------------------------------
# unit chaos: routing, requeue, quarantine/recovery, hedge, saturation
# ---------------------------------------------------------------------------

class TestPoolUnit:
    def test_requeue_masks_one_flaky_replica(self):
        engines, pool = _fake_pool(2)
        try:
            engines[0].fail_next = engines[1].fail_next = 0
            # whichever replica routes first fails once; the requeue to
            # the sibling must answer the caller
            engines[0].fail_next = 1
            engines[1].fail_next = 0
            out = pool.embed_text(_rows())
            np.testing.assert_array_equal(out, _expected(_rows()))
            # either the flaky replica was routed (requeue fired) or the
            # healthy one was — in both cases the request succeeded; force
            # the flaky path deterministically for the counter:
            engines[0].fail_next = engines[1].fail_next = 1
            with pytest.raises(RuntimeError, match="scripted"):
                # both replicas fail -> requeue exhausts -> caller sees it
                pool.embed_text(_rows())
            assert pool.counts()["requeued"] >= 1
        finally:
            pool.close()

    def test_consecutive_errors_quarantine_then_probe_recovers(self):
        engines, pool = _fake_pool(2, error_threshold=2, max_requeues=0)
        try:
            for e in engines:
                e.fail_next = 10**6
            for _ in range(4):          # 2 consecutive errors per replica
                with pytest.raises(RuntimeError):
                    pool.embed_text(_rows())
            states = {pool._replica_state(r) for r in pool.replicas}
            assert states == {QUARANTINED}
            with pytest.raises(PoolUnavailable):
                pool.embed_text(_rows())
            assert pool.counts()["quarantines"] == 2
            # heal the fakes; the background probe must recover both
            for e in engines:
                with e._lock:
                    e.fail_next = 0
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if all(pool._replica_state(r) == SERVING
                       for r in pool.replicas):
                    break
                time.sleep(0.02)
            assert all(pool._replica_state(r) == SERVING
                       for r in pool.replicas), "probe recovery timed out"
            assert pool.counts()["recoveries"] == 2
            assert pool.counts()["probes"] >= 2
            np.testing.assert_array_equal(pool.embed_text(_rows()),
                                          _expected(_rows()))
        finally:
            pool.close()

    def test_replica_dead_quarantines_immediately_and_probes_keep_failing(
            self):
        engines, pool = _fake_pool(2, error_threshold=5)
        try:
            engines[0].kill()
            engines[1].kill()
            with pytest.raises((ReplicaDead, PoolUnavailable)):
                pool.embed_text(_rows())
            # one dispatch error quarantines a DEAD replica (no
            # threshold wait), and probes never revive it
            time.sleep(0.3)
            dead_states = [pool._replica_state(r) for r in pool.replicas
                           if r.engine.dead]
            assert QUARANTINED in dead_states
            assert pool.counts()["probes"] >= 1
            assert pool.counts()["recoveries"] == 0
        finally:
            pool.close()

    def test_hedge_first_result_wins_is_value_deterministic(self):
        engines, pool = _fake_pool(2, hedge_quantile=0.1, hedge_min_ms=4.0,
                                   probe_interval_s=60.0)
        try:
            rows = _rows()
            for _ in range(20):          # prime the latency window
                pool.embed_text(rows)
            engines[0].delay_s = 0.4     # primary goes slow
            with pool._state_lock:       # force routing onto replica 0
                pool.replicas[1].state = DEGRADED
            t0 = time.monotonic()
            out = pool.embed_text(rows)
            dt = time.monotonic() - t0
            # the hedge (replica 1) answered long before the wedged
            # primary could have, and the value is EXACTLY the function
            # of the rows — whichever copy wins, the answer is the same
            np.testing.assert_array_equal(out, _expected(rows))
            assert dt < 0.3, f"hedge did not win ({dt:.3f}s)"
            counts = pool.counts()
            assert counts["hedged"] == 1
            assert counts["hedge_wins"] == 1
        finally:
            pool.close()

    def test_hedged_loser_queue_slot_is_reclaimed_unexecuted(self):
        engines, pool = _fake_pool(2, hedge_quantile=0.1, hedge_min_ms=4.0,
                                   probe_interval_s=60.0, queue_depth=8)
        try:
            rows = _rows()
            for _ in range(20):
                pool.embed_text(rows)
            calls_before = engines[0].calls + engines[1].calls
            engines[0].delay_s = 0.25
            with pool._state_lock:
                pool.replicas[1].state = DEGRADED
            # A executes on replica 0 (slow); B queues BEHIND it, gets
            # hedged to replica 1, and its stale copy on replica 0 must
            # be skipped when the worker finally reaches it
            fut_a = pool.submit_text(rows)
            fut_b = pool.submit_text(rows)
            np.testing.assert_array_equal(fut_b.result(timeout=5),
                                          _expected(rows))
            np.testing.assert_array_equal(fut_a.result(timeout=5),
                                          _expected(rows))
            deadline = time.monotonic() + 5.0
            while (time.monotonic() < deadline
                   and pool.counts()["reclaimed"] < 1):
                time.sleep(0.02)
            assert pool.counts()["reclaimed"] >= 1
            # the reclaimed copy never executed: 2 logical dispatches,
            # at most 3 executions (A on r0, B's hedge on r1, NOT B on r0)
            assert engines[0].calls + engines[1].calls <= calls_before + 3
        finally:
            pool.close()

    def test_all_queues_full_is_saturated_not_a_hang(self):
        engines, pool = _fake_pool(2, queue_depth=1, probe_interval_s=60.0)
        try:
            for e in engines:
                e.delay_s = 0.5
            futs = []
            t0 = time.monotonic()
            with pytest.raises(PoolSaturated) as exc_info:
                for _ in range(16):      # 2 executing + 2 queued, then boom
                    futs.append(pool.submit_text(_rows()))
            assert time.monotonic() - t0 < 2.0, "saturation must be instant"
            assert exc_info.value.retry_after_ms > 0
            assert pool.counts()["saturated"] >= 1
            for f in futs:               # everything admitted still resolves
                f.result(timeout=10)
        finally:
            pool.close()

    def test_inflight_registry_drains_to_empty(self):
        """Every resolved dispatch must leave the hedge monitor's
        in-flight registry — a submit-vs-worker race that re-added a
        resolved dispatch after its discard leaked it (and its padded
        rows) there forever."""
        _engines, pool = _fake_pool(2)
        try:
            for i in range(20):
                pool.embed_text(_rows(fill=i + 1))
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                with pool._state_lock:
                    if not pool._inflight:
                        break
                time.sleep(0.01)
            with pool._state_lock:
                assert not pool._inflight, (
                    f"{len(pool._inflight)} resolved dispatches leaked "
                    "in the in-flight registry")
        finally:
            pool.close()

    def test_raising_latency_observer_does_not_kill_the_worker_lane(self):
        """The service-injected on_latency callback runs on the worker
        thread AFTER the dispatch resolves; if it raises, the lane must
        survive (a dead worker would strand every queued dispatch while
        the replica still reads SERVING)."""
        _engines, pool = _fake_pool(1)
        try:
            def bad_observer(dur_ms, rows):
                raise RuntimeError("observer bug")

            pool.set_on_latency(bad_observer)
            np.testing.assert_array_equal(pool.embed_text(_rows()),
                                          _expected(_rows()))
            # the worker survived the observer's exception: still serving
            np.testing.assert_array_equal(
                pool.embed_text(_rows(fill=5)), _expected(_rows(fill=5)))
            assert pool._replica_state(pool.replicas[0]) == SERVING
        finally:
            pool.close()

    def test_pool_stats_shape(self):
        _engines, pool = _fake_pool(2)
        try:
            pool.embed_text(_rows())
            ps = pool.pool_stats()
            assert len(ps["replicas"]) == 2
            for rep in ps["replicas"]:
                for key in ("id", "state", "outstanding",
                            "consecutive_errors", "dispatches", "errors",
                            "last_probe_age_s", "dead", "recompiles"):
                    assert key in rep, f"pool replica stats missing {key}"
            for key in ("requeued", "hedged", "hedge_wins", "saturated",
                        "quarantines", "recoveries", "probes"):
                assert key in ps
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# admission controller: bounded global queue + deadline feasibility
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_overload_sheds_with_retry_hint(self):
        ac = AdmissionController(4, max_batch=4,
                                 registry=obs_metrics.MetricsRegistry())
        with ac.admit(3, None):
            with pytest.raises(ShedError) as exc_info:
                with ac.admit(2, None):
                    pass
            assert exc_info.value.reason == "overload"
            assert exc_info.value.retry_after_ms > 0
        # slots released on exit: admissible again
        with ac.admit(4, None):
            pass
        assert ac.stats()["shed"] == {"overload": 1}

    def test_deadline_infeasibility_needs_samples_and_is_provable(self):
        depth = [0]
        ac = AdmissionController(1000, max_batch=4, lanes=1,
                                 depth_fn=lambda: depth[0],
                                 registry=obs_metrics.MetricsRegistry())
        depth[0] = 40
        with ac.admit(1, 1.0):       # no flush samples yet: never sheds
            pass
        ac.observe_flush(50.0, 4)    # fastest dispatch ever seen: 50 ms
        with pytest.raises(ShedError) as exc_info:
            with ac.admit(1, 100.0):  # 10 batches ahead -> floor 500 ms
                pass
        assert exc_info.value.reason == "deadline_infeasible"
        assert exc_info.value.retry_after_ms >= 100.0
        with ac.admit(1, 1000.0):    # a feasible deadline passes
            pass
        with ac.admit(1, None):      # no deadline: feasibility can't shed
            pass

    def test_unarmed_controller_never_sheds(self):
        """max_inflight=0 disarms BOTH refusal conditions (the config.py
        contract: max_inflight 'arms the admission controller') — an
        unarmed service must not 429 on feasibility either."""
        depth = [40]
        ac = AdmissionController(0, max_batch=4, lanes=1,
                                 depth_fn=lambda: depth[0],
                                 registry=obs_metrics.MetricsRegistry())
        ac.observe_flush(50.0, 4)
        with ac.admit(1, 100.0):     # would shed if armed
            pass

    def test_admission_judges_the_effective_default_deadline(self):
        """Feasibility must see the deadline the batcher will actually
        apply: a client omitting timeout_ms still gets the service's
        default_timeout_ms judged at admission (a raw None would
        silently disable the check for every default-deadline client)."""
        service = RetrievalService(FakeEngine(), None, max_delay_ms=1.0,
                                   default_timeout_ms=123.0,
                                   registry=obs_metrics.MetricsRegistry())
        try:
            seen = []
            real_admit = service._admission.admit

            def spying_admit(rows, timeout_ms, tier=None):
                seen.append(timeout_ms)
                return real_admit(rows, timeout_ms, tier)

            service._admission.admit = spying_admit
            service.embed_text_ids(_rows(1))
            service.embed_text_ids(_rows(1, fill=4), timeout_ms=77.0)
            assert seen == [123.0, 77.0]
        finally:
            service.close()

    def test_pool_saturated_is_a_refusal_not_a_query_error(self):
        """PoolSaturated reaching the query path is a structured 429
        refusal — it must not inflate the unstructured query_errors
        counter (the error-rate gate's input)."""
        class _SaturatingEngine(FakeEngine):
            def embed_text(self, rows):
                raise PoolSaturated("full", retry_after_ms=5.0)

        class _FakeIndex:
            k = 5

            def topk(self, emb):
                n = emb.shape[0]
                return (np.zeros((n, 5), np.float32),
                        np.zeros((n, 5), np.int64))

            def stats(self):
                return {"size": 1}

        service = RetrievalService(_SaturatingEngine(), _FakeIndex(),
                                   max_delay_ms=1.0,
                                   registry=obs_metrics.MetricsRegistry())
        try:
            with pytest.raises(PoolSaturated):
                service.query_ids(_rows(1))
            assert service.health()["query_errors"] == 0
        finally:
            service.close()

    def test_shed_never_hangs_through_the_service(self):
        slow = FakeEngine(delay_s=1.0)
        service = RetrievalService(slow, None, max_delay_ms=1.0,
                                   registry=obs_metrics.MetricsRegistry(),
                                   max_inflight=1)
        try:
            started = threading.Event()

            def occupy():
                started.set()
                service.embed_text_ids(_rows(1))

            t = threading.Thread(target=occupy, daemon=True)
            t.start()
            started.wait()
            time.sleep(0.1)          # the occupant is admitted + in flight
            t0 = time.monotonic()
            with pytest.raises(ShedError):
                service.embed_text_ids(_rows(1, fill=9))
            assert time.monotonic() - t0 < 0.5, "shed must be instant"
            t.join(timeout=10)
        finally:
            service.close()


# ---------------------------------------------------------------------------
# HTTP error contract: structured bodies + Retry-After on 429/503/504
# ---------------------------------------------------------------------------

def _post(base, route, payload):
    req = urllib.request.Request(
        base + route, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=30)


class TestHTTPErrorContract:
    def test_shed_is_429_with_structured_body_and_header_healthz_never_sheds(
            self):
        slow = FakeEngine(delay_s=1.0)
        service = RetrievalService(slow, None, max_delay_ms=1.0,
                                   registry=obs_metrics.MetricsRegistry(),
                                   max_inflight=1)
        server = serve_http(service, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            started = threading.Event()

            def occupy():
                started.set()
                try:
                    _post(base, "/v1/embed_text",
                          {"token_ids": [[1, 1, 1, 1]]})
                except Exception:
                    pass
            t = threading.Thread(target=occupy, daemon=True)
            t.start()
            started.wait()
            time.sleep(0.15)
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                _post(base, "/v1/embed_text", {"token_ids": [[2, 2, 2, 2]]})
            err = exc_info.value
            assert err.code == 429
            body = json.loads(err.read())
            assert body["kind"] == "shed"
            assert body["reason"] == "overload"
            assert body["retry_after_ms"] > 0
            assert int(err.headers["Retry-After"]) >= 1
            # the observability plane NEVER sheds, even right now
            for route in ("/healthz", "/metrics"):
                with urllib.request.urlopen(base + route, timeout=30) as r:
                    assert r.status == 200
            t.join(timeout=10)
        finally:
            server.shutdown()
            server.server_close()
            service.close()

    def test_deadline_expiry_is_504_with_retry_hint(self):
        service = RetrievalService(FakeEngine(), None, max_delay_ms=40.0,
                                   registry=obs_metrics.MetricsRegistry())
        server = serve_http(service, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                _post(base, "/v1/embed_text",
                      {"token_ids": [[3, 3, 3, 3]], "timeout_ms": 1})
            err = exc_info.value
            assert err.code == 504
            body = json.loads(err.read())
            assert body["kind"] == "deadline_expired"
            assert body["retry_after_ms"] > 0
            assert int(err.headers["Retry-After"]) >= 1
        finally:
            server.shutdown()
            server.server_close()
            service.close()

    def test_degraded_ladder_cache_hits_answered_misses_503_then_full_503(
            self):
        engines, pool = _fake_pool(2, probe_interval_s=60.0)
        from milnce_tpu.serving.cache import EmbeddingLRUCache

        service = RetrievalService(pool, None,
                                   cache=EmbeddingLRUCache(64),
                                   max_delay_ms=1.0,
                                   registry=obs_metrics.MetricsRegistry())
        server = serve_http(service, port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            hot = [[5, 5, 5, 5]]
            with _post(base, "/v1/embed_text", {"token_ids": hot}) as r:
                cached = json.loads(r.read())["embeddings"]
            for e in engines:            # kill the whole pool
                e.kill()
            # drive a dispatch error so both replicas quarantine
            with pytest.raises(urllib.error.HTTPError):
                _post(base, "/v1/embed_text", {"token_ids": [[6, 6, 6, 6]]})
            # cache-only tier: the hot row still answers...
            with _post(base, "/v1/embed_text", {"token_ids": hot}) as r:
                assert json.loads(r.read())["embeddings"] == cached
            # ...a miss is a STRUCTURED 503
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                _post(base, "/v1/embed_text", {"token_ids": [[7, 7, 7, 7]]})
            err = exc_info.value
            assert err.code == 503
            body = json.loads(err.read())
            assert body["kind"] == "degraded"
            assert body["reason"] in ("cache_only", "no_healthy_replicas")
            assert int(err.headers["Retry-After"]) >= 1
            # /healthz stays up and surfaces the pool section
            with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
                h = json.loads(r.read())
            assert "pool" in h and len(h["pool"]["replicas"]) == 2
            assert {rep["state"] for rep in h["pool"]["replicas"]} \
                == {QUARANTINED}
            assert h["admission"]["max_inflight"] == 0
        finally:
            server.shutdown()
            server.server_close()
            service.close()
            pool.close()


# ---------------------------------------------------------------------------
# fault-site grammar
# ---------------------------------------------------------------------------

def test_serving_fault_sites_parse_and_unknown_still_rejected():
    spec = faults.parse_spec(
        "serve.dispatch_raise@%5;serve.dispatch_hang@1:x=0.5;"
        "serve.replica_dead@3")
    assert set(spec) == {"serve.dispatch_raise", "serve.dispatch_hang",
                         "serve.replica_dead"}
    assert spec["serve.dispatch_hang"].x == 0.5
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.parse_spec("serve.typo@*")


# ---------------------------------------------------------------------------
# real-engine chaos: the fault sites through InferenceEngine._run on a
# 2-replica pool (single-device engines, own dispatch locks)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def real_stack():
    import jax
    import jax.numpy as jnp

    from milnce_tpu.models import S3D

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, _FRAMES, _SIZE, _SIZE, 3)),
                           jnp.zeros((1, _WORDS), jnp.int32))
    pool = ReplicaPool.build(
        model, dict(variables), 2, text_words=_WORDS,
        video_shape=(_FRAMES, _SIZE, _SIZE, 3), max_batch=8, min_bucket=4,
        probe_interval_s=0.2, error_threshold=2,
        registry=obs_metrics.MetricsRegistry())
    yield dict(model=model, variables=variables, pool=pool)
    pool.close()


class TestRealEngineChaos:
    def _ids(self, n=4, seed=0):
        return np.random.default_rng(seed).integers(
            1, 64, (n, _WORDS)).astype(np.int32)

    def test_dispatch_raise_survives_via_requeue(self, real_stack):
        pool = real_stack["pool"]
        clean = pool.embed_text(self._ids())
        before = pool.counts()["requeued"]
        with faults.armed("serve.dispatch_raise@1"):
            out = pool.embed_text(self._ids())
        np.testing.assert_array_equal(out, clean)
        assert pool.counts()["requeued"] == before + 1
        assert all(pool._replica_state(r) != QUARANTINED
                   for r in pool.replicas)

    def test_dispatch_hang_slows_but_survives(self, real_stack):
        pool = real_stack["pool"]
        clean = pool.embed_text(self._ids(seed=1))
        with faults.armed("serve.dispatch_hang@1:x=0.4"):
            t0 = time.monotonic()
            out = pool.embed_text(self._ids(seed=1))
            dt = time.monotonic() - t0
        np.testing.assert_array_equal(out, clean)
        assert dt >= 0.4, "the hang site did not fire"
        assert pool.recompiles() == 0

    def test_quarantine_then_recovery_round_trip(self, real_stack):
        pool = real_stack["pool"]
        rec_before = pool.counts()["recoveries"]
        with faults.armed("serve.dispatch_raise@*"):
            outcomes = []
            for _ in range(6):
                try:
                    pool.embed_text(self._ids(1))
                    outcomes.append("ok")
                except Exception as exc:
                    outcomes.append(type(exc).__name__)
                if "PoolUnavailable" in outcomes:
                    break
            assert "PoolUnavailable" in outcomes, outcomes
            assert all(pool._replica_state(r) == QUARANTINED
                       for r in pool.replicas)
        # disarmed: probes must recover BOTH replicas within a few
        # intervals, and the recovered pool serves with zero recompiles
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(pool._replica_state(r) == SERVING
                   for r in pool.replicas):
                break
            time.sleep(0.05)
        assert all(pool._replica_state(r) == SERVING
                   for r in pool.replicas), "probe recovery timed out"
        assert pool.counts()["recoveries"] >= rec_before + 2
        assert pool.embed_text(self._ids()).shape[0] == 4
        assert pool.recompiles() == 0

    def test_replica_dead_reroutes_within_a_probe_interval(self,
                                                          real_stack):
        # fresh pool: this test leaves a permanently dead replica behind
        pool = ReplicaPool.build(
            real_stack["model"], dict(real_stack["variables"]), 2,
            text_words=_WORDS, video_shape=(_FRAMES, _SIZE, _SIZE, 3),
            max_batch=8, min_bucket=4, probe_interval_s=0.2,
            registry=obs_metrics.MetricsRegistry())
        try:
            clean = pool.embed_text(self._ids(seed=2))
            with faults.armed("serve.replica_dead@1"):
                out = pool.embed_text(self._ids(seed=2))
            # the request that KILLED a replica still answered (requeue),
            # bitwise-identical — replicas are exact peers
            np.testing.assert_array_equal(out, clean)
            dead = [r for r in pool.replicas if r.engine.dead]
            alive = [r for r in pool.replicas if not r.engine.dead]
            assert len(dead) == 1 and len(alive) == 1
            assert pool._replica_state(dead[0]) == QUARANTINED
            # traffic immediately reroutes to the survivor...
            for _ in range(3):
                np.testing.assert_array_equal(
                    pool.embed_text(self._ids(seed=2)), clean)
            # ...probes keep failing (death is permanent), and the
            # survivor never recompiled
            time.sleep(0.5)
            assert pool._replica_state(dead[0]) == QUARANTINED
            assert pool.counts()["recoveries"] == 0
            assert pool.recompiles() == 0
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# ISSUE acceptance: closed-loop chaos bench (subprocess)
# ---------------------------------------------------------------------------

def test_chaos_serve_bench_closed_loop_acceptance(tmp_path):
    """``serve.dispatch_raise@%5`` armed + one replica force-killed
    (``serve.replica_dead@25``) mid-run on a 2-replica pool: the
    closed-loop bench completes with zero hung requests (the run
    finishing inside its timeout IS the no-hang pin — every worker
    joins), the dead replica quarantined with traffic rerouted, errors
    bounded and structured (zero UNstructured errors), and recompiles=0
    on the surviving replica.  (Fast-child exemption in
    test_suite_hygiene.py: tiny preset + shared persistent compile
    cache, seconds-scale.)"""
    out = tmp_path / "SB_CHAOS.json"
    env = dict(os.environ)
    env.pop("MILNCE_FAULTS", None)
    # share the suite's persistent compile cache with the child (the
    # script itself doesn't configure one — production benches must
    # measure real compiles): warmup becomes disk hits after the first
    # run, keeping this acceptance child seconds-scale in tier-1
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "serve_bench.py"),
         "--backend", "cpu", "--preset", "tiny", "--mode", "closed",
         "--duration", "2", "--concurrency", "4", "--replicas", "2",
         "--max_batch", "8", "--min_bucket", "8",
         "--distinct", "0", "--corpus", "16",
         "--probe_interval_s", "0.2", "--max_requeues", "2",
         "--faults", "serve.dispatch_raise@%5;serve.replica_dead@25",
         "--out", str(out)],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, (
        f"chaos bench failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    report = json.loads(out.read_text())
    assert report["requests"] > 20, "the chaos window barely served"
    # unstructured failures bounded at ~zero: a raise-hit request either
    # answers via requeue or refuses STRUCTURED (503 degraded when only
    # the quarantined replica was left to retry on); at most a rare
    # interleaving can exhaust the requeue budget on back-to-back
    # scheduled occurrences
    assert report["errors"] <= 2, (report["errors"], proc.stdout)
    assert report["error_rate"] <= 0.01
    res = report["resilience"]
    assert res["requeued"] >= 1, "dispatch_raise@%5 never requeued"
    assert res["quarantines"] >= 1, "the dead replica never quarantined"
    replicas = report["pool"]["replicas"]
    dead = [r for r in replicas if r["dead"]]
    alive = [r for r in replicas if not r["dead"]]
    assert len(dead) == 1 and dead[0]["state"] == QUARANTINED
    # traffic rerouted: the survivor kept dispatching after the kill
    assert len(alive) == 1 and alive[0]["dispatches"] > dead[0]["dispatches"]
    # recompiles=0 on every surviving replica (pool recompiles sums
    # survivors; the per-replica stats pin it individually)
    assert report["engine"]["recompiles"] == 0
    assert alive[0]["recompiles"] == 0
