"""MIL-NCE loss: golden-value tests vs an independent numpy transcription of
the reference math (loss.py:10-18), plus sharded == unsharded on a virtual
8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.losses.milnce import milnce_loss
from milnce_tpu.parallel.compat import set_mesh, shard_map


def numpy_milnce(v, t):
    """Reference formula, straight from the math in loss.py:10-18."""
    b = v.shape[0]
    x = (v @ t.T).reshape(b, b, -1)                  # (B, B, K)
    nominator = x[np.arange(b), np.arange(b), :]     # (B, K)
    num = _logsumexp(nominator, axis=1)
    both = np.concatenate([x, x.transpose(1, 0, 2)], axis=1).reshape(b, -1)
    denom = _logsumexp(both, axis=1)
    return float(np.mean(denom - num))


def _logsumexp(a, axis):
    m = a.max(axis=axis, keepdims=True)
    return (m + np.log(np.exp(a - m).sum(axis=axis, keepdims=True))).squeeze(axis)


@pytest.mark.parametrize("b,k,d", [(4, 1, 8), (4, 3, 8), (6, 5, 16)])
def test_matches_reference_formula(b, k, d):
    rng = np.random.RandomState(0)
    v = rng.randn(b, d).astype(np.float32)
    t = rng.randn(b * k, d).astype(np.float32)
    ours = float(milnce_loss(jnp.asarray(v), jnp.asarray(t)))
    np.testing.assert_allclose(ours, numpy_milnce(v, t), rtol=1e-5)


def test_hand_computed_tiny_case():
    # B=2, K=1, D=1: x = [[1, 2], [2, 4]] (v=[1,2], t=[1,2] columns)
    v = jnp.array([[1.0], [2.0]])
    t = jnp.array([[1.0], [2.0]])
    x = np.array([[1.0, 2.0], [2.0, 4.0]])
    num = np.array([1.0, 4.0])
    denom = np.array([_logsumexp(np.array([1, 2, 1, 2.0]), 0),
                      _logsumexp(np.array([2, 4, 2, 4.0]), 0)])
    expected = float(np.mean(denom - num))
    np.testing.assert_allclose(float(milnce_loss(v, t)), expected, rtol=1e-6)


def test_sharded_equals_unsharded():
    devices = jax.devices()
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    mesh = Mesh(np.array(devices), ("data",))
    b, k, d = 16, 3, 32
    rng = np.random.RandomState(1)
    v = rng.randn(b, d).astype(np.float32)
    t = rng.randn(b * k, d).astype(np.float32)

    @jax.jit
    def sharded(v, t):
        return shard_map(
            lambda vv, tt: milnce_loss(vv, tt, axis_name="data"),
            mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P())(v, t)

    with set_mesh(mesh):
        out = sharded(jax.device_put(v, NamedSharding(mesh, P("data"))),
                      jax.device_put(t, NamedSharding(mesh, P("data"))))
    np.testing.assert_allclose(float(out), numpy_milnce(v, t), rtol=1e-5)


def test_sharded_gradients_match_unsharded():
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    b, k, d = 8, 2, 16
    rng = np.random.RandomState(2)
    v = rng.randn(b, d).astype(np.float32)
    t = rng.randn(b * k, d).astype(np.float32)

    ref_grad_v, ref_grad_t = jax.grad(
        lambda vv, tt: milnce_loss(vv, tt), argnums=(0, 1))(
            jnp.asarray(v), jnp.asarray(t))

    @jax.jit
    def sharded_grads(v, t):
        def local(vv, tt):
            gv, gt = jax.grad(
                lambda a, b_: milnce_loss(a, b_, axis_name="data"),
                argnums=(0, 1))(vv, tt)
            return gv, gt
        return shard_map(local, mesh=mesh,
                             in_specs=(P("data"), P("data")),
                             out_specs=(P("data"), P("data")))(v, t)

    with set_mesh(mesh):
        gv, gt = sharded_grads(jax.device_put(v, NamedSharding(mesh, P("data"))),
                               jax.device_put(t, NamedSharding(mesh, P("data"))))
    np.testing.assert_allclose(np.asarray(gv), np.asarray(ref_grad_v),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gt), np.asarray(ref_grad_t),
                               atol=1e-6)


def test_per_chip_memory_at_baseline_scale():
    """Compile-only memory proof at the baseline's global batch
    (SURVEY §7 hard part 4 / VERDICT r1 next #10): at Bg=8192, K=5 on an
    8-device mesh, the compiled per-chip temp footprint stays at the two
    local logits cubes O(B_local*Bg*K) — NOT the replicated O(Bg^2*K)
    cube (which alone would be 8192*8192*5*4 B = 1.3 TB)."""
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    bg, k, d = 8192, 5, 32
    b_local = bg // len(devices)

    @jax.jit
    def sharded(v, t):
        return shard_map(
            lambda vv, tt: milnce_loss(vv, tt, axis_name="data"),
            mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P())(v, t)

    v = jax.ShapeDtypeStruct((bg, d), jnp.float32,
                             sharding=NamedSharding(mesh, P("data")))
    t = jax.ShapeDtypeStruct((bg * k, d), jnp.float32,
                             sharding=NamedSharding(mesh, P("data")))
    with set_mesh(mesh):
        stats = sharded.lower(v, t).compile().memory_analysis()
    cube = b_local * bg * k * 4                      # one (B_local, Bg, K) f32
    # temp budget: rows + cols cubes + reduction scratch; flag anything
    # beyond 4 cubes (the old concat form needed ~6, replicated needs ~800)
    assert stats.temp_size_in_bytes <= 4 * cube, (
        f"per-chip temps {stats.temp_size_in_bytes/1e6:.0f} MB exceed "
        f"4 cubes ({4*cube/1e6:.0f} MB) — logits memory no longer "
        f"O(B_local*Bg*K)")


def test_scale_invariance_of_batch_position():
    """Permuting batch order permutes nothing about the mean loss."""
    rng = np.random.RandomState(3)
    b, k, d = 6, 2, 8
    v = rng.randn(b, d).astype(np.float32)
    t = rng.randn(b * k, d).astype(np.float32)
    perm = rng.permutation(b)
    t_resh = t.reshape(b, k, d)[perm].reshape(b * k, d)
    l1 = float(milnce_loss(jnp.asarray(v), jnp.asarray(t)))
    l2 = float(milnce_loss(jnp.asarray(v[perm]), jnp.asarray(t_resh)))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
