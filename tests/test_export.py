"""Params-only frozen export (ISSUE 4 satellite): training checkpoint ->
``milnce-export`` artifact -> serving loader round-trip, exactly."""

import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def train_ckpt(tmp_path_factory):
    """A saved tiny training checkpoint + the state that produced it."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import ModelConfig, OptimConfig
    from milnce_tpu.models.build import build_model
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    mcfg = ModelConfig(embedding_dim=16, vocab_size=128,
                       word_embedding_dim=8, text_hidden_dim=16,
                       inception_blocks=1)
    model = build_model(mcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4, 32, 32, 3)),
                           jnp.zeros((1, 6), jnp.int32))
    opt_cfg = OptimConfig(warmup_steps=2)
    opt = build_optimizer(opt_cfg, build_schedule(opt_cfg, 10))
    state = create_train_state(dict(variables), opt)
    ckpt_dir = str(tmp_path_factory.mktemp("run"))
    mgr = CheckpointManager(ckpt_dir, keep=2)
    mgr.save(0, state)
    mgr.wait()
    mgr.close()
    return dict(ckpt_dir=ckpt_dir, state=state, model_cfg=mcfg)


_CLI_MODEL_FLAGS = ["--model.embedding_dim", "16",
                    "--model.vocab_size", "128",
                    "--model.word_embedding_dim", "8",
                    "--model.text_hidden_dim", "16",
                    "--model.inception_blocks", "1",
                    "--data.max_words", "6"]


@pytest.fixture(scope="module")
def export_dir(train_ckpt, tmp_path_factory):
    from milnce_tpu.serving.export import main as export_main

    out = str(tmp_path_factory.mktemp("export"))
    export_main(["--checkpoint_dir", train_ckpt["ckpt_dir"], "--out", out,
                 "--preset", "tiny"] + _CLI_MODEL_FLAGS)
    return out


def test_round_trip_is_exact(train_ckpt, export_dir):
    """Every params + batch_stats leaf survives checkpoint -> export ->
    load bit-exactly (same tree paths, same values)."""
    import jax

    from milnce_tpu.serving.export import load_inference_checkpoint

    _meta, loaded = load_inference_checkpoint(export_dir)
    state = train_ckpt["state"]
    for name, orig, back in (("params", state.params, loaded["params"]),
                             ("batch_stats", state.batch_stats,
                              loaded["batch_stats"])):
        a = jax.tree_util.tree_leaves_with_path(orig)
        b = dict(jax.tree_util.tree_leaves_with_path(back))
        assert len(a) == len(b), name
        for path, leaf in a:
            assert np.array_equal(np.asarray(leaf), b[path]), (name, path)


def test_metadata_contract(export_dir):
    from milnce_tpu.serving.export import METADATA_FILE

    meta = json.load(open(os.path.join(export_dir, METADATA_FILE)))
    assert meta["format_version"] == 1
    assert "milnce_tpu/serving/export.py" in meta["generator"]
    assert meta["video_shape"] == [4, 32, 32, 3]        # tiny preset
    assert meta["tokenizer"]["max_words"] == 6
    assert meta["model"]["embedding_dim"] == 16
    assert meta["model"]["word2vec_path"] == ""         # sanitized
    assert meta["step"] == 0 and meta["param_bytes"] > 0
    # dtype manifest: one entry per shipped array, float leaves f32 by
    # construction (bf16 is a load-time cast), and the manifest must
    # agree with the npz it describes — the precision contract
    # scripts/precision_audit.py's quant-readiness report audits
    from milnce_tpu.serving.export import ARRAYS_FILE

    dtypes = meta["array_dtypes"]
    with np.load(os.path.join(export_dir, ARRAYS_FILE)) as z:
        assert sorted(dtypes) == sorted(z.files)
        for key in z.files:
            assert dtypes[key] == str(z[key].dtype), key
    assert all(v == "float32" for k, v in dtypes.items()
               if v.startswith("float")), dtypes


def test_no_optimizer_state_ships(export_dir):
    """The artifact is params-only: no Adam moments, and it is SMALLER
    than the float bytes of params+stats+2x-moments would be."""
    from milnce_tpu.serving.export import ARRAYS_FILE

    with np.load(os.path.join(export_dir, ARRAYS_FILE)) as z:
        keys = list(z.files)
    assert all(k.startswith(("params/", "batch_stats/")) for k in keys)
    assert not any("opt" in k for k in keys)


def test_engine_boots_from_export_and_serves(export_dir):
    import jax
    from jax.sharding import Mesh

    from milnce_tpu.serving.engine import InferenceEngine

    mesh = Mesh(np.array(jax.devices()), ("data",))
    engine = InferenceEngine.from_export(export_dir, mesh, max_batch=8)
    out = engine.embed_text(np.ones((2, 6), np.int32))
    assert out.shape == (2, 16) and np.isfinite(out).all()
    assert engine.recompiles() == 0


def test_bf16_cast_is_a_load_time_decision(export_dir):
    """One f32 artifact serves both precisions: dtype='bfloat16' casts
    params at load and the engine emits bf16 embeddings."""
    import jax
    from jax.sharding import Mesh

    from milnce_tpu.serving.engine import InferenceEngine

    mesh = Mesh(np.array(jax.devices()), ("data",))
    engine = InferenceEngine.from_export(export_dir, mesh, max_batch=8,
                                         dtype="bfloat16")
    out = engine.embed_text(np.ones((2, 6), np.int32))
    assert str(out.dtype) == "bfloat16" and np.isfinite(
        out.astype(np.float32)).all()


def test_format_version_gate(export_dir, tmp_path):
    import shutil

    from milnce_tpu.serving.export import (METADATA_FILE,
                                           load_inference_checkpoint)

    bad = tmp_path / "bad_export"
    shutil.copytree(export_dir, bad)
    meta_path = bad / METADATA_FILE
    meta = json.load(open(meta_path))
    meta["format_version"] = 999
    json.dump(meta, open(meta_path, "w"))
    with pytest.raises(ValueError, match="format"):
        load_inference_checkpoint(str(bad))