"""Edge-tier quantization subsystem (ISSUE 19): int8 calibrated
towers, the distilled text student, and heterogeneous replica classes.

Four regression fences:

- quantize -> export -> restore round-trips BIT-EXACTLY (int8 leaves
  and scales), and the v1 loader refuses the v2 artifact loudly;
- both edge artifacts (int8, student) boot through the serving engine
  and answer with recall@10 inside the stated degradation budgets
  against the f32 tower on a tiny synthetic corpus;
- a mixed ReplicaPool routes class-pinned requests STRICTLY (an edge
  pin never silently lands on an f32 replica, and vice versa);
- the NUMERICS.md readiness-verdict parser keeps reading the committed
  table the calibration defaults are seeded from.
"""

import os

import numpy as np
import pytest

_WORDS = 6
_FRAMES, _SIZE = 4, 32
_VIDEO_SHAPE = (_FRAMES, _SIZE, _SIZE, 3)
_CORPUS = 24

# Edge-tier recall@10 degradation budgets (SERVING.md "Edge tier"):
# each edge class's top-10 rankings against the f32 tower's on the
# tiny synthetic corpus must keep at least this mean overlap.  The
# committed serve_bench --tier-class records pin the same quantity at
# serving scale; obs_report gates drift.
INT8_RECALL_BUDGET = 0.80
STUDENT_RECALL_BUDGET = 0.50


@pytest.fixture(scope="module")
def tiny():
    """Tiny teacher: model + frozen f32 serving tree."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import ModelConfig
    from milnce_tpu.models.build import build_model

    mcfg = ModelConfig(embedding_dim=16, vocab_size=128,
                       word_embedding_dim=8, text_hidden_dim=16,
                       inception_blocks=1)
    model = build_model(mcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1,) + _VIDEO_SHAPE),
                           jnp.zeros((1, _WORDS), jnp.int32))
    frozen = {"params": variables["params"],
              "batch_stats": variables.get("batch_stats", {})}
    return dict(mcfg=mcfg, model=model, frozen=frozen)


@pytest.fixture(scope="module")
def f32_dir(tiny, tmp_path_factory):
    from milnce_tpu.serving.export import export_inference_checkpoint

    out = str(tmp_path_factory.mktemp("f32_export"))
    export_inference_checkpoint(
        out, tiny["frozen"]["params"], tiny["frozen"]["batch_stats"],
        tiny["mcfg"], max_words=_WORDS, video_shape=_VIDEO_SHAPE)
    return out


@pytest.fixture(scope="module")
def calibrated(tiny):
    """The full offline pass: quantized tree + calibration metadata."""
    from milnce_tpu.quant.calibrate import calibrate_and_quantize

    rng = np.random.default_rng(3)
    video = rng.integers(0, 255, (2,) + _VIDEO_SHAPE).astype(np.float32)
    tokens = rng.integers(1, 128, (4, _WORDS)).astype(np.int32)
    qvars, calibration = calibrate_and_quantize(
        tiny["model"], tiny["frozen"], video_batches=[video],
        text_batches=[tokens])
    return dict(qvars=qvars, calibration=calibration)


@pytest.fixture(scope="module")
def quant_dir(tiny, calibrated, tmp_path_factory):
    from milnce_tpu.serving.export import export_quantized_checkpoint

    out = str(tmp_path_factory.mktemp("quant_export"))
    export_quantized_checkpoint(
        out, calibrated["qvars"], tiny["mcfg"], max_words=_WORDS,
        video_shape=_VIDEO_SHAPE, calibration=calibrated["calibration"])
    return out


@pytest.fixture(scope="module")
def student(tiny):
    from milnce_tpu.quant.distill import (build_student_variables,
                                          distill_text_student,
                                          student_model_config)

    sparams, sinfo = distill_text_student(
        tiny["model"], tiny["frozen"], max_words=_WORDS, steps=80,
        batch_size=16)
    scfg = student_model_config(tiny["mcfg"], sinfo["hidden_dim"])
    svars = build_student_variables(tiny["frozen"], sparams)
    return dict(scfg=scfg, svars=svars, sinfo=sinfo)


@pytest.fixture(scope="module")
def student_dir(student, tmp_path_factory):
    from milnce_tpu.serving.export import export_inference_checkpoint

    out = str(tmp_path_factory.mktemp("student_export"))
    export_inference_checkpoint(
        out, student["svars"]["params"], student["svars"]["batch_stats"],
        student["scfg"], max_words=_WORDS, video_shape=_VIDEO_SHAPE,
        source="distilled text student (quant/distill.py)")
    return out


def _mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), ("data",))


# ---------------------------------------------------------------------------
# quantize: scheme + round-trip
# ---------------------------------------------------------------------------

class TestQuantize:
    def test_int8_where_quantizable_f32_elsewhere(self, calibrated):
        import jax

        qvars = calibrated["qvars"]
        scales = qvars["quant_scales"]
        assert scales, "nothing was quantized"
        flat = jax.tree_util.tree_leaves_with_path(qvars["params"])
        n_int8 = sum(np.asarray(leaf).dtype == np.int8
                     for _, leaf in flat)
        assert n_int8 == len(scales)
        for _, leaf in jax.tree_util.tree_leaves_with_path(
                qvars["batch_stats"]):
            assert np.asarray(leaf).dtype != np.int8

    def test_dequant_error_bounded_by_half_scale(self, tiny):
        """Symmetric int8 round-to-nearest: |x - q*s| <= s/2 per
        element (per-channel: that channel's scale)."""
        from milnce_tpu.quant.quantize import (quantize_array)

        rng = np.random.default_rng(0)
        arr = rng.standard_normal((12, 8)).astype(np.float32)
        arr[:, 0] *= 40.0                  # an outlier channel
        for per_channel in (False, True):
            q, scale = quantize_array(arr, per_channel=per_channel)
            assert q.dtype == np.int8
            err = np.abs(arr - q.astype(np.float32) * scale)
            assert (err <= np.asarray(scale) * 0.5 + 1e-7).all()

    def test_per_channel_verdicts_follow_readiness_rule(self, tiny):
        from milnce_tpu.quant.quantize import (
            per_channel_keys_from_weights, weight_readiness_row)

        keys = per_channel_keys_from_weights(tiny["frozen"]["params"])
        # the rule and the key set must agree leaf by leaf
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(
            tiny["frozen"]["params"])
        for path, leaf in flat:
            arr = np.asarray(leaf)
            if arr.ndim < 2:
                continue
            key = "params/" + "/".join(
                getattr(p, "key", str(p)) for p in path)
            row = weight_readiness_row(key, arr)
            assert (key in keys) == row["per_channel"], key


# ---------------------------------------------------------------------------
# export format v2
# ---------------------------------------------------------------------------

class TestQuantExport:
    def test_round_trip_is_bit_exact(self, calibrated, quant_dir):
        import jax

        from milnce_tpu.serving.export import load_quantized_checkpoint

        meta, loaded = load_quantized_checkpoint(quant_dir)
        qvars = calibrated["qvars"]
        a = jax.tree_util.tree_leaves_with_path(qvars["params"])
        b = dict(jax.tree_util.tree_leaves_with_path(loaded["params"]))
        assert len(a) == len(b)
        for path, leaf in a:
            orig = np.asarray(leaf)
            back = np.asarray(b[path])
            assert orig.dtype == back.dtype, path
            assert np.array_equal(orig, back), path
        assert sorted(loaded["quant_scales"]) == sorted(
            qvars["quant_scales"])
        for key, scale in qvars["quant_scales"].items():
            assert np.array_equal(np.asarray(scale, np.float32),
                                  loaded["quant_scales"][key]), key

    def test_metadata_contract(self, quant_dir):
        from milnce_tpu.serving.export import (ARRAYS_FILE,
                                               QUANT_FORMAT_VERSION,
                                               SCALES_PREFIX,
                                               read_export_metadata)

        meta = read_export_metadata(quant_dir)
        assert meta["format_version"] == QUANT_FORMAT_VERSION
        quant = meta["quant"]
        assert quant["scheme"] == "symmetric-int8"
        assert quant["n_quantized"] > 0
        # calibration block rode along (quality stats + ranges)
        assert quant["calibration"]["quality"]["text_cosine_mean"] > 0.9
        # dtype manifest covers every shipped array, int8 where the
        # scales say a leaf was quantized, f32 for the scales themselves
        dtypes = meta["array_dtypes"]
        with np.load(os.path.join(quant_dir, ARRAYS_FILE)) as z:
            assert sorted(dtypes) == sorted(z.files)
        for key in quant["per_channel"]:
            assert dtypes[key] == "int8", key
        assert all(v == "float32" for k, v in dtypes.items()
                   if k.startswith(SCALES_PREFIX + "/"))

    def test_v1_loader_rejects_v2_with_hint(self, quant_dir):
        from milnce_tpu.serving.export import load_inference_checkpoint

        with pytest.raises(ValueError, match="load_quantized_checkpoint"):
            load_inference_checkpoint(quant_dir)

    def test_dtype_override_refused_on_quant_exports(self, quant_dir):
        from milnce_tpu.serving.engine import InferenceEngine

        with pytest.raises(ValueError, match="dtype override"):
            InferenceEngine.from_export(quant_dir, _mesh(), max_batch=8,
                                        dtype="bfloat16")


# ---------------------------------------------------------------------------
# serving: both edge artifacts boot and stay inside the recall budgets
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served(f32_dir, quant_dir, student_dir):
    """Rankings per class: engine-from-export -> corpus + query
    embeddings -> top-10 ids (one shared u8 corpus + query pool)."""
    from milnce_tpu.serving.engine import InferenceEngine

    mesh = _mesh()
    rng = np.random.default_rng(11)
    clips = rng.integers(0, 255, (_CORPUS,) + _VIDEO_SHAPE,
                         dtype=np.uint8)
    queries = rng.integers(1, 128, (8, _WORDS)).astype(np.int32)
    out = {}
    for name, export_dir in (("f32", f32_dir), ("int8", quant_dir),
                             ("student", student_dir)):
        engine = InferenceEngine.from_export(export_dir, mesh,
                                             max_batch=16)
        corpus = np.concatenate([engine.embed_video(clips[:16]),
                                 engine.embed_video(clips[16:])])
        text = engine.embed_text(queries)
        out[name] = {
            "top10": np.argsort(-(text @ corpus.T), axis=1)[:, :10],
            "recompiles": engine.recompiles(),
            "embed_dim": text.shape[-1],
        }
    return out


def _recall(idx, base) -> float:
    return float(np.mean([len(set(a) & set(b)) / idx.shape[1]
                          for a, b in zip(idx, base)]))


class TestEdgeServing:
    def test_all_classes_boot_with_zero_recompiles(self, served):
        for name, r in served.items():
            assert r["recompiles"] == 0, name
            assert r["embed_dim"] == 16, name    # shared embedding space

    def test_int8_recall_budget(self, served):
        recall = _recall(served["int8"]["top10"], served["f32"]["top10"])
        assert recall >= INT8_RECALL_BUDGET, recall

    def test_student_recall_budget(self, served):
        recall = _recall(served["student"]["top10"],
                         served["f32"]["top10"])
        assert recall >= STUDENT_RECALL_BUDGET, recall

    def test_student_keeps_teacher_word_table(self, tiny, student):
        teacher = np.asarray(
            tiny["frozen"]["params"]["text_module"]["word_embd"]
            ["embedding"])
        svars = student["svars"]
        mine = np.asarray(
            svars["params"]["text_module"]["word_embd"]["embedding"])
        assert np.array_equal(teacher, mine)
        assert student["sinfo"]["hidden_dim"] < \
            student["sinfo"]["teacher_hidden_dim"]


# ---------------------------------------------------------------------------
# heterogeneous replica classes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mixed_stack(f32_dir, quant_dir):
    """One f32 + one edge (int8) replica behind one service."""
    from milnce_tpu.serving.index import DeviceRetrievalIndex
    from milnce_tpu.serving.pool import ReplicaPool
    from milnce_tpu.serving.service import RetrievalService

    pool = ReplicaPool.from_export(f32_dir, 1, max_batch=8,
                                   edge_export_dir=quant_dir,
                                   edge_replicas=1)
    rng = np.random.default_rng(5)
    clips = rng.integers(0, 255, (8,) + _VIDEO_SHAPE, dtype=np.uint8)
    corpus_emb = pool.embed_video(clips)
    index = DeviceRetrievalIndex(_mesh(), corpus_emb, k=5,
                                 query_buckets=pool.buckets)
    service = RetrievalService(pool, index, max_delay_ms=2.0)
    yield dict(pool=pool, service=service)
    service.close()
    pool.close()


class TestReplicaClasses:
    def test_pool_reports_both_classes(self, mixed_stack):
        stats = mixed_stack["pool"].stats()
        assert stats["classes"] == {"edge": 1, "f32": 1}

    @pytest.mark.parametrize("cls", ["f32", "edge"])
    def test_class_pinned_embed_serves(self, mixed_stack, cls):
        tokens = np.ones((2, _WORDS), np.int32)
        out = mixed_stack["pool"].embed_text(tokens, cls=cls)
        assert out.shape == (2, 16) and np.isfinite(out).all()

    def test_unknown_class_is_a_loud_error(self, mixed_stack):
        with pytest.raises(ValueError, match="replica class"):
            mixed_stack["pool"].embed_text(np.ones((1, _WORDS), np.int32),
                                           cls="gpu")

    def test_class_routing_is_strict(self, mixed_stack):
        """A pinned dispatch NEVER falls back across classes: with the
        only edge replica excluded, routing fails PoolUnavailable even
        though the f32 replica has capacity."""
        from milnce_tpu.serving.pool import PoolUnavailable

        pool = mixed_stack["pool"]
        (edge_rid,) = [r.rid for r in pool.replicas if r.cls == "edge"]
        with pytest.raises(PoolUnavailable, match="edge"):
            pool._route(cls="edge", exclude=(edge_rid,))

    @pytest.mark.parametrize("cls", ["f32", "edge"])
    def test_service_request_pins_a_class(self, mixed_stack, cls):
        tokens = np.ones((1, _WORDS), np.int32)
        scores, ids = mixed_stack["service"].query_ids(
            tokens, replica_class=cls)
        assert scores.shape == (1, 5) and ids.shape == (1, 5)

    def test_service_unknown_class_is_a_loud_error(self, mixed_stack):
        with pytest.raises(ValueError, match="replica class"):
            mixed_stack["service"].query_ids(
                np.ones((1, _WORDS), np.int32), replica_class="gpu")

    def test_unpooled_service_refuses_class_pins(self, tiny):
        from milnce_tpu.serving.engine import InferenceEngine
        from milnce_tpu.serving.index import DeviceRetrievalIndex
        from milnce_tpu.serving.service import RetrievalService

        mesh = _mesh()
        engine = InferenceEngine(tiny["model"], dict(tiny["frozen"]),
                                 mesh, text_words=_WORDS,
                                 video_shape=_VIDEO_SHAPE, max_batch=8)
        rng = np.random.default_rng(6)
        corpus = engine.embed_video(rng.integers(
            0, 255, (8,) + _VIDEO_SHAPE, dtype=np.uint8))
        index = DeviceRetrievalIndex(mesh, corpus, k=3,
                                     query_buckets=engine.buckets)
        service = RetrievalService(engine, index)
        try:
            with pytest.raises(ValueError, match="pooled"):
                service.query_ids(np.ones((1, _WORDS), np.int32),
                                  replica_class="f32")
        finally:
            service.close()

    def test_contract_mismatch_refused(self, tiny, calibrated, f32_dir,
                                       tmp_path):
        """An edge artifact disagreeing on the serving contract
        (max_words here) must not join the pool."""
        from milnce_tpu.serving.export import export_quantized_checkpoint
        from milnce_tpu.serving.pool import ReplicaPool

        bad = str(tmp_path / "bad_edge")
        export_quantized_checkpoint(
            bad, calibrated["qvars"], tiny["mcfg"],
            max_words=_WORDS + 1, video_shape=_VIDEO_SHAPE)
        with pytest.raises(ValueError, match="serving contract"):
            ReplicaPool.from_export(f32_dir, 1, max_batch=8,
                                    edge_export_dir=bad,
                                    edge_replicas=1)


# ---------------------------------------------------------------------------
# NUMERICS.md verdict parsing (the calibration defaults' seed)
# ---------------------------------------------------------------------------

class TestVerdictParser:
    def test_parses_both_verdict_spellings(self, tmp_path):
        from milnce_tpu.quant.calibrate import read_numerics_verdicts

        report = tmp_path / "NUMERICS.md"
        report.write_text(
            "| layer | shape | absmax | verdict |\n"
            "| --- | --- | --- | --- |\n"
            "| `params/text_module/fc1/kernel` | (8, 16) | 1.2 "
            "| **per-channel** |\n"
            "| `params/conv1/conv/kernel` | (3, 3, 3, 8) | 0.4 "
            "| per-tensor ok |\n")
        verdicts = read_numerics_verdicts(str(report))
        assert verdicts == {"params/text_module/fc1/kernel": True,
                            "params/conv1/conv/kernel": False}

    def test_committed_report_still_parses(self):
        """The committed NUMERICS.md keeps a readable readiness table —
        calibrate_and_quantize seeds its per-channel defaults from it."""
        from milnce_tpu.quant.calibrate import read_numerics_verdicts

        report = os.path.join(os.path.dirname(__file__), os.pardir,
                              "NUMERICS.md")
        verdicts = read_numerics_verdicts(report)
        assert verdicts, "NUMERICS.md lost its quantization-readiness " \
                         "table (regenerate: python scripts/" \
                         "precision_audit.py)"
        assert all(k.startswith("params/") for k in verdicts)

    def test_committed_verdicts_seed_calibration(self, tiny):
        """The whole loop: the COMMITTED report's verdicts must always
        be a usable per-channel default for quantization — a report
        naming a non-quantizable (or absent) layer per-channel must be
        filtered, not explode in quantize_variables."""
        from milnce_tpu.quant.calibrate import calibrate_and_quantize

        report = os.path.join(os.path.dirname(__file__), os.pardir,
                              "NUMERICS.md")
        qvars, calibration = calibrate_and_quantize(
            tiny["model"], tiny["frozen"], numerics_report=report)
        assert calibration["verdict_source"] == report
        assert qvars["quant_scales"]
