"""Unit gates for the FSDP per-parameter sharding map
(milnce_tpu/parallel/sharding_map.py): the automatic size-threshold
rule, the conv_impl_map-style override grammar, the loud-failure paths
(phantom axis, typo'd glob, unshardable dim), and the placement helper's
actual per-shard byte accounting on the 4x2 (data, model) grid."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from milnce_tpu.config import ParallelConfig
from milnce_tpu.parallel.mesh import build_mesh
from milnce_tpu.parallel.sharding_map import (build_param_specs, describe_map,
                                              map_hash, parse_sharding_spec,
                                              place_tree, sharded_count,
                                              sharded_dim, spec_leaves,
                                              state_partition_specs,
                                              tree_shardings)


@pytest.fixture(scope="module")
def mesh2d():
    return build_mesh(ParallelConfig(model_axis="model",
                                     model_parallel_size=2))


def _params():
    return {
        "conv": {"kernel": jnp.zeros((3, 3, 3, 8, 16)),   # 3456 elems
                 "bias": jnp.zeros((16,))},
        "dense": {"kernel": jnp.zeros((64, 32)),          # 2048 elems
                  "bias": jnp.zeros((32,))},
        "odd": {"kernel": jnp.zeros((7, 9))},             # no dim % 2 == 0
    }


# ---- spec grammar --------------------------------------------------------

def test_parse_empty_and_inline():
    assert parse_sharding_spec("") == {}
    got = parse_sharding_spec("conv/*=4,dense/*=-")
    assert got == {"conv/*": 4, "dense/*": None}


def test_parse_json_artifact(tmp_path):
    path = tmp_path / "map.json"
    path.write_text(json.dumps({"sharding_map": {"conv/*": 0, "d/*": "-"}}))
    assert parse_sharding_spec(str(path)) == {"conv/*": 0, "d/*": None}
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps({"x": 1}))
    assert parse_sharding_spec(str(raw)) == {"x": 1}


def test_parse_malformed_fails_at_config_time():
    with pytest.raises(ValueError, match="missing '='"):
        parse_sharding_spec("conv/kernel,dense=1")
    with pytest.raises(ValueError, match="integer dim"):
        parse_sharding_spec("conv/*=big")


# ---- automatic rule ------------------------------------------------------

def test_auto_rule_shards_large_divisible_replicates_small(mesh2d):
    specs = build_param_specs(_params(), mesh2d, "model", min_size=1024)
    # conv kernel (3456 elems >= 1024): largest divisible extent is the
    # LAST dim (16) — ties toward channels-out
    assert sharded_dim(specs["conv"]["kernel"], "model") == 4
    # dense kernel (2048): dim 0 extent 64 wins over dim 1 extent 32
    assert sharded_dim(specs["dense"]["kernel"], "model") == 0
    # small params replicate
    assert specs["conv"]["bias"] == P()
    assert specs["dense"]["bias"] == P()
    # large-but-indivisible would replicate too (7x9 is below threshold
    # here; force it large to prove the no-divisible-dim fallback)
    specs_lo = build_param_specs(_params(), mesh2d, "model", min_size=32)
    assert specs_lo["odd"]["kernel"] == P()   # 63 elems, no dim % 2 == 0
    # at min_size=32 the 32-elem dense bias shards too: kernels + bias
    assert sharded_count(specs_lo, "model") == 3


def test_threshold_boundary_is_inclusive(mesh2d):
    specs = build_param_specs({"w": jnp.zeros((32, 64))}, mesh2d, "model",
                              min_size=2048)
    assert sharded_dim(specs["w"], "model") == 1
    specs = build_param_specs({"w": jnp.zeros((32, 64))}, mesh2d, "model",
                              min_size=2049)
    assert specs["w"] == P()


# ---- overrides -----------------------------------------------------------

def test_override_forces_dim_and_replication(mesh2d):
    specs = build_param_specs(_params(), mesh2d, "model", min_size=1024,
                              spec="conv/kernel=3,dense/*=-")
    assert sharded_dim(specs["conv"]["kernel"], "model") == 3   # extent 8
    assert specs["dense"]["kernel"] == P()                      # forced off


def test_override_errors_are_loud(mesh2d):
    with pytest.raises(ValueError, match="matched no parameter"):
        build_param_specs(_params(), mesh2d, "model", spec="convv/*=0")
    with pytest.raises(ValueError, match="out of range"):
        build_param_specs(_params(), mesh2d, "model", spec="dense/kernel=5")
    with pytest.raises(ValueError, match="does not divide"):
        build_param_specs(_params(), mesh2d, "model", spec="odd/kernel=0")


def test_phantom_axis_raises(mesh2d):
    # the runtime twin of graftlint GL009: a map naming an axis the mesh
    # does not declare must fail loudly, never silently replicate
    with pytest.raises(ValueError, match="mesh has"):
        build_param_specs(_params(), mesh2d, "modle")
    mesh1d = build_mesh(ParallelConfig())
    with pytest.raises(ValueError, match="mesh has"):
        build_param_specs(_params(), mesh1d, "model")


# ---- summary + hash ------------------------------------------------------

def test_describe_and_hash_distinguish_layouts(mesh2d):
    p = _params()
    s_hi = build_param_specs(p, mesh2d, "model", min_size=1024)
    s_lo = build_param_specs(p, mesh2d, "model", min_size=32)
    d_hi = describe_map(p, s_hi, "model")
    assert d_hi["conv/kernel"] == "model@4 (3x3x3x8x16)"
    assert d_hi["conv/bias"] == "replicated (16)"
    h_hi, h_lo = map_hash(d_hi), map_hash(describe_map(p, s_lo, "model"))
    assert h_hi != h_lo                      # different layout, different id
    assert h_hi == map_hash(describe_map(p, s_hi, "model"))  # stable
    assert len(h_hi) == 12


# ---- state specs ---------------------------------------------------------

def test_state_specs_follow_params_and_replicate_the_rest(mesh2d):
    import optax
    from flax import struct

    @struct.dataclass
    class FakeState:
        step: object
        params: object
        batch_stats: object
        opt_state: object

        def replace(self, **kw):
            return FakeState(**{**self.__dict__, **kw})

    params = _params()
    opt = optax.adam(1e-3)
    st = FakeState(step=jnp.zeros((), jnp.int32), params=params,
                   batch_stats={"bn": {"mean": jnp.zeros((4096,))}},
                   opt_state=opt.init(params))
    specs = state_partition_specs(st, mesh2d, "model", min_size=1024)
    assert specs.step == P()
    # Adam mu/nu mirror the param layout leaf-for-leaf (same shapes)
    mu_specs = spec_leaves(specs.opt_state)
    assert any(sharded_dim(s, "model") is not None for s in mu_specs)
    # batch_stats ALWAYS replicate — even a stats vector over the
    # threshold (4096 >= 1024, divisible) must not shard
    assert all(s == P() for s in spec_leaves(specs.batch_stats))


def test_moments_follow_by_path_not_shape(mesh2d):
    """Regression: two SAME-SHAPE kernels with an override on one — the
    other's moments must follow ITS spec, not the overridden sibling's
    (a shape-keyed lookup handed every same-shape leaf the first
    sibling's spec and failed at trace time)."""
    import optax
    from flax import struct

    @struct.dataclass
    class FakeState:
        step: object
        params: object
        batch_stats: object
        opt_state: object

        def replace(self, **kw):
            return FakeState(**{**self.__dict__, **kw})

    params = {"a": {"kernel": jnp.zeros((64, 32))},
              "b": {"kernel": jnp.zeros((64, 32))}}
    opt = optax.adam(1e-3)
    st = FakeState(step=jnp.zeros((), jnp.int32), params=params,
                   batch_stats={}, opt_state=opt.init(params))
    specs = state_partition_specs(st, mesh2d, "model", min_size=1024,
                                  spec="a/kernel=-")
    assert specs.params["a"]["kernel"] == P()
    assert sharded_dim(specs.params["b"]["kernel"], "model") == 0
    mu = specs.opt_state[0].mu
    assert mu["a"]["kernel"] == P()                       # follows a
    assert sharded_dim(mu["b"]["kernel"], "model") == 0   # follows b


# ---- placement + byte accounting -----------------------------------------

def test_place_tree_shards_bytes_not_just_specs(mesh2d):
    """The acceptance pin: sharding must be REAL — each model-axis shard
    holds exactly 1/2 of a sharded leaf's bytes (4x2 grid), replicated
    leaves hold full size everywhere, and resharding an already-placed
    tree (the 1-D-checkpoint-onto-2-D-mesh restore path) round-trips
    values bit-exactly."""
    rng = np.random.default_rng(0)
    tree = {"big": rng.standard_normal((64, 32)).astype(np.float32),
            "small": rng.standard_normal((16,)).astype(np.float32)}
    specs = build_param_specs(tree, mesh2d, "model", min_size=1024)
    placed = place_tree(tree, specs, mesh2d)

    big = placed["big"]
    assert sharded_dim(specs["big"], "model") == 0
    for shard in big.addressable_shards:
        assert shard.data.nbytes == tree["big"].nbytes // 2
        assert shard.data.shape == (32, 32)
    for shard in placed["small"].addressable_shards:
        assert shard.data.nbytes == tree["small"].nbytes

    # re-placing an ALREADY-placed tree is an identity pass-through (the
    # rollback path restores into the live shardings and re-places; on a
    # multi-host mesh a byte round-trip there is impossible, not just
    # wasteful)
    again = place_tree(placed, specs, mesh2d)
    assert again["big"] is placed["big"]
    assert again["small"] is placed["small"]

    # values survive placement and the reverse reshard (2-D -> 1-D)
    np.testing.assert_array_equal(np.asarray(big), tree["big"])
    mesh1d = build_mesh(ParallelConfig())
    spec1d = {"big": P(), "small": P()}
    back = place_tree(placed, spec1d, mesh1d)
    np.testing.assert_array_equal(np.asarray(back["big"]), tree["big"])
    sh = tree_shardings(specs, mesh2d)
    assert sh["big"].spec == specs["big"]
