"""Runtime lock sanitizer gates (ISSUE 7, Pass 3b): SanitizedLock
order/cycle/self-deadlock/hold-budget semantics, the make_lock env
switch, an in-process 16-thread hammer over the real batcher + cache +
registry + service code under sanitized locks, and the subprocess
hammer that drives the FULL serving stack (engine -> index -> /metrics)
with ``MILNCE_LOCK_SANITIZE=1`` set before import so even the
module-level DEVICE_DISPATCH_LOCK is sanitized.

The ABBA test is the acceptance pin: a deliberately inverted ordering
MUST raise LockOrderError at the inversion site, without needing the
actual deadlock interleaving.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from milnce_tpu.analysis import lockrt
from milnce_tpu.analysis.lockrt import (LockHoldBudgetExceeded,
                                        LockOrderError, LockOrderGraph,
                                        SanitizedLock, SanitizedRLock)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "lockrt_hammer_child.py")


def _pair(graph=None):
    g = graph if graph is not None else LockOrderGraph()
    return SanitizedLock("A", graph=g), SanitizedLock("B", graph=g)


class TestOrderDetection:
    def test_abba_inversion_raises_across_threads(self):
        """The acceptance pin: thread 1 establishes A -> B; thread 2
        taking B then A raises at the inversion — no deadlock needed."""
        a, b = _pair()
        established = threading.Event()
        caught = []

        def t1():
            with a:
                with b:
                    pass
            established.set()

        def t2():
            established.wait(timeout=10)
            try:
                with b:
                    with a:
                        pass
            except LockOrderError as exc:
                caught.append(exc)

        threads = [threading.Thread(target=t1), threading.Thread(target=t2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(caught) == 1
        assert "cycle" in str(caught[0])
        # both edges' first sites are recorded for the post-mortem
        assert "A" in str(caught[0]) and "B" in str(caught[0])

    def test_consistent_order_never_raises(self):
        a, b = _pair()

        def worker():
            for _ in range(200):
                with a:
                    with b:
                        pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # graph holds exactly the one established edge
        (edge,) = [e[:2] for e in a._graph.snapshot()["edges"]]
        assert edge == ["A", "B"]

    def test_three_lock_cycle_detected(self):
        g = LockOrderGraph()
        a, b = _pair(g)
        c = SanitizedLock("C", graph=g)
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(LockOrderError, match="cycle"):
            with c:
                with a:
                    pass

    def test_self_deadlock_detected(self):
        a = SanitizedLock("A", graph=LockOrderGraph())
        with a:
            with pytest.raises(LockOrderError, match="self-deadlock"):
                a.acquire()
        # the held stack unwound correctly: re-acquire after release works
        with a:
            pass

    def test_trylock_is_exempt_from_ordering(self):
        """Lockdep parity: a failed (or successful) non-blocking acquire
        can never deadlock, so it must neither record edges nor be
        judged against the order graph — the avoid-deadlock-by-trylock
        pattern stays legal."""
        g = LockOrderGraph()
        a, b = _pair(g)
        with a:
            with b:
                pass                        # establishes A -> B
        with b:
            assert a.acquire(blocking=False)   # would be B -> A if judged
            a.release()
        assert [e[:2] for e in g.snapshot()["edges"]] == [["A", "B"]]
        # ...and a trylock on a self-held lock returns False, not a
        # self-deadlock report (stdlib semantics)
        with a:
            assert a.acquire(blocking=False) is False

    def test_rlock_reacquire_is_legal(self):
        r = SanitizedRLock("R", graph=LockOrderGraph())
        with r:
            with r:
                pass
        with r:
            pass

    def test_lock_classes_share_discipline_by_name(self):
        """Two INSTANCES with one name are one order class (lockdep
        semantics): AB on instance pair 1, BA on pair 2 still raises."""
        g = LockOrderGraph()
        a1, b1 = SanitizedLock("A", graph=g), SanitizedLock("B", graph=g)
        a2, b2 = SanitizedLock("A", graph=g), SanitizedLock("B", graph=g)
        with a1:
            with b1:
                pass
        with pytest.raises(LockOrderError):
            with b2:
                with a2:
                    pass


class TestHoldBudget:
    def test_budget_exceeded_raises_after_release(self):
        a = SanitizedLock("A", hold_budget_s=0.01, graph=LockOrderGraph())
        with pytest.raises(LockHoldBudgetExceeded, match="budget"):
            with a:
                time.sleep(0.05)
        # the lock was RELEASED before raising — nobody is wedged
        assert a.acquire(blocking=False)
        a.release()

    def test_within_budget_is_silent(self):
        a = SanitizedLock("A", hold_budget_s=5.0, graph=LockOrderGraph())
        with a:
            pass

    def test_budget_report_never_masks_the_body_exception(self):
        """An exception unwinding through the with-block is the root
        cause; the budget overrun must not replace its traceback."""
        a = SanitizedLock("A", hold_budget_s=0.01, graph=LockOrderGraph())
        with pytest.raises(ValueError, match="root cause"):
            with a:
                time.sleep(0.05)
                raise ValueError("root cause")
        assert a.acquire(blocking=False)    # still released cleanly
        a.release()


class TestMakeLock:
    def test_plain_lock_without_env(self, monkeypatch):
        monkeypatch.delenv(lockrt.ENV_SANITIZE, raising=False)
        lk = lockrt.make_lock("x")
        assert not isinstance(lk, SanitizedLock)
        with lk:
            pass

    def test_sanitized_with_env_and_budget(self, monkeypatch):
        monkeypatch.setenv(lockrt.ENV_SANITIZE, "1")
        monkeypatch.setenv(lockrt.ENV_HOLD_BUDGET_MS, "250")
        lk = lockrt.make_lock("serving.test")
        assert isinstance(lk, SanitizedLock)
        assert lk.name == "serving.test"
        assert lk.hold_budget_s == pytest.approx(0.25)

    def test_budget_zero_means_disabled(self, monkeypatch):
        """MILNCE_LOCK_HOLD_BUDGET_MS=0 disables the budget — a literal
        0.0 s budget would raise on essentially every release."""
        monkeypatch.setenv(lockrt.ENV_SANITIZE, "1")
        monkeypatch.setenv(lockrt.ENV_HOLD_BUDGET_MS, "0")
        lk = lockrt.make_lock("serving.test0")
        assert lk.hold_budget_s is None
        with lk:
            pass


# ---------------------------------------------------------------------------
# in-process hammer: real batcher + cache + registry + service code
# under sanitized locks (a fake engine keeps it jax-free and fast)
# ---------------------------------------------------------------------------

class _FakeEngine:
    """Engine-shaped stand-in: bucket ladder semantics without jax.
    embed_text acquires the dispatch-named sanitized lock so the order
    graph sees the same batcher-worker -> dispatch shape as production."""

    buckets = (4, 8)
    max_batch = 8
    text_words = 4
    embed_dim = 8

    def __init__(self):
        self._dispatch = lockrt.make_lock("serving.device_dispatch")
        self._calls = 0

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def embed_text(self, rows):
        with self._dispatch:
            self._calls += 1
            return np.tile(rows[:, :1].astype(np.float32), (1, 8))

    def recompiles(self):
        return 0

    def stats(self):
        return {"recompiles": 0, "calls": {"text@8": self._calls}}


def test_in_process_service_hammer_under_sanitizer(monkeypatch):
    """16 threads through RetrievalService.embed_text_ids + health +
    Prometheus scrape, every component lock sanitized: exact final
    counts, zero order violations."""
    monkeypatch.setenv(lockrt.ENV_SANITIZE, "1")
    lockrt.reset_global_graph()
    try:
        from milnce_tpu.obs import metrics as obs_metrics
        from milnce_tpu.serving.cache import EmbeddingLRUCache
        from milnce_tpu.serving.service import RetrievalService

        service = RetrievalService(
            _FakeEngine(), None, cache=EmbeddingLRUCache(256),
            max_delay_ms=1.0, registry=obs_metrics.MetricsRegistry())
        assert isinstance(service.cache._lock, SanitizedLock)
        assert isinstance(service._batcher._children_lock, SanitizedLock)
        errors = []
        n_embed, n_read, k = 12, 4, 10

        def embedder(tid):
            try:
                for i in range(k):
                    rows = np.full((1, 4), tid * 100 + i, np.int32)
                    out = service.embed_text_ids(rows, timeout_ms=30_000)
                    assert out.shape == (1, 8)
            except Exception as exc:  # noqa: BLE001 - the assertion IS the test
                errors.append(exc)

        def reader():
            try:
                for _ in range(k):
                    service.health()
                    service.metrics_text()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=embedder, args=(t,))
                   for t in range(n_embed)]
        threads += [threading.Thread(target=reader) for _ in range(n_read)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        service.close()
        assert not errors, errors
        # every row was a distinct cache key: exact request accounting
        assert service.health()["batcher"]["requests"] == n_embed * k
        # the sanitizer actually saw the mesh: ordering edges recorded
        assert lockrt.GLOBAL_GRAPH.snapshot()["edges"]
    finally:
        lockrt.reset_global_graph()


# ---------------------------------------------------------------------------
# subprocess hammer: the FULL serving stack (engine -> index -> HTTP)
# with MILNCE_LOCK_SANITIZE=1 set before import
# ---------------------------------------------------------------------------

def test_serving_hammer_subprocess_under_sanitizer():
    """ISSUE 7 acceptance: 16 threads drive batcher -> engine -> index
    and /metrics in a child process whose locks — including the
    module-level DEVICE_DISPATCH_LOCK — are all SanitizedLock, cycle
    detection armed.  Exit 0 == no order violation, no recompiles, all
    requests 200.  (Fast child exemption in test_suite_hygiene.py: tiny
    preset + the shared persistent compile cache, seconds-scale.)"""
    env = dict(os.environ)
    env["MILNCE_LOCK_SANITIZE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
    proc = subprocess.run([sys.executable, _CHILD], capture_output=True,
                          text=True, timeout=540, env=env)
    assert proc.returncode == 0, (
        f"hammer child failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    assert "HAMMER_OK" in proc.stdout, proc.stdout
