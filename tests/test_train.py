"""End-to-end training slice on the virtual 8-device mesh: the runnable
equivalent of the reference's train_small path (which is import-broken,
SURVEY.md §2.4) — config -> synthetic data -> S3D -> sharded MIL-NCE ->
optimizer -> checkpoint save/resume round-trip."""

import numpy as np
import pytest

from milnce_tpu.config import tiny_preset


@pytest.fixture(scope="module")
def tiny_cfg(tmp_path_factory):
    cfg = tiny_preset()
    base = tmp_path_factory.mktemp("train_run")
    cfg.train.checkpoint_root = str(base / "ckpt")
    cfg.train.log_root = str(base / "log")
    cfg.train.batch_size = 8
    cfg.data.synthetic_num_samples = 32
    cfg.data.num_reader_threads = 2
    return cfg


def test_train_step_smoke_fast_tier():
    """Fast-tier guard that a real sharded train step executes (ADVICE r3:
    the default `pytest` run must not go green without ever running one).
    Minimal on purpose — tiny 1-block S3D, one step on the 8-device mesh;
    the full loop/resume/convergence coverage lives in the slow tier."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import OptimConfig, ParallelConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    video = np.random.default_rng(0).integers(
        0, 255, (8, 4, 32, 32, 3), dtype=np.uint8)
    text = np.zeros((8, 5), np.int32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2,) + video.shape[1:], jnp.float32),
                           text[:2])
    opt = build_optimizer(OptimConfig(name="adam", warmup_steps=2),
                          build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, opt)
    mesh = build_mesh(ParallelConfig())
    step = make_train_step(model, opt, mesh, donate=False)
    # two steps: linear warmup makes the step-0 LR exactly 0
    mid_state, loss = step(state, video, text, np.zeros((8,), np.float32))
    new_state, loss = step(mid_state, video, text,
                           np.zeros((8,), np.float32))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 2
    # some trainable leaf moved (leaf 0 is the frozen word2vec table)
    changed = [not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(jax.tree_util.tree_leaves(state.params),
                               jax.tree_util.tree_leaves(new_state.params))]
    assert any(changed)


@pytest.mark.slow
def test_training_runs_and_loss_is_finite(tiny_cfg):
    from milnce_tpu.train.loop import run_training

    result = run_training(tiny_cfg, max_steps=2)
    assert result.steps == 2
    assert np.isfinite(result.last_loss)


@pytest.mark.slow
def test_no_per_step_host_sync(tiny_cfg, tmp_path, monkeypatch):
    """The hot loop must not block the host on every step (VERDICT r1 #7):
    loss transfers happen only at display points / exit, via
    jax.device_get — count them over a 4-step run with n_display=2."""
    import jax

    import milnce_tpu.train.loop as loop_mod

    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(loop_mod.jax, "device_get", counting)
    cfg = tiny_cfg
    cfg.train.checkpoint_root = str(tmp_path / "ckpt_sync")
    cfg.train.n_display = 2
    result = loop_mod.run_training(cfg, max_steps=4)
    assert result.steps == 4
    # 2 display fetches + 1 exit fetch; a per-step sync would be >= 4
    assert calls["n"] <= 3, f"host synced {calls['n']} times in 4 steps"


@pytest.mark.slow
def test_checkpoint_resume_roundtrip(tiny_cfg, tmp_path):
    import jax

    from milnce_tpu.train.loop import run_training

    cfg = tiny_cfg
    cfg.train.checkpoint_root = str(tmp_path / "ckpt2")
    r1 = run_training(cfg, max_steps=2)

    cfg.train.resume = True
    cfg.optim.epochs = 2          # resume lands at epoch 1; allow one more
    r2 = run_training(cfg, max_steps=1)
    # the restored optimizer step counter carries over (r1 took 2 steps)
    assert int(r2.state.step) == int(r1.state.step) + 1
    assert r2.steps == 1
    assert np.isfinite(r2.last_loss)


@pytest.mark.slow
def test_resume_survives_optimizer_structure_change(tmp_path):
    """A checkpoint saved under an older optimizer tree (pre-masked-Adam)
    must still resume: restore_latest falls back to weights-only restore
    and reinitializes the optimizer instead of crashing on the Orbax
    structure mismatch (an in-flight preempted run upgraded across the
    optax.masked change would otherwise be stranded)."""
    import jax
    import jax.numpy as jnp
    import optax

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import (TrainState, build_optimizer,
                                        create_train_state)

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 4, 32, 32, 3), jnp.float32),
                           jnp.zeros((4, 5), jnp.int32))
    cfg = OptimConfig(name="adam", warmup_steps=2)
    schedule = build_schedule(cfg, 10)

    # the pre-change optimizer layout: plain Adam, no masked wrapper
    old_opt = optax.inject_hyperparams(optax.adam)(learning_rate=schedule)
    old_state = create_train_state(variables, old_opt)
    old_state = old_state.replace(
        step=jnp.asarray(7, jnp.int32),
        params=jax.tree_util.tree_map(lambda x: x + 1.0, old_state.params))
    mgr = CheckpointManager(str(tmp_path / "old_run"), keep=2)
    mgr.save(3, old_state)
    mgr.close()

    new_opt = build_optimizer(cfg, schedule)       # masked layout
    template = create_train_state(variables, new_opt)
    mgr2 = CheckpointManager(str(tmp_path / "old_run"), keep=2, create=False)
    epoch, restored = mgr2.restore_latest(template)
    assert epoch == 3
    assert int(restored.step) == 7
    # weights came from the checkpoint (the +1.0 perturbation survived)...
    old_leaf = jax.tree_util.tree_leaves(old_state.params)[0]
    new_leaf = jax.tree_util.tree_leaves(restored.params)[0]
    np.testing.assert_allclose(np.asarray(new_leaf), np.asarray(old_leaf))
    # ...while the opt_state is the template's fresh masked structure
    assert (jax.tree_util.tree_structure(restored.opt_state)
            == jax.tree_util.tree_structure(template.opt_state))

    # A *params* mismatch (model changed) must NOT be rescued — installing
    # stale-shaped weights under a benign-sounding warning would defer the
    # crash to a confusing optax error; the original exception re-raises.
    other_model = S3D(num_classes=16, vocab_size=48, word_embedding_dim=8,
                      text_hidden_dim=24, inception_blocks=2)
    other_vars = other_model.init(jax.random.PRNGKey(1),
                                  jnp.zeros((2, 4, 32, 32, 3), jnp.float32),
                                  jnp.zeros((4, 5), jnp.int32))
    bad_template = create_train_state(other_vars, new_opt)
    mgr3 = CheckpointManager(str(tmp_path / "old_run"), keep=2, create=False)
    with pytest.raises((ValueError, KeyError, TypeError)):
        mgr3.restore_latest(bad_template)

    # A TRANSIENT restore error on a structure-compatible checkpoint must
    # NOT trigger the weights-only fallback (that would silently drop
    # healthy optimizer moments): with a template whose opt_state
    # fingerprint matches the stored one, the original exception re-raises.
    compat_template = create_train_state(variables, old_opt)
    mgr4 = CheckpointManager(str(tmp_path / "old_run"), keep=2, create=False)
    mgr4.restore = lambda *a, **k: (_ for _ in ()).throw(
        ValueError("transient orbax failure"))
    with pytest.raises(ValueError, match="transient orbax failure"):
        mgr4.restore_latest(compat_template)

    # An optimizer evolution whose new states carry NO array leaves
    # (chain wrapper adds only EmptyStates) must still be detected as a
    # structure change — the per-path fingerprint shifts every adam
    # leaf's tuple index — and rescued by the weights-only fallback.
    chain_opt = optax.chain(optax.clip_by_global_norm(1.0),
                            optax.inject_hyperparams(optax.adam)(
                                learning_rate=schedule))
    chain_template = create_train_state(variables, chain_opt)
    mgr5 = CheckpointManager(str(tmp_path / "old_run"), keep=2, create=False)
    epoch5, restored5 = mgr5.restore_latest(chain_template)
    assert epoch5 == 3 and int(restored5.step) == 7
    assert (jax.tree_util.tree_structure(restored5.opt_state)
            == jax.tree_util.tree_structure(chain_template.opt_state))


def test_forced_save_crash_window_recovers_boundary_checkpoint(tmp_path):
    """A SIGKILL between moving the stale epoch-boundary checkpoint aside
    and committing its mid-epoch replacement (save(force=True)) must not
    lose the boundary save: the replacement protocol renames rather than
    deletes, and the next CheckpointManager open finishes the protocol in
    whichever direction is safe (ADVICE r4, train/checkpoint.py)."""
    import os
    import shutil

    import jax
    import jax.numpy as jnp
    import optax

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.checkpoint import CheckpointManager
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 4, 32, 32, 3), jnp.float32),
                           jnp.zeros((4, 5), jnp.int32))
    opt = build_optimizer(OptimConfig(name="adam", warmup_steps=2),
                          build_schedule(OptimConfig(), 10))
    boundary = create_train_state(variables, opt).replace(
        step=jnp.asarray(4, jnp.int32))
    run = str(tmp_path / "run")
    mgr = CheckpointManager(run, keep=3)
    mgr.save(1, boundary)
    mgr.close()

    # Simulate the kill window: the stale boundary save was moved aside
    # but the replacement never committed.
    os.rename(os.path.join(run, "1"), os.path.join(run, "stale-epoch-1"))
    mgr2 = CheckpointManager(run, keep=3)            # recovery sweep runs
    assert os.path.isdir(os.path.join(run, "1"))
    assert not os.path.isdir(os.path.join(run, "stale-epoch-1"))
    template = create_train_state(variables, opt)
    epoch, restored = mgr2.restore_latest(template)
    assert epoch == 1 and int(restored.step) == 4    # boundary save intact

    # Happy-path replacement: forced save commits, backup is gone,
    # restore sees the strictly-newer mid-epoch state.
    mid_epoch = boundary.replace(step=jnp.asarray(6, jnp.int32))
    mgr2.save(1, mid_epoch, force=True)
    mgr2.close()
    assert not os.path.isdir(os.path.join(run, "stale-epoch-1"))
    mgr3 = CheckpointManager(run, keep=3, create=False)
    _, restored3 = mgr3.restore_latest(template)
    assert int(restored3.step) == 6
    mgr3.close()

    # Kill AFTER commit but before backup cleanup: the committed step
    # wins and the orphaned backup is garbage-collected on open.
    shutil.copytree(os.path.join(run, "1"),
                    os.path.join(run, "stale-epoch-1"))
    mgr4 = CheckpointManager(run, keep=3)
    assert not os.path.isdir(os.path.join(run, "stale-epoch-1"))
    _, restored4 = mgr4.restore_latest(template)
    assert int(restored4.step) == 6
    mgr4.close()


def _eval_csvs(tmp_path):
    import csv as csv_mod

    yc = tmp_path / "yc.csv"
    with open(yc, "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["end", "start", "task", "text", "video_id"])
        for i in range(4):
            w.writerow([20 + i, 10 + i, "226", f"step {i}", f"v{i}"])
    hm = tmp_path / "hm.csv"
    with open(hm, "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["video_id", "label", "split1", "split2", "split3"])
        for i in range(6):
            lab = "brush_hair_test" if i % 2 == 0 else "wave_test"
            s = 1 if i < 4 else 2
            w.writerow([f"v{i}.avi", lab, s, s, s])
    return str(yc), str(hm)


@pytest.mark.slow
@pytest.mark.parametrize("task", ["youcook", "hmdb"])
def test_in_training_eval_runs(tiny_cfg, tmp_path, task, capsys):
    """The reference's in-training evaluator is dead code
    (main_distributed.py:188-189 NameErrors); ours runs — probe AND
    retrieval flavors — on the synthetic decoder."""
    import copy

    from milnce_tpu.train.loop import run_training

    yc, hm = _eval_csvs(tmp_path)
    cfg = copy.deepcopy(tiny_cfg)     # module-scoped fixture: don't leak
    cfg.train.checkpoint_root = str(tmp_path / f"ckpt_{task}")
    cfg.train.evaluate = True
    cfg.train.eval_task = task
    cfg.data.eval_csv = yc if task == "youcook" else hm
    cfg.data.eval_video_root = str(tmp_path)
    result = run_training(cfg, max_steps=1)
    assert result.steps == 1
    out = capsys.readouterr().out
    expect = "linear probe" if task == "hmdb" else "youcook retrieval"
    assert expect in out, f"eval never ran; log was:\n{out}"


def test_in_training_eval_task_validated_early(tiny_cfg):
    import copy

    from milnce_tpu.train.loop import run_training

    cfg = copy.deepcopy(tiny_cfg)
    cfg.train.evaluate = True
    cfg.train.eval_task = "msr-vtt"   # typo
    with pytest.raises(ValueError, match="hmdb|youcook|msrvtt"):
        run_training(cfg, max_steps=1)


def test_frozen_word2vec_has_no_optimizer_state():
    """The word2vec table is frozen (stop_gradient lookup, reference
    parity) — Adam/SGD must not allocate moments for it (~160 MB of HBM
    at the full 66,250-word vocab; the reference's torch lazy per-param
    state never materializes for no-grad params)."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 4, 32, 32, 3), jnp.float32),
                           jnp.zeros((4, 5), jnp.int32))
    table_shapes = {
        tuple(leaf.shape)
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            variables["params"])
        if any(getattr(p, "key", None) == "word_embd" for p in path)}
    assert table_shapes, "no word_embd params found — did the name change?"
    for name in ("adam", "sgd"):
        cfg = OptimConfig(name=name, warmup_steps=2)
        opt = build_optimizer(cfg, build_schedule(cfg, 10))
        state = create_train_state(variables, opt)
        opt_shapes = [tuple(x.shape)
                      for x in jax.tree_util.tree_leaves(state.opt_state)]
        for shape in table_shapes:
            assert shape not in opt_shapes, (
                f"{name} allocated optimizer state for the frozen table")


def test_schedule_matches_reference_shape():
    """Golden values of the cosine-warmup schedule (utils.py:26-38)."""
    import math

    from milnce_tpu.train.schedule import cosine_with_warmup

    sched = cosine_with_warmup(1.0, num_warmup_steps=10,
                               num_training_steps=110, num_cycles=0.5)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(5)), 0.5)
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    # halfway through decay: progress 0.5 -> 0.5*(1+cos(pi/2)) = 0.5
    np.testing.assert_allclose(float(sched(60)), 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-6)
    # quarter: 0.5*(1+cos(pi/4))
    np.testing.assert_allclose(float(sched(35)),
                               0.5 * (1 + math.cos(math.pi / 4)), rtol=1e-5)


def test_loader_shards_partition_global_batch():
    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    src = SyntheticVideoTextSource(cfg.data, num_samples=32)
    # simulate 2 hosts
    l0 = ShardedLoader(src, 8, seed=0, num_threads=1, process_index=0,
                       process_count=2)
    l1 = ShardedLoader(src, 8, seed=0, num_threads=1, process_index=1,
                       process_count=2)
    b0 = next(iter(l0.epoch(0)))
    b1 = next(iter(l1.epoch(0)))
    assert b0["video"].shape[0] == 4 and b1["video"].shape[0] == 4
    # the two hosts' samples are disjoint
    assert not np.array_equal(b0["video"], b1["video"])


def test_loader_lookahead_preserves_batches():
    """Cross-batch decode pipelining must not change batch contents or
    order (samples are pure functions of (seed, epoch, index))."""
    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    src = SyntheticVideoTextSource(cfg.data, num_samples=48)
    plain = ShardedLoader(src, 8, seed=3, num_threads=4, process_index=0,
                          process_count=1, lookahead_batches=0)
    ahead = ShardedLoader(src, 8, seed=3, num_threads=4, process_index=0,
                          process_count=1, lookahead_batches=3)
    for b0, b1 in zip(plain.epoch(1), ahead.epoch(1)):
        for k in b0:
            np.testing.assert_array_equal(b0[k], b1[k])


def test_loader_early_close_cancels_queued_decodes():
    """Stopping mid-epoch (max_steps / preemption) closes the generator;
    QUEUED decode futures must be cancelled, not drained — with a slow
    source, draining the 4-batch lookahead window would take >3 s."""
    import time

    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    inner = SyntheticVideoTextSource(cfg.data, num_samples=64)

    class Slow:
        def __len__(self):
            return len(inner)

        def sample(self, idx, rng):
            time.sleep(0.1)
            return inner.sample(idx, rng)

    loader = ShardedLoader(Slow(), 8, seed=0, num_threads=1, process_index=0,
                           process_count=1, lookahead_batches=4)
    gen = loader.epoch(0)
    next(gen)                  # first batch: 8 x 0.1 s
    t0 = time.perf_counter()
    gen.close()
    dt = time.perf_counter() - t0
    # 32 queued samples at 0.1 s on 1 thread would drain in ~3.2 s;
    # cancellation returns after at most the one in-flight sample
    assert dt < 1.0, f"close drained the queue ({dt:.2f}s)"


def test_loader_epoch_reshuffles():
    from milnce_tpu.data.pipeline import ShardedLoader
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    src = SyntheticVideoTextSource(cfg.data, num_samples=64)
    loader = ShardedLoader(src, 16, seed=0, num_threads=1, process_index=0,
                           process_count=1)
    e0 = next(iter(loader.epoch(0)))
    e1 = next(iter(loader.epoch(1)))
    assert not np.array_equal(e0["video"], e1["video"])


@pytest.mark.slow
def test_loss_decreases_when_overfitting_one_batch():
    """End-to-end learning sanity: repeated steps on ONE fixed batch must
    reduce the MIL-NCE loss — gradients flow through conv towers, text
    tower, gather, and optimizer in the sharded program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from milnce_tpu.config import LossConfig, OptimConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    b, k, frames, size, words = 8, 2, 4, 32, 5
    rng = np.random.RandomState(0)
    video = rng.randint(0, 255, (b, frames, size, size, 3), np.uint8)
    text = rng.randint(1, 64, (b * k, words)).astype(np.int32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3)),
                           jnp.zeros((2 * k, words), jnp.int32))
    optim_cfg = OptimConfig(lr=1e-3, warmup_steps=1)
    optimizer = build_optimizer(optim_cfg, build_schedule(optim_cfg, 100))
    state = create_train_state(variables, optimizer)
    step_fn = make_train_step(model, optimizer, mesh, donate=False,
                              loss_cfg=LossConfig(name="milnce"))
    sh = NamedSharding(mesh, P("data"))
    args = (jax.device_put(video, sh), jax.device_put(text, sh),
            jax.device_put(np.zeros((b,), np.float32), sh))

    losses = []
    for _ in range(10):
        state, loss = step_fn(state, *args)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses), losses


@pytest.mark.slow
def test_train_step_on_two_axis_mesh():
    """SURVEY §2.3: TP isn't needed for S3D, but the mesh must be READY
    for a model axis — the identical train step has to compile and match
    the 1-D result on a (data x model) mesh with params replicated."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from milnce_tpu.config import OptimConfig, ParallelConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    # sync BN: local per-shard BN stats would legitimately differ between
    # an 8x1 and a 4x2 sharding of the same batch; cross-replica BN over
    # 'data' normalizes with GLOBAL batch stats on both meshes.
    model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1, dtype=jnp.float32,
                bn_axis_name="data")
    rng = np.random.RandomState(3)
    b, k = 8, 2
    video = rng.randint(0, 255, (b, 4, 16, 16, 3), np.uint8)
    text = rng.randint(0, 32, (b * k, 5)).astype(np.int32)
    start = np.zeros((b,), np.float32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 4, 16, 16, 3), jnp.float32),
                           jnp.zeros((2 * k, 5), jnp.int32))
    optim_cfg = OptimConfig(warmup_steps=2)

    def one_step(mesh):
        opt = build_optimizer(optim_cfg, build_schedule(optim_cfg, 10))
        state = create_train_state(variables, opt)
        step = make_train_step(model, opt, mesh, donate=False)
        sh = NamedSharding(mesh, P("data"))
        _, loss = step(state, jax.device_put(video, sh),
                       jax.device_put(text, sh), jax.device_put(start, sh))
        return float(loss)

    mesh_1d = build_mesh(ParallelConfig())
    mesh_2d = build_mesh(ParallelConfig(model_axis="model",
                                        model_parallel_size=2))
    assert mesh_2d.devices.shape == (4, 2)
    np.testing.assert_allclose(one_step(mesh_2d), one_step(mesh_1d),
                               rtol=1e-5)


@pytest.mark.slow
class TestGradCache:
    """Two-pass embedding-cache contrastive step (train/step.py
    make_grad_cache_step), for MIL-NCE and the DTW family: M microbatches
    on N chips must equal one microbatch on M*N chips — a microbatch IS
    a virtual data-parallel shard (per-microbatch BN == the reference's
    per-GPU local BN)."""

    def _setup(self, n_text_candidates=2):
        import jax
        import jax.numpy as jnp

        from milnce_tpu.config import OptimConfig
        from milnce_tpu.models import S3D
        from milnce_tpu.train.schedule import build_schedule
        from milnce_tpu.train.state import build_optimizer, create_train_state

        model = S3D(num_classes=16, vocab_size=32, word_embedding_dim=8,
                    text_hidden_dim=16, inception_blocks=1)
        b, k, frames, size, words = 16, n_text_candidates, 4, 32, 5
        rng = np.random.RandomState(0)
        video = rng.randint(0, 255, (b, frames, size, size, 3), np.uint8)
        text = rng.randint(0, 32, (b * k, words)).astype(np.int32)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((2, frames, size, size, 3), jnp.float32),
            jnp.zeros((2 * k, words), jnp.int32))
        optim_cfg = OptimConfig(warmup_steps=2)
        optimizer = build_optimizer(optim_cfg, build_schedule(optim_cfg, 10))
        state = create_train_state(variables, optimizer)
        return model, optimizer, state, video, text, b

    def test_microbatch_equals_virtual_shard(self):
        import jax
        import numpy as onp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from milnce_tpu.train.step import (make_grad_cache_step,
                                           make_train_step)

        model, optimizer, state, video, text, b = self._setup()
        devices = jax.devices()
        assert len(devices) >= 8

        # reference: plain step on an 8-device mesh
        mesh8 = Mesh(onp.asarray(devices[:8]), ("data",))
        step8 = make_train_step(model, optimizer, mesh8, donate=False)
        sh8 = NamedSharding(mesh8, P("data"))
        s8, loss8 = step8(state, jax.device_put(video, sh8),
                          jax.device_put(text, sh8),
                          jax.device_put(onp.zeros((b,), onp.float32), sh8))

        # grad-cache: 2 microbatches on a 4-device mesh (same global batch)
        mesh4 = Mesh(onp.asarray(devices[:4]), ("data",))
        gc = make_grad_cache_step(model, optimizer, mesh4, micro_batches=2,
                                  donate=False)
        sh4 = NamedSharding(mesh4, P("data"))
        s4, loss4 = gc(state, jax.device_put(video, sh4),
                       jax.device_put(text, sh4),
                       jax.device_put(onp.zeros((b,), onp.float32), sh4))

        np.testing.assert_allclose(float(loss4), float(loss8), rtol=1e-5)
        flat8 = jax.tree_util.tree_leaves(s8.params)
        flat4 = jax.tree_util.tree_leaves(s4.params)
        for a8, a4 in zip(flat8, flat4):
            np.testing.assert_allclose(np.asarray(a4), np.asarray(a8),
                                       rtol=2e-4, atol=2e-5)
        stats8 = jax.tree_util.tree_leaves(s8.batch_stats)
        stats4 = jax.tree_util.tree_leaves(s4.batch_stats)
        for a8, a4 in zip(stats8, stats4):
            np.testing.assert_allclose(np.asarray(a4), np.asarray(a8),
                                       rtol=1e-4, atol=1e-5)

    def test_microbatch_equals_virtual_shard_dtw(self):
        """The embedding-cache step covers the fork's DTW losses too:
        pass 1 caches SEQUENCE embeddings (B, T', D), the gathered
        replicated loss seeds the VJP, grads pmean-reduced — 2
        microbatches on 4 chips == 1 microbatch on 8 chips."""
        import jax
        import numpy as onp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from milnce_tpu.config import LossConfig
        from milnce_tpu.train.step import (make_grad_cache_step,
                                           make_train_step)

        model, optimizer, state, video, text, b = self._setup()
        devices = jax.devices()
        assert len(devices) >= 8
        loss_cfg = LossConfig(name="cdtw")
        start = onp.linspace(0.0, 30.0, b).astype(onp.float32)

        mesh8 = Mesh(onp.asarray(devices[:8]), ("data",))
        step8 = make_train_step(model, optimizer, mesh8, donate=False,
                                loss_cfg=loss_cfg)
        sh8 = NamedSharding(mesh8, P("data"))
        s8, loss8 = step8(state, jax.device_put(video, sh8),
                          jax.device_put(text, sh8),
                          jax.device_put(start, sh8))

        mesh4 = Mesh(onp.asarray(devices[:4]), ("data",))
        gc = make_grad_cache_step(model, optimizer, mesh4, micro_batches=2,
                                  donate=False, loss_cfg=loss_cfg)
        sh4 = NamedSharding(mesh4, P("data"))
        s4, loss4 = gc(state, jax.device_put(video, sh4),
                       jax.device_put(text, sh4),
                       jax.device_put(start, sh4))

        np.testing.assert_allclose(float(loss4), float(loss8), rtol=1e-5)
        for a8, a4 in zip(jax.tree_util.tree_leaves(s8.params),
                          jax.tree_util.tree_leaves(s4.params)):
            np.testing.assert_allclose(np.asarray(a4), np.asarray(a8),
                                       rtol=2e-4, atol=2e-5)
        for a8, a4 in zip(jax.tree_util.tree_leaves(s8.batch_stats),
                          jax.tree_util.tree_leaves(s4.batch_stats)):
            np.testing.assert_allclose(np.asarray(a4), np.asarray(a8),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("loss_name", ["milnce", "cdtw"])
    def test_loop_integration(self, tiny_cfg, tmp_path, loss_name):
        """grad_accum=2 trains through run_training end to end — for the
        MIL-NCE and the DTW-family paths of the embedding-cache step."""
        from milnce_tpu.train.loop import run_training

        import copy

        cfg = copy.deepcopy(tiny_cfg)    # module-scoped fixture: don't mutate
        cfg.train.checkpoint_root = str(tmp_path / f"ckpt_gc_{loss_name}")
        cfg.train.grad_accum = 2
        cfg.loss.name = loss_name
        # per-shard batch must split into grad_accum microbatches
        cfg.train.batch_size = 16
        result = run_training(cfg, max_steps=2)
        assert result.steps == 2
        assert np.isfinite(result.last_loss)


@pytest.mark.slow
def test_mid_epoch_resume_skips_consumed_batches(tiny_cfg, tmp_path):
    """Preemption mid-epoch must not retrain consumed batches: a 4-step
    epoch stopped at step 3 resumes with exactly 1 batch left."""
    import copy

    from milnce_tpu.train.loop import run_training

    cfg = copy.deepcopy(tiny_cfg)
    cfg.train.checkpoint_root = str(tmp_path / "ckpt_resume_pos")
    cfg.data.synthetic_num_samples = 32          # 4 steps/epoch at batch 8
    cfg.optim.epochs = 1
    first = run_training(cfg, max_steps=3)       # mid-epoch checkpoint
    assert first.steps == 3
    cfg.train.resume = True
    second = run_training(cfg)                   # finish epoch 0 only
    assert second.steps == 1, (
        f"resume replayed the epoch: ran {second.steps} steps, expected 1")
    assert int(second.state.step) == 4


@pytest.mark.slow
def test_mid_epoch_stop_after_completed_epoch_keeps_progress(tiny_cfg,
                                                             tmp_path):
    """A mid-epoch stop AFTER at least one completed epoch collides with
    the boundary save's label (epoch 1 ends -> save(1); stop at step 6
    of epoch 1 -> save(1) again): Orbax's should_save silently refuses a
    step <= the latest, so without the forced save the partial epoch's
    steps would be dropped while the log claims a checkpoint was written
    (code-review r4 finding).  Resume must continue from step 6, not 4."""
    import copy

    from milnce_tpu.train.loop import run_training

    cfg = copy.deepcopy(tiny_cfg)
    cfg.train.checkpoint_root = str(tmp_path / "ckpt_collide")
    cfg.data.synthetic_num_samples = 32          # 4 steps/epoch at batch 8
    cfg.optim.epochs = 2
    first = run_training(cfg, max_steps=6)       # epoch 0 done + 2 steps
    assert first.steps == 6
    cfg.train.resume = True
    second = run_training(cfg)                   # finish epoch 1 only
    assert second.steps == 2, (
        f"mid-epoch checkpoint was dropped: resume ran {second.steps} "
        "steps, expected 2")
    assert int(second.state.step) == 8


@pytest.mark.slow
def test_boundary_stop_resumes_as_epoch_complete(tiny_cfg, tmp_path):
    """A stop landing exactly on the epoch's last batch must label the
    checkpoint epoch+1: resuming with epochs=1 has nothing left to run
    (a current-epoch label would retrain all 4 batches)."""
    import copy

    from milnce_tpu.train.loop import run_training

    cfg = copy.deepcopy(tiny_cfg)
    cfg.train.checkpoint_root = str(tmp_path / "ckpt_boundary")
    cfg.data.synthetic_num_samples = 32          # 4 steps/epoch at batch 8
    cfg.optim.epochs = 1
    first = run_training(cfg, max_steps=4)       # stop ON the boundary
    assert first.steps == 4
    cfg.train.resume = True
    second = run_training(cfg)
    assert second.steps == 0, (
        f"boundary stop retrained the epoch: ran {second.steps} steps")
    assert int(second.state.step) == 4


@pytest.mark.slow
def test_end_to_end_learning_retrieval():
    """The whole learning system works: train MIL-NCE on the synthetic
    source's deterministic video<->text pairs and zero-shot retrieval
    R@1 over the trained set rises from chance (1/32) to a majority —
    forward, gather, loss, grads, Adam, BN stats, and both embed paths
    all pulling in the same direction.  (The only convergence evidence
    possible without the dataset; the reference has no equivalent.)"""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from milnce_tpu.config import DataConfig, OptimConfig
    from milnce_tpu.data.synthetic import SyntheticVideoTextSource
    from milnce_tpu.eval.metrics import compute_retrieval_metrics
    from milnce_tpu.models import S3D
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import (make_text_embed_fn, make_train_step,
                                       make_video_embed_fn)

    n, k, words, frames, size = 32, 2, 6, 4, 32
    dcfg = DataConfig(num_frames=frames, video_size=size, num_candidates=k,
                      max_words=words, synthetic_num_samples=n)
    src = SyntheticVideoTextSource(dcfg, vocab_size=64, num_samples=n)
    rng = onp.random.RandomState(0)
    samples = [src.sample(i, rng) for i in range(n)]
    videos = onp.stack([s["video"] for s in samples])
    texts = onp.concatenate([s["text"] for s in samples])
    starts = onp.zeros((n,), onp.float32)

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3), jnp.float32),
                           jnp.zeros((2 * k, words), jnp.int32))
    ocfg = OptimConfig(lr=1e-3, warmup_steps=5)
    optimizer = build_optimizer(ocfg, build_schedule(ocfg, 200))
    state = create_train_state(variables, optimizer)

    mesh = Mesh(onp.asarray(jax.devices()[:8]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    step = make_train_step(model, optimizer, mesh, donate=False)
    v_d = jax.device_put(videos, sh)
    t_d = jax.device_put(texts, sh)
    s_d = jax.device_put(starts, sh)

    embed_v = make_video_embed_fn(model, mesh)
    embed_t = make_text_embed_fn(model, mesh)

    def r_at_1(st):
        var = {"params": st.params, "batch_stats": st.batch_stats}
        v = onp.asarray(embed_v(var, v_d))
        t = onp.asarray(embed_t(var, t_d)).reshape(n, k, -1).mean(axis=1)
        return compute_retrieval_metrics(t @ v.T)["R1"]

    before = r_at_1(state)
    assert before <= 0.2, f"untrained R@1 {before} is already non-chance"

    first_loss = None
    for _ in range(120):
        state, loss = step(state, v_d, t_d, s_d)
        if first_loss is None:
            first_loss = float(loss)
    last_loss = float(loss)
    after = r_at_1(state)

    # prototype run (2026-07-31): 0.031 -> 0.56, loss 4.16 -> 0.70
    assert last_loss < first_loss * 0.5, (first_loss, last_loss)
    assert after >= 0.4, f"R@1 only reached {after} (before: {before})"
