"""Native C++ runtime: reader pool (hermetic — uses shell printf, not
ffmpeg) and soft-DTW CPU kernels vs the scan golden.  Skipped wholesale
when no C++ toolchain is available."""

import numpy as np
import pytest

from milnce_tpu.native.build import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no C++ toolchain / build failed")


class TestReaderPool:
    def test_concurrent_jobs_fill_buffers(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=4)
        n = 12
        bufs = [np.zeros(16, np.uint8) for _ in range(n)]
        cmds = [f"printf 'job%02d-data' {i}" for i in range(n)]
        got = pool.decode_into(cmds, bufs)
        for i in range(n):
            assert got[i] == 10
            assert bytes(bufs[i][:10]) == f"job{i:02d}-data".encode()
        pool.close()

    def test_oversized_output_truncated_to_capacity(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=2)
        buf = np.zeros(8, np.uint8)
        (got,) = pool.decode_into(["printf '0123456789ABCDEF'"], [buf])
        assert got == 8
        assert bytes(buf) == b"01234567"
        pool.close()

    def test_argv_style_command_quoting(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=1)
        buf = np.zeros(32, np.uint8)
        (got,) = pool.decode_into([["printf", "a b"]], [buf])
        assert bytes(buf[:got]) == b"a b"
        pool.close()


def _stub_ffmpeg(tmp_path, frames, size, value=9):
    """Executable stub standing in for the ffmpeg binary: ignores the
    decode argv and emits `frames` rgb24 frames of constant `value`."""
    nbytes = frames * size * size * 3
    script = tmp_path / "fake_ffmpeg"
    script.write_text("#!/bin/sh\n"
                      f"head -c {nbytes} /dev/zero | tr '\\0' '\\0{value:o}'\n")
    script.chmod(0o755)
    return str(script)


class TestNativeFFmpegDecoder:
    def test_decode_through_reader_pool(self, tmp_path):
        from milnce_tpu.data.video import NativeFFmpegDecoder

        size, frames = 8, 5
        dec = NativeFFmpegDecoder(binary=_stub_ffmpeg(tmp_path, frames, size),
                                  workers=2)
        out = dec.decode("x.mp4", 0.0, frames / 2.0, 2, size)
        assert out.shape == (frames, size, size, 3)
        assert out.dtype == np.uint8
        assert (out == 9).all()

    def test_empty_output_raises_for_resample_path(self, tmp_path):
        """A corrupt video (0 bytes out) must RAISE so HowTo100MSource's
        resample-on-failure logic kicks in."""
        from milnce_tpu.data.video import NativeFFmpegDecoder

        script = tmp_path / "fake_ffmpeg"
        script.write_text("#!/bin/sh\nexit 1\n")
        script.chmod(0o755)
        dec = NativeFFmpegDecoder(binary=str(script), workers=1)
        with pytest.raises(RuntimeError, match="no frames"):
            dec.decode("corrupt.mp4", 0.0, 2.0, 2, 8)

    def test_howto_source_native_flag(self, tmp_path, monkeypatch):
        """DataConfig.use_native_reader routes the source's default decoder
        through the C++ pool (VERDICT r1 weak #5 / next #6)."""
        import json

        import milnce_tpu.data.video as video_mod
        from milnce_tpu.config import tiny_preset
        from milnce_tpu.data.datasets import HowTo100MSource
        from milnce_tpu.data.video import NativeFFmpegDecoder

        # no real ffmpeg on this host: satisfy the build-time availability
        # gate (the decode itself is routed to a stub binary below)
        monkeypatch.setattr(video_mod.shutil, "which",
                            lambda _: "/usr/bin/ffmpeg")

        (tmp_path / "captions").mkdir()
        (tmp_path / "captions" / "vid0.json").write_text(json.dumps(
            {"start": [0], "end": [6], "text": ["hello world"]}))
        (tmp_path / "train.csv").write_text("video_path\nvid0.mp4")
        cfg = tiny_preset()
        cfg.data.train_csv = str(tmp_path / "train.csv")
        cfg.data.video_root = str(tmp_path)
        cfg.data.caption_root = str(tmp_path / "captions")
        cfg.data.use_native_reader = True
        cfg.data.num_reader_threads = 2
        src = HowTo100MSource(cfg.data, cfg.model)
        assert isinstance(src.decoder, NativeFFmpegDecoder)
        # route the stub binary in and draw a real sample through the pool
        src.decoder = NativeFFmpegDecoder(
            binary=_stub_ffmpeg(tmp_path, cfg.data.num_frames,
                                cfg.data.video_size),
            workers=2)
        s = src.sample(0, np.random.RandomState(0))
        assert s["video"].shape == (cfg.data.num_frames, cfg.data.video_size,
                                    cfg.data.video_size, 3)
        assert src.decode_failures == 0

    def test_reader_bench_harness(self):
        from milnce_tpu.native.bench_reader import main

        rec = main(n_jobs=8, mb_per_job=1, workers=4)
        assert rec["python_MBps"] > 0 and rec["native_MBps"] > 0


class TestNativeSoftDTW:
    def test_forward_matches_scan(self):
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_forward_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(0)
        D = rng.rand(3, 7, 5).astype(np.float32)
        value, _ = softdtw_forward_native(D, 0.5)
        expected = np.asarray(softdtw_scan(jnp.asarray(D), 0.5))
        np.testing.assert_allclose(value, expected, rtol=1e-4, atol=1e-5)

    def test_backward_matches_scan_autodiff(self):
        import jax
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(1)
        D = rng.rand(2, 6, 6).astype(np.float32)
        _, vjp = softdtw_native(D, 0.7)
        grad = vjp(np.ones(2, np.float32))
        expected = jax.grad(
            lambda d: softdtw_scan(d, 0.7).sum())(jnp.asarray(D))
        np.testing.assert_allclose(grad, np.asarray(expected), rtol=1e-3,
                                   atol=1e-4)

    def test_bandwidth(self):
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_forward_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(2)
        D = rng.rand(2, 8, 8).astype(np.float32)
        value, _ = softdtw_forward_native(D, 0.5, bandwidth=2)
        expected = np.asarray(softdtw_scan(jnp.asarray(D), 0.5, bandwidth=2))
        np.testing.assert_allclose(value, expected, rtol=1e-4, atol=1e-5)
