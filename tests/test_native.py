"""Native C++ runtime: reader pool (hermetic — uses shell printf, not
ffmpeg) and soft-DTW CPU kernels vs the scan golden.  Skipped wholesale
when no C++ toolchain is available."""

import numpy as np
import pytest

from milnce_tpu.native.build import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no C++ toolchain / build failed")


class TestReaderPool:
    def test_concurrent_jobs_fill_buffers(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=4)
        n = 12
        bufs = [np.zeros(16, np.uint8) for _ in range(n)]
        cmds = [f"printf 'job%02d-data' {i}" for i in range(n)]
        got = pool.decode_into(cmds, bufs)
        for i in range(n):
            assert got[i] == 10
            assert bytes(bufs[i][:10]) == f"job{i:02d}-data".encode()
        pool.close()

    def test_oversized_output_truncated_to_capacity(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=2)
        buf = np.zeros(8, np.uint8)
        (got,) = pool.decode_into(["printf '0123456789ABCDEF'"], [buf])
        assert got == 8
        assert bytes(buf) == b"01234567"
        pool.close()

    def test_argv_style_command_quoting(self):
        from milnce_tpu.native.reader import ReaderPool

        pool = ReaderPool(workers=1)
        buf = np.zeros(32, np.uint8)
        (got,) = pool.decode_into([["printf", "a b"]], [buf])
        assert bytes(buf[:got]) == b"a b"
        pool.close()


class TestNativeSoftDTW:
    def test_forward_matches_scan(self):
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_forward_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(0)
        D = rng.rand(3, 7, 5).astype(np.float32)
        value, _ = softdtw_forward_native(D, 0.5)
        expected = np.asarray(softdtw_scan(jnp.asarray(D), 0.5))
        np.testing.assert_allclose(value, expected, rtol=1e-4, atol=1e-5)

    def test_backward_matches_scan_autodiff(self):
        import jax
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(1)
        D = rng.rand(2, 6, 6).astype(np.float32)
        _, vjp = softdtw_native(D, 0.7)
        grad = vjp(np.ones(2, np.float32))
        expected = jax.grad(
            lambda d: softdtw_scan(d, 0.7).sum())(jnp.asarray(D))
        np.testing.assert_allclose(grad, np.asarray(expected), rtol=1e-3,
                                   atol=1e-4)

    def test_bandwidth(self):
        import jax.numpy as jnp

        from milnce_tpu.native.softdtw_cpu import softdtw_forward_native
        from milnce_tpu.ops.softdtw import softdtw_scan

        rng = np.random.RandomState(2)
        D = rng.rand(2, 8, 8).astype(np.float32)
        value, _ = softdtw_forward_native(D, 0.5, bandwidth=2)
        expected = np.asarray(softdtw_scan(jnp.asarray(D), 0.5, bandwidth=2))
        np.testing.assert_allclose(value, expected, rtol=1e-4, atol=1e-5)
