"""Eval layer: retrieval metrics goldens, window-ensembled retrieval on a
fake dataset, linear probe end-to-end (spec: reference metrics.py,
eval_msrvtt.py, eval_hmdb.py)."""

import numpy as np
import pytest

from milnce_tpu.eval.metrics import compute_retrieval_metrics, format_metrics


class TestMetrics:
    def test_identity_similarity_is_perfect(self):
        sim = np.eye(20)
        m = compute_retrieval_metrics(sim)
        assert m == {"R1": 1.0, "R5": 1.0, "R10": 1.0, "MR": 1.0}

    def test_hand_computed_ranks(self):
        # query 0: gt scores 0.9, best -> rank 0
        # query 1: gt 0.1 with 0.5 and 0.2 above -> rank 2
        sim = np.array([[0.9, 0.5, 0.1],
                        [0.5, 0.1, 0.2],
                        [0.0, 0.1, 0.8]])
        m = compute_retrieval_metrics(sim)
        assert m["R1"] == pytest.approx(2 / 3)
        assert m["R5"] == 1.0
        assert m["MR"] == 1.0

    def test_worst_case(self):
        n = 12
        sim = -np.eye(n)  # gt is always ranked last
        m = compute_retrieval_metrics(sim)
        assert m["R1"] == 0.0
        assert m["MR"] == n

    def test_format(self):
        s = format_metrics({"R1": 0.1, "R5": 0.2, "R10": 0.3, "MR": 4.0})
        assert "R@1: 0.1000" in s and "Median R: 4.0" in s


class _PairedSource:
    """Fake retrieval source whose video and text are trivially alignable
    only through the model? No — for pipeline tests we only need shapes."""

    def __init__(self, n=6, num_clip=2, frames=4, size=32, words=6):
        self.n, self.c, self.t, self.s, self.w = n, num_clip, frames, size, words

    def __len__(self):
        return self.n

    def sample(self, idx, rng=None):
        rng = np.random.RandomState(idx)
        return {
            "video": rng.randint(0, 255, (self.c, self.t, self.s, self.s, 3),
                                 dtype=np.uint8),
            "text": rng.randint(1, 50, (1, self.w)).astype(np.int32),
        }


@pytest.fixture(scope="module")
def tiny_model_vars():
    import jax
    import jax.numpy as jnp

    from milnce_tpu.models import S3D

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4, 32, 32, 3)),
                           jnp.zeros((1, 6), jnp.int32))
    return model, variables


@pytest.mark.slow
def test_retrieval_eval_pipeline(tiny_model_vars):
    import jax
    from jax.sharding import Mesh

    from milnce_tpu.eval.retrieval import evaluate_retrieval

    model, variables = tiny_model_vars
    mesh = Mesh(np.array(jax.devices()), ("data",))
    metrics = evaluate_retrieval(model, variables, _PairedSource(n=6), mesh,
                                 batch_size=8)
    assert set(metrics) == {"R1", "R5", "R10", "MR"}
    assert 0.0 <= metrics["R1"] <= 1.0
    assert 1.0 <= metrics["MR"] <= 6.0


class _ProbeSource:
    def __init__(self, n=8, num_clip=2):
        self.n, self.c = n, num_clip

    def __len__(self):
        return self.n

    def sample(self, idx, rng=None):
        rng = np.random.RandomState(idx)
        label = "classA" if idx % 2 == 0 else "classB"
        video = rng.randint(0, 255, (self.c, 4, 32, 32, 3), dtype=np.uint8)
        # make the two classes visually separable
        if idx % 2 == 0:
            video[..., 0] = 255
        return {"video": video, "label": label,
                "splits": np.array([1 if idx < 6 else 2] * 3, np.int32)}


@pytest.mark.slow
def test_linear_probe_pipeline(tiny_model_vars):
    import jax
    from jax.sharding import Mesh

    from milnce_tpu.eval.linear_probe import evaluate_linear_probe

    model, variables = tiny_model_vars
    mesh = Mesh(np.array(jax.devices()), ("data",))
    accs = evaluate_linear_probe(model, variables, _ProbeSource(), mesh)
    assert set(accs) == {"split1", "split2", "split3", "mean"}
    for v in accs.values():
        assert 0.0 <= v <= 1.0


def test_linear_probe_separable_features():
    """Pure-sklearn path: trivially separable features hit 100%."""
    from milnce_tpu.eval.linear_probe import linear_probe_accuracy

    n, w, d = 24, 3, 8
    rng = np.random.RandomState(0)
    feats = rng.randn(n, w, d)
    labels = np.array(["abc"[i % 3] for i in range(n)])
    feats[0::3, :, 0] += 10.0
    feats[1::3, :, 1] += 10.0
    splits = np.full((n, 3), 1, np.int32)
    splits[-6:] = 2
    accs = linear_probe_accuracy(feats, labels, splits)
    assert accs["mean"] == 1.0
