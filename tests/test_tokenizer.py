"""Tokenizer parity tests (behavior spec: reference s3dg.py:164-194,
video_loader.py:97-117)."""

import numpy as np

from milnce_tpu.data.tokenizer import Tokenizer, synthetic_vocab


def test_basic_encoding():
    tok = Tokenizer(["hello", "world", "don't"], max_words=5)
    out = tok.encode("hello world")
    assert out.tolist() == [1, 2, 0, 0, 0]  # ids are index+1; 0 pads


def test_regex_split_keeps_apostrophes():
    # reference splits on [\w']+ (s3dg.py:180-182)
    assert Tokenizer.split("don't stop, now!") == ["don't", "stop", "now"]


def test_unknown_words_dropped_not_unked():
    tok = Tokenizer(["alpha"], max_words=4)
    out = tok.encode("alpha zebra alpha")
    assert out.tolist() == [1, 1, 0, 0]


def test_all_oov_gives_all_pad():
    tok = Tokenizer(["alpha"], max_words=3)
    assert tok.encode("zebra yak").tolist() == [0, 0, 0]  # s3dg.py:189-190


def test_truncation():
    tok = Tokenizer([f"w{i}" for i in range(10)], max_words=3)
    out = tok.encode(" ".join(f"w{i}" for i in range(10)))
    assert out.tolist() == [1, 2, 3]


def test_batch_shape_and_dtype():
    tok = Tokenizer(synthetic_vocab(16), max_words=6)
    out = tok.encode_batch(["word1 word2", "word3"])
    assert out.shape == (2, 6) and out.dtype == np.int32


def test_non_string_input_stringified():
    # reference tokenizes str(sentence) (video_loader.py:98)
    tok = Tokenizer(["3"], max_words=2)
    assert tok.encode(3).tolist() == [1, 0]


def test_concurrent_encode_hammer():
    """Thread-safety audit gate for the serving request path (ISSUE 4):
    one shared Tokenizer hammered by N threads must produce exactly the
    serial goldens — no torn dict reads, no shared scratch state.  The
    audit's conclusion (module docstring 'Thread safety') is only
    trustworthy while this test exists."""
    import threading

    tok = Tokenizer(synthetic_vocab(64), max_words=8)
    rng = np.random.RandomState(0)
    sentences = [
        " ".join(f"word{rng.randint(0, 80)}"          # ~20% OOV on purpose
                 for _ in range(rng.randint(1, 14)))
        for _ in range(200)]
    golden = [tok.encode(s) for s in sentences]       # serial reference

    n_threads, rounds = 8, 5
    failures: list[str] = []
    barrier = threading.Barrier(n_threads)

    def hammer(tid: int):
        order = list(range(len(sentences)))
        rng_t = np.random.RandomState(tid)
        for _ in range(rounds):
            rng_t.shuffle(order)
            barrier.wait()                 # maximize true concurrency
            for i in order:
                got = tok.encode(sentences[i])
                if not np.array_equal(got, golden[i]):
                    failures.append(
                        f"thread {tid} sentence {i}: {got} != {golden[i]}")
        # batch entry point too
        got = tok.encode_batch(sentences[:32])
        if not np.array_equal(got, np.stack(golden[:32])):
            failures.append(f"thread {tid}: encode_batch diverged")

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not failures, failures[:5]
