"""Test bootstrap: force an 8-device virtual CPU platform so every
multi-device/sharding test runs hermetically without TPU hardware
(SURVEY.md §4 'implication' (c))."""

import os

# Must run before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env-var route (JAX_PLATFORMS=cpu) can be overridden by accelerator
# plugins that force their own platform list; the config update wins.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent compilation cache: the S3D train step takes ~2 min to compile
# on the virtual 8-device CPU mesh; identical HLO across test runs hits disk.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
