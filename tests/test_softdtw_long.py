"""Long-sequence soft-DTW paths: chunked streaming forward + scan backward
must agree with the in-VMEM kernels / golden on sizes where both run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.ops import softdtw_pallas as sp
from milnce_tpu.ops.softdtw import skew_cost, softdtw_scan


@pytest.mark.parametrize("n,m,chunk", [
    (6, 6, 4),
    pytest.param(9, 5, 3, marks=pytest.mark.slow),
    pytest.param(5, 12, 8, marks=pytest.mark.slow),
])
def test_chunked_forward_matches_scan(n, m, chunk):
    rng = np.random.RandomState(0)
    D = jnp.asarray(rng.rand(2, n, m).astype(np.float32))
    d_skew = skew_cost(D)
    value, r_skew = sp._run_forward_chunked(d_skew, n, m, 0.5, 0, chunk)
    expected = np.asarray(softdtw_scan(D, 0.5))
    np.testing.assert_allclose(np.asarray(value), expected, rtol=1e-5,
                               atol=1e-5)
    # r_skew must match the single-block kernel's table
    _, r_ref = sp._run_forward(d_skew, n, m, 0.5, 0)
    np.testing.assert_allclose(np.asarray(r_skew), np.asarray(r_ref),
                               rtol=1e-5, atol=1e-4)


@pytest.mark.slow
def test_scan_backward_matches_pallas_backward():
    rng = np.random.RandomState(1)
    n = m = 7
    D = jnp.asarray(rng.rand(2, n, m).astype(np.float32))
    grad_ref = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    # force the scan backward by shrinking the budget
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1       # everything takes the long path
        grad_long = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    finally:
        sp._VMEM_TABLE_BUDGET = old
    np.testing.assert_allclose(np.asarray(grad_long), np.asarray(grad_ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n,m", [
    (7, 7),
    pytest.param(9, 5, marks=pytest.mark.slow),
    pytest.param(5, 12, marks=pytest.mark.slow),
])
def test_chunked_backward_matches_scan_backward(n, m):
    """The HBM-streaming backward kernel (reverse-ordered chunks + six
    carry rows) must produce the scan backward's gradients exactly."""
    rng = np.random.RandomState(4)
    D = jnp.asarray(rng.rand(3, n, m).astype(np.float32))
    grad_ref = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1       # force the long path
        grad_chunked = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    finally:
        sp._VMEM_TABLE_BUDGET = old
    np.testing.assert_allclose(np.asarray(grad_chunked),
                               np.asarray(grad_ref), rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_chunked_backward_with_bandwidth():
    rng = np.random.RandomState(5)
    D = jnp.asarray(rng.rand(2, 16, 16).astype(np.float32))
    g_ref = jax.grad(
        lambda d: sp.softdtw_pallas(d, 0.5, 4).sum())(D)
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1
        g_ch = jax.grad(lambda d: sp.softdtw_pallas(d, 0.5, 4).sum())(D)
    finally:
        sp._VMEM_TABLE_BUDGET = old
    np.testing.assert_allclose(np.asarray(g_ch), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_genuinely_long_backward_chunked_vs_scan(monkeypatch):
    """A shape that routes to the chunked kernel through the REAL
    dispatch (no budget monkeypatching): (200, 180) tables are ~7x the
    VMEM budget.  The scan is reachable via the escape hatch and must
    agree."""
    rng = np.random.RandomState(6)
    D = jnp.asarray(rng.rand(2, 200, 180).astype(np.float32))
    assert not sp._table_fits_vmem(200, 180)
    assert not sp._use_lanes(2, 200, 180)
    g_kernel = jax.grad(lambda d: sp.softdtw_pallas(d, 1.0).sum())(D)
    monkeypatch.setenv("MILNCE_SDTW_BWD_SCAN", "1")
    g_scan = jax.grad(lambda d: sp.softdtw_pallas(d, 1.0).sum())(D)
    np.testing.assert_allclose(np.asarray(g_kernel), np.asarray(g_scan),
                               rtol=1e-4, atol=1e-5)


def test_long_path_value_matches_golden():
    rng = np.random.RandomState(2)
    D = jnp.asarray(rng.rand(1, 40, 30).astype(np.float32))
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1
        got = np.asarray(sp.softdtw_pallas(D, 0.3))
    finally:
        sp._VMEM_TABLE_BUDGET = old
    expected = np.asarray(softdtw_scan(D, 0.3))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)
