"""Long-sequence soft-DTW paths: chunked streaming forward + scan backward
must agree with the in-VMEM kernels / golden on sizes where both run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.ops import softdtw_pallas as sp
from milnce_tpu.ops.softdtw import skew_cost, softdtw_scan


@pytest.mark.parametrize("n,m,chunk", [(6, 6, 4), (9, 5, 3), (5, 12, 8)])
def test_chunked_forward_matches_scan(n, m, chunk):
    rng = np.random.RandomState(0)
    D = jnp.asarray(rng.rand(2, n, m).astype(np.float32))
    d_skew = skew_cost(D)
    value, r_skew = sp._run_forward_chunked(d_skew, n, m, 0.5, 0, chunk)
    expected = np.asarray(softdtw_scan(D, 0.5))
    np.testing.assert_allclose(np.asarray(value), expected, rtol=1e-5,
                               atol=1e-5)
    # r_skew must match the single-block kernel's table
    _, r_ref = sp._run_forward(d_skew, n, m, 0.5, 0)
    np.testing.assert_allclose(np.asarray(r_skew), np.asarray(r_ref),
                               rtol=1e-5, atol=1e-4)


def test_scan_backward_matches_pallas_backward():
    rng = np.random.RandomState(1)
    n = m = 7
    D = jnp.asarray(rng.rand(2, n, m).astype(np.float32))
    grad_ref = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    # force the scan backward by shrinking the budget
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1       # everything takes the long path
        grad_long = jax.grad(lambda d: sp.softdtw_pallas(d, 0.7).sum())(D)
    finally:
        sp._VMEM_TABLE_BUDGET = old
    np.testing.assert_allclose(np.asarray(grad_long), np.asarray(grad_ref),
                               rtol=1e-4, atol=1e-5)


def test_long_path_value_matches_golden():
    rng = np.random.RandomState(2)
    D = jnp.asarray(rng.rand(1, 40, 30).astype(np.float32))
    old = sp._VMEM_TABLE_BUDGET
    try:
        sp._VMEM_TABLE_BUDGET = 1
        got = np.asarray(sp.softdtw_pallas(D, 0.3))
    finally:
        sp._VMEM_TABLE_BUDGET = old
    expected = np.asarray(softdtw_scan(D, 0.3))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)
