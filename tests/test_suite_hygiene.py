"""Suite/tooling hygiene gates, fast enough for tier-1.

Two classes of silent rot this pins down:

- **Marker audit**: tests that spawn the measurement stack (bench
  children, probe subprocesses) are multi-minute; an unmarked one slips
  into the `-m 'not slow'` tier and eats the 870 s timeout for every
  later test.  The audit walks the test sources so a NEW probe/autotune
  test cannot land unmarked.
- **Report-header lint**: every auto-written report artifact must open
  by naming its generator — a table whose provenance is guessable only
  from git archaeology gets trusted (or distrusted) wrongly, and the
  round-5 advisor already caught two byte-identical probe artifacts
  drifting apart.
"""

import ast
import json
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TESTS = os.path.join(_REPO, "tests")

# source fragments that mean "this test runs the measurement stack in a
# child process" — multi-minute by construction.  The lockrt hammer
# child is listed so the audit SEES it; its one caller is then an
# explicit, reasoned exemption below rather than an invisible spawn.
_EXPENSIVE_FRAGMENTS = ("bench.py", "stage_probe.py", "xla_flag_probe.py",
                        "milnce_loss_bench.py", "real_train_eval.py",
                        "._run_config(", "lockrt_hammer_child.py",
                        "live_index_hammer_child.py")

# audited exceptions: child-process tests that are seconds-scale by
# construction and REQUIRED tier-1 by their ISSUE (a fresh interpreter +
# tiny preset, not the measurement stack).  Each entry must say why.
_FAST_CHILD_EXEMPT = {
    # ISSUE 4 acceptance: serve_bench --preset tiny --duration 1 on CPU
    # (~20 s incl. jax import); the report format is the contract, so it
    # must run the real script, and the serving gates pin it tier-1.
    "test_serve_bench.py::test_cpu_smoke_emits_valid_report",
    # ISSUE 7 acceptance: the 16-thread serving hammer under
    # MILNCE_LOCK_SANITIZE=1 — a subprocess because the sanitizer must
    # be armed BEFORE the serving modules import (module-level
    # DEVICE_DISPATCH_LOCK); ~20 s on the shared persistent compile
    # cache (dimensions match test_serving's stack), and the lock-order
    # gate pins it tier-1.
    "test_lockrt.py::test_serving_hammer_subprocess_under_sanitizer",
    # ISSUE 10 acceptance: the closed-loop chaos bench — serve_bench
    # --preset tiny --duration 2 with serve.dispatch_raise@%5 armed and
    # one replica force-killed mid-run.  A subprocess because the chaos
    # acceptance pin IS the real script end-to-end (fault arming, pool
    # build, report schema); tiny preset + the shared persistent compile
    # cache keep it seconds-scale, and the serving-chaos gate pins it
    # tier-1.
    "test_serve_chaos.py::test_chaos_serve_bench_closed_loop_acceptance",
    # ISSUE 14 satellite: the 16-thread ingest-while-query hammer under
    # MILNCE_LOCK_SANITIZE=1 — a subprocess because the sanitizer must
    # be armed BEFORE the serving modules import; tiny dims (16-wide
    # embeddings, no model) keep it seconds-scale, and the live-index
    # gate pins it tier-1.
    "test_live_index.py::test_live_index_hammer_subprocess_under_sanitizer",
    # ISSUE 14 acceptance: the two-tier chaos bench (interactive +
    # batch backfill with live-index ingest under index.swap_raise@%3,
    # continuous batching on) gated via obs_report --check.  A
    # subprocess because the acceptance pin IS the real script + gate
    # end-to-end; tiny preset + the shared persistent compile cache
    # keep it seconds-scale, and the live-index gate pins it tier-1.
    "test_serve_tiers.py::test_two_tier_chaos_bench_acceptance",
}


def _is_slow_marked(node, class_slow: bool) -> bool:
    for deco in getattr(node, "decorator_list", []):
        text = ast.unparse(deco)
        if "slow" in text and "mark" in text:
            return True
    return class_slow


def _iter_tests(tree):
    """(node, inherits_class_slow_mark) for every test function."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            class_slow = _is_slow_marked(node, False)
            for sub in node.body:
                if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub.name.startswith("test")):
                    yield sub, class_slow
        elif (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name.startswith("test")):
            yield node, False


def test_measurement_stack_tests_are_slow_marked():
    offenders = []
    for fname in sorted(os.listdir(_TESTS)):
        if not fname.endswith(".py") or fname == os.path.basename(__file__):
            continue
        src = open(os.path.join(_TESTS, fname)).read()
        tree = ast.parse(src)
        for node, class_slow in _iter_tests(tree):
            seg = ast.get_source_segment(src, node) or ""
            # only child-process launches count: monkeypatched fakes and
            # unit tests of the pure logic are cheap and belong in tier-1
            spawns = ("sys.executable" in seg
                      and any(f in seg for f in _EXPENSIVE_FRAGMENTS))
            calls_real_child = ("._run_config(" in seg
                                and "monkeypatch" not in seg)
            if ((spawns or calls_real_child)
                    and not _is_slow_marked(node, class_slow)
                    and f"{fname}::{node.name}" not in _FAST_CHILD_EXEMPT):
                offenders.append(f"{fname}::{node.name}")
    assert not offenders, (
        "tests spawning the measurement stack must carry "
        f"@pytest.mark.slow (tier-1 budget): {offenders}")


# artifact -> generator whose name its first line must carry.  Only
# artifacts present on disk are checked (probe outputs are re-written on
# the chip; a fresh clone may lack some).
_REPORT_GENERATORS = {
    "BENCH_NOTES.md": "bench.py",
    "STAGE_PROBE.md": "scripts/stage_probe.py",
    "STAGE_PROBE_native_fwdbwd.md": "scripts/stage_probe.py",
    "STAGE_AUTOTUNE.md": "scripts/stage_probe.py",
    "XLA_FLAGS_PROBE.md": "scripts/xla_flag_probe.py",
    "DATA_BENCH.md": "scripts/data_bench.py",
    "LINT.md": "scripts/graft_lint.py",
    "MEMPLAN.md": "scripts/mem_plan.py",
    "BENCH_MILNCE_LOSS.md": "scripts/milnce_loss_bench.py",
    "NUMERICS.md": "scripts/precision_audit.py",
}


def test_auto_written_reports_name_their_generator():
    bad = []
    for fname, generator in _REPORT_GENERATORS.items():
        path = os.path.join(_REPO, fname)
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            first = fh.readline()
        if generator not in first or "auto-written" not in first:
            bad.append(f"{fname}: {first.strip()!r}")
    assert not bad, ("auto-written reports must open with "
                     f"'(auto-written by <generator>)': {bad}")


def test_report_writers_emit_generator_headers():
    """Source-side half of the lint: every md-writing helper in the
    measurement scripts opens its artifact with the auto-written header,
    so a NEW report can't ship anonymous."""
    writers = {
        os.path.join(_REPO, "bench.py"): "auto-written by bench.py",
        os.path.join(_REPO, "scripts", "stage_probe.py"):
            "auto-written by scripts/stage_probe.py",
        os.path.join(_REPO, "scripts", "xla_flag_probe.py"):
            "auto-written by scripts/xla_flag_probe.py",
        os.path.join(_REPO, "scripts", "data_bench.py"):
            "auto-written by scripts/data_bench.py",
        # LINT.md's renderer lives in the package; the header still names
        # the CLI that users run
        os.path.join(_REPO, "milnce_tpu", "analysis", "report.py"):
            "auto-written by scripts/graft_lint.py",
        os.path.join(_REPO, "scripts", "mem_plan.py"):
            "auto-written by scripts/mem_plan.py",
        os.path.join(_REPO, "scripts", "milnce_loss_bench.py"):
            "auto-written by scripts/milnce_loss_bench.py",
        os.path.join(_REPO, "scripts", "precision_audit.py"):
            "auto-written by scripts/precision_audit.py",
    }
    for path, header in writers.items():
        assert header in open(path).read(), (
            f"{os.path.basename(path)} writes a report without naming "
            f"itself ('{header}')")


# graftlint gate tests (ISSUE 2; ISSUE 7 added the concurrency pass and
# the runtime lock sanitizer; ISSUE 8 the static HBM planner): the
# static-analysis + trace-invariant + lock-discipline + memory-plan
# layer only guards the hot path if it runs on EVERY default `pytest`
# invocation — a slow-marked (or vanished) gate ships regressions (and
# re-ships the /healthz-dict class of race).
_ANALYSIS_GATES = ("test_graftlint.py", "test_graftlint_concurrency.py",
                   "test_lockrt.py", "test_trace_invariants.py",
                   "test_transfer_guard.py", "test_memplan.py",
                   "test_numerics.py")


def test_analysis_gates_exist_and_stay_tier1():
    for fname in _ANALYSIS_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"analysis gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "graftlint gates must be tier-1/CPU-safe, never @slow "
            f"(they ARE the fast regression fence): {fname}::{slow}")


# chaos-test gate (ISSUE 3): the fault-injection tests ARE the permanent
# regression harness for the recovery paths (watchdog, finite guard,
# rollback, ckpt retry) — and for PRs 1-2's hot-path guarantees holding
# UNDER injected faults.  Like the analysis gates, they only guard if
# they run on every default `pytest`: never @slow, never vanished.
_CHAOS_GATES = ("test_resilience.py",)


def test_chaos_gates_exist_and_stay_tier1():
    for fname in _CHAOS_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"chaos gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "chaos tests must be tier-1/CPU-safe, never @slow (they are "
            f"the fault-path regression fence): {fname}::{slow}")


# serving gates (ISSUE 4): the online-serving subsystem's tests — engine
# bucket ladder, batcher deadline semantics, export round-trip, the
# served-vs-offline parity pin, and the serve_bench smoke — are the
# regression fence for the request path.  Same rule as the analysis and
# chaos gates: tier-1, never @slow, never vanished.
_SERVING_GATES = ("test_serving.py", "test_serve_batcher.py",
                  "test_export.py", "test_serve_bench.py")


def test_serving_gates_exist_and_stay_tier1():
    for fname in _SERVING_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"serving gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "serving tests must be tier-1/CPU-safe, never @slow (they "
            f"are the request-path regression fence): {fname}::{slow}")


# serving-chaos gate (ISSUE 10): the replica-pool fault-injection tests
# — per-site survival (raise/hang/dead), quarantine-then-recovery,
# hedge determinism, shed-never-hangs, the HTTP error contract and the
# closed-loop chaos bench — are the permanent regression harness for
# serving-path failure isolation.  Same rule as every other gate:
# tier-1, never @slow, never vanished.
_SERVE_CHAOS_GATES = ("test_serve_chaos.py",)


def test_serve_chaos_gates_exist_and_stay_tier1():
    for fname in _SERVE_CHAOS_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"serving-chaos gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "serving chaos tests must be tier-1/CPU-safe, never @slow "
            "(they are the serving failure-isolation regression fence): "
            f"{fname}::{slow}")


# observability gates (ISSUE 5; ISSUE 9 added the attribution tier —
# goodput ledger, live MFU, anomaly->capture, pod aggregation): the obs
# subsystem's tests — registry thread-safety with exact counts, the
# Prometheus exposition golden, the obs_report regression gate, the
# instrumented-train-run event stream, and the ledger/capture
# acceptance runs — are the telemetry regression fence.  Same rule as
# the analysis/chaos/serving gates: tier-1, never @slow, never
# vanished.
_OBS_GATES = ("test_obs.py", "test_goodput.py")


def test_obs_gates_exist_and_stay_tier1():
    for fname in _OBS_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"obs gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "obs tests must be tier-1/CPU-safe, never @slow (they are "
            f"the telemetry regression fence): {fname}::{slow}")


# 2-D mesh gates (ISSUE 6): the FSDP sharding-map unit gates and the
# mesh-layout parity / zero-recompile / checkpoint-resharding /
# per-shard-byte-accounting tests are the regression fence for the
# pod-scale (data, model) training layout.  Same rule as every other
# subsystem gate: tier-1, never @slow, never vanished.
_MESH2D_GATES = ("test_sharding_map.py", "test_train_2d.py")


def test_mesh2d_gates_exist_and_stay_tier1():
    for fname in _MESH2D_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"2-D mesh gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "2-D mesh tests must be tier-1/CPU-safe, never @slow (they "
            f"are the pod-scale-layout regression fence): {fname}::{slow}")


# memory-efficient loss gates (ISSUE 12): the chunked MIL-NCE parity
# suite — dense-vs-chunked value/grad parity across backends and mesh
# layouts, plus the 2-optimizer-step train parity pins — is the
# regression fence for the streaming loss path.  Same rule as every
# other subsystem gate: tier-1, never @slow, never vanished.
_MEMLOSS_GATES = ("test_milnce_chunked.py",)


def test_memloss_gates_exist_and_stay_tier1():
    for fname in _MEMLOSS_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"mem-loss gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "chunked MIL-NCE tests must be tier-1/CPU-safe, never @slow "
            "(they are the memory-efficient-loss regression fence): "
            f"{fname}::{slow}")


# live-index + SLO-tier gates (ISSUE 14): the generation-swap parity
# pin, swap-failure chaos, snapshot round trip, the ingest-while-query
# hammer, the tier admission units and the two-tier chaos bench are the
# regression fence for the online-ingest serving path.  Same rule as
# every other subsystem gate: tier-1, never @slow, never vanished.
_LIVE_INDEX_GATES = ("test_live_index.py", "test_serve_tiers.py")


def test_live_index_gates_exist_and_stay_tier1():
    for fname in _LIVE_INDEX_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"live-index gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "live-index tests must be tier-1/CPU-safe, never @slow "
            "(they are the online-ingest regression fence): "
            f"{fname}::{slow}")


# curriculum gates (ISSUE 16): the staged-schedule grammar + plan
# simulator (including the resume_batch_offset / stop_save_label
# equivalence the flat path rides on), the checkpoint-compatible stage
# transitions, the pre-flight refusal and the goodput stage_switch
# attribution are the regression fence for curriculum training.  Same
# rule as every other subsystem gate: tier-1, never @slow, never
# vanished.
_CURRICULUM_GATES = ("test_curriculum.py",)


def test_curriculum_gates_exist_and_stay_tier1():
    for fname in _CURRICULUM_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"curriculum gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "curriculum tests must be tier-1/CPU-safe, never @slow "
            "(they are the staged-training regression fence): "
            f"{fname}::{slow}")


# edge-tier gates (ISSUE 19): the quantized-export bit-exact
# round-trip, the recall@10 degradation budgets (int8 + distilled
# student vs f32), strict class-pinned pool routing and the NUMERICS.md
# verdict parser are the regression fence for the edge serving tier.
# Same rule as every other subsystem gate: tier-1, never @slow, never
# vanished.
_QUANT_GATES = ("test_quant.py",)


def test_quant_gates_exist_and_stay_tier1():
    for fname in _QUANT_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"edge-tier gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "edge-tier tests must be tier-1/CPU-safe, never @slow "
            "(they are the quantized-serving regression fence): "
            f"{fname}::{slow}")


# elastic gates (ISSUE 20): the drain -> cross-topology-resume chaos
# chain (8-way -> 4x2 -> 4-way with loss-trajectory continuity), the
# stamp refusals, the drained-save atomicity regression and the
# straggler policy are the regression fence for elastic pod training.
# Same rule as every other subsystem gate: tier-1, never @slow, never
# vanished.
_ELASTIC_GATES = ("test_elastic.py",)


def test_elastic_gates_exist_and_stay_tier1():
    for fname in _ELASTIC_GATES:
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"elastic gate {fname} is missing"
        src = open(path).read()
        tests = list(_iter_tests(ast.parse(src)))
        assert tests, f"{fname} defines no tests"
        slow = [node.name for node, class_slow in tests
                if _is_slow_marked(node, class_slow)]
        assert not slow, (
            "elastic tests must be tier-1/CPU-safe, never @slow "
            "(they are the preemption/topology-change regression fence): "
            f"{fname}::{slow}")


def test_fast_child_exemptions_stay_real():
    """Every _FAST_CHILD_EXEMPT entry must name a test that still
    exists — a stale exemption is a hole the audit thinks it covers."""
    for entry in _FAST_CHILD_EXEMPT:
        fname, _, test_name = entry.partition("::")
        path = os.path.join(_TESTS, fname)
        assert os.path.exists(path), f"exemption names missing file {fname}"
        names = {node.name for node, _ in
                 _iter_tests(ast.parse(open(path).read()))}
        assert test_name in names, f"exemption names missing test {entry}"


def test_autotune_artifact_carries_generator_key():
    """The JSON impl-map artifact can't carry a markdown header; its
    'generator' key is the same contract."""
    path = os.path.join(_REPO, "build", "impl_map.json")
    if not os.path.exists(path):
        return
    art = json.load(open(path))
    assert art["generator"].startswith("scripts/stage_probe.py")
