"""Chunked streaming MIL-NCE (ISSUE 12): value + gradient parity against
the dense cube loss, across both streaming backends (scan, and the
Pallas kernel in interpret mode on CPU), K in {1, 5}, uneven last chunks
(Bg % chunk != 0), and the single-shard / 8-way 1-D / 4x2 2-D mesh
layouts — plus the train-step-level pin: dense and chunked steps train
identically through 2 full optimizer steps, params leaf-for-leaf
(the test_train_2d layout-parity harness, re-aimed at the loss impl).

Pinned tier-1 (never @slow) by tests/test_suite_hygiene.py: these are
the regression fence for the memory-efficient loss path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.config import LossConfig, OptimConfig, ParallelConfig
from milnce_tpu.losses.milnce import milnce_loss
from milnce_tpu.losses.milnce_chunked import (build_milnce_loss,
                                              milnce_default_chunk,
                                              milnce_loss_chunked,
                                              prefers_chunked)
from milnce_tpu.models import S3D
from milnce_tpu.parallel.compat import set_mesh, shard_map
from milnce_tpu.parallel.mesh import build_mesh, replicate_to_mesh
from milnce_tpu.parallel.sharding_map import (place_tree, sharded_count,
                                              state_partition_specs)
from milnce_tpu.train.schedule import build_schedule
from milnce_tpu.train.state import build_optimizer, create_train_state
from milnce_tpu.train.step import make_grad_cache_step, make_train_step


def _embeddings(b, k, d, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(b, d).astype(np.float32),
            rng.randn(b * k, d).astype(np.float32))


def _dense_value_and_grads(v, t):
    return jax.value_and_grad(lambda a, b_: milnce_loss(a, b_),
                              argnums=(0, 1))(jnp.asarray(v),
                                              jnp.asarray(t))


# --------------------------------------------------------------------------
# single-shard parity: both backends, K in {1, 5}, uneven chunks
# --------------------------------------------------------------------------

@pytest.mark.parametrize("backend", ["scan", "pallas"])
@pytest.mark.parametrize("b,k,d,chunk", [
    (8, 1, 16, 4),          # K=1
    (8, 5, 16, 4),          # K=5, even chunks
    (8, 5, 16, 5),          # uneven last chunk (8 % 5 != 0)
    (6, 5, 16, 4),          # uneven + batch off the sublane grid
], ids=["k1", "k5", "uneven", "uneven-b6"])
def test_single_shard_value_and_grad_parity(backend, b, k, d, chunk):
    v, t = _embeddings(b, k, d, seed=b * 10 + k)
    dense_val, dense_grads = _dense_value_and_grads(v, t)
    val, grads = jax.value_and_grad(
        lambda a, b_: milnce_loss_chunked(a, b_, chunk=chunk,
                                          backend=backend),
        argnums=(0, 1))(jnp.asarray(v), jnp.asarray(t))
    np.testing.assert_allclose(float(val), float(dense_val), rtol=2e-6)
    for g, gd in zip(grads, dense_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(gd),
                                   atol=2e-6)


def test_default_chunk_rule_and_auto_impl_rule():
    # the chunk=0 rule: sublane-aligned, bounded by Bg, ~2 MiB of row
    # logits at the baseline point
    assert milnce_default_chunk(4, 1, 4) == 4          # tiny Bg passthrough
    c = milnce_default_chunk(128, 5, 8192)
    assert c % 8 == 0 and 8 <= c <= 8192
    assert 1_000_000 <= 128 * c * 5 * 4 <= 4_000_000   # ~2 MiB target
    # impl='auto': dense at test scale, chunked at the 8192 recipe
    assert not prefers_chunked(16, 16, 5)
    assert prefers_chunked(128, 8192, 5)


def test_build_milnce_loss_rejects_bad_knobs():
    with pytest.raises(ValueError, match="milnce_impl"):
        build_milnce_loss(LossConfig(milnce_impl="streamed"))
    with pytest.raises(ValueError, match="milnce_backend"):
        build_milnce_loss(LossConfig(milnce_impl="chunked",
                                     milnce_backend="cuda"))
    # loss_cfg=None keeps the dense path (the pinned default)
    v, t = _embeddings(4, 2, 8)
    fn = build_milnce_loss(None)
    np.testing.assert_allclose(
        float(fn(jnp.asarray(v), jnp.asarray(t), None)),
        float(milnce_loss(jnp.asarray(v), jnp.asarray(t))), rtol=1e-6)


# --------------------------------------------------------------------------
# sharded parity: 8-way 1-D and 4x2 2-D meshes
# --------------------------------------------------------------------------

def _sharded_loss_and_grads(mesh, axes, v, t, chunk, backend):
    spec = P(axes)

    @jax.jit
    def run(v, t):
        def local(vv, tt):
            def loss_of(a, b_):
                return milnce_loss_chunked(a, b_, axis_name=axes,
                                           chunk=chunk, backend=backend)
            val, grads = jax.value_and_grad(loss_of, argnums=(0, 1))(vv, tt)
            return val, grads

        return shard_map(local, mesh=mesh, in_specs=(spec, spec),
                         out_specs=(P(), (spec, spec)),
                         check_vma=False)(v, t)

    sh = NamedSharding(mesh, spec)
    with set_mesh(mesh):
        return run(jax.device_put(v, sh), jax.device_put(t, sh))


@pytest.mark.parametrize("layout,backend", [
    ("1d", "scan"), ("2d", "pallas"),
], ids=["1d-scan", "2d-pallas"])
def test_sharded_parity_matches_unsharded_dense(layout, backend):
    """8-way data mesh and the 4x2 (data, model) grid: the chunked loss
    + grads over mesh-wide negatives equal the unsharded dense loss —
    the same transitivity pin the dense loss carries in test_milnce.py,
    now across the chunk scan AND the gather/psum structure.  Two
    layout/backend pairs cover both axes of the matrix (the full
    backend cross-product is pinned single-shard above; compiling all
    four sharded grad programs again would only re-pay the 870 s tier-1
    budget for combinations the single-shard matrix already proves)."""
    devices = jax.devices()
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    if layout == "1d":
        mesh = Mesh(np.array(devices), ("data",))
        axes = "data"
    else:
        mesh = Mesh(np.array(devices).reshape(4, 2), ("data", "model"))
        axes = ("data", "model")
    b, k, d, chunk = 16, 3, 32, 5                     # uneven: 16 % 5 != 0
    v, t = _embeddings(b, k, d, seed=7)
    dense_val, dense_grads = _dense_value_and_grads(v, t)
    val, grads = _sharded_loss_and_grads(mesh, axes, v, t, chunk, backend)
    np.testing.assert_allclose(float(val), float(dense_val), rtol=1e-5)
    for g, gd in zip(grads, dense_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(gd),
                                   atol=1e-6)


# --------------------------------------------------------------------------
# train-step parity: 2 full optimizer steps, params leaf-for-leaf
# --------------------------------------------------------------------------

_B, _FRAMES, _SIZE, _WORDS, _VOCAB = 16, 4, 32, 5, 32
_MIN_SIZE = 256


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    video = rng.integers(0, 255, (_B, _FRAMES, _SIZE, _SIZE, 3),
                         dtype=np.uint8)
    text = rng.integers(0, _VOCAB, (_B, _WORDS)).astype(np.int32)
    start = np.zeros((_B,), np.float32)
    return video, text, start


def _train(loss_cfg, two_d=False, grad_accum=1, n_steps=2):
    """Fresh init -> n_steps of the real step program; returns per-step
    losses and the final state (mirror of test_train_2d._train, with the
    loss impl as the axis under test)."""
    if two_d:
        mesh = build_mesh(ParallelConfig(model_axis="model",
                                         model_parallel_size=2))
        bn_axes = ("data", "model")
    else:
        mesh = build_mesh(ParallelConfig())
        bn_axes = "data"
    model = S3D(num_classes=16, vocab_size=_VOCAB, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1,
                bn_axis_name=bn_axes)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, _FRAMES, _SIZE, _SIZE, 3), jnp.float32),
        jnp.zeros((2, _WORDS), jnp.int32))
    opt = build_optimizer(OptimConfig(warmup_steps=2),
                          build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, opt)
    if two_d:
        specs = state_partition_specs(state, mesh, "model",
                                      min_size=_MIN_SIZE)
        assert sharded_count(specs.params, "model") > 0
        state = place_tree(state, specs, mesh)
    else:
        specs = None
        state = replicate_to_mesh(state, mesh)
    kw = dict(donate=False, loss_cfg=loss_cfg, state_specs=specs,
              model_axis="model" if two_d else None)
    if grad_accum > 1:
        step = make_grad_cache_step(model, opt, mesh, grad_accum, **kw)
    else:
        step = make_train_step(model, opt, mesh, **kw)
    losses = []
    for i in range(n_steps):
        state, loss = step(state, *_batch(i))
        losses.append(float(loss))
    return losses, state


_CHUNKED = LossConfig(name="milnce", milnce_impl="chunked", milnce_chunk=6,
                      milnce_backend="scan")


def _assert_states_match(st1, st2):
    for a, b in zip(jax.tree_util.tree_leaves(st1.params),
                    jax.tree_util.tree_leaves(st2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_train_step_parity_dense_vs_chunked_1d():
    """2 full optimizer steps on the 8-way mesh: step-2 loss is a
    function of step-1's update, so agreement transitively pins the
    streamed loss's gradients THROUGH the optimizer — and final params
    agree leaf-for-leaf."""
    dense, st_d = _train(None)
    chunked, st_c = _train(_CHUNKED)
    np.testing.assert_allclose(chunked, dense, rtol=2e-4, atol=2e-5)
    _assert_states_match(st_d, st_c)


def test_train_step_parity_dense_vs_chunked_2d():
    """The 4x2 FSDP twin: the chunked loss under the 2-D step (negatives
    gathered over BOTH axes, grads through the per-leaf
    psum_scatter+psum reduction) trains identically to the dense 2-D
    step.  (The grad-cache composition — the chunk scan inside the
    loss-of-cached-embeddings stage — is pinned structurally by the
    scan-reduction-free check on the traced grad-cache program and by
    grad-cache's own dense parity in test_train.py; re-compiling two
    more full step programs here bought nothing those pins don't.)"""
    dense, st_d = _train(None, two_d=True)
    chunked, st_c = _train(_CHUNKED, two_d=True)
    np.testing.assert_allclose(chunked, dense, rtol=2e-4, atol=2e-5)
    _assert_states_match(st_d, st_c)
