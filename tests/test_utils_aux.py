"""Aux utilities: asset converter CLI, multi-host env detection, the
training divergence guard, and the RunLogger (ISSUE 5 satellite)."""

import json
import threading

import numpy as np
import pytest


class TestRunLogger:
    def test_single_persistent_handle_flushed_per_line(self, tmp_path):
        """The handle is opened ONCE (the old open-per-log() cost a full
        syscall round-trip per display line) and line-buffered: every
        line is on disk the moment log() returns."""
        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1")
        fh = logger._fh
        logger.log("first")
        assert logger._fh is fh, "log() must not reopen the file"
        # flushed without close: a crash loses at most the current line
        assert "first" in open(logger.path).read()
        logger.log("second")
        assert logger._fh is fh
        lines = open(logger.path).read().splitlines()
        assert len(lines) == 2 and lines[1].endswith("second")
        logger.close()
        assert logger._fh is None
        logger.close()                        # idempotent

    def test_log_event_appends_jsonl_twin(self, tmp_path):
        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1")
        logger.log_event({"step": 1, "loss": 0.5})
        logger.log_event({"step": 2, "loss": 0.25})
        logger.close()
        records = [json.loads(l) for l in open(logger.events_path)]
        assert records == [{"step": 1, "loss": 0.5},
                           {"step": 2, "loss": 0.25}]

    def test_close_is_terminal_for_both_streams(self, tmp_path):
        # close() must not be resurrectable: a late log()/log_event()
        # from a thread holding a stale reference is a no-op, never a
        # silently reopened handle
        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1")
        logger.log("before")
        logger.log_event({"step": 1})
        logger.close()
        logger.log("after")
        logger.log_event({"step": 2})
        assert open(logger.path).read().count("\n") == 1
        records = [json.loads(l) for l in open(logger.events_path)]
        assert records == [{"step": 1}]

    def test_disabled_logger_writes_nothing(self, tmp_path):
        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1", enabled=False)
        logger.log("x")
        logger.log_event({"a": 1})
        logger.close()
        assert logger.path is None and logger.events_path is None

    def test_log_event_racing_close_never_derefs_or_reopens(self, tmp_path):
        """ISSUE 7 regression: log_event's lock-free `_closed` check +
        lazy open-under-lock raced close() (graftlint GL010/GL012) —
        now the nulled handle IS the closed flag, checked under the
        lock.  Writers racing close must never raise, and every line
        that landed is whole valid JSON."""
        import threading

        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1")
        errors = []

        def writer(tid):
            try:
                for i in range(200):
                    logger.log_event({"t": tid, "i": i})
            except Exception as exc:  # pragma: no cover - asserted below
                errors.append(exc)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        logger.close()                 # races the writers mid-stream
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        records = [json.loads(l) for l in open(logger.events_path)]
        assert all(set(r) == {"t", "i"} for r in records)
        logger.log_event({"late": 1})  # no-op, never a reopened handle
        assert len([json.loads(l) for l in open(logger.events_path)]) \
            == len(records)

    def test_concurrent_writers_interleave_whole_lines(self, tmp_path):
        """Reader threads log decode failures while the loop logs the
        display line — lines must never shear."""
        from milnce_tpu.utils.logging import RunLogger

        logger = RunLogger(str(tmp_path), "run1")
        n, k = 4, 50

        def worker(tid):
            for i in range(k):
                logger.log(f"t{tid}:{i}:{'x' * 64}")

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        logger.close()
        lines = open(logger.path).read().splitlines()
        assert len(lines) == n * k
        assert all(line.endswith("x" * 64) for line in lines)


class TestAssetsCLI:
    def test_word2vec_conversion_roundtrip(self, tmp_path):
        torch = pytest.importorskip("torch")

        from milnce_tpu.models.build import load_word2vec_table
        from milnce_tpu.utils.assets import main

        table = torch.randn(17, 300)
        src = tmp_path / "word2vec.pth"
        dst = tmp_path / "word2vec.npy"
        torch.save(table, src)
        main(["word2vec", str(src), str(dst)])
        loaded = load_word2vec_table(str(dst))
        np.testing.assert_allclose(loaded, table.numpy(), rtol=1e-6)

    def test_word2vec_accepts_embedding_module(self, tmp_path):
        torch = pytest.importorskip("torch")

        from milnce_tpu.utils.assets import convert_word2vec

        emb = torch.nn.Embedding(9, 5)
        src = tmp_path / "emb.pth"
        torch.save(emb, src)
        v, d = convert_word2vec(str(src), str(tmp_path / "emb.npy"))
        assert (v, d) == (9, 5)

    def test_inspect_prints_tensors(self, tmp_path, capsys):
        torch = pytest.importorskip("torch")

        from milnce_tpu.utils.assets import main

        src = tmp_path / "ckpt.pth.tar"
        torch.save({"epoch": 3, "state_dict": {"a.weight": torch.ones(2, 2)}},
                   src)
        main(["inspect", str(src)])
        out = capsys.readouterr().out
        assert "1 entries" in out and "a.weight: (2, 2)" in out


class TestMultihostDetect:
    def test_single_host_is_noop(self, monkeypatch):
        import milnce_tpu.parallel.mesh as mesh_mod
        from milnce_tpu.config import ParallelConfig

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
        called = []
        monkeypatch.setattr(mesh_mod.jax.distributed, "initialize",
                            lambda *a, **k: called.append((a, k)))
        mesh_mod.initialize_distributed(ParallelConfig())
        assert called == []

    def test_multihost_tpu_auto_initializes(self, monkeypatch):
        import milnce_tpu.parallel.mesh as mesh_mod
        from milnce_tpu.config import ParallelConfig

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1w-0,t1w-1,t1w-2")
        called = []
        monkeypatch.setattr(mesh_mod.jax.distributed, "initialize",
                            lambda *a, **k: called.append((a, k)))
        mesh_mod.initialize_distributed(ParallelConfig())
        assert called == [((), {})]     # bare call: TPU metadata autodetect

    def test_explicit_coordinator_wins(self, monkeypatch):
        import milnce_tpu.parallel.mesh as mesh_mod
        from milnce_tpu.config import ParallelConfig

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1w-0,t1w-1")
        called = []
        monkeypatch.setattr(mesh_mod.jax.distributed, "initialize",
                            lambda *a, **k: called.append(k))
        cfg = ParallelConfig(coordinator_address="10.0.0.1:8476",
                             num_processes=2, process_id=1)
        mesh_mod.initialize_distributed(cfg)
        assert called[0]["coordinator_address"] == "10.0.0.1:8476"
        assert called[0]["num_processes"] == 2

    def test_platform_pin_applies_jax_config(self, monkeypatch):
        """--parallel.platform pins the backend via jax.config (env vars
        alone lose to accelerator plugins); '' leaves it untouched."""
        import milnce_tpu.parallel.mesh as mesh_mod
        from milnce_tpu.config import ParallelConfig, parse_cli

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
        updates = []
        monkeypatch.setattr(mesh_mod.jax.config, "update",
                            lambda k, v: updates.append((k, v)))
        mesh_mod.initialize_distributed(ParallelConfig())
        assert updates == []                        # default: no pin
        mesh_mod.initialize_distributed(ParallelConfig(platform="cpu"))
        assert updates == [("jax_platforms", "cpu")]
        # threaded through the CLI front-end
        cfg = parse_cli(["--parallel.platform", "cpu"])
        assert cfg.parallel.platform == "cpu"

    def test_platform_pin_skips_multihost_autojoin(self, monkeypatch):
        """A CPU-pinned hermetic run on a multi-host TPU slice must NOT
        auto-join the pod's distributed cluster (it would block at the
        coordinator barrier waiting for never-launched workers)."""
        import milnce_tpu.parallel.mesh as mesh_mod
        from milnce_tpu.config import ParallelConfig

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1w-0,t1w-1,t1w-2")
        monkeypatch.setattr(mesh_mod.jax.config, "update", lambda k, v: None)
        called = []
        monkeypatch.setattr(mesh_mod.jax.distributed, "initialize",
                            lambda *a, **k: called.append((a, k)))
        mesh_mod.initialize_distributed(ParallelConfig(platform="cpu"))
        assert called == []
        # explicit coordinator still wins even with a pin
        mesh_mod.initialize_distributed(ParallelConfig(
            platform="cpu", coordinator_address="10.0.0.1:8476",
            num_processes=3, process_id=0))
        assert len(called) == 1


@pytest.mark.slow
class TestNaNGuard:
    def test_halts_and_checkpoints_on_nan(self, tmp_path):
        """A synthetic source whose batches drive the loss to NaN must
        halt with FloatingPointError at the first display fetch."""
        from milnce_tpu.config import tiny_preset
        from milnce_tpu.train.loop import run_training

        cfg = tiny_preset()
        cfg.train.checkpoint_root = str(tmp_path / "ckpt")
        cfg.train.log_root = str(tmp_path / "log")
        cfg.train.batch_size = 8
        cfg.data.synthetic_num_samples = 16
        cfg.data.num_reader_threads = 1
        cfg.train.n_display = 1
        cfg.optim.lr = 1e18                # diverge within a couple of steps
        cfg.optim.warmup_steps = 0
        with pytest.raises(FloatingPointError, match="non-finite"):
            run_training(cfg, max_steps=8)
        # post-mortem snapshot exists, OUTSIDE the resume rotation
        pm = tmp_path / "ckpt" / "run" / "nan_postmortem"
        assert pm.is_dir() and any(pm.iterdir())

    def test_guard_disabled_keeps_running(self, tmp_path):
        from milnce_tpu.config import tiny_preset
        from milnce_tpu.train.loop import run_training

        cfg = tiny_preset()
        cfg.train.checkpoint_root = str(tmp_path / "ckpt")
        cfg.train.log_root = str(tmp_path / "log")
        cfg.train.batch_size = 8
        cfg.data.synthetic_num_samples = 16
        cfg.data.num_reader_threads = 1
        cfg.train.n_display = 1
        cfg.train.halt_on_nan = False
        cfg.optim.lr = 1e18
        cfg.optim.warmup_steps = 0
        result = run_training(cfg, max_steps=2)
        assert result.steps == 2


class TestFlagReducer:
    def test_overlap_mode_pipelines_one_boundary_behind(self):
        """overlap=True returns the PREVIOUS boundary's verdict (never
        blocks on the collective it just enqueued): a flag raised at
        boundary k is visible at k+1, uniformly across the mesh
        (ADVICE r4, parallel/mesh.py)."""
        import jax

        from milnce_tpu.config import ParallelConfig
        from milnce_tpu.parallel.mesh import build_mesh, make_flag_reducer

        mesh = build_mesh(ParallelConfig(), jax.devices())

        blocking = make_flag_reducer(mesh)
        assert blocking(False) is False
        assert blocking(True) is True            # same-boundary verdict

        lagged = make_flag_reducer(mesh, overlap=True)
        assert lagged(False) is False            # nothing pending yet
        assert lagged(True) is False             # enqueued, not yet read
        assert lagged(False) is True             # previous boundary's flag
        assert lagged(False) is False
