"""DTW loss family pinned to the REFERENCE math (loss.py:20-134) with
numpy transcriptions — the same way test_milnce.py pins MIL-NCE to
loss.py:6-18.

Each golden below is a line-by-line float64 numpy transcription of the
reference formulas (soft-DTW DP: soft_dtw_cuda.py:186-207; dist funcs:
:325-363; loss compositions: loss.py:20-134), evaluated at the
reference's hardcoded shapes where it has them (world size 8 for CDTW's
``repeat(8,...)``, B=160/n=8/stride-1288 for SDTW_negative).  Deliberate
deviations are tested explicitly and documented inline.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from milnce_tpu.losses.dtw_losses import (cdtw_batch_loss, cdtw_loss,
                                          sdtw_3_loss, sdtw_cidm_loss,
                                          sdtw_negative_loss)


# ------------------------------------------------------------ transcriptions
def np_softdtw(D, gamma):
    """compute_softdtw, soft_dtw_cuda.py:186-207 (float64, inf borders)."""
    B, N, M = D.shape
    R = np.full((B, N + 2, M + 2), np.inf)
    R[:, 0, 0] = 0.0
    for b in range(B):
        for j in range(1, M + 1):
            for i in range(1, N + 1):
                r0 = -R[b, i - 1, j - 1] / gamma
                r1 = -R[b, i - 1, j] / gamma
                r2 = -R[b, i, j - 1] / gamma
                rmax = max(r0, r1, r2)
                rsum = (np.exp(r0 - rmax) + np.exp(r1 - rmax)
                        + np.exp(r2 - rmax))
                softmin = -gamma * (np.log(rsum) + rmax)
                R[b, i, j] = D[b, i - 1, j - 1] + softmin
    return R[:, -2, -2]


def np_cosine_cost(x, y, eps=1e-8):
    """exp(1 - cosine_similarity) (soft_dtw_cuda.py:337-348; torch
    cosine_similarity clamps the norm product at eps)."""
    num = np.einsum("bnd,bmd->bnm", x, y)
    nx = np.linalg.norm(x, axis=-1)[:, :, None]
    ny = np.linalg.norm(y, axis=-1)[:, None, :]
    return np.exp(1.0 - num / np.maximum(nx * ny, eps))


def np_negative_dot_cost(x, y):
    """-<x, y> (soft_dtw_cuda.py:350-363)."""
    return -np.einsum("bnd,bmd->bnm", x, y)


def np_sdtw_cosine(x, y, gamma):
    return np_softdtw(np_cosine_cost(x, y), gamma)


def logsumexp(v, axis=None):
    mx = np.max(v, axis=axis, keepdims=True)
    out = np.log(np.sum(np.exp(v - mx), axis=axis, keepdims=True)) + mx
    return np.squeeze(out, axis=axis) if axis is not None else out.item()


def _seqs(b, n, m, d, seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    return (rng.randn(b, n, d).astype(np.float32) * scale,
            rng.randn(b, m, d).astype(np.float32) * scale)


# ------------------------------------------------------------------- CDTW
class TestCDTWGolden:
    """Reference CDTW (loss.py:20-32): gamma=1e-5 cosine soft-DTW;
    pos = own-pair score of the ``args.rank``-th sample; neg = that
    sample's video against every text (the hardcoded ``repeat(8,...)`` =
    world size 8); loss = pos - logsumexp(neg)."""

    GAMMA = 1e-5
    B = 8  # the reference's hardcoded world size

    def golden(self, v, t, rank):
        pos = np_sdtw_cosine(v[rank:rank + 1], t[rank:rank + 1], self.GAMMA)
        neg = np_sdtw_cosine(np.broadcast_to(v[rank], (self.B,) + v[rank].shape),
                             t, self.GAMMA)
        return pos[0] - logsumexp(neg)

    @pytest.mark.parametrize("rank", [0, 3, 7])
    def test_anchor_loss_matches_reference(self, rank):
        v, t = _seqs(self.B, 5, 5, 6, seed=rank)
        ours = float(cdtw_loss(jnp.asarray(v), jnp.asarray(t), index=rank,
                               gamma=self.GAMMA)[0])
        np.testing.assert_allclose(ours, self.golden(v, t, rank), rtol=2e-4)

    def test_batch_loss_is_mean_over_anchors(self):
        """Documented deviation: our batch-generic form averages the
        reference's per-rank loss over every anchor (identical in
        expectation over ranks — VERDICT r1 / dtw_losses.py:20-29)."""
        v, t = _seqs(self.B, 5, 5, 6, seed=11)
        want = np.mean([self.golden(v, t, r) for r in range(self.B)])
        ours = float(cdtw_batch_loss(jnp.asarray(v), jnp.asarray(t),
                                     gamma=self.GAMMA))
        np.testing.assert_allclose(ours, want, rtol=2e-4)


# ---------------------------------------------------------------- SDTW_CIDM
class TestSDTWCIDMGolden:
    """Reference SDTW_CIDM (loss.py:34-68), gamma=0.1, sigma=10, lam=1.

    The reference's attract/repel terms multiply a (B,B) interval mask
    into a (B,n,n) per-sample FRAME-distance tensor — it only broadcasts
    when B == n and then mixes sample indices with frame indices
    (VERDICT r1 weak #8; SURVEY §2.4).  Our cleaned form defines the
    pair distance on frame-MEAN embeddings, a (B,B) object matching the
    (B,B) mask.  The building blocks shared with the reference (interval
    mask y/w_/w, the soft-DTW term) are pinned to the reference formulas
    exactly; the cleaned I_x/I_y composition is pinned to its own
    documented formula so the semantics cannot drift.
    """

    GAMMA, SIGMA, LAM = 0.1, 10.0, 1.0

    def test_matches_transcription(self):
        b, n, d = 4, 6, 5
        v, t = _seqs(b, n, n, d, seed=3)
        start = np.array([0.0, 4.0, 25.0, 40.0], np.float32)

        # reference loss.py:59-62: y, w_, w from pairwise |start_i-start_j|
        dist = np.abs(start[:, None] - start[None, :])
        y = (dist > self.SIGMA).astype(np.float64)
        w_ = dist + 1.0
        w = 1.0 / w_
        # cleaned pair distance: cosine dist between frame-mean embeddings
        vm, tm = v.mean(1), t.mean(1)

        # raw 1-cos distance (loss.py:40-47 — unlike the soft-DTW cost,
        # the CIDM distance is NOT exponentiated)
        def cos_dist(a):
            num = a @ a.T
            nrm = np.linalg.norm(a, axis=-1)
            return 1.0 - num / np.maximum(nrm[:, None] * nrm[None, :], 1e-8)

        d_x = cos_dist(vm)
        d_y = cos_dist(tm)
        i_x = (y * w_ * np.maximum(self.LAM - d_x, 0.0)
               + (1 - y) * w * d_x).sum(1)
        i_y = (y * w_ * np.maximum(self.LAM - d_y, 0.0)
               + (1 - y) * w * d_y).sum(1)
        # soft-DTW term exactly as the reference (loss.py:67: cosine, 0.1)
        dtw = np_sdtw_cosine(v, t, self.GAMMA)
        want = np.mean(i_x + i_y + dtw)

        ours = float(sdtw_cidm_loss(jnp.asarray(v), jnp.asarray(t),
                                    jnp.asarray(start), gamma=self.GAMMA,
                                    sigma=self.SIGMA, lam=self.LAM))
        np.testing.assert_allclose(ours, want, rtol=1e-4)

    def test_reference_broadcast_requires_b_equals_n(self):
        """Document the defect motivating the deviation: the reference's
        (B,B) mask times (B,n,n) frame distances only broadcasts when
        B == n (loss.py:59-66)."""
        b, n = 4, 6
        mask = np.zeros((b, b))
        frame_dist = np.zeros((b, n, n))
        with pytest.raises(ValueError):
            np.broadcast_arrays(mask, frame_dist)

    def test_exact_broadcast_matches_reference_at_b_equals_n(self):
        """TRANSCRIPTION parity at the only shape where the reference's
        formula is defined: exact_broadcast=True reproduces loss.py:59-67
        — the (B,B) clip mask right-align-broadcast against the (B,n,n)
        per-sample FRAME-distance tensor, sample/frame index mixing and
        all — so the deviation is pinned numerically, not just argued."""
        b = n = 5
        d = 7
        v, t = _seqs(b, n, n, d, seed=9)
        start = np.array([0.0, 3.0, 14.0, 27.0, 55.0], np.float32)

        dist = np.abs(start[:, None] - start[None, :])
        y = (dist > self.SIGMA).astype(np.float64)
        w_ = dist + 1.0
        w = 1.0 / w_

        def frame_cos_dist(a):                     # (B, n, n), loss.py:40-47
            num = np.einsum("bnd,bmd->bnm", a, a)
            nrm = np.linalg.norm(a, axis=-1)
            return 1.0 - num / np.maximum(
                nrm[:, :, None] * nrm[:, None, :], 1e-8)

        d_x = frame_cos_dist(v.astype(np.float64))
        d_y = frame_cos_dist(t.astype(np.float64))
        # torch right-aligns (B,B) -> (1,B,B): clip-pair weights hit
        # frame-pair distances (loss.py:65-66), then .sum(1).sum(1)
        i_x = (y[None] * w_[None] * np.maximum(self.LAM - d_x, 0.0)
               + (1 - y[None]) * w[None] * d_x).sum(axis=(1, 2))
        i_y = (y[None] * w_[None] * np.maximum(self.LAM - d_y, 0.0)
               + (1 - y[None]) * w[None] * d_y).sum(axis=(1, 2))
        dtw = np_sdtw_cosine(v, t, self.GAMMA)
        want = np.mean(i_x + i_y + dtw)

        ours = float(sdtw_cidm_loss(jnp.asarray(v), jnp.asarray(t),
                                    jnp.asarray(start), gamma=self.GAMMA,
                                    sigma=self.SIGMA, lam=self.LAM,
                                    exact_broadcast=True))
        np.testing.assert_allclose(ours, want, rtol=1e-4)

        # and the guard: any other shape is rejected loudly
        v2, t2 = _seqs(4, 6, 6, d, seed=10)
        with pytest.raises(ValueError, match="B == n"):
            sdtw_cidm_loss(jnp.asarray(v2), jnp.asarray(t2),
                           jnp.zeros((4,)), exact_broadcast=True)


# ------------------------------------------------------------ SDTW_negative
class TestSDTWNegativeGolden:
    """Reference SDTW_negative (loss.py:70-91) at its HARDCODED shapes:
    B=160 clips x n=8 frames; the chunk/cat/mask-stride-1288 dance zeroes
    each clip's own 8x8 block of the (1280,1280) frame-pair matrix."""

    GAMMA = 0.1
    B, N = 160, 8

    def test_matches_chunk_mask_transcription(self):
        d = 16
        v, t = _seqs(self.B, self.N, self.N, d, seed=5, scale=0.3)

        # loss.py:80-88, literally:
        pairwise = v.reshape(-1, d).astype(np.float64) @ t.reshape(-1, d).T
        chunks = np.split(pairwise, self.B, axis=0)          # 160 x (8, 1280)
        cat = np.concatenate(chunks, axis=1)                 # (8, 204800)
        mask = [1288 * i + j for i in range(self.B) for j in range(self.N)]
        cat[:, mask] = 0.0
        back = np.concatenate(np.split(cat, self.B, axis=1), axis=0)
        negative = np.exp(back).sum(1).reshape(self.B, self.N).sum(1)

        sdtw = np_sdtw_cosine(v, t, self.GAMMA)
        want = np.mean(sdtw + negative / (self.B - 1))       # loss.py:90

        ours = float(sdtw_negative_loss(jnp.asarray(v), jnp.asarray(t),
                                        gamma=self.GAMMA))
        np.testing.assert_allclose(ours, want, rtol=1e-4)


# ----------------------------------------------------------------- SDTW_3
class TestSDTW3Golden:
    """Reference SDTW_3 (loss.py:93-134): three NCE-over-soft-DTW terms
    with negative_dot distance, gamma=0.1; neg[i,j] = -sdtw(x_j, y_i),
    logsumexp over j."""

    GAMMA = 0.1

    def nce(self, x, y):
        pos = -np_softdtw(np_negative_dot_cost(x, y), self.GAMMA)
        b = x.shape[0]
        neg = np.empty((b, b))
        for i in range(b):
            for j in range(b):
                neg[i, j] = -np_softdtw(
                    np_negative_dot_cost(x[j:j + 1], y[i:i + 1]),
                    self.GAMMA)[0]
        return np.mean(logsumexp(neg, axis=1) - pos)

    def test_all_three_terms_match(self):
        b, n, d = 3, 4, 5
        v, t = _seqs(b, n, n, d, seed=9, scale=0.5)
        want = (self.nce(v, v), self.nce(v, t), self.nce(t, t))
        ours = sdtw_3_loss(jnp.asarray(v), jnp.asarray(t), gamma=self.GAMMA)
        for o, w in zip(ours, want):
            np.testing.assert_allclose(float(o), w, rtol=2e-4)
