"""DTW loss family: shape-generic behavior (spec: reference loss.py:20-134,
with the hardcoded shapes removed per SURVEY.md §1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from milnce_tpu.parallel.compat import shard_map
from milnce_tpu.losses.dtw_losses import (cdtw_loss, sdtw_3_loss,
                                          sdtw_cidm_loss, sdtw_negative_loss)


def _seqs(b=4, n=6, m=5, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(b, n, d).astype(np.float32)),
            jnp.asarray(rng.randn(b, m, d).astype(np.float32)))


def test_cdtw_scalar_and_finite():
    v, t = _seqs()
    out = cdtw_loss(v, t, index=2, gamma=0.1)
    assert out.shape == (1,)
    assert np.isfinite(float(out[0]))


def test_cdtw_anchor_matters():
    v, t = _seqs(seed=1)
    l0 = float(cdtw_loss(v, t, index=0, gamma=0.1)[0])
    l1 = float(cdtw_loss(v, t, index=1, gamma=0.1)[0])
    assert l0 != l1


def test_sdtw_cidm_runs_any_batch_size():
    for b in (2, 5):
        v, t = _seqs(b=b, seed=b)
        start = jnp.asarray(np.arange(b, dtype=np.float32) * 7.0)
        out = sdtw_cidm_loss(v, t, start)
        assert np.isfinite(float(out))


def test_sdtw_negative_any_batch_size():
    """The reference hardcodes B=160, n=8 (loss.py:81-88); ours must not."""
    for b, n in [(3, 4), (5, 2)]:
        v, t = _seqs(b=b, n=n, m=n, seed=b)
        out = sdtw_negative_loss(v, t, gamma=0.1)
        assert np.isfinite(float(out))


def test_sdtw_negative_matches_numpy_formula():
    """Negative term: block-diagonal (own-clip) entries zeroed — exp(0)=1
    still contributes, exactly like the reference mask (loss.py:83-88)."""
    from milnce_tpu.ops.softdtw import SoftDTW

    b, n, d = 3, 4, 8
    rng = np.random.RandomState(7)
    v = rng.randn(b, n, d).astype(np.float32)
    t = rng.randn(b, n, d).astype(np.float32)
    pairwise = v.reshape(-1, d) @ t.reshape(-1, d).T
    for i in range(b):
        pairwise[i * n:(i + 1) * n, i * n:(i + 1) * n] = 0.0
    negative = np.exp(pairwise).sum(1).reshape(b, n).sum(1)
    sdtw = SoftDTW(gamma=0.1, dist_func="cosine")
    pos = np.asarray(sdtw(jnp.asarray(v), jnp.asarray(t)))
    expected = float(np.mean(pos + negative / (b - 1)))
    got = float(sdtw_negative_loss(jnp.asarray(v), jnp.asarray(t), gamma=0.1))
    np.testing.assert_allclose(got, expected, rtol=1e-4)


def test_sdtw3_pair_chunk_parity():
    """ISSUE 12 satellite: ``pair_chunk`` streams each NCE term's
    negative logsumexp over anchor-row chunks (jax.checkpoint'd scan —
    O(B * pair_chunk) pair batches instead of the B^2 broadcast) and
    must match the dense all-pairs form to float tolerance, values AND
    gradients, including the uneven B % pair_chunk != 0 tail."""
    v, t = _seqs(b=5, n=4, m=4, d=8, seed=21)
    dense = sdtw_3_loss(v, t, gamma=0.1)
    for chunk in (2, 3, 5):                     # uneven (5 % 2, 5 % 3) + whole
        chunked = sdtw_3_loss(v, t, gamma=0.1, pair_chunk=chunk)
        for a, b in zip(dense, chunked):
            np.testing.assert_allclose(float(b), float(a), rtol=1e-4,
                                       atol=1e-5)
    g_dense = jax.grad(lambda a: sum(sdtw_3_loss(a, t, gamma=0.1)))(v)
    g_chunk = jax.grad(
        lambda a: sum(sdtw_3_loss(a, t, gamma=0.1, pair_chunk=2)))(v)
    np.testing.assert_allclose(np.asarray(g_chunk), np.asarray(g_dense),
                               atol=1e-5)
    # pair_chunk=0 (and >= B) keeps the dense program — the pinned
    # train_step_sdtw3 trace never moves by default
    full = sdtw_3_loss(v, t, gamma=0.1, pair_chunk=0)
    for a, b in zip(dense, full):
        assert float(a) == float(b)


def test_sequence_loss_threads_pair_chunk(monkeypatch):
    """loss.sdtw_pair_chunk must reach sdtw_3_loss through the
    train-step dispatcher (a config-only dead knob would leave the
    streamed form unreachable in production).  A capturing fake stands
    in for the DP — the dispatcher imports it at call time, so the
    monkeypatch intercepts the real forwarding path at trace cost only
    (the streamed values themselves are pinned by the parity test
    above)."""
    import jax as _jax
    from jax.sharding import Mesh, PartitionSpec as P

    import milnce_tpu.losses.dtw_losses as dtw_mod
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import _sequence_loss

    seen = {}

    def fake_sdtw_3(v_all, t_all, pair_chunk=0, **kw):
        seen["pair_chunk"] = pair_chunk
        zero = jnp.float32(0)
        return (zero, zero, zero)

    monkeypatch.setattr(dtw_mod, "sdtw_3_loss", fake_sdtw_3)
    v, t = _seqs(b=8, n=3, m=3, d=4, seed=17)
    start = jnp.zeros((8,))
    mesh = Mesh(np.asarray(_jax.devices()), ("data",))
    cfg = LossConfig(name="sdtw_3", sdtw_gamma=0.1, sdtw_pair_chunk=3)
    fn = shard_map(
        lambda a, b_, s: _sequence_loss(cfg, a, b_, s, "data"),
        mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=P(), check_vma=False)
    _jax.make_jaxpr(fn)(v, t, start)     # trace is enough to dispatch
    assert seen["pair_chunk"] == 3, "sdtw_pair_chunk never reached the dp"


@pytest.mark.slow
def test_sdtw3_three_terms_and_gradients():
    v, t = _seqs(b=3, n=4, m=4, seed=9)
    l1, l2, l3 = sdtw_3_loss(v, t, gamma=0.1)
    for l in (l1, l2, l3):
        assert np.isfinite(float(l))
    g = jax.grad(lambda a: sum(sdtw_3_loss(a, t, gamma=0.1)))(v)
    assert np.isfinite(np.asarray(g)).all()


def test_dist_and_bandwidth_knobs_reach_the_dp():
    """--loss.sdtw_dist / --loss.sdtw_bandwidth must actually change the
    computation (they were once config-only dead knobs); '' keeps each
    loss's reference default distance."""
    from milnce_tpu.losses.dtw_losses import cdtw_batch_loss

    v, t = _seqs(b=3, n=4, m=4, seed=11)
    base = float(cdtw_batch_loss(v, t, gamma=0.1))
    assert base == float(cdtw_batch_loss(v, t, gamma=0.1, dist="cosine"))
    assert base != float(cdtw_batch_loss(v, t, gamma=0.1, dist="negative_dot"))
    assert base != float(cdtw_batch_loss(v, t, gamma=0.1, bandwidth=1))
    l3 = sdtw_3_loss(v, t, gamma=0.1)                     # negative_dot default
    l3_override = sdtw_3_loss(v, t, gamma=0.1, dist="cosine")
    assert float(l3[1]) != float(l3_override[1])


@pytest.mark.slow
def test_sequence_loss_threads_config_knobs():
    """The train-step dispatcher forwards dist/bandwidth from LossConfig."""
    from jax.sharding import Mesh
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import _sequence_loss
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    v, t = _seqs(b=8, n=4, m=4, seed=12)
    start = jnp.zeros((8,))
    mesh = Mesh(np.asarray(_jax.devices()), ("data",))

    def run(cfg):
        fn = shard_map(
            lambda a, b_, s: _sequence_loss(cfg, a, b_, s, "data"),
            mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
            out_specs=P(), check_vma=False)
        return float(fn(v, t, start))

    base = run(LossConfig(name="cdtw", sdtw_gamma=0.1))
    banded = run(LossConfig(name="cdtw", sdtw_gamma=0.1, sdtw_bandwidth=1))
    distd = run(LossConfig(name="cdtw", sdtw_gamma=0.1,
                           sdtw_dist="negative_dot"))
    assert base != banded and base != distd


@pytest.mark.slow
def test_sequence_loss_per_loss_gamma_defaults():
    """sdtw_gamma=None resolves to each loss's reference default: 1e-5
    for cdtw (loss.py:26), 0.1 for the sdtw_* family (loss.py:38,74,97);
    an explicit value overrides."""
    from jax.sharding import Mesh, PartitionSpec as P
    import jax as _jax
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import _sequence_loss

    v, t = _seqs(b=8, n=4, m=4, seed=13)
    start = jnp.zeros((8,))
    mesh = Mesh(np.asarray(_jax.devices()), ("data",))

    def run(cfg):
        fn = shard_map(
            lambda a, b_, s: _sequence_loss(cfg, a, b_, s, "data"),
            mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
            out_specs=P(), check_vma=False)
        return float(fn(v, t, start))

    assert run(LossConfig(name="cdtw")) == run(
        LossConfig(name="cdtw", sdtw_gamma=1e-5))
    assert run(LossConfig(name="cdtw")) != run(
        LossConfig(name="cdtw", sdtw_gamma=0.1))
    assert run(LossConfig(name="sdtw_3")) == run(
        LossConfig(name="sdtw_3", sdtw_gamma=0.1))
