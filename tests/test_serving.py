"""Serving subsystem gates (ISSUE 4): bucketed engine, device-resident
index, cache, service, HTTP front — and the served-vs-offline parity
pin: top-k through the full batcher -> engine -> index path must equal
the offline eval/retrieval.py ranking exactly.

Everything runs on the hermetic 8-virtual-CPU mesh (conftest.py); one
module-scoped stack keeps the compile bill to one warmup sweep."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

_FRAMES, _SIZE, _WORDS = 4, 32, 6
_CORPUS = 21


@pytest.fixture(scope="module")
def stack():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from milnce_tpu.data.tokenizer import Tokenizer, synthetic_vocab
    from milnce_tpu.models import S3D
    from milnce_tpu.serving.cache import EmbeddingLRUCache
    from milnce_tpu.serving.engine import InferenceEngine
    from milnce_tpu.serving.index import DeviceRetrievalIndex
    from milnce_tpu.serving.service import RetrievalService

    model = S3D(num_classes=16, vocab_size=64, word_embedding_dim=8,
                text_hidden_dim=16, inception_blocks=1)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, _FRAMES, _SIZE, _SIZE, 3)),
                           jnp.zeros((1, _WORDS), jnp.int32))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    engine = InferenceEngine(model, dict(variables), mesh,
                             text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=16)
    rng = np.random.default_rng(0)
    clips = rng.integers(0, 255, (_CORPUS, _FRAMES, _SIZE, _SIZE, 3),
                         dtype=np.uint8)
    corpus_emb = np.concatenate(
        [engine.embed_video(clips[:16]), engine.embed_video(clips[16:])])
    index = DeviceRetrievalIndex(mesh, corpus_emb, k=5,
                                 query_buckets=engine.buckets)
    service = RetrievalService(
        engine, index, tokenizer=Tokenizer(synthetic_vocab(63), _WORDS),
        cache=EmbeddingLRUCache(128), max_delay_ms=3.0)
    yield dict(model=model, variables=variables, mesh=mesh, engine=engine,
               clips=clips, corpus_emb=corpus_emb, index=index,
               service=service)
    service.close()


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class TestEngine:
    def test_bucket_ladder_on_the_test_mesh(self, stack):
        # 8 virtual devices -> ladder starts at the mesh size
        assert stack["engine"].buckets == (8, 16)

    @pytest.mark.parametrize("n,bucket", [(1, 8), (8, 8), (9, 16), (16, 16)])
    def test_bucket_for_boundaries(self, stack, n, bucket):
        assert stack["engine"].bucket_for(n) == bucket

    def test_oversize_batch_rejected(self, stack):
        with pytest.raises(ValueError, match="max_batch"):
            stack["engine"].bucket_for(17)

    def test_wrong_trailing_shape_rejected(self, stack):
        eng = stack["engine"]
        with pytest.raises(ValueError, match="token ids"):
            eng.embed_text(np.zeros((2, _WORDS + 1), np.int32))
        with pytest.raises(ValueError, match="uint8 video"):
            eng.embed_video(np.zeros((2, _FRAMES, _SIZE, 16, 3), np.uint8))

    def test_pad_unpad_identity(self, stack):
        """Rows of a padded partial batch == the same rows embedded in a
        full bucket: padding slots never leak into real rows."""
        eng = stack["engine"]
        rng = np.random.default_rng(1)
        ids = rng.integers(1, 64, (5, _WORDS)).astype(np.int32)
        five = eng.embed_text(ids)                     # pads 5 -> 8
        singles = np.stack([eng.embed_text(ids[i:i + 1])[0]  # pads 1 -> 8
                            for i in range(5)])
        np.testing.assert_allclose(five, singles, rtol=1e-5, atol=1e-6)

    def test_ladder_sweep_causes_zero_recompiles(self, stack):
        eng = stack["engine"]
        rng = np.random.default_rng(2)
        for n in (1, 3, 8, 11, 16):
            eng.embed_text(rng.integers(1, 64, (n, _WORDS)).astype(np.int32))
            eng.embed_video(rng.integers(
                0, 255, (n, _FRAMES, _SIZE, _SIZE, 3), dtype=np.uint8))
        assert eng.recompiles() == 0

    def test_concurrent_call_accounting_is_exact(self, stack):
        """ISSUE 7 regression: the engine's per-(entry, bucket) call
        dict is written from the batcher worker AND request threads
        while /healthz readers iterate it — the old unlocked
        read-modify-write lost increments under contention (graftlint
        GL010).  N threads x K embeds must land EXACTLY N*K counts,
        with stats() readers racing the whole time."""
        eng = stack["engine"]
        key = "text@8"
        before = eng.stats()["calls"].get(key, 0)
        n_threads, k = 6, 4
        ids = np.ones((1, _WORDS), np.int32)
        stop = threading.Event()
        errors = []

        def embedder():
            try:
                for _ in range(k):
                    eng.embed_text(ids)
            except Exception as exc:  # pragma: no cover - the assert
                errors.append(exc)    # below is the real check

        def reader():
            while not stop.is_set():
                s = eng.stats()
                assert s["calls"].get(key, 0) >= before

        threads = [threading.Thread(target=embedder)
                   for _ in range(n_threads)]
        readers = [threading.Thread(target=reader) for _ in range(2)]
        for t in readers + threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        for t in readers:
            t.join(timeout=30)
        assert not errors, errors
        assert eng.stats()["calls"][key] == before + n_threads * k


# ---------------------------------------------------------------------------
# index
# ---------------------------------------------------------------------------

class TestIndex:
    def test_topk_matches_numpy_ranking(self, stack):
        index, corpus_emb = stack["index"], stack["corpus_emb"]
        rng = np.random.default_rng(3)
        q = rng.standard_normal((5, corpus_emb.shape[1])).astype(np.float32)
        scores, idx = index.topk(q)
        ref = np.argsort(-(q @ corpus_emb.T), axis=1)[:, :index.k]
        assert np.array_equal(idx, ref)
        np.testing.assert_allclose(
            scores, np.take_along_axis(q @ corpus_emb.T, ref, axis=1),
            rtol=1e-5, atol=1e-5)

    def test_pad_rows_never_retrieved(self, stack):
        # every returned index addresses a REAL corpus row (pads are -inf)
        index = stack["index"]
        rng = np.random.default_rng(4)
        q = rng.standard_normal((3, index.dim)).astype(np.float32)
        _, idx = index.topk(q)
        assert idx.max() < index.size

    def test_query_bucket_overflow_rejected(self, stack):
        index = stack["index"]
        with pytest.raises(ValueError, match="query bucket"):
            index.topk(np.zeros((17, index.dim), np.float32))

    def test_k_bounds_validated(self, stack):
        from milnce_tpu.serving.index import DeviceRetrievalIndex

        with pytest.raises(ValueError, match="outside"):
            DeviceRetrievalIndex(stack["mesh"], stack["corpus_emb"],
                                 k=_CORPUS + 1, precompile=False)

    def test_concurrent_topk_call_accounting_is_exact(self, stack):
        """ISSUE 7 regression: `self._calls += 1` straight off request
        threads lost increments (graftlint GL010) — N threads x K
        queries must count exactly."""
        index = stack["index"]
        before = index.stats()["calls"]
        n_threads, k = 6, 4
        q = np.zeros((1, index.dim), np.float32)
        threads = [threading.Thread(
            target=lambda: [index.topk(q) for _ in range(k)])
            for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert index.stats()["calls"] == before + n_threads * k

    def test_geometry_follows_data_axis_on_a_model_parallel_mesh(self,
                                                                 stack):
        """On a (data, model) mesh, rows shard over DATA only (P(data)
        replicates over model) — geometry sized by the total device
        count would mask most of every shard's corpus to -inf and
        silently drop it from retrieval."""
        import jax
        from jax.sharding import Mesh

        from milnce_tpu.serving.index import DeviceRetrievalIndex

        mesh2d = Mesh(np.array(jax.devices()).reshape(4, 2),
                      ("data", "model"))
        corpus_emb = stack["corpus_emb"]
        index = DeviceRetrievalIndex(mesh2d, corpus_emb, k=5,
                                     query_buckets=(4,))
        rng = np.random.default_rng(6)
        q = rng.standard_normal((4, corpus_emb.shape[1])).astype(np.float32)
        _, idx = index.topk(q)
        ref = np.argsort(-(q @ corpus_emb.T), axis=1)[:, :5]
        assert np.array_equal(idx, ref)

    def test_engine_bucket_ladder_follows_data_axis(self, stack):
        import jax
        from jax.sharding import Mesh

        from milnce_tpu.serving.engine import InferenceEngine

        mesh2d = Mesh(np.array(jax.devices()).reshape(4, 2),
                      ("data", "model"))
        eng = InferenceEngine(
            stack["model"], dict(stack["variables"]), mesh2d,
            text_words=_WORDS, video_shape=(_FRAMES, _SIZE, _SIZE, 3),
            max_batch=16, precompile=False)
        assert eng.buckets == (4, 8, 16)   # data extent 4, not 8 devices


# ---------------------------------------------------------------------------
# service (cache + batcher + engine + index) and the parity pin
# ---------------------------------------------------------------------------

class TestService:
    def test_served_topk_equals_offline_eval_ranking(self, stack):
        """ISSUE 4 acceptance: a synthetic corpus queried through the
        FULL serve path (token rows -> dynamic batcher -> bucketed
        engine -> sharded device index) ranks exactly as the offline
        eval/retrieval.py extraction + argsort."""
        from milnce_tpu.eval.retrieval import extract_retrieval_embeddings

        clips, service = stack["clips"], stack["service"]
        rng = np.random.default_rng(5)
        texts = rng.integers(1, 64, (_CORPUS, _WORDS)).astype(np.int32)

        class _Source:
            def __len__(self):
                return _CORPUS

            def sample(self, i, rng=None):
                return {"video": clips[i:i + 1], "text": texts[i:i + 1]}

        t_emb, v_emb = extract_retrieval_embeddings(
            stack["model"], dict(stack["variables"]), _Source(),
            stack["mesh"], batch_size=8)
        offline = np.argsort(-(t_emb @ v_emb.T), axis=1)[:, :5]

        # serve the same corpus: many threads, one row each, so the
        # batcher actually batches (not one pre-formed request)
        results = [None] * _CORPUS

        def one(i):
            _, idx = service.query_ids(texts[i:i + 1])
            results[i] = idx[0]

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(_CORPUS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        served = np.stack(results)
        assert np.array_equal(served, offline), (
            "served top-k diverged from the offline eval ranking")
        # the batcher actually coalesced: fewer flushes than requests
        flushes = service.health()["batcher"]["flushes"]
        assert flushes < _CORPUS

    def test_pooled_serving_matches_single_engine_and_offline_ranking(
            self, stack):
        """ISSUE 10 parity pin: pooled serving (2 single-device replicas,
        concurrent request threads, hedge/requeue machinery in place)
        returns rankings EXACTLY equal to the single-engine path and the
        offline argsort for the same queries — replicas are exact peers
        of the 8-device engine (the embed programs are collective-free
        row-wise maps, so device-group shape cannot change the math)."""
        from milnce_tpu.obs import metrics as obs_metrics
        from milnce_tpu.serving.cache import EmbeddingLRUCache
        from milnce_tpu.serving.pool import ReplicaPool
        from milnce_tpu.serving.service import RetrievalService

        engine, index = stack["engine"], stack["index"]
        rng = np.random.default_rng(9)
        texts = rng.integers(1, 64, (_CORPUS, _WORDS)).astype(np.int32)
        t_emb = np.concatenate([engine.embed_text(texts[:16]),
                                engine.embed_text(texts[16:])])
        offline = np.argsort(-(t_emb @ stack["corpus_emb"].T),
                             axis=1)[:, :5]
        single = np.stack([stack["service"].query_ids(texts[i:i + 1])[1][0]
                           for i in range(_CORPUS)])
        pool = ReplicaPool.build(
            stack["model"], dict(stack["variables"]), 2,
            text_words=_WORDS, video_shape=(_FRAMES, _SIZE, _SIZE, 3),
            max_batch=8, min_bucket=4,
            registry=obs_metrics.MetricsRegistry())
        service = RetrievalService(pool, index,
                                   cache=EmbeddingLRUCache(0),
                                   max_delay_ms=3.0)
        try:
            results = [None] * _CORPUS

            def one(i):
                _, idx = service.query_ids(texts[i:i + 1])
                results[i] = idx[0]

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(_CORPUS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            served = np.stack(results)
            assert np.array_equal(served, offline), (
                "pooled top-k diverged from the offline eval ranking")
            assert np.array_equal(served, single), (
                "pooled top-k diverged from the single-engine path")
            assert pool.recompiles() == 0
        finally:
            service.close()
            pool.close()

    def test_cache_hits_skip_the_device(self, stack):
        service = stack["service"]
        ids = np.full((1, _WORDS), 7, np.int32)
        service.embed_text_ids(ids)
        calls_before = dict(service.engine.stats()["calls"])
        before_hits = service.cache.stats()["hits"]
        out = service.embed_text_ids(ids)
        assert service.cache.stats()["hits"] == before_hits + 1
        assert service.engine.stats()["calls"] == calls_before  # no dispatch
        assert out.shape == (1, service.engine.embed_dim)

    def test_query_k_validation(self, stack):
        with pytest.raises(ValueError, match="outside"):
            stack["service"].query_ids(np.ones((1, _WORDS), np.int32), k=99)

    def test_health_surfaces_resilience_counters(self, stack):
        h = stack["service"].health()
        assert h["status"] == "ok"
        assert h["engine"]["recompiles"] == 0
        assert h["index"]["recompiles"] == 0
        for key in ("requests", "flushes", "deadline_expired",
                    "batch_errors", "occupancy"):
            assert key in h["batcher"]
        assert 0.0 <= h["cache"]["hit_rate"] <= 1.0


# ---------------------------------------------------------------------------
# LRU cache (host-only)
# ---------------------------------------------------------------------------

class TestCache:
    def test_lru_eviction_order(self):
        from milnce_tpu.serving.cache import EmbeddingLRUCache

        c = EmbeddingLRUCache(capacity=2)
        c.put((1,), np.array([1.0]))
        c.put((2,), np.array([2.0]))
        assert c.get((1,)) is not None      # refresh 1 -> 2 is now LRU
        c.put((3,), np.array([3.0]))
        assert c.get((2,)) is None
        assert c.get((1,)) is not None and c.get((3,)) is not None

    def test_disabled_cache_never_stores(self):
        from milnce_tpu.serving.cache import EmbeddingLRUCache

        c = EmbeddingLRUCache(capacity=0)
        c.put((1,), np.array([1.0]))
        assert c.get((1,)) is None and len(c) == 0

    def test_stored_rows_are_immutable(self):
        from milnce_tpu.serving.cache import EmbeddingLRUCache

        c = EmbeddingLRUCache(capacity=4)
        c.put((1,), np.array([1.0, 2.0]))
        row = c.get((1,))
        with pytest.raises(ValueError):
            row[0] = 99.0


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def http_server(stack):
    from milnce_tpu.serving.service import serve_http

    server = serve_http(stack["service"], port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestHTTP:
    def test_healthz(self, http_server):
        with urllib.request.urlopen(f"{http_server}/healthz",
                                    timeout=30) as r:
            body = json.loads(r.read())
        assert r.status == 200 and body["status"] == "ok"
        assert body["engine"]["recompiles"] == 0

    def test_query_by_sentences(self, stack, http_server):
        status, body = _post(f"{http_server}/v1/query",
                             {"sentences": ["word1 word2"], "k": 3})
        assert status == 200
        (res,) = body["results"]
        assert len(res["indices"]) == 3 == len(res["scores"])
        assert all(0 <= i < stack["index"].size for i in res["indices"])

    def test_query_by_token_ids_matches_programmatic(self, stack,
                                                     http_server):
        ids = [[1, 2, 3, 0, 0, 0]]
        status, body = _post(f"{http_server}/v1/query", {"token_ids": ids})
        assert status == 200
        _, idx = stack["service"].query_ids(np.asarray(ids, np.int32))
        assert body["results"][0]["indices"] == idx[0].tolist()

    def test_embed_endpoint(self, stack, http_server):
        status, body = _post(f"{http_server}/v1/embed_text",
                             {"token_ids": [[1, 2, 3, 0, 0, 0]]})
        assert status == 200
        assert np.asarray(body["embeddings"]).shape == (
            1, stack["service"].engine.embed_dim)

    def test_bad_request_is_400(self, http_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"{http_server}/v1/query", {"nonsense": True})
        assert exc.value.code == 400

    def test_unknown_route_is_404(self, http_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"{http_server}/v1/nope", {})
        assert exc.value.code == 404

    def test_metrics_prometheus_exposition(self, stack, http_server):
        """ISSUE 5 acceptance: GET /metrics on a live service returns
        valid Prometheus text — request counters, batcher occupancy
        histogram, cache hit rate, recompile gauge."""
        # guarantee traffic has flowed through the request path
        stack["service"].query_ids(
            np.zeros((1, stack["service"].engine.text_words), np.int32))
        with urllib.request.urlopen(f"{http_server}/metrics",
                                    timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            text = r.read().decode()
        assert "# TYPE milnce_serve_requests_total counter" in text
        assert "# TYPE milnce_serve_batch_occupancy histogram" in text
        assert 'milnce_serve_batch_occupancy_bucket{batcher="text",' in text
        assert "# TYPE milnce_serve_cache_hit_rate gauge" in text
        assert "milnce_serve_engine_recompiles 0" in text
        assert "milnce_serve_queries_total" in text
        # /healthz keys stay backward-compatible AND agree with the
        # exposition (one source of truth for both surfaces)
        health = stack["service"].health()
        assert (f"milnce_serve_queries_total {health['queries']}"
                in text)
        assert (f"milnce_serve_requests_total{{batcher=\"text\"}} "
                f"{health['batcher']['requests']}" in text)

    def test_obs_events_ring_over_http(self, stack, http_server):
        stack["service"].query_ids(
            np.zeros((1, stack["service"].engine.text_words), np.int32))
        with urllib.request.urlopen(f"{http_server}/obs/events?n=50",
                                    timeout=30) as r:
            body = json.loads(r.read())
        events = body["events"]
        assert isinstance(events, list) and len(events) <= 50
        # the batcher worker's flush spans land on the process recorder
        assert any(e.get("name") == "batcher.flush" for e in events)

    def test_obs_events_bad_n_is_400(self, http_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{http_server}/obs/events?n=abc",
                                   timeout=30)
        assert exc.value.code == 400

    def test_obs_events_since_filters_incrementally(self, stack,
                                                    http_server):
        """ISSUE 9 satellite: ?since=<mono> returns only records
        appended after that cursor, so pollers stop re-downloading the
        whole ring."""
        words = stack["service"].engine.text_words
        stack["service"].query_ids(
            np.zeros((1, words), np.int32))
        with urllib.request.urlopen(f"{http_server}/obs/events",
                                    timeout=30) as r:
            events = json.loads(r.read())["events"]
        assert events and all("mono" in e for e in events)
        cursor = events[-1]["mono"]
        with urllib.request.urlopen(
                f"{http_server}/obs/events?since={cursor}",
                timeout=30) as r:
            assert json.loads(r.read())["events"] == []
        # new traffic -> only the new records come back.  The row must
        # be a row no test has embedded before: a repeat is a CACHE HIT
        # answered on host — no flush, no new events (that's the cache
        # working, not the filter failing)
        fresh = (np.arange(words, dtype=np.int32)[None, :] % 50) + 11
        stack["service"].query_ids(fresh)
        with urllib.request.urlopen(
                f"{http_server}/obs/events?since={cursor}",
                timeout=30) as r:
            newer = json.loads(r.read())["events"]
        assert newer and all(e["mono"] > cursor for e in newer)

    def test_obs_events_bad_since_is_400(self, http_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"{http_server}/obs/events?since=yesterday", timeout=30)
        assert exc.value.code == 400

    def test_obs_capture_404_without_capture(self, http_server):
        # this module's service is built without a ProfilerCapture
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"{http_server}/obs/capture", {})
        assert exc.value.code == 404

    def test_obs_capture_arms_injected_capture(self, stack, tmp_path):
        """POST /obs/capture arms the bounded one-shot capture; the
        budget's refusal reason comes back as JSON (ISSUE 9)."""
        from milnce_tpu.obs.capture import ProfilerCapture
        from milnce_tpu.serving.service import serve_http

        calls = {"start": 0, "stop": 0}
        cap = ProfilerCapture(
            str(tmp_path), duration_s=1000.0, max_captures=1,
            start_fn=lambda d: calls.__setitem__("start",
                                                calls["start"] + 1),
            stop_fn=lambda: calls.__setitem__("stop", calls["stop"] + 1))
        service = stack["service"]
        old_cap = service.capture
        service.capture = cap
        server = serve_http(service, port=0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            status, body = _post(f"{base}/obs/capture",
                                 {"reason": "drill"})
            assert status == 200 and body["armed"]
            assert "capture_001-drill" in body["trace_dir"]
            assert calls["start"] == 1
            # active -> refused with a reason, not double-started
            status, body = _post(f"{base}/obs/capture", {})
            assert status == 200 and not body["armed"]
            assert "reason" in body
            cap.stop()
            assert calls["stop"] == 1
        finally:
            service.capture = old_cap
            server.shutdown()
            server.server_close()