"""Throughput benchmark: clips/sec/chip of the full jitted train step
(S3D-G fwd+bwd + MIL-NCE + Adam) on synthetic data.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md: "to be
established"), so vs_baseline is measured against a fixed reference
point recorded on first TPU runs (see BASELINE_THROUGHPUT below) —
1.0 until a history exists.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


# clips/sec/chip anchor for vs_baseline; updated as rounds establish history.
BASELINE_THROUGHPUT = None  # none published (BASELINE.md)


def main():
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), "build", "jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())

    import jax.numpy as jnp

    from milnce_tpu.config import full_preset
    from milnce_tpu.models.build import build_model
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step
    from milnce_tpu.data.pipeline import device_prefetch

    cfg = full_preset()
    # Bench config: 16-frame 224^2 clips (the reference's published GPU
    # configs, README.md:114-129), batch sized for one chip.
    frames, size, words, k = 16, 224, 20, 5
    batch = 16 if on_tpu else 2
    if not on_tpu:
        frames, size = 4, 64

    cfg.model.vocab_size = 66250
    model = build_model(cfg.model)
    mesh = build_mesh(cfg.parallel)

    rng = np.random.RandomState(0)
    video = rng.randint(0, 255, (batch, frames, size, size, 3), np.uint8)
    text = rng.randint(0, 66250, (batch * k, words)).astype(np.int32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, frames, size, size, 3), jnp.float32),
                           jnp.zeros((2 * k, words), jnp.int32))
    optimizer = build_optimizer(cfg.optim, build_schedule(cfg.optim, 1000))
    state = create_train_state(variables, optimizer)
    step_fn = make_train_step(model, optimizer, mesh)

    video_d = jax.device_put(video)
    text_d = jax.device_put(text)
    start_d = jax.device_put(np.zeros((batch,), np.float32))

    # warmup / compile
    state, loss = step_fn(state, video_d, text_d, start_d)
    jax.block_until_ready(loss)

    n_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step_fn(state, video_d, text_d, start_d)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    clips_per_sec_per_chip = batch * n_steps / dt / n_chips
    result = {
        "metric": f"train_step clips/sec/chip ({frames}f@{size})",
        "value": round(clips_per_sec_per_chip, 3),
        "unit": "clips/sec/chip",
        "vs_baseline": (round(clips_per_sec_per_chip / BASELINE_THROUGHPUT, 3)
                        if BASELINE_THROUGHPUT else 1.0),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
