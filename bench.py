"""Throughput benchmark: clips/sec/chip of the full jitted train step
(S3D-G fwd+bwd + MIL-NCE + Adam) on synthetic data.

Streams one-line JSON records to stdout:
    {"metric", "value", "unit", "vs_baseline", ...}
**Consumers take the LAST parsable record line** — an interim
best-so-far is emitted after every measured config (forwarded upward by
the parent as it arrives), superseded by the final record, so ANY exit
— crash, tunnel hang, even a hard kill of the parent mid-sweep — leaves
the best measurement so far on stdout.  Backend init is guarded (probe,
CPU-fallback re-exec, then a parsable error record): the process never
exits without at least one record line.  Detailed sweep results
(per-dtype, per-batch, MFU) go to stderr and ``BENCH_NOTES.md``.

The reference publishes no throughput numbers (BASELINE.md: "to be
established"); the headline metric is the best clips/sec/chip across the
{bfloat16, float32} x batch sweep at 16f@224^2 (the reference's
published GPU input config, /root/reference/README.md:114-129).
``vs_baseline`` is measured against BASELINE_THROUGHPUT once a first
real-TPU number exists in round history; 1.0 until then.

Mesh sweep axis (ISSUE 6): ``MILNCE_BENCH_MESH=data,model[=N]`` runs
the whole sweep on the 2-D FSDP grid (state sharded per
parallel/sharding_map.py; batch over both axes); by default a
``mesh_2d`` comparison row is measured at the winning 1-D operating
point.  Every record carries its mesh shape and sharding-map hash so
``obs_report --check`` compares like with like, and a 2-D row whose
map shards nothing is REFUSED rather than measured as fake FSDP.
Related knobs: MILNCE_BENCH_FSDP_MIN (threshold override),
MILNCE_BENCH_MESH_2D=0 (skip the comparison row).

Curriculum axis (ISSUE 16): ``MILNCE_BENCH_CURRICULUM=<train.curriculum
spec>`` measures every stage of a staged-resolution schedule as its own
row (stage shape, winning dtype) and reports the whole-schedule
clips/sec against a flat full-res run of the same total clip count —
the measured answer to "what does the curriculum buy".  Stages must be
``until_step``-bounded; the open-ended final stage defaults to the
bounded stages' total steps (override:
MILNCE_BENCH_CURRICULUM_STEPS).  Stage rows ride in the record under
``curriculum`` and in BENCH_NOTES.md with a ``stage`` column; they
never displace the headline sweep measurement.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
_CHILD_MODE_ENV = "MILNCE_BENCH_CHILD_MODE"  # "cpu" | "tpu"
_CONFIG_ENV = "MILNCE_BENCH_CONFIG_JSON"     # one-config measurement child
_INFO_ENV = "MILNCE_BENCH_DEVICE_INFO"       # probe's device info, reused

# clips/sec/chip anchor for vs_baseline: the first recorded real-TPU
# operating point (round-2 session, v5e, bfloat16 batch 256 @16f/224 —
# BENCH_NOTES.md).  Later rounds report speedup against it.  Only
# meaningful for on-TPU runs; CPU fallbacks report vs_baseline for
# completeness but are not comparable.  NOTE: recorded with
# latency-inclusive timing (the record's anchor_timing field says so);
# the best measurement under the current differenced+materialized
# method is LAST_TPU_OPERATING_POINT.
BASELINE_THROUGHPUT = 95.35

# best honest (differenced + host-materialized) real-TPU measurement so
# far — what a CPU-fallback record should point readers at
LAST_TPU_OPERATING_POINT = 392.95

# Peak dense matmul FLOP/s per chip: single-sourced from
# utils/roofline.py (the train loop's live MFU gauge shares the SAME
# table + formula, so the two diagnostics can never disagree on what
# "peak" means).  Imported LAZILY with a fallback: the orchestrator
# must keep its never-exits-without-a-record contract even on a
# bring-up host where the package import path is broken — the MFU
# diagnostic is the only thing lost there.
_FALLBACK_MAX_PEAK = 918e12     # v6e, the table's ceiling: keeps the
#                                 measurement plausibility bound armed
#                                 if the package table is unreachable


def _roofline():
    try:
        from milnce_tpu.utils import roofline
    except ImportError:
        return None
    return roofline


def _emit(result):
    sys.stdout.write(json.dumps(result) + "\n")
    sys.stdout.flush()


def _last_tagged_json(raw: bytes, predicate):
    """The last JSON object in ``raw`` whose dict satisfies ``predicate``
    (the streaming protocols all agree: later lines supersede earlier
    ones; stray JSON-shaped log lines are filtered by the predicate)."""
    for line in reversed(raw.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if isinstance(rec, dict) and predicate(rec):
                return rec
    return None


def _last_json(raw: bytes):
    """The last parsable bench record in a child's captured stdout (the
    interim-streaming protocol: later records supersede earlier ones)."""
    return _last_tagged_json(raw, lambda r: "metric" in r and "value" in r)


def _note(msg):
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def _peak_flops(device_kind: str):
    rl = _roofline()
    return rl.device_peak_flops(device_kind) if rl else None


def _probe_device_json(run_execute: bool, force_cpu: bool, timeout_s: float):
    """Shared device-probe subprocess: spawn a throwaway python, optionally
    pin it to CPU (via jax.config — the JAX_PLATFORMS env var is
    overridden by accelerator plugins), optionally run one tiny jitted
    execute, and print the device-info JSON.  TERM-first on timeout
    (_graceful_stop) and registered as the active child so the SIGTERM
    forwarder reaches a probe that happens to be live when the parent's
    budget expires.  Returns (info_dict_or_None, error_text_or_None)."""
    global _ACTIVE_CHILD_PROC
    pin = ("jax.config.update('jax_platforms', 'cpu'); "
           if force_cpu else "")
    execute = ("v = float(jax.jit(lambda: jnp.ones(4).sum())()); "
               if run_execute else "v = None; ")
    code = ("import json, jax, jax.numpy as jnp; " + pin + execute +
            "d = jax.devices(); "
            "print(json.dumps({'platform': d[0].platform, "
            "'kind': str(getattr(d[0], 'device_kind', d[0].platform)), "
            "'n': len(d), 'probe_value': v}))")
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=_REPO,
                            env=dict(os.environ), stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    _ACTIVE_CHILD_PROC = proc
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _graceful_stop(proc)
        return None, f"hung >{timeout_s}s"
    finally:
        _ACTIVE_CHILD_PROC = None
    if proc.returncode != 0:
        return None, (f"rc={proc.returncode}: "
                      f"{err.decode(errors='replace')[-300:]}")
    info = _last_tagged_json(out, lambda r: "platform" in r)
    if info is None:
        return None, "printed no device info"
    return info, None


def _probe_backend(timeout_s: float = 180.0):
    """Initialize the accelerator backend AND run one tiny jitted execute
    in a THROWAWAY subprocess first.

    Three observed failure modes of the TPU tunnel make in-process use
    unsafe: init can raise UNAVAILABLE (the round-1 bench crash), init
    can HANG (a previous client died mid-connect), and — nastiest —
    init can SUCCEED while the first compile/execute hangs forever (a
    previous client was killed mid-execute; observed 2026-07-30, the
    compile-helper ports refuse connections).  A hang in the main
    process would eat the driver's whole gate timeout with no JSON
    emitted; probing with a real execute converts all three into a
    clean verdict.

    Returns the device-info dict (platform/kind/n) on success — the
    probe already paid for a live backend, so it reports what it sees
    and spares the sweep a second multi-minute tunnel bring-up — or
    None on any failure."""
    info, err = _probe_device_json(run_execute=True, force_cpu=False,
                                   timeout_s=timeout_s)
    if err:
        _note(f"bench: backend probe {err} — falling back")
    return info


def _device_info(timeout_s: float = 240.0, force_cpu: bool = False) -> dict:
    """Platform / device-kind / chip-count, read in a THROWAWAY
    subprocess (no execute — topology only).  The sweep orchestrator
    must never hold a live TPU client itself: its per-config measurement
    children each open their own connection, and a second concurrent
    client is a tunnel failure mode we can't afford in a gate."""
    info, err = _probe_device_json(run_execute=False, force_cpu=force_cpu,
                                   timeout_s=timeout_s)
    if info is None:
        raise RuntimeError(f"device-info probe {err}")
    return info


def _step_flops(step_fn, args):
    """Per-step FLOPs from XLA's cost analysis of the lowered single-step
    program (unlike analyzing the inner_steps>1 scan program, this counts
    the whole step exactly once; lowering is compile-free).  Some
    backends (axon tunnel, 2026-07-30) return None here — the caller
    then falls back to the analytic roofline model rather than paying a
    full-model compile just for the MFU diagnostic."""
    try:
        cost = step_fn.lower(*args).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost:
            flops = float(cost.get("flops", 0.0))
            if flops > 0:
                return flops
    except Exception as exc:
        _note(f"bench: cost_analysis unavailable: {exc}")
    return None


def _parse_mesh_spec(spec: str):
    """``--mesh``/MILNCE_BENCH_MESH grammar: '' (1-D data mesh) or
    'data,model[=N]' (2-D FSDP grid, model axis N wide — default 2).
    Mirrors config's fail-at-parse-time discipline."""
    if not spec:
        return None, 1
    names = [p for p in spec.split(",") if p]
    if len(names) != 2 or names[0] != "data":
        raise ValueError(f"mesh spec {spec!r}: expected 'data,model[=N]'")
    axis, _, n = names[1].partition("=")
    return axis, int(n) if n else 2


def _bench_config(dtype: str, batch: int, frames: int, size: int,
                  words: int, k: int, remat: bool,
                  inner: int = 1, s2d: bool = False,
                  conv_impl: str = "native", conv_impl_map: str = "",
                  loss: str = "milnce", grad_accum: int = 1,
                  mesh_spec: str = "", loss_impl: str = "dense",
                  peak: float | None = None, flops_hint: float | None = None):
    """Time the full train step at one operating point.

    ``inner`` optimizer steps run inside ONE XLA program per dispatch
    (lax.scan in make_train_step) so per-dispatch host latency — seconds
    over a remote TPU tunnel — doesn't masquerade as device time.
    ``loss`` selects the trained loss: 'milnce' (headline) or a DTW
    family name ('sdtw_3', 'cdtw', ...) with ``sdtw_backend='auto'`` —
    the Pallas kernel inside the full compiled train step.  FLOPs/MFU
    are reported for milnce only (the analytic model doesn't count the
    alignment DP).
    ``mesh_spec`` ('data,model[=N]') runs the row on the 2-D FSDP grid:
    state sharded per the sharding map, batch over both axes, the
    record carrying mesh shape + map hash so ``obs_report`` can compare
    1-D and 2-D runs.  A 2-D row whose map shards NOTHING is refused
    (RuntimeError) — paying model-axis collectives for pure replication
    must not masquerade as an FSDP measurement.
    Returns dict with clips/sec/chip (+flops) or raises on OOM."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import full_preset
    from milnce_tpu.models.build import build_model
    from milnce_tpu.parallel.mesh import batch_sharding, build_mesh, replicated
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import make_train_step

    cfg = full_preset()
    cfg.model.dtype = dtype
    cfg.model.remat = remat
    cfg.model.space_to_depth = s2d
    cfg.model.conv_impl = conv_impl
    # per-stage overrides: inline spec or stage_probe --autotune artifact
    # path (config.parse_conv_impl_map handles both)
    cfg.model.conv_impl_map = conv_impl_map
    model_axis, model_n = _parse_mesh_spec(mesh_spec)
    if model_axis:
        cfg.parallel.model_axis = model_axis
        cfg.parallel.model_parallel_size = model_n
        min_env = os.environ.get("MILNCE_BENCH_FSDP_MIN")
        if min_env:
            cfg.parallel.fsdp_min_size = int(min_env)
    model = build_model(cfg.model)
    mesh = build_mesh(cfg.parallel)

    loss_cfg = None
    if loss != "milnce":
        cfg.loss.name = loss
        cfg.loss.sdtw_backend = "auto"   # Pallas where the measured
        loss_cfg = cfg.loss              # crossover says it wins
    elif loss_impl != "dense":
        # MIL-NCE impl axis (ISSUE 12): 'chunked'/'auto' swap the dense
        # similarity cubes for the streaming loss (losses/
        # milnce_chunked.py) inside the full compiled step; the row's
        # predicted_peak_bytes_per_chip then carries the memory delta
        # alongside the throughput cost (BENCH_MILNCE_LOSS.md)
        cfg.loss.milnce_impl = loss_impl
        loss_cfg = cfg.loss
    optimizer = build_optimizer(cfg.optim, build_schedule(cfg.optim, 1000))

    # Everything below runs ON DEVICE in three jitted programs.  The
    # obvious host-side version (eager model.init + optimizer.init +
    # device_put of host-generated arrays) issues hundreds of tiny
    # dispatches and ships ~0.1-1 GB of synthetic video over the wire —
    # over the remote TPU tunnel (multi-second per-dispatch latency,
    # limited bandwidth) that took LONGER than the measurement itself.
    repl = replicated(mesh)
    batch_axes = ((cfg.parallel.data_axis, model_axis) if model_axis
                  else cfg.parallel.data_axis)
    data_sh = batch_sharding(mesh, batch_axes)

    def init_state(key):
        variables = model.init(
            key, jnp.zeros((2, frames, size, size, 3), jnp.float32),
            jnp.zeros((2 * k, words), jnp.int32))
        return create_train_state(variables, optimizer)

    state = jax.jit(init_state, out_shardings=repl)(jax.random.PRNGKey(0))

    state_specs = None
    mesh_fields = {
        "mesh": "x".join(str(mesh.shape[a]) for a in mesh.axis_names)
                + f" ({','.join(mesh.axis_names)})"}
    if model_axis:
        from milnce_tpu.parallel.sharding_map import shard_and_place_state

        placement = shard_and_place_state(
            state, mesh, model_axis, min_size=cfg.parallel.fsdp_min_size,
            spec=cfg.parallel.sharding_map)
        if placement.n_sharded == 0:
            # refuse, don't measure: a 2-D row paying model-axis
            # collectives for pure replication is not an FSDP data point
            raise RuntimeError(
                "2-D mesh row with a sharding map that shards NOTHING "
                f"(threshold {cfg.parallel.fsdp_min_size} elements) — "
                "lower MILNCE_BENCH_FSDP_MIN or fix the map")
        state_specs = placement.specs
        mesh_fields["sharding_map_hash"] = placement.hash
        mesh_fields["params_sharded"] = placement.n_sharded
        state = placement.state

    if grad_accum > 1:
        # the two-pass embedding-cache program (the 8192-global-batch
        # recipe's step): ``batch`` clips consumed per update via
        # grad_accum microbatches.  No inner-step scan — one dispatch IS
        # already grad_accum sub-steps of work, which amortizes tunnel
        # latency the same way.
        assert inner == 1, "grad_accum rows measure with inner=1"
        from milnce_tpu.train.step import make_grad_cache_step

        step_fn = make_grad_cache_step(model, optimizer, mesh, grad_accum,
                                       donate=False, loss_cfg=loss_cfg,
                                       state_specs=state_specs,
                                       model_axis=model_axis)
    else:
        step_fn = make_train_step(model, optimizer, mesh, donate=False,
                                  inner_steps=inner, loss_cfg=loss_cfg,
                                  state_specs=state_specs,
                                  model_axis=model_axis)

    def make_inputs(key):
        kv, kt = jax.random.split(key)
        video = jax.random.randint(
            kv, (batch, frames, size, size, 3), 0, 255).astype(jnp.uint8)
        text = jax.random.randint(
            kt, (batch * k, words), 0, cfg.model.vocab_size, jnp.int32)
        start = jnp.zeros((batch,), jnp.float32)
        return video, text, start

    video_d, text_d, start_d = jax.jit(
        make_inputs, out_shardings=(data_sh, data_sh, data_sh))(
            jax.random.PRNGKey(1))

    if loss != "milnce" or grad_accum > 1:
        # DTW rows: neither the hint nor the analytic model counts the
        # alignment DP.  grad_accum rows: the two-pass step does ~2x the
        # forward FLOPs of the plain step, so the plain-model MFU would
        # be fiction.  Report raw throughput only.
        flops, flops_source = None, None
    elif flops_hint is not None:
        # Seeded from an earlier XLA-counted config of the same plan (see
        # run_bench's hint(), which rescales model and logits terms
        # separately) — avoids another full-model compile over the tunnel
        # just for the MFU diagnostic.
        flops, flops_source = flops_hint, "hint"
    else:
        single = (step_fn if inner == 1 else
                  make_train_step(model, optimizer, mesh, donate=False,
                                  state_specs=state_specs,
                                  model_axis=model_axis))
        flops = _step_flops(single, (state, video_d, text_d, start_d))
        if flops is not None:
            flops_source = "xla"
        else:
            # analytic model (valid-tap conv counting, pinned against
            # XLA's own analysis in tests/test_roofline.py) — no extra
            # compile over the tunnel, exact at every batch.  Arch fields
            # come from the SAME cfg.model the timed step was built from.
            from milnce_tpu.utils.roofline import train_step_flops

            flops = train_step_flops(
                batch, frames, size, k, words, space_to_depth=s2d,
                inception_blocks=cfg.model.inception_blocks,
                embedding_dim=cfg.model.embedding_dim,
                word_dim=cfg.model.word_embedding_dim,
                hidden=cfg.model.text_hidden_dim)
            flops_source = "analytic"
            _note(f"bench: using analytic FLOPs model ({flops:.3e}/step)")

    # static HBM plan of the timed program (graftlint Pass 4,
    # analysis/memplan.py): per-chip predicted peak bytes ride in the
    # record so obs_report --check gates memory drift alongside
    # step-time — a row that got faster by doubling its footprint is a
    # regression the throughput gate alone would wave through.  Traced
    # with the TPU donation intent (the production path donates the
    # state even though this harness builds donate=False for
    # comparability).  Best-effort: a planner error must cost the
    # memory field, never the measurement.
    predicted_peak = None
    try:
        from milnce_tpu.analysis.memplan import plan_fn
        from milnce_tpu.train.step import STATE_DONATION_ARGNUMS

        predicted_peak = plan_fn(
            step_fn, (state, video_d, text_d, start_d),
            argnames=("state", "video", "text", "start"),
            donate_argnums=STATE_DONATION_ARGNUMS).peak_bytes
    except Exception as exc:
        _note(f"bench: memplan prediction failed ({type(exc).__name__}: "
              f"{exc}) — row ships without predicted_peak_bytes_per_chip")

    # precision fingerprint of the timed program (graftlint Pass 5,
    # analysis/numerics.py): sha of the dtype census + cast inventory
    # rides in the record so obs_report --check can FLAG cross-precision
    # compares — a bf16 row beating an f32 baseline is a dtype change,
    # not a speedup.  Best-effort for the same reason as the plan.
    dtype_census_hash = None
    try:
        from milnce_tpu.analysis.numerics import audit_fn

        dtype_census_hash = audit_fn(
            step_fn, (state, video_d, text_d, start_d),
            argnames=("state", "video", "text", "start"),
            entry="bench").census_hash()
    except Exception as exc:
        _note(f"bench: numerics audit failed ({type(exc).__name__}: "
              f"{exc}) — row ships without dtype_census_hash")

    # warmup / compile (NOT `loss` — that name is the loss-selector arg
    # and ends up verbatim in the result record)
    state, warmup_loss = step_fn(state, video_d, text_d, start_d)
    float(warmup_loss)

    def wall(n_dispatch: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for _ in range(n_dispatch):
            state, loss = step_fn(state, video_d, text_d, start_d)
        # Materialize the scalar ON HOST: over the axon tunnel
        # block_until_ready can resolve before the device work is
        # observable (the softdtw_profile harness hit the same thing —
        # a kernel "measured" at 5 us chained); a device->host transfer
        # of the computed value cannot.
        float(loss)
        return time.perf_counter() - t0

    # Differenced timing: W(n) = latency + n * device_time when dispatches
    # pipeline, so (W(k2) - W(k1)) / (k2 - k1) cancels the per-dispatch
    # host/tunnel latency that a plain W(n)/n measurement folds into the
    # step time (observed ~4 s per dispatch over the remote TPU tunnel —
    # ~20% of the old batch-256 reading).  If the backend serializes
    # dispatches the difference degrades to the old estimate, never worse.
    k1, k2 = 1, 3
    w1 = min(wall(k1) for _ in range(2))
    w2 = min(wall(k2) for _ in range(2))
    if w2 - w1 < 0.05 * w2:
        # Difference lost in scheduler jitter (tiny models on the CPU
        # smoke path): fall back to the plain latency-inclusive estimate
        # rather than emitting absurd near-zero step times.
        _note(f"bench: differenced timing degenerate (w1={w1:.4f}s "
              f"w2={w2:.4f}s) — falling back to W(k2)/k2")
        dt = w2 / k2
    else:
        dt = (w2 - w1) / (k2 - k1)         # per-dispatch device time

    n_chips = len(jax.devices())
    guard_flops = flops
    if guard_flops is None:
        # DTW / grad_accum rows report no FLOPs, but the plausibility
        # guard below must still hold: the PLAIN step's analytic FLOPs
        # are a strict lower bound on the true work per clip for both
        # (the DP / the second embedding pass only add work), so a
        # tunnel fantasy reading still trips the bound.
        from milnce_tpu.utils.roofline import train_step_flops

        guard_flops = train_step_flops(
            batch, frames, size, k, words, space_to_depth=s2d,
            inception_blocks=cfg.model.inception_blocks,
            embedding_dim=cfg.model.embedding_dim,
            word_dim=cfg.model.word_embedding_dim,
            hidden=cfg.model.text_hidden_dim)
    if guard_flops:
        # Physical sanity: implied FLOP/s beyond this device's peak means
        # the measurement is broken (e.g. a tunnel whose block_until_ready
        # resolves early — observed 2026-07-30 producing 392k clips/s/chip,
        # 4000x reality).  Better no row than a fantasy row.  flops counts
        # the whole sharded step, so scale the bound by chip count; the
        # fleet-wide max is the fallback when the device kind is unknown.
        implied = guard_flops * inner / dt
        rl = _roofline()
        fleet_max = (max(rl.PEAK_FLOPS_BY_KIND.values()) if rl
                     else _FALLBACK_MAX_PEAK)
        bound = 1.5 * (peak or fleet_max) * n_chips
        if implied > bound:
            raise RuntimeError(
                f"implausible measurement: {implied:.3e} FLOP/s implied "
                f"(dt={dt:.6f}s for {inner} steps of >={guard_flops:.3e} "
                f"FLOPs on {n_chips} chips, bound {bound:.3e})")
    # record the EFFECTIVE loss impl: 'auto' resolves per shape
    # (prefers_chunked at this row's per-chip batch), and a row the rule
    # resolved to dense must not read as a streaming-loss measurement
    effective_impl = loss_impl if loss == "milnce" else None
    if effective_impl == "auto":
        from milnce_tpu.losses.milnce_chunked import prefers_chunked

        effective_impl = ("chunked" if prefers_chunked(
            batch // n_chips, batch, k) else "dense")
    result = {
        "dtype": dtype,
        "batch": batch,
        "remat": remat,
        "s2d": s2d,
        "conv_impl": conv_impl,
        "impl_map": conv_impl_map,
        "loss": loss,
        "loss_impl": effective_impl,
        "loss_impl_requested": (loss_impl if loss == "milnce"
                                and loss_impl == "auto" else None),
        "grad_accum": grad_accum,
        "inner": inner,
        **mesh_fields,
        "step_ms": round(dt / inner * 1e3, 2),
        "clips_per_sec_per_chip": round(batch * inner / dt / n_chips, 3),
        "flops_per_step": flops,
        "flops_source": flops_source if flops else None,
        "flops_per_sec": (flops * inner / dt) if flops else None,
        "predicted_peak_bytes_per_chip": predicted_peak,
        "dtype_census_hash": dtype_census_hash,
    }
    if peak and flops:
        # the SHARED MFU definition (utils/roofline.py) — identical to
        # the train loop's live gauge given the same throughput
        from milnce_tpu.utils.roofline import mfu as _shared_mfu

        result["mfu"] = round(_shared_mfu(flops, inner / dt, peak,
                                          n_chips), 4)
    return result


# the measurement grand-child currently running under this orchestrator
# (None between configs) — the SIGTERM forwarder needs to reach it
_ACTIVE_CHILD_PROC = None


def _forward_term_and_exit(signum, frame):
    """Orchestrator SIGTERM handler: the parent's budget timeout TERMs
    only this process — without forwarding, the measurement grand-child
    (the process actually holding the live TPU tunnel client) would be
    orphaned mid-compile, becoming both a concurrent-client hazard and a
    future hard-kill relay wedge.  Forward the TERM, give the client the
    same grace the parent gives us, then exit."""
    del signum, frame
    proc = _ACTIVE_CHILD_PROC
    if proc is not None and proc.poll() is None:
        # TERM, 25s grace (inside the parent's 30s), then KILL — an
        # orphan left alive holding the tunnel client is the one outcome
        # strictly worse than a hard kill of a wedged one
        _graceful_stop(proc, grace=25)
    os._exit(1)


def _graceful_stop(proc, grace: float = 30.0):
    """TERM first with a grace period, then KILL.  A hard kill of a live
    TPU client is what wedges the tunnel relay for every LATER client
    (init succeeds, first compile hangs — observed 2026-07-30/31); a
    SIGTERM lets the client tear its connection down cleanly.  Does not
    read the pipe — callers own proc.stdout (possibly from a reader
    thread)."""
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _run_config(timeout_s: float | None = None, **kwargs):
    """Run ONE _bench_config measurement in its own subprocess.

    Isolation buys two things the in-process sweep couldn't have:
    (a) a watchdog — a wedged tunnel compile (batch-256 hung >50 min,
    2026-07-31) costs ``timeout_s``, not the whole sweep budget; and
    (b) a clean allocator — an OOM'd process on this backend fails even
    tiny follow-up allocations (a batch-256 OOM killed the float32
    batch-32 row), so every config starts in a fresh process.

    Raises RuntimeError carrying the child's error text (so the caller's
    OOM detection keeps working) or a 'config timeout' marker.  The
    child's stderr is captured and re-streamed to OUR stderr; when the
    child dies with no record the stderr tail rides in the exception —
    an rc=1 before jax even initializes (e.g. an XLA_FLAGS the client's
    flag parser rejects, the round-5 xla_flag_probe failure mode) used
    to surface as a bare 'no record' with the diagnosis lost."""
    global _ACTIVE_CHILD_PROC
    env = dict(os.environ)
    env[_CONFIG_ENV] = json.dumps(kwargs)
    env.pop(_CHILD_MODE_ENV, None)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, cwd=_REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    _ACTIVE_CHILD_PROC = proc
    err = b""
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # keep DRAINING the pipes while the TERM grace runs: a child
        # flushing a large XLA/traceback tail into a full 64KB pipe
        # would otherwise block, ignore the TERM, and get hard-killed —
        # the exact live-TPU-client kill that wedges the relay for every
        # later client (_graceful_stop notes)
        drained = {}

        def _drain():
            drained["out"], drained["err"] = proc.communicate()

        reader = threading.Thread(target=_drain, daemon=True)
        reader.start()
        _graceful_stop(proc)
        reader.join(timeout=10)
        _relay_child_stderr(drained.get("err") or b"")
        raise RuntimeError(f"config timeout>{timeout_s}s: {kwargs}")
    finally:
        _ACTIVE_CHILD_PROC = None
    _relay_child_stderr(err)
    rec = _last_tagged_json(
        out or b"", lambda r: "config_result" in r or "config_error" in r)
    if rec is None:
        tail = (err or b"").decode(errors="replace").strip()[-2000:]
        raise RuntimeError(f"config child rc={proc.returncode}, no record; "
                           f"stderr tail: {tail or '(empty)'}")
    if "config_error" in rec:
        raise RuntimeError(rec["config_error"])
    return rec["config_result"]


def _relay_child_stderr(err: bytes) -> None:
    """Captured child stderr still belongs on our stderr (the sweep's
    per-config diagnostics read it live before capture existed)."""
    if err:
        sys.stderr.write(err.decode(errors="replace"))
        sys.stderr.flush()


def _is_oom(exc) -> bool:
    text = f"{type(exc).__name__}: {exc}".lower()
    return ("resource_exhausted" in text or "out of memory" in text
            or "oom" in text or "exceeds the memory" in text)


_BENCH_RUN_ID = None


def _bench_run_id():
    """One id per bench invocation, stamped into every record (interim
    and final) — the obs run-identity contract (obs/runctx.py), so a
    directory of bench records aggregates/splits like any other
    ``milnce.obs/v1`` artifact."""
    global _BENCH_RUN_ID
    if _BENCH_RUN_ID is None:
        try:
            from milnce_tpu.obs.runctx import auto_run_id

            _BENCH_RUN_ID = auto_run_id("bench-")
        except ImportError:
            # broken package path (bring-up host): the record still
            # ships, with a same-shape locally generated id
            _BENCH_RUN_ID = f"bench-{int(time.time())}-{os.getpid():04x}"
    return _BENCH_RUN_ID


def _make_record(best, frames, size, on_tpu, kind):
    value = best["clips_per_sec_per_chip"]
    out = {
        # versioned obs envelope (milnce_tpu/obs/export.py): train bench
        # records share one schema with SERVE_BENCH_*.json and registry
        # snapshots, so scripts/obs_report.py can summarize/gate all of
        # them.  Literal (not imported): the record must survive even if
        # the package import path is broken on a bring-up host.
        "schema": "milnce.obs/v1",
        "kind": "train_bench",
        "run_id": _bench_run_id(),
        "process_index": 0,
        "metric": f"train_step clips/sec/chip ({frames}f@{size}, "
                  f"{best['dtype']}, batch {best['batch']}"
                  + (", s2d stem" if best.get("s2d") else "")
                  + (", fold2d convs"
                     if best.get("conv_impl") == "fold2d" else "")
                  + (", tuned impl map"
                     if best.get("impl_map") else "")
                  + (", chunked loss"
                     if best.get("loss_impl") not in (None, "dense")
                     else "") + ")",
        "value": value,
        "unit": "clips/sec/chip",
        # ratio vs the recorded TPU anchor — only meaningful on TPU (a
        # CPU-fallback number against a TPU anchor would be noise).
        "vs_baseline": (round(value / BASELINE_THROUGHPUT, 3)
                        if BASELINE_THROUGHPUT and on_tpu else 1.0),
        "timing": "differenced+host-materialized",
        # The 95.35 anchor predates host-materialized differenced timing;
        # part of any ratio != 1 is that method change.  Dropped when the
        # anchor is re-measured under the current method.
        "anchor_timing": "latency-inclusive (pre-differencing)",
        "on_tpu": on_tpu,
        "device_kind": str(kind),
    }
    if "mfu" in best:
        out["mfu"] = best["mfu"]
    # mesh layout + sharding-map identity (ISSUE 6): obs_report --check
    # can only compare 1-D and 2-D runs if the record says which layout
    # (and which map) produced the number.  predicted_peak_bytes_per_chip
    # (ISSUE 8) makes memory drift gateable the same way.
    # dtype_census_hash (Pass 5) rides along so a cross-precision
    # compare is flagged, not silently scored as a speedup/regression
    for key in ("mesh", "sharding_map_hash", "params_sharded",
                "predicted_peak_bytes_per_chip", "dtype_census_hash"):
        if best.get(key) is not None:
            out[key] = best[key]
    if not on_tpu:
        # a fallback record must point at the real data: the recorded TPU
        # operating point lives in BENCH_NOTES.md
        out["note"] = ("accelerator unavailable — CPU fallback; last "
                       "recorded TPU operating point "
                       f"{LAST_TPU_OPERATING_POINT} clips/sec/chip "
                       "(BENCH_NOTES.md)")
        out["last_tpu_value"] = LAST_TPU_OPERATING_POINT
    return out


def run_bench(on_tpu: bool, info: dict):
    """Sweep orchestrator: picks configs, runs each in its own
    watchdogged subprocess (_run_config), streams an interim best-so-far
    record after every row.  Holds NO jax backend itself — `info` comes
    from the _device_info probe."""
    kind, n_devices = info["kind"], info["n"]
    peak = _peak_flops(str(kind)) if on_tpu else None
    cfg_timeout = float(os.environ.get("MILNCE_BENCH_CONFIG_TIMEOUT",
                                       "900" if on_tpu else "600"))
    _note(f"bench: platform={info['platform']} kind={kind} "
          f"n={n_devices} peak_flops={peak} config_timeout={cfg_timeout}s")

    # opt-in: bench the space_to_depth stem (what the original TPU
    # training used) — densifies conv1, the stage most starved on the
    # 128-wide MXU (see BENCH_NOTES.md headroom notes)
    s2d = os.environ.get("MILNCE_BENCH_S2D") == "1"
    # conv lowering for the sweep: 'native' 3D convs, 'fold2d' (2D-conv
    # decomposition) or 'im2col' (patches + one dot_general,
    # models/conv3d.py); a fold2d row is also auto-measured at the
    # winning operating point (opt out: MILNCE_BENCH_FOLD2D=0)
    conv_impl = os.environ.get("MILNCE_BENCH_CONV", "native")
    # per-stage impl map for every sweep row: inline spec or the
    # stage_probe --autotune artifact path (absolute, or relative to the
    # repo root so the child resolves it from its own cwd)
    impl_map = os.environ.get("MILNCE_BENCH_IMPL_MAP", "")
    if impl_map and "=" not in impl_map and not os.path.isabs(impl_map):
        impl_map = os.path.join(_REPO, impl_map)
    # mesh layout for the sweep rows: '' = 1-D data mesh (default),
    # 'data,model[=N]' runs the WHOLE sweep on the 2-D FSDP grid; with
    # the default 1-D sweep a mesh_2d comparison row is auto-measured at
    # the winning operating point (opt out: MILNCE_BENCH_MESH_2D=0)
    mesh_spec = os.environ.get("MILNCE_BENCH_MESH", "")
    # MIL-NCE loss impl for every sweep row: 'dense' (default), 'chunked'
    # (streaming loss), or 'auto' (the prefers_chunked budget rule); with
    # the default a milnce_chunked comparison row is auto-measured at the
    # winning operating point (opt out: MILNCE_BENCH_MILNCE_CHUNKED=0)
    loss_impl = os.environ.get("MILNCE_BENCH_LOSS_IMPL", "dense")
    if on_tpu:
        frames, size, words, k = 16, 224, 20, 5
        # differenced W(k2)-W(k1) timing cancels dispatch latency, so the
        # scan only needs enough inner steps to dominate scheduler jitter
        inner = 4
        # 256 was still climbing on 2026-07-29 but its compile wedged the
        # tunnel twice on 2026-07-31 — 192 captures most of the remaining
        # climb if 256 times out again; 512 stays excluded (OOMed even
        # with remat)
        plans = [("bfloat16", [64, 128, 192, 256, 384], False),
                 ("float32", [32, 64], False)]
    else:
        frames, size, words, k = 4, 64, 6, 3
        inner = 1
        # batch must divide over the data mesh (a host forced to N virtual
        # CPU devices — the test rig — still has to measure something)
        plans = [("float32", [2 * n_devices], False)]

    results = []
    # (dtype, remat, s2d) -> (batch, flops) seeds, XLA-sourced only (the
    # analytic model is free to recompute exactly at every batch)
    flops_seen = {}

    def hint(dtype, remat, s2d_, batch):
        seen = flops_seen.get((dtype, remat, s2d_))
        if not seen:
            return None
        # model FLOPs scale linearly in batch; the MIL-NCE logits matmul
        # is quadratic — rescale the two terms separately
        from milnce_tpu.utils.roofline import milnce_logits_flops

        b0, f0 = seen
        linear = f0 - milnce_logits_flops(b0, k)
        return linear * batch / b0 + milnce_logits_flops(batch, k)

    def measure(dtype, batch, remat, s2d, conv_impl, loss="milnce",
                grad_accum=1, timeout_s=None, conv_impl_map=None,
                mesh=None, impl=None, frames_=None, size_=None):
        # frames_/size_ override the sweep's fixed input shape (the
        # curriculum stage rows); hint() seeds are keyed per-shape
        # implicitly (one sweep shape), so off-shape rows skip the hint
        off_shape = frames_ is not None or size_ is not None
        return _run_config(
            timeout_s=timeout_s or cfg_timeout,
            platform_pin=None if on_tpu else "cpu",
            dtype=dtype, batch=batch,
            frames=frames if frames_ is None else frames_,
            size=size if size_ is None else size_, words=words, k=k,
            remat=remat,
            inner=1 if grad_accum > 1 else inner, s2d=s2d,
            conv_impl=conv_impl,
            conv_impl_map=impl_map if conv_impl_map is None else conv_impl_map,
            loss=loss, grad_accum=grad_accum,
            mesh_spec=mesh_spec if mesh is None else mesh,
            loss_impl=loss_impl if impl is None else impl, peak=peak,
            flops_hint=None if grad_accum > 1 or off_shape
            else hint(dtype, remat, s2d, batch))

    def tunnel_wedged(exc) -> bool:
        """A config timeout on TPU may mean the whole tunnel is wedged
        (a dead client mid-compile hangs every later client).  Re-probe;
        if even a trivial execute fails now, the sweep is over."""
        if not on_tpu or "config timeout" not in str(exc):
            return False
        if _probe_backend():
            return False
        _note("bench: tunnel no longer answers after a config timeout — "
              "ending sweep with the rows in hand")
        return True

    dead = False
    for dtype, batches, plan_remat in plans:
        if dead:
            break
        prev = 0.0
        remat = plan_remat
        for batch in batches:
            try:
                r = measure(dtype, batch, remat, s2d, conv_impl)
            except Exception as exc:
                if tunnel_wedged(exc):
                    dead = True
                    break
                if _is_oom(exc) and not remat:
                    _note(f"bench: {dtype} batch={batch} OOM — retrying with "
                          "remat (kept on for larger batches)")
                    remat = True   # larger batches can only need MORE memory
                    # remat recomputes activations, so this row dropping
                    # below the last non-remat row is expected — reset the
                    # knee reference so the drop doesn't end the plan
                    # before larger remat batches get their shot.
                    prev = 0.0
                    try:
                        r = measure(dtype, batch, True, s2d, conv_impl)
                    except Exception as exc2:
                        dead = tunnel_wedged(exc2)
                        _note(f"bench: {dtype} batch={batch} remat also failed: "
                              f"{type(exc2).__name__} — stopping sweep")
                        break
                else:
                    # Never discard the measurements already in hand for a
                    # mid-sweep failure: stop this plan, keep the results.
                    _note(f"bench: {dtype} batch={batch} failed "
                          f"({type(exc).__name__}: {exc}) — stopping sweep")
                    break
            if r["flops_per_step"] and r.get("flops_source") == "xla":
                flops_seen.setdefault((dtype, remat, s2d),
                                      (batch, r["flops_per_step"]))
            if prev and r["clips_per_sec_per_chip"] < 0.90 * prev:
                # >10% regression vs a SMALLER batch is not the usual
                # diminishing-returns knee — it's a padded-batch/tiling
                # cliff (the observed 281-vs-393 clips/s drop at batch
                # 192; PERF.md "Batch cliffs") and the row is flagged so
                # BENCH_NOTES readers don't average across it
                r["cliff_vs_smaller_batch"] = round(
                    1.0 - r["clips_per_sec_per_chip"] / prev, 3)
                _note(f"bench: {dtype} batch={batch} regresses "
                      f"{100 * r['cliff_vs_smaller_batch']:.0f}% vs the "
                      "smaller batch — padded-batch/tiling cliff "
                      "(PERF.md)")
            _note(f"bench: {r}")
            results.append(r)
            # Interim record after every config: a later config hanging
            # the tunnel must not cost the rows already measured — the
            # parent forwards the LAST parsable stdout line it saw.
            _emit(_make_record(
                max(results, key=lambda x: x["clips_per_sec_per_chip"]),
                frames, size, on_tpu, kind))
            # stop climbing only once throughput actually DECLINES past a
            # small noise margin: with 192 interposed in the ladder a
            # healthy 128->256 climb splits into two small steps, so a
            # large gain threshold would end the plan before 256/384 ever
            # ran — but an exact <= would let run-to-run jitter (either a
            # dead-flat repeat or a 0.1% dip) decide whether the larger
            # batches get measured at all
            if r["clips_per_sec_per_chip"] < prev * 0.99:
                break
            prev = max(prev, r["clips_per_sec_per_chip"])

    if not results:
        raise RuntimeError(
            "no config produced a measurement — every sweep arm failed "
            "(see stderr for per-config errors)")
    best = max(results, key=lambda r: r["clips_per_sec_per_chip"])

    def extra_row(label, **overrides):
        """One comparison row at the winning operating point, with the
        same record/interim-emit protocol as the sweep rows."""
        nonlocal best, dead
        if dead:
            return
        try:
            kw = dict(dtype=best["dtype"], batch=best["batch"],
                      remat=best["remat"], s2d=best.get("s2d", False),
                      conv_impl=conv_impl)
            kw.update(overrides)
            r = measure(**kw)
            _note(f"bench: {r}")
            results.append(r)
            # comparison rows that change the WORK per clip — a
            # different loss, grad-accum, or the chunked stream's
            # backward recompute — must not displace the headline: the
            # vs_baseline anchor is a dense-loss measurement.  Only a
            # sweep PINNED to chunked (MILNCE_BENCH_LOSS_IMPL=chunked)
            # lifts the impl filter — it is its own headline population;
            # an 'auto' sweep resolves per row, and its forced
            # milnce_chunked comparison row must not slip in on noise.
            pool = [x for x in results
                    if x.get("loss", "milnce") == "milnce"
                    and x.get("grad_accum", 1) == 1
                    and x.get("stage") is None
                    and (loss_impl == "chunked"
                         or x.get("loss_impl") in (None, "dense"))]
            if pool:    # empty = every auto row resolved chunked; keep
                best = max(pool,            # the sweep's own best then
                           key=lambda x: x["clips_per_sec_per_chip"])
            _emit(_make_record(best, frames, size, on_tpu, kind))
        except Exception as exc:
            dead = tunnel_wedged(exc)
            _note(f"bench: {label} row failed ({type(exc).__name__}: {exc})"
                  " — keeping prior results")

    # space_to_depth row at the winning operating point: the original TPU
    # training used the s2d stem (s3dg.py:214-215, 248-253) precisely
    # because it densifies conv1 for the MXU — always measure the
    # comparison (opt out: MILNCE_BENCH_S2D=0).
    # comparison rows pin conv_impl_map="" so each measures its PURE
    # configuration — with a global MILNCE_BENCH_IMPL_MAP the sweep rows
    # carry the map (the operating point) while these stay the labeled
    # baselines they claim to be (an s2d row under a plain-stem-tuned
    # map would even misapply the conv1 entry to the 2x4x4 kernel)
    if on_tpu and not s2d and os.environ.get("MILNCE_BENCH_S2D") != "0":
        extra_row("s2d", s2d=True, conv_impl_map="")
    # fold2d row: same math lowered as 2D convs (models/conv3d.py) — if
    # XLA's 3D-conv tiling is the MFU sink (PERF.md headroom reading)
    # this row shows it directly.
    if (on_tpu and conv_impl == "native"
            and os.environ.get("MILNCE_BENCH_FOLD2D") != "0"):
        extra_row("fold2d", conv_impl="fold2d", conv_impl_map="")
    # im2col-stem row: the fwd+bwd stage probe convicts conv1 (1% of
    # peak, 102x roofline — STAGE_PROBE_native_fwdbwd.md); this measures
    # the patches+dot_general stem at the winning operating point.  A
    # full autotuned map (MILNCE_BENCH_IMPL_MAP) supersedes it (opt out:
    # MILNCE_BENCH_IM2COL=0).
    if (on_tpu and conv_impl == "native" and not impl_map
            and os.environ.get("MILNCE_BENCH_IM2COL") != "0"):
        extra_row("im2col_stem", s2d=False, conv_impl_map="conv1=im2col")
    # DTW-family row: the Pallas soft-DTW kernel inside the FULL compiled
    # train step (loss sdtw_3, backend auto) at the winning operating
    # point — the fork's signature loss measured on the real chip, not
    # just in the kernel microbench (opt out: MILNCE_BENCH_SDTW=0).
    if on_tpu and os.environ.get("MILNCE_BENCH_SDTW") != "0":
        extra_row("sdtw_3", loss="sdtw_3", s2d=False, conv_impl="native",
                  conv_impl_map="")
    # Chunked MIL-NCE row: the streaming loss (losses/milnce_chunked.py)
    # inside the full compiled step at the winning operating point — the
    # predicted_peak_bytes_per_chip delta vs the dense sweep rows is the
    # memory win, step_ms the recompute cost (opt out:
    # MILNCE_BENCH_MILNCE_CHUNKED=0).  Measured unless the sweep was
    # ALREADY pinned to chunked via MILNCE_BENCH_LOSS_IMPL=chunked — an
    # 'auto' sweep still needs it, since at typical bench shapes the
    # prefers_chunked budget resolves every row to dense.
    if (on_tpu and loss_impl != "chunked"
            and os.environ.get("MILNCE_BENCH_MILNCE_CHUNKED") != "0"):
        extra_row("milnce_chunked", impl="chunked", s2d=False,
                  conv_impl="native", conv_impl_map="")
    # 2-D mesh row: the FSDP (data, model) grid at the winning operating
    # point — mesh shape + sharding-map hash land in the record so
    # obs_report can diff it against the 1-D rows (opt out:
    # MILNCE_BENCH_MESH_2D=0; a sweep already pinned to a 2-D mesh via
    # MILNCE_BENCH_MESH measures nothing extra).
    if (on_tpu and not mesh_spec
            and os.environ.get("MILNCE_BENCH_MESH_2D") != "0"):
        extra_row("mesh_2d", mesh="data,model", s2d=False,
                  conv_impl="native", conv_impl_map="")
    # North-star recipe row: the per-chip slice of the 8192-global-batch
    # training step — 8 embedding-cache microbatches of the winning batch
    # in ONE update (BASELINE.md HMDB-53.1 recipe; memory- and
    # equivalence-proven in tests, measured here).  The row inherits the
    # sweep's mesh and carries mesh/map-hash fields, so the ga=8
    # operating point is comparable against BENCH_r05's 25%-down reading
    # (and against a 2-D sweep) in obs_report.  Bigger compile + 8x
    # the work per dispatch -> double timeout (opt out:
    # MILNCE_BENCH_GRAD_ACCUM=0).
    if on_tpu and os.environ.get("MILNCE_BENCH_GRAD_ACCUM") != "0":
        extra_row("grad_accum8", batch=8 * best["batch"], grad_accum=8,
                  s2d=False, conv_impl="native", conv_impl_map="",
                  timeout_s=2 * cfg_timeout)

    # Curriculum axis (ISSUE 16): MILNCE_BENCH_CURRICULUM holds a
    # train.curriculum spec — each stage is measured as its own row at
    # the stage's (frames, resolution, batch) on the winning dtype, and
    # the whole-schedule rate (steps-weighted composition of the
    # per-stage rates) is compared against running the SAME total clip
    # count flat at the final stage's full-res shape.  Stage rows carry
    # ``stage``/``stage_label`` and never enter the headline pool:
    # different input shapes are not comparable operating points.
    curriculum_spec = os.environ.get("MILNCE_BENCH_CURRICULUM", "")
    curriculum_summary = None
    if curriculum_spec and not dead:
        try:
            # jax-free at module scope (the orchestrator must not hold
            # a backend) — same parser the train loop uses, so the axis
            # refuses exactly the specs run_training would refuse
            from milnce_tpu.train.curriculum import parse_curriculum

            stages = parse_curriculum(curriculum_spec,
                                      default_batch_size=best["batch"])
            # per-stage step counts from the until_step boundaries.  The
            # bench axis requires step-bounded stages (epoch bounds need
            # a dataset size a synthetic bench doesn't have); the
            # open-ended final stage defaults to the bounded stages'
            # total (override: MILNCE_BENCH_CURRICULUM_STEPS).
            stage_steps, prev_bound = [], 0
            for i, st in enumerate(stages[:-1]):
                if st.until_step is None:
                    raise ValueError(
                        f"bench curriculum stage {i} must be bounded by "
                        "until_step — epoch bounds need a dataset size")
                stage_steps.append(st.until_step - prev_bound)
                prev_bound = st.until_step
            final_steps = int(os.environ.get(
                "MILNCE_BENCH_CURRICULUM_STEPS", "0"))
            stage_steps.append(final_steps or sum(stage_steps) or 1000)
            stage_rows = []
            for i, (st, n_steps) in enumerate(zip(stages, stage_steps)):
                r = measure(best["dtype"], st.batch_size, best["remat"],
                            False, "native", conv_impl_map="",
                            frames_=st.num_frames, size_=st.resolution)
                r["stage"] = i
                r["stage_label"] = st.label()
                r["stage_steps"] = n_steps
                _note(f"bench: {r}")
                results.append(r)
                stage_rows.append(r)
            total_clips = sum(r["stage_steps"] * r["batch"]
                              for r in stage_rows)
            # chip-seconds per chip of the whole schedule: each stage
            # contributes steps*batch clips at its own per-chip rate
            sched_time = sum(r["stage_steps"] * r["batch"]
                             / r["clips_per_sec_per_chip"]
                             for r in stage_rows)
            schedule_cps = total_clips / sched_time
            flat_cps = stage_rows[-1]["clips_per_sec_per_chip"]
            curriculum_summary = {
                "spec": curriculum_spec,
                "stages": [{
                    "stage": r["stage"], "label": r["stage_label"],
                    "steps": r["stage_steps"], "batch": r["batch"],
                    "step_ms": r["step_ms"],
                    "clips_per_sec_per_chip": r["clips_per_sec_per_chip"],
                } for r in stage_rows],
                "total_clips": total_clips,
                "schedule_clips_per_sec_per_chip": round(schedule_cps, 3),
                "flat_clips_per_sec_per_chip": flat_cps,
                # flat comparator = the final stage's full-res rate over
                # the same clip COUNT (a throughput comparison — the
                # learning-curve question is PERF.md's, not bench's)
                "speedup_vs_flat": round(schedule_cps / flat_cps, 3),
            }
            _note(f"bench: curriculum schedule "
                  f"{schedule_cps:.2f} clips/s/chip vs flat {flat_cps} "
                  f"at {total_clips} total clips "
                  f"({curriculum_summary['speedup_vs_flat']}x)")
        except Exception as exc:
            dead = tunnel_wedged(exc)
            _note(f"bench: curriculum axis failed "
                  f"({type(exc).__name__}: {exc}) — keeping prior results")

    _write_notes(results, best, kind, on_tpu, n_devices,
                 truncated=dead, curriculum=curriculum_summary)
    final = _make_record(best, frames, size, on_tpu, kind)
    if curriculum_summary:
        # attached to the headline record, never emitted as its own
        # final line: consumers take the LAST parsable record, and a
        # stage-shaped row must not displace the sweep's measurement
        final["curriculum"] = curriculum_summary
    if dead:
        # machine-visible truncation: rows measured before the tunnel
        # died must not read as a complete sweep (the orchestrator still
        # exits 0, so the parent's timeout marker never fires)
        final["partial"] = "tunnel wedged mid-sweep"
    return final


def _write_notes(results, best, kind, on_tpu, n_chips, truncated=False,
                 curriculum=None):
    notes = os.path.join(_REPO, "BENCH_NOTES.md")
    hand_notes = ""
    if os.path.exists(notes):
        with open(notes) as fh:
            existing = fh.read()
        if not on_tpu and "on_tpu=True" in existing:
            # never clobber a real-TPU sweep with CPU-fallback numbers
            _note("bench: keeping existing TPU BENCH_NOTES.md")
            return
        # durable hand-written context (methodology caveats, operating-
        # point history) survives the auto-rewrite: everything from the
        # '## Hand notes' heading down is carried over verbatim
        marker = existing.find("## Hand notes")
        if marker >= 0:
            hand_notes = existing[marker:].rstrip()
    try:
        lines = ["# BENCH notes (auto-written by bench.py)", "",
                 f"- device: {kind} x{n_chips} (on_tpu={on_tpu})",
                 f"- chosen operating point: dtype={best['dtype']} "
                 f"batch={best['batch']} remat={best['remat']} -> "
                 f"{best['clips_per_sec_per_chip']} clips/sec/chip",
                 "", "| dtype | batch | remat | s2d | conv | map | loss | ga | mesh | stage | step_ms | clips/s/chip | MFU |",
                 "|---|---|---|---|---|---|---|---|---|---|---|---|---|"]
        for r in results:
            clips = str(r["clips_per_sec_per_chip"])
            if r.get("cliff_vs_smaller_batch"):
                clips += (f" (cliff: -{100 * r['cliff_vs_smaller_batch']:.0f}"
                          "% vs smaller batch)")
            loss_lbl = r.get("loss", "milnce")
            if r.get("loss_impl") not in (None, "dense"):
                loss_lbl += f"({r['loss_impl']})"      # streaming MIL-NCE
            stage_lbl = ("-" if r.get("stage") is None
                         else f"{r['stage']} ({r.get('stage_label', '?')})")
            lines.append(f"| {r['dtype']} | {r['batch']} | {r['remat']} | "
                         f"{r.get('s2d', False)} | "
                         f"{r.get('conv_impl', 'native')} | "
                         f"{'tuned' if r.get('impl_map') else '-'} | "
                         f"{loss_lbl} | "
                         f"{r.get('grad_accum', 1)} | "
                         f"{r.get('mesh', '-')} | "
                         f"{stage_lbl} | "
                         f"{r['step_ms']} | {clips} | "
                         f"{r.get('mfu', '-')} |")
        maps2d = sorted({r["sharding_map_hash"] for r in results
                         if r.get("sharding_map_hash")})
        if maps2d:
            lines += ["", "2-D rows' sharding-map hash: "
                      + "; ".join(f"`{h}`" for h in maps2d)
                      + " (per-param layout: parallel/sharding_map.py "
                      "describe_map; PERF.md '2-D mesh & sharding map')."]
        maps = sorted({r["impl_map"] for r in results if r.get("impl_map")})
        if maps:
            lines += ["", "Per-stage impl map for 'tuned' rows: "
                      + "; ".join(f"`{m}`" for m in maps)
                      + " (stage_probe --autotune artifact / inline spec)."]
        if any(r.get("cliff_vs_smaller_batch") for r in results):
            lines += ["", "Rows marked 'cliff' regress >10% clips/s vs a "
                      "SMALLER batch — a padded-batch/tiling cliff, not "
                      "the usual diminishing-returns knee (PERF.md "
                      "'Batch cliffs')."]
        if curriculum:
            lines += ["", "## Curriculum schedule", "",
                      f"- spec: `{curriculum['spec']}`",
                      f"- whole-schedule rate: "
                      f"{curriculum['schedule_clips_per_sec_per_chip']} "
                      "clips/sec/chip vs flat full-res "
                      f"{curriculum['flat_clips_per_sec_per_chip']} at "
                      f"equal total clips ({curriculum['total_clips']}) "
                      f"-> **{curriculum['speedup_vs_flat']}x**",
                      "- throughput-equal comparison only: same clip "
                      "count, not necessarily the same learning curve "
                      "(PERF.md 'Curriculum training'); stage rows above "
                      "carry their per-stage shapes in the `stage` "
                      "column and are excluded from the headline "
                      "operating point."]
        if truncated:
            lines += ["", "**SWEEP TRUNCATED**: the TPU tunnel wedged "
                      "mid-sweep; rows above are what was measured "
                      "before it died."]
        lines += ["", "Roofline context for these numbers: PERF.md "
                  "(analytic per-stage FLOPs/bytes/intensity model)."]
        if hand_notes:
            lines += ["", hand_notes]
        with open(os.path.join(_REPO, "BENCH_NOTES.md"), "w") as fh:
            fh.write("\n".join(lines) + "\n")
    except Exception as exc:
        _note(f"bench: could not write BENCH_NOTES.md: {exc}")


def main():
    try:
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(_REPO, "build", "jax_cache"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass

        cfg_json = os.environ.get(_CONFIG_ENV)
        if cfg_json:
            # Measurement grand-child: time exactly ONE config in this
            # fresh process (clean allocator, own tunnel client) and hand
            # the result dict up as a tagged JSON line.  Errors are data
            # too — the orchestrator's OOM/timeout handling needs the
            # text — so they go to stdout tagged, never the driver record.
            try:
                kwargs = json.loads(cfg_json)
                # env-var platform pins are overridden by accelerator
                # plugins; the jax.config route wins (conftest.py note)
                if kwargs.pop("platform_pin", None) == "cpu":
                    jax.config.update("jax_platforms", "cpu")
                r = _bench_config(**kwargs)
                _emit({"config_result": r})
                return
            except Exception as exc:
                _emit({"config_error": f"{type(exc).__name__}: {exc}"})
                sys.exit(1)

        mode = os.environ.get(_CHILD_MODE_ENV)
        if mode in ("cpu", "tpu"):
            # Sweep-orchestrator child: picks configs, spawns one
            # measurement grand-child per config, prints interim records
            # to stdout (streamed upward by the parent).  It never holds
            # a backend itself — a second concurrent tunnel client is a
            # failure mode.  A child that fails before ANY config exits
            # nonzero with no record and the parent falls back — a
            # swallowed 0.0 record here would mask a working CPU path.
            try:
                import signal

                signal.signal(signal.SIGTERM, _forward_term_and_exit)
                info_env = os.environ.get(_INFO_ENV)
                if info_env and mode == "tpu":
                    # the parent's probe already initialized a backend
                    # and reported what it saw — don't pay the tunnel
                    # bring-up a second time
                    info = json.loads(info_env)
                else:
                    info = _device_info(force_cpu=(mode == "cpu"))
                on_tpu = (mode == "tpu" and
                          info["platform"] in ("tpu", "axon"))
                _emit(run_bench(on_tpu, info))
                return
            except Exception as exc:
                _note(f"bench child[{mode}]: {type(exc).__name__}: {exc}")
                sys.exit(1)

        # Parent: orchestrate the measurement in CHILDREN so no tunnel
        # failure mode — crash, hang at init, or hang at first execute
        # (all three observed) — can eat the driver's gate timeout
        # without a JSON line being printed.  Child records are STREAMED
        # to our stdout as they arrive (later records supersede earlier:
        # the consumer takes the last parsable line), so even a hard
        # kill of this parent mid-sweep leaves the best-so-far behind.
        def run_child(child_mode: str, timeout=None, device_info=None):
            env = dict(os.environ)
            env[_CHILD_MODE_ENV] = child_mode
            if device_info:
                env[_INFO_ENV] = json.dumps(device_info)
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env=env, cwd=_REPO, stdout=subprocess.PIPE)
            last = None

            def pump():
                nonlocal last
                for raw in proc.stdout:
                    rec = _last_json(raw)
                    if rec is not None:
                        last = rec
                        _emit(rec)

            reader = threading.Thread(target=pump, daemon=True)
            reader.start()
            try:
                proc.wait(timeout=timeout)
                status = "ok" if proc.returncode == 0 else f"rc={proc.returncode}"
            except subprocess.TimeoutExpired:
                # SIGTERM first with a grace period: a hard kill of a live
                # TPU client is what wedges the relay (SKILL.md notes);
                # only escalate if the client ignores the term.
                _graceful_stop(proc)
                status = f"timeout>{timeout}s"
            reader.join(timeout=10)
            return last, status

        probe_info = _probe_backend()
        # Wait-for-heal: the tunnel wedges for stretches of tens of
        # minutes (observed 2026-07-29..31) and a round's bench gate that
        # happens to land inside one records a CPU fallback even though
        # the chip is fine (BENCH_r02.json).  Re-probe on a cadence within
        # MILNCE_BENCH_WAIT_HEAL — and charge BOTH the sleeps and the
        # probes against it, then deduct the whole wait from the TPU
        # child's budget below, so the worst-case time-to-JSON-record is
        # NO LONGER than before this feature existed (an outer gate tuned
        # to the old worst case must never kill us record-less mid-wait).
        heal_spent = 0.0
        if probe_info is None:
            heal_budget = float(os.environ.get("MILNCE_BENCH_WAIT_HEAL",
                                               "1800"))
            # Placeholder record BEFORE the wait: if an outer gate kills
            # this parent mid-sleep, the consumer (last parsable line)
            # still gets an honest marker instead of no JSON at all.
            # Any real measurement emitted later supersedes it.
            _emit({"metric": "train_step clips/sec/chip", "value": 0.0,
                   "unit": "clips/sec/chip", "vs_baseline": 0.0,
                   "on_tpu": False,
                   "note": "tunnel down at probe time; waiting up to "
                           f"{heal_budget:.0f}s for heal — if this is the "
                           "final line, the process was killed mid-wait",
                   "last_tpu_value": LAST_TPU_OPERATING_POINT})
            heal_start = time.time()
            while probe_info is None:
                remaining = heal_budget - (time.time() - heal_start)
                if remaining <= 0:
                    break
                wait_s = min(300.0, remaining)
                _note(f"bench: waiting {wait_s:.0f}s for the tunnel to heal "
                      f"({remaining / 60:.0f} min of budget left)")
                time.sleep(wait_s)
                remaining = heal_budget - (time.time() - heal_start)
                if remaining <= 0:
                    break
                probe_info = _probe_backend(timeout_s=min(180.0, remaining))
            heal_spent = time.time() - heal_start
        if probe_info:
            # Even a healthy-probing tunnel can wedge mid-sweep; bound the
            # whole TPU run and fall back rather than hang the gate.  A
            # full sweep with two cold compiles and one wedged-config cap
            # was ~65 min (~3900s); the grad_accum8 row adds up to
            # 2*cfg_timeout more, so the default clears ~5700s.
            # Interim records stream to stdout as they land, so if an
            # OUTER timeout kills this parent first no measurement is
            # lost — but the kill skips _graceful_stop and can still
            # wedge the tunnel for LATER clients, so prefer setting
            # MILNCE_BENCH_TPU_TIMEOUT below any outer deadline.
            budget = float(os.environ.get("MILNCE_BENCH_TPU_TIMEOUT", "6300"))
            # a late heal ate into the overall time box: hand the sweep
            # what's left (it streams interim records and marks partial,
            # so a truncated sweep still lands its rows)
            budget = max(300.0, budget - heal_spent)
            rec, status = run_child("tpu", timeout=budget,
                                    device_info=probe_info)
            if rec is not None:
                if status != "ok":
                    _note(f"bench: TPU child {status}; forwarding the record "
                          "it emitted before dying")
                    # machine-visible truncation: a best-so-far from a dead
                    # child must not read as a complete sweep
                    rec["partial"] = status
                _emit(rec)
                return
            _note(f"bench: TPU child {status} with no record — CPU fallback")
        else:
            _note("bench: accelerator unavailable; re-exec on CPU")
        # The CPU child gets a deadline too: an unbounded hang here (stuck
        # import, wedged compile-cache lock) would eat the gate with no
        # JSON, the exact failure the parent/child design exists to stop.
        cpu_budget = float(os.environ.get("MILNCE_BENCH_CPU_TIMEOUT", "900"))
        rec, status = run_child("cpu", timeout=cpu_budget)
        if rec is None:
            raise RuntimeError(f"CPU fallback child {status} with no record")
        if status != "ok":
            _note(f"bench: CPU child {status}; forwarding the record it "
                  "emitted before dying")
            rec["partial"] = status
        _emit(rec)
    except Exception as exc:  # LAST RESORT: the line must always be parsable
        _emit({"metric": "train_step clips/sec/chip", "value": 0.0,
               "unit": "clips/sec/chip", "vs_baseline": 0.0,
               "error": f"{type(exc).__name__}: {exc}"})
        sys.exit(0)


if __name__ == "__main__":
    main()
