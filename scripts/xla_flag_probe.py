"""XLA flag probe: re-measure the winning train-step operating point
under candidate XLA:TPU flags.

The measured MFU (18.2%, BENCH_NOTES.md) sits far under the analytic
roofline ceiling (~63%, PERF.md) and the gap is scheduling/tiling —
exactly the territory XLA flags move.  Each candidate flag set runs in
its own watchdogged bench config child (bench._run_config: fresh
process, own tunnel client, TERM-first stop), so a flag that wedges the
compiler costs one timeout, and a flag the compiler rejects surfaces as
a tagged error row WITH the child's stderr, not a crash.

Round-5 lesson (XLA_FLAGS_PROBE.md): every non-baseline row died
``rc=1, no record`` because the ``--xla_tpu_*`` knobs went into
``XLA_FLAGS``, which the CLIENT-side XLA flag parser also reads — and
it hard-aborts the process on any flag its own build doesn't know
(the TPU-compiler knobs live in libtpu, not the client).  The fix is a
flag ROUTER (:func:`split_flags`): ``--xla_tpu_*`` candidates ride
``LIBTPU_INIT_ARGS`` (the TPU runtime's own flag channel), everything
else stays in ``XLA_FLAGS``; both are restored after every row, and the
child's stderr is captured into the report either way so the next
failure diagnoses itself.

The grid crosses the flag candidates with the winning stem lowering
when an autotune artifact exists (``scripts/stage_probe.py --autotune``
-> build/impl_map.json, or ``--impl_map``): the scoped-vmem limit is
exactly the knob that decides how big a tile the one large im2col
dot_general gets, so the two must be measured together.

    python scripts/xla_flag_probe.py                 # bf16 batch 128
    python scripts/xla_flag_probe.py --batch 64 --timeout 600
    MILNCE_FLAGPROBE_CPU=1 python scripts/xla_flag_probe.py   # smoke

Writes one JSON line per flag set to stdout and (TPU runs only)
XLA_FLAGS_PROBE.md, incrementally — a mid-probe tunnel wedge keeps the
rows measured, stops the remaining candidates, and marks the artifact
truncated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402

# Candidate sets, each relative to the baseline flags the environment
# already carries.  Conservative public knobs relevant to a single-chip
# conv workload; collectives-oriented flags are pointless on one chip.
CANDIDATES = [
    ("baseline", ""),
    # more scoped VMEM lets the conv emitter / dot tiler pick bigger
    # tiles (the small-temporal-dim stages are exactly the ones starved
    # for tile)
    ("vmem_64m", "--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("vmem_128m", "--xla_tpu_scoped_vmem_limit_kib=131072"),
    # overlap-oriented scheduler; mostly collectives but also reorders
    # copies around the big fusions
    ("latency_hiding", "--xla_tpu_enable_latency_hiding_scheduler=true"),
    # both together
    ("vmem_128m+lhs", "--xla_tpu_scoped_vmem_limit_kib=131072 "
     "--xla_tpu_enable_latency_hiding_scheduler=true"),
]

# CPU smoke grid: the TPU knobs above would be rejected by the CPU
# client's flag parser (the exact round-5 failure this probe now
# guards against), so the smoke exercises the same launcher/env
# plumbing with flags the host XLA build does know.
CPU_CANDIDATES = [
    ("baseline", ""),
    ("host_devices_2", "--xla_force_host_platform_device_count=2"),
]


def split_flags(flags: str) -> tuple[str, str]:
    """Route one candidate set: (xla_flags_part, libtpu_part).

    ``--xla_tpu_*`` knobs are TPU-compiler flags parsed by libtpu; fed
    to the client's XLA_FLAGS parser they abort the process before jax
    even initializes (rc=1, no record — the round-5 row killer)."""
    tpu, generic = [], []
    for tok in flags.split():
        (tpu if tok.startswith("--xla_tpu_") else generic).append(tok)
    return " ".join(generic), " ".join(tpu)


def build_grid(cpu: bool, stem_impl_map: str) -> list:
    """(name, flags, extra _run_config kwargs) rows.

    When a winning stem lowering is known (autotune artifact or inline
    spec), it is crossed with the baseline and the two flag sets that
    interact with the big-matmul stem (scoped VMEM sizes the dot tiles;
    the latency-hiding scheduler reorders the copies around them)."""
    base = CPU_CANDIDATES if cpu else CANDIDATES
    grid = [(name, flags, {}) for name, flags in base]
    if stem_impl_map:
        extra = {"conv_impl_map": stem_impl_map}
        cross = ([("", "")] if cpu else
                 [("", ""),
                  ("+vmem_128m", "--xla_tpu_scoped_vmem_limit_kib=131072"),
                  ("+lhs", "--xla_tpu_enable_latency_hiding_scheduler=true")])
        for suffix, flags in cross:
            grid.append((f"stem_tuned{suffix}", flags, dict(extra)))
    return grid


def resolve_impl_map(arg: str, cpu: bool = False) -> str:
    """--impl_map value -> the spec _run_config gets: '' (none), an
    inline spec passed through, or an artifact path made absolute (the
    child resolves from its own cwd).

    An EXPLICIT --impl_map is obeyed as given.  The default
    build/impl_map.json is auto-picked only when it is trustworthy for
    this run: marked complete, and tuned on a matching platform — the
    documented CPU smoke writes that path too, and a TPU probe silently
    crossing its flag grid with a CPU-chosen map would publish wrong
    winners."""
    if not arg:
        default = os.path.join(_REPO, "build", "impl_map.json")
        if not os.path.exists(default):
            return ""
        try:
            with open(default) as fh:
                art = json.load(fh)
        except (OSError, ValueError):
            return ""
        if not art.get("complete"):
            return ""
        tuned_on_cpu = str(art.get("device", "")).lower() == "cpu"
        return default if tuned_on_cpu == cpu else ""
    if "=" in arg:
        return arg
    return arg if os.path.isabs(arg) else os.path.join(_REPO, arg)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--frames", type=int, default=16)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--impl_map", default="",
                    help="per-stage impl map to cross with the flag "
                         "grid: inline spec or artifact path; '' = "
                         "build/impl_map.json when it exists")
    args = ap.parse_args()

    # TERMing this probe must reach the live measurement grand-child
    # (bench's own child mode registers the same forwarder)
    import signal

    signal.signal(signal.SIGTERM, bench._forward_term_and_exit)

    cpu = os.environ.get("MILNCE_FLAGPROBE_CPU") == "1"
    if cpu:
        peak, pin = None, "cpu"       # sanity run on tiny shapes
        args.frames, args.size, args.batch = 2, 32, 8
    else:
        probe = bench._probe_backend()
        if not probe or probe.get("platform") not in ("tpu", "axon"):
            # a healthy CPU backend is still the wrong instrument: five
            # 900s full-size S3D steps on host CPU would write rows that
            # read as TPU results
            print(json.dumps({"error": "no TPU backend", "probe": probe}))
            sys.exit(1)
        peak, pin = bench._peak_flops(str(probe.get("kind", ""))), None

    impl_map = resolve_impl_map(args.impl_map, cpu)
    grid = build_grid(cpu, impl_map)

    base_xla = os.environ.get("XLA_FLAGS", "")
    base_libtpu = os.environ.get("LIBTPU_INIT_ARGS", "")
    rows = []
    truncated = False
    try:
        for name, flags, extra in grid:
            xla_part, libtpu_part = split_flags(flags)
            os.environ["XLA_FLAGS"] = (base_xla + " " + xla_part).strip()
            os.environ["LIBTPU_INIT_ARGS"] = (
                base_libtpu + " " + libtpu_part).strip()
            try:
                r = bench._run_config(
                    timeout_s=args.timeout, platform_pin=pin,
                    dtype=args.dtype, batch=args.batch,
                    frames=args.frames, size=args.size, words=20, k=5,
                    remat=False, inner=4 if not cpu else 1, s2d=False,
                    conv_impl="native", peak=peak, flops_hint=None,
                    **extra)
                row = {"name": name, "flags": flags,
                       "impl_map": extra.get("conv_impl_map", ""),
                       "clips_per_sec_per_chip": r["clips_per_sec_per_chip"],
                       "step_ms": r["step_ms"], "mfu": r.get("mfu")}
            except Exception as exc:
                # _run_config now carries the child's stderr tail for
                # record-less deaths; keep the whole text — the report
                # table shows a truncation, the failure section the rest
                row = {"name": name, "flags": flags,
                       "impl_map": extra.get("conv_impl_map", ""),
                       "error": f"{type(exc).__name__}: {exc}"}
            print(json.dumps(row), flush=True)
            rows.append(row)
            if not cpu:
                _write_md(rows, args, truncated)
            if "error" in row and "config timeout" in row["error"] and not cpu:
                # the timed-out compile may have wedged the tunnel (the
                # batch-256 failure mode): without this re-probe every later
                # candidate would burn its full timeout and be recorded as a
                # flag failure it never earned (bench.run_bench does the same)
                os.environ["XLA_FLAGS"] = base_xla
                os.environ["LIBTPU_INIT_ARGS"] = base_libtpu
                if not bench._probe_backend():
                    truncated = True
                    _write_md(rows, args, truncated)
                    print(json.dumps({"error": "tunnel wedged mid-probe; "
                                      "remaining candidates not tested"}))
                    break
    finally:
        # an exception escaping the loop (e.g. _write_md IOError) must
        # not leave a candidate's flags polluting the parent environment
        os.environ["XLA_FLAGS"] = base_xla
        os.environ["LIBTPU_INIT_ARGS"] = base_libtpu


def _write_md(rows, args, truncated=False) -> None:
    # TPU runs only (callers gate on `cpu`): a sanity run must never
    # clobber a real-chip artifact — same rule as bench._write_notes
    # and stage_probe
    lines = [
        "# XLA flag probe (auto-written by scripts/xla_flag_probe.py)", "",
        f"- config: {args.dtype} batch={args.batch} "
        f"{args.frames}f@{args.size}^2, full train step, differenced "
        "timing (4 inner steps/dispatch)",
        "- --xla_tpu_* candidates ride LIBTPU_INIT_ARGS (the client-side "
        "XLA_FLAGS parser aborts on flags it doesn't know — the round-5 "
        "rc=1 rows); stem_tuned rows apply the per-stage impl map.",
        "", "| name | flags | map | step_ms | clips/s/chip | MFU |",
        "|---|---|---|---|---|---|",
    ]
    if truncated:
        lines.insert(4, "- **PROBE TRUNCATED**: the tunnel wedged "
                     "mid-probe; rows below are what was measured, "
                     "remaining candidates were NOT tested.")
    failures = []
    for r in rows:
        mapped = "tuned" if r.get("impl_map") else "-"
        if "error" in r:
            failures.append(r)
            lines.append(f"| {r['name']} | `{r['flags'] or '(none)'}` | "
                         f"{mapped} | error (see below) | | |")
        else:
            lines.append(f"| {r['name']} | `{r['flags'] or '(none)'}` | "
                         f"{mapped} | {r['step_ms']} | "
                         f"{r['clips_per_sec_per_chip']} | "
                         f"{r.get('mfu', '-')} |")
    if failures:
        lines += ["", "## Failures (child stderr captured per row)"]
        for r in failures:
            lines += ["", f"### {r['name']}", "```",
                      r["error"][:2000], "```"]
    with open(os.path.join(_REPO, "XLA_FLAGS_PROBE.md"), "w") as fh:
        fh.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
