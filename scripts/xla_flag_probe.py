"""XLA flag probe: re-measure the winning train-step operating point
under candidate XLA:TPU flags.

The measured MFU (18.1%, BENCH_NOTES.md) sits far under the analytic
roofline ceiling (~63%, PERF.md) and the gap is scheduling/tiling —
exactly the territory XLA flags move.  Each candidate flag set runs in
its own watchdogged bench config child (bench._run_config: fresh
process, own tunnel client, TERM-first stop), so a flag that wedges the
compiler costs one timeout, and a flag the compiler rejects surfaces as
a tagged error row, not a crash.

    python scripts/xla_flag_probe.py                 # bf16 batch 128
    python scripts/xla_flag_probe.py --batch 64 --timeout 600

Writes one JSON line per flag set to stdout and (TPU runs only)
XLA_FLAGS_PROBE.md, incrementally — a mid-probe tunnel wedge keeps the
rows measured, stops the remaining candidates, and marks the artifact
truncated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402

# Candidate sets, each relative to the baseline flags the environment
# already carries.  Conservative public knobs relevant to a single-chip
# conv workload; collectives-oriented flags are pointless on one chip.
CANDIDATES = [
    ("baseline", ""),
    # more scoped VMEM lets the conv emitter pick bigger tiles (the
    # small-temporal-dim stages are exactly the ones starved for tile)
    ("vmem_64m", "--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("vmem_128m", "--xla_tpu_scoped_vmem_limit_kib=131072"),
    # overlap-oriented scheduler; mostly collectives but also reorders
    # copies around the big fusions
    ("latency_hiding", "--xla_tpu_enable_latency_hiding_scheduler=true"),
    # both together
    ("vmem_128m+lhs", "--xla_tpu_scoped_vmem_limit_kib=131072 "
     "--xla_tpu_enable_latency_hiding_scheduler=true"),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--frames", type=int, default=16)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args()

    # TERMing this probe must reach the live measurement grand-child
    # (bench's own child mode registers the same forwarder)
    import signal

    signal.signal(signal.SIGTERM, bench._forward_term_and_exit)

    cpu = os.environ.get("MILNCE_FLAGPROBE_CPU") == "1"
    if cpu:
        peak, pin = None, "cpu"       # sanity run on tiny shapes
        args.frames, args.size, args.batch = 2, 32, 8
    else:
        probe = bench._probe_backend()
        if not probe or probe.get("platform") not in ("tpu", "axon"):
            # a healthy CPU backend is still the wrong instrument: five
            # 900s full-size S3D steps on host CPU would write rows that
            # read as TPU results
            print(json.dumps({"error": "no TPU backend", "probe": probe}))
            sys.exit(1)
        peak, pin = bench._peak_flops(str(probe.get("kind", ""))), None

    base_flags = os.environ.get("XLA_FLAGS", "")
    rows = []
    truncated = False
    try:
        for name, flags in CANDIDATES:
            os.environ["XLA_FLAGS"] = (base_flags + " " + flags).strip()
            try:
                r = bench._run_config(
                    timeout_s=args.timeout, platform_pin=pin,
                    dtype=args.dtype, batch=args.batch,
                    frames=args.frames, size=args.size, words=20, k=5,
                    remat=False, inner=4 if not cpu else 1, s2d=False,
                    conv_impl="native", peak=peak, flops_hint=None)
                row = {"name": name, "flags": flags,
                       "clips_per_sec_per_chip": r["clips_per_sec_per_chip"],
                       "step_ms": r["step_ms"], "mfu": r.get("mfu")}
            except Exception as exc:
                row = {"name": name, "flags": flags,
                       "error": f"{type(exc).__name__}: {exc}"}
            print(json.dumps(row), flush=True)
            rows.append(row)
            if not cpu:
                _write_md(rows, args, truncated)
            if "error" in row and "config timeout" in row["error"] and not cpu:
                # the timed-out compile may have wedged the tunnel (the
                # batch-256 failure mode): without this re-probe every later
                # candidate would burn its full timeout and be recorded as a
                # flag failure it never earned (bench.run_bench does the same)
                os.environ["XLA_FLAGS"] = base_flags
                if not bench._probe_backend():
                    truncated = True
                    _write_md(rows, args, truncated)
                    print(json.dumps({"error": "tunnel wedged mid-probe; "
                                      "remaining candidates not tested"}))
                    break
    finally:
        # an exception escaping the loop (e.g. _write_md IOError) must
        # not leave a candidate's flags polluting the parent environment
        os.environ["XLA_FLAGS"] = base_flags


def _write_md(rows, args, truncated=False) -> None:
    # TPU runs only (callers gate on `cpu`): a sanity run must never
    # clobber a real-chip artifact — same rule as bench._write_notes
    # and stage_probe
    lines = [
        "# XLA flag probe (auto-written by scripts/xla_flag_probe.py)", "",
        f"- config: {args.dtype} batch={args.batch} "
        f"{args.frames}f@{args.size}^2, full train step, differenced "
        "timing (4 inner steps/dispatch)",
        "", "| name | flags | step_ms | clips/s/chip | MFU |",
        "|---|---|---|---|---|",
    ]
    if truncated:
        lines.insert(3, "- **PROBE TRUNCATED**: the tunnel wedged "
                     "mid-probe; rows below are what was measured, "
                     "remaining candidates were NOT tested.")
    for r in rows:
        if "error" in r:
            lines.append(f"| {r['name']} | `{r['flags'] or '(none)'}` | "
                         f"error: {r['error'][:80]} | | |")
        else:
            lines.append(f"| {r['name']} | `{r['flags'] or '(none)'}` | "
                         f"{r['step_ms']} | {r['clips_per_sec_per_chip']} | "
                         f"{r.get('mfu', '-')} |")
    with open(os.path.join(_REPO, "XLA_FLAGS_PROBE.md"), "w") as fh:
        fh.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
