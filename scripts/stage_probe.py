"""Per-stage on-device timing of the S3D-G trunk.

BENCH_NOTES.md records whole-train-step MFU far below the analytic
roofline ceiling (PERF.md: weighted ceiling ~63%); this probe answers
*where* the gap lives by timing every trunk stage (conv1, pools,
conv_2b/2c, each Inception block, head) as its own jitted program on
the real chip, with the same chained-scan + differenced +
host-materialized timing the soft-DTW harness uses (the axon tunnel's
``block_until_ready`` can resolve early and per-dispatch latency is
seconds — ``milnce_tpu/ops/softdtw_profile.py:timed_run`` notes).

Per stage it reports measured ms, the analytic roofline expectation at
the same shape (FLOPs, bytes, and the min(MXU, HBM) time bound from
``milnce_tpu/utils/roofline.py``), and the achieved fraction of that
bound — a stage far under its own bound is a scheduling/tiling problem,
not physics.

    python scripts/stage_probe.py                  # bf16 batch 32
    python scripts/stage_probe.py --batch 128 --dtype bfloat16
    MILNCE_PROFILE_CPU=1 python scripts/stage_probe.py --batch 2 --size 64

``--autotune`` turns the probe into a per-stage impl SELECTOR: every
conv stage is timed under each lowering in ``--impls`` (native, fold2d,
im2col — models/conv3d.py) for each mode in ``--modes`` (fwd, fwdbwd),
the winner per stage is the one with the lowest fwd+bwd time (the
training cost; PERF.md puts the backward near 13% MFU, so a
forward-picked winner could still lose the step), and the winning map
is written as a JSON artifact (``--out``, default build/impl_map.json)
that ``ModelConfig.conv_impl_map``, ``bench.py``
(MILNCE_BENCH_IMPL_MAP) and ``scripts/xla_flag_probe.py`` all consume:

    python scripts/stage_probe.py --autotune
    MILNCE_PROFILE_CPU=1 python scripts/stage_probe.py --autotune \
        --batch 2 --frames 4 --size 32 --stages conv1 --iters 2

Writes one JSON line per stage to stdout and a summary table to
``STAGE_PROBE.md`` / ``STAGE_AUTOTUNE.md`` (TPU runs only; a CPU sanity
run must never clobber a real-chip artifact).  The autotune JSON
artifact is written on every platform — it records its device honestly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from bench import _probe_backend  # noqa: E402  (shared wedged-tunnel probe)


# HBM bandwidth (bytes/s) by device_kind substring — public figures,
# companion to bench._PEAK_FLOPS; the roofline bound needs both axes to
# track the device.
_HBM_BW = {
    "v6": 1640e9,       # Trillium / v6e
    "v5p": 2765e9,
    "v5e": 820e9,
    "v5 lite": 820e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
}


def _validate_stage_filter(stages_csv: str) -> set:
    """--stages value -> set of conv stage names; a typo must fail HERE,
    not silently autotune zero stages and ship an empty map marked
    complete (config.parse_conv_impl_map guards the consume side; this
    guards the produce side)."""
    from milnce_tpu.config import CONV_STAGES

    only = {s for s in stages_csv.split(",") if s}
    unknown = only - set(CONV_STAGES)
    if unknown:
        raise ValueError(
            f"--stages names unknown conv stage(s) {sorted(unknown)} "
            f"(stages: {', '.join(CONV_STAGES)})")
    return only


def _hbm_bandwidth(device_kind: str) -> float:
    kind = device_kind.lower()
    for key, val in _HBM_BW.items():
        if key in kind:
            return val
    return min(_HBM_BW.values())


def _timed(fn, x, n_iters: int) -> float:
    """Seconds per fn(x) execution via the shared chained-scan protocol
    (milnce_tpu.utils.timing); short k1 keeps per-stage compiles cheap."""
    import jax.numpy as jnp

    from milnce_tpu.utils.timing import chained_seconds

    return chained_seconds(lambda d: jnp.sum(fn(d)), x, n_iters, k1=2)


def _stage_fns(model, variables, method, mode: str):
    """(fwd, probe) for one stage method of ``model``: probe is the
    forward in 'fwd' mode, or the fwd+bwd scalar (grads w.r.t. params
    AND input — what training pays at this stage) in 'fwdbwd' mode."""
    import jax
    import jax.numpy as jnp

    def fwd(x):
        return model.apply(variables, x, method=method)

    if mode == "fwd":
        return fwd, fwd

    def fwdbwd(x):
        # Both grads fold into one scalar so neither is DCE'd.  Only the
        # 'params' collection is differentiated (batch_stats and friends
        # stay closed over); grads of params the stage doesn't touch are
        # constant zeros XLA folds away, costing trace size, not runtime.
        rest = {k: v for k, v in variables.items() if k != "params"}

        def loss(p, xx):
            return jnp.sum(
                model.apply({"params": p, **rest}, xx, method=method)
                .astype(jnp.float32))

        dp, dx = jax.grad(loss, argnums=(0, 1))(variables["params"], x)
        acc = jnp.sum(dx.astype(jnp.float32))
        for leaf in jax.tree_util.tree_leaves(dp):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    return fwd, fwdbwd


def _build_stages(model, variables, mode: str):
    """The trunk as (name, (fwd, probe), pool_before, is_conv) tuples,
    in forward order — shared by the single-impl probe and the
    autotuner."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.models.s3dg import _tf_same_max_pool
    from milnce_tpu.utils import roofline

    def stage(method):
        return _stage_fns(model, variables, method, mode)

    def block_stage(name):
        def method(m, x):
            return getattr(m, name)(x, False)

        return stage(method)

    def pool_stage(window, strides):
        def fwd(x):
            return _tf_same_max_pool(x, window, strides)

        if mode == "fwd":
            return fwd, fwd
        return fwd, jax.grad(lambda x: jnp.sum(fwd(x).astype(jnp.float32)))

    stages = [
        ("conv1", stage(lambda m, x: m.conv1(x, False)), None, True),
        ("maxpool_2a", pool_stage((1, 3, 3), (1, 2, 2)), None, False),
        ("conv_2b", stage(lambda m, x: m.conv_2b(x, False)), None, True),
        ("conv_2c", stage(lambda m, x: m.conv_2c(x, False)), None, True),
        ("gating", stage(lambda m, x: m.stem_gating(x)), None, False),
        ("maxpool_3a", pool_stage((1, 3, 3), (1, 2, 2)), None, False),
    ]
    for idx, (name, _) in enumerate(roofline.INCEPTION_PLAN):
        stages.append((name, block_stage(name),
                       roofline.POOLS_BEFORE.get(idx), True))
    return stages


def _init_jitted(model, frames: int, size: int):
    """jit the init: eager Flax init dispatches every parameter's RNG +
    op individually — multi-second per-dispatch latency over the axon
    tunnel turns that into tens of minutes (bench.py learned the same)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda key: model.init(
        key, jnp.zeros((2, frames, size, size, 3), jnp.float32),
        jnp.zeros((2, 6), jnp.int32)))(jax.random.PRNGKey(0))


def _setup_backend(args):
    if os.environ.get("MILNCE_PROFILE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif not _probe_backend():
        print(json.dumps({"error": "accelerator unreachable; set "
                          "MILNCE_PROFILE_CPU=1 for a CPU sanity run"}))
        sys.exit(1)

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "build", "jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    dev_kind = getattr(jax.devices()[0], "device_kind",
                       jax.devices()[0].platform)
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    return str(dev_kind), on_tpu


def _device_input_fn(args, compute_dtype):
    """Synthetic input generated ON DEVICE: shipping host-generated
    video over the tunnel costs more than the measurement.  One jitted
    generator reused for all seeds (a fresh lambda per call would miss
    the jit trace cache and recompile over the tunnel)."""
    import jax
    import jax.numpy as jnp

    gen = jax.jit(lambda key: jax.random.uniform(
        key, (args.batch, args.frames, args.size, args.size, 3),
        jnp.float32).astype(compute_dtype))
    return lambda seed: gen(jax.random.PRNGKey(seed))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--frames", type=int, default=16)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--conv_impl", default="native",
                    choices=["native", "fold2d", "im2col"])
    ap.add_argument("--iters", type=int, default=8,
                    help="chained executions per measurement")
    ap.add_argument("--mode", default="fwd", choices=["fwd", "fwdbwd"],
                    help="fwdbwd also differentiates each stage w.r.t. "
                         "its params AND input — the training cost.  The "
                         "backward is ~2/3 of a train step's FLOPs and "
                         "grad-conv lowerings tile differently from the "
                         "forward, so a stage at its forward roofline can "
                         "still be the step's MFU sink")
    ap.add_argument("--autotune", action="store_true",
                    help="time every conv stage under each impl in "
                         "--impls and emit the winning per-stage map "
                         "(see --out)")
    ap.add_argument("--impls", default="native,fold2d,im2col",
                    help="autotune candidates, comma-separated")
    ap.add_argument("--modes", default="fwd,fwdbwd",
                    help="autotune measurement modes; the LAST one "
                         "listed picks the winner (fwdbwd = training "
                         "cost, the default tiebreak)")
    ap.add_argument("--stages", default="",
                    help="autotune only these conv stages (comma list; "
                         "'' = all) — the CPU smoke path")
    ap.add_argument("--out", default=os.path.join("build", "impl_map.json"),
                    help="autotune artifact path (repo-relative)")
    args = ap.parse_args()

    if args.autotune:
        autotune(args)
        return

    dev_kind, on_tpu = _setup_backend(args)

    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import full_preset
    from milnce_tpu.models.build import build_model
    from milnce_tpu.models.s3dg import _tf_same_max_pool
    from milnce_tpu.utils import roofline

    cfg = full_preset()
    cfg.model.dtype = args.dtype
    cfg.model.conv_impl = args.conv_impl
    model = build_model(cfg.model)
    variables = _init_jitted(model, args.frames, args.size)

    # peak flops / HBM GB/s for the roofline bound (bench.py table)
    from bench import _PEAK_FLOPS, _peak_flops

    peak_flops = _peak_flops(dev_kind) or max(_PEAK_FLOPS.values())
    hbm_gbs = _hbm_bandwidth(dev_kind) if on_tpu else 50e9     # CPU ~DDR

    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    stages = _build_stages(model, variables, args.mode)

    # analytic per-stage roofline at this shape
    model_stages = roofline.s3d_video_stages(
        args.batch, args.frames, args.size,
        dtype_bytes=2 if args.dtype == "bfloat16" else 4)
    flops_by_prefix = {}
    bytes_by_prefix = {}
    for st in model_stages:
        prefix = st.name.split(".")[0]
        flops_by_prefix[prefix] = flops_by_prefix.get(prefix, 0.0) + st.flops
        bytes_by_prefix[prefix] = bytes_by_prefix.get(prefix, 0.0) + st.bytes

    device_input = _device_input_fn(args, compute_dtype)
    x = device_input(0)

    records = []
    total_ms = 0.0
    for name, (fwd_fn, probe_fn), pool, _ in stages:
        if pool is not None:
            x = _tf_same_max_pool(x, *pool)
        t = _timed(probe_fn, x, args.iters)
        if args.mode == "fwdbwd":
            # heuristics, stated in the artifact: fwd + dX + dW = ~3x
            # conv FLOPs (param-free pool stages pay no dW: ~2x);
            # activations re-read and grads written = ~2x traffic
            f_mult = 2.0 if name.startswith("maxpool") else 3.0
            b_mult = 2.0
        else:
            f_mult = b_mult = 1.0
        flops = f_mult * flops_by_prefix.get(name, 0.0)
        byts = b_mult * bytes_by_prefix.get(name, 0.0)
        bound_s = max(flops / peak_flops, byts / hbm_gbs) if byts else None
        rec = {
            "stage": name,
            "mode": args.mode,
            "in_shape": list(x.shape),
            "ms": round(t * 1e3, 3),
            "gflop": round(flops / 1e9, 2),
            "tflops_per_s": round(flops / t / 1e12, 2) if t else None,
            "pct_of_peak": round(100 * flops / t / peak_flops, 1) if t else None,
            "roofline_ms": round(bound_s * 1e3, 3) if bound_s else None,
            "x_over_roofline": (round(t / bound_s, 1)
                                if bound_s and bound_s > 0 else None),
        }
        print(json.dumps(rec), flush=True)
        records.append(rec)
        if on_tpu:
            # rewrite after EVERY stage: a tunnel wedge mid-probe (the
            # observed killed-client failure mode) must not cost the
            # stages already measured
            _write_md(records, args)
        total_ms += t * 1e3
        x = jax.jit(fwd_fn)(x)          # advance via the FORWARD output

    # whole-trunk forward for reconciliation (sum of parts vs one program:
    # the difference is what XLA's cross-stage fusion buys)
    # _stage_fns's second element is already the mode-appropriate probe
    _, trunk_probe = _stage_fns(model, variables,
                                lambda m, v: m.forward_video(v), args.mode)
    x0 = device_input(1)
    t_trunk = _timed(trunk_probe, x0, args.iters)
    summary = {
        "stage": ("TRUNK_FWDBWD(one program)" if args.mode == "fwdbwd"
                  else "TRUNK_FWD(one program)"),
        "mode": args.mode,
        "ms": round(t_trunk * 1e3, 3),
        "sum_of_stage_ms": round(total_ms, 3),
        "device": dev_kind,
        "batch": args.batch,
        "dtype": args.dtype,
        "conv_impl": args.conv_impl,
    }
    print(json.dumps(summary), flush=True)
    records.append(summary)

    if on_tpu:
        _write_md(records, args)


def _hbm_budget_bytes() -> float | None:
    """Per-chip device-memory budget for the autotune pre-flight:
    MILNCE_HBM_GIB (explicit, e.g. 16 for v5e) wins; otherwise the
    backend's own bytes_limit when it exposes one (TPU does, the CPU
    test platform doesn't).  None = no budget known, pre-flight off."""
    env = os.environ.get("MILNCE_HBM_GIB")
    if env:
        return float(env) * 2 ** 30
    import jax

    stats = getattr(jax.local_devices()[0], "memory_stats", lambda: None)()
    if stats and stats.get("bytes_limit"):
        return float(stats["bytes_limit"])
    return None


def _preflight_peak(probe_fn, x) -> float | None:
    """Predicted per-chip peak bytes of one candidate's probe program
    (graftlint Pass 4 planner) — None when the trace itself fails (the
    candidate will fail identically when timed; let the sweep surface
    that error, not the pre-flight)."""
    try:
        from milnce_tpu.analysis.memplan import preflight_fn_peak

        return float(preflight_fn_peak(probe_fn, x))
    except Exception as exc:  # graftlint: disable=GL007(pre-flight is advisory: a planner crash must not kill the sweep the planner exists to protect)
        print(json.dumps({"preflight_error": f"{type(exc).__name__}: "
                                             f"{exc}"}), flush=True)
        return None


def autotune(args) -> None:
    """Measure every conv stage under each candidate impl and emit the
    winning per-stage map as a config artifact.

    One model per impl, ONE shared parameter tree (the impls are
    layout-identical by design — models/conv3d.py), stage inputs
    advanced by the native forward so every impl times the same tensor.
    """
    from milnce_tpu.config import CONV_IMPLS

    # validate BEFORE paying for a backend: a typo'd filter would
    # otherwise autotune zero stages and ship an empty complete map
    impls = [s for s in args.impls.split(",") if s]
    modes = [s for s in args.modes.split(",") if s]
    only = _validate_stage_filter(args.stages)
    unknown = set(impls) - set(CONV_IMPLS)
    if unknown:
        raise ValueError(f"--impls names unknown impl(s) {sorted(unknown)} "
                         f"(impls: {', '.join(CONV_IMPLS)})")
    bad_modes = set(modes) - {"fwd", "fwdbwd"}
    if bad_modes:
        # _stage_fns treats anything non-'fwd' as fwdbwd; a typo'd mode
        # would burn a chip session and mislabel the artifact
        raise ValueError(f"--modes names unknown mode(s) {sorted(bad_modes)} "
                         "(modes: fwd, fwdbwd)")

    dev_kind, on_tpu = _setup_backend(args)

    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import full_preset
    from milnce_tpu.models.build import build_model
    from milnce_tpu.models.s3dg import _tf_same_max_pool

    cfg = full_preset()
    cfg.model.dtype = args.dtype
    models = {}
    for impl in impls:
        cfg.model.conv_impl = impl
        models[impl] = build_model(cfg.model)
    variables = _init_jitted(models[impls[0]], args.frames, args.size)

    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    device_input = _device_input_fn(args, compute_dtype)
    x = device_input(0)

    # per-impl stage lists share the walk order; index them together
    per_impl = {impl: {mode: _build_stages(models[impl], variables, mode)
                       for mode in modes}
                for impl in impls}
    walk = per_impl[impls[0]][modes[0]]

    results = {}                        # stage -> impl -> mode -> ms
    impl_map = {}
    # pre-flight budget is sweep-invariant; resolving it per candidate
    # would re-query device memory stats ~impls x stages times
    budget = _hbm_budget_bytes()
    for idx, (name, _, pool, is_conv) in enumerate(walk):
        if pool is not None:
            x = _tf_same_max_pool(x, *pool)
        if is_conv and (not only or name in only):
            timings = {}
            for impl in impls:
                # pre-flight what-if (ISSUE 8): a candidate whose
                # PREDICTED peak exceeds the budget would OOM mid-grid
                # and cost the sweep its remaining stages — skip it with
                # the reason on record instead of crashing the probe
                if budget:
                    peak = _preflight_peak(
                        per_impl[impl][modes[-1]][idx][1][1], x)
                    if peak is not None and peak > budget:
                        print(json.dumps({
                            "stage": name, "impl": impl,
                            "skipped": "predicted peak "
                            f"{peak / 2**30:.2f} GiB exceeds the "
                            f"{budget / 2**30:.2f} GiB budget "
                            "(mem_plan pre-flight)"}), flush=True)
                        continue
                timings[impl] = {}
                for mode in modes:
                    _, probe_fn = per_impl[impl][mode][idx][1]
                    timings[impl][mode] = round(
                        _timed(probe_fn, x, args.iters) * 1e3, 3)
            if not timings:
                print(json.dumps({
                    "stage": name,
                    "skipped": "every candidate failed the mem_plan "
                               "pre-flight — stage keeps conv_impl "
                               "native (no map entry)"}), flush=True)
                fwd_fn = per_impl[impls[0]][modes[0]][idx][1][0]
                x = jax.jit(fwd_fn)(x)
                continue
            # the LAST mode listed picks the winner (fwdbwd by default —
            # the training cost) among candidates that passed pre-flight
            decide = modes[-1]
            winner = min(timings, key=lambda i: timings[i][decide])
            results[name] = timings
            if winner != "native":      # map only carries overrides
                impl_map[name] = winner
            print(json.dumps({"stage": name, "winner": winner,
                              "by": decide, "ms": timings}), flush=True)
            _write_artifact(results, impl_map, args, dev_kind)
            if on_tpu:
                _write_autotune_md(results, impl_map, args, dev_kind)
        # advance via the FIRST impl's forward: all impls compute the
        # same math, so the walk input is impl-independent
        fwd_fn = per_impl[impls[0]][modes[0]][idx][1][0]
        x = jax.jit(fwd_fn)(x)

    _write_artifact(results, impl_map, args, dev_kind, final=True)
    if on_tpu:
        _write_autotune_md(results, impl_map, args, dev_kind)
    print(json.dumps({"artifact": _artifact_path(args),
                      "impl_map": impl_map}), flush=True)


def _artifact_path(args) -> str:
    out = args.out
    return out if os.path.isabs(out) else os.path.join(_REPO, out)


def _write_artifact(results, impl_map, args, dev_kind, final=False) -> None:
    """Incrementally (re)write the autotune artifact — a mid-probe
    tunnel wedge must not cost the stages already decided.  The map
    feeds ModelConfig.conv_impl_map / bench.py MILNCE_BENCH_IMPL_MAP."""
    path = _artifact_path(args)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "generator": "scripts/stage_probe.py --autotune",
        "device": dev_kind,
        "config": {"batch": args.batch, "frames": args.frames,
                   "size": args.size, "dtype": args.dtype,
                   "iters": args.iters, "modes": args.modes},
        "complete": final,
        "impl_map": impl_map,
        "stage_ms": results,
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")


def _write_autotune_md(results, impl_map, args, dev_kind) -> None:
    modes = [s for s in args.modes.split(",") if s]
    impls = [s for s in args.impls.split(",") if s]
    lines = [
        "# Stage impl autotune (auto-written by scripts/stage_probe.py"
        " --autotune)", "",
        f"- config: batch={args.batch} {args.frames}f@{args.size}^2 "
        f"dtype={args.dtype} device={dev_kind}; winner per stage by "
        f"{modes[-1]} ms (the training cost)",
        f"- winning map (native omitted): "
        f"`{json.dumps(impl_map, sort_keys=True)}` -> {args.out}",
        "",
        "| stage | " + " | ".join(f"{i} {m} ms" for i in impls
                                  for m in modes) + " | winner |",
        "|---" * (1 + len(impls) * len(modes) + 1) + "|",
    ]
    for stage, timings in results.items():
        # a candidate absent from timings failed the mem_plan pre-flight
        cells = [str(timings.get(i, {}).get(m, "skipped"))
                 for i in impls for m in modes]
        winner = impl_map.get(stage, "native")
        lines.append(f"| {stage} | " + " | ".join(cells) + f" | {winner} |")
    with open(os.path.join(_REPO, "STAGE_AUTOTUNE.md"), "w") as fh:
        fh.write("\n".join(lines) + "\n")


def _write_md(records, args) -> None:
    path = os.path.join(_REPO, "STAGE_PROBE.md")
    lines = [
        "# Stage probe (auto-written by scripts/stage_probe.py)", "",
        f"- config: batch={args.batch} {args.frames}f@{args.size}^2 "
        f"dtype={args.dtype} conv_impl={args.conv_impl} mode={args.mode}"
        + (" (per-stage fwd+bwd incl. param grads; bound heuristics: "
           "FLOPs x3, x2 for param-free pools; bytes x2)"
           if args.mode == "fwdbwd" else ""),
        "- ms = chained-scan differenced host-materialized time; "
        "roofline_ms = max(FLOPs/peak, bytes/HBM) analytic bound; "
        "x_over = measured/bound (1.0 = at the roofline).", "",
        "| stage | ms | GFLOP | TFLOP/s | % peak | roofline ms | x over |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in records:
        if "gflop" not in r:
            continue
        lines.append(
            f"| {r['stage']} | {r['ms']} | {r['gflop']} | "
            f"{r['tflops_per_s']} | {r['pct_of_peak']} | "
            f"{r['roofline_ms']} | {r['x_over_roofline']} |")
    tail = [r for r in records if r.get("stage", "").startswith("TRUNK")]
    if tail:
        what = ("fwd+bwd" if tail[0].get("mode") == "fwdbwd" else "forward")
        lines += ["", f"Whole-trunk {what} in ONE program: "
                  f"{tail[0]['ms']} ms vs sum-of-stages "
                  f"{tail[0]['sum_of_stage_ms']} ms "
                  "(difference = cross-stage fusion + per-program overhead)."]
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
