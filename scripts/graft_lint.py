#!/usr/bin/env python
"""graftlint CLI: JAX-aware static analysis + trace invariants.

Usage:
    python scripts/graft_lint.py                  # all passes, write LINT.md
    python scripts/graft_lint.py --check          # exit 1 on any finding
    python scripts/graft_lint.py --check --no-trace   # AST passes only
                                                      # (fast, no jax import)
    python scripts/graft_lint.py --no-concurrency # skip Pass 3 (GL010-012)
    python scripts/graft_lint.py --no-memplan     # skip Pass 4 (GL013-015)
    python scripts/graft_lint.py --no-numerics    # skip Pass 5 (GL016-018)
    python scripts/graft_lint.py milnce_tpu/train # explicit scope

Default scope is the ``milnce_tpu`` package — the library code that runs
on the hot path.  The measurement harnesses (bench.py, scripts/*_probe)
deliberately wall-clock-time things and are out of scope by default;
lint them explicitly when touching them.

The tier-1 gate (tests/test_graftlint.py) runs ``--check --no-trace`` as
a subprocess and the trace pass in-process, so a new finding fails the
suite, not just this tool.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Must happen before any jax import (the trace pass needs the hermetic
# multi-device CPU platform the tests use; see tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from milnce_tpu.analysis.astlint import lint_paths_full  # noqa: E402
from milnce_tpu.analysis.report import render_report  # noqa: E402

DEFAULT_SCOPE = ["milnce_tpu"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: milnce_tpu)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero on any unsuppressed finding or "
                         "failed invariant")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the trace-invariant pass (no jax import)")
    ap.add_argument("--no-concurrency", action="store_true",
                    help="skip the concurrency pass (GL010-GL012 + the "
                         "lock-order graph); still jax-free either way")
    ap.add_argument("--no-memplan", action="store_true",
                    help="skip the static HBM planner pass (GL013-GL015 "
                         "peak/donation/contributor gates; implied by "
                         "--no-trace)")
    ap.add_argument("--no-numerics", action="store_true",
                    help="skip the numerics pass (GL016-GL018 dtype "
                         "census / cast-inventory / f32-residency gates; "
                         "implied by --no-trace)")
    ap.add_argument("--report", default=os.path.join(_REPO, "LINT.md"),
                    help="report path ('' to skip writing)")
    args = ap.parse_args(argv)

    os.chdir(_REPO)          # findings print repo-relative paths
    paths = args.paths or DEFAULT_SCOPE
    findings, lock_graph = lint_paths_full(
        paths, concurrency=not args.no_concurrency)
    active = [f for f in findings if not f.suppressed]
    for f in active:
        print(f.format())

    trace_results = None
    if not args.no_trace:
        # jax config must be applied before the backend initializes
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        from milnce_tpu.analysis.trace_invariants import run_trace_invariants

        trace_results = run_trace_invariants()
        for r in trace_results:
            print(r.format())

    mem_results = None
    if not args.no_trace and not args.no_memplan:
        # Pass 4 rides on the same hermetic mesh + cached tiny setup the
        # trace pass just built, so it costs tracing, not model builds
        from milnce_tpu.analysis.memplan import run_memplan_checks

        mem_results = run_memplan_checks()
        for r in mem_results:
            print(r.format())

    numerics_results = None
    if not args.no_trace and not args.no_numerics:
        # Pass 5 audits the SAME traced programs Pass 4 just cached
        # (memplan._traced_entry), so it costs walks, not traces
        from milnce_tpu.analysis.numerics import run_numerics_checks

        numerics_results = run_numerics_checks()
        for r in numerics_results:
            print(r.format())

    if args.report:
        with open(args.report, "w") as fh:
            fh.write(render_report(findings, trace_results, paths,
                                   lock_graph, mem_results,
                                   numerics_results))
        print(f"report: {args.report}")

    n_bad = (len(active) + sum(not r.ok for r in trace_results or [])
             + sum(not r.ok for r in mem_results or [])
             + sum(not r.ok for r in numerics_results or []))
    suppressed = sum(f.suppressed for f in findings)
    print(f"graftlint: {len(active)} finding(s), {suppressed} audited "
          f"suppression(s)"
          + ("" if trace_results is None else
             f", {sum(not r.ok for r in trace_results)} invariant "
             f"failure(s)")
          + ("" if mem_results is None else
             f", {sum(not r.ok for r in mem_results)} memplan "
             f"failure(s)")
          + ("" if numerics_results is None else
             f", {sum(not r.ok for r in numerics_results)} numerics "
             f"failure(s)"))
    return 1 if (args.check and n_bad) else 0


if __name__ == "__main__":
    raise SystemExit(main())
