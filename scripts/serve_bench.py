#!/usr/bin/env python
"""Serving load generator: open/closed-loop driver over the full
batcher -> engine -> index path, emitting a ``SERVE_BENCH_*.json``
report (latency percentiles, QPS, batch-occupancy histogram, cache hit
rate).

Usage::

    python scripts/serve_bench.py --backend cpu --preset tiny      # smoke
    python scripts/serve_bench.py --preset tiny --mode open --qps 200
    python scripts/serve_bench.py --export_dir export/run1 ...     # real params

Modes:

- **closed** (default): ``--concurrency`` workers each issue the next
  query the moment the previous one completes — measures the service's
  self-paced throughput and the latency it costs.
- **open**: queries arrive on a Poisson clock at ``--qps`` regardless of
  completions (the honest SLO view: latency under an offered load that
  does not politely wait for the server).
- **tiered** (``--tiers interactive:80,batch:200``): one open-loop
  Poisson driver PER SLO tier, concurrently, each request stamped with
  its tier — the per-tenant view.  The report gains a ``tiers`` block
  (per-tier p50/p99, qps, refusal taxonomy, ``error_rate``) and
  ``obs_report --check`` gates per-tier p99 + error_rate.  ``--knee``
  sweeps the offered load (doubling per round) and reports each tier's
  QPS knee — the last load the service cleared inside
  ``--knee_slo_ms``.
- **tier-class** (``--tier-class``): bench each serving replica class
  (f32 / int8 / distilled student — SERVING.md "Edge tier")
  sequentially at the SAME offered load, one
  ``SERVE_BENCH_<preset>_class_<class>.json`` record per class.  Each
  record carries ``recall_at_10`` (top-10 overlap against the f32
  class's rankings on a fixed query pool; an ``obs_report --check``
  gate metric) and the program's ``dtype_census_hash``, so gating an
  edge class against the committed f32 baseline pins the quality floor
  while latency drift stays attributable to the precision change.

Live-index options: ``--live_index`` serves through the
generation-swapped ``LiveRetrievalIndex`` and ``--ingest_rows N
--ingest_interval_s S`` runs a background ingest job (N random rows
every S seconds through ``service.index_add``), so a chaos spec like
``--faults 'index.swap_raise@%3'`` exercises swap failures UNDER load.
``--continuous`` turns on continuous batching (SERVING.md).

Queries are drawn from a ``--distinct``-sized pool with a Zipf-ish
(1/rank) distribution, so the text-embedding cache sees a realistic
heavy-tailed hit pattern; ``--distinct 0`` disables reuse (pure-miss).

Timing honesty: every recorded latency spans submit -> numpy result on
host (the service API materializes results), so there is no async-
dispatch mirage to correct for; the engine warmup (compiles) happens
before the measurement window and is reported separately as
``warmup_s``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_service(args, tier_class=""):
    """Tiny-preset service stack: random frozen params (or an export),
    synthetic video corpus, programmatic API only.  ``--replicas N``
    builds a ReplicaPool (N single-device engines on the CPU backend)
    instead of one engine — the chaos-bench configuration.

    ``tier_class`` swaps the random-init tower for its edge-tier
    counterpart before the engine is built: ``"int8"`` quantizes the
    frozen tree (weight-only symmetric int8, per-channel where the
    readiness rule demands — quant/quantize.py) and serves it through
    ``QuantizedModel``; ``"student"`` distils the text tower
    (quant/distill.py) and serves the grafted student variables."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from milnce_tpu.config import PRESETS
    from milnce_tpu.models.build import build_model
    from milnce_tpu.obs import metrics as obs_metrics
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.serving.cache import EmbeddingLRUCache
    from milnce_tpu.serving.engine import InferenceEngine
    from milnce_tpu.serving.index import DeviceRetrievalIndex
    from milnce_tpu.serving.service import RetrievalService

    cfg = PRESETS[args.preset]()
    mesh = build_mesh(cfg.parallel)
    video_shape = (cfg.data.num_frames, cfg.data.video_size,
                   cfg.data.video_size, 3)
    registry = obs_metrics.MetricsRegistry()
    pool_kwargs = dict(
        queue_depth=args.replica_queue_depth,
        error_threshold=args.error_threshold,
        probe_interval_s=args.probe_interval_s,
        hedge_quantile=args.hedge_quantile,
        hedge_min_ms=args.hedge_min_ms,
        max_requeues=args.max_requeues, registry=registry)
    if args.export_dir:
        if args.replicas > 1:
            from milnce_tpu.serving.pool import ReplicaPool

            engine = ReplicaPool.from_export(
                args.export_dir, args.replicas, max_batch=args.max_batch,
                min_bucket=args.min_bucket, **pool_kwargs)
        else:
            engine = InferenceEngine.from_export(args.export_dir, mesh,
                                                 max_batch=args.max_batch,
                                                 min_bucket=args.min_bucket)
    else:
        model = build_model(cfg.model)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1,) + video_shape, jnp.float32),
            jnp.zeros((1, cfg.data.max_words), jnp.int32))
        frozen = {"params": variables["params"],
                  "batch_stats": variables.get("batch_stats", {})}
        if tier_class == "int8":
            from milnce_tpu.quant.quantize import (
                QuantizedModel, per_channel_keys_from_weights,
                quantize_variables)

            frozen = quantize_variables(
                frozen, per_channel_keys=per_channel_keys_from_weights(
                    frozen["params"]))
            model = QuantizedModel(model)
        elif tier_class == "student":
            from milnce_tpu.quant.distill import (
                build_student_variables, distill_text_student,
                student_model_config)

            sparams, sinfo = distill_text_student(
                model, frozen, max_words=cfg.data.max_words)
            model = build_model(student_model_config(cfg.model,
                                                     sinfo["hidden_dim"]))
            frozen = build_student_variables(frozen, sparams)
        elif tier_class:
            raise ValueError(f"unknown tier class {tier_class!r}")
        if args.replicas > 1:
            from milnce_tpu.serving.pool import ReplicaPool

            engine = ReplicaPool.build(
                model, frozen, args.replicas,
                text_words=cfg.data.max_words, video_shape=video_shape,
                max_batch=args.max_batch, min_bucket=args.min_bucket,
                **pool_kwargs)
        else:
            engine = InferenceEngine(
                model, frozen, mesh, text_words=cfg.data.max_words,
                video_shape=video_shape, max_batch=args.max_batch,
                min_bucket=args.min_bucket)

    # synthetic corpus, embedded through the engine in bucket-sized chunks
    rng = np.random.default_rng(0)
    corpus_emb = []
    top = engine.buckets[-1]
    for lo in range(0, args.corpus, top):
        n = min(top, args.corpus - lo)
        clips = rng.integers(0, 255, (n,) + video_shape, dtype=np.uint8)
        corpus_emb.append(engine.embed_video(clips))
    corpus_emb = np.concatenate(corpus_emb, axis=0)
    k = min(args.topk, args.corpus)
    if args.live_index:
        from milnce_tpu.serving.live_index import LiveRetrievalIndex

        index = LiveRetrievalIndex(mesh, corpus_emb, k=k,
                                   query_buckets=engine.buckets,
                                   registry=registry)
    else:
        index = DeviceRetrievalIndex(mesh, corpus_emb, k=k,
                                     query_buckets=engine.buckets)
    service = RetrievalService(
        engine, index, cache=EmbeddingLRUCache(args.cache_capacity),
        max_delay_ms=args.max_delay_ms,
        default_timeout_ms=args.timeout_ms, registry=registry,
        max_inflight=args.max_inflight, tiers=args.tier_shares,
        continuous=args.continuous)
    return cfg, service


def make_query_draw(cfg, distinct: int):
    """-> ``draw(rng) -> (W,) int32 token row``.

    ``distinct > 0``: rows come from a fixed pool with 1/rank (Zipf-ish)
    weights — the heavy-tailed repeat pattern the cache exists for.
    ``distinct <= 0``: every draw is a FRESH random row (pure-miss mode;
    the cache never helps)."""
    import numpy as np

    vocab, words = cfg.model.vocab_size, cfg.data.max_words
    if distinct <= 0:
        def draw(rng):
            return rng.integers(1, vocab, (words,)).astype(np.int32)

        return draw
    pool_rng = np.random.default_rng(7)
    pool = pool_rng.integers(1, vocab, (distinct, words)).astype(np.int32)
    ranks = np.arange(1, len(pool) + 1, dtype=np.float64)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()

    def draw(rng):
        return pool[rng.choice(len(pool), p=probs)]

    return draw


def _make_issue(service, lats: list, counters: dict,
                lock: threading.Lock, tier=None):
    """-> ``issue(row)``: one query with the full refusal taxonomy
    counted — expired (504), shed (429), degraded (503) are STRUCTURED
    refusals, ``errors`` is everything unstructured.  Every branch
    returns; nothing can hang a worker.  ``tier`` stamps the request's
    SLO class (tiered mode)."""
    from milnce_tpu.serving.batcher import DeadlineExpired
    from milnce_tpu.serving.pool import PoolSaturated, PoolUnavailable
    from milnce_tpu.serving.service import DegradedError, ShedError

    def issue(row) -> None:
        t0 = time.perf_counter()
        try:
            service.query_ids(row[None, :], tier=tier)
        except DeadlineExpired:
            with lock:
                counters["deadline_expired"] += 1
        except (ShedError, PoolSaturated):
            with lock:
                counters["shed"] += 1
        except (DegradedError, PoolUnavailable):
            with lock:
                counters["degraded"] += 1
        except Exception:
            with lock:
                counters["errors"] += 1
        else:
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

    return issue


def new_counters() -> dict:
    return {"errors": 0, "deadline_expired": 0, "shed": 0, "degraded": 0}


def run_closed_loop(service, draw, duration: float,
                    concurrency: int):
    """Each worker issues the next query on completion; returns
    (latencies_s, counters)."""
    import numpy as np

    lats: list[float] = []
    counters = new_counters()
    lock = threading.Lock()
    issue = _make_issue(service, lats, counters, lock)
    t_end = time.monotonic() + duration

    def worker(wid: int):
        rng = np.random.default_rng(1000 + wid)
        while time.monotonic() < t_end:
            issue(draw(rng))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, counters


def _open_loop_drive(issue, draw, duration: float, qps: float,
                     seed: int = 11) -> None:
    """Poisson arrivals at ``qps``; each arrival runs on its own thread
    (requests keep arriving whether or not earlier ones finished)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    inflight: list[threading.Thread] = []
    t_end = time.monotonic() + duration
    next_arrival = time.monotonic()
    while time.monotonic() < t_end:
        now = time.monotonic()
        if now < next_arrival:
            time.sleep(min(next_arrival - now, 0.01))
            continue
        next_arrival += rng.exponential(1.0 / qps)
        t = threading.Thread(target=issue, args=(draw(rng),), daemon=True)
        t.start()
        inflight.append(t)
    for t in inflight:
        t.join(timeout=30.0)


def run_open_loop(service, draw, duration: float, qps: float):
    lats: list[float] = []
    counters = new_counters()
    lock = threading.Lock()
    _open_loop_drive(_make_issue(service, lats, counters, lock),
                     draw, duration, qps)
    return lats, counters


def run_tiered_open_loop(service, draw, duration: float, tier_qps: dict):
    """One open-loop Poisson driver per SLO tier, concurrently; returns
    ``{tier: (lats, counters, qps_offered)}``."""
    results = {}
    drivers = []
    for i, (tier, qps) in enumerate(tier_qps.items()):
        lats: list[float] = []
        counters = new_counters()
        lock = threading.Lock()
        results[tier] = (lats, counters, qps)
        issue = _make_issue(service, lats, counters, lock, tier=tier)
        drivers.append(threading.Thread(
            target=_open_loop_drive,
            args=(issue, draw, duration, qps, 100 + i), daemon=True))
    for t in drivers:
        t.start()
    for t in drivers:
        t.join()
    return results


def parse_tier_qps(spec: str) -> dict:
    """'interactive:80,batch:200' -> ordered {tier: offered qps}.
    Duplicate names are an error (same contract as the service's
    parse_tier_spec) — a typo'd mix must not silently collapse."""
    out = {}
    for item in filter(None, (c.strip() for c in spec.split(","))):
        name, _, qps = item.partition(":")
        name = name.strip()
        if not name or not qps or name in out:
            raise ValueError(f"tier item {item!r}: expected a UNIQUE "
                             "name:qps")
        out[name] = float(qps)
    if not out:
        raise ValueError("--tiers given but names no tier")
    return out


# serving replica classes the --tier-class comparison knows how to
# build (SERVING.md "Edge tier"); f32 is the recall baseline
TIER_CLASSES = ("f32", "int8", "student")


def _tier_class_rankings(service, cfg, k: int):
    """Top-``k`` corpus ids for a FIXED deterministic query pool — the
    cross-class recall probe.  Same seed for every class, so overlap
    against the f32 class's rankings is attributable to the tower swap
    alone, not query drift."""
    import numpy as np

    rng = np.random.default_rng(17)
    pool = rng.integers(1, cfg.model.vocab_size,
                        (16, cfg.data.max_words)).astype(np.int32)
    top = service.engine.buckets[-1]
    idx = []
    for lo in range(0, len(pool), top):
        _scores, ids = service.query_ids(pool[lo:lo + top])
        idx.append(np.asarray(ids))
    return np.concatenate(idx, axis=0)[:, :k]


def recall_at_k(idx, base_idx) -> float:
    """Mean top-k overlap fraction against the baseline rankings."""
    k = idx.shape[1]
    return float(sum(len(set(a) & set(b)) for a, b in zip(idx, base_idx))
                 / (len(idx) * k))


def _dtype_census_hash(service, cfg) -> str:
    """Precision fingerprint of the service's text embed program at the
    bottom bucket (analysis/numerics.py) — stamped into each class
    record so ``obs_report --check`` marks cross-class gates as
    cross-precision compares instead of plain regressions."""
    import numpy as np

    from milnce_tpu.analysis import numerics

    engine = service.engine
    tokens = np.zeros((engine.buckets[0], cfg.data.max_words), np.int32)
    # the engine's device-resident tree IS the program's weight operand
    audit = numerics.audit_fn(engine.jit_entries()["text"],
                              (engine._variables, tokens),
                              argnames=("variables", "tokens"),
                              entry="serve_bench_text")
    return audit.census_hash()


def run_tier_class(args) -> int:
    """``--tier-class``: bench every class in ``--classes``
    sequentially at the SAME offered load, one milnce.obs/v1 record per
    class.  The f32 class runs first and its top-10 rankings are the
    recall baseline; the exit gate requires recompiles == 0 for every
    class — an edge class that re-traces under the f32 bucket ladder is
    a fail, not a footnote."""
    classes = [c.strip() for c in args.classes.split(",") if c.strip()]
    bad = sorted(set(classes) - set(TIER_CLASSES))
    if bad:
        raise SystemExit(f"serve_bench: unknown --classes {bad}; "
                         f"known classes: {', '.join(TIER_CLASSES)}")
    if not classes or classes[0] != "f32":
        raise SystemExit("serve_bench: --tier-class needs f32 FIRST in "
                         "--classes — it is the recall@10 baseline")
    k = min(10, args.corpus)
    args.topk = max(args.topk, k)   # the index must answer top-10
    base_idx = None
    outputs = []
    ok = True
    for cls in classes:
        t0 = time.monotonic()
        cfg, service = build_service(
            args, tier_class="" if cls == "f32" else cls)
        warmup_s = time.monotonic() - t0
        idx = _tier_class_rankings(service, cfg, k)
        if base_idx is None:
            base_idx = idx
        recall = recall_at_k(idx, base_idx)
        census = _dtype_census_hash(service, cfg)
        draw = make_query_draw(cfg, args.distinct)
        t_run = time.monotonic()
        if args.mode == "closed":
            lats, counters = run_closed_loop(
                service, draw, args.duration, args.concurrency)
        else:
            lats, counters = run_open_loop(
                service, draw, args.duration, args.qps)
        elapsed = time.monotonic() - t_run
        errors = counters["errors"]
        expired = counters["deadline_expired"]
        health = service.health()
        service.close()
        if args.replicas > 1:
            service.engine.close()
        extra = {
            "generator": "scripts/serve_bench.py",
            "mode": f"tier-class/{args.mode}",
            "backend": args.backend,
            "preset": args.preset,
            "tier_class": cls,
            "config": {key: v for key, v in vars(args).items()
                       if key != "out"},
            "warmup_s": round(warmup_s, 3),
            "elapsed_s": round(elapsed, 3),
            "requests": len(lats),
            "errors": errors,
            "deadline_expired": expired,
            "resilience": {key: counters[key]
                           for key in ("shed", "degraded")},
            "error_rate": round(
                errors / max(1, len(lats) + errors + expired
                             + counters["shed"] + counters["degraded"]),
                5),
            "qps": round(len(lats) / elapsed, 2) if elapsed > 0 else 0.0,
            "latency_ms": _lat_summary(lats),
            # the edge-tier quality gate (obs_report: higher is better)
            "recall_at_10": round(recall, 4),
            "dtype_census_hash": census,
            "cache": health["cache"],
            "engine": health["engine"],
            "index": health["index"],
        }
        from milnce_tpu.obs import export as obs_export
        from milnce_tpu.obs.runctx import auto_run_id

        report = obs_export.snapshot(service.registry, kind="serve_bench",
                                     extra=extra,
                                     run_id=auto_run_id("sbench-"),
                                     process_index=0)
        out = os.path.join(
            _REPO, f"SERVE_BENCH_{args.preset}_class_{cls}.json")
        with open(out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        outputs.append((cls, report, out))
        ok = ok and report["engine"]["recompiles"] in (0, -1)
    print(f"serve_bench --tier-class: {len(outputs)} classes at the "
          f"same offered load (mode={args.mode}, "
          f"duration={args.duration}s)")
    for cls, report, out in outputs:
        print(f"  class {cls:<8} qps={report['qps']:<8g} "
              f"p50={report['latency_ms']['p50']}ms "
              f"p99={report['latency_ms']['p99']}ms "
              f"recall@10={report['recall_at_10']} "
              f"census={report['dtype_census_hash']} "
              f"recompiles={report['engine']['recompiles']} "
              f"-> {os.path.basename(out)}")
    return 0 if ok else 1


def knee_from_rounds(rounds: list, slo_ms: float,
                     min_served_frac: float = 0.9):
    """The QPS knee from an open-loop sweep: the highest offered load
    whose round held p99 <= ``slo_ms`` AND served at least
    ``min_served_frac`` of its offered requests (refusals and errors
    count against it).  None when even the first round blew through —
    the knee is below the sweep's floor, a finding in itself."""
    knee = None
    for r in rounds:
        ok = (r["p99_ms"] <= slo_ms
              and r["served_frac"] >= min_served_frac)
        if ok and (knee is None or r["qps_offered"] > knee):
            knee = r["qps_offered"]
    return knee


def _lat_summary(lats: list) -> dict:
    import numpy as np

    lat_ms = np.asarray(sorted(lats), np.float64) * 1e3
    pct = (lambda q: float(np.percentile(lat_ms, q))) if len(lat_ms) \
        else (lambda q: float("nan"))
    return {
        "p50": round(pct(50), 3), "p95": round(pct(95), 3),
        "p99": round(pct(99), 3),
        "mean": round(float(lat_ms.mean()), 3) if len(lat_ms)
        else float("nan"),
        "max": round(float(lat_ms.max()), 3) if len(lat_ms)
        else float("nan"),
    }


def _tier_block(results: dict, elapsed: float) -> dict:
    """Per-tier report block: latency summary + refusal taxonomy +
    the per-tier ``error_rate`` / ``qps`` gate metrics."""
    out = {}
    for tier, (lats, counters, offered) in results.items():
        total = (len(lats) + counters["errors"]
                 + counters["deadline_expired"] + counters["shed"]
                 + counters["degraded"])
        out[tier] = {
            "qps_offered": offered,
            "qps": round(len(lats) / elapsed, 2) if elapsed > 0 else 0.0,
            "requests": len(lats),
            "latency_ms": _lat_summary(lats),
            "error_rate": round(counters["errors"] / max(1, total), 5),
            "served_frac": round(len(lats) / max(1, total), 5),
            **counters,
        }
    return out


def start_ingest(service, rows: int, interval_s: float,
                 stop: threading.Event, seed: int = 99):
    """Background ingest job: ``rows`` random embedding rows through
    ``service.index_add`` every ``interval_s`` — the write-path load for
    live-index benches (ingest errors are counted, never raised into
    the bench)."""
    import numpy as np

    counters = {"ingests": 0, "ingest_errors": 0}
    dim = service.engine.embed_dim
    rng = np.random.default_rng(seed)

    def loop():
        while not stop.wait(interval_s):
            try:
                service.index_add(embeddings=rng.standard_normal(
                    (rows, dim)).astype(np.float32))
                counters["ingests"] += 1
            except Exception:
                counters["ingest_errors"] += 1

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t, counters


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serving load generator (scripts/serve_bench.py)")
    ap.add_argument("--backend", choices=("cpu", "default"), default="cpu",
                    help="'cpu' pins JAX_PLATFORMS=cpu (hermetic smoke); "
                         "'default' uses whatever accelerator jax finds")
    ap.add_argument("--preset", choices=("tiny", "small", "full"),
                    default="tiny")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="measurement window seconds")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop workers")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop offered load")
    ap.add_argument("--corpus", type=int, default=64,
                    help="synthetic video corpus size")
    ap.add_argument("--distinct", type=int, default=32,
                    help="distinct query pool, Zipf-weighted (repeats hit "
                         "the cache); 0 = fresh random row per request "
                         "(pure-miss)")
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--max_batch", type=int, default=16,
                    help="top bucket (taller ladders compile longer)")
    ap.add_argument("--min_bucket", type=int, default=0,
                    help="bottom bucket (0 = mesh/replica-group size; "
                         "raise it to shrink the ladder's compile bill — "
                         "single-device pool replicas otherwise start "
                         "their ladder at 1)")
    ap.add_argument("--max_delay_ms", type=float, default=3.0)
    ap.add_argument("--timeout_ms", type=float, default=0.0)
    ap.add_argument("--cache_capacity", type=int, default=4096)
    ap.add_argument("--export_dir", default="",
                    help="serve a milnce-export instead of random params")
    ap.add_argument("--replicas", type=int, default=1,
                    help="engine replica pool size (>1 = ReplicaPool; on "
                         "the cpu backend the virtual device count is "
                         "forced to match)")
    ap.add_argument("--replica_queue_depth", type=int, default=16)
    ap.add_argument("--error_threshold", type=int, default=3)
    ap.add_argument("--probe_interval_s", type=float, default=0.5)
    ap.add_argument("--hedge_quantile", type=float, default=0.0,
                    help="hedge dispatches past this latency quantile to "
                         "a second replica (0 = off)")
    ap.add_argument("--hedge_min_ms", type=float, default=20.0)
    ap.add_argument("--max_requeues", type=int, default=1,
                    help="failed dispatches retried on another replica "
                         "before the caller sees the error")
    ap.add_argument("--max_inflight", type=int, default=0,
                    help="admission bound: rows in flight before requests "
                         "shed with 429 (0 = unbounded)")
    ap.add_argument("--continuous", action="store_true",
                    help="continuous batching: flush the instant a "
                         "dispatch lane is free, accumulate while lanes "
                         "are busy (SERVING.md; default = flush-and-wait)")
    ap.add_argument("--live_index", action="store_true",
                    help="serve through the generation-swapped "
                         "LiveRetrievalIndex (ingest-capable)")
    ap.add_argument("--ingest_rows", type=int, default=0,
                    help="live-index background ingest: rows per ingest "
                         "(0 = no ingest job; needs --live_index)")
    ap.add_argument("--ingest_interval_s", type=float, default=0.5,
                    help="seconds between background ingests")
    ap.add_argument("--tiers", default="",
                    help="tiered open-loop mode: 'name:qps[,name:qps...]' "
                         "— one Poisson driver per SLO tier (overrides "
                         "--mode; first tier = highest priority)")
    ap.add_argument("--tier_shares", default="",
                    help="admission tier spec 'name:share[,...]' "
                         "(service.parse_tier_spec grammar); '' with "
                         "--tiers = first tier 1.0, the rest 0.5")
    ap.add_argument("--tier-class", dest="tier_class",
                    action="store_true",
                    help="per-replica-class comparison: bench every "
                         "class in --classes sequentially at the same "
                         "offered load, one SERVE_BENCH_<preset>_class_"
                         "<class>.json record per class with recall@10 "
                         "vs the f32 rankings + the program's "
                         "dtype_census_hash (SERVING.md 'Edge tier')")
    ap.add_argument("--classes", default="f32,int8,student",
                    help="--tier-class roster (f32 must come first: it "
                         "is the recall@10 baseline)")
    ap.add_argument("--knee", action="store_true",
                    help="with --tiers: sweep offered load (doubling per "
                         "round) and report each tier's QPS knee")
    ap.add_argument("--knee_rounds", type=int, default=3,
                    help="sweep rounds (offered load x1, x2, x4, ...)")
    ap.add_argument("--knee_slo_ms", type=float, default=500.0,
                    help="p99 bound a round must hold to count toward "
                         "the knee")
    ap.add_argument("--faults", default="",
                    help="fault-injection spec (resilience/faults.py "
                         "grammar, e.g. 'serve.dispatch_raise@%%5;"
                         "serve.replica_dead@40').  Armed AFTER warmup — "
                         "the measurement window is the chaos window — "
                         "and exported as MILNCE_FAULTS for any child")
    ap.add_argument("--out", default="",
                    help="report path (default "
                         "SERVE_BENCH_<preset>_<mode>.json at repo root)")
    args = ap.parse_args(argv)

    if args.backend == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if (args.replicas > 1
                and "xla_force_host_platform_device_count" not in flags):
            # a pool needs one device per replica on the CPU backend;
            # must land before jax initializes its backends
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.replicas}").strip()
    import numpy as np

    if args.ingest_rows and not args.live_index:
        ap.error("--ingest_rows needs --live_index")
    if args.tier_class:
        if args.tiers or args.export_dir or args.live_index or args.faults:
            ap.error("--tier-class is a self-contained comparison: drop "
                     "--tiers/--export_dir/--live_index/--faults")
        return run_tier_class(args)
    tier_qps = parse_tier_qps(args.tiers) if args.tiers else None
    if tier_qps and not args.tier_shares:
        # default shares: the first (highest-priority) tier may use the
        # whole admission budget, every later tier half of it
        args.tier_shares = ",".join(
            f"{name}:{1.0 if i == 0 else 0.5}"
            for i, name in enumerate(tier_qps))

    t0 = time.monotonic()
    cfg, service = build_service(args)     # includes engine+index warmup
    warmup_s = time.monotonic() - t0
    draw = make_query_draw(cfg, args.distinct)

    if args.faults:
        # armed AFTER build/warmup: occurrences count from the first
        # measured request, so a spec like @%5 is reproducible and the
        # compile sweep can't eat scheduled occurrences
        from milnce_tpu.resilience import faults

        os.environ[faults.ENV_VAR] = args.faults
        faults.arm(args.faults)

    ingest_stop = threading.Event()
    ingest_counters = None
    if args.ingest_rows:
        _ingest_thread, ingest_counters = start_ingest(
            service, args.ingest_rows, args.ingest_interval_s, ingest_stop)

    tier_results = None
    knee_report = None
    t_run = time.monotonic()
    if tier_qps:
        rounds_by_tier = {t: [] for t in tier_qps}
        factors = ([2 ** r for r in range(max(1, args.knee_rounds))]
                   if args.knee else [1])
        round_elapsed = args.duration
        for factor in factors:
            scaled = {t: q * factor for t, q in tier_qps.items()}
            t_round = time.monotonic()
            res = run_tiered_open_loop(service, draw, args.duration,
                                       scaled)
            round_elapsed = time.monotonic() - t_round
            tier_results = res          # the LAST round feeds the report
            block = _tier_block(res, round_elapsed)
            for t, td in block.items():
                rounds_by_tier[t].append({
                    "qps_offered": td["qps_offered"],
                    "p99_ms": td["latency_ms"]["p99"],
                    "served_frac": td["served_frac"]})
        if args.knee:
            knee_report = {
                t: {"knee_qps": knee_from_rounds(rounds, args.knee_slo_ms),
                    "slo_ms": args.knee_slo_ms, "rounds": rounds}
                for t, rounds in rounds_by_tier.items()}
        lats, counters = [], new_counters()
        for t_lats, t_counters, _ in tier_results.values():
            lats.extend(t_lats)
            for key in counters:
                counters[key] += t_counters[key]
    elif args.mode == "closed":
        lats, counters = run_closed_loop(
            service, draw, args.duration, args.concurrency)
    else:
        lats, counters = run_open_loop(
            service, draw, args.duration, args.qps)
    elapsed = time.monotonic() - t_run
    if tier_qps:
        # lats/counters hold the LAST round only — qps (top-level and
        # per-tier) must divide by that round's measured window, not the
        # whole sweep (a --knee run's elapsed spans every round)
        elapsed = round_elapsed
    ingest_stop.set()
    errors, expired = counters["errors"], counters["deadline_expired"]
    health = service.health()
    service.close()
    if args.live_index:
        service.index.close()
    if args.replicas > 1:
        service.engine.close()

    extra = {
        "generator": "scripts/serve_bench.py",
        "mode": "tiers" if tier_qps else args.mode,
        "backend": args.backend,
        "preset": args.preset,
        "config": {k: v for k, v in vars(args).items() if k != "out"},
        "warmup_s": round(warmup_s, 3),
        "elapsed_s": round(elapsed, 3),
        "requests": len(lats),
        "errors": errors,
        "deadline_expired": expired,
        # the chaos-bench taxonomy: shed (429) / degraded (503) are
        # structured refusals, requeued/hedged/quarantines/recoveries
        # come from the pool's resilience counters; error_rate is the
        # UNSTRUCTURED failure fraction and an obs_report gate metric
        # (lower is better) so chaos runs can gate error-rate drift
        "resilience": {
            **{k: counters[k] for k in ("shed", "degraded")},
            **(service.engine.counts() if args.replicas > 1 else {}),
        },
        "error_rate": round(
            errors / max(1, len(lats) + errors + expired
                         + counters["shed"] + counters["degraded"]), 5),
        "qps": round(len(lats) / elapsed, 2) if elapsed > 0 else 0.0,
        "latency_ms": _lat_summary(lats),
        "batch_occupancy": health["batcher"]["occupancy"],
        "batcher": {k: v for k, v in health["batcher"].items()
                    if k != "occupancy"},
        "cache": health["cache"],
        "engine": health["engine"],
        "index": health["index"],
        "admission": health["admission"],
        "pool": health.get("pool"),
    }
    if tier_results is not None:
        # per-tier gate metrics: obs_report reads latency_ms_p99@<tier>
        # and error_rate@<tier> out of this block
        extra["tiers"] = _tier_block(tier_results, elapsed)
    if knee_report is not None:
        extra["knee"] = knee_report
    if ingest_counters is not None:
        idx_stats = health["index"]
        extra["ingest"] = {
            **ingest_counters,
            "generation": idx_stats.get("generation"),
            "swaps": idx_stats.get("swaps"),
            "swap_failures": idx_stats.get("swap_failures"),
            "pending_rows": idx_stats.get("pending_rows"),
            "corpus_size": idx_stats.get("size"),
        }
    # the versioned obs snapshot (OBSERVABILITY.md): registry metrics
    # (request counters, per-bucket occupancy, collect-time gauges) plus
    # the report keys above as extras — SERVE_BENCH_*.json and train
    # bench records are now diffable by one tool (scripts/obs_report.py).
    # run_id/process_index tag the report like every other artifact
    # (obs/runctx.py).
    from milnce_tpu.obs import export as obs_export
    from milnce_tpu.obs.runctx import auto_run_id

    report = obs_export.snapshot(service.registry, kind="serve_bench",
                                 extra=extra,
                                 run_id=auto_run_id("sbench-"),
                                 process_index=0)
    out = args.out or os.path.join(
        _REPO, f"SERVE_BENCH_{args.preset}_"
               f"{'tiers' if tier_qps else args.mode}.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    res = report["resilience"]
    print(f"serve_bench: {report['requests']} requests in {elapsed:.2f}s "
          f"({report['qps']} QPS), p50={report['latency_ms']['p50']}ms "
          f"p99={report['latency_ms']['p99']}ms, cache hit rate "
          f"{report['cache']['hit_rate']:.2f}, "
          f"errors={report['errors']} expired={report['deadline_expired']} "
          f"shed={res['shed']} degraded={res['degraded']} "
          f"requeued={res.get('requeued', 0)} hedged={res.get('hedged', 0)} "
          f"quarantines={res.get('quarantines', 0)}, "
          f"recompiles={report['engine']['recompiles']} -> {out}")
    if report.get("tiers"):
        for t, td in report["tiers"].items():
            print(f"  tier {t}: offered {td['qps_offered']} qps, served "
                  f"{td['qps']} qps, p50={td['latency_ms']['p50']}ms "
                  f"p99={td['latency_ms']['p99']}ms, shed={td['shed']} "
                  f"errors={td['errors']} error_rate={td['error_rate']}")
    if report.get("knee"):
        for t, kd in report["knee"].items():
            print(f"  knee {t}: {kd['knee_qps']} qps @ p99<="
                  f"{kd['slo_ms']}ms ({len(kd['rounds'])} rounds)")
    if report.get("ingest"):
        ing = report["ingest"]
        print(f"  ingest: {ing['ingests']} ingests -> generation "
              f"{ing['generation']} ({ing['corpus_size']} rows live, "
              f"{ing['swaps']} swaps, {ing['swap_failures']} swap "
              f"failures, {ing['pending_rows']} pending)")
    index_recompiles = (report["index"] or {}).get("recompiles", 0)
    ok = (report["engine"]["recompiles"] in (0, -1)
          and index_recompiles in (0, -1, None))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
