#!/usr/bin/env python
"""Serving load generator: open/closed-loop driver over the full
batcher -> engine -> index path, emitting a ``SERVE_BENCH_*.json``
report (latency percentiles, QPS, batch-occupancy histogram, cache hit
rate).

Usage::

    python scripts/serve_bench.py --backend cpu --preset tiny      # smoke
    python scripts/serve_bench.py --preset tiny --mode open --qps 200
    python scripts/serve_bench.py --export_dir export/run1 ...     # real params

Modes:

- **closed** (default): ``--concurrency`` workers each issue the next
  query the moment the previous one completes — measures the service's
  self-paced throughput and the latency it costs.
- **open**: queries arrive on a Poisson clock at ``--qps`` regardless of
  completions (the honest SLO view: latency under an offered load that
  does not politely wait for the server).

Queries are drawn from a ``--distinct``-sized pool with a Zipf-ish
(1/rank) distribution, so the text-embedding cache sees a realistic
heavy-tailed hit pattern; ``--distinct 0`` disables reuse (pure-miss).

Timing honesty: every recorded latency spans submit -> numpy result on
host (the service API materializes results), so there is no async-
dispatch mirage to correct for; the engine warmup (compiles) happens
before the measurement window and is reported separately as
``warmup_s``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_service(args):
    """Tiny-preset service stack: random frozen params (or an export),
    synthetic video corpus, programmatic API only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from milnce_tpu.config import PRESETS
    from milnce_tpu.models.build import build_model
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.serving.cache import EmbeddingLRUCache
    from milnce_tpu.serving.engine import InferenceEngine
    from milnce_tpu.serving.index import DeviceRetrievalIndex
    from milnce_tpu.serving.service import RetrievalService

    cfg = PRESETS[args.preset]()
    mesh = build_mesh(cfg.parallel)
    video_shape = (cfg.data.num_frames, cfg.data.video_size,
                   cfg.data.video_size, 3)
    if args.export_dir:
        engine = InferenceEngine.from_export(args.export_dir, mesh,
                                             max_batch=args.max_batch)
    else:
        model = build_model(cfg.model)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1,) + video_shape, jnp.float32),
            jnp.zeros((1, cfg.data.max_words), jnp.int32))
        engine = InferenceEngine(
            model, {"params": variables["params"],
                    "batch_stats": variables.get("batch_stats", {})},
            mesh, text_words=cfg.data.max_words, video_shape=video_shape,
            max_batch=args.max_batch)

    # synthetic corpus, embedded through the engine in bucket-sized chunks
    rng = np.random.default_rng(0)
    corpus_emb = []
    top = engine.buckets[-1]
    for lo in range(0, args.corpus, top):
        n = min(top, args.corpus - lo)
        clips = rng.integers(0, 255, (n,) + video_shape, dtype=np.uint8)
        corpus_emb.append(engine.embed_video(clips))
    index = DeviceRetrievalIndex(
        mesh, np.concatenate(corpus_emb, axis=0),
        k=min(args.topk, args.corpus), query_buckets=engine.buckets)
    service = RetrievalService(
        engine, index, cache=EmbeddingLRUCache(args.cache_capacity),
        max_delay_ms=args.max_delay_ms,
        default_timeout_ms=args.timeout_ms)
    return cfg, service


def make_query_draw(cfg, distinct: int):
    """-> ``draw(rng) -> (W,) int32 token row``.

    ``distinct > 0``: rows come from a fixed pool with 1/rank (Zipf-ish)
    weights — the heavy-tailed repeat pattern the cache exists for.
    ``distinct <= 0``: every draw is a FRESH random row (pure-miss mode;
    the cache never helps)."""
    import numpy as np

    vocab, words = cfg.model.vocab_size, cfg.data.max_words
    if distinct <= 0:
        def draw(rng):
            return rng.integers(1, vocab, (words,)).astype(np.int32)

        return draw
    pool_rng = np.random.default_rng(7)
    pool = pool_rng.integers(1, vocab, (distinct, words)).astype(np.int32)
    ranks = np.arange(1, len(pool) + 1, dtype=np.float64)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()

    def draw(rng):
        return pool[rng.choice(len(pool), p=probs)]

    return draw


def run_closed_loop(service, draw, duration: float,
                    concurrency: int):
    """Each worker issues the next query on completion; returns
    (latencies_s, errors, expired)."""
    import numpy as np

    from milnce_tpu.serving.batcher import DeadlineExpired

    lats: list[float] = []
    errors = [0]
    expired = [0]
    lock = threading.Lock()
    t_end = time.monotonic() + duration

    def worker(wid: int):
        rng = np.random.default_rng(1000 + wid)
        while time.monotonic() < t_end:
            row = draw(rng)
            t0 = time.perf_counter()
            try:
                service.query_ids(row[None, :])
            except DeadlineExpired:
                with lock:
                    expired[0] += 1
                continue
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, errors[0], expired[0]


def run_open_loop(service, draw, duration: float, qps: float):
    """Poisson arrivals at ``qps``; each arrival runs on its own thread
    (requests keep arriving whether or not earlier ones finished)."""
    import numpy as np

    from milnce_tpu.serving.batcher import DeadlineExpired

    lats: list[float] = []
    errors = [0]
    expired = [0]
    lock = threading.Lock()
    rng = np.random.default_rng(11)
    inflight: list[threading.Thread] = []

    def one(row):
        t0 = time.perf_counter()
        try:
            service.query_ids(row[None, :])
        except DeadlineExpired:
            with lock:
                expired[0] += 1
            return
        except Exception:
            with lock:
                errors[0] += 1
            return
        dt = time.perf_counter() - t0
        with lock:
            lats.append(dt)

    t_end = time.monotonic() + duration
    next_arrival = time.monotonic()
    while time.monotonic() < t_end:
        now = time.monotonic()
        if now < next_arrival:
            time.sleep(min(next_arrival - now, 0.01))
            continue
        next_arrival += rng.exponential(1.0 / qps)
        row = draw(rng)
        t = threading.Thread(target=one, args=(row,), daemon=True)
        t.start()
        inflight.append(t)
    for t in inflight:
        t.join(timeout=30.0)
    return lats, errors[0], expired[0]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serving load generator (scripts/serve_bench.py)")
    ap.add_argument("--backend", choices=("cpu", "default"), default="cpu",
                    help="'cpu' pins JAX_PLATFORMS=cpu (hermetic smoke); "
                         "'default' uses whatever accelerator jax finds")
    ap.add_argument("--preset", choices=("tiny", "small", "full"),
                    default="tiny")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="measurement window seconds")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop workers")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop offered load")
    ap.add_argument("--corpus", type=int, default=64,
                    help="synthetic video corpus size")
    ap.add_argument("--distinct", type=int, default=32,
                    help="distinct query pool, Zipf-weighted (repeats hit "
                         "the cache); 0 = fresh random row per request "
                         "(pure-miss)")
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--max_batch", type=int, default=16,
                    help="top bucket (taller ladders compile longer)")
    ap.add_argument("--max_delay_ms", type=float, default=3.0)
    ap.add_argument("--timeout_ms", type=float, default=0.0)
    ap.add_argument("--cache_capacity", type=int, default=4096)
    ap.add_argument("--export_dir", default="",
                    help="serve a milnce-export instead of random params")
    ap.add_argument("--out", default="",
                    help="report path (default "
                         "SERVE_BENCH_<preset>_<mode>.json at repo root)")
    args = ap.parse_args(argv)

    if args.backend == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    t0 = time.monotonic()
    cfg, service = build_service(args)     # includes engine+index warmup
    warmup_s = time.monotonic() - t0
    draw = make_query_draw(cfg, args.distinct)

    t_run = time.monotonic()
    if args.mode == "closed":
        lats, errors, expired = run_closed_loop(
            service, draw, args.duration, args.concurrency)
    else:
        lats, errors, expired = run_open_loop(
            service, draw, args.duration, args.qps)
    elapsed = time.monotonic() - t_run
    health = service.health()
    service.close()

    lat_ms = np.asarray(sorted(lats), np.float64) * 1e3
    pct = (lambda q: float(np.percentile(lat_ms, q))) if len(lat_ms) else (
        lambda q: float("nan"))
    extra = {
        "generator": "scripts/serve_bench.py",
        "mode": args.mode,
        "backend": args.backend,
        "preset": args.preset,
        "config": {k: v for k, v in vars(args).items() if k != "out"},
        "warmup_s": round(warmup_s, 3),
        "elapsed_s": round(elapsed, 3),
        "requests": len(lats),
        "errors": errors,
        "deadline_expired": expired,
        "qps": round(len(lats) / elapsed, 2) if elapsed > 0 else 0.0,
        "latency_ms": {
            "p50": round(pct(50), 3), "p95": round(pct(95), 3),
            "p99": round(pct(99), 3),
            "mean": round(float(lat_ms.mean()), 3) if len(lat_ms) else
            float("nan"),
            "max": round(float(lat_ms.max()), 3) if len(lat_ms) else
            float("nan"),
        },
        "batch_occupancy": health["batcher"]["occupancy"],
        "batcher": {k: v for k, v in health["batcher"].items()
                    if k != "occupancy"},
        "cache": health["cache"],
        "engine": health["engine"],
        "index": health["index"],
    }
    # the versioned obs snapshot (OBSERVABILITY.md): registry metrics
    # (request counters, per-bucket occupancy, collect-time gauges) plus
    # the report keys above as extras — SERVE_BENCH_*.json and train
    # bench records are now diffable by one tool (scripts/obs_report.py).
    # run_id/process_index tag the report like every other artifact
    # (obs/runctx.py).
    from milnce_tpu.obs import export as obs_export
    from milnce_tpu.obs.runctx import auto_run_id

    report = obs_export.snapshot(service.registry, kind="serve_bench",
                                 extra=extra,
                                 run_id=auto_run_id("sbench-"),
                                 process_index=0)
    out = args.out or os.path.join(
        _REPO, f"SERVE_BENCH_{args.preset}_{args.mode}.json")
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(f"serve_bench: {report['requests']} requests in {elapsed:.2f}s "
          f"({report['qps']} QPS), p50={report['latency_ms']['p50']}ms "
          f"p99={report['latency_ms']['p99']}ms, cache hit rate "
          f"{report['cache']['hit_rate']:.2f}, "
          f"recompiles={report['engine']['recompiles']} -> {out}")
    return 0 if report["engine"]["recompiles"] in (0, -1) else 1


if __name__ == "__main__":
    raise SystemExit(main())
