"""Real-video train->eval loop on actual encoded bytes (VERDICT r3 #5).

The reference's end-to-end evidence is full HowTo100M training
(/root/reference/train.py:70-225 -> README.md:114-129); no video data
ships in this environment, so this drives the SAME production path —
cv2 decode of real mp4 containers -> HowTo100MSource MIL caption
windows -> sharded train step -> Orbax checkpoint -> the youcook eval
CLI — on a locally-encoded corpus whose video<->text correspondence is
learnable: each class is a colored moving square and every caption
contains the class's vocabulary word.

No FakeDecoder and no synthetic in-memory source anywhere: every
training clip is decoded from mp4 bytes by the production Cv2Decoder
(container seek, fps resample, crop, flip), captions go through the
real JSON track -> MIL candidate-window sampler, and the after-training
retrieval numbers come from the real `milnce_tpu.eval.cli` on held-out
videos.

    python scripts/real_train_eval.py --steps 300 --out REAL_TRAIN.md

Writes the corpus under --root (idempotent), trains, evals the
checkpoint before/after, and appends a markdown report to --out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# class -> (BGR color, class vocabulary word id offset); colors are far
# apart so mpeg4 quantization at 64x64 cannot blur them together
_COLORS = [(40, 40, 230), (40, 230, 40), (230, 40, 40), (40, 230, 230),
           (230, 40, 230), (230, 230, 40), (40, 140, 230), (230, 230, 230)]


def class_word(c: int) -> str:
    """The caption token that identifies class ``c`` (synthetic_vocab
    naming: 'word<i>'); ids 10.. keep clear of filler words."""
    return f"word{10 + c}"


def _write_video(path: str, cls: int, rng: np.random.RandomState,
                 seconds: float, fps: int, side: int) -> None:
    import cv2

    color = _COLORS[cls % len(_COLORS)]
    sq = side // 3
    x, y = rng.randint(0, side - sq, size=2)
    vx, vy = rng.choice([-2, -1, 1, 2], size=2)
    vw = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), float(fps),
                         (side, side))
    assert vw.isOpened(), path
    for _ in range(int(seconds * fps)):
        frame = rng.randint(0, 30, (side, side, 3)).astype(np.uint8)
        frame[y:y + sq, x:x + sq] = color
        vw.write(frame)
        x += vx
        y += vy
        if not 0 <= x <= side - sq:
            vx = -vx
            x = int(np.clip(x, 0, side - sq))
        if not 0 <= y <= side - sq:
            vy = -vy
            y = int(np.clip(y, 0, side - sq))
    vw.release()


def _caption_track(cls: int, rng: np.random.RandomState,
                   seconds: float) -> dict:
    """HowTo100M-style caption JSON: contiguous ~2.5 s segments, every
    text containing the class word plus random filler (the MIL bag then
    always carries the class signal, like narration does)."""
    starts, ends, texts = [], [], []
    t = 0.0
    while t < seconds - 2.5:
        dur = float(rng.uniform(2.0, 3.0))
        texts.append(f"{class_word(cls)} word{rng.randint(30, 40)} "
                     f"word{rng.randint(40, 50)}")
        starts.append(round(t, 2))
        ends.append(round(min(t + dur, seconds), 2))
        t += dur
    return {"start": starts, "end": ends, "text": texts}


def build_corpus(root: str, classes: int = 8, train_per_class: int = 12,
                 eval_per_class: int = 2, seconds: float = 20.0,
                 fps: int = 8, side: int = 64, seed: int = 0) -> dict:
    """Write the corpus (idempotent via a params marker). Layout:

    root/videos/<id>.mp4 + root/captions/<id>.json + root/train.csv
    root/eval_videos/validation/77/<id>.mp4 + root/eval.csv
    """
    import csv as csv_mod

    params = dict(classes=classes, train_per_class=train_per_class,
                  eval_per_class=eval_per_class, seconds=seconds, fps=fps,
                  side=side, seed=seed, version=1)
    marker = os.path.join(root, "corpus.json")
    out = {"root": root, "train_csv": os.path.join(root, "train.csv"),
           "caption_root": os.path.join(root, "captions"),
           "eval_csv": os.path.join(root, "eval.csv"),
           "eval_root": os.path.join(root, "eval_videos"),
           "n_train": classes * train_per_class,
           "n_eval": classes * eval_per_class}
    if os.path.exists(marker) and json.load(open(marker)) == params:
        return out
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.join(root, "videos"), exist_ok=True)
    os.makedirs(out["caption_root"], exist_ok=True)
    rows = []
    for c in range(classes):
        for j in range(train_per_class):
            vid = f"c{c}v{j}"
            _write_video(os.path.join(root, "videos", vid + ".mp4"), c, rng,
                         seconds, fps, side)
            with open(os.path.join(out["caption_root"], vid + ".json"),
                      "w") as f:
                json.dump(_caption_track(c, rng, seconds), f)
            rows.append(os.path.join("videos", vid + ".mp4"))
    with open(out["train_csv"], "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["video_path"])
        w.writerows([[r] for r in rows])

    eval_dir = os.path.join(out["eval_root"], "validation", "77")
    os.makedirs(eval_dir, exist_ok=True)
    with open(out["eval_csv"], "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["end", "start", "task", "text", "video_id"])
        for c in range(classes):
            for j in range(eval_per_class):
                vid = f"ev{c}x{j}"
                _write_video(os.path.join(eval_dir, vid + ".mp4"), c, rng,
                             seconds, fps, side)
                w.writerow([int(seconds) - 2, 2, "77",
                            f"{class_word(c)} word{30 + j}", vid])
    with open(marker, "w") as f:
        json.dump(params, f)
    return out


def build_probe_corpus(root: str, classes: int = 8, per_class: int = 6,
                       seconds: float = 8.0, fps: int = 8, side: int = 64,
                       seed: int = 7) -> dict:
    """HMDB-style labeled corpus for the linear probe (idempotent):
    root/probe_videos/<id>.mp4 + root/probe.csv with the hmdb51.csv
    schema (video_id,label,split1,split2,split3; 1=train 2=test,
    hmdb_loader.py:14-95).  Each split rotates which third of a class's
    videos is held out, so every video is a test sample in exactly one
    split — all three SVMs fit on real disjoint train/test partitions."""
    import csv as csv_mod

    params = dict(classes=classes, per_class=per_class, seconds=seconds,
                  fps=fps, side=side, seed=seed, version=1)
    marker = os.path.join(root, "probe_corpus.json")
    out = {"csv": os.path.join(root, "probe.csv"),
           "video_root": os.path.join(root, "probe_videos"),
           "classes": classes, "n_videos": classes * per_class}
    if os.path.exists(marker) and json.load(open(marker)) == params:
        return out
    rng = np.random.RandomState(seed)
    os.makedirs(out["video_root"], exist_ok=True)
    with open(out["csv"], "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["video_id", "label", "split1", "split2", "split3"])
        for c in range(classes):
            for j in range(per_class):
                vid = f"c{c}p{j}.mp4"
                _write_video(os.path.join(out["video_root"], vid), c, rng,
                             seconds, fps, side)
                splits = [2 if j % 3 == s else 1 for s in range(3)]
                w.writerow([vid, f"class{c}_test"] + splits)
    with open(marker, "w") as f:
        json.dump(params, f)
    return out


def probe_cli_args(probe: dict, ckpt_dir: str, cfg,
                   num_windows: int = 3) -> list[str]:
    return ["hmdb", "--ckpt", ckpt_dir, "--csv", probe["csv"],
            "--video_root", probe["video_root"], "--platform", "cpu",
            "--num_windows", str(num_windows), "--batch_size", "8",
            "--num_frames", str(cfg.data.num_frames),
            "--video_size", str(cfg.data.video_size),
            "--fps", str(cfg.data.fps),
            "--max_words", str(cfg.data.max_words),
            "--embedding_dim", str(cfg.model.embedding_dim),
            "--inception_blocks", str(cfg.model.inception_blocks),
            "--word_embedding_dim", str(cfg.model.word_embedding_dim),
            "--text_hidden_dim", str(cfg.model.text_hidden_dim),
            "--vocab_size", str(cfg.model.vocab_size)]


def train_config(corpus: dict, root: str, batch: int = 16):
    from milnce_tpu.config import tiny_preset

    cfg = tiny_preset()
    cfg.parallel.platform = "cpu"       # hermetic: never touch a TPU tunnel
    cfg.data.synthetic = False
    cfg.data.train_csv = corpus["train_csv"]
    cfg.data.video_root = corpus["root"]
    cfg.data.caption_root = corpus["caption_root"]
    cfg.data.decoder_backend = "cv2"    # the production in-process decoder
    cfg.data.num_frames = 4
    cfg.data.fps = 4
    cfg.data.video_size = 32
    cfg.data.crop_only = False          # largest-square crop + resize: the
                                        # whole 64px frame lands in the clip
    cfg.data.min_time = 1.0
    cfg.data.max_words = 6
    cfg.data.num_candidates = 3
    cfg.data.num_reader_threads = 8
    cfg.model.embedding_dim = 32
    cfg.model.inception_blocks = 2
    cfg.model.word_embedding_dim = 16
    cfg.model.text_hidden_dim = 32
    cfg.model.vocab_size = 64
    cfg.train.batch_size = batch
    cfg.train.n_display = 10
    cfg.train.checkpoint_keep = 3
    cfg.train.checkpoint_root = os.path.join(root, "ckpt")
    cfg.train.log_root = os.path.join(root, "log")
    cfg.optim.warmup_steps = 20
    cfg.optim.lr = 1e-3
    cfg.optim.epochs = 10_000           # bounded by max_steps
    return cfg


def eval_cli_args(corpus: dict, ckpt_dir: str, cfg) -> list[str]:
    return ["youcook", "--ckpt", ckpt_dir, "--csv", corpus["eval_csv"],
            "--video_root", corpus["eval_root"], "--platform", "cpu",
            "--num_windows", "2", "--batch_size", "8",
            "--num_frames", str(cfg.data.num_frames),
            "--video_size", str(cfg.data.video_size),
            "--fps", str(cfg.data.fps),
            "--max_words", str(cfg.data.max_words),
            "--embedding_dim", str(cfg.model.embedding_dim),
            "--inception_blocks", str(cfg.model.inception_blocks),
            "--word_embedding_dim", str(cfg.model.word_embedding_dim),
            "--text_hidden_dim", str(cfg.model.text_hidden_dim),
            "--vocab_size", str(cfg.model.vocab_size)]


def loss_trajectory(cfg) -> list[float]:
    """Parse 'Training loss: <x>' display lines from the run log
    (RunLogger names the file after the run's checkpoint_dir)."""
    path = os.path.join(cfg.train.log_root,
                        (cfg.train.checkpoint_dir or "run") + ".log")
    losses = []
    if os.path.exists(path):
        for line in open(path):
            if "Training loss:" in line:
                losses.append(float(
                    line.split("Training loss:")[1].split(",")[0]))
    return losses


def run(root: str, steps: int, classes: int = 8, train_per_class: int = 12,
        eval_per_class: int = 2, batch: int = 16, probe: bool = False,
        probe_per_class: int = 6, dtype: str = "float32") -> dict:
    """Build corpus, eval at init, train, eval after; returns the report
    dict.  Importable by tests (scaled down) and by __main__.

    ``probe=True`` additionally runs the HMDB-style linear probe
    (eval/linear_probe.py: mixed_5c features -> LinearSVC(C=100) per
    split -> window-summed top-1, matching eval_hmdb.py:60-104) on a
    separate labeled real-mp4 corpus, before and after training.
    ``dtype`` sets model.dtype — 'bfloat16' reproduces the bench
    operating point's numerics (VERDICT r4 #3)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from milnce_tpu.eval.cli import main as eval_main
    from milnce_tpu.train.loop import run_training

    corpus = build_corpus(root, classes=classes,
                          train_per_class=train_per_class,
                          eval_per_class=eval_per_class)
    cfg = train_config(corpus, root, batch=batch)
    cfg.model.dtype = dtype
    probe_corpus = (build_probe_corpus(root, classes=classes,
                                       per_class=probe_per_class)
                    if probe else None)

    # "before": one optimizer step in a throwaway run dir — the linear
    # warmup makes the step-0 LR exactly 0, so the checkpointed weights
    # ARE the random init, produced through the full production path.
    cfg.train.checkpoint_dir = "before"
    before_res = run_training(cfg, max_steps=1)
    before_dir = os.path.join(cfg.train.checkpoint_root, "before")
    before = eval_main(eval_cli_args(corpus, before_dir, cfg))
    probe_before = (eval_main(probe_cli_args(probe_corpus, before_dir, cfg))
                    if probe else None)

    cfg.train.checkpoint_dir = "trained"
    result = run_training(cfg, max_steps=steps)
    trained_dir = os.path.join(cfg.train.checkpoint_root, "trained")
    after = eval_main(eval_cli_args(corpus, trained_dir, cfg))
    probe_after = (eval_main(probe_cli_args(probe_corpus, trained_dir, cfg))
                   if probe else None)

    losses = loss_trajectory(cfg)
    return {"corpus": corpus, "steps": result.steps,
            "first_loss": losses[0] if losses else float(before_res.last_loss),
            "final_loss": float(result.last_loss), "losses": losses,
            "before": before, "after": after,
            "chance_r1": 1.0 / corpus["n_eval"], "dtype": dtype,
            "probe_before": probe_before, "probe_after": probe_after,
            "probe_chance": (1.0 / classes) if probe else None,
            "probe_corpus": probe_corpus}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/milnce_real_corpus")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--train_per_class", type=int, default=12)
    ap.add_argument("--eval_per_class", type=int, default=2)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--probe", action="store_true",
                    help="also run the HMDB-style linear probe on a "
                         "separate labeled real-mp4 corpus")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--out", default="")
    ap.add_argument("--json_out", default="",
                    help="also dump the raw report dict as JSON (tests)")
    args = ap.parse_args()
    rep = run(args.root, args.steps, classes=args.classes,
              train_per_class=args.train_per_class,
              eval_per_class=args.eval_per_class, batch=args.batch,
              probe=args.probe, dtype=args.dtype)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({k: v for k, v in rep.items()
                       if k not in ("corpus", "probe_corpus")}, f)
    b, a = rep["before"], rep["after"]
    lines = [
        f"# Real-video train->eval (cv2-decoded mp4 corpus, "
        f"dtype={rep['dtype']})", "",
        f"- corpus: {rep['corpus']['n_train']} train / "
        f"{rep['corpus']['n_eval']} eval videos (8 classes, 20 s mpeg4 "
        f"64x64; decoded by Cv2Decoder, no FakeDecoder anywhere)",
        f"- trained {rep['steps']} steps, batch {args.batch}, "
        f"K=3 MIL candidates",
        f"- loss: {rep['first_loss']:.4f} (first display window) -> "
        f"{rep['final_loss']:.4f} (final)",
        f"- loss trajectory (every 10 steps): "
        + ", ".join(f"{v:.3f}" for v in rep["losses"]),
        f"- youcook-CLI retrieval on held-out videos (chance R@1 = "
        f"{rep['chance_r1']:.3f}):",
        f"  - before (init ckpt): R@1 {b['R1']:.3f}, R@5 {b['R5']:.3f}, "
        f"R@10 {b['R10']:.3f}, MR {b['MR']:.1f}",
        f"  - after  (trained):   R@1 {a['R1']:.3f}, R@5 {a['R5']:.3f}, "
        f"R@10 {a['R10']:.3f}, MR {a['MR']:.1f}"]
    if rep["probe_after"] is not None:
        pb, pa = rep["probe_before"], rep["probe_after"]
        lines += [
            f"- HMDB-style linear probe on a separate labeled real-mp4 "
            f"corpus ({rep['probe_corpus']['n_videos']} videos, "
            f"{rep['probe_corpus']['classes']} classes; mixed_5c -> "
            f"LinearSVC(C=100) per split, window-summed top-1; chance = "
            f"{rep['probe_chance']:.3f}):",
            f"  - before (init ckpt): "
            + ", ".join(f"{k} {v:.3f}" for k, v in pb.items()),
            f"  - after  (trained):   "
            + ", ".join(f"{k} {v:.3f}" for k, v in pa.items())]
    lines.append("")
    report = "\n".join(lines)
    print(report)
    if args.out:
        with open(args.out, "a") as f:
            f.write(report + "\n")


if __name__ == "__main__":
    main()
