#!/bin/bash
# Wait for the TPU tunnel to heal, then run the whole measurement queue
# once: tpu_smoke.sh (bench sweep + train-loop cross-check), then the
# per-stage probe for both conv lowerings.
#
#   nohup bash scripts/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
#
# Probes are bounded subprocess executes (the bench.py _probe_backend
# recipe) spaced 10 min apart — a wedged relay has been observed to heal
# on the scale of hours.
set -uo pipefail
cd "$(dirname "$0")/.."

for i in $(seq 1 60); do
  if timeout 240 python -c "import jax, jax.numpy as jnp; print(float(jax.jit(lambda: jnp.ones(4).sum())()))" >/dev/null 2>&1; then
    echo "=== tunnel healthy (probe $i, $(date -u +%H:%M)) — running measurement queue ==="
    # Unique diagnostics FIRST: if the tunnel heals late in a round,
    # only the head of this queue completes — and the round driver
    # re-runs bench.py itself at round end, so the sweep goes last-ish.
    echo "=== stage probe (native) ==="
    python scripts/stage_probe.py --batch 64 --dtype bfloat16 --conv_impl native \
      && cp STAGE_PROBE.md STAGE_PROBE_native.md
    echo "=== XLA flag probe at the winning operating point ==="
    python scripts/xla_flag_probe.py --batch 128
    echo "=== bench sweep + train cross-check ==="
    bash scripts/tpu_smoke.sh
    echo "=== stage probe (fold2d) ==="
    python scripts/stage_probe.py --batch 64 --dtype bfloat16 --conv_impl fold2d \
      && cp STAGE_PROBE.md STAGE_PROBE_fold2d.md
    echo "=== soft-DTW kernel profile (reference presets; exercises the"
    echo "    new chunked HBM-streaming backward at the long presets) ==="
    python -m milnce_tpu.ops.softdtw_profile | tee SOFTDTW_PROFILE_r03.jsonl
    echo "=== measurement queue done ($(date -u +%H:%M)) ==="
    exit 0
  fi
  echo "probe $i failed ($(date -u +%H:%M)); sleeping 600s"
  sleep 600
done
echo "gave up after 60 probes (~10 h)"
exit 1
