#!/bin/bash
# Wait for the TPU tunnel to heal, then run the measurement queue once:
# per-stage probe, XLA flag probe, tpu_smoke.sh (bench sweep +
# train-loop cross-check), fold2d stage probe, soft-DTW preset profile.
#
#   nohup bash scripts/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
#
# Probes are bounded subprocess executes (the bench.py _probe_backend
# recipe) spaced 10 min apart — a wedged relay has been observed to heal
# on the scale of hours.
#
# MILNCE_WATCH_DEADLINE (epoch seconds, default now+6h) bounds BOTH the
# probing and the queue: near a round boundary the driver runs its own
# bench client, and a second concurrent tunnel client is a known wedge
# mode — better to stop clean than to contend.  After the deadline only
# the currently-running queue step finishes; remaining steps are skipped
# with a note.
set -uo pipefail
cd "$(dirname "$0")/.."

DEADLINE="${MILNCE_WATCH_DEADLINE:-$(( $(date +%s) + 6*3600 ))}"

past_deadline() { [ "$(date +%s)" -ge "$DEADLINE" ]; }

step() {  # step <name> <cmd...>
  local name="$1"; shift
  if past_deadline; then
    echo "=== SKIPPED $name: past deadline ($(date -u +%H:%M)) — leaving the tunnel to the round driver ==="
    return 0
  fi
  echo "=== $name ($(date -u +%H:%M)) ==="
  "$@"
}

for i in $(seq 1 60); do
  if past_deadline; then
    echo "deadline reached while probing ($(date -u +%H:%M)) — exiting clean"
    exit 0
  fi
  if timeout 240 python -c "import jax, jax.numpy as jnp; print(float(jax.jit(lambda: jnp.ones(4).sum())()))" >/dev/null 2>&1; then
    echo "=== tunnel healthy (probe $i, $(date -u +%H:%M)) — running measurement queue ==="
    # Unique diagnostics FIRST: if the tunnel heals late in a round,
    # only the head of this queue completes — and the round driver
    # re-runs bench.py itself at round end, so the sweep goes last-ish.
    step "stage probe (native, fwd)" bash -c \
      "python scripts/stage_probe.py --batch 64 --dtype bfloat16 --conv_impl native && cp STAGE_PROBE.md STAGE_PROBE_native.md"
    step "stage probe (native, fwd+bwd — the training cost)" bash -c \
      "python scripts/stage_probe.py --batch 64 --dtype bfloat16 --conv_impl native --mode fwdbwd && cp STAGE_PROBE.md STAGE_PROBE_native_fwdbwd.md"
    step "XLA flag probe at the winning operating point" \
      python scripts/xla_flag_probe.py --batch 128
    step "bench sweep + train cross-check" bash scripts/tpu_smoke.sh
    step "stage probe (fold2d)" bash -c \
      "python scripts/stage_probe.py --batch 64 --dtype bfloat16 --conv_impl fold2d && cp STAGE_PROBE.md STAGE_PROBE_fold2d.md"
    step "soft-DTW profile (reference presets; chunked bwd at the long ones)" bash -c \
      "python -m milnce_tpu.ops.softdtw_profile | tee SOFTDTW_PROFILE_r03.jsonl"
    echo "=== measurement queue done ($(date -u +%H:%M)) ==="
    exit 0
  fi
  echo "probe $i failed ($(date -u +%H:%M)); sleeping 600s"
  sleep 600
done
echo "gave up after 60 probes (~10 h)"
exit 1
