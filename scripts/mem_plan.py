#!/usr/bin/env python
"""Static HBM planner CLI (graftlint Pass 4 — analysis/memplan.py).

Usage:
    python scripts/mem_plan.py                   # plan entries, write MEMPLAN.md
    python scripts/mem_plan.py --check           # exit 1 on GL013/14/15 findings
    python scripts/mem_plan.py --what-if --batch 256 --mesh data=4,model=2 \
        --hbm-gib 16                             # operating-point prediction;
                                                 # exit 1 when it doesn't fit

The default mode walks every registered trace-invariant entry on the
hermetic CPU mesh and writes the per-entry peak table + top contributors
to MEMPLAN.md.  ``--check`` is the CI half: the same walk gated against
the pins in analysis/memplan.py (GL013 peak budget, GL014 donation
audit, GL015 top-contributor attribution), wired into
``graft_lint --check`` and the README verify recipe.

``--what-if`` answers "will this config fit?" WITHOUT a chip: the full
(or tiny) preset model is built at the requested batch/frames/mesh,
traced abstractly (``jax.eval_shape`` state + ShapeDtypeStruct inputs —
no device bytes move), and the predicted per-chip peak is compared
against ``--hbm-gib``.  A config that doesn't fit is REFUSED with a
nonzero exit naming the top-3 contributors — the 192-batch-cliff /
curriculum-ladder / FSDP-threshold triage loop, minus the chip time.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _parse_mesh(spec: str) -> dict:
    """'data=4,model=2' -> {'data': 4, 'model': 2} ('' -> {'data': 8},
    the hermetic default).  Malformed items fail here, not as a silently
    1-sized axis."""
    if not spec:
        return {"data": 8}
    out: dict = {}
    for item in spec.split(","):
        if "=" not in item:
            raise ValueError(f"mesh item {item!r}: expected axis=N "
                             "(e.g. data=4,model=2)")
        ax, n = item.split("=", 1)
        out[ax.strip()] = int(n)
    return out


def _force_devices(n: int) -> None:
    """Must run before any jax import: the what-if mesh needs that many
    virtual CPU devices in the hermetic platform."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


HEADER = ("<!-- (auto-written by scripts/mem_plan.py — do not hand-edit; "
          "regenerate with `python scripts/mem_plan.py`) -->\n")

# The committed curriculum operating-point ladder (PERF.md "Curriculum
# training"): the staged recipe recommended for the paper's full run,
# pre-flighted here so the 32f@224 final stage's fit is triaged before
# any chip time.  Regen recomputes every row, so the table tracks the
# current model + planner.  The ga=1 final-stage row is kept
# deliberately: it documents WHY the recipe carries grad_accum=8.
#   (label, frames, size, batch, grad_accum)
CURRICULUM_LADDER = (
    ("stage 0", 4, 64, 512, 1),
    ("stage 1", 8, 112, 256, 1),
    ("stage 2 (ga=1, naive)", 32, 224, 256, 1),
    ("stage 2 (ga=8)", 32, 224, 256, 8),
)
LADDER_MESH = {"data": 4, "model": 2}   # v5e-8 slice
LADDER_HBM_GIB = 16.0


def _plan_ladder(memplan) -> list:
    """(label, shape, batch, ga, peak_bytes, fits, top_label) per ladder
    row — the curriculum section of MEMPLAN.md."""
    rows = []
    for label, frames, size, batch, ga in CURRICULUM_LADDER:
        p = memplan.what_if_step(
            batch=batch, frames=frames, size=size, grad_accum=ga,
            mesh_axes=dict(LADDER_MESH))
        fits, _ = memplan.budget_verdict(p, LADDER_HBM_GIB)
        top = (f"{p.contributors[0][0]} "
               f"({p.contributors[0][1] / 2**20:.0f} MiB)"
               if p.contributors else "-")
        rows.append((label, f"{frames}f@{size}", batch, ga,
                     p.peak_bytes, fits, top))
    return rows


def _render_memplan(plans: dict, results, ladder=None) -> str:
    lines = [HEADER, "# MEMPLAN — static per-chip HBM plan", ""]
    lines.append(
        "Per-entry peak device bytes from jaxpr live-range analysis "
        "(graftlint Pass 4, `milnce_tpu/analysis/memplan.py`) on the "
        "hermetic CPU meshes — sharding-aware (bytes / mesh-axis extent "
        "per the committed specs) and donation-aware (the TPU path's "
        "`donate_argnums` applied).  Pinned by `graft_lint --check` "
        "(GL013/GL015); model + known approximations: ANALYSIS.md "
        "\"Pass 4\".")
    lines.append("")
    lines.append("| entry | mesh | peak/chip | args/chip | outs/chip "
                 "| top contributors |")
    lines.append("|---|---|---|---|---|---|")
    for name, p in plans.items():
        top = "<br>".join(f"{label} ({b / 2**20:.2f} MiB)"
                          for label, b in p.contributors[:3])
        lines.append(
            f"| {name} | {p.mesh} | {p.peak_bytes / 2**20:.2f} MiB "
            f"| {p.arg_bytes / 2**20:.2f} MiB "
            f"| {p.out_bytes / 2**20:.2f} MiB | {top} |")
    lines.append("")
    lines.append("## Sharding attribution")
    lines.append("")
    lines.append("Donated arg leaves per entry (the GL014 audit surface; "
                 "donation is gated OFF on CPU by parallel/compat.py but "
                 "must stay requested for TPU):")
    lines.append("")
    for name, p in plans.items():
        n_don = len(p.donated)
        lines.append(f"- `{name}`: {n_don} donated leaves"
                     + (" (none — inference entry)" if not n_don else
                        f" (state tree; first: `{p.donated[0]}`)"))
    lines.append("")
    lines.append("## Pass 4 checks")
    lines.append("")
    bad = [r for r in results if not r.ok]
    lines.append(f"- checks: {len(results)}, failing: **{len(bad)}**")
    lines.append("")
    lines.append("| entry | check | status |")
    lines.append("|---|---|---|")
    for r in results:
        status = "ok" if r.ok else f"**FAIL** — {r.detail}"
        lines.append(f"| {r.entry} | {r.check} | {status} |")
    lines.append("")
    lines.append("What-if mode (`python scripts/mem_plan.py --what-if "
                 "--batch 256 --mesh data=4,model=2 --hbm-gib 16`) "
                 "predicts TPU operating-point footprints from CPU "
                 "traces and refuses configs that don't fit — see "
                 "PERF.md \"Memory planning\".")
    lines.append("")
    if ladder:
        mesh = "x".join(str(n) for n in LADDER_MESH.values())
        axes = ",".join(LADDER_MESH)
        lines.append("## Curriculum ladder (operating points)")
        lines.append("")
        lines.append(
            f"The staged recipe from PERF.md \"Curriculum training\", "
            f"pre-flighted on {mesh} ({axes}) against the v5e "
            f"{LADDER_HBM_GIB:.0f} GiB/chip budget — the same per-stage "
            "prediction `run_training` performs at startup before any "
            "stage is traced.  One invocation reproduces it: "
            "`python scripts/mem_plan.py --what-if --curriculum "
            "'<spec>' --mesh data=4,model=2 --hbm-gib 16`.  The naive "
            "ga=1 final stage is listed to show the triage: 32f@224 at "
            "batch 256 only fits with gradient accumulation.")
        lines.append("")
        lines.append("| stage | shape | batch | grad-accum | peak/chip "
                     "| fits 16 GiB | top contributor |")
        lines.append("|---|---|---|---|---|---|---|")
        for label, shape, batch, ga, peak, fits, top in ladder:
            verdict = "yes" if fits else "**NO — refused at pre-flight**"
            lines.append(f"| {label} | {shape} | {batch} | {ga} "
                         f"| {peak / 2**30:.3f} GiB | {verdict} "
                         f"| {top} |")
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any GL013/GL014/GL015 finding")
    ap.add_argument("--entries", default="",
                    help="comma list of entries (default: all registered)")
    ap.add_argument("--report", default=os.path.join(_REPO, "MEMPLAN.md"),
                    help="report path ('' to skip writing)")
    ap.add_argument("--what-if", action="store_true",
                    help="predict one operating point instead of "
                         "planning the registered entries")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--frames", type=int, default=32)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--words", type=int, default=20)
    ap.add_argument("--k", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--loss-impl", default="dense",
                    choices=["dense", "chunked", "auto"],
                    help="MIL-NCE impl for --what-if (loss.milnce_impl): "
                         "predict the same operating point under the "
                         "dense cube vs the chunked stream")
    ap.add_argument("--milnce-chunk", type=int, default=0,
                    help="chunked-impl streamed block size (0 = the "
                         "milnce_default_chunk rule)")
    ap.add_argument("--curriculum", default="",
                    help="with --what-if: a train.curriculum spec (or "
                         "JSON artifact path) — predict EVERY stage as "
                         "its own operating point in one invocation and "
                         "exit 1 if any stage exceeds --hbm-gib; "
                         "--grad-accum/--words/--k/--dtype apply to all "
                         "stages")
    ap.add_argument("--mesh", default="",
                    help="'data=4,model=2' (what-if; '' = 8-way data)")
    ap.add_argument("--hbm-gib", type=float, default=16.0,
                    help="per-chip HBM budget the what-if verdict gates "
                         "against (v5e 16, v3 32, v5p 95)")
    ap.add_argument("--preset", default="full", choices=["full", "tiny"],
                    help="model preset for --what-if (tiny = the test "
                         "config, seconds to trace)")
    args = ap.parse_args(argv)

    mesh_axes = _parse_mesh(args.mesh)
    import math

    _force_devices(math.prod(mesh_axes.values()) if args.what_if else 8)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from milnce_tpu.analysis import memplan

    if args.what_if and args.curriculum:
        # stdlib parser (train/curriculum.py imports no jax at module
        # scope beyond what this process already initialised)
        from milnce_tpu.train.curriculum import parse_curriculum

        stages = parse_curriculum(args.curriculum,
                                  default_batch_size=args.batch)
        rows, refused = [], []
        for i, st in enumerate(stages):
            plan = memplan.what_if_step(
                batch=st.batch_size, frames=st.num_frames,
                size=st.resolution, words=args.words, k=args.k,
                dtype=args.dtype, grad_accum=args.grad_accum,
                mesh_axes=mesh_axes, preset=args.preset,
                loss_impl=args.loss_impl,
                milnce_chunk=args.milnce_chunk)
            fits, msg = memplan.budget_verdict(plan, args.hbm_gib)
            rows.append((i, st, plan, fits))
            if not fits:
                refused.append((i, st, msg))
        print("| stage | shape | batch | peak/chip | fits "
              f"{args.hbm_gib:g} GiB |")
        print("|---|---|---|---|---|")
        for i, st, plan, fits in rows:
            print(f"| {i} | {st.num_frames}f@{st.resolution} "
                  f"| {st.batch_size} | {plan.peak_bytes / 2**30:.3f} "
                  f"GiB | {'yes' if fits else '**NO**'} |")
        for i, st, msg in refused:
            print(f"\nstage {i} ({st.label()}) REFUSED: {msg}")
        return 1 if refused else 0

    if args.what_if:
        plan = memplan.what_if_step(
            batch=args.batch, frames=args.frames, size=args.size,
            words=args.words, k=args.k, dtype=args.dtype,
            grad_accum=args.grad_accum, mesh_axes=mesh_axes,
            preset=args.preset, loss_impl=args.loss_impl,
            milnce_chunk=args.milnce_chunk)
        fits, msg = memplan.budget_verdict(plan, args.hbm_gib)
        print(msg)
        return 0 if fits else 1

    entries = ([e for e in args.entries.split(",") if e]
               or None)
    plans = memplan.plan_all(entries)
    results = memplan.run_memplan_checks(entries, plans=plans)
    for r in results:
        print(r.format())
    n_bad = sum(not r.ok for r in results)
    if n_bad:
        # BOTH re-pin dicts, ready to paste — a DELIBERATE change (GL013
        # peak drift or GL015 contributor drift) should cost one copy,
        # not archaeology
        print("\n# current values (re-pin consciously if intended):")
        print("EXPECTED_PEAK_BYTES = {")
        for name, p in plans.items():
            print(f'    "{name}": {p.peak_bytes},')
        print("}")
        print("EXPECTED_TOP_CONTRIBUTORS = {")
        for name, p in plans.items():
            tops = ",\n        ".join(f'"{label}"' for label in p.top())
            print(f'    "{name}": (\n        {tops}),')
        print("}")
    if args.report:
        # recompute the committed curriculum ladder alongside the entry
        # plans (~9s/row of pure CPU tracing) so the operating-point
        # table can never go stale against the model
        ladder = _plan_ladder(memplan)
        with open(args.report, "w") as fh:
            fh.write(_render_memplan(plans, results, ladder=ladder))
        print(f"report: {args.report}")
    print(f"mem_plan: {len(plans)} entries planned, {n_bad} finding(s)")
    return 1 if (args.check and n_bad) else 0


if __name__ == "__main__":
    raise SystemExit(main())
