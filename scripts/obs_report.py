#!/usr/bin/env python
"""Observability report + regression gate over the unified artifacts.

One tool reads everything the obs subsystem emits (OBSERVABILITY.md):

- ``RUN_EVENTS.jsonl`` span/event streams (train runs, obs/spans.py);
- ``milnce.obs/v1`` snapshot documents — serve_bench reports
  (``SERVE_BENCH_*.json``), raw registry snapshots, train bench records
  (the ``schema``/``kind`` keys discriminate producers).

Usage::

    python scripts/obs_report.py RUN_EVENTS.jsonl            # summarize
    python scripts/obs_report.py SERVE_BENCH_tiny_closed.json
    python scripts/obs_report.py --check CURRENT --baseline BASELINE \
        [--tolerance 0.10]                                   # CI gate

The gate compares the artifacts' *gate metrics* (step-time p50/p99 from
a span stream; latency p50/p99 + QPS from a serve_bench report;
clips/sec from a train bench record) against a committed baseline and
exits nonzero when any drifts more than ``--tolerance`` (default 10%)
in the bad direction — wired next to ``graft_lint.py --check`` in the
README verify recipe.  Drift in the *good* direction never fails: the
gate is a regression fence, not a pin.

stdlib-only, no jax import: the gate must cost milliseconds in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from milnce_tpu.obs.export import SNAPSHOT_SCHEMA  # noqa: E402  (jax-free)

# gate metric name -> direction ("lower" = lower is better)
GATE_DIRECTIONS = {
    "step_ms_p50": "lower",
    "step_ms_p99": "lower",
    "latency_ms_p50": "lower",
    "latency_ms_p99": "lower",
    "qps": "higher",
    "clips_per_sec_per_chip": "higher",
    # static HBM plan of the benched program (graftlint Pass 4,
    # ISSUE 8): a row that got faster by inflating its footprint is a
    # regression; cross-layout compares stay attributable via the
    # mesh/sharding_map_hash note
    "predicted_peak_bytes_per_chip": "lower",
}


def _percentile(sorted_vals: list, q: float) -> float:
    """Linear-interpolated percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(sorted_vals) - 1)
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_artifact(path: str) -> dict:
    """-> ``{"format": "events", "records": [...]}`` for a JSONL stream,
    or ``{"format": "snapshot", "doc": {...}}`` for a schema'd JSON
    document.  Unversioned JSON is an error, not a guess — the whole
    point of the shared schema is that this tool never sniffs."""
    with open(path) as fh:
        head = fh.read(1)
        fh.seek(0)
        if not head:
            raise ValueError(f"{path}: empty artifact")
        if path.endswith(".jsonl"):
            records = [json.loads(line) for line in fh if line.strip()]
            return {"format": "events", "records": records, "path": path}
        doc = json.load(fh)
    schema = doc.get("schema")
    if schema != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"{path}: schema {schema!r} is not {SNAPSHOT_SCHEMA!r} — "
            "regenerate the artifact with the current tools "
            "(OBSERVABILITY.md 'Snapshot schema')")
    return {"format": "snapshot", "doc": doc, "path": path}


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

def summarize_events(records: list) -> dict:
    """Per-name span duration stats + event counts."""
    spans: dict[str, list] = {}
    span_errors: dict[str, int] = {}
    events: dict[str, int] = {}
    for rec in records:
        name = rec.get("name", "?")
        if rec.get("kind") == "span":
            spans.setdefault(name, []).append(float(rec.get("dur_ms", 0.0)))
            if "error" in rec:
                span_errors[name] = span_errors.get(name, 0) + 1
        elif rec.get("kind") == "event":
            events[name] = events.get(name, 0) + 1
    span_stats = {}
    for name, durs in spans.items():
        durs = sorted(durs)
        span_stats[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "mean_ms": round(sum(durs) / len(durs), 4),
            "p50_ms": round(_percentile(durs, 50), 4),
            "p99_ms": round(_percentile(durs, 99), 4),
            "errors": span_errors.get(name, 0),
        }
    return {"spans": span_stats, "events": events}


def gate_metrics(artifact: dict) -> dict[str, float]:
    """The comparable numbers an artifact contributes to the gate."""
    out: dict[str, float] = {}
    if artifact["format"] == "events":
        stats = summarize_events(artifact["records"])["spans"].get("step")
        if stats:
            out["step_ms_p50"] = stats["p50_ms"]
            out["step_ms_p99"] = stats["p99_ms"]
        return out
    doc = artifact["doc"]
    lat = doc.get("latency_ms") or {}
    for src, dst in (("p50", "latency_ms_p50"), ("p99", "latency_ms_p99")):
        v = lat.get(src)
        if isinstance(v, (int, float)):
            out[dst] = float(v)
    for key in ("qps", "clips_per_sec_per_chip",
                "predicted_peak_bytes_per_chip"):
        v = doc.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    if "value" in doc and doc.get("unit") == "clips/sec/chip":
        out["clips_per_sec_per_chip"] = float(doc["value"])
    return out


def render_summary(artifact: dict) -> str:
    lines = [f"artifact: {artifact['path']} ({artifact['format']})"]
    if artifact["format"] == "events":
        s = summarize_events(artifact["records"])
        lines.append(f"  records: {len(artifact['records'])}")
        if s["spans"]:
            lines.append("  spans (name count mean/p50/p99 ms errors):")
            for name in sorted(s["spans"]):
                st = s["spans"][name]
                lines.append(
                    f"    {name:<16} {st['count']:>6}  "
                    f"{st['mean_ms']:>10.3f} {st['p50_ms']:>10.3f} "
                    f"{st['p99_ms']:>10.3f}  {st['errors']}")
        if s["events"]:
            lines.append("  events: " + ", ".join(
                f"{k}={v}" for k, v in sorted(s["events"].items())))
    else:
        doc = artifact["doc"]
        lines.append(f"  kind: {doc.get('kind')}  schema: {doc['schema']}")
        for k, v in sorted(gate_metrics(artifact).items()):
            lines.append(f"  {k}: {v}")
        metrics = doc.get("metrics") or {}
        if metrics:
            lines.append(f"  registry families: {len(metrics)}")
            for name in sorted(metrics):
                fam = metrics[name]
                if fam["type"] == "histogram":
                    tot = sum(v.get("count", 0) for v in fam["values"])
                    lines.append(f"    {name} (histogram): {tot} samples")
                else:
                    vals = ", ".join(
                        (("{" + ",".join(f"{lk}={lv}" for lk, lv in
                                         v["labels"].items()) + "}")
                         if v["labels"] else "") + f"{v['value']:g}"
                        for v in fam["values"][:6])
                    lines.append(f"    {name} ({fam['type']}): {vals}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def check(current: dict, baseline: dict, tolerance: float) -> tuple[bool,
                                                                    str]:
    """-> (ok, report).  Fails on any shared gate metric drifting more
    than ``tolerance`` in its bad direction; errors (ok=False) when the
    artifacts share no gate metrics at all — a gate that silently
    compares nothing is worse than no gate."""
    cur, base = gate_metrics(current), gate_metrics(baseline)
    shared = sorted(set(cur) & set(base))
    if not shared:
        return False, (
            f"no shared gate metrics between {current['path']} "
            f"({sorted(cur) or 'none'}) and baseline {baseline['path']} "
            f"({sorted(base) or 'none'}) — artifacts are not comparable")
    lines = [f"gate: {current['path']} vs baseline {baseline['path']} "
             f"(tolerance {tolerance:.0%})"]
    # mesh layout / sharding-map identity (ISSUE 6): 1-D vs 2-D runs ARE
    # comparable (that comparison is the point of the fields), but a
    # drift across layouts must be ATTRIBUTABLE — say so in the report
    # instead of letting a layout change read as a plain regression
    cur_doc, base_doc = current.get("doc") or {}, baseline.get("doc") or {}
    for key in ("mesh", "sharding_map_hash"):
        b, c = base_doc.get(key), cur_doc.get(key)
        if (b or c) and b != c:
            lines.append(f"  [note] {key} differs: baseline {b or '-'} "
                         f"-> current {c or '-'} (cross-layout compare)")
    ok = True
    compared = 0
    for name in shared:
        b, c = base[name], cur[name]
        if b == 0:
            lines.append(f"  [skip] {name}: baseline is 0")
            continue
        compared += 1
        drift = (c - b) / b
        bad = (drift > tolerance if GATE_DIRECTIONS[name] == "lower"
               else drift < -tolerance)
        ok = ok and not bad
        lines.append(f"  [{'FAIL' if bad else 'ok'}] {name}: "
                     f"{b:g} -> {c:g} ({drift:+.1%}, "
                     f"{GATE_DIRECTIONS[name]} is better)")
    if compared == 0:
        # every shared metric got skipped (all-zero baseline, e.g. a
        # bench error-path record committed by mistake) — a gate that
        # compared nothing must not pass
        lines.append("  FAIL: every shared gate metric has a zero "
                     "baseline — nothing was compared; fix the baseline "
                     "artifact")
        return False, "\n".join(lines)
    return ok, "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="observability summarizer + regression gate "
                    "(scripts/obs_report.py)")
    ap.add_argument("artifact",
                    help="RUN_EVENTS.jsonl or a milnce.obs/v1 JSON doc")
    ap.add_argument("--check", action="store_true",
                    help="gate the artifact against --baseline; exit 1 "
                         "on regression")
    ap.add_argument("--baseline", default="",
                    help="committed baseline artifact to gate against")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed bad-direction drift fraction "
                         "(default 0.10)")
    args = ap.parse_args(argv)

    try:
        current = load_artifact(args.artifact)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"obs_report: cannot read {args.artifact}: {exc}",
              file=sys.stderr)
        return 2

    if not args.check:
        print(render_summary(current))
        return 0

    if not args.baseline:
        print("obs_report: --check requires --baseline", file=sys.stderr)
        return 2
    try:
        baseline = load_artifact(args.baseline)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"obs_report: cannot read baseline {args.baseline}: {exc}",
              file=sys.stderr)
        return 2
    ok, report = check(current, baseline, args.tolerance)
    print(report)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
