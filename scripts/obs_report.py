#!/usr/bin/env python
"""Observability report + regression gate over the unified artifacts.

One tool reads everything the obs subsystem emits (OBSERVABILITY.md):

- ``RUN_EVENTS.jsonl`` span/event streams (train runs, obs/spans.py);
- ``milnce.obs/v1`` snapshot documents — serve_bench reports
  (``SERVE_BENCH_*.json``), raw registry snapshots, train bench records
  (the ``schema``/``kind`` keys discriminate producers).

Usage::

    python scripts/obs_report.py RUN_EVENTS.jsonl            # summarize
    python scripts/obs_report.py SERVE_BENCH_tiny_closed.json
    python scripts/obs_report.py --check CURRENT --baseline BASELINE \
        [--tolerance 0.10]                                   # CI gate
    python scripts/obs_report.py --check CURRENT --baseline latest
    python scripts/obs_report.py --merge SNAP0 SNAP1 [...] \
        [--out POD.json]                                     # pod view

The gate compares the artifacts' *gate metrics* (step-time p50/p99 from
a span stream; latency p50/p99 + QPS from a serve_bench report;
clips/sec, MFU + predicted peak bytes from a train bench record;
``goodput_fraction`` + ``mfu`` from a goodput ledger) against a
committed baseline and exits nonzero when any drifts more than
``--tolerance`` (default 10%) in the bad direction — wired next to
``graft_lint.py --check`` in the README verify recipe.  Drift in the
*good* direction never fails: the gate is a regression fence, not a
pin.  ``--baseline latest`` auto-picks the newest same-kind artifact
in the current artifact's directory.

Run identity (obs/runctx.py): event streams holding records from more
than one ``run_id`` are a LOUD error (the documented cross-run append
ambiguity) — pass ``--run-id`` to select one.  ``--merge`` fuses >= 2
per-process snapshots (or event streams) of ONE run into a pod view:
counters summed, gauges min/median/max across hosts, straggler
detection as cross-host step-span skew; the merged snapshot gates with
``--check`` exactly like a single-process artifact (obs/aggregate.py).

stdlib-only, no jax import: the gate must cost milliseconds in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from milnce_tpu.obs import aggregate  # noqa: E402  (jax-free)
from milnce_tpu.obs.export import SNAPSHOT_SCHEMA  # noqa: E402  (jax-free)
from milnce_tpu.obs.goodput import select_run, split_runs  # noqa: E402

# gate metric name -> direction ("lower" = lower is better)
GATE_DIRECTIONS = {
    "step_ms_p50": "lower",
    "step_ms_p99": "lower",
    "latency_ms_p50": "lower",
    "latency_ms_p99": "lower",
    "qps": "higher",
    "clips_per_sec_per_chip": "higher",
    # static HBM plan of the benched program (graftlint Pass 4,
    # ISSUE 8): a row that got faster by inflating its footprint is a
    # regression; cross-layout compares stay attributable via the
    # mesh/sharding_map_hash note
    "predicted_peak_bytes_per_chip": "lower",
    # attribution tier (ISSUE 9): live MFU + kept-compute fraction are
    # first-class gate metrics — a run that kept its clips/s by hiding
    # badput (skips, data waits) fails here
    "mfu": "higher",
    "goodput_fraction": "higher",
    # serving resilience tier (ISSUE 10): the UNSTRUCTURED failure
    # fraction of a serve_bench run (structured refusals — 429/503/504 —
    # are counted separately and do NOT gate here); chaos benches pin
    # error-rate drift with this
    "error_rate": "lower",
    # edge tier (ISSUE 19): retrieval quality of a serve_bench
    # ``--tier-class`` record, measured as top-10 overlap against the
    # f32 class's rankings on the same query pool.  Gating an edge-class
    # (int8 / distilled-student) record against the committed f32
    # baseline pins the quality floor; the dtype_census_hash note below
    # marks the compare as cross-precision so latency drift stays
    # attributable to the precision change
    "recall_at_10": "higher",
}


def gate_direction(name: str) -> str:
    """Direction for a gate metric name.  Per-tier metrics (ISSUE 14 —
    serve_bench ``--tiers``) are ``<base>@<tier>`` and inherit the base
    metric's direction, so ``latency_ms_p99@interactive`` gates exactly
    like the aggregate p99."""
    return GATE_DIRECTIONS[name.partition("@")[0]]


def _percentile(sorted_vals: list, q: float) -> float:
    """Linear-interpolated percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(sorted_vals) - 1)
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_artifact(path: str, run_id: str | None = None) -> dict:
    """-> ``{"format": "events", "records": [...]}`` for a JSONL stream,
    or ``{"format": "snapshot", "doc": {...}}`` for a schema'd JSON
    document.  Unversioned JSON is an error, not a guess — the whole
    point of the shared schema is that this tool never sniffs.

    Event streams are split on ``run_id``: a stream holding more than
    one run (the append-only cross-run case OBSERVABILITY.md documents)
    is an error unless ``run_id`` picks one — mixed-run percentiles are
    confidently wrong, which is worse than failing."""
    with open(path) as fh:
        head = fh.read(1)
        fh.seek(0)
        if not head:
            raise ValueError(f"{path}: empty artifact")
        if path.endswith(".jsonl"):
            records = [json.loads(line) for line in fh if line.strip()]
            records = select_run(records, run_id)
            return {"format": "events", "records": records, "path": path}
        doc = json.load(fh)
    schema = doc.get("schema")
    if schema != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"{path}: schema {schema!r} is not {SNAPSHOT_SCHEMA!r} — "
            "regenerate the artifact with the current tools "
            "(OBSERVABILITY.md 'Snapshot schema')")
    return {"format": "snapshot", "doc": doc, "path": path}


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

def summarize_events(records: list) -> dict:
    """Per-name span duration stats + event counts."""
    spans: dict[str, list] = {}
    span_errors: dict[str, int] = {}
    events: dict[str, int] = {}
    for rec in records:
        name = rec.get("name", "?")
        if rec.get("kind") == "span":
            spans.setdefault(name, []).append(float(rec.get("dur_ms", 0.0)))
            if "error" in rec:
                span_errors[name] = span_errors.get(name, 0) + 1
        elif rec.get("kind") == "event":
            events[name] = events.get(name, 0) + 1
    span_stats = {}
    for name, durs in spans.items():
        durs = sorted(durs)
        span_stats[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "mean_ms": round(sum(durs) / len(durs), 4),
            "p50_ms": round(_percentile(durs, 50), 4),
            "p99_ms": round(_percentile(durs, 99), 4),
            "errors": span_errors.get(name, 0),
        }
    return {"spans": span_stats, "events": events}


def gate_metrics(artifact: dict) -> dict[str, float]:
    """The comparable numbers an artifact contributes to the gate."""
    out: dict[str, float] = {}
    if artifact["format"] == "events":
        stats = summarize_events(artifact["records"])["spans"].get("step")
        if stats:
            out["step_ms_p50"] = stats["p50_ms"]
            out["step_ms_p99"] = stats["p99_ms"]
        return out
    doc = artifact["doc"]
    lat = doc.get("latency_ms") or {}
    for src, dst in (("p50", "latency_ms_p50"), ("p99", "latency_ms_p99")):
        v = lat.get(src)
        if isinstance(v, (int, float)):
            out[dst] = float(v)
    for key in ("qps", "clips_per_sec_per_chip",
                "predicted_peak_bytes_per_chip", "mfu",
                "goodput_fraction", "error_rate", "recall_at_10"):
        v = doc.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    # per-tier serve_bench block (ISSUE 14): each SLO tier contributes
    # its own p50/p99/qps/error_rate as <base>@<tier> gate metrics, so
    # a chaos run pins "interactive p99 inside its SLO" directly
    tiers = doc.get("tiers")
    if isinstance(tiers, dict):
        for tier, td in sorted(tiers.items()):
            if not isinstance(td, dict):
                continue
            lat = td.get("latency_ms") or {}
            for src in ("p50", "p99"):
                v = lat.get(src)
                if isinstance(v, (int, float)):
                    out[f"latency_ms_{src}@{tier}"] = float(v)
            for key in ("qps", "error_rate"):
                v = td.get(key)
                if isinstance(v, (int, float)):
                    out[f"{key}@{tier}"] = float(v)
    if "value" in doc and doc.get("unit") == "clips/sec/chip":
        out["clips_per_sec_per_chip"] = float(doc["value"])
    return out


def render_summary(artifact: dict) -> str:
    lines = [f"artifact: {artifact['path']} ({artifact['format']})"]
    if artifact["format"] == "events":
        s = summarize_events(artifact["records"])
        lines.append(f"  records: {len(artifact['records'])}")
        if s["spans"]:
            lines.append("  spans (name count mean/p50/p99 ms errors):")
            for name in sorted(s["spans"]):
                st = s["spans"][name]
                lines.append(
                    f"    {name:<16} {st['count']:>6}  "
                    f"{st['mean_ms']:>10.3f} {st['p50_ms']:>10.3f} "
                    f"{st['p99_ms']:>10.3f}  {st['errors']}")
        if s["events"]:
            lines.append("  events: " + ", ".join(
                f"{k}={v}" for k, v in sorted(s["events"].items())))
    else:
        doc = artifact["doc"]
        lines.append(f"  kind: {doc.get('kind')}  schema: {doc['schema']}")
        if doc.get("run_id") is not None:
            pi = doc.get("process_index")
            pod = doc.get("processes")
            lines.append(
                f"  run: {doc['run_id']}"
                + (f"  process: {pi}" if pi is not None else "")
                + (f"  processes merged: {pod}" if pod is not None else ""))
        for k, v in sorted(gate_metrics(artifact).items()):
            lines.append(f"  {k}: {v}")
        cats = doc.get("categories_s")
        if isinstance(cats, dict):      # goodput ledger attribution
            wall = float(doc.get("wall_s", 0.0)) or None
            lines.append("  wall-time attribution:")
            for name, sec in sorted(cats.items(), key=lambda kv: -kv[1]):
                frac = f" ({sec / wall:.1%})" if wall else ""
                lines.append(f"    {name:<14} {sec:>10.3f}s{frac}")
        spread = doc.get("spread")
        if isinstance(spread, dict):    # pod merge: per-host extremes
            lines.append("  cross-host spread (min/median/max):")
            for name in sorted(spread):
                s = spread[name]
                lines.append(f"    {name}: {s['min']:g} / "
                             f"{s['median']:g} / {s['max']:g}")
        metrics = doc.get("metrics") or {}
        if metrics:
            lines.append(f"  registry families: {len(metrics)}")
            for name in sorted(metrics):
                fam = metrics[name]
                if fam["type"] == "histogram":
                    tot = sum(v.get("count", 0) for v in fam["values"])
                    lines.append(f"    {name} (histogram): {tot} samples")
                else:
                    vals = ", ".join(
                        (("{" + ",".join(f"{lk}={lv}" for lk, lv in
                                         v["labels"].items()) + "}")
                         if v["labels"] else "") + f"{v['value']:g}"
                        for v in fam["values"][:6])
                    lines.append(f"    {name} ({fam['type']}): {vals}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def check(current: dict, baseline: dict, tolerance: float) -> tuple[bool,
                                                                    str]:
    """-> (ok, report).  Fails on any shared gate metric drifting more
    than ``tolerance`` in its bad direction; errors (ok=False) when the
    artifacts share no gate metrics at all — a gate that silently
    compares nothing is worse than no gate."""
    cur, base = gate_metrics(current), gate_metrics(baseline)
    shared = sorted(set(cur) & set(base))
    if not shared:
        return False, (
            f"no shared gate metrics between {current['path']} "
            f"({sorted(cur) or 'none'}) and baseline {baseline['path']} "
            f"({sorted(base) or 'none'}) — artifacts are not comparable")
    lines = [f"gate: {current['path']} vs baseline {baseline['path']} "
             f"(tolerance {tolerance:.0%})"]
    # mesh layout / sharding-map identity (ISSUE 6): 1-D vs 2-D runs ARE
    # comparable (that comparison is the point of the fields), but a
    # drift across layouts must be ATTRIBUTABLE — say so in the report
    # instead of letting a layout change read as a plain regression
    cur_doc, base_doc = current.get("doc") or {}, baseline.get("doc") or {}
    # dtype_census_hash: a differing precision fingerprint (Pass 5)
    # means the two rows ran different-precision programs — the drift
    # below is attributable to the dtype change, not the code under test
    for key in ("mesh", "sharding_map_hash", "dtype_census_hash"):
        b, c = base_doc.get(key), cur_doc.get(key)
        if (b or c) and b != c:
            kind = ("cross-precision" if key == "dtype_census_hash"
                    else "cross-layout")
            lines.append(f"  [note] {key} differs: baseline {b or '-'} "
                         f"-> current {c or '-'} ({kind} compare)")
    ok = True
    compared = 0
    for name in shared:
        b, c = base[name], cur[name]
        if b == 0:
            lines.append(f"  [skip] {name}: baseline is 0")
            continue
        compared += 1
        drift = (c - b) / b
        bad = (drift > tolerance if gate_direction(name) == "lower"
               else drift < -tolerance)
        ok = ok and not bad
        lines.append(f"  [{'FAIL' if bad else 'ok'}] {name}: "
                     f"{b:g} -> {c:g} ({drift:+.1%}, "
                     f"{gate_direction(name)} is better)")
    if compared == 0:
        # every shared metric got skipped (all-zero baseline, e.g. a
        # bench error-path record committed by mistake) — a gate that
        # compared nothing must not pass
        lines.append("  FAIL: every shared gate metric has a zero "
                     "baseline — nothing was compared; fix the baseline "
                     "artifact")
        return False, "\n".join(lines)
    return ok, "\n".join(lines)


def resolve_latest_baseline(current: dict) -> str:
    """``--baseline latest``: the newest artifact of the SAME kind in
    the current artifact's directory (event streams match event
    streams; snapshots match on their ``kind``).  Kind mismatches are
    not silently compared — if nothing matches, the error names what
    WAS found so the refusal is as loud as the incomparable-pair one."""
    # a merged view has a placeholder path ("<merged:N>"); its "dir"
    # records the FIRST input artifact's directory so --baseline latest
    # scans where the snapshots actually live, never the cwd
    cur_path = os.path.abspath(current["path"])
    directory = (current.get("dir")
                 or os.path.dirname(cur_path) or ".")
    if current["format"] == "events":
        want_kind = None
    else:
        want_kind = current["doc"].get("kind")
    candidates, rejected = [], []
    for fname in sorted(os.listdir(directory)):
        path = os.path.join(directory, fname)
        if os.path.abspath(path) == cur_path or not os.path.isfile(path):
            continue
        if not fname.endswith((".json", ".jsonl")):
            continue
        try:
            art = load_artifact(path)
        except (OSError, ValueError, json.JSONDecodeError):
            continue                    # unreadable/mixed: not a baseline
        got_kind = (art["doc"].get("kind")
                    if art["format"] == "snapshot" else None)
        if art["format"] == current["format"] and got_kind == want_kind:
            candidates.append(path)
        else:
            rejected.append(f"{fname} ({got_kind or art['format']})")
    if not candidates:
        raise ValueError(
            f"--baseline latest: no other "
            f"{want_kind or 'event-stream'} artifact in {directory}"
            + (f" — kinds present: {', '.join(rejected)}" if rejected
               else " (directory holds no other artifacts)"))
    return max(candidates, key=os.path.getmtime)


def merge_artifacts(paths: list, run_id: str | None) -> dict:
    """``--merge``: >= 2 per-process artifacts -> one pod view
    (obs/aggregate.py).  All-snapshots -> a merged ``pod_<kind>``
    snapshot artifact; all-event-streams -> a straggler/skew report
    document.  Mixing the two formats is an error."""
    arts = [load_artifact(p, run_id) for p in paths]
    formats = {a["format"] for a in arts}
    if len(formats) > 1:
        raise ValueError("--merge needs all-snapshots or all-event-"
                         "streams, not a mix")
    src_dir = os.path.dirname(os.path.abspath(paths[0])) or "."
    if formats == {"snapshot"}:
        doc = aggregate.merge_snapshots([a["doc"] for a in arts])
        return {"format": "snapshot", "doc": doc,
                "path": f"<merged:{len(arts)}>", "dir": src_dir}
    view = aggregate.merge_event_streams([a["records"] for a in arts])
    return {"format": "pod_events", "doc": view,
            "path": f"<merged:{len(arts)}>", "dir": src_dir}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="observability summarizer + regression gate "
                    "(scripts/obs_report.py)")
    ap.add_argument("artifacts", nargs="+",
                    help="RUN_EVENTS.jsonl or milnce.obs/v1 JSON doc(s); "
                         ">= 2 with --merge")
    ap.add_argument("--check", action="store_true",
                    help="gate the artifact against --baseline; exit 1 "
                         "on regression")
    ap.add_argument("--baseline", default="",
                    help="committed baseline artifact to gate against, "
                         "or 'latest' to auto-pick the newest same-kind "
                         "artifact in the current artifact's directory")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed bad-direction drift fraction "
                         "(default 0.10)")
    ap.add_argument("--run-id", default=None,
                    help="select ONE run out of a shared append-only "
                         "event stream (mixed-run streams error "
                         "otherwise)")
    ap.add_argument("--merge", action="store_true",
                    help="merge >= 2 per-process artifacts of one run "
                         "into a pod view (counters summed, gauges "
                         "min/median/max, straggler skew)")
    ap.add_argument("--out", default="",
                    help="with --merge: write the merged pod snapshot "
                         "here (gate it later with --check)")
    args = ap.parse_args(argv)

    try:
        if args.merge:
            current = merge_artifacts(args.artifacts, args.run_id)
        else:
            if len(args.artifacts) != 1:
                print("obs_report: multiple artifacts need --merge",
                      file=sys.stderr)
                return 2
            current = load_artifact(args.artifacts[0], args.run_id)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"obs_report: cannot read {' '.join(args.artifacts)}: {exc}",
              file=sys.stderr)
        return 2

    if current["format"] == "pod_events":
        # straggler report: per-process step stats + cross-host skew
        view = current["doc"]
        print(f"pod event merge: run {view['run_id']}, "
              f"{view['processes']} processes")
        for pi in sorted(view["per_process"]):
            s = view["per_process"][pi]
            lines = (f"  p{pi}: {s['steps']} steps, step p50 "
                     f"{s['step_ms_p50']} ms, p99 {s['step_ms_p99']} ms")
            if pi in view["stragglers"]:
                lines += "   <-- STRAGGLER"
            print(lines)
        print(f"  step p50 skew (slowest/fastest): "
              f"{view['step_p50_skew']}x "
              f"(straggler threshold {view['straggler_ratio']}x)")
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(view, fh, indent=2, sort_keys=True)
                fh.write("\n")
        # a skewed pod is a finding, not a gate failure — gating step
        # time happens against a baseline via --check on the streams
        return 0

    if args.merge and args.out:
        with open(args.out, "w") as fh:
            json.dump(current["doc"], fh, indent=2, sort_keys=True)
            fh.write("\n")
        current["path"] = args.out

    if not args.check:
        print(render_summary(current))
        return 0

    if not args.baseline:
        print("obs_report: --check requires --baseline", file=sys.stderr)
        return 2
    try:
        baseline_path = (resolve_latest_baseline(current)
                         if args.baseline == "latest" else args.baseline)
        # the baseline is a DIFFERENT run by definition — it must be a
        # clean single-run artifact on its own, so --run-id (which
        # selects out of the CURRENT stream) does not apply to it
        baseline = load_artifact(baseline_path)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"obs_report: cannot resolve baseline {args.baseline}: "
              f"{exc}", file=sys.stderr)
        return 2
    ok, report = check(current, baseline, args.tolerance)
    print(report)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
