#!/bin/bash
# End-to-end TPU measurement queue: probe -> bench -> train-loop
# cross-check.  Safe on a flaky accelerator: the probe runs a REAL tiny
# jitted execute in a bounded subprocess first (init alone can succeed
# on a wedged tunnel whose first execute hangs), and nothing here kills
# a live TPU client mid-execute.
#
#   bash scripts/tpu_smoke.sh
#
# Outputs: BENCH_NOTES.md rewritten by bench.py, one JSON line on
# stdout, and a 12-step batch-256 bf16 training-loop run whose logged
# clips/s should roughly agree with the bench step at the same batch.
set -euo pipefail
cd "$(dirname "$0")/.."
REPO="$PWD"

echo "=== probe ==="
timeout 240 python -c "import jax, jax.numpy as jnp; print(float(jax.jit(lambda: jnp.ones(4).sum())()))" \
  || { echo "accelerator unreachable — aborting (bench.py alone would fall back to CPU)"; exit 1; }

echo "=== bench ==="
MILNCE_BENCH_TPU_TIMEOUT="${MILNCE_BENCH_TPU_TIMEOUT:-6300}" python bench.py

echo "=== re-probe (the tunnel can wedge DURING bench: observed 2026-07-30,"
echo "    remote_compile port refused connections 33 min after a healthy probe) ==="
timeout 240 python -c "import jax, jax.numpy as jnp; print(float(jax.jit(lambda: jnp.ones(4).sum())()))" \
  || { echo "accelerator lost mid-queue — skipping the train-loop cross-check (bench rows above are still valid)"; exit 0; }

echo "=== train-loop cross-check (batch 128, 12 steps, synthetic) ==="
# batch 128 = the measured operating point (BENCH_NOTES.md); the 256
# compile wedged the tunnel twice on 2026-07-31, and this step has no
# watchdog (a timeout-kill of a live client is what causes the wedge)
RUNDIR="$(mktemp -d)"
cd "$RUNDIR"
PYTHONPATH="$REPO" python -m milnce_tpu.train.cli --preset small \
  --data.synthetic true --data.synthetic_num_samples 1536 \
  --data.num_frames 16 --data.max_words 20 \
  --train.batch_size 128 --model.dtype bfloat16 \
  --train.max_steps 12 --train.n_display 4 \
  | grep -E "Training loss|Throughput|done:"
echo "=== done (run dir: $RUNDIR) ==="
