"""Host decode feed-rate benchmark: can the input pipeline keep a chip fed?

Measures the PRODUCTION decode path (cv2 in-process backend, or the
ffmpeg subprocess path when a binary exists) on real encoded video at
training clip shapes — both as raw decoder calls and as sustained
ShardedLoader consumption — and reports clips/s per host thread plus
the thread count needed to sustain the measured chip demand (read live
from BENCH_NOTES.md's operating-point line).

The reference feeds its pods with 40 ffmpeg reader threads per worker
(README.md:56); this script produces the equivalent sizing number for
our host pipeline.

    python scripts/data_bench.py                  # writes DATA_BENCH.md
    python scripts/data_bench.py --clips 64 --threads 1 2 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

def chip_demand() -> float:
    """The current measured operating point (clips/s/chip), read from
    BENCH_NOTES.md so this script can't silently drift from the bench —
    falls back to the last hand-recorded value if the notes are absent
    or reformatted."""
    import re

    path = os.path.join(_REPO, "BENCH_NOTES.md")
    try:
        m = re.search(r"->\s*\*{0,2}([0-9.]+)\*{0,2} clips/sec/chip",
                      open(path).read())
        if m:
            return float(m.group(1))
    except OSError:
        pass
    return 393.968              # bf16 b128 operating point, 2026-08-02


CHIP_DEMAND = chip_demand()


def _write_source_video(path: str, w: int, h: int, seconds: float,
                        fps: int) -> None:
    """Realistic-ish mpeg4 source: moving gradient so inter-frame motion
    gives the codec real work (a static scene decodes unrealistically
    fast)."""
    import cv2

    vw = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"),
                         float(fps), (w, h))
    assert vw.isOpened(), "cv2.VideoWriter failed to open"
    base = np.add.outer(np.arange(h), np.arange(w)) % 256
    for i in range(int(seconds * fps)):
        frame = ((base + 7 * i) % 256).astype(np.uint8)
        vw.write(np.stack([frame, np.roll(frame, i, 0),
                           np.roll(frame, -i, 1)], axis=2))
    vw.release()


def _measure(decoder, paths, n_clips: int, threads: int, num_frames: int,
             fps: int, size: int, crop_only: bool,
             source_seconds: float) -> dict:
    """Decode ``n_clips`` random training clips over ``threads`` workers;
    returns wall-clock clips/s (whole pool) and per-thread rate."""
    from milnce_tpu.data.video import sample_clip

    clip_sec = num_frames / float(fps)
    # keep every random seek inside the source so each draw decodes real
    # frames (a seek past EOF would zero-pad and inflate the rate)
    end = max(clip_sec, source_seconds - clip_sec - 0.5)

    def one(i):
        # fresh per-task RNG: tasks i and i+threads can run concurrently on
        # different threads, so sharing a RandomState across tasks would
        # mutate it unlocked (RandomState is not thread-safe)
        rng = np.random.RandomState(1000 + i)
        path = paths[i % len(paths)]
        clip = sample_clip(decoder, path, 0.0, end, num_frames, fps, size,
                           rng, crop_only, False, True)
        assert clip.shape == (num_frames, size, size, 3)
        assert clip.any(), "decoded clip is all zeros — seek past EOF?"
        return clip.nbytes

    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(one, range(min(threads * 2, n_clips))))  # warm up
        t0 = time.perf_counter()
        total = sum(pool.map(one, range(n_clips)))
        dt = time.perf_counter() - t0
    return {"threads": threads, "clips_per_sec": n_clips / dt,
            "clips_per_sec_per_thread": n_clips / dt / threads,
            "mb_per_sec": total / dt / 1e6, "wall_s": dt}


def _measure_loader(tmp: str, paths, threads: int, batch: int,
                    n_batches: int, num_frames: int, fps: int, size: int,
                    seconds: float, crop_only: bool) -> dict:
    """Sustained throughput of the PRODUCTION pipeline: HowTo100MSource
    (caption JSON -> MIL windows -> cv2 decode) driven by ShardedLoader's
    pipelined thread pool, measured at the consume side — the number that
    actually answers "can this host feed a chip?" (VERDICT r4 #6; the
    per-decoder _measure rows above isolate raw codec cost)."""
    import csv as csv_mod

    from milnce_tpu.config import DataConfig, ModelConfig
    from milnce_tpu.data.datasets import HowTo100MSource
    from milnce_tpu.data.pipeline import ShardedLoader

    rows_needed = batch * (n_batches + 4)          # warmup + lookahead slack
    csv_path = os.path.join(tmp, "train.csv")
    cap_root = os.path.join(tmp, "captions")
    os.makedirs(cap_root, exist_ok=True)
    # caption windows must stay inside the source (minus one clip length)
    # or draws seek past EOF and the rate is inflated by zero-padding —
    # same guard as _measure's `end` bound
    clip_sec = num_frames / float(fps)
    n_windows = max(1, int((seconds - clip_sec - 0.5) // 2.5))
    for i, p in enumerate(paths):
        vid = os.path.splitext(os.path.basename(p))[0]
        track = {"start": [round(2.5 * j, 1) for j in range(n_windows)],
                 "end": [round(2.5 * j + 2.5, 1) for j in range(n_windows)],
                 "text": [f"word{j} word{j + 1} word{j + 2}"
                          for j in range(n_windows)]}
        with open(os.path.join(cap_root, vid + ".json"), "w") as f:
            json.dump(track, f)
    with open(csv_path, "w", newline="") as f:
        w = csv_mod.writer(f)
        w.writerow(["video_path"])
        for i in range(rows_needed):
            w.writerow([os.path.basename(paths[i % len(paths)])])

    cfg = DataConfig(train_csv=csv_path, video_root=tmp,
                     caption_root=cap_root, num_frames=num_frames, fps=fps,
                     video_size=size, max_words=20, num_candidates=5,
                     min_time=clip_sec, crop_only=crop_only,
                     decoder_backend="cv2", num_reader_threads=threads)
    source = HowTo100MSource(cfg, ModelConfig(vocab_size=64))
    loader = ShardedLoader(source, batch, num_threads=threads,
                           process_index=0, process_count=1, shuffle=True)
    it = loader.epoch(0)
    next(it)                                       # warmup: pool spin-up
    t0 = time.perf_counter()
    total_clips = 0
    for _ in range(n_batches):
        b = next(it)
        assert b["video"].shape[0] == batch
        total_clips += batch
    dt = time.perf_counter() - t0
    return {"threads": threads, "batch": batch,
            "clips_per_sec": total_clips / dt,
            "clips_per_sec_per_thread": total_clips / dt / threads,
            "wall_s": dt, "decode_failures": source.decode_failures}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clips", type=int, default=48)
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--num_frames", type=int, default=16)
    ap.add_argument("--fps", type=int, default=10)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--src", default="320x240",
                    help="source resolution WxH (240p is HowTo100M-like)")
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--loader_threads", type=int, nargs="+",
                    default=[1, 2, 4, 8])
    ap.add_argument("--loader_batch", type=int, default=16)
    ap.add_argument("--loader_batches", type=int, default=4,
                    help="timed batches per loader row (after 1 warmup)")
    ap.add_argument("--no_md", action="store_true")
    args = ap.parse_args()
    w, h = (int(x) for x in args.src.split("x"))

    from milnce_tpu.data.video import build_decoder

    import shutil

    tmp = tempfile.mkdtemp(prefix="data_bench_")
    try:
        paths = []
        for i in range(4):
            p = os.path.join(tmp, f"src{i}.mp4")
            _write_source_video(p, w, h, args.seconds, 30)
            paths.append(p)
        src_mb = sum(os.path.getsize(p) for p in paths) / 1e6

        decoder = build_decoder("auto")
        backend = type(decoder).__name__
        # crop_only needs a source >= crop size; 240p is smaller than
        # 224^2 in one dimension only when h < size
        crop_only = w >= args.size and h >= args.size

        rows = []
        for t in args.threads:
            r = _measure(decoder, paths, args.clips, t, args.num_frames,
                         args.fps, args.size, crop_only, args.seconds)
            r["backend"] = backend
            print(json.dumps(r), flush=True)
            rows.append(r)

        loader_rows = []
        for t in args.loader_threads:
            r = _measure_loader(tmp, paths, t, args.loader_batch,
                                args.loader_batches, args.num_frames,
                                args.fps, args.size, args.seconds, crop_only)
            print(json.dumps({"loader": r}), flush=True)
            # a row whose throughput came from the black-frame fallback
            # is not a throughput measurement at all
            assert r["decode_failures"] == 0, (
                f"loader row threads={t} hit {r['decode_failures']} decode "
                "failures — the clips/s number is contaminated by "
                "black-frame fallbacks; fix the corpus/config first")
            loader_rows.append(r)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    best = max(rows, key=lambda r: r["clips_per_sec"])
    per_thread = max(r["clips_per_sec_per_thread"] for r in rows)
    need = int(np.ceil(CHIP_DEMAND / per_thread))
    summary = {"backend": backend, "source": f"{w}x{h} mpeg4",
               "clip": f"{args.num_frames}f@{args.size}^2 fps{args.fps}",
               "best_clips_per_sec": round(best["clips_per_sec"], 2),
               "per_thread_clips_per_sec": round(per_thread, 2),
               "threads_for_chip_demand": need,
               "chip_demand": CHIP_DEMAND}
    print(json.dumps(summary), flush=True)

    if not args.no_md:
        lines = [
            "# Host decode feed rate (auto-written by scripts/data_bench.py)",
            "",
            f"- decode backend: **{backend}** (production path; no fakes)",
            f"- source: {w}x{h} mpeg4, {args.seconds:.0f}s, 30fps, "
            f"{src_mb / 4:.1f} MB/video "
            f"({src_mb / 4 / args.seconds:.2f} MB/s bitrate)",
            f"- clip: {args.num_frames} frames @ {args.size}^2, "
            f"fps={args.fps}, random seek/crop/flip (sample_clip, the "
            "training draw)",
            f"- host: {os.cpu_count()} CPU core(s) visible",
            "",
            "| threads | clips/s (pool) | clips/s/thread | MB/s decoded |",
            "|---|---|---|---|",
        ]
        for r in rows:
            lines.append(f"| {r['threads']} | {r['clips_per_sec']:.2f} | "
                         f"{r['clips_per_sec_per_thread']:.2f} | "
                         f"{r['mb_per_sec']:.1f} |")
        best_loader = max(loader_rows, key=lambda r: r["clips_per_sec"])
        loader_need = int(np.ceil(
            CHIP_DEMAND * best_loader["threads"]
            / best_loader["clips_per_sec"]))
        lines += [
            "",
            "## Sustained ShardedLoader throughput (production pipeline, "
            "consume side)",
            "",
            "HowTo100MSource (caption JSON -> MIL windows -> cv2 decode) "
            "driven by ShardedLoader's pipelined pool; measured at "
            f"`next(batch)` over {args.loader_batches} batches of "
            f"{args.loader_batch} after one warmup batch:",
            "",
            "| threads | clips/s (sustained) | clips/s/thread | "
            "decode failures |",
            "|---|---|---|---|",
        ]
        for r in loader_rows:
            lines.append(f"| {r['threads']} | {r['clips_per_sec']:.2f} | "
                         f"{r['clips_per_sec_per_thread']:.2f} | "
                         f"{r['decode_failures']} |")
        lines += [
            "",
            f"**Sizing (measured, not extrapolated)**: the best loader row "
            f"({best_loader['threads']} threads -> "
            f"{best_loader['clips_per_sec']:.1f} clips/s) implies "
            f"**~{loader_need} reader threads per chip** to sustain the "
            f"{CHIP_DEMAND} clips/s/chip operating point, assuming thread "
            "scaling holds to that count on a multi-core production host "
            "(this measurement host caps at "
            f"{os.cpu_count()} core(s)).",
            "",
            f"**Raw-decoder sizing**: at {per_thread:.2f} clips/s/thread, "
            f"sustaining the measured chip demand of {CHIP_DEMAND} "
            f"clips/s/chip (BENCH_NOTES.md bf16 b128 operating point) needs "
            f"**~{need} reader threads per chip** — the reference provisions "
            "40 ffmpeg threads per worker for its v3-32 pods "
            "(README.md:56).",
            "",
            "Caveats: single-core measurement host (thread rows mostly "
            "show GIL/`cv2` release behavior, not real scaling); mpeg4 "
            "(HowTo100M is largely h264 — cv2 decodes both through "
            "libavcodec, rates within the same order).",
        ]
        with open(os.path.join(_REPO, "DATA_BENCH.md"), "w") as fh:
            fh.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
